"""Built-in randomized test driver.

Analog of `src/ops/dbcsr_tests.F` (`dbcsr_run_tests`:74, test types
`dbcsr_test_mm` / `dbcsr_test_binary_io`): a user-callable harness that
builds random block-sparse matrices with random block sizes, runs the
requested operation n_loops times, and verifies against the dense
oracle (`dbcsr_test_multiply.F:523` dbcsr_check_multiply) / a
round-trip checksum.  CP2K uses this entry to smoke-test a DBCSR build
from application code; it plays the same role here.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from dbcsr_tpu.core.kinds import dtype_of, is_complex
from dbcsr_tpu.core.matrix import BlockSparseMatrix
from dbcsr_tpu.mm.multiply import multiply
from dbcsr_tpu.ops.test_methods import (
    checksum,
    impose_sparsity,
    make_random_matrix,
    to_dense,
)

TEST_MM = 1         # ref dbcsr_test_mm (dbcsr_tests.F:68)
TEST_BINARY_IO = 2  # ref dbcsr_test_binary_io (dbcsr_tests.F:69)


def make_random_block_sizes(total: int, pattern: Sequence[int],
                            rng=None) -> np.ndarray:
    """Random block-size sequence covering ``total`` elements, drawn
    from a (mult1, size1, mult2, size2, ...) multiset — ref
    `dbcsr_make_random_block_sizes` (`dbcsr_test_methods.F`)."""
    rng = rng or np.random.default_rng(0)
    pat = list(pattern)
    if len(pat) % 2:
        raise ValueError("pattern must be (mult, size) pairs")
    mults = np.asarray(pat[0::2], np.float64)
    sizes = np.asarray(pat[1::2], np.int64)
    probs = mults / mults.sum()
    out = []
    covered = 0
    while covered < total:
        s = int(rng.choice(sizes, p=probs))
        s = min(s, total - covered)
        out.append(s)
        covered += s
    return np.asarray(out, np.int32)


class TestFailure(AssertionError):
    """A built-in test detected a result outside tolerance."""


def _check_multiply(c_out, dense_want, eps: float) -> float:
    """Elementwise comparison against the dense oracle with the
    reference's normalized criterion (`dbcsr_check_multiply:523`)."""
    got = to_dense(c_out)
    scale = max(float(np.abs(dense_want).max()), 1.0)
    err = float(np.abs(got - dense_want).max()) / scale
    if not np.isfinite(err) or err > eps:
        raise TestFailure(
            f"multiply result differs from dense oracle: "
            f"max rel err {err:.3e} > eps {eps:.1e}"
        )
    return err


def run_tests(
    matrix_sizes: Tuple[int, int, int],
    trs: Tuple[bool, bool] = (False, False),
    bs_m: Optional[Sequence[int]] = None,
    bs_n: Optional[Sequence[int]] = None,
    bs_k: Optional[Sequence[int]] = None,
    sparsities: Tuple[float, float, float] = (0.0, 0.0, 0.0),
    alpha=1.0,
    beta=0.0,
    data_type: int = 3,
    test_type: int = TEST_MM,
    n_loops: int = 1,
    eps: Optional[float] = None,
    retain_sparsity: bool = False,
    always_checksum: bool = False,
    seed: int = 2131,
    io=print,
) -> list:
    """Run the built-in randomized test (ref `dbcsr_run_tests`,
    `dbcsr_tests.F:74`).  Returns the per-loop checksums; raises
    `TestFailure` on an oracle mismatch.

    ``bs_*`` are (mult, size, mult, size, ...) multisets like the
    reference's; None selects the reference default (1,13,2,5).
    ``eps=None`` picks a dtype-appropriate tolerance (a correct f32
    product is nowhere near 1e-8).
    """
    rng = np.random.default_rng(seed)
    if eps is None:
        resolution = np.finfo(
            np.zeros(1, dtype_of(data_type)).real.dtype
        ).resolution
        eps = 100.0 * np.sqrt(matrix_sizes[2]) * resolution
    default_bs = (1, 13, 2, 5)
    m_sizes = make_random_block_sizes(matrix_sizes[0], bs_m or default_bs, rng)
    n_sizes = make_random_block_sizes(matrix_sizes[1], bs_n or default_bs, rng)
    k_sizes = make_random_block_sizes(matrix_sizes[2], bs_k or default_bs, rng)
    dt = dtype_of(data_type)

    a_rbs, a_cbs = (k_sizes, m_sizes) if trs[0] else (m_sizes, k_sizes)
    b_rbs, b_cbs = (n_sizes, k_sizes) if trs[1] else (k_sizes, n_sizes)
    a = make_random_matrix("test A", a_rbs, a_cbs, dtype=dt,
                           occupation=1.0 - sparsities[0], rng=rng)
    b = make_random_matrix("test B", b_rbs, b_cbs, dtype=dt,
                           occupation=1.0 - sparsities[1], rng=rng)
    c0 = make_random_matrix("test C", m_sizes, n_sizes, dtype=dt,
                            occupation=1.0 - sparsities[2], rng=rng)

    if test_type == TEST_BINARY_IO:
        return _run_binary_io(c0, n_loops, io)
    if test_type != TEST_MM:
        raise ValueError(f"unknown test_type {test_type}")

    transa = "T" if trs[0] else "N"
    transb = "T" if trs[1] else "N"

    def _op(mat, tr):
        d = to_dense(mat)
        return d.T if tr else d

    dense_c0 = to_dense(c0)
    want = alpha * (_op(a, trs[0]) @ _op(b, trs[1])) + beta * dense_c0
    if retain_sparsity:
        want = impose_sparsity(want, c0)

    checksums = []
    for loop in range(n_loops):
        c = c0.copy()
        multiply(transa, transb, alpha, a, b, beta, c,
                 retain_sparsity=retain_sparsity)
        err = _check_multiply(c, want, eps)
        cs = checksum(c)
        checksums.append(cs)
        if always_checksum or loop == n_loops - 1:
            io(f" loop {loop + 1}/{n_loops}: max rel err {err:.3e}, "
               f"checksum {cs:.15e}")
    if len(set(checksums)) > 1:
        raise TestFailure(
            f"checksums differ across {n_loops} identical multiplies: "
            f"{sorted(set(checksums))} (determinism contract broken)"
        )
    return checksums


def _run_binary_io(matrix: BlockSparseMatrix, n_loops: int, io) -> list:
    """Write/read round trip preserving the checksum
    (ref `dbcsr_test_binary_io`, tested via `dbcsr_tests.F:64`)."""
    import os
    import tempfile

    from dbcsr_tpu.ops.io import binary_read, binary_write

    checksums = []
    want = checksum(matrix)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "m.dbcsr")
        for loop in range(n_loops):
            binary_write(matrix, path)
            back = binary_read(path)
            got = checksum(back)
            checksums.append(got)
            if got != want:
                raise TestFailure(
                    f"binary I/O round trip changed the checksum: "
                    f"{got!r} != {want!r}"
                )
        io(f" binary_io: {n_loops} round trips OK, checksum {want:.15e}")
    return checksums
