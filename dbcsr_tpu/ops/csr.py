"""Element-level CSR conversions.

Ref `src/ops/dbcsr_csr_conversions.F` (csr_type :115-143,
`csr_create_from_dbcsr` :762, `convert_csr_to_dbcsr` :377): conversion
between the block-sparse format and a scipy-style element CSR
(indptr/indices/data), the PEXSI/SuperLU interop path.  Also the
workhorse for `complete_redistribute` (arbitrary re-blocking goes
through element coordinates).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from dbcsr_tpu.core.matrix import NO_SYMMETRY, BlockSparseMatrix
from dbcsr_tpu.ops.transformations import desymmetrize


def csr_from_matrix(
    matrix: BlockSparseMatrix, keep_zeros: bool = False
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Block-sparse -> element CSR (indptr, indices, data).

    Stored blocks are emitted element-wise (zeros inside stored blocks
    kept only with ``keep_zeros``), row-major sorted.
    """
    m = desymmetrize(matrix) if matrix.matrix_type != NO_SYMMETRY else matrix
    if not m.valid:
        raise RuntimeError("finalize() first")
    row_off = m.row_blk_offsets
    col_off = m.col_blk_offsets
    rows_l, cols_l, vals_l = [], [], []
    ent_rows, ent_cols = m.entry_coords()
    for b_id, b in enumerate(m.bins):
        mask = m.ent_bin == b_id
        if not mask.any():
            continue
        bm, bn = b.shape
        blocks = np.asarray(b.data[: b.count])[m.ent_slot[mask]]
        er = (
            row_off[ent_rows[mask]][:, None, None]
            + np.arange(bm)[None, :, None]
        )
        ec = (
            col_off[ent_cols[mask]][:, None, None]
            + np.arange(bn)[None, None, :]
        )
        er = np.broadcast_to(er, blocks.shape).reshape(-1)
        ec = np.broadcast_to(ec, blocks.shape).reshape(-1)
        vals = blocks.reshape(-1)
        rows_l.append(er)
        cols_l.append(ec)
        vals_l.append(vals)
    if rows_l:
        rows = np.concatenate(rows_l)
        cols = np.concatenate(cols_l)
        vals = np.concatenate(vals_l)
    else:
        rows = np.empty(0, np.int64)
        cols = np.empty(0, np.int64)
        vals = np.empty(0, np.dtype(m.dtype))
    if not keep_zeros:
        nz = vals != 0
        rows, cols, vals = rows[nz], cols[nz], vals[nz]
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    indptr = np.zeros(m.nfullrows + 1, np.int64)
    np.add.at(indptr, rows + 1, 1)
    np.cumsum(indptr, out=indptr)
    return indptr, cols.astype(np.int64), vals


def matrix_from_csr(
    name: str,
    indptr: np.ndarray,
    indices: np.ndarray,
    data: np.ndarray,
    row_blk_sizes,
    col_blk_sizes,
    dist=None,
) -> BlockSparseMatrix:
    """Element CSR -> block-sparse; a block is stored iff it contains a
    structural entry (ref `convert_csr_to_dbcsr`)."""
    out = BlockSparseMatrix(name, row_blk_sizes, col_blk_sizes, data.dtype, dist)
    if out.nfullrows != len(indptr) - 1:
        raise ValueError("indptr length != full rows")
    row_off = out.row_blk_offsets
    col_off = out.col_blk_offsets
    erows = np.repeat(np.arange(len(indptr) - 1, dtype=np.int64), np.diff(indptr))
    ecols = np.asarray(indices, np.int64)
    if len(ecols) and ecols.max() >= out.nfullcols:
        raise ValueError("column index out of range")
    brow = np.searchsorted(row_off, erows, side="right") - 1
    bcol = np.searchsorted(col_off, ecols, side="right") - 1
    bkey = brow * out.nblkcols + bcol
    uniq, blk_of_entry = np.unique(bkey, return_inverse=True)
    ur, uc = np.divmod(uniq, out.nblkcols)
    bms = out.row_blk_sizes[ur].astype(np.int64)
    bns = out.col_blk_sizes[uc].astype(np.int64)
    sizes = bms * bns
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    flat = np.zeros(int(offsets[-1]), np.dtype(data.dtype))
    lr = erows - row_off[brow]
    lc = ecols - col_off[bcol]
    vals = np.ascontiguousarray(data)

    from dbcsr_tpu import native

    if not native.coo_fill_blocks(blk_of_entry, lr, lc, vals,
                                  offsets[:-1], bns, flat):
        flat[offsets[blk_of_entry] + lr * bns[blk_of_entry] + lc] = vals
    for u in range(len(uniq)):
        blk = flat[offsets[u] : offsets[u + 1]].reshape(bms[u], bns[u])
        out.put_block(int(ur[u]), int(uc[u]), blk)
    return out.finalize()


def complete_redistribute(
    matrix: BlockSparseMatrix,
    row_blk_sizes,
    col_blk_sizes,
    dist=None,
    name: Optional[str] = None,
) -> BlockSparseMatrix:
    """Re-block a matrix onto an arbitrary new blocking of the same
    element space (ref `dbcsr_complete_redistribute`,
    `dbcsr_transformations.F:1546`).  Goes through element coordinates,
    so any blocking change is supported."""
    new_rbs = np.asarray(row_blk_sizes, np.int32)
    new_cbs = np.asarray(col_blk_sizes, np.int32)
    if new_rbs.sum() != matrix.nfullrows or new_cbs.sum() != matrix.nfullcols:
        raise ValueError("new blocking covers a different element space")
    indptr, indices, data = csr_from_matrix(matrix, keep_zeros=True)
    return matrix_from_csr(
        name or matrix.name, indptr, indices, data, new_rbs, new_cbs, dist
    )
