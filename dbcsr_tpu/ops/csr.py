"""Element-level CSR conversions.

Ref `src/ops/dbcsr_csr_conversions.F` (csr_type :115-143,
`csr_create_from_dbcsr` :762, `convert_csr_to_dbcsr` :377): conversion
between the block-sparse format and a scipy-style element CSR
(indptr/indices/data), the PEXSI/SuperLU interop path.  Also the
workhorse for `complete_redistribute` (arbitrary re-blocking goes
through element coordinates).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from dbcsr_tpu.core.matrix import NO_SYMMETRY, BlockSparseMatrix
from dbcsr_tpu.ops.transformations import desymmetrize


def csr_from_matrix(
    matrix: BlockSparseMatrix, keep_zeros: bool = False
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Block-sparse -> element CSR (indptr, indices, data).

    Stored blocks are emitted element-wise (zeros inside stored blocks
    kept only with ``keep_zeros``), row-major sorted.
    """
    m = desymmetrize(matrix) if matrix.matrix_type != NO_SYMMETRY else matrix
    if not m.valid:
        raise RuntimeError("finalize() first")
    row_off = m.row_blk_offsets
    col_off = m.col_blk_offsets
    rows_l, cols_l, vals_l = [], [], []
    ent_rows, ent_cols = m.entry_coords()
    for b_id, b in enumerate(m.bins):
        mask = m.ent_bin == b_id
        if not mask.any():
            continue
        bm, bn = b.shape
        blocks = np.asarray(b.data[: b.count])[m.ent_slot[mask]]
        er = (
            row_off[ent_rows[mask]][:, None, None]
            + np.arange(bm)[None, :, None]
        )
        ec = (
            col_off[ent_cols[mask]][:, None, None]
            + np.arange(bn)[None, None, :]
        )
        er = np.broadcast_to(er, blocks.shape).reshape(-1)
        ec = np.broadcast_to(ec, blocks.shape).reshape(-1)
        vals = blocks.reshape(-1)
        rows_l.append(er)
        cols_l.append(ec)
        vals_l.append(vals)
    if rows_l:
        rows = np.concatenate(rows_l)
        cols = np.concatenate(cols_l)
        vals = np.concatenate(vals_l)
    else:
        rows = np.empty(0, np.int64)
        cols = np.empty(0, np.int64)
        vals = np.empty(0, np.dtype(m.dtype))
    if not keep_zeros:
        nz = vals != 0
        rows, cols, vals = rows[nz], cols[nz], vals[nz]
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    indptr = np.zeros(m.nfullrows + 1, np.int64)
    np.add.at(indptr, rows + 1, 1)
    np.cumsum(indptr, out=indptr)
    return indptr, cols.astype(np.int64), vals


def matrix_from_csr(
    name: str,
    indptr: np.ndarray,
    indices: np.ndarray,
    data: np.ndarray,
    row_blk_sizes,
    col_blk_sizes,
    dist=None,
) -> BlockSparseMatrix:
    """Element CSR -> block-sparse; a block is stored iff it contains a
    structural entry (ref `convert_csr_to_dbcsr`)."""
    out = BlockSparseMatrix(name, row_blk_sizes, col_blk_sizes, data.dtype, dist)
    if out.nfullrows != len(indptr) - 1:
        raise ValueError("indptr length != full rows")
    row_off = out.row_blk_offsets
    col_off = out.col_blk_offsets
    erows = np.repeat(np.arange(len(indptr) - 1, dtype=np.int64), np.diff(indptr))
    ecols = np.asarray(indices, np.int64)
    if len(ecols) and ecols.max() >= out.nfullcols:
        raise ValueError("column index out of range")
    brow = np.searchsorted(row_off, erows, side="right") - 1
    bcol = np.searchsorted(col_off, ecols, side="right") - 1
    bkey = brow * out.nblkcols + bcol
    uniq, blk_of_entry = np.unique(bkey, return_inverse=True)
    ur, uc = np.divmod(uniq, out.nblkcols)
    bms = out.row_blk_sizes[ur].astype(np.int64)
    bns = out.col_blk_sizes[uc].astype(np.int64)
    sizes = bms * bns
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    flat = np.zeros(int(offsets[-1]), np.dtype(data.dtype))
    lr = erows - row_off[brow]
    lc = ecols - col_off[bcol]
    vals = np.ascontiguousarray(data)

    from dbcsr_tpu import native

    if not native.coo_fill_blocks(blk_of_entry, lr, lc, vals,
                                  offsets[:-1], bns, flat):
        flat[offsets[blk_of_entry] + lr * bns[blk_of_entry] + lc] = vals
    for u in range(len(uniq)):
        blk = flat[offsets[u] : offsets[u + 1]].reshape(bms[u], bns[u])
        out.put_block(int(ur[u]), int(uc[u]), blk)
    return out.finalize()


def complete_redistribute(
    matrix: BlockSparseMatrix,
    row_blk_sizes,
    col_blk_sizes,
    dist=None,
    name: Optional[str] = None,
) -> BlockSparseMatrix:
    """Re-block a matrix onto an arbitrary new blocking of the same
    element space (ref `dbcsr_complete_redistribute`,
    `dbcsr_transformations.F:1546`).  Goes through element coordinates,
    so any blocking change is supported."""
    new_rbs = np.asarray(row_blk_sizes, np.int32)
    new_cbs = np.asarray(col_blk_sizes, np.int32)
    if new_rbs.sum() != matrix.nfullrows or new_cbs.sum() != matrix.nfullcols:
        raise ValueError("new blocking covers a different element space")
    indptr, indices, data = csr_from_matrix(matrix, keep_zeros=True)
    return matrix_from_csr(
        name or matrix.name, indptr, indices, data, new_rbs, new_cbs, dist
    )


# ------------------------------------------------------------- csr_type API
# row-distribution modes for a CSR matrix over processes
# (ref `dbcsr_csr_conversions.F:70,769-799`)
CSR_DBCSR_BLKROW_DIST = 1  # whole DBCSR block rows per process
CSR_EQROW_CEIL_DIST = 2    # ceiling(N/P) rows per process
CSR_EQROW_FLOOR_DIST = 3   # floor(N/P) rows per process (last takes rest)


def csr_eqrow_ceil_dist(nrows: int, nbins: int) -> np.ndarray:
    """Row -> bin map with ceiling(N/P) rows per bin
    (ref csr_eqrow_ceil_dist)."""
    per = -(-nrows // max(nbins, 1))
    return np.minimum(np.arange(nrows, dtype=np.int64) // max(per, 1),
                      nbins - 1).astype(np.int32)


def csr_eqrow_floor_dist(nrows: int, nbins: int) -> np.ndarray:
    """Row -> bin map with floor(N/P) rows per bin; the last bin takes
    the remainder (ref csr_eqrow_floor_dist)."""
    per = max(nrows // max(nbins, 1), 1)
    return np.minimum(np.arange(nrows, dtype=np.int64) // per,
                      nbins - 1).astype(np.int32)


def csr_blkrow_dist(matrix: BlockSparseMatrix, nbins: int) -> np.ndarray:
    """Row -> bin map that never splits a DBCSR block row across bins
    (ref csr_dbcsr_blkrow_dist): block rows are assigned by cumulative
    element-row count, balancing rows per bin."""
    sizes = matrix.row_blk_sizes.astype(np.int64)
    total = int(sizes.sum())
    starts = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    blk_bin = np.minimum(starts * nbins // max(total, 1), nbins - 1)
    return np.repeat(blk_bin, sizes).astype(np.int32)


class CsrMatrix:
    """Element CSR with an optional row distribution — the `csr_type`
    analog (ref `dbcsr_csr_conversions.F:115-143`)."""

    def __init__(self, nrows, ncols, indptr, indices, data, row_dist=None):
        self.nrows = int(nrows)
        self.ncols = int(ncols)
        self.indptr = np.ascontiguousarray(indptr, np.int64)
        self.indices = np.ascontiguousarray(indices, np.int64)
        self.data = np.ascontiguousarray(data)
        self.row_dist = row_dist
        self.valid = True

    @property
    def nze(self) -> int:
        return len(self.data)


def csr_create_from_matrix(
    matrix: BlockSparseMatrix,
    nprocs: int = 1,
    dist_format: int = CSR_EQROW_CEIL_DIST,
    keep_zeros: bool = False,
) -> CsrMatrix:
    """Block-sparse -> `CsrMatrix` with a row distribution in the
    requested format (ref `dbcsr_csr_create_from_dbcsr`,
    `dbcsr_csr_conversions.F:762`)."""
    indptr, indices, data = csr_from_matrix(matrix, keep_zeros=keep_zeros)
    nrows, ncols = matrix.nfullrows, matrix.nfullcols
    if dist_format == CSR_EQROW_CEIL_DIST:
        rd = csr_eqrow_ceil_dist(nrows, nprocs)
    elif dist_format == CSR_EQROW_FLOOR_DIST:
        rd = csr_eqrow_floor_dist(nrows, nprocs)
    elif dist_format == CSR_DBCSR_BLKROW_DIST:
        rd = csr_blkrow_dist(matrix, nprocs)
    else:
        raise ValueError(f"unknown dist_format {dist_format}")
    return CsrMatrix(nrows, ncols, indptr, indices, data, row_dist=rd)


def to_csr_filter(matrix: BlockSparseMatrix, eps: float) -> BlockSparseMatrix:
    """0/1 sparsity template of ``matrix`` with elements |x| < eps
    marked 0 — improves CSR sparsity before conversion
    (ref `dbcsr_to_csr_filter`, `dbcsr_csr_conversions.F:1027`)."""
    import jax.numpy as jnp

    out = matrix.copy(name="CSR sparsity")
    if not out.valid:
        out.finalize()
    if eps > 0.0:
        out.map_bin_data(
            lambda d: jnp.where(jnp.abs(d) < eps, 0.0, 1.0).astype(d.dtype)
        )
    else:
        out.map_bin_data(lambda d: jnp.ones_like(d))
    return out


def csr_write(csr: CsrMatrix, file, upper_triangle: bool = False,
              threshold: float = 0.0, binary: bool = False) -> None:
    """Write a CSR matrix: text lines "row col value" (1-based) or a
    raw binary dump (ref `csr_write`, `dbcsr_csr_conversions.F:1085`)."""
    if not csr.valid:
        raise RuntimeError("cannot write an invalid CSR matrix")
    rows = np.repeat(np.arange(csr.nrows, dtype=np.int64),
                     np.diff(csr.indptr))
    cols = csr.indices
    vals = csr.data
    keep = np.ones(len(vals), bool)
    if upper_triangle:
        keep &= cols >= rows
    if threshold > 0.0:
        keep &= np.abs(vals) >= threshold
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    if binary:
        np.asarray([csr.nrows, csr.ncols, len(vals)], np.int64).tofile(file)
        rows.tofile(file)
        cols.tofile(file)
        vals.tofile(file)
        return
    if np.iscomplexobj(vals):
        for r, c, v in zip(rows, cols, vals):
            file.write(f"{r + 1} {c + 1} {v.real:.14E} {v.imag:.14E}\n")
    else:
        for r, c, v in zip(rows, cols, vals):
            file.write(f"{r + 1} {c + 1} {v:.14E}\n")


def csr_print_sparsity(csr: CsrMatrix, file=None) -> None:
    """Print CSR non-zero count and percentage
    (ref `csr_print_sparsity`, `dbcsr_csr_conversions.F:1284`)."""
    import sys

    out = file or sys.stdout
    pct = 100.0 * csr.nze / max(csr.nrows * csr.ncols, 1)
    print(f"{'Number of  CSR non-zero elements:':>48} {csr.nze:>13d}",
          file=out)
    print(f"{'Percentage CSR non-zero elements:':>48} {pct:>6.2f}", file=out)
