"""Matrix operations.

Analogs of `src/ops/dbcsr_operations.F` (:109-125 public list): add,
scale, scale_by_vector, trace, dot, norms (frobenius/maxabs/gershgorin/
column, :2032-2380), filter (:1887), function_of_elements (:821),
hadamard (:971), diagonal access.  Index logic on host; block data
touched in bulk per shape bin on device.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dbcsr_tpu.core import mempool
from dbcsr_tpu.core import stats  # noqa: F401  (kept for parity instrumentation)
from dbcsr_tpu.core.kinds import is_complex, real_dtype_of
from dbcsr_tpu.core.matrix import (
    HERMITIAN as HERMITIAN_TYPE,
    NO_SYMMETRY,
    BlockSparseMatrix,
    _Bin,
)
from dbcsr_tpu.utils.rounding import bucket_size


def _require_valid(*mats: BlockSparseMatrix) -> None:
    for m in mats:
        if not m.valid:
            raise RuntimeError(f"matrix {m.name!r} needs finalize() first")


def _same_blocking(a: BlockSparseMatrix, b: BlockSparseMatrix) -> None:
    if not (
        np.array_equal(a.row_blk_sizes, b.row_blk_sizes)
        and np.array_equal(a.col_blk_sizes, b.col_blk_sizes)
    ):
        raise ValueError("matrices have different blockings")


# --------------------------------------------------------------- structure
@functools.partial(jax.jit, static_argnames=("capacity",))
def _gather_pad(data, slots, capacity):
    out = jnp.take(data, slots, axis=0)
    pad = capacity - out.shape[0]
    if pad > 0:
        out = jnp.concatenate([out, jnp.zeros((pad,) + out.shape[1:], out.dtype)])
    return out


def _subset_bins(matrix: BlockSparseMatrix, keep: np.ndarray):
    """(keys, freshly gathered bins) for the ``keep``-masked entries —
    the slot-ordering contract (sorted slots preserve key order within
    a bin) lives HERE, shared by compress and get_block_diag."""
    new_keys = matrix.keys[keep]
    ent_bin = matrix.ent_bin[keep]
    ent_slot = matrix.ent_slot[keep]
    bins = []
    for b_id, b in enumerate(matrix.bins):
        mask = ent_bin == b_id
        count = int(mask.sum())
        if count == 0:
            # shapes absent from the subset are never referenced by
            # set_structure_from_device; skip the dispatch entirely
            continue
        slots = np.sort(ent_slot[mask])  # preserve key order within bin
        data = _gather_pad(b.data, mempool.upload_index("subset", slots),
                           bucket_size(count))
        bins.append(_Bin(b.shape, data, count))
    return new_keys, bins


def compress(matrix: BlockSparseMatrix, keep: np.ndarray) -> BlockSparseMatrix:
    """Drop entries where ``keep`` is False; rebuild bins by device gather."""
    _require_valid(matrix)
    if keep.all():
        return matrix
    new_keys, bins = _subset_bins(matrix, keep)
    matrix.set_structure_from_device(new_keys, bins)
    return matrix


def filter_matrix(matrix: BlockSparseMatrix, eps: float) -> BlockSparseMatrix:
    """Drop blocks with Frobenius norm below eps (ref `dbcsr_filter`,
    `dbcsr_operations.F:1887`; criterion ||blk||² >= eps² as in
    `multrec_filtering`, `dbcsr_mm_multrec.F:694-748`)."""
    _require_valid(matrix)
    norms = matrix.block_norms()
    return compress(matrix, norms.astype(np.float64) ** 2 >= float(eps) ** 2)


# ------------------------------------------------------------------ scaling
def scale(matrix: BlockSparseMatrix, factor) -> BlockSparseMatrix:
    """In-place A <- factor*A (ref `dbcsr_scale`)."""
    _require_valid(matrix)
    f = jnp.asarray(factor, dtype=matrix.dtype)
    matrix.map_bin_data(lambda d: d * f)
    return matrix


def scale_by_vector(
    matrix: BlockSparseMatrix, vector, side: str = "right"
) -> BlockSparseMatrix:
    """A <- A*diag(v) ('right') or diag(v)*A ('left')
    (ref `dbcsr_scale_by_vector`)."""
    _require_valid(matrix)
    if matrix.matrix_type != NO_SYMMETRY:
        # A*diag(v) of a symmetric matrix is not symmetric; triangular
        # storage cannot represent the result
        raise ValueError("scale_by_vector requires a non-symmetric matrix; "
                         "desymmetrize() first")
    v = np.asarray(vector)
    rows, cols = matrix.entry_coords()
    if side == "right":
        if len(v) != matrix.nfullcols:
            raise ValueError("vector length != full cols")
        offsets, sizes, which = matrix.col_blk_offsets, matrix.col_blk_sizes, cols
    elif side == "left":
        if len(v) != matrix.nfullrows:
            raise ValueError("vector length != full rows")
        offsets, sizes, which = matrix.row_blk_offsets, matrix.row_blk_sizes, rows
    else:
        raise ValueError(side)
    for b_id, b in enumerate(matrix.bins):
        if b.count == 0:
            continue
        mask = matrix.ent_bin == b_id
        blk_of = which[mask]
        slot_of = matrix.ent_slot[mask]
        seg_len = b.shape[1] if side == "right" else b.shape[0]
        segs = np.zeros((b.capacity, seg_len), dtype=np.dtype(matrix.dtype))
        for e in range(len(blk_of)):
            o = offsets[blk_of[e]]
            segs[slot_of[e]] = v[o : o + sizes[blk_of[e]]]
        segs_d = jnp.asarray(segs)
        if side == "right":
            b.data = b.data * segs_d[:, None, :]
        else:
            b.data = b.data * segs_d[:, :, None]
    matrix.invalidate_dense_cache()
    matrix._note_mutation(matrix.keys)  # every stored value scaled
    return matrix


# named elementwise functions (ref dbcsr_func_* constants,
# `dbcsr_operations.F:72-75`, semantics documented at :821-960)
FUNC_INVERSE = "inverse"                  # 1/(a1*x+a0); aborts on inf
FUNC_INVERSE_SPECIAL = "inverse_special"  # 1/(x+sign(a0,x)); safe for a0>0
FUNC_TANH = "tanh"                        # tanh(a1*x+a0)
FUNC_DTANH = "dtanh"                      # d tanh(a1*x+a0)/dx
FUNC_DDTANH = "ddtanh"                    # d2 tanh(a1*x+a0)/dx2
FUNC_ARTANH = "artanh"                    # artanh(a1*x+a0); |y|<1 required
FUNC_SIN = "sin"                          # sin(a1*x+a0)
FUNC_COS = "cos"                          # cos(a1*x+a0)
FUNC_DSIN = "dsin"                        # a1*cos(a1*x+a0)
FUNC_DDSIN = "ddsin"                      # -a1^2*sin(a1*x+a0)
FUNC_ASIN = "asin"                        # asin(a1*x+a0); |y|<=1 required
FUNC_SPREAD_FROM_ZERO = "spread_from_zero"  # |x|<|a0| -> sign(a0,x)
FUNC_TRUNCATE = "truncate"                  # |x|>|a0| -> sign(a0,x)

_NAMED_FUNCS = {
    FUNC_INVERSE: lambda x, a0, a1: 1.0 / (a1 * x + a0),
    FUNC_INVERSE_SPECIAL: lambda x, a0, a1: 1.0
    / (x + jnp.copysign(jnp.asarray(a0, x.dtype), x)),
    FUNC_TANH: lambda x, a0, a1: jnp.tanh(a1 * x + a0),
    FUNC_DTANH: lambda x, a0, a1: a1 * (1.0 - jnp.tanh(a1 * x + a0) ** 2),
    FUNC_DDTANH: lambda x, a0, a1: 2.0
    * a1**2
    * (jnp.tanh(a1 * x + a0) ** 3 - jnp.tanh(a1 * x + a0)),
    FUNC_ARTANH: lambda x, a0, a1: jnp.arctanh(a1 * x + a0),
    FUNC_SIN: lambda x, a0, a1: jnp.sin(a1 * x + a0),
    FUNC_COS: lambda x, a0, a1: jnp.cos(a1 * x + a0),
    FUNC_DSIN: lambda x, a0, a1: a1 * jnp.cos(a1 * x + a0),
    FUNC_DDSIN: lambda x, a0, a1: -(a1**2) * jnp.sin(a1 * x + a0),
    FUNC_ASIN: lambda x, a0, a1: jnp.arcsin(a1 * x + a0),
    FUNC_SPREAD_FROM_ZERO: lambda x, a0, a1: jnp.where(
        jnp.abs(x) < abs(a0), jnp.copysign(jnp.asarray(a0, x.dtype), x), x
    ),
    FUNC_TRUNCATE: lambda x, a0, a1: jnp.where(
        jnp.abs(x) > abs(a0), jnp.copysign(jnp.asarray(a0, x.dtype), x), x
    ),
}

# domain guards the reference enforces with DBCSR_ABORT after MAXVAL
# (`dbcsr_operations.F:926,941,956`): (pre-transform y = a1*x+a0, test)
_FUNC_DOMAIN = {
    FUNC_INVERSE: ("post", lambda y: ~jnp.isfinite(y), "division by zero"),
    FUNC_ARTANH: ("pre", lambda y: jnp.abs(y) >= 1.0, "ARTANH undefined for |x|>=1"),
    FUNC_ASIN: ("pre", lambda y: jnp.abs(y) > 1.0, "ASIN undefined for |x|>1"),
}


def function_of_elements(
    matrix: BlockSparseMatrix, fn, *args, a0: float = 0.0, a1: float = 1.0,
    a2: float = 0.0
) -> BlockSparseMatrix:
    """Apply an elementwise function to stored blocks only
    (ref `dbcsr_function_of_elements`, `dbcsr_operations.F:821`).

    ``fn`` is a FUNC_* name (reference parity, with the reference's
    positional-or-keyword (a0, a1, a2) parameterization and domain
    aborts) or any callable taking the block array (extension; extra
    positional args pass through to the callable)."""
    _require_valid(matrix)
    if callable(fn):
        matrix.map_bin_data(lambda d: fn(d, *args).astype(d.dtype))
        return matrix
    if args:
        if len(args) > 3:
            raise TypeError("at most (a0, a1, a2) positional parameters")
        a0, a1, a2 = (list(args) + [a0, a1, a2][len(args):])[:3]
    if fn not in _NAMED_FUNCS:
        raise ValueError(f"unknown function of matrix elements: {fn!r}")
    if is_complex(matrix.dtype):
        # ref: "Operation is implemented only for dp real values"
        raise TypeError("named element functions require a real matrix")
    f = _NAMED_FUNCS[fn]
    guard = _FUNC_DOMAIN.get(fn)
    bad = False
    for b in matrix.bins:
        if b.count == 0:
            continue
        if guard is not None:
            when, pred, _ = guard
            probe = (a1 * b.data + a0) if when == "pre" else f(b.data, a0, a1)
            live = (jnp.arange(b.data.shape[0]) < b.count).reshape(-1, 1, 1)
            bad = bad | bool(jnp.any(pred(probe) & live))
    if bad:
        raise FloatingPointError(guard[2])
    matrix.map_bin_data(lambda d: f(d, a0, a1).astype(d.dtype))
    return matrix


# ---------------------------------------------------------------- additive
@functools.partial(jax.jit, donate_argnums=0)
def _axpby_donate(da, db, alpha, beta):
    """Same-pattern add with A's buffer DONATED into the result — the
    chain-aware in-place update (`P' = 3P² - 2P³` becomes one
    elementwise pass reusing P²'s device storage).  Pad rows stay zero
    (alpha*0 + beta*0)."""
    return alpha * da + beta * db


@jax.jit
def _axpby(da, db, alpha, beta):
    return alpha * da + beta * db


def _add_aligned(a: BlockSparseMatrix, b: BlockSparseMatrix) -> bool:
    """True when a and b share pattern, dtype, and bin geometry, so
    `add` reduces to per-bin elementwise axpby (bitwise-identical to
    the gather/scatter path: same accumulation order, zero pads)."""
    if a.nblks == 0 or a.nblks != b.nblks:
        return False
    if np.dtype(a.dtype) != np.dtype(b.dtype):
        return False
    if len(a.bins) != len(b.bins):
        return False
    if not np.array_equal(a.keys, b.keys):
        return False
    for ba, bb in zip(a.bins, b.bins):
        if ba.shape != bb.shape or ba.count != bb.count \
                or ba.data.shape != bb.data.shape:
            return False
    return bool(
        np.array_equal(a.ent_bin, b.ent_bin)
        and np.array_equal(a.ent_slot, b.ent_slot)
    )


def _add_checks(matrix_a, matrix_b) -> None:
    _require_valid(matrix_a, matrix_b)
    _same_blocking(matrix_a, matrix_b)
    if matrix_a.matrix_type != matrix_b.matrix_type:
        raise ValueError("mixed symmetry add not supported")


def _add_union(dest, matrix_a, matrix_b, alpha, beta) -> None:
    """alpha*A + beta*B on the pattern union, installed into ``dest``
    (which may BE matrix_a — the in-place `add` — or a fresh matrix —
    `added`).  Accumulation order is fixed (A's term first)."""
    new_keys = np.union1d(matrix_a.keys, matrix_b.keys)
    rows = (new_keys // matrix_a.nblkcols).astype(np.int64)
    cols = (new_keys % matrix_a.nblkcols).astype(np.int64)
    from dbcsr_tpu.core.matrix import _bin_entries

    nb, nsl, shapes = _bin_entries(
        matrix_a.row_blk_sizes, matrix_a.col_blk_sizes, rows, cols
    )
    pos_a = np.searchsorted(new_keys, matrix_a.keys)
    pos_b = np.searchsorted(new_keys, matrix_b.keys)
    bins = []
    for b_id, (bm, bn) in enumerate(shapes):
        mask = nb == b_id
        count = int(mask.sum())
        cap = bucket_size(count)
        data = mempool.zeros((cap, bm, bn), matrix_a.dtype)
        for src, pos, fac in ((matrix_a, pos_a, alpha), (matrix_b, pos_b, beta)):
            sel = nb[pos] == b_id  # src entries landing in this bin
            if not sel.any():
                continue
            src_ent = np.nonzero(sel)[0]
            src_bin = src.ent_bin[src_ent[0]]
            dst_slots = nsl[pos[sel]]
            src_slots = src.ent_slot[src_ent]
            data = data.at[mempool.upload_index("add_dst", dst_slots)].add(
                fac * jnp.take(src.bins[src_bin].data,
                               mempool.upload_index("add_src", src_slots),
                               axis=0)
            )
        bins.append(_Bin((bm, bn), data, count))
    dest.set_structure_from_device(new_keys, bins, binning=(nb, nsl, shapes))


def add(
    matrix_a: BlockSparseMatrix,
    matrix_b: BlockSparseMatrix,
    alpha_scalar=1.0,
    beta_scalar=1.0,
) -> BlockSparseMatrix:
    """In-place A <- alpha*A + beta*B with pattern union
    (ref `dbcsr_add`, `dbcsr_operations.F:608`).

    Same-pattern operands skip the index rebuild entirely: one
    elementwise axpby per bin, with A's buffer donated when A owns it
    exclusively (chain-adopted, never shared) — the in-place device
    update iterative chains live on."""
    _add_checks(matrix_a, matrix_b)
    alpha = jnp.asarray(alpha_scalar, dtype=matrix_a.dtype)
    beta = jnp.asarray(beta_scalar, dtype=matrix_a.dtype)
    if _add_aligned(matrix_a, matrix_b):
        donate = (mempool.enabled() and matrix_a is not matrix_b
                  and matrix_a._donatable)
        for ba, bb in zip(matrix_a.bins, matrix_b.bins):
            fn = _axpby_donate if donate and ba.data is not bb.data \
                else _axpby
            ba.data = mempool.run_donated(fn, ba.data, bb.data, alpha, beta)
        matrix_a._bins_shared = False  # fresh outputs: exclusive again
        matrix_a.invalidate_dense_cache()
        matrix_a._note_mutation(matrix_a.keys)  # every stored value axpby'd
        return matrix_a
    _add_union(matrix_a, matrix_a, matrix_b, alpha, beta)
    return matrix_a


def added(
    matrix_a: BlockSparseMatrix,
    matrix_b: BlockSparseMatrix,
    alpha_scalar=1.0,
    beta_scalar=1.0,
    name: Optional[str] = None,
) -> BlockSparseMatrix:
    """Out-of-place alpha*A + beta*B into a FRESH matrix, never
    aliasing either operand — the residency-friendly sibling of `add`
    for consumers that need both the sum and the operands afterwards
    (e.g. a chain's convergence diff): no `copy()` is involved, so the
    operands stay exclusively owned and keep donating to the memory
    pool.  Bitwise-identical values to ``add(copy(A), B, ...)``."""
    _add_checks(matrix_a, matrix_b)
    out = BlockSparseMatrix(
        name or f"{matrix_a.name}+{matrix_b.name}",
        matrix_a.row_blk_sizes,
        matrix_a.col_blk_sizes,
        matrix_a.dtype,
        matrix_a.dist,
        matrix_a.matrix_type,
    )
    alpha = jnp.asarray(alpha_scalar, dtype=matrix_a.dtype)
    beta = jnp.asarray(beta_scalar, dtype=matrix_a.dtype)
    if _add_aligned(matrix_a, matrix_b):
        shapes = [b.shape for b in matrix_a.bins]
        bins = [
            _Bin(ba.shape, _axpby(ba.data, bb.data, alpha, beta), ba.count)
            for ba, bb in zip(matrix_a.bins, matrix_b.bins)
        ]
        out.set_structure_from_device(
            matrix_a.keys.copy(), bins,
            binning=(matrix_a.ent_bin.copy(), matrix_a.ent_slot.copy(),
                     shapes),
        )
        return out
    _add_union(out, matrix_a, matrix_b, alpha, beta)
    return out


def copy(matrix: BlockSparseMatrix, name: Optional[str] = None) -> BlockSparseMatrix:
    """Ref `dbcsr_copy`."""
    return matrix.copy(name)


def set_value(matrix: BlockSparseMatrix, alpha) -> BlockSparseMatrix:
    """Set every STORED element to ``alpha`` (ref `dbcsr_set`,
    `dbcsr_operations.F:2840`; the sparsity pattern is unchanged)."""
    _require_valid(matrix)
    if alpha == 0:
        matrix.zero_data()
        return matrix
    a = jnp.asarray(alpha, dtype=matrix.dtype)
    matrix.map_bin_data(lambda d: jnp.full_like(d, a))
    return matrix


def clear(matrix: BlockSparseMatrix) -> BlockSparseMatrix:
    """Remove all blocks, keeping blocking/distribution/type
    (ref `dbcsr_clear`, `dbcsr_operations.F:2571`)."""
    fresh = BlockSparseMatrix(
        matrix.name,
        matrix.row_blk_sizes,
        matrix.col_blk_sizes,
        matrix.dtype,
        matrix.dist,
        matrix.matrix_type,
    )
    _swap_state(matrix, fresh)
    return matrix


def _swap_state(matrix: BlockSparseMatrix,
                replacement: BlockSparseMatrix) -> None:
    """Replace ``matrix``'s state with ``replacement``'s wholesale
    (clear / triu's symmetry fold).  The mutation epoch must stay
    MONOTONE through the swap: the replacement is a fresh object whose
    epoch restarts at ~0, and lazily attached epoch-keyed caches
    (``_value_digest_cache``) survive a plain ``__dict__.update`` —
    a reset epoch counting back up could then re-serve a stale digest
    as current.  Carry the old epoch over and record an all-dirty
    mutation instead."""
    epoch = matrix._epoch
    matrix.__dict__.pop("_value_digest_cache", None)
    matrix.__dict__.update(replacement.__dict__)
    matrix._epoch = epoch
    matrix._note_mutation(None)


def get_block_diag(
    matrix: BlockSparseMatrix, name: Optional[str] = None
) -> BlockSparseMatrix:
    """New matrix holding only the diagonal blocks of ``matrix``
    (ref `dbcsr_get_block_diag`, `dbcsr_operations.F:1158`).  Gathers
    just the diagonal entries — no copy of the off-diagonal data."""
    _require_valid(matrix)
    out = BlockSparseMatrix(
        name or f"diag of {matrix.name}",
        matrix.row_blk_sizes,
        matrix.col_blk_sizes,
        matrix.dtype,
        matrix.dist,
        matrix.matrix_type,
    )
    rows, cols = matrix.entry_coords()
    keys, bins = _subset_bins(matrix, rows == cols)
    out.set_structure_from_device(keys, bins)
    return out


def copy_into_existing(
    matrix_b: BlockSparseMatrix, matrix_a: BlockSparseMatrix
) -> BlockSparseMatrix:
    """Copy A's data into B, RETAINING B's sparsity pattern
    (ref `dbcsr_copy_into_existing`, `dbcsr_operations.F:1352`): blocks
    present in both are copied; B blocks absent in A are zeroed; A
    blocks absent in B are skipped.  Vectorized: one device
    gather/scatter per shape bin, no host round-trip."""
    _require_valid(matrix_a, matrix_b)
    _same_blocking(matrix_a, matrix_b)
    if matrix_a.matrix_type != matrix_b.matrix_type:
        # the reference's making-symmetric special case
        # (dbcsr_copy_into_existing_sym) folds a general matrix onto a
        # symmetric pattern; here: desymmetrize the stricter side first
        raise ValueError(
            "copy_into_existing requires matching matrix types; desymmetrize first"
        )
    if np.dtype(matrix_a.dtype) != np.dtype(matrix_b.dtype):
        raise ValueError("matrices have different data types")
    pos = np.searchsorted(matrix_a.keys, matrix_b.keys)
    pos_c = np.minimum(pos, max(len(matrix_a.keys) - 1, 0))
    in_a = (
        np.zeros(len(matrix_b.keys), bool)
        if len(matrix_a.keys) == 0
        else matrix_a.keys[pos_c] == matrix_b.keys
    )
    for b_id, b in enumerate(matrix_b.bins):
        if b.count == 0:
            continue
        new_data = jnp.zeros_like(b.data)
        mask = (matrix_b.ent_bin == b_id) & in_a
        ent = np.nonzero(mask)[0]
        if len(ent):
            a_bin = matrix_a.bins[matrix_a.ent_bin[pos_c[ent][0]]]
            blocks = jnp.take(
                a_bin.data, jnp.asarray(matrix_a.ent_slot[pos_c[ent]]), axis=0
            )
            new_data = new_data.at[jnp.asarray(matrix_b.ent_slot[ent])].set(blocks)
        b.data = new_data
    matrix_b.invalidate_dense_cache()
    matrix_b._note_mutation(matrix_b.keys)  # every stored value rewritten
    return matrix_b


# ----------------------------------------------------------- block reserve
def reserve_blocks(matrix: BlockSparseMatrix, rows, cols) -> BlockSparseMatrix:
    """Ensure the listed blocks exist (zero where absent, existing data
    kept) — vectorized (ref `dbcsr_reserve_blocks`,
    `dbcsr_block_access.F:493`).

    Already-present blocks are filtered out up front, so the steady
    state of an iterative chain (every block already reserved) is a
    pure host index check — no staging, no finalize, no host zero
    blocks.  Missing blocks of a non-symmetric matrix stage as DEVICE
    zeros (pool-recycled) through `stage_device_blocks`; the symmetric
    fallback keeps the host `put_blocks` summation-of-zeros path."""
    rows = np.ascontiguousarray(rows, np.int64)
    cols = np.ascontiguousarray(cols, np.int64)
    if len(rows) == 0:
        return matrix.finalize()
    if matrix.matrix_type != NO_SYMMETRY:
        fold = rows > cols
        rows, cols = np.where(fold, cols, rows), np.where(fold, rows, cols)
    keys = rows * matrix.nblkcols + cols
    uniq, first = np.unique(keys, return_index=True)
    rows, cols = rows[first], cols[first]
    if matrix.valid and len(matrix.keys):
        pos = np.minimum(np.searchsorted(matrix.keys, uniq),
                         len(matrix.keys) - 1)
        missing = matrix.keys[pos] != uniq
        if not missing.any():
            return matrix  # all present: zero work
        rows, cols = rows[missing], cols[missing]
    if matrix.matrix_type == NO_SYMMETRY:
        bm = matrix.row_blk_sizes[rows].astype(np.int64)
        bn = matrix.col_blk_sizes[cols].astype(np.int64)
        code = bm << 32 | bn
        for u in np.unique(code):
            sel = np.nonzero(code == u)[0]
            matrix.stage_device_blocks(
                rows[sel], cols[sel],
                mempool.zeros((len(sel), int(u >> 32), int(u & 0xFFFFFFFF)),
                              matrix.dtype),
                summation=True,
            )
        return matrix.finalize()
    bm = matrix.row_blk_sizes[rows]
    bn = matrix.col_blk_sizes[cols]
    if np.all(bm == bm[0]) and np.all(bn == bn[0]):
        blocks = np.zeros((len(rows), int(bm[0]), int(bn[0])), matrix.dtype)
    else:
        blocks = [
            np.zeros((int(bm[i]), int(bn[i])), matrix.dtype) for i in range(len(rows))
        ]
    matrix.put_blocks(rows, cols, blocks, summation=True)
    return matrix.finalize()


def reserve_diag_blocks(matrix: BlockSparseMatrix) -> BlockSparseMatrix:
    """Reserve all diagonal blocks (ref `dbcsr_reserve_diag_blocks`,
    `dbcsr_block_access.F:451`)."""
    n = min(matrix.nblkrows, matrix.nblkcols)
    idx = np.arange(n, dtype=np.int64)
    return reserve_blocks(matrix, idx, idx)


def reserve_all_blocks(matrix: BlockSparseMatrix) -> BlockSparseMatrix:
    """Reserve every block — the dense pattern (ref
    `dbcsr_reserve_all_blocks`, `dbcsr_block_access.F:391`)."""
    rows, cols = np.divmod(
        np.arange(matrix.nblkrows * matrix.nblkcols, dtype=np.int64), matrix.nblkcols
    )
    if matrix.matrix_type != NO_SYMMETRY:
        keep = rows <= cols  # canonical triangle only
        rows, cols = rows[keep], cols[keep]
    return reserve_blocks(matrix, rows, cols)


def hadamard_product(
    matrix_a: BlockSparseMatrix, matrix_b: BlockSparseMatrix, name: str = "hadamard"
) -> BlockSparseMatrix:
    """C = A .* B on the pattern intersection (ref `dbcsr_hadamard_product`,
    `dbcsr_operations.F:971`)."""
    _require_valid(matrix_a, matrix_b)
    _same_blocking(matrix_a, matrix_b)
    if matrix_a.matrix_type != NO_SYMMETRY or matrix_b.matrix_type != NO_SYMMETRY:
        # elementwise products change the symmetry class (A∘A is symmetric,
        # S∘A antisymmetric, ...); expand and return a plain matrix
        from dbcsr_tpu.ops.transformations import desymmetrize

        return hadamard_product(desymmetrize(matrix_a), desymmetrize(matrix_b), name)
    common = np.intersect1d(matrix_a.keys, matrix_b.keys)
    out = BlockSparseMatrix(
        name,
        matrix_a.row_blk_sizes,
        matrix_a.col_blk_sizes,
        matrix_a.dtype,
        matrix_a.dist,
        matrix_a.matrix_type,
    )
    pos_a = np.searchsorted(matrix_a.keys, common)
    pos_b = np.searchsorted(matrix_b.keys, common)
    rows = (common // matrix_a.nblkcols).astype(np.int64)
    cols = (common % matrix_a.nblkcols).astype(np.int64)
    from dbcsr_tpu.core.matrix import _bin_entries

    nb, nsl, shapes = _bin_entries(
        matrix_a.row_blk_sizes, matrix_a.col_blk_sizes, rows, cols
    )
    bins = []
    for b_id, (bm, bn) in enumerate(shapes):
        mask = nb == b_id
        count = int(mask.sum())
        cap = bucket_size(count)
        data = jnp.zeros((cap, bm, bn), matrix_a.dtype)
        if count:
            ent = np.nonzero(mask)[0]
            a_bin = matrix_a.ent_bin[pos_a[ent][0]]
            b_bin = matrix_b.ent_bin[pos_b[ent][0]]
            prod = jnp.take(
                matrix_a.bins[a_bin].data, jnp.asarray(matrix_a.ent_slot[pos_a[ent]]), axis=0
            ) * jnp.take(
                matrix_b.bins[b_bin].data, jnp.asarray(matrix_b.ent_slot[pos_b[ent]]), axis=0
            )
            data = data.at[jnp.asarray(nsl[mask])].set(prod)
        bins.append(_Bin((bm, bn), data, count))
    out.set_structure_from_device(common, bins, binning=(nb, nsl, shapes))
    return out


# ---------------------------------------------------------------- reductions
def trace(matrix: BlockSparseMatrix) -> complex:
    """tr(A) (ref `dbcsr_trace`)."""
    _require_valid(matrix)
    rows, cols = matrix.entry_coords()
    total = 0.0
    for b_id, b in enumerate(matrix.bins):
        mask = (matrix.ent_bin == b_id) & (rows == cols)
        if not mask.any():
            continue
        slots = mempool.upload_index("trace", matrix.ent_slot[mask])
        blocks = jnp.take(b.data, slots, axis=0)
        d = min(b.shape)
        total += complex(jnp.sum(jnp.trace(blocks[:, :d, :d], axis1=1, axis2=2)))
    return total if is_complex(matrix.dtype) else float(np.real(total))


def dot(matrix_a: BlockSparseMatrix, matrix_b: BlockSparseMatrix) -> complex:
    """tr(A^T B) = sum_ij A_ij B_ij (ref `dbcsr_dot`)."""
    _require_valid(matrix_a, matrix_b)
    _same_blocking(matrix_a, matrix_b)
    if matrix_a.matrix_type != matrix_b.matrix_type:
        # mixed symmetry classes: the implicit-triangle cross terms are not
        # derivable from the stored-product sum; expand
        from dbcsr_tpu.ops.transformations import desymmetrize

        return dot(desymmetrize(matrix_a), desymmetrize(matrix_b))
    mtype = matrix_a.matrix_type
    common = np.intersect1d(matrix_a.keys, matrix_b.keys)
    if mtype != NO_SYMMETRY:
        rows = common // matrix_a.nblkcols
        cols = common % matrix_a.nblkcols
    total = 0.0
    pos_a = np.searchsorted(matrix_a.keys, common)
    pos_b = np.searchsorted(matrix_b.keys, common)
    for b_id, b in enumerate(matrix_a.bins):
        mask = matrix_a.ent_bin[pos_a] == b_id
        if not mask.any():
            continue
        ent = np.nonzero(mask)[0]
        b_bin = matrix_b.ent_bin[pos_b[ent][0]]
        a_blk = jnp.take(b.data, jnp.asarray(matrix_a.ent_slot[pos_a[ent]]), axis=0)
        b_blk = jnp.take(
            matrix_b.bins[b_bin].data, jnp.asarray(matrix_b.ent_slot[pos_b[ent]]), axis=0
        )
        part = jnp.sum(a_blk * b_blk, axis=(1, 2))
        if mtype == NO_SYMMETRY:
            total += complex(jnp.sum(part))
        else:
            offdiag = rows[ent] != cols[ent]
            p = np.asarray(part).astype(complex)
            total += complex(p.sum())
            if mtype == HERMITIAN_TYPE:
                # implicit lower term is conj(A_ij)*conj(B_ij)
                total += complex(p[offdiag].conj().sum())
            else:
                # S.S and A.A both reproduce +A_ij*B_ij in the lower triangle
                total += complex(p[offdiag].sum())
    return total if is_complex(matrix_a.dtype) else float(np.real(total))


def frobenius_norm(matrix: BlockSparseMatrix) -> float:
    """||A||_F (ref `dbcsr_frobenius_norm`)."""
    _require_valid(matrix)
    norms = matrix.block_norms().astype(np.float64)
    if matrix.matrix_type == NO_SYMMETRY:
        return float(np.sqrt((norms**2).sum()))
    rows, cols = matrix.entry_coords()
    w = np.where(rows == cols, 1.0, 2.0)
    return float(np.sqrt((w * norms**2).sum()))


def maxabs_norm(matrix: BlockSparseMatrix) -> float:
    """max |a_ij| (ref `dbcsr_maxabs_norm`)."""
    _require_valid(matrix)
    best = 0.0
    for b in matrix.bins:
        if b.count:
            best = max(best, float(jnp.max(jnp.abs(b.data[: b.count]))))
    return best


def gershgorin_norm(matrix: BlockSparseMatrix) -> float:
    """max_i sum_j |a_ij| (ref `dbcsr_gershgorin_norm`)."""
    from dbcsr_tpu.ops.transformations import desymmetrize

    m = desymmetrize(matrix) if matrix.matrix_type != NO_SYMMETRY else matrix
    _require_valid(m)
    row_sums = np.zeros(m.nfullrows, np.float64)
    rows, _ = m.entry_coords()
    row_off = m.row_blk_offsets
    for b_id, b in enumerate(m.bins):
        mask = m.ent_bin == b_id
        if not mask.any():
            continue
        partial = np.asarray(
            jnp.sum(jnp.abs(jnp.take(b.data, jnp.asarray(m.ent_slot[mask]), axis=0)), axis=2)
        ).astype(np.float64)
        for e, r in enumerate(rows[mask]):
            o = row_off[r]
            row_sums[o : o + b.shape[0]] += partial[e]
    return float(row_sums.max(initial=0.0))


def column_norms(matrix: BlockSparseMatrix) -> np.ndarray:
    """Per-full-column 2-norms (ref `dbcsr_norm_col`)."""
    from dbcsr_tpu.ops.transformations import desymmetrize

    m = desymmetrize(matrix) if matrix.matrix_type != NO_SYMMETRY else matrix
    _require_valid(m)
    col_sq = np.zeros(m.nfullcols, np.float64)
    _, cols = m.entry_coords()
    col_off = m.col_blk_offsets
    for b_id, b in enumerate(m.bins):
        mask = m.ent_bin == b_id
        if not mask.any():
            continue
        blocks = jnp.take(b.data, jnp.asarray(m.ent_slot[mask]), axis=0)
        partial = np.asarray(jnp.sum(jnp.abs(blocks) ** 2, axis=1)).astype(np.float64)
        for e, c in enumerate(cols[mask]):
            o = col_off[c]
            col_sq[o : o + b.shape[1]] += partial[e]
    return np.sqrt(col_sq)


# ----------------------------------------------------------------- diagonal
@jax.jit
def _gather_diagonals(data, slots):
    """(S, d) diagonals of the selected blocks, one device gather."""
    d = min(data.shape[1], data.shape[2])
    blocks = jnp.take(data, slots, axis=0)
    return jnp.diagonal(blocks[:, :d, :d], axis1=1, axis2=2)


@jax.jit
def _set_diagonals(data, slots, vals):
    """Write (S, d) diagonal values into the selected blocks."""
    d = vals.shape[1]
    idx = jnp.arange(d)
    return data.at[slots[:, None], idx[None, :], idx[None, :]].set(vals)


@jax.jit
def _add_alpha_eye(data, slots, alpha):
    """Add alpha*I to the selected blocks (square up to min(bm, bn))."""
    d = min(data.shape[1], data.shape[2])
    idx = jnp.arange(d)
    return data.at[slots[:, None], idx[None, :], idx[None, :]].add(
        jnp.broadcast_to(alpha, (1, d)))


def _diag_entries(matrix: BlockSparseMatrix, b_id: int, rows, cols):
    """(entry indices, slots, block rows) of this bin's diagonal
    blocks; ``rows``/``cols`` are the caller's one `entry_coords`
    pass (hoisted so the per-bin loop is O(nblks) once, not per bin)."""
    sel = np.nonzero((matrix.ent_bin == b_id) & (rows == cols))[0]
    return sel, matrix.ent_slot[sel], rows[sel]


def get_diag(matrix: BlockSparseMatrix) -> np.ndarray:
    """Diagonal elements (ref `dbcsr_get_diag`) — one batched device
    gather per shape bin instead of a full per-block host fetch."""
    _require_valid(matrix)
    n = min(matrix.nfullrows, matrix.nfullcols)
    out = np.zeros(n, dtype=np.dtype(matrix.dtype))
    row_off = matrix.row_blk_offsets
    rows, cols = matrix.entry_coords()
    for b_id, b in enumerate(matrix.bins):
        sel, slots, rws = _diag_entries(matrix, b_id, rows, cols)
        if not len(sel):
            continue
        diags = np.asarray(_gather_diagonals(
            b.data, mempool.upload_index("diag", slots)))
        mempool.record_d2h(diags.nbytes)
        d = diags.shape[1]
        for i, r in enumerate(rws):
            o = row_off[r]
            out[o : o + d] = diags[i][: max(0, n - o)]
    return out


def set_diag(matrix: BlockSparseMatrix, values) -> BlockSparseMatrix:
    """Set diagonal elements of the stored diagonal blocks
    (ref `dbcsr_set_diag`) — one batched device scatter per shape bin,
    no host round-trip of the block data.  A diagonal block straddling
    the short edge of a non-square matrix gets only its in-range
    prefix written; its tail keeps the stored values."""
    _require_valid(matrix)
    v = np.asarray(values)
    n = min(matrix.nfullrows, matrix.nfullcols)
    row_off = matrix.row_blk_offsets
    rows, cols = matrix.entry_coords()
    touched = []  # diag block keys written, for the delta journal
    for b_id, b in enumerate(matrix.bins):
        sel, slots, rws = _diag_entries(matrix, b_id, rows, cols)
        if not len(sel):
            continue
        d = min(b.shape)
        widths = np.maximum(0, np.minimum(d, n - row_off[rws]))
        slots_dev = mempool.upload_index("diag", slots)
        if (widths < d).any():
            # straddling blocks: keep the out-of-range diagonal tail
            # (np.array: a writable host copy — np.asarray of a jax
            # array is a read-only view)
            vals = np.array(_gather_diagonals(b.data, slots_dev),
                            dtype=np.dtype(matrix.dtype))
        else:
            vals = np.zeros((len(sel), d), np.dtype(matrix.dtype))
        for i, r in enumerate(rws):
            o = row_off[r]
            w = int(widths[i])
            vals[i, :w] = v[o : o + w]
        mempool.record_h2d(vals.nbytes)
        new = _set_diagonals(b.data, slots_dev, jnp.asarray(vals))
        if matrix._donatable:
            mempool.release(b.data)  # non-donating jit: old buffer dies here
        b.data = new
        touched.append(matrix.keys[sel])
    matrix.invalidate_dense_cache()
    matrix._note_mutation(
        np.concatenate(touched) if touched else matrix.keys[:0])
    return matrix


def add_on_diag(matrix: BlockSparseMatrix, alpha) -> BlockSparseMatrix:
    """A <- A + alpha*I, reserving missing diagonal blocks
    (ref `dbcsr_add_on_diag`).  Fully device-side: missing diagonal
    blocks reserve through the pool-backed fast path (a no-op once the
    chain's pattern is steady), then one scatter-add of alpha*I per
    shape bin — the per-block host fetch+put round-trip this op used
    to pay every chain iteration is gone."""
    _require_valid(matrix)
    n = min(matrix.nblkrows, matrix.nblkcols)
    for r in range(n):
        if matrix.row_blk_sizes[r] != matrix.col_blk_sizes[r]:
            raise ValueError("add_on_diag needs square diagonal blocks")
    idx = np.arange(n, dtype=np.int64)
    reserve_blocks(matrix, idx, idx)
    a = jnp.asarray(alpha).astype(matrix.dtype)
    rows, cols = matrix.entry_coords()
    touched = []  # diag block keys written, for the delta journal
    for b_id, b in enumerate(matrix.bins):
        sel, slots, _ = _diag_entries(matrix, b_id, rows, cols)
        if not len(sel):
            continue
        new = _add_alpha_eye(
            b.data, mempool.upload_index("diag", slots), a)
        if matrix._donatable:
            mempool.release(b.data)  # non-donating jit: old buffer dies here
        b.data = new
        touched.append(matrix.keys[sel])
    matrix.invalidate_dense_cache()
    matrix._note_mutation(
        np.concatenate(touched) if touched else matrix.keys[:0])
    return matrix


# ------------------------------------------------------------ triu / crop
@jax.jit
def _zero_strict_lower(data, slots):
    """Zero the strictly-lower triangle of the selected blocks."""
    bm, bn = data.shape[1], data.shape[2]
    ri = jnp.arange(bm)[None, :, None]
    ci = jnp.arange(bn)[None, None, :]
    blocks = jnp.take(data, slots, axis=0)
    blocks = jnp.where(ri > ci, jnp.zeros_like(blocks), blocks)
    return data.at[slots].set(blocks)


def triu(matrix: BlockSparseMatrix) -> BlockSparseMatrix:
    """In-place block upper triangle (ref `dbcsr_triu`,
    `dbcsr_operations.F:1849-1885`): drop blocks with block-row >
    block-col, zero the strictly-lower elements of diagonal blocks."""
    _require_valid(matrix)
    if matrix.matrix_type != NO_SYMMETRY:
        # stored triangle is already row<=col; materialize plain type
        from dbcsr_tpu.ops.transformations import desymmetrize

        desymmetrized = desymmetrize(matrix, name=matrix.name)
        _swap_state(matrix, desymmetrized)
    rows, cols = matrix.entry_coords()
    compress(matrix, rows <= cols)
    rows, cols = matrix.entry_coords()
    diag = np.nonzero(rows == cols)[0]
    for b_id, b in enumerate(matrix.bins):
        sel = diag[matrix.ent_bin[diag] == b_id]
        if len(sel):
            b.data = _zero_strict_lower(b.data, jnp.asarray(matrix.ent_slot[sel]))
    matrix.invalidate_dense_cache()
    matrix._note_mutation(matrix.keys[diag])
    return matrix


def window_mask(bm: int, bn: int, r_lo, r_hi, c_lo, c_hi):
    """(N, bm, bn) bool mask of block-local element windows: True where
    row in [r_lo, r_hi] and col in [c_lo, c_hi] (per block).  Shared by
    the crop op and the multiply engine's windowed-beta scatter."""
    ri = jnp.arange(bm)[None, :, None]
    ci = jnp.arange(bn)[None, None, :]
    return (
        (ri >= r_lo[:, None, None])
        & (ri <= r_hi[:, None, None])
        & (ci >= c_lo[:, None, None])
        & (ci <= c_hi[:, None, None])
    )


@jax.jit
def _mask_block_range(data, slots, r_lo, r_hi, c_lo, c_hi):
    """Keep only elements with block-local row in [r_lo, r_hi] and col in
    [c_lo, c_hi] (per selected block); zero the rest."""
    keep = window_mask(data.shape[1], data.shape[2], r_lo, r_hi, c_lo, c_hi)
    blocks = jnp.take(data, slots, axis=0)
    return data.at[slots].set(jnp.where(keep, blocks, jnp.zeros_like(blocks)))


def crop_matrix(
    matrix: BlockSparseMatrix,
    row_bounds=None,
    col_bounds=None,
    name: Optional[str] = None,
) -> BlockSparseMatrix:
    """Copy restricted to an element range (ref `dbcsr_crop_matrix`,
    `dbcsr_operations.F:1666-1847`).  Bounds are inclusive 0-based
    (element, not block) index pairs; blocking is unchanged — blocks
    straddling a bound keep zeros outside it."""
    _require_valid(matrix)
    from dbcsr_tpu.ops.transformations import desymmetrize

    src = desymmetrize(matrix) if matrix.matrix_type != NO_SYMMETRY else matrix
    out = copy(src, name=name or f"crop({matrix.name})")
    r0, r1 = row_bounds if row_bounds is not None else (0, out.nfullrows - 1)
    c0, c1 = col_bounds if col_bounds is not None else (0, out.nfullcols - 1)
    roff = out.row_blk_offsets
    coff = out.col_blk_offsets
    rows, cols = out.entry_coords()
    keep = (
        (roff[rows + 1] - 1 >= r0)
        & (roff[rows] <= r1)
        & (coff[cols + 1] - 1 >= c0)
        & (coff[cols] <= c1)
    )
    compress(out, keep)
    rows, cols = out.entry_coords()
    # blocks straddling a bound get the outside part zeroed
    r_lo = np.maximum(r0 - roff[rows], 0)
    r_hi = np.minimum(r1 - roff[rows], out.row_blk_sizes[rows] - 1)
    c_lo = np.maximum(c0 - coff[cols], 0)
    c_hi = np.minimum(c1 - coff[cols], out.col_blk_sizes[cols] - 1)
    partial = (
        (r_lo > 0)
        | (r_hi < out.row_blk_sizes[rows] - 1)
        | (c_lo > 0)
        | (c_hi < out.col_blk_sizes[cols] - 1)
    )
    sel = np.nonzero(partial)[0]
    for b_id, b in enumerate(out.bins):
        ss = sel[out.ent_bin[sel] == b_id]
        if len(ss):
            b.data = _mask_block_range(
                b.data,
                jnp.asarray(out.ent_slot[ss]),
                jnp.asarray(r_lo[ss]),
                jnp.asarray(r_hi[ss]),
                jnp.asarray(c_lo[ss]),
                jnp.asarray(c_hi[ss]),
            )
    return out


def verify_matrix(matrix: BlockSparseMatrix, check_data: bool = True) -> bool:
    """Structural invariant check (ref `dbcsr_verify_matrix`,
    `dbcsr_dist_util.F:578-732`); raises ValueError on violation.

    Explicit raises (not ``assert``) so the checker keeps its contract
    under ``python -O``."""

    def _check(cond, msg):
        if not cond:
            raise ValueError(f"verify_matrix({matrix.name}): {msg}")

    _require_valid(matrix)
    keys = matrix.keys
    _check(np.all(np.diff(keys) > 0), "index keys not strictly sorted")
    nb = matrix.nblkrows * matrix.nblkcols
    _check(len(keys) == 0 or (keys[0] >= 0 and keys[-1] < nb), "key out of range")
    rows, cols = matrix.entry_coords()
    counts = np.bincount(rows, minlength=matrix.nblkrows)
    _check(np.array_equal(np.diff(matrix.row_ptr), counts), "row_ptr inconsistent")
    _check(
        len(matrix.ent_bin) == len(keys) and len(matrix.ent_slot) == len(keys),
        "entry->bin maps length mismatch",
    )
    for b_id, b in enumerate(matrix.bins):
        sel = matrix.ent_bin == b_id
        slots = matrix.ent_slot[sel]
        _check(len(np.unique(slots)) == len(slots), f"bin {b_id} slot collision")
        _check(b.count == int(sel.sum()), f"bin {b_id} count mismatch")
        _check(b.data.shape[0] >= b.count, f"bin {b_id} capacity < count")
        _check(slots.size == 0 or slots.max() < b.count, f"bin {b_id} slot >= count")
        bm, bn = b.shape
        _check(np.all(matrix.row_blk_sizes[rows[sel]] == bm), f"bin {b_id} row size")
        _check(np.all(matrix.col_blk_sizes[cols[sel]] == bn), f"bin {b_id} col size")
    if matrix.matrix_type != NO_SYMMETRY:
        _check(np.all(rows <= cols), "symmetric matrix stores lower-triangle block")
    if check_data:
        for b in matrix.bins:
            if b.count:
                finite = jnp.all(jnp.isfinite(b.data.real)) & jnp.all(
                    jnp.isfinite(b.data.imag)
                )
                _check(bool(finite), "non-finite block data")
    return True
