"""Structure transformations: transpose, desymmetrize, redistribute.

Analogs of `src/ops/dbcsr_transformations.F`: `dbcsr_new_transposed`
(:113), `dbcsr_desymmetrize_deep` (:307), `dbcsr_redistribute` (:1951).
Index permutations happen on host (NumPy); block data moves in bulk on
device (one gather+transpose per shape bin).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dbcsr_tpu.core.dist import Distribution
from dbcsr_tpu.core.matrix import (
    ANTISYMMETRIC,
    HERMITIAN,
    NO_SYMMETRY,
    SYMMETRIC,
    BlockSparseMatrix,
    _Bin,
    _bin_entries,
)
from dbcsr_tpu.utils.rounding import bucket_size


@functools.partial(jax.jit, static_argnames=("capacity", "transpose", "conjugate", "negate"))
def _gather_blocks(data, slots, capacity, transpose=False, conjugate=False, negate=False):
    out = jnp.take(data, slots, axis=0)
    if transpose:
        out = jnp.swapaxes(out, 1, 2)
    if conjugate:
        out = jnp.conj(out)
    if negate:
        out = -out
    pad = capacity - out.shape[0]
    if pad > 0:
        out = jnp.concatenate([out, jnp.zeros((pad,) + out.shape[1:], out.dtype)])
    return out


def new_transposed(
    matrix: BlockSparseMatrix,
    conjugate: bool = False,
    name: Optional[str] = None,
) -> BlockSparseMatrix:
    """Out-of-place transpose (ref `dbcsr_new_transposed`,
    `dbcsr_transformations.F:113`)."""
    if not matrix.valid:
        raise RuntimeError("finalize() before transposing")
    m = matrix
    if m.matrix_type != NO_SYMMETRY:
        m = desymmetrize(m)
    t = BlockSparseMatrix(
        name or (m.name + "^T"),
        m.col_blk_sizes,
        m.row_blk_sizes,
        m.dtype,
        m.dist.transposed(),
        NO_SYMMETRY,
    )
    rows, cols = m.entry_coords()
    new_keys = cols * m.nblkrows + rows
    order = np.argsort(new_keys, kind="stable")
    t_keys = new_keys[order]
    t_rows = cols[order]
    t_cols = rows[order]
    old_bin = m.ent_bin[order]
    old_slot = m.ent_slot[order]
    nb, nsl, shapes = _bin_entries(t.row_blk_sizes, t.col_blk_sizes, t_rows, t_cols)
    bins = []
    for b, (bm, bn) in enumerate(shapes):
        mask = nb == b
        count = int(mask.sum())
        src_bin = m.bins[old_bin[mask][0]]
        # slot p of the new bin holds old slot perm[p], transposed
        perm = np.empty(count, np.int32)
        perm[nsl[mask]] = old_slot[mask]
        data = _gather_blocks(
            src_bin.data,
            jnp.asarray(perm),
            bucket_size(count),
            transpose=True,
            conjugate=conjugate,
        )
        bins.append(_Bin((bm, bn), data, count))
    t.keys = t_keys
    t.row_ptr = np.zeros(t.nblkrows + 1, np.int64)
    np.add.at(t.row_ptr, t_rows + 1, 1)
    np.cumsum(t.row_ptr, out=t.row_ptr)
    t.ent_bin = nb
    t.ent_slot = nsl
    t.bins = bins
    t._shape_to_bin = {b.shape: i for i, b in enumerate(bins)}
    t.valid = True
    return t


def desymmetrize(matrix: BlockSparseMatrix, name: Optional[str] = None) -> BlockSparseMatrix:
    """Expand canonical triangular storage to a full non-symmetric matrix
    (ref `dbcsr_desymmetrize_deep`, `dbcsr_transformations.F:307`)."""
    if matrix.matrix_type == NO_SYMMETRY:
        return matrix.copy(name)
    out = BlockSparseMatrix(
        name or (matrix.name + "_desym"),
        matrix.row_blk_sizes,
        matrix.col_blk_sizes,
        matrix.dtype,
        matrix.dist,
        NO_SYMMETRY,
    )
    for r, c, blk in matrix.iterate_blocks():
        out.put_block(r, c, blk)
        if r != c:
            if matrix.matrix_type == SYMMETRIC:
                out.put_block(c, r, blk.T)
            elif matrix.matrix_type == ANTISYMMETRIC:
                out.put_block(c, r, -blk.T)
            elif matrix.matrix_type == HERMITIAN:
                out.put_block(c, r, blk.conj().T)
    return out.finalize()


def submatrix(
    matrix: BlockSparseMatrix,
    row_lo: int,
    row_hi: int,
    col_lo: int,
    col_hi: int,
    name: Optional[str] = None,
) -> BlockSparseMatrix:
    """Block-index submatrix [row_lo, row_hi) x [col_lo, col_hi) with
    renumbered block indices (ref `dbcsr_crop_matrix` flavor; also the
    building block of the TAS grid split, `dbcsr_tas_split.F`).
    Block data is shared (device arrays are immutable); only the index
    is rebuilt."""
    if matrix.matrix_type != NO_SYMMETRY:
        matrix = desymmetrize(matrix)
    if not matrix.valid:
        raise RuntimeError("finalize() first")
    rows, cols = matrix.entry_coords()
    keep = (rows >= row_lo) & (rows < row_hi) & (cols >= col_lo) & (cols < col_hi)
    out = BlockSparseMatrix(
        name or f"{matrix.name}[{row_lo}:{row_hi},{col_lo}:{col_hi}]",
        matrix.row_blk_sizes[row_lo:row_hi],
        matrix.col_blk_sizes[col_lo:col_hi],
        matrix.dtype,
        None,
        NO_SYMMETRY,
    )
    sub_rows = rows[keep] - row_lo
    sub_cols = cols[keep] - col_lo
    new_keys = sub_rows * out.nblkcols + sub_cols
    order = np.argsort(new_keys, kind="stable")
    new_keys = new_keys[order]
    ent = np.nonzero(keep)[0][order]
    old_bin = matrix.ent_bin[ent]
    old_slot = matrix.ent_slot[ent]
    nb, nsl, shapes = _bin_entries(
        out.row_blk_sizes, out.col_blk_sizes, sub_rows[order], sub_cols[order]
    )
    bins = []
    for b, (bm, bn) in enumerate(shapes):
        mask = nb == b
        count = int(mask.sum())
        src_bin = matrix.bins[old_bin[mask][0]]
        perm = np.empty(count, np.int32)
        perm[nsl[mask]] = old_slot[mask]
        data = _gather_blocks(src_bin.data, jnp.asarray(perm), bucket_size(count))
        bins.append(_Bin((bm, bn), data, count))
    out.set_structure_from_device(new_keys, bins, binning=(nb, nsl, shapes))
    return out


def redistribute(
    matrix: BlockSparseMatrix, dist: Distribution, name: Optional[str] = None
) -> BlockSparseMatrix:
    """Move a matrix onto a new distribution (ref `dbcsr_redistribute`,
    `dbcsr_transformations.F:1951`).

    The returned copy carries ``dist``, which the distributed engine
    honors when assembling device panels (`parallel/sparse_dist.py:
    _resolve_maps`), so blocks genuinely land on different devices at
    the next mesh operation.  In the single-controller model the host
    index is global; the data movement happens at panel-assembly time
    rather than eagerly (the reference, with per-rank memory, must move
    immediately — `dbcsr_transformations.F:1951`).
    """
    if dist.nblkrows != matrix.nblkrows or dist.nblkcols != matrix.nblkcols:
        raise ValueError("distribution blocking mismatch")
    out = matrix.copy(name)
    out.dist = dist
    return out
