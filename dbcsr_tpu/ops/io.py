"""Binary matrix I/O — the checkpoint/restore path.

Ref `src/ops/dbcsr_io.F` (`dbcsr_binary_write`:578, `dbcsr_binary_read`
:757): serialize a matrix as header + index + data and restore it,
possibly under a new distribution.  The reference streams per-rank
offsets over MPI-IO; here one file holds a JSON header followed by raw
little-endian arrays (index, then per-shape-bin block data), written
from the host index and bulk-fetched device bins.
"""

from __future__ import annotations

import json
import struct
from typing import Optional

import numpy as np

from dbcsr_tpu.core.dist import Distribution
from dbcsr_tpu.core.matrix import BlockSparseMatrix

_MAGIC = b"DBCSRTPU"
_VERSION = 1


def binary_write(matrix: BlockSparseMatrix, path: str) -> None:
    """Serialize a finalized matrix (ref `dbcsr_binary_write`)."""
    if not matrix.valid:
        raise RuntimeError("finalize() first")
    header = {
        "version": _VERSION,
        "name": matrix.name,
        "dtype": np.dtype(matrix.dtype).str,
        "matrix_type": matrix.matrix_type,
        "row_blk_sizes": matrix.row_blk_sizes.tolist(),
        "col_blk_sizes": matrix.col_blk_sizes.tolist(),
        "nblks": int(matrix.nblks),
        "bins": [
            {"shape": list(b.shape), "count": int(b.count)} for b in matrix.bins
        ],
    }
    hbytes = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<q", len(hbytes)))
        f.write(hbytes)
        matrix.keys.astype("<i8").tofile(f)
        matrix.ent_bin.astype("<i4").tofile(f)
        matrix.ent_slot.astype("<i4").tofile(f)
        for b in matrix.bins:
            np.asarray(b.data[: b.count]).astype(header["dtype"]).tofile(f)


def binary_read(
    path: str, dist: Optional[Distribution] = None, name: Optional[str] = None
) -> BlockSparseMatrix:
    """Restore a matrix, optionally under a new distribution
    (ref `dbcsr_binary_read`)."""
    import jax.numpy as jnp

    from dbcsr_tpu.core.matrix import _Bin
    from dbcsr_tpu.utils.rounding import bucket_size

    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path}: not a dbcsr_tpu binary matrix")
        (hlen,) = struct.unpack("<q", f.read(8))
        header = json.loads(f.read(hlen).decode())
        if header["version"] != _VERSION:
            raise ValueError(f"unsupported version {header['version']}")
        nblks = header["nblks"]
        keys = np.fromfile(f, "<i8", nblks)
        ent_bin = np.fromfile(f, "<i4", nblks)
        ent_slot = np.fromfile(f, "<i4", nblks)
        dtype = np.dtype(header["dtype"])
        bins = []
        for binfo in header["bins"]:
            bm, bn = binfo["shape"]
            count = binfo["count"]
            host = np.fromfile(f, dtype, count * bm * bn).reshape(count, bm, bn)
            cap = bucket_size(count)
            if cap > count:
                host = np.concatenate(
                    [host, np.zeros((cap - count, bm, bn), dtype)]
                )
            bins.append(_Bin((bm, bn), jnp.asarray(host), count))
    m = BlockSparseMatrix(
        name or header["name"],
        header["row_blk_sizes"],
        header["col_blk_sizes"],
        dtype,
        dist,
        header["matrix_type"],
    )
    m.keys = keys
    rows = (keys // m.nblkcols).astype(np.int64)
    m.row_ptr = np.zeros(m.nblkrows + 1, np.int64)
    np.add.at(m.row_ptr, rows + 1, 1)
    np.cumsum(m.row_ptr, out=m.row_ptr)
    m.ent_bin = ent_bin
    m.ent_slot = ent_slot
    m.bins = bins
    m._shape_to_bin = {b.shape: i for i, b in enumerate(bins)}
    m.valid = True
    return m


def print_matrix(
    matrix: BlockSparseMatrix, file=None, nodata: bool = False
) -> None:
    """Human-readable dump: header plus every stored block
    (ref `dbcsr_print`, `src/ops/dbcsr_io.F`)."""
    import sys

    out = file or sys.stdout
    info = matrix.get_info()
    print(
        f"DBCSR {info['name']!r} {info['nfullrows_total']}x{info['nfullcols_total']} "
        f"({info['nblkrows_total']}x{info['nblkcols_total']} blocks), "
        f"type={info['matrix_type']}, dtype={info['data_type']}, "
        f"{info['nblks']} blocks stored, occ={info['occupation']:.4f}",
        file=out,
    )
    if nodata:
        return
    for r, c, blk in matrix.iterate_blocks():
        print(f" block ({r},{c}) {blk.shape[0]}x{blk.shape[1]}:", file=out)
        with np.printoptions(precision=6, suppress=True):
            print(np.array2string(blk, prefix="  "), file=out)


def print_block_sum(matrix: BlockSparseMatrix, file=None) -> None:
    """Print the element sum of each stored block, one line per block —
    a cheap cross-implementation fingerprint (ref `dbcsr_print_block_sum`,
    `src/ops/dbcsr_io.F:1081`)."""
    import sys

    import jax.numpy as jnp

    out = file or sys.stdout
    sums = np.zeros(matrix.nblks, np.dtype(matrix.dtype))
    for b_id, b in enumerate(matrix.bins):
        if b.count == 0:
            continue
        mask = matrix.ent_bin == b_id
        bin_sums = np.asarray(jnp.sum(b.data, axis=(1, 2)))
        sums[mask] = bin_sums[matrix.ent_slot[mask]]
    rows, cols = matrix.entry_coords()
    for e in range(matrix.nblks):
        print(f"{int(rows[e]) + 1:7d} {int(cols[e]) + 1:7d} {sums[e]:.10E}", file=out)
