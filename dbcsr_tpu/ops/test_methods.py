"""Randomized test utilities: the dense oracle pattern.

Analog of `src/ops/dbcsr_test_methods.F` (`dbcsr_make_random_matrix`:70,
`dbcsr_to_dense_local`) — the reference's core verification approach
(SURVEY §4): build random block-sparse matrices, run the sparse op,
densify, compare against dense NumPy within epsilon.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dbcsr_tpu.core.dist import Distribution
from dbcsr_tpu.core.kinds import dtype_of, is_complex
from dbcsr_tpu.core.matrix import NO_SYMMETRY, BlockSparseMatrix


# module-level generator used when no rng is passed; re-seedable like
# the reference's global random-matrix seed (ref `dbcsr_reset_randmat_seed`)
_RANDMAT_SEED = 0
_randmat_rng = np.random.default_rng(_RANDMAT_SEED)


def reset_randmat_seed(seed: int = _RANDMAT_SEED) -> None:
    """Reset the default random-matrix stream (ref
    `dbcsr_reset_randmat_seed`, `dbcsr_api.F:177`) so runs reproduce."""
    global _randmat_rng
    _randmat_rng = np.random.default_rng(seed)


def make_random_matrix(
    name: str,
    row_blk_sizes,
    col_blk_sizes,
    dtype=np.float64,
    occupation: float = 0.5,
    dist: Optional[Distribution] = None,
    matrix_type: str = NO_SYMMETRY,
    rng=None,
) -> BlockSparseMatrix:
    """Random block-sparse matrix with ~`occupation` block fill
    (ref `dbcsr_make_random_matrix`, `dbcsr_test_methods.F:70`)."""
    rng = rng or _randmat_rng
    m = BlockSparseMatrix(name, row_blk_sizes, col_blk_sizes, dtype, dist, matrix_type)
    dt = dtype_of(dtype)
    nbr, nbc = m.nblkrows, m.nblkcols
    present = rng.random((nbr, nbc)) < occupation
    if matrix_type != NO_SYMMETRY:
        present = np.triu(present)
    rows, cols = np.nonzero(present)
    for r, c in zip(rows, cols):
        shape = m.block_shape(r, c)
        blk = rng.standard_normal(shape)
        if is_complex(dt):
            blk = blk + 1j * rng.standard_normal(shape)
        if matrix_type != NO_SYMMETRY and r == c:
            blk = (blk + _fold(blk, matrix_type)) / 2  # consistent diagonal
        m.put_block(r, c, blk.astype(dt))
    return m.finalize()


def _fold(blk, matrix_type):
    if matrix_type == "S":
        return blk.T
    if matrix_type == "A":
        return -blk.T
    return blk.conj().T


def to_dense(matrix: BlockSparseMatrix) -> np.ndarray:
    """Densify locally (ref `dbcsr_to_dense_local`,
    used at `tests/dbcsr_test_multiply.F:315-317`)."""
    out = np.zeros((matrix.nfullrows, matrix.nfullcols), dtype=np.dtype(matrix.dtype))
    row_off = matrix.row_blk_offsets
    col_off = matrix.col_blk_offsets
    for r, c, blk in matrix.iterate_blocks():
        out[row_off[r] : row_off[r] + blk.shape[0], col_off[c] : col_off[c] + blk.shape[1]] = blk
        if matrix.matrix_type != NO_SYMMETRY and r != c:
            tb = _fold(blk, matrix.matrix_type)
            out[col_off[c] : col_off[c] + blk.shape[1], row_off[r] : row_off[r] + blk.shape[0]] = tb
    return out


def from_dense(
    name: str,
    dense: np.ndarray,
    row_blk_sizes,
    col_blk_sizes,
    dist: Optional[Distribution] = None,
    keep_zero_blocks: bool = False,
) -> BlockSparseMatrix:
    """Blocked matrix from a dense array, dropping all-zero blocks."""
    m = BlockSparseMatrix(name, row_blk_sizes, col_blk_sizes, dense.dtype, dist)
    row_off = m.row_blk_offsets
    col_off = m.col_blk_offsets
    for r in range(m.nblkrows):
        for c in range(m.nblkcols):
            blk = dense[
                row_off[r] : row_off[r + 1], col_off[c] : col_off[c + 1]
            ]
            if keep_zero_blocks or np.any(blk != 0):
                m.put_block(r, c, blk)
    return m.finalize()


def impose_sparsity(dense: np.ndarray, matrix: BlockSparseMatrix) -> np.ndarray:
    """Zero out dense entries outside the matrix's block pattern
    (ref `dbcsr_impose_sparsity`, `dbcsr_test_multiply.F:633`)."""
    mask = np.zeros_like(dense, dtype=bool)
    row_off = matrix.row_blk_offsets
    col_off = matrix.col_blk_offsets
    rows, cols = matrix.entry_coords()
    for r, c in zip(rows, cols):
        mask[row_off[r] : row_off[r + 1], col_off[c] : col_off[c + 1]] = True
        if matrix.matrix_type != NO_SYMMETRY and r != c:
            mask[col_off[c] : col_off[c + 1], row_off[r] : row_off[r + 1]] = True
    out = dense.copy()
    out[~mask] = 0
    return out


_pos_term_jit = None


def _pos_checksum_bin(data, ro, co):
    """Jitted per-bin position-dependent checksum term (one compiled
    callable, retraced per bin shape; returns a device scalar)."""
    global _pos_term_jit
    if _pos_term_jit is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _term(data, ro, co):
            bm, bn = data.shape[1], data.shape[2]
            grow = ro[:, None, None] + 1.0 + jnp.arange(
                bm, dtype=jnp.float64)[None, :, None]
            gcol = co[:, None, None] + 1.0 + jnp.arange(
                bn, dtype=jnp.float64)[None, None, :]
            w = jnp.log(jnp.abs(grow * gcol))
            return (jnp.real(data).astype(jnp.float64) * w).sum()

        _pos_term_jit = _term
    return _pos_term_jit(data, ro, co)


def checksum(matrix: BlockSparseMatrix, pos: bool = False) -> float:
    """Scalar checksum (ref `dbcsr_checksum`, `src/dist/dbcsr_dist_util.F:431`).

    Default: sum of squares of stored elements.  With ``pos``, the
    position-dependent variant of the reference (`pd_blk_cs`,
    `dbcsr_dist_util.F:551`): sum of Re(a[r,c]) * log(grow * gcol) with
    1-based global element coordinates — catches blocks landing at wrong
    positions, which the plain sum of squares cannot.
    """
    if pos:
        # per-bin DEVICE reduction, one 8-byte fetch per bin: the
        # previous host-loop implementation fetched every block —
        # through the axon tunnel a full-matrix d2h fetch persistently
        # degrades the session (PERF_NOTES.md), and the perf driver
        # computes this checksum after every run
        import jax.numpy as jnp

        row_off = matrix.row_blk_offsets
        col_off = matrix.col_blk_offsets
        rows, cols = matrix.entry_coords()
        total = 0.0
        for b_id, b in enumerate(matrix.bins):
            if b.count == 0:
                continue
            mask = matrix.ent_bin == b_id
            ro = np.zeros(b.count, np.float64)
            co = np.zeros(b.count, np.float64)
            slots = matrix.ent_slot[mask]
            ro[slots] = row_off[rows[mask]]
            co[slots] = col_off[cols[mask]]
            total += float(
                _pos_checksum_bin(b.data[: b.count], jnp.asarray(ro),
                                  jnp.asarray(co))
            )
        return total
    norms = matrix.block_norms().astype(np.float64)
    if matrix.matrix_type != NO_SYMMETRY:
        rows, cols = matrix.entry_coords()
        w = np.where(rows == cols, 1.0, 2.0)
        return float((w * norms**2).sum())
    return float((norms**2).sum())
