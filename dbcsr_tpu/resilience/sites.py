"""Checked registry of every fault-injection site.

Pure data, import-free (tools/lint parses this file with stdlib
``ast``; the chaos suite imports it).  One source of truth for three
previously hand-kept lists:

* the site table in `docs/resilience.md` is GENERATED from this dict
  (``python -m tools.lint --gen-docs`` rewrites the block between the
  ``lint:sites`` markers);
* `tools/chaos_suite.py` derives its schedule draw (`chaos_sites`) and
  corruption targets (`chaos_corrupt_targets`) from it;
* the static analyzer (rule ``fault-site-registry``) checks that every
  literal site passed to `resilience.faults.maybe_inject` /
  ``corrupt`` / ``fail_probe`` in source is registered here, and that
  every registered site appears in the docs table.

Fields per site: ``boundary`` (docs-table cell), ``corruptible``
(honors nan/flip output corruption), ``chaos`` (drawn by the chaos
suite's randomized schedule — multi-process-only and bench-only sites
stay out), ``dynamic`` (the site name reaches the injection call
through a variable, so the analyzer does not require a source
literal).
"""

SITES = {
    "execute_stack": {
        "boundary": "`acc.smm.execute_stack` per driver launch",
        "corruptible": True, "chaos": True, "dynamic": False,
    },
    "execute_superstack": {
        "boundary": "`acc.smm.execute_superstack` per fused C-bin launch "
                    "(`docs/performance.md`)",
        # corruption honored at the fused boundary, but kept out of the
        # randomized chaos draw (historical set): the fused engine's
        # fault recovery is pinned by targeted tests in
        # tests/test_resilience.py instead
        "corruptible": True, "chaos": False, "dynamic": False,
    },
    "prepare_stack": {
        "boundary": "`acc.smm.prepare_stack` (host-side planning)",
        "corruptible": False, "chaos": True, "dynamic": False,
    },
    "dense": {
        "boundary": "the canvas paths in `mm.multiply` (whole-panel "
                    "dense AND the batched composite panels share this "
                    "site: one failover, one corruption hook)",
        "corruptible": True, "chaos": True, "dynamic": False,
    },
    "format_plan": {
        "boundary": "the storage-format planner's decision boundary "
                    "(`mm.format_planner.choose`) — a fault degrades "
                    "the plan to the stack format for that product "
                    "only, never cached (labels `name`)",
        "corruptible": False, "chaos": True, "dynamic": False,
    },
    "multihost_init": {
        "boundary": "`parallel.multihost.init_multihost`",
        # multi-process world joins cannot fire inside the single-process
        # chaos suite
        "corruptible": False, "chaos": False, "dynamic": False,
    },
    "collective": {
        "boundary": "`parallel.sparse_dist` mesh dispatch boundary",
        # kept out of the randomized draw (historical set): the mesh
        # corpus cases fault the tick edges below instead
        "corruptible": False, "chaos": False, "dynamic": False,
    },
    "mesh_shift": {
        "boundary": "the double-buffered Cannon tick/shift boundary "
                    "(`parallel.overlap.run_ticks`, one per ring shift; "
                    "labels `engine`, `tick`)",
        "corruptible": True, "chaos": True, "dynamic": True,
    },
    "gather_chunk": {
        "boundary": "the chunked all-gather pipeline's per-shard ring "
                    "step on rectangular grids (same `run_ticks` edge, "
                    "breaker `gather_pipe`; labels `engine`, `tick`)",
        "corruptible": True, "chaos": True, "dynamic": True,
    },
    "tas_tick": {
        "boundary": "the staggered grouped-TAS metronome's tick/shift "
                    "edge (breaker `cannon_db` keyed engine=\"tas\")",
        "corruptible": True, "chaos": True, "dynamic": True,
    },
    "incremental": {
        "boundary": "the delta-aware incremental multiply's splice path "
                    "(`mm.incremental`; raise/oom abort the splice and "
                    "fall back to a full recompute, nan/flip corrupt the "
                    "spliced C — `docs/resilience.md` § incremental)",
        "corruptible": True, "chaos": True, "dynamic": False,
    },
    "probe": {
        "boundary": "`bench._probe_tpu`",
        # bench-only boolean site (fail_probe), not a multiply boundary
        "corruptible": False, "chaos": False, "dynamic": False,
    },
    "attribution": {
        "boundary": "the cost-attribution billing boundary "
                    "(`obs.attribution.bill_window`) — a fault is "
                    "observed (bus event + counter) but ALWAYS "
                    "swallowed before any ledger mutation, so the "
                    "books stay balanced (labels `requests`, "
                    "`request_id`)",
        "corruptible": False, "chaos": True, "dynamic": False,
    },
    "serve_admit": {
        "boundary": "serving-plane admission (`serve.queue`) — a fault "
                    "sheds the submission with a structured rejection "
                    "(labels `tenant`, `request_id`; `docs/serving.md`)",
        "corruptible": False, "chaos": True, "dynamic": False,
    },
    "serve_execute": {
        "boundary": "the serving worker's group-execution boundary "
                    "(`serve.engine`) — a coalesced group degrades to "
                    "serialized, a lone request fails TRANSIENT (labels "
                    "`request_id`, `n`)",
        "corruptible": True, "chaos": True, "dynamic": False,
    },
    "replay_submit": {
        "boundary": "the workload-replay submission choke point "
                    "(`serve.workload.replay_submit`, the load harness "
                    "and the chaos replay case both go through it) — a "
                    "fault sheds the replayed submission before it "
                    "reaches the engine (labels `tenant`, "
                    "`request_id`; `docs/loadtest.md`)",
        "corruptible": False, "chaos": True, "dynamic": False,
    },
    "fleet_route": {
        "boundary": "the fleet router's placement/submit boundary "
                    "(`serve.router.FleetRouter` — a fault fails the "
                    "routed attempt, exercising the retry/backoff and "
                    "re-placement paths; labels `tenant`, `worker`, "
                    "`request_id`; `docs/serving.md` § fleet)",
        # multi-process serving topology: driven deterministically by
        # the fleet_storm corpus case and the fleet tests, never by the
        # single-process randomized draw (the multihost_init precedent)
        "corruptible": False, "chaos": False, "dynamic": False,
    },
    "worker_heartbeat": {
        "boundary": "the fleet router's per-worker heartbeat probe "
                    "(`serve.router.FleetRouter.check` — a fault counts "
                    "as a missed beat, driving the UP -> SUSPECT -> "
                    "DOWN suspicion ladder; labels `worker`)",
        "corruptible": False, "chaos": False, "dynamic": False,
    },
    "fleet_handoff": {
        "boundary": "the exactly-once failover boundary "
                    "(`serve.router.FleetRouter.failover` — a fault "
                    "aborts the handoff attempt before any replay "
                    "lands; the journal survives for the retry; labels "
                    "`worker`, `target`)",
        "corruptible": False, "chaos": False, "dynamic": False,
    },
    "tune_trial": {
        "boundary": "the online autotuner's trial boundary "
                    "(`tune.trials`, one per candidate sweep; labels "
                    "`mnk`, `dtype`) — a fault aborts the trial and NO "
                    "promotion may land from it "
                    "(`docs/autotuning.md` § trial runner)",
        # off the hot path by construction: a faulted trial is absorbed
        # by the tuner (counted, never promoted); in the randomized
        # chaos draw the spec simply never fires outside the dedicated
        # tune_storm corpus case, which also drives it deterministically
        "corruptible": False, "chaos": True, "dynamic": False,
    },
}

# driver labels a fault spec's *target* may also match at a site
# (``pallas:nan`` fires on execute_stack launches whose plan driver is
# pallas) — drawn by the chaos suite alongside the sites themselves
DRIVER_TARGETS = ("xla", "xla_group", "host", "pallas")


def chaos_sites() -> tuple:
    """The chaos suite's schedule-draw targets: every ``chaos`` site
    plus the driver labels."""
    return tuple(
        s for s, meta in SITES.items() if meta["chaos"]) + DRIVER_TARGETS


def chaos_corrupt_targets() -> tuple:
    """Targets whose OUTPUT a nan/flip spec can corrupt in the chaos
    suite: corruptible chaos sites plus the driver labels (a driver
    label fires on the execute_stack corrupt hook)."""
    return tuple(
        s for s, meta in SITES.items()
        if meta["chaos"] and meta["corruptible"]) + DRIVER_TARGETS
