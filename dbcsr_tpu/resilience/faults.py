"""Deterministic, seeded fault injection at the engine's trust
boundaries.

Round 5 lost a full capture round because the only way to exercise the
engine's failure handling was a real hardware fault — the tunnel wedged
and nothing in CI had ever walked the recovery paths.  This module
makes every failure kind the TPU path has actually produced injectable
on CPU, deterministically, so `tests/test_resilience.py` and
`tools/chaos_suite.py` can drive the failover/breaker/watchdog
machinery without hardware.

**Sites** (where `maybe_inject`/`corrupt` hooks are registered):

========================  ====================================================
site                      boundary
========================  ====================================================
``execute_stack``         `acc.smm.execute_stack`, per driver launch
                          (labels: ``driver``)
``prepare_stack``         `acc.smm.prepare_stack` (driver selection)
``dense``                 the dense paths in `mm.multiply`
``multihost_init``        `parallel.multihost.init_multihost`
``collective``            `parallel.sparse_dist` mesh dispatch boundary
``mesh_shift``            the double-buffered Cannon tick/shift
                          boundary (`parallel.overlap.run_ticks`, one
                          per ring shift; labels: ``engine``,
                          ``tick``) — a fault here degrades the
                          multiply to the serial fused program
``gather_chunk``          the chunked all-gather pipeline's per-shard
                          ring-step boundary on rectangular grids
                          (same `run_ticks` edge, driver
                          ``gather_pipe``; labels: ``engine``,
                          ``tick``) — degrades to the fused
                          one-collective program
``tas_tick``              the staggered grouped-TAS metronome's
                          tick/shift boundary (same `run_ticks` edge,
                          driver ``cannon_db`` keyed engine="tas") —
                          degrades to the fused lockstep program
``probe``                 `bench._probe_tpu`
``serve_admit``           `serve.queue.AdmissionQueue.admit` — a fault
                          here sheds the submission with a structured
                          rejection (labels: ``tenant``,
                          ``request_id``)
``serve_execute``         the serving worker's group-execution
                          boundary (`serve.engine`) — a fault on a
                          coalesced group degrades it to serialized
                          per-request execution; on a lone request it
                          fails that request TRANSIENT (labels:
                          ``request_id``, ``n``)
========================  ====================================================

A spec's *target* matches either the site name or a label value (the
driver name), so ``pallas:raise`` fires only on pallas launches while
``execute_stack:raise`` fires on every driver.

**Kinds**: ``raise`` (XlaRuntimeError), ``oom`` (RESOURCE_EXHAUSTED —
the transient classification the demotion handlers key on), ``nan``
(corrupt the output blocks with NaN — caught by the post-execution
output check), ``flip`` (perturb one output element by a large but
FINITE seed-deterministic delta — the silent-data-corruption model:
invisible to every finite-output check, detectable only by the ABFT
probe / chain-invariant layer, ``DBCSR_TPU_ABFT``), ``hang`` (sleep
past a deadline, default ``sleep=30``), ``fail`` (generic failure for
boolean sites like the probe — also what ``raise`` means to the
probe).

**DSL** (``DBCSR_TPU_FAULTS``): specs separated by ``;``::

    target:kind[@stack{>=,<=,==,<,>}N][,prob=P][,seed=S][,times=N][,sleep=SEC]

    pallas:raise@stack>=3,prob=0.5,seed=7   # from the 3rd pallas
                                            # launch, coin-flip (seeded)
    dense:nan,times=1                       # corrupt one dense product
    probe:fail,times=35                     # a 35-probe failure streak
    multihost_init:hang,sleep=5             # wedge the world join 5 s

``@stack>=N`` conditions on the per-spec *matching-call counter* (1 on
the first matching call).  ``times=N`` caps how often the spec fires —
a wedge streak that then heals.  ``prob`` draws from a per-spec
`random.Random(seed)`, so schedules replay bit-identically.

Activation: the env var is parsed on first use; tests use
`inject_faults(...)` (a context manager) or `configure`/`clear`.  When
no spec is configured, every hook is one module-attribute truth check
(`active()`), keeping the disabled path inside the existing
≤10 µs/multiply budget.

Stdlib-only at import; jax is reached lazily (error type, NaN
corruption).
"""

from __future__ import annotations

import contextlib
import os
import random
import re
import threading
import time
from typing import List, Optional

_lock = threading.Lock()
_specs: List["FaultSpec"] = []
_env_parsed = False

KINDS = ("raise", "oom", "nan", "hang", "fail", "flip")


class FaultError(RuntimeError):
    """Raised for injected ``fail`` faults (and as the fallback when
    the real XlaRuntimeError type is unavailable)."""


def _xla_error_type():
    """The runtime error type a real failing device launch raises —
    injected faults must walk the exact same except-clauses."""
    try:
        import jax

        return jax.errors.JaxRuntimeError
    except Exception:  # jax absent / too old: a stand-in is fine
        return FaultError


_SPEC_RE = re.compile(
    r"^(?P<target>[A-Za-z0-9_.]+):(?P<kind>[a-z]+)"
    r"(?:@stack(?P<op>>=|<=|==|<|>)(?P<n>\d+))?$"
)


class FaultSpec:
    """One parsed fault rule (see the module docstring for the DSL)."""

    __slots__ = ("target", "kind", "op", "n", "prob", "seed", "times",
                 "sleep", "calls", "fired", "_rng")

    def __init__(self, target: str, kind: str, op: str = ">=", n: int = 0,
                 prob: float = 1.0, seed: int = 0,
                 times: Optional[int] = None, sleep: float = 30.0):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (one of {KINDS})")
        self.target = target
        self.kind = kind
        self.op = op
        self.n = n
        self.prob = prob
        self.seed = seed
        self.times = times
        self.sleep = sleep
        self.calls = 0   # matching calls seen
        self.fired = 0   # faults actually injected
        self._rng = random.Random(seed)

    def _cond_ok(self) -> bool:
        c, n = self.calls, self.n
        return {
            ">=": c >= n, "<=": c <= n, "==": c == n,
            "<": c < n, ">": c > n,
        }[self.op]

    def matches(self, site: str, labels: dict) -> bool:
        return self.target == site or self.target in labels.values()

    def should_fire(self) -> bool:
        """Advance the matching-call counter and decide (deterministic
        given the seed and call sequence)."""
        self.calls += 1
        if not self._cond_ok():
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            return False
        self.fired += 1
        return True

    def __repr__(self):
        cond = f"@stack{self.op}{self.n}" if self.n else ""
        return (f"FaultSpec({self.target}:{self.kind}{cond},"
                f"prob={self.prob},seed={self.seed},times={self.times})")


def parse(spec_string: str) -> List[FaultSpec]:
    """Parse a ``DBCSR_TPU_FAULTS`` value into FaultSpecs."""
    specs = []
    for part in spec_string.split(";"):
        part = part.strip()
        if not part:
            continue
        head, *opts = part.split(",")
        m = _SPEC_RE.match(head.strip())
        if m is None:
            raise ValueError(
                f"bad fault spec {head!r} (want target:kind[@stack>=N])")
        kw = dict(target=m.group("target"), kind=m.group("kind"))
        if m.group("op"):
            kw["op"], kw["n"] = m.group("op"), int(m.group("n"))
        for o in opts:
            k, _, v = o.strip().partition("=")
            if k == "prob":
                kw["prob"] = float(v)
            elif k == "seed":
                kw["seed"] = int(v)
            elif k == "times":
                kw["times"] = int(v)
            elif k == "sleep":
                kw["sleep"] = float(v)
            else:
                raise ValueError(f"unknown fault option {k!r} in {part!r}")
        specs.append(FaultSpec(**kw))
    return specs


def configure(spec_string: Optional[str]) -> List[FaultSpec]:
    """Install a fault schedule (replacing any active one); None/""
    clears it."""
    global _specs, _env_parsed
    with _lock:
        _env_parsed = True  # explicit configuration overrides the env
        _specs = parse(spec_string) if spec_string else []
        return _specs


def clear() -> None:
    configure(None)


def _ensure_env() -> None:
    global _env_parsed
    if _env_parsed:
        return
    with _lock:
        if _env_parsed:
            return
        env = os.environ.get("DBCSR_TPU_FAULTS")
        if env:
            _specs.extend(parse(env))
        _env_parsed = True


def active() -> bool:
    """True when any fault spec is installed.  THE hot-path gate: call
    sites guard every other function in this module behind it."""
    if not _env_parsed:
        _ensure_env()
    return bool(_specs)


def specs() -> List[FaultSpec]:
    _ensure_env()
    return list(_specs)


def _note(site: str, spec: FaultSpec, labels: dict) -> None:
    """Every injected fault is observable: trace instant + counter +
    flight-recorder event."""
    import sys

    if "dbcsr_tpu.obs.metrics" not in sys.modules:
        # standalone use (bench probe loads this module by file path):
        # never be the cause of the first obs import — an env-activated
        # trace session must only open in engine processes
        return
    try:
        from dbcsr_tpu.obs import events as _events
        from dbcsr_tpu.obs import metrics as _metrics

        _metrics.counter(
            "dbcsr_tpu_faults_injected_total",
            "faults injected by dbcsr_tpu.resilience.faults per site/kind",
        ).inc(site=site, kind=spec.kind)
        # one publish = bus record (product-correlated) + trace instant
        # + flight event, replacing the three hand-rolled emissions
        _events.publish(
            "fault_injected",
            {"site": site, "kind": spec.kind, "target": spec.target,
             "fired": spec.fired,
             **{k: str(v) for k, v in labels.items()}},
            flight=("fault_injected", {"site": site, "kind": spec.kind,
                                       "target": spec.target}),
        )
    except Exception:
        pass  # observability must never turn an injected fault into a real one


def _firing_spec(site: str, kinds, labels: dict) -> Optional[FaultSpec]:
    for spec in _specs:
        if spec.kind in kinds and spec.matches(site, labels):
            if spec.should_fire():
                return spec
    return None


def maybe_inject(site: str, **labels) -> None:
    """Raise/sleep if a configured ``raise``/``oom``/``fail``/``hang``
    fault fires at this site.  No-op (after the `active()` gate the
    call sites apply) when nothing matches."""
    if not _specs:
        return
    spec = _firing_spec(site, ("raise", "oom", "fail", "hang"), labels)
    if spec is None:
        return
    _note(site, spec, labels)
    if spec.kind == "hang":
        time.sleep(spec.sleep)
        return
    if spec.kind == "fail":
        raise FaultError(f"injected fault at {site} ({spec!r})")
    err = _xla_error_type()
    if spec.kind == "oom":
        raise err(
            f"RESOURCE_EXHAUSTED: injected device OOM at {site} "
            f"(fault injection, {spec.target})")
    raise err(
        f"INTERNAL: injected XlaRuntimeError at {site} "
        f"(fault injection, {spec.target})")


def corrupt(site: str, value, **labels):
    """Apply a configured ``nan``/``flip`` corruption to a device array
    (the simulated bad-kernel output).  Returns ``value`` unchanged
    when no spec fires.

    ``nan`` poisons one element with NaN (caught by the finite-output
    check); ``flip`` adds a large FINITE seed-deterministic delta to
    one element — the silent-data-corruption model that only the ABFT
    probe / chain-invariant layer can see."""
    if not _specs:
        return value
    spec = _firing_spec(site, ("nan", "flip"), labels)
    if spec is None:
        return value
    _note(site, spec, labels)
    import jax.numpy as jnp

    flat = jnp.ravel(value)
    if flat.size == 0 or not jnp.issubdtype(value.dtype, jnp.inexact):
        return value
    # poison a deterministic element so the corruption is reproducible
    idx = spec.seed % int(flat.size)
    if spec.kind == "flip":
        # large-but-finite, exactly representable in every engine dtype
        # (bf16 included), deterministic per (seed): a bit-flip-scale
        # perturbation far above any ABFT tolerance floor
        delta = float(1 << 10) + float(spec.seed % 997)
        return jnp.reshape(flat.at[idx].add(
            jnp.asarray(delta, dtype=flat.dtype)), value.shape)
    return jnp.reshape(flat.at[idx].set(jnp.nan), value.shape)


def fail_probe(site: str = "probe", **labels) -> bool:
    """Boolean form for probe-style sites: True when a failure streak
    fault fires (``fail``/``raise`` kinds; ``hang`` sleeps, then
    fails)."""
    if not _specs:
        return False
    spec = _firing_spec(site, ("raise", "fail", "hang"), labels)
    if spec is None:
        return False
    _note(site, spec, labels)
    if spec.kind == "hang":
        time.sleep(spec.sleep)
    return True


@contextlib.contextmanager
def inject_faults(spec_string: str):
    """Context-manager API for tests: install a schedule, restore the
    previous one on exit.

        with inject_faults("pallas:raise,times=1"):
            multiply(...)  # first pallas launch raises, failover runs
    """
    global _specs
    _ensure_env()
    with _lock:
        prev = list(_specs)
    installed = configure(spec_string)
    try:
        yield installed
    finally:
        with _lock:
            _specs = prev
