"""Hardware watchdog: ONE deadline-guarded executor for every place
the engine talks to hardware that can wedge.

Before this module, three call sites hand-rolled the same logic with
different bugs: `bench._probe_tpu` (subprocess + timeout, no retry
memory), `tools/capture_tiered.py --loop` (fixed 20-minute cadence —
35 consecutive failed probes in round 5 hammered a dead tunnel all
night), and `perf.driver.run_perf_multiproc` (communicate(timeout) +
one blind retry).  All three now share this executor.

**Outcome taxonomy** — every guarded call classifies into exactly one:

* ``OK`` — returned within the deadline, faster than
  ``slow_fraction * deadline``.
* ``SLOW`` — returned a usable result, but late enough
  (> ``slow_fraction * deadline``) that the caller should treat the
  device as degraded (shorter legs, no new heavy work).
* ``TRANSIENT`` — raised an ordinary exception: the attempt failed but
  the channel answered, so a backoff retry is worthwhile.
* ``WEDGED`` — hit the hard deadline (`DeadlineExceeded` /
  `subprocess.TimeoutExpired`): the channel is not answering; retries
  must back off exponentially, and queued work must stop.

**Backoff**: ``delay(streak) = min(base * 2^streak, max) * (1 ± jitter)``
with a deterministic per-instance RNG.  The *streak* counts consecutive
non-OK outcomes (WEDGED counts double-weight via ``wedge_streak``).

**Persistence**: with ``state_path``, every outcome appends one JSONL
record ``{"ts", "name", "outcome", "streak", "wedge_streak",
"elapsed_s", "error"}``; on construction the last record for ``name``
is reloaded, so a restarted capture loop resumes its backoff position
instead of re-probing a dead tunnel on the base cadence.  The same
file doubles as the structured probe-outcome log the loop commits next
to ``capture_loop.log``.  The file is size-capped: past
``DBCSR_TPU_WATCHDOG_LOG_MAX_BYTES`` (1 MiB) every persist rotates it
down to the last record per channel name (the resume state) plus the
newest half-cap of history (`rotate_jsonl`).

Stdlib-only (bench.py imports this before a JAX backend exists); the
obs trace/metric emission is lazy and best-effort.  Clock, sleep and
RNG are injectable for deterministic tests.
"""

from __future__ import annotations

import json
import os
import random
import time
import zlib
from typing import Any, Callable, Optional

OK = "OK"
SLOW = "SLOW"
TRANSIENT = "TRANSIENT"
WEDGED = "WEDGED"

OUTCOMES = (OK, SLOW, TRANSIENT, WEDGED)


class DeadlineExceeded(TimeoutError):
    """A guarded callable overran its hard deadline."""


def rotate_jsonl(path: str, max_bytes: Optional[int] = None) -> bool:
    """Size-capped rotation of an append-only outcome JSONL (the
    capture loop's ``capture_probe.jsonl`` grows one row per guarded
    attempt, without bound under ``--loop``).  When ``path`` exceeds
    ``max_bytes`` (``DBCSR_TPU_WATCHDOG_LOG_MAX_BYTES``, default
    1 MiB), rewrite it keeping

    * the LAST record of every ``name`` — `_resume` scans for exactly
      these, so every channel's live streak/backoff state survives the
      rotation — plus
    * the newest tail of rows up to half the cap (recent history for
      `tools/doctor.py` and humans).

    Atomic (write-temp + rename), torn tail lines tolerated, and never
    raises: rotation is bookkeeping, not an outcome."""
    if max_bytes is None:
        try:
            max_bytes = int(os.environ.get(
                "DBCSR_TPU_WATCHDOG_LOG_MAX_BYTES", 1 << 20))
        except ValueError:
            max_bytes = 1 << 20
    try:
        if max_bytes <= 0 or os.path.getsize(path) <= max_bytes:
            return False
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return False
    last_by_name: dict = {}
    for i, line in enumerate(lines):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        name = rec.get("name")
        if name:
            last_by_name[name] = i
    keep = set(last_by_name.values())
    budget = max_bytes // 2
    size = 0
    for i in range(len(lines) - 1, -1, -1):
        size += len(lines[i])
        if size > budget:
            break
        keep.add(i)
    tmp = path + ".rot"
    try:
        with open(tmp, "w") as fh:
            fh.writelines(lines[i] for i in sorted(keep))
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    return True


class WatchdogResult:
    """Outcome of one guarded call (or one retry loop)."""

    __slots__ = ("outcome", "value", "elapsed_s", "attempts", "error")

    def __init__(self, outcome: str, value: Any = None,
                 elapsed_s: float = 0.0, attempts: int = 1,
                 error: Optional[str] = None):
        self.outcome = outcome
        self.value = value
        self.elapsed_s = elapsed_s
        self.attempts = attempts
        self.error = error

    @property
    def ok(self) -> bool:
        return self.outcome in (OK, SLOW)

    def __repr__(self):
        return (f"WatchdogResult({self.outcome}, attempts={self.attempts}, "
                f"elapsed={self.elapsed_s:.3f}s, error={self.error!r})")


def _timeout_types() -> tuple:
    import subprocess

    return (DeadlineExceeded, subprocess.TimeoutExpired, TimeoutError)


class Watchdog:
    """Deadline-guarded executor with backoff memory for one named
    hardware channel (e.g. ``tpu_probe``, ``mp_world_join``)."""

    def __init__(self, name: str, deadline_s: float,
                 slow_fraction: float = 0.5,
                 backoff_base_s: float = 60.0,
                 backoff_max_s: float = 3600.0,
                 jitter: float = 0.1,
                 state_path: Optional[str] = None,
                 clock=time.monotonic, sleep=time.sleep,
                 rng: Optional[random.Random] = None,
                 resume: bool = True):
        self.name = name
        self.deadline_s = float(deadline_s)
        self.slow_fraction = slow_fraction
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self.state_path = state_path
        self.clock = clock
        self.sleep = sleep
        # crc32, not hash(): str hashing is salted per process, and the
        # jitter sequence must replay across runs (the same determinism
        # contract as the faults layer)
        self.rng = rng if rng is not None else random.Random(
            zlib.crc32(name.encode()))
        self.streak = 0        # consecutive non-OK outcomes
        self.wedge_streak = 0  # consecutive WEDGED outcomes
        self.last_outcome: Optional[str] = None
        # resume=False: persist outcomes but skip the state-file scan —
        # for one-shot guards that never consult next_delay()
        if state_path and resume:
            self._resume()

    # -- persistence -----------------------------------------------------

    def _resume(self) -> None:
        """Reload the last persisted outcome for this name (torn tail
        lines tolerated, same policy as the capture evidence pickers)."""
        try:
            with open(self.state_path) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("name") == self.name:
                        self.streak = int(rec.get("streak", 0))
                        self.wedge_streak = int(rec.get("wedge_streak", 0))
                        self.last_outcome = rec.get("outcome")
        except OSError:
            pass

    def _persist(self, result: WatchdogResult) -> None:
        if not self.state_path:
            return
        rec = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "name": self.name,
            "outcome": result.outcome,
            "streak": self.streak,
            "wedge_streak": self.wedge_streak,
            "elapsed_s": round(result.elapsed_s, 3),
            "error": result.error,
        }
        try:
            with open(self.state_path, "a") as fh:
                fh.write(json.dumps(rec) + "\n")
        except OSError:
            return
        # bound the append-only log; the just-written record is by
        # definition the newest, so the streak state always survives
        rotate_jsonl(self.state_path)

    # -- observability ---------------------------------------------------

    def _emit(self, result: WatchdogResult) -> None:
        import sys

        if "dbcsr_tpu.obs.metrics" not in sys.modules:
            # never the cause of the first `dbcsr_tpu.obs` import: the
            # capture-loop driver loads this module standalone (by file
            # path) precisely so an env-activated trace session cannot
            # open shards meant for its bench subprocesses
            return
        try:
            from dbcsr_tpu.obs import events as _events
            from dbcsr_tpu.obs import metrics as _metrics

            _metrics.counter(
                "dbcsr_tpu_watchdog_outcomes_total",
                "guarded hardware-call outcomes per watchdog channel",
            ).inc(name=self.name, outcome=result.outcome)
            _metrics.gauge(
                "dbcsr_tpu_watchdog_wedge_streak",
                "consecutive WEDGED outcomes per watchdog channel",
            ).set(self.wedge_streak, name=self.name)
            _events.publish("watchdog_outcome", {
                "name": self.name, "outcome": result.outcome,
                "elapsed_s": round(result.elapsed_s, 3),
                "streak": self.streak,
                "wedge_streak": self.wedge_streak,
                "error": result.error,
            })
        except Exception:
            pass

    # -- core ------------------------------------------------------------

    def classify(self, elapsed_s: float, error: Optional[BaseException]) -> str:
        """The outcome taxonomy (module docstring), as a pure function
        so tests can pin it."""
        if error is not None:
            if isinstance(error, _timeout_types()):
                return WEDGED
            return TRANSIENT
        if elapsed_s > self.slow_fraction * self.deadline_s:
            return SLOW
        return OK

    def guard(self, fn: Callable[[float], Any]) -> WatchdogResult:
        """One guarded attempt.  ``fn`` receives the deadline (seconds)
        and must enforce it itself (subprocess timeout, socket timeout,
        …), raising `DeadlineExceeded` / `subprocess.TimeoutExpired` on
        overrun — the watchdog cannot preempt arbitrary in-process code,
        it classifies and keeps the streak book."""
        t0 = self.clock()
        error: Optional[BaseException] = None
        value = None
        try:
            value = fn(self.deadline_s)
        except BaseException as exc:  # noqa: BLE001 — classified below
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                raise
            error = exc
        elapsed = self.clock() - t0
        outcome = self.classify(elapsed, error)
        if outcome == OK:
            self.streak = 0
            self.wedge_streak = 0
        else:
            self.streak += 1
            if outcome == WEDGED:
                self.wedge_streak += 1
            else:
                self.wedge_streak = 0
        self.last_outcome = outcome
        result = WatchdogResult(
            outcome, value=value, elapsed_s=elapsed,
            error=None if error is None else
            f"{type(error).__name__}: {error}",
        )
        self._emit(result)
        self._persist(result)
        return result

    def next_delay(self) -> float:
        """Backoff delay before the next attempt, from the current
        streak (0 → base cadence; wedges escalate exponentially)."""
        streak = max(self.streak, self.wedge_streak * 2)
        delay = min(self.backoff_base_s * (2 ** max(streak - 1, 0)),
                    self.backoff_max_s) if streak else self.backoff_base_s
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * self.rng.random() - 1.0)
        return delay

    def run(self, fn: Callable[[float], Any], retries: int = 0,
            retry_on=(TRANSIENT, WEDGED)) -> WatchdogResult:
        """Guarded call with up to ``retries`` backoff retries on the
        given outcome classes.  Returns the LAST attempt's result with
        ``attempts`` stamped."""
        attempts = 0
        while True:
            attempts += 1
            result = self.guard(fn)
            result.attempts = attempts
            if result.outcome not in retry_on or attempts > retries:
                return result
            self.sleep(self.next_delay())


def run_guarded(name: str, fn: Callable[[float], Any], deadline_s: float,
                **kwargs) -> WatchdogResult:
    """One-shot convenience: build a Watchdog, guard one call."""
    return Watchdog(name, deadline_s, **kwargs).guard(fn)
