"""Per-(driver, shape-key) circuit breakers for the stack-driver chain.

The reference's answer to a broken kernel is static: if no JIT kernel
exists for an (m, n, k), dispatch takes the CPU path forever
(`libsmm_acc.cpp:227-249`).  Here a driver can fail *dynamically* — a
Mosaic lowering gap on one backend, an emulated-dtype NaN, transient
device OOM — so quarantine must be dynamic too: a standard
closed → open → half-open breaker per (driver, shape-key).

* **closed** — healthy; launches flow.  ``fail_threshold`` consecutive
  failures (default 3, ``DBCSR_TPU_BREAKER_THRESHOLD``) trip it open.
  A hard failure kind (``validation`` — numeric corruption proven
  against the host oracle) trips it open immediately.
* **open** — quarantined; `allow()` is False until ``cooldown_s``
  (default 30, ``DBCSR_TPU_BREAKER_COOLDOWN_S``) elapses, so dispatch
  routes the shape down the failover chain without re-paying the
  failure.
* **half-open** — after the cooldown, exactly one trial launch is let
  through; success closes the breaker, failure re-opens it (cooldown
  doubles, capped at 16x, so a deterministically broken kernel decays
  to a rare background probe instead of a fixed-cadence retry storm).

Every transition emits a trace instant, a flight-recorder event, and
refreshes the ``dbcsr_tpu_breaker_state{driver,shape}`` gauge
(0=closed, 1=half_open, 2=open).  `acc.smm.execute_stack` owns the
wiring: record_failure/record_success around each launch, allow() as
the pre-launch gate.

Fused superstack launches (`acc.smm.execute_superstack`) register
under the pseudo-driver ``"fused"`` keyed by the C bin's (m, n,
span-count, dtype): a failing fused launch can't name the guilty span
from outside its program, so instead of condemning a real driver it
trips the bin's fused breaker and DECOMPOSES to per-span execution —
where these per-(driver, shape) breakers and the failover chain apply
as usual.  An open fused breaker routes the bin per-span up front.

Stdlib-only; the clock is injectable for deterministic tests.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

# failure kinds whose first occurrence trips the breaker straight open:
# a validation failure is proven numeric corruption (the host-oracle
# gate), never worth two more tries on live data
_HARD_KINDS = ("validation",)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class Breaker:
    """One (driver, shape-key) breaker.  Not thread-safe on its own —
    the board serializes access."""

    __slots__ = ("state", "failures", "successes", "opened_at",
                 "cooldown_s", "base_cooldown_s", "last_kind", "trips")

    def __init__(self, cooldown_s: float):
        self.state = CLOSED
        self.failures = 0       # consecutive, since last success
        self.successes = 0
        self.opened_at = 0.0
        self.base_cooldown_s = cooldown_s
        self.cooldown_s = cooldown_s
        self.last_kind: Optional[str] = None
        self.trips = 0


class BreakerBoard:
    """Registry of breakers keyed by (driver, shape_key)."""

    def __init__(self, fail_threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None, clock=time.monotonic):
        self.fail_threshold = (
            fail_threshold if fail_threshold is not None
            else _env_int("DBCSR_TPU_BREAKER_THRESHOLD", 3))
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else _env_float("DBCSR_TPU_BREAKER_COOLDOWN_S", 30.0))
        self.clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[Tuple[str, tuple], Breaker] = {}

    # -- observability ---------------------------------------------------

    def _emit(self, driver: str, key, br: Breaker, transition: str) -> None:
        try:
            from dbcsr_tpu.obs import events as _events
            from dbcsr_tpu.obs import metrics as _metrics

            shape = "x".join(str(x) for x in key) if key else "-"
            _metrics.gauge(
                "dbcsr_tpu_breaker_state",
                "circuit-breaker state per (driver, shape): 0=closed, "
                "1=half_open, 2=open",
            ).set(_STATE_CODE[br.state], driver=driver, shape=shape)
            # single choke point: the bus record, the trace instant and
            # the flight event all come from one publish (correlated to
            # the open multiply's product_id when there is one)
            _events.publish(
                "breaker_transition",
                {"driver": driver, "shape": shape, "to": br.state,
                 "transition": transition, "failures": br.failures,
                 "kind": br.last_kind},
                flight=("breaker", {"driver": driver, "shape": shape,
                                    "to": br.state, "why": transition}),
            )
        except Exception:
            pass

    # -- core protocol ---------------------------------------------------

    def _get(self, driver: str, key) -> Breaker:
        k = (driver, tuple(key) if key is not None else ())
        br = self._breakers.get(k)
        if br is None:
            br = self._breakers[k] = Breaker(self.cooldown_s)
        return br

    def allow(self, driver: str, key) -> bool:
        """May this driver launch this shape now?  Open breakers whose
        cooldown elapsed move to half-open and admit ONE trial."""
        if not self._breakers:  # fast path: nothing ever failed
            return True
        with self._lock:
            k = (driver, tuple(key) if key is not None else ())
            br = self._breakers.get(k)
            if br is None or br.state == CLOSED:
                return True
            if br.state == HALF_OPEN:
                # one trial is already in flight this period; further
                # launches keep falling down the chain
                return False
            if self.clock() - br.opened_at >= br.cooldown_s:
                br.state = HALF_OPEN
                self._emit(driver, k[1], br, "cooldown-elapsed")
                return True
            return False

    def record_success(self, driver: str, key) -> None:
        if not self._breakers:
            return
        with self._lock:
            k = (driver, tuple(key) if key is not None else ())
            br = self._breakers.get(k)
            if br is None:
                return
            br.successes += 1
            br.failures = 0
            if br.state != CLOSED:
                br.state = CLOSED
                br.cooldown_s = br.base_cooldown_s
                self._emit(driver, k[1], br, "trial-succeeded")

    def record_failure(self, driver: str, key, kind: str = "runtime") -> None:
        with self._lock:
            br = self._get(driver, key)
            br.failures += 1
            br.last_kind = kind
            if br.state == HALF_OPEN:
                # the trial failed: re-open, back off harder
                br.state = OPEN
                br.opened_at = self.clock()
                br.cooldown_s = min(br.cooldown_s * 2,
                                    br.base_cooldown_s * 16)
                br.trips += 1
                self._emit(driver, key, br, "trial-failed")
            elif br.state == CLOSED and (
                    kind in _HARD_KINDS
                    or br.failures >= self.fail_threshold):
                br.state = OPEN
                br.opened_at = self.clock()
                br.trips += 1
                self._emit(driver, key, br,
                           "hard-failure" if kind in _HARD_KINDS
                           else "threshold")
            else:
                self._emit(driver, key, br, "failure-recorded")

    def state(self, driver: str, key) -> str:
        with self._lock:
            br = self._breakers.get(
                (driver, tuple(key) if key is not None else ()))
            return br.state if br is not None else CLOSED

    def snapshot(self) -> dict:
        """{driver|shape: {state, failures, trips, cooldown_s}} for
        dumps and tests."""
        with self._lock:
            return {
                f"{drv}|{'x'.join(str(x) for x in key) or '-'}": {
                    "state": br.state, "failures": br.failures,
                    "successes": br.successes, "trips": br.trips,
                    "cooldown_s": br.cooldown_s, "last_kind": br.last_kind,
                }
                for (drv, key), br in self._breakers.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()


_board: Optional[BreakerBoard] = None
_board_lock = threading.Lock()


def get_board() -> BreakerBoard:
    """The process-wide board `acc.smm` wires through (tests build
    their own with a fake clock)."""
    global _board
    if _board is None:
        with _board_lock:
            if _board is None:
                _board = BreakerBoard()
    return _board


def reset_board() -> None:
    """Drop all breaker state (tests; paired with metrics.reset)."""
    global _board
    with _board_lock:
        _board = None
