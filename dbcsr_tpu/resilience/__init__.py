"""dbcsr_tpu.resilience — fault injection, driver failover, watchdog.

The robustness subsystem: DBCSR's contract is that the multiply engine
keeps producing correct results regardless of which backend executes
the small-GEMM stacks (the reference falls back from a missing JIT
kernel to the CPU path, `libsmm_acc.cpp:227-249`); on the TPU
reproduction the accelerator path additionally fails in ways the
reference never sees — a wedged axon tunnel, Mosaic lowering fatals,
emulated-dtype NaNs, device OOM.  Three parts:

* `faults` — deterministic, seeded fault injection at the driver /
  collective / probe boundaries, configured by ``DBCSR_TPU_FAULTS``
  (e.g. ``pallas:raise@stack>=3,prob=0.5,seed=7``) or the
  `inject_faults` context manager.  Lets CI exercise every failure
  path on CPU, with no real hardware faults.
* `breaker` — per-(driver, shape-key) circuit breakers
  (closed → open → half-open with cooldown) backing the stack-driver
  failover chain wired through `acc.smm.execute_stack`: a failing
  driver is quarantined and the stack re-executes down
  pallas → xla_group → xla_flat → xla → host, so one bad kernel never
  poisons a multiply.
* `watchdog` — a single deadline-guarded executor with exponential
  backoff + jitter and structured outcome classification
  (OK / SLOW / TRANSIENT / WEDGED), adopted by `bench._probe_tpu`,
  `tools/capture_tiered.py --loop` and the multi-process perf driver
  join in place of their hand-rolled timeout code.  Wedge streaks
  persist as JSONL so a restarted loop resumes its backoff state.

Every module here is stdlib-only at import time (`bench.py` must be
able to import the watchdog before a JAX backend is chosen); jax/numpy
are reached lazily inside the few functions that need them.  With no
faults configured and no failures recorded, every hook is a single
attribute check — the same no-op contract as `obs`.
"""

from dbcsr_tpu.resilience import breaker
from dbcsr_tpu.resilience import faults
from dbcsr_tpu.resilience import watchdog

from dbcsr_tpu.resilience.breaker import (  # noqa: F401
    BreakerBoard,
    get_board,
)
from dbcsr_tpu.resilience.faults import (  # noqa: F401
    FaultError,
    FaultSpec,
    inject_faults,
)
from dbcsr_tpu.resilience.watchdog import (  # noqa: F401
    OK,
    SLOW,
    TRANSIENT,
    WEDGED,
    DeadlineExceeded,
    Watchdog,
    WatchdogResult,
)

__all__ = [
    "faults", "breaker", "watchdog",
    "FaultSpec", "FaultError", "inject_faults",
    "BreakerBoard", "get_board",
    "Watchdog", "WatchdogResult", "DeadlineExceeded",
    "OK", "SLOW", "TRANSIENT", "WEDGED",
]
