"""dbcsr_tpu — a TPU-native distributed block-sparse matrix framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of DBCSR
(CP2K's Distributed Block Compressed Sparse Row library; reference
`README.md:13-15`): distributed block-sparse matrix-matrix multiplication
and supporting operations, a tall-and-skinny (TAS) layer, and an n-rank
block-sparse tensor-contraction layer.

This is NOT a port.  Design mapping (reference concept -> here):

* Fortran BCSR index + typed data areas  ->  host NumPy block index +
  per-block-shape device arrays in HBM (`dbcsr_tpu.core.matrix`).
* libsmm_acc JIT'd CUDA batched small-GEMM kernels
  (`src/acc/libsmm_acc/libsmm_acc.cpp`)  ->  XLA/Pallas batched SMM over
  integer parameter stacks (`dbcsr_tpu.acc`).
* MPI Cannon metronome loop (`src/mm/dbcsr_mm_cannon.F:1345`)  ->
  `shard_map` over a 2D `jax.sharding.Mesh` with `lax.ppermute` ring
  shifts (`dbcsr_tpu.parallel`).
* OpenMP threads / per-thread work matrices  ->  vectorized device work;
  no host threading needed.
"""

from dbcsr_tpu.core.kinds import (
    dbcsr_type_real_4,
    dbcsr_type_real_8,
    dbcsr_type_complex_4,
    dbcsr_type_complex_8,
    dtype_of,
)
from dbcsr_tpu.core.config import (
    get_config,
    get_default_config,
    print_config,
    set_config,
)
from dbcsr_tpu.core.lib import init_lib, finalize_lib, print_statistics
from dbcsr_tpu.core.dist import (
    ProcessGrid,
    Distribution,
    convert_offsets_to_sizes,
    convert_sizes_to_offsets,
    dist_bin,
)
from dbcsr_tpu.core.matrix import BlockIterator, BlockSparseMatrix, create
from dbcsr_tpu.core import mempool
from dbcsr_tpu.core.mempool import chain
from dbcsr_tpu.mm.multiply import multiply
from dbcsr_tpu import obs
from dbcsr_tpu import resilience
from dbcsr_tpu.ops.operations import (
    FUNC_ARTANH,
    FUNC_ASIN,
    FUNC_COS,
    FUNC_DDSIN,
    FUNC_DDTANH,
    FUNC_DSIN,
    FUNC_DTANH,
    FUNC_INVERSE,
    FUNC_INVERSE_SPECIAL,
    FUNC_SIN,
    FUNC_SPREAD_FROM_ZERO,
    FUNC_TANH,
    FUNC_TRUNCATE,
    add,
    add_on_diag,
    clear,
    column_norms,
    copy,
    copy_into_existing,
    crop_matrix,
    dot,
    filter_matrix,
    frobenius_norm,
    function_of_elements,
    gershgorin_norm,
    get_block_diag,
    hadamard_product,
    maxabs_norm,
    reserve_all_blocks,
    reserve_blocks,
    reserve_diag_blocks,
    scale,
    scale_by_vector,
    set_diag,
    set_value,
    get_diag,
    trace,
    triu,
    verify_matrix,
)
from dbcsr_tpu.ops.transformations import (
    desymmetrize,
    new_transposed,
    redistribute,
    submatrix,
)
from dbcsr_tpu.ops.csr import (
    CSR_DBCSR_BLKROW_DIST,
    CSR_EQROW_CEIL_DIST,
    CSR_EQROW_FLOOR_DIST,
    CsrMatrix,
    complete_redistribute,
    csr_create_from_matrix,
    csr_from_matrix,
    csr_print_sparsity,
    csr_write,
    matrix_from_csr,
    to_csr_filter,
)
from dbcsr_tpu.ops.io import binary_read, binary_write, print_block_sum, print_matrix
from dbcsr_tpu.ops.test_methods import (
    checksum,
    from_dense,
    make_random_matrix,
    reset_randmat_seed,
    to_dense,
)
from dbcsr_tpu.ops.tests import TEST_BINARY_IO, TEST_MM, run_tests
# ref dbcsr_replicate_all (`dbcsr_transformations.F:108`); the paired
# dbcsr_sum_replicated merge is a lax.psum inside shard_map here (see
# parallel/dist_matrix.py:replicate docstring)
from dbcsr_tpu.parallel.dist_matrix import replicate as replicate_all

__version__ = "0.1.0"

# the public surface (~88 symbols; the dbcsr_api.F analog list,
# see PARITY.md for the name-by-name mapping)
__all__ = [
    "BlockIterator",
    "BlockSparseMatrix",
    "CSR_DBCSR_BLKROW_DIST",
    "CSR_EQROW_CEIL_DIST",
    "CSR_EQROW_FLOOR_DIST",
    "CsrMatrix",
    "Distribution",
    "FUNC_ARTANH",
    "FUNC_ASIN",
    "FUNC_COS",
    "FUNC_DDSIN",
    "FUNC_DDTANH",
    "FUNC_DSIN",
    "FUNC_DTANH",
    "FUNC_INVERSE",
    "FUNC_INVERSE_SPECIAL",
    "FUNC_SIN",
    "FUNC_SPREAD_FROM_ZERO",
    "FUNC_TANH",
    "FUNC_TRUNCATE",
    "ProcessGrid",
    "TEST_BINARY_IO",
    "TEST_MM",
    "add",
    "add_on_diag",
    "binary_read",
    "binary_write",
    "checksum",
    "clear",
    "column_norms",
    "complete_redistribute",
    "convert_offsets_to_sizes",
    "convert_sizes_to_offsets",
    "copy",
    "copy_into_existing",
    "chain",
    "create",
    "mempool",
    "crop_matrix",
    "csr_create_from_matrix",
    "csr_from_matrix",
    "csr_print_sparsity",
    "csr_write",
    "dbcsr_type_complex_4",
    "dbcsr_type_complex_8",
    "dbcsr_type_real_4",
    "dbcsr_type_real_8",
    "desymmetrize",
    "dist_bin",
    "dot",
    "dtype_of",
    "filter_matrix",
    "finalize_lib",
    "frobenius_norm",
    "from_dense",
    "function_of_elements",
    "gershgorin_norm",
    "get_block_diag",
    "get_config",
    "get_default_config",
    "get_diag",
    "hadamard_product",
    "init_lib",
    "make_random_matrix",
    "matrix_from_csr",
    "maxabs_norm",
    "multiply",
    "new_transposed",
    "obs",
    "resilience",
    "print_block_sum",
    "print_config",
    "print_matrix",
    "print_statistics",
    "redistribute",
    "replicate_all",
    "reserve_all_blocks",
    "reserve_blocks",
    "reserve_diag_blocks",
    "reset_randmat_seed",
    "run_tests",
    "scale",
    "scale_by_vector",
    "set_config",
    "set_diag",
    "set_value",
    "submatrix",
    "to_csr_filter",
    "to_dense",
    "trace",
    "triu",
    "verify_matrix",
]

