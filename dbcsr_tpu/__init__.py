"""dbcsr_tpu — a TPU-native distributed block-sparse matrix framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of DBCSR
(CP2K's Distributed Block Compressed Sparse Row library; reference
`README.md:13-15`): distributed block-sparse matrix-matrix multiplication
and supporting operations, a tall-and-skinny (TAS) layer, and an n-rank
block-sparse tensor-contraction layer.

This is NOT a port.  Design mapping (reference concept -> here):

* Fortran BCSR index + typed data areas  ->  host NumPy block index +
  per-block-shape device arrays in HBM (`dbcsr_tpu.core.matrix`).
* libsmm_acc JIT'd CUDA batched small-GEMM kernels
  (`src/acc/libsmm_acc/libsmm_acc.cpp`)  ->  XLA/Pallas batched SMM over
  integer parameter stacks (`dbcsr_tpu.acc`).
* MPI Cannon metronome loop (`src/mm/dbcsr_mm_cannon.F:1345`)  ->
  `shard_map` over a 2D `jax.sharding.Mesh` with `lax.ppermute` ring
  shifts (`dbcsr_tpu.parallel`).
* OpenMP threads / per-thread work matrices  ->  vectorized device work;
  no host threading needed.
"""

from dbcsr_tpu.core.kinds import (
    dbcsr_type_real_4,
    dbcsr_type_real_8,
    dbcsr_type_complex_4,
    dbcsr_type_complex_8,
    dtype_of,
)
from dbcsr_tpu.core.config import get_config, set_config, print_config
from dbcsr_tpu.core.lib import init_lib, finalize_lib, print_statistics
from dbcsr_tpu.core.dist import ProcessGrid, Distribution, dist_bin
from dbcsr_tpu.core.matrix import BlockSparseMatrix, create
from dbcsr_tpu.mm.multiply import multiply
from dbcsr_tpu.ops.operations import (
    add,
    add_on_diag,
    copy,
    crop_matrix,
    dot,
    filter_matrix,
    frobenius_norm,
    function_of_elements,
    gershgorin_norm,
    hadamard_product,
    maxabs_norm,
    scale,
    scale_by_vector,
    set_diag,
    get_diag,
    trace,
    triu,
    verify_matrix,
)
from dbcsr_tpu.ops.transformations import (
    desymmetrize,
    new_transposed,
    redistribute,
    submatrix,
)
from dbcsr_tpu.ops.csr import complete_redistribute, csr_from_matrix, matrix_from_csr
from dbcsr_tpu.ops.io import binary_read, binary_write
from dbcsr_tpu.ops.test_methods import (
    checksum,
    from_dense,
    make_random_matrix,
    to_dense,
)

__version__ = "0.1.0"
