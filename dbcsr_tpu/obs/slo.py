"""Declarative service-level objectives evaluated as multi-window burn
rates over the telemetry history store (`obs.timeseries`).

An instantaneous health verdict answers "is this process sick NOW";
the SLO plane answers "is it *spending its error budget* faster than
it can afford" — the signal an operator pages on.  Four built-in
objectives (each env-tunable, all evaluated per sample):

=====================  ==============================================
objective              bad when / budget
=====================  ==============================================
``serve_p95_latency``  a tenant's rolling p95 latency sample exceeds
                       ``DBCSR_TPU_SLO_SERVE_P95_MS`` (500 ms);
                       budget = fraction of samples allowed over
                       (``…_P95_BUDGET``, 0.10)
``serve_errors``       shed + deadline-missed requests (counter
                       deltas over the window) vs total requests;
                       budget ``DBCSR_TPU_SLO_SERVE_ERR_BUDGET``
                       (0.05)
``roofline_floor``     a driver's roofline-fraction sample drops
                       below ``DBCSR_TPU_SLO_ROOFLINE_FLOOR``
                       (0.002); budget ``…_ROOFLINE_BUDGET`` (0.25)
``abft_unrecovered``   ABFT mismatches NOT matched by recoveries
                       (counter deltas) vs probe checks; budget
                       ``DBCSR_TPU_SLO_SDC_BUDGET`` (1e-6 — any
                       escaped SDC burns hard)
=====================  ==============================================

**Multi-window burn rate** (the SRE convention): each objective's bad
fraction is computed over a SHORT window (``DBCSR_TPU_SLO_SHORT_S``,
60 s) and a LONG window (``DBCSR_TPU_SLO_LONG_S``, 600 s);
``burn = bad_fraction / budget`` per window, and the objective is
BURNING only when BOTH windows exceed 1.0 (``burn`` reported =
``min(burn_short, burn_long)``) — a transient spike alone never pages,
a sustained burn always does.  Burning at
``DBCSR_TPU_SLO_CRITICAL_BURN`` (8.0) or more is CRITICAL.

Outputs: ``dbcsr_tpu_slo_burn_rate{objective}`` gauges (scraped +
sampled back into the store, so ``--trend`` replays burn history from
the shard alone), rising-edge ``slo_burn`` bus events +
``dbcsr_tpu_slo_burn_total{objective}``, and the ``slo`` component of
`health.verdict()` (`component()`).

Stdlib-only at import; evaluation is driven by
`timeseries.sample()` — `collect()` — so SLO cost rides the sampling
cadence, never the multiply hot path.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

from dbcsr_tpu.obs import timeseries as _ts

_lock = threading.Lock()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective.

    ``kind``:

    * ``gauge_threshold`` — bad fraction = samples of ``metric``
      violating ``op``/``target`` over all matching series.
    * ``counter_ratio`` — bad fraction = (sum of ``bad_metrics``
      deltas − sum of ``credit_metrics`` deltas, clamped ≥ 0) /
      (sum of ``total_metrics`` deltas) over the window.  Each metric
      entry is a name, or a ``(name, ((label, value), ...))`` pair
      restricting the delta to series matching those labels.
    """
    name: str
    kind: str
    metric: str = ""
    labels: tuple = ()
    op: str = ">"           # gauge_threshold: "bad when value <op> target"
    target_env: str = ""
    target_default: float = 0.0
    budget_env: str = ""
    budget_default: float = 0.1
    bad_metrics: tuple = ()
    credit_metrics: tuple = ()
    total_metrics: tuple = ()

    def target(self) -> float:
        return _env_float(self.target_env, self.target_default) \
            if self.target_env else self.target_default

    def budget(self) -> float:
        b = _env_float(self.budget_env, self.budget_default) \
            if self.budget_env else self.budget_default
        return max(b, 1e-12)


DEFAULT_OBJECTIVES = (
    Objective(
        name="serve_p95_latency", kind="gauge_threshold",
        metric="dbcsr_tpu_serve_latency_p95_ms", op=">",
        target_env="DBCSR_TPU_SLO_SERVE_P95_MS", target_default=500.0,
        budget_env="DBCSR_TPU_SLO_SERVE_P95_BUDGET", budget_default=0.10),
    Objective(
        name="serve_errors", kind="counter_ratio",
        bad_metrics=("dbcsr_tpu_serve_shed_total",
                     "dbcsr_tpu_serve_deadline_missed_total"),
        # the denominator counts each SUBMISSION exactly once: the
        # requests_total counter also records terminal outcomes (done/
        # failed/...), which would double-count a completed request and
        # halve the burn rate — only the admission outcomes qualify
        total_metrics=(
            ("dbcsr_tpu_serve_requests_total",
             (("outcome", "admitted"),)),
            ("dbcsr_tpu_serve_requests_total",
             (("outcome", "queued_degraded"),)),
            ("dbcsr_tpu_serve_requests_total",
             (("outcome", "shed"),))),
        budget_env="DBCSR_TPU_SLO_SERVE_ERR_BUDGET", budget_default=0.05),
    Objective(
        name="roofline_floor", kind="gauge_threshold",
        metric="dbcsr_tpu_roofline_fraction", op="<",
        target_env="DBCSR_TPU_SLO_ROOFLINE_FLOOR", target_default=0.002,
        budget_env="DBCSR_TPU_SLO_ROOFLINE_BUDGET", budget_default=0.25),
    Objective(
        name="abft_unrecovered", kind="counter_ratio",
        bad_metrics=("dbcsr_tpu_abft_mismatches_total",),
        credit_metrics=("dbcsr_tpu_abft_recoveries_total",),
        total_metrics=("dbcsr_tpu_abft_checks_total",),
        budget_env="DBCSR_TPU_SLO_SDC_BUDGET", budget_default=1e-6),
)

# extra objectives registered by embedding apps/tests
_extra: list = []
# rising-edge state + last evaluation (the health component reads it)
_burning: dict = {}
_last_eval: dict = {}
_last_eval_t = 0.0

# minimum samples in a window before a gauge objective may judge it
_MIN_POINTS = 2


def objectives() -> tuple:
    return DEFAULT_OBJECTIVES + tuple(_extra)


def register_objective(obj: Objective) -> None:
    _extra.append(obj)


def reset() -> None:
    global _last_eval_t
    with _lock:
        _burning.clear()
        _last_eval.clear()
        del _extra[:]
        _last_eval_t = 0.0


def windows_s() -> tuple:
    """(short_s, long_s) evaluation windows."""
    short = max(1.0, _env_float("DBCSR_TPU_SLO_SHORT_S", 60.0))
    long_ = max(short, _env_float("DBCSR_TPU_SLO_LONG_S", 600.0))
    return short, long_


# ---------------------------------------------------------- evaluation

def _gauge_bad_fraction(obj: Objective, since: float,
                        path: str | None) -> tuple:
    """(bad_fraction or None, detail) over one window."""
    total = bad = 0
    offenders: dict = {}
    target, over = obj.target(), obj.op == ">"
    for ser in _ts.query(obj.metric, labels=dict(obj.labels) or None,
                         since=since, path=path, tier="auto"):
        for t, v in ser["points"]:
            total += 1
            violated = v > target if over else v < target
            if violated:
                bad += 1
                key = ",".join(f"{k}={v2}" for k, v2 in
                               sorted(ser["labels"].items())) or "-"
                offenders[key] = offenders.get(key, 0) + 1
    if total < _MIN_POINTS:
        return None, {}
    return bad / total, offenders


def _counter_delta(metric, since: float, path: str | None) -> float:
    """Summed per-series increase of a counter over the window
    (clamped ≥ 0 per series: a reset mid-window must not go negative).
    ``metric`` is a name or a ``(name, labels_pairs)`` restriction."""
    labels = None
    if isinstance(metric, tuple):
        metric, pairs = metric
        labels = dict(pairs)
    out = 0.0
    for ser in _ts.query(metric, labels=labels, since=since, path=path,
                         tier="auto"):
        pts = ser["points"]
        if len(pts) >= 2:
            out += max(0.0, pts[-1][1] - pts[0][1])
    return out


def _ratio_bad_fraction(obj: Objective, since: float,
                        path: str | None) -> tuple:
    total = sum(_counter_delta(m, since, path) for m in obj.total_metrics)
    if total <= 0:
        return None, {}
    bad = sum(_counter_delta(m, since, path) for m in obj.bad_metrics)
    credit = sum(_counter_delta(m, since, path)
                 for m in obj.credit_metrics)
    bad = max(0.0, bad - credit)
    return bad / total, {"bad": bad, "total": total}


def evaluate(now: float | None = None, path: str | None = None) -> dict:
    """Evaluate every objective over the short and long windows.

    Returns ``{name: {"burn", "burn_short", "burn_long",
    "bad_frac_short", "bad_frac_long", "target", "budget", "status",
    "detail"}}``; ``status`` is ``OK``/``BURNING``/``NO_DATA``.  With
    ``path`` the evaluation replays a committed shard family instead
    of the live store (offline analysis — no side effects on the
    rising-edge state)."""
    now = time.time() if now is None else now
    short_s, long_s = windows_s()
    out: dict = {}
    for obj in objectives():
        row: dict = {"target": obj.target() if obj.kind == "gauge_threshold"
                     else None,
                     "budget": obj.budget(), "windows_s": [short_s, long_s]}
        fracs = []
        details = []
        for w in (short_s, long_s):
            since = now - w
            if obj.kind == "gauge_threshold":
                frac, det = _gauge_bad_fraction(obj, since, path)
            else:
                frac, det = _ratio_bad_fraction(obj, since, path)
            fracs.append(frac)
            details.append(det)
        if any(f is None for f in fracs):
            row.update(status="NO_DATA", burn=0.0, burn_short=0.0,
                       burn_long=0.0, bad_frac_short=fracs[0],
                       bad_frac_long=fracs[1], detail=details[0] or {})
            out[obj.name] = row
            continue
        budget = obj.budget()
        burn_short = fracs[0] / budget
        burn_long = fracs[1] / budget
        burn = min(burn_short, burn_long)
        row.update(
            burn=round(burn, 4), burn_short=round(burn_short, 4),
            burn_long=round(burn_long, 4),
            bad_frac_short=round(fracs[0], 6),
            bad_frac_long=round(fracs[1], 6),
            status="BURNING" if burn > 1.0 else "OK",
            detail=details[0] or details[1] or {})
        out[obj.name] = row
    return out


# ------------------------------------------------------ store coupling

def collect(now: float | None = None) -> list:
    """Evaluate against the LIVE store, publish gauges + rising-edge
    ``slo_burn`` events, cache the result for `component()`, and
    return the burn-rate points for `timeseries.sample()` to ingest
    (so burn history persists in the shard next to its inputs)."""
    global _last_eval_t
    now = time.time() if now is None else now
    ev = evaluate(now=now)
    pts = []
    from dbcsr_tpu.obs import metrics as _metrics

    for name, row in ev.items():
        burn = row["burn"]
        _metrics.gauge(
            "dbcsr_tpu_slo_burn_rate",
            "multi-window SLO error-budget burn rate per objective "
            "(min of short/long windows; >1 = budget burning)",
        ).set(burn, objective=name)
        pts.append(("dbcsr_tpu_slo_burn_rate", {"objective": name},
                    burn, _ts.GAUGE))
        _edge(name, row, now)
    with _lock:
        _last_eval.clear()
        _last_eval.update(ev)
        _last_eval_t = now
    return pts


def _edge(name: str, row: dict, now: float) -> None:
    """Rising-edge ``slo_burn`` emission per objective (the anomaly
    detectors' convention: one event + one counter inc per entry into
    the burning state; re-arms below threshold)."""
    burning = row["status"] == "BURNING"
    with _lock:
        was = _burning.get(name, False)
        _burning[name] = burning
    if burning and not was:
        from dbcsr_tpu.obs import events as _events
        from dbcsr_tpu.obs import metrics as _metrics

        _metrics.counter(
            "dbcsr_tpu_slo_burn_total",
            "SLO burn-rate alerts by objective (rising edge)",
        ).inc(objective=name)
        _events.publish("slo_burn", {
            "objective": name, "burn": row["burn"],
            "burn_short": row["burn_short"],
            "burn_long": row["burn_long"], "budget": row["budget"],
            "detail": str(row.get("detail", ""))[:200]}, flight=True)
        # a burn transition is a health transition: force the next
        # sample boundary so the shard records the state change —
        # and arm an incident-bundle capture there (flag-set only)
        _ts.request_sample(f"slo_burn:{name}")
        try:
            from dbcsr_tpu.obs import incidents as _incidents

            _incidents.trigger(f"slo_burn:{name}",
                               {"burn": row["burn"]})
        except Exception:
            pass


def burning() -> dict:
    """{objective: last evaluation row} of objectives currently in the
    burning state."""
    with _lock:
        return {n: dict(_last_eval[n]) for n, on in _burning.items()
                if on and n in _last_eval}


def component() -> dict:
    """The ``slo`` component of `health.verdict()`: DEGRADED while any
    objective burns, CRITICAL at ``DBCSR_TPU_SLO_CRITICAL_BURN`` (8x)
    sustained burn; OK (with a reason) when the store is off or no
    evaluation ran yet.  A cached evaluation older than the long
    window is re-evaluated here: sampling is boundary-driven, so an
    idle process would otherwise serve a past burn as CRITICAL forever
    (503ing ``/healthz`` long after the windows drained)."""
    global _last_eval_t

    from dbcsr_tpu.obs import health as _health

    status, reasons = _health.OK, []
    if not _ts.enabled():
        return {"status": status,
                "reasons": ["timeseries store off (DBCSR_TPU_TS=0): "
                            "SLOs not evaluated"],
                "objectives": {}}
    crit = _env_float("DBCSR_TPU_SLO_CRITICAL_BURN", 8.0)
    now = time.time()
    _, long_s = windows_s()
    with _lock:
        ev = {n: dict(r) for n, r in _last_eval.items()}
        t_eval = _last_eval_t
    if t_eval and now - t_eval > long_s:
        # stale cache: recompute for reporting (no rising-edge side
        # effects — the next collect() owns the edge state)
        ev = evaluate(now=now)
        with _lock:
            _last_eval.clear()
            _last_eval.update(ev)
            _last_eval_t = t_eval = now
    for name, row in sorted(ev.items()):
        if row["status"] != "BURNING":
            continue
        status = _health.DEGRADED
        reasons.append(
            f"objective {name!r} burning its error budget at "
            f"{row['burn']:.1f}x (short {row['burn_short']:.1f}x / "
            f"long {row['burn_long']:.1f}x, budget {row['budget']:g})")
        if row["burn"] >= crit:
            status = _health.CRITICAL
            reasons.append(
                f"{name!r} sustained burn ≥ {crit:g}x: the budget is "
                f"gone within the long window — shed load or roll back")
    return {"status": status, "reasons": reasons, "objectives": ev,
            "t_eval": t_eval or None}
