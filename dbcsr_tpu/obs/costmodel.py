"""Analytic FLOP/byte cost model, roofline peaks, and XLA cross-check.

The attribution layer the reference builds into its STATISTICS block
(`dbcsr_mm_sched.F:390-546` true-vs-marketing flops) and that CP2K uses
to say *how far from peak* a run is — rebuilt as three pieces:

* **Analytic model** — `stack_flops`/`stack_bytes` model one parameter
  stack (gather A+B per entry, C read+written once per segment — the
  same HBM-traffic convention as `acc/bench.py`), `dense_cost` one
  dense-canvas matmul.  `core.stats` aggregates these per driver, so
  `obs.metrics.snapshot()` can report achieved GFLOP/s, arithmetic
  intensity and roofline fraction per stack driver.
* **Roofline peak table** — per-`device_kind` peak compute (per dtype)
  and memory/interconnect bandwidth.  The built-ins are order-of-
  magnitude engineering estimates, not vendor numbers; override with
  ``DBCSR_TPU_ROOFLINE`` (a JSON dict merged over the table) or the
  scalar ``DBCSR_TPU_PEAK_GFLOPS`` / ``DBCSR_TPU_PEAK_GBS`` /
  ``DBCSR_TPU_ICI_GBS`` env knobs.  `roofline()` computes the
  attainable rate ``min(peak, intensity * bw)`` and the achieved
  fraction of it.
* **XLA cross-check** — with ``DBCSR_TPU_XLA_COST=1`` (or
  `enable_xla_capture()`), the first launch of each jitted stack-kernel
  specialization additionally captures XLA's own
  ``lowered.compile().cost_analysis()`` / ``memory_analysis()`` numbers
  (one extra AOT compile per specialization — opt-in for exactly that
  reason), stored next to the analytic model's prediction so drift
  between the two is a queryable artifact (`xla_costs()`, and
  `metrics.snapshot()["xla_cost"]`).

Module-level imports are stdlib-only: `core.stats` imports this module
on the multiply hot path, and must stay importable without jax.
"""

from __future__ import annotations

import json
import os


# ---------------------------------------------------------------- model

def stack_flops(m: int, n: int, k: int, entries: int) -> int:
    """True flops of one parameter stack: 2*m*n*k per entry (the
    reference's 'true flops', `dbcsr_mm.F:664-667`)."""
    return 2 * m * n * k * entries


def stack_bytes(m: int, n: int, k: int, entries: int, *,
                nseg: int | None = None, itemsize: int = 8) -> int:
    """Modeled HBM traffic of one stack: gather one A (m,k) and one B
    (k,n) block per entry, read+write each C segment once.  A lower
    bound — TPU tile padding and revisited gathers only add to it; the
    same convention as the `acc/bench.py` GB/s line, so kernel
    micro-bench and engine rollups are comparable."""
    if nseg is None:
        nseg = entries
    return itemsize * (entries * (m * k + k * n) + 2 * nseg * m * n)


def superstack_bytes(span_shapes, *, nseg: int, itemsize: int = 8) -> int:
    """Modeled HBM traffic of one FUSED C-bin launch: every span still
    gathers its own A/B blocks, but the bin's C buffer is read+written
    exactly once for the whole launch — the N−1 C round-trips the
    per-span path pays are the traffic fusion eliminates, so charging
    them would overstate bytes and understate the fused roofline
    fraction.  ``span_shapes`` is an iterable of (m, n, k, entries)
    sharing one (m, n); equals the sum of per-span `stack_bytes` where
    only the first span passes ``nseg`` and the rest pass ``nseg=0``
    (the convention `mm.multiply._run_stacks` records)."""
    gather = 0
    m = n = 0
    for m, n, k, entries in span_shapes:
        gather += entries * (m * k + k * n)
    return itemsize * (gather + 2 * nseg * m * n)


def dense_cost(m: int, n: int, k: int, *, itemsize: int = 8) -> dict:
    """FLOPs/bytes of one dense (m,k)x(k,n) canvas matmul: read A and
    B once, write (and read, for beta-merge) C once."""
    flops = 2 * m * n * k
    nbytes = itemsize * (m * k + k * n + 2 * m * n)
    return {"flops": flops, "bytes": nbytes,
            "intensity": flops / nbytes if nbytes else 0.0}


def intensity(flops: float, nbytes: float) -> float:
    """Arithmetic intensity in flops/byte."""
    return float(flops) / float(nbytes) if nbytes else 0.0


# ------------------------------------------- storage-format cost curves

# Modeled efficiency of each execution format relative to its own
# roofline attainable.  The stack engine's per-entry gathers revisit
# tile-padded rows and its scatter read-modify-writes C segments, so it
# lands far below attainable (acc/bench.py measures 5-15% across
# devices; PERF_NOTES.md's 23^3 f64 case measured 7.3 vs 370 GFLOP/s
# dense); one big padded GEMM runs near peak.  These constants are the
# model's PRIOR — the planner's decision is overridden per device by
# learned `format`/`format_occ` rows in the tune params table, so a
# wrong prior costs one mis-crossover window, not the fleet's steady
# state.
_FORMAT_EFF = {"stack": 0.10, "dense": 0.70, "composite": 0.55}
# fixed per-launch dispatch overhead charged to every format leg
_DISPATCH_S = 5e-5


def format_costs(*, nbr: int, nbc: int, nbk: int,
                 bm: int, bn: int, bk: int, entries: int,
                 nseg: int | None = None, dispatches: int = 1,
                 panels=None, dtype: str = "float64",
                 itemsize: int = 8, kind: str | None = None) -> dict:
    """Occupancy-parameterized cost curves of one product under each
    storage format: modeled seconds and GFLOP/s for the BCSR stack
    path, the whole-panel padded dense GEMM, and (when ``panels``
    describes a feasible packing) the block-diagonal composite panel.

    ``entries`` is the product's TRUE (A-block, B-block) pair count —
    the stack path's work scales with it (occupancy), the dense panel's
    work is the full ``(nbr*bm, nbk*bk) x (nbk*bk, nbc*bn)`` canvas
    regardless.  ``panels`` is the ``(groups, panel_rows, panel_kblocks)``
    summary of `mm.multiply.composite_panels`; None marks composite
    structurally ineligible.  Each leg models ``t = max(flops/peak,
    bytes/bw) / efficiency + dispatch`` against the live `peaks_for`
    roofline (env peak overrides apply, so tests pin the crossover
    deterministically).  Stdlib-only, like everything in this module.
    """
    kind = kind or device_kind()
    peak = peak_gflops(kind, dtype) * 1e9
    bw = peaks_for(kind)["gbs"] * 1e9

    def _leg(fmt: str, flops: float, nbytes: float, n_disp: int) -> dict:
        eff = _FORMAT_EFF[fmt]
        t_min = max(flops / peak if peak else 0.0,
                    nbytes / bw if bw else 0.0)
        secs = t_min / eff + n_disp * _DISPATCH_S
        return {"flops": int(flops), "bytes": int(nbytes),
                "seconds": secs,
                "gflops": flops / secs / 1e9 if secs > 0 else 0.0}

    entries = max(int(entries), 1)
    true_flops = 2.0 * bm * bn * bk * entries
    dense = dense_cost(nbr * bm, nbc * bn, nbk * bk, itemsize=itemsize)
    out = {
        "stack": _leg("stack", true_flops,
                      stack_bytes(bm, bn, bk, entries,
                                  nseg=nseg, itemsize=itemsize),
                      max(int(dispatches), 1)),
        "dense": _leg("dense", dense["flops"], dense["bytes"], 1),
        "composite": None,
    }
    if panels is not None:
        groups, mp, kp = (int(panels[0]), int(panels[1]), int(panels[2]))
        n_el = nbc * bn
        c_flops = 2.0 * groups * (mp * bm) * n_el * (kp * bk)
        c_bytes = itemsize * groups * (
            mp * bm * kp * bk + kp * bk * n_el + 2 * mp * bm * n_el)
        out["composite"] = _leg("composite", c_flops, c_bytes, 1)
    return out


# machine epsilon of the ACCUMULATION dtype each engine dtype uses
# (bf16 accumulates in f32, acc/smm._accum_dtype) — stdlib-only so the
# tolerance stays computable without jax/numpy imported
_ACC_EPS = {
    "float64": 2.220446049250313e-16,
    "complex128": 2.220446049250313e-16,
    "float32": 1.1920929e-07,
    "complex64": 1.1920929e-07,
    "bfloat16": 1.1920929e-07,  # f32 accumulation
    "float16": 9.765625e-04,
}


# machine epsilon of each COMPUTE dtype's own representation (the
# input-rounding term of a demoted or reduced-precision kernel) — the
# companion of _ACC_EPS, which maps bf16 to its f32 ACCUMULATION
# epsilon instead.  Stdlib-only like everything in this module.
_COMPUTE_EPS = {
    "float64": 2.220446049250313e-16,
    "complex128": 2.220446049250313e-16,
    "float32": 1.1920929e-07,
    "complex64": 1.1920929e-07,
    "bfloat16": 2.0 ** -8,
    "float16": 2.0 ** -11,
}


def effective_epsilon(compute: str, compensated: bool) -> float:
    """Effective per-product relative rounding of a DEMOTED compute
    scheme: the compute dtype's own epsilon, or — under two-product
    compensation (the hi/lo split of `acc.smm`, which restores every
    cross term and drops only lo·lo plus the split residue) — its
    square, with a x4 margin for the three extra roundings the
    compensated recombination performs."""
    eps = _COMPUTE_EPS.get(str(compute), 2.0 ** -8)
    return 4.0 * eps * eps if compensated else eps


def abft_tolerance(dtype: str, k: int, depth: int) -> float:
    """Relative tolerance of an ABFT probe-checksum comparison: the
    rank-1 probe ``C·v`` vs ``A·(B·v)`` evaluates the same bilinear
    form along two association orders, so the legitimate disagreement
    is pure rounding — bounded by the accumulation dtype's epsilon
    times the reduction lengths (``k`` inner-product terms per entry,
    ``depth`` entries accumulated per C segment).  The constant is an
    engineering margin (false positives trigger a failover walk, far
    more expensive than a slightly blunter detector); injected/real SDC
    perturbs O(1) values, orders of magnitude above this floor."""
    eps = _ACC_EPS.get(str(dtype), 1.1920929e-07)
    k = max(int(k), 1)
    depth = max(int(depth), 1)
    return 64.0 * eps * (k + 1) * float(depth + 1) ** 0.5


def demoted_abft_tolerance(dtype: str, compute: str, compensated: bool,
                           k: int, depth: int) -> float:
    """Probe ceiling of a launch executed at a DEMOTED compute dtype:
    the per-product demotion error is relative to each product term,
    and the probe's comparison scale already bounds the sum of |terms|
    (the S_c scale of the beta==0 probe form, the max-|p| scale of the
    delta form), so the demotion term is the effective compute epsilon
    times the same x64 engineering margin as the native tolerance —
    the (k, depth) reduction factors are NOT re-applied to it (they
    are absorbed by the scale).  EXCEPT: the uncompensated kernel
    accumulates INSIDE the dot at the compute family's natural narrow
    accumulator (`acc.smm._batch_dot`), and a ``k``-deep narrow sum
    legitimately contributes up to ~k*eps_acc relative to sum|terms| —
    callers pass the MERGED contraction length (r0*k for the k-merged
    xla_group layout), or the ceiling would condemn healthy grouped
    launches.  The native accumulation tolerance floors the result (a
    demoted launch can never be held to a tighter bound than a native
    one)."""
    tol = 64.0 * effective_epsilon(compute, compensated)
    if not compensated:
        acc_eps = _ACC_EPS.get(str(compute), 1.1920929e-07)
        tol += 8.0 * acc_eps * max(int(k), 1)
    return max(tol, abft_tolerance(dtype, k, depth))


def kernel_validation_tolerance(dtype: str, k: int, depth: int) -> float:
    """Relative tolerance of a kernel-vs-host-oracle ELEMENTWISE-max
    validation (the first-use Pallas gate in
    `acc.smm._validate_pallas_kernel` and its test-suite mirrors): an
    accumulation term ~eps_acc*sqrt((k+1)*(depth+1)) for the k-deep
    dot times depth-deep segment sum, plus an input-rounding term for
    dtypes whose own epsilon exceeds their accumulation epsilon (bf16
    inputs round at 2^-8 while accumulating in f32) — one dtype-aware
    source of truth replacing the historical `5e-2 if bf16 else 1e-5`
    literals.  Deliberately NOT `abft_tolerance`: that bound carries
    the probe comparison's x64 margin and scale-absorption reasoning,
    which would loosen this elementwise gate ~100x and let a subtly
    miscompiled kernel through first-use validation."""
    eps_acc = _ACC_EPS.get(str(dtype), 1.1920929e-07)
    eps_in = _COMPUTE_EPS.get(str(dtype), eps_acc)
    k = max(int(k), 1)
    depth = max(int(depth), 1)
    return max(2.0 * eps_acc * float((k + 1) * (depth + 1)) ** 0.5,
               4.0 * eps_in * float(k + 1) ** 0.5)


# ------------------------------------------------------- roofline table

# Per-device_kind peaks.  Matching is by lowercase substring of
# jax's `device.device_kind` ("TPU v5 lite", "TPU v4", "cpu", ...).
# "gflops" is peak compute per chip per dtype; f64/c128 entries model
# the EMULATED split-f32/bf16 passes on TPU (no native f64 unit).
# "gbs" is HBM bandwidth, "ici_gbs" per-device interconnect bandwidth
# (the Cannon ring rides ICI).  All are engineering estimates meant to
# anchor a fraction-of-peak signal, not vendor benchmarks — override
# via DBCSR_TPU_ROOFLINE / DBCSR_TPU_PEAK_* for calibrated numbers.
_PEAKS: dict = {
    "tpu v6": {"gflops": {"bfloat16": 918000.0, "float32": 229000.0,
                          "float64": 7000.0},
               "gbs": 1640.0, "ici_gbs": 448.0},
    "tpu v5p": {"gflops": {"bfloat16": 459000.0, "float32": 115000.0,
                           "float64": 5000.0},
                "gbs": 2765.0, "ici_gbs": 600.0},
    "tpu v5 lite": {"gflops": {"bfloat16": 197000.0, "float32": 49000.0,
                               "float64": 3000.0},
                    "gbs": 819.0, "ici_gbs": 200.0},
    "tpu v4": {"gflops": {"bfloat16": 275000.0, "float32": 69000.0,
                          "float64": 4000.0},
               "gbs": 1228.0, "ici_gbs": 300.0},
    # the CI container: one CPU core through XLA-CPU (BASELINE.md's
    # committed north-star engine number is ~3 GFLOP/s f64)
    "cpu": {"gflops": {"bfloat16": 100.0, "float32": 100.0,
                       "float64": 50.0},
            "gbs": 20.0, "ici_gbs": 20.0},
}
_DEFAULT_PEAK = {"gflops": {"float64": 100.0, "float32": 200.0,
                            "bfloat16": 200.0},
                 "gbs": 100.0, "ici_gbs": 100.0}

_env_table = None  # parsed DBCSR_TPU_ROOFLINE, cached


def _env_overrides() -> dict:
    global _env_table
    if _env_table is None:
        raw = os.environ.get("DBCSR_TPU_ROOFLINE", "")
        try:
            _env_table = json.loads(raw) if raw else {}
        except ValueError:
            _env_table = {}
    return _env_table


def device_kind() -> str:
    """Best-effort `device_kind` of the default device.  Never forces
    backend initialization (same guard as `obs.tracer._process_index`):
    before any jax work has run it reports "unknown"."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return "unknown"
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return "unknown"
    try:
        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def peaks_for(kind: str | None = None) -> dict:
    """Peak entry for a device kind: longest-matching table row, with
    env overrides folded in.  Unknown kinds get the conservative
    generic entry."""
    kind = (kind or device_kind()).lower()
    table = dict(_PEAKS)
    for key, row in _env_overrides().items():
        base = dict(table.get(key.lower(), _DEFAULT_PEAK))
        gf = dict(base.get("gflops", {}))
        gf.update(row.get("gflops", {}))
        base.update(row)
        base["gflops"] = gf
        table[key.lower()] = base
    best = None
    for key, row in table.items():
        if key in kind and (best is None or len(key) > len(best[0])):
            best = (key, row)
    entry = dict(best[1]) if best else dict(_DEFAULT_PEAK)
    env_gf = os.environ.get("DBCSR_TPU_PEAK_GFLOPS")
    if env_gf:
        entry["gflops"] = {d: float(env_gf) for d in
                           set(entry["gflops"]) | {"float64", "float32"}}
    env_bw = os.environ.get("DBCSR_TPU_PEAK_GBS")
    if env_bw:
        entry["gbs"] = float(env_bw)
    env_ici = os.environ.get("DBCSR_TPU_ICI_GBS")
    if env_ici:
        entry["ici_gbs"] = float(env_ici)
    return entry


def peak_gflops(kind: str | None = None, dtype: str = "float64") -> float:
    """Peak compute for a dtype on a device kind.  Complex dtypes map
    to their real component peak / 4 (a complex MAC is 4 real MACs;
    the engine counts 2*m*n*k 'entry flops' regardless of dtype)."""
    entry = peaks_for(kind)
    gf = entry["gflops"]
    dtype = str(dtype)
    if dtype in gf:
        return float(gf[dtype])
    if dtype == "complex64":
        return float(gf.get("float32", _DEFAULT_PEAK["gflops"]["float32"])) / 4
    if dtype == "complex128":
        return float(gf.get("float64", _DEFAULT_PEAK["gflops"]["float64"])) / 4
    if dtype == "float16":
        return float(gf.get("bfloat16", gf.get("float32", 100.0)))
    return float(gf.get("float32", _DEFAULT_PEAK["gflops"]["float32"]))


def roofline(flops: float, nbytes: float, seconds: float,
             kind: str | None = None, dtype: str = "float64") -> dict:
    """Roofline attribution of one measured region: achieved GFLOP/s,
    arithmetic intensity, the attainable rate at that intensity
    (``min(peak_compute, intensity * peak_bandwidth)``), and the
    achieved fraction of it."""
    kind = kind or device_kind()
    entry = peaks_for(kind)
    peak = peak_gflops(kind, dtype)
    inten = intensity(flops, nbytes)
    attainable = min(peak, inten * entry["gbs"]) if nbytes else peak
    achieved = flops / seconds / 1e9 if seconds > 0 else 0.0
    return {
        "device_kind": kind,
        "dtype": str(dtype),
        "achieved_gflops": achieved,
        "arithmetic_intensity": inten,
        "peak_gflops": peak,
        "peak_gbs": entry["gbs"],
        "attainable_gflops": attainable,
        "roofline_fraction": achieved / attainable if attainable else 0.0,
        "bytes_moved": int(nbytes),
        "flops": int(flops),
        "seconds": seconds,
    }


def _tick_balance(flops: float, comm_bytes: float, dtype: str,
                  kind: str | None) -> dict:
    """Comm/compute balance of one metronome tick against the roofline
    peaks: ``overlap_ratio`` = modeled comm time / compute time — below
    1.0 the collective hides fully behind the local contraction (the
    comm-thread overlap the reference gets from USE_COMM_THREAD)."""
    kind = kind or device_kind()
    peak = peak_gflops(kind, dtype) * 1e9
    ici = peaks_for(kind)["ici_gbs"] * 1e9
    t_comp = flops / peak if peak else 0.0
    t_comm = comm_bytes / ici if ici else 0.0
    return {
        "tick_flops": int(flops),
        "tick_comm_bytes": int(comm_bytes),
        "t_compute_s": t_comp,
        "t_comm_s": t_comm,
        "overlap_ratio": (t_comm / t_comp) if t_comp > 0 else 0.0,
    }


def cannon_tick_model(m: int, n: int, k: int, kl: int, s: int,
                      itemsize: int, dtype: str,
                      kind: str | None = None) -> dict:
    """Per-device, per-tick comm/compute balance of the dense Cannon:
    each metronome tick contracts a local (m/s, k/(kl*s)) x
    (k/(kl*s), n/s) panel while ring-shifting both operand shards over
    ICI."""
    m_loc, n_loc, k_loc = m / s, n / s, k / (kl * s)
    flops = 2.0 * m_loc * n_loc * k_loc
    comm_bytes = (m_loc * k_loc + k_loc * n_loc) * itemsize
    return _tick_balance(flops, comm_bytes, dtype, kind)


def mesh_tick_model(cap_a: int, cap_b: int, bm: int, bk: int, bn: int,
                    entries: int, nticks: int, ndev: int,
                    itemsize: int, dtype: str,
                    kind: str | None = None) -> dict:
    """Per-device, per-tick comm/compute balance of the block-sparse
    mesh Cannon: each tick ring-shifts a full padded A panel
    (``cap_a`` blocks of (bm, bk)) and B panel (``cap_b`` of (bk, bn))
    while contracting this tick's share of the symbolic product's
    ``entries`` (true flops split evenly over devices x ticks — the
    stack fill balances by construction)."""
    flops = 2.0 * bm * bn * bk * entries / max(ndev * nticks, 1)
    comm_bytes = (cap_a * bm * bk + cap_b * bk * bn) * itemsize
    return _tick_balance(flops, comm_bytes, dtype, kind)


def gather_chunk_model(cap_a: int, cap_b: int, bm: int, bk: int, bn: int,
                       entries: int, nticks: int, ndev: int,
                       itemsize: int, dtype: str,
                       kind: str | None = None) -> dict:
    """Per-device, per-chunk comm/compute balance of the CHUNKED
    all-gather pipeline on rectangular grids: each of the ``nticks``
    ring steps moves one padded A shard (``cap_a`` blocks of (bm, bk))
    and one B shard over ICI while the tick contracts its
    shard-arrival share of the product's ``entries`` (the same shard
    pair per step a Cannon tick ring-shifts — `mesh_tick_model`'s
    balance applied to the gather schedule, so the two routes share
    one gauge family)."""
    return mesh_tick_model(cap_a, cap_b, bm, bk, bn, entries, nticks,
                           ndev, itemsize, dtype, kind)


# ------------------------------------------------------- XLA cross-check

_xla_costs: dict = {}  # fn -> {key_str: {model + xla numbers}}
_capture = None  # resolved lazily from env; enable_xla_capture overrides


def xla_capture_enabled() -> bool:
    global _capture
    if _capture is None:
        _capture = os.environ.get("DBCSR_TPU_XLA_COST", "").lower() in (
            "1", "true", "yes")
    return _capture


def enable_xla_capture(on: bool = True) -> None:
    """Programmatic toggle for the per-specialization XLA cost capture
    (the env knob is ``DBCSR_TPU_XLA_COST=1``)."""
    global _capture
    _capture = bool(on)


def capture_xla_cost(fn_name: str, key, jit_fn, args, *,
                     kwargs: dict | None = None,
                     model: dict | None = None) -> dict | None:
    """Capture XLA's own cost/memory analysis for one fresh jit
    specialization, storing it next to the analytic ``model`` numbers.

    Costs one extra AOT ``lower().compile()`` of the same computation
    (the dispatch-path cache is separate), so call sites gate on
    `xla_capture_enabled()` AND on `metrics.record_jit` returning True
    — once per specialization, never on the steady-state path.
    Best-effort: any backend/API failure records nothing."""
    try:
        compiled = jit_fn.lower(*args, **(kwargs or {})).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        rec = {
            "xla_flops": float(ca.get("flops", 0.0)),
            "xla_bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        }
        try:
            ma = compiled.memory_analysis()
            rec["xla_argument_bytes"] = int(
                getattr(ma, "argument_size_in_bytes", 0))
            rec["xla_output_bytes"] = int(
                getattr(ma, "output_size_in_bytes", 0))
            rec["xla_temp_bytes"] = int(
                getattr(ma, "temp_size_in_bytes", 0))
        except Exception:
            pass
        if model:
            rec["model"] = dict(model)
            if model.get("flops") and rec["xla_flops"]:
                rec["flops_ratio"] = rec["xla_flops"] / model["flops"]
        _xla_costs.setdefault(fn_name, {})[str(key)] = rec
        return rec
    except Exception:
        return None


def xla_costs() -> dict:
    """{fn: {specialization_key: {model vs XLA numbers}}} for every
    capture since the last `reset()`."""
    return {fn: dict(d) for fn, d in _xla_costs.items()}


def reset() -> None:
    _xla_costs.clear()
