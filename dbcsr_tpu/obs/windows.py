"""Shared rolling-window statistics: the ONE quantile/median/MAD
implementation the obs plane agrees on.

Three consumers historically carried private copies of this logic —
`serve/engine.py`'s exact rolling p50/p95 (the `/serve/tenants`
latency percentiles), `obs/health.py`'s median/MAD (the
`tools/perf_gate.py` noise convention reused by the latency-spike
detector), and now `obs/slo.py`'s multi-window burn rates.  They are
deduplicated here with the historical output conventions PINNED:

* `median` / `mad` — the perf-gate convention: true median (mean of
  the two middle elements on even length), MAD = median of absolute
  deviations.  `obs/health.py` re-exports both unchanged.
* `rank_quantile` — the serving plane's exact empirical quantile:
  ``sorted_xs[min(n - 1, int(n * q))]``.  For q=0.5 this is
  ``sorted_xs[n // 2]`` — the upper median, NOT `median()`'s
  interpolated one; `/serve/tenants` has always reported it this way
  and the pinned tests keep it so.
* `Window` — a bounded rolling sample window with O(1) running sums
  (the health detectors' budget: no O(window) pass per multiply).

Stdlib-only: `serve.engine` and `obs.health` reach this from hot-ish
paths.
"""

from __future__ import annotations

import collections


def median(xs) -> float:
    """True median (interpolated on even length) — the
    `tools/perf_gate.py` noise convention."""
    xs = sorted(xs)
    n = len(xs)
    if n == 0:
        return 0.0
    mid = n // 2
    return float(xs[mid]) if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def mad(xs) -> float:
    """Median absolute deviation (same convention as `median`)."""
    m = median(xs)
    return median([abs(x - m) for x in xs])


def rank_quantile(sorted_xs, q: float) -> float:
    """The serving plane's exact empirical quantile over an already
    SORTED sequence: ``sorted_xs[min(n - 1, int(n * q))]``.  Matches
    the historical `/serve/tenants` p50/p95 outputs bit-for-bit."""
    n = len(sorted_xs)
    if n == 0:
        return 0.0
    return float(sorted_xs[min(n - 1, int(n * q))])


def p50_p95(values) -> tuple:
    """(p50, p95) of an UNSORTED sample via `rank_quantile` — the one
    call `/serve/tenants` and the timeseries serve collector share."""
    xs = sorted(values)
    return rank_quantile(xs, 0.5), rank_quantile(xs, 0.95)


class Window:
    """Bounded rolling window of float samples with a running sum.

    `append` evicts the oldest sample once ``maxlen`` is reached and
    keeps ``sum`` incrementally — consumers that need a rate over the
    window (shed fraction, recompiles per multiply) read it O(1).
    """

    __slots__ = ("_dq", "sum")

    def __init__(self, maxlen: int):
        self._dq: collections.deque = collections.deque(
            maxlen=max(1, int(maxlen)))
        self.sum = 0.0

    def append(self, v: float) -> None:
        if len(self._dq) == self._dq.maxlen:
            self.sum -= self._dq[0]
        self._dq.append(v)
        self.sum += v

    def __len__(self) -> int:
        return len(self._dq)

    def __iter__(self):
        return iter(self._dq)

    def mean(self) -> float:
        n = len(self._dq)
        return self.sum / n if n else 0.0

    def clear(self) -> None:
        self._dq.clear()
        self.sum = 0.0
