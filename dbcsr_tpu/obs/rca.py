"""Automated root-cause attribution: the change ledger + causal ranker.

With PRs 15–19 the engine *changes itself* continuously — autotuner
promotions rewrite the params table, the format planner learns
crossovers, precision schedules demote cells, breakers quarantine
drivers, the serve fleet fails workers over and rolls them.  When a
change-point fires (`obs/changepoint.py`: "this series stepped to a
worse level at time T"), the question a human used to answer by
scrolling four dashboards is "which of those changes did it".  This
module answers it in-process:

* **Change ledger** — a bounded ring of every *system-change* event,
  fed by an `obs.events.subscribe` hook (the bus is the one choke
  point all change sites already publish through).  The admissible
  kinds are the lint-checked `LEDGER_KINDS` registry: `tools/lint`
  fails tier-1 when a registered kind has no publish site in the tree
  or is missing from docs/observability.md.  Two change classes do not
  reach the bus on their own and are synthesized here:

  - ``knob_change`` — `WATCHED_KNOBS` env knobs (driver/format/
    precision forces) are polled at every sample boundary; a mid-
    process flip becomes a ledger entry (and a bus event),
  - ``format_decision`` — `mm.format_planner` publishes one event per
    *changed* per-bucket choice (not per multiply; see
    `note_decision`).

* **Causal ranking** — when a regression change-point arrives, every
  ledger entry inside the attribution window is scored::

      score = kind_weight * exp(-dt / tau) * (1 + label_overlap)

  ``dt`` is the distance from the entry to the *estimated shift time*
  (entries after the shift keep a doubled distance — the estimate is
  noisy, causes strictly can't postdate their effect), and
  ``label_overlap`` counts (key, value) matches between the regressed
  series' labels and the entry payload (a `tune_promotion` with
  ``driver=xla_group`` outranks an unrelated worker restart for an
  ``achieved_gflops{driver=xla_group}`` shift).

* **Report** — the ranked causes, the change-point, and the
  window-pair profile diff (`obs.profiler.diff_around`) land in a
  bounded report ring (`reports()`, ``GET /rca``,
  ``doctor --diagnose``), count
  ``dbcsr_tpu_rca_reports_total{cause}``, publish an ``rca_report``
  bus event, and arm an `obs.incidents` capture so the full bundle —
  report included — persists for offline diagnosis.

Stdlib-only; every emission is guarded (diagnosis must never fail the
sample boundary that hosts it).
"""

from __future__ import annotations

import collections
import math
import os
import threading

_lock = threading.Lock()

# ------------------------------------------------------------ registry
#
# The checked change-ledger registry (pure literals: `tools/lint`
# loads this by AST).  ``weight`` is the ranking prior — how often
# this change class is the true cause of a perf level shift; ``doc``
# feeds the generated table in docs/observability.md.

LEDGER_KINDS = {
    "tune_promotion": {
        "weight": 1.0,
        "doc": "autotuner promoted a params row (generation bump)",
    },
    "tune_demotion": {
        "weight": 1.0,
        "doc": "a promoted params row was demoted after live regression",
    },
    "format_decision": {
        "weight": 0.9,
        "doc": "the storage-format planner changed a per-bucket choice",
    },
    "knob_change": {
        "weight": 1.0,
        "doc": "a watched DBCSR_TPU_* env knob flipped mid-process",
    },
    "precision_schedule": {
        "weight": 0.8,
        "doc": "the adaptive precision plane (re)scheduled a demotion",
    },
    "precision_promote": {
        "weight": 0.8,
        "doc": "a demoted cell was promoted back to full precision",
    },
    "breaker_transition": {
        "weight": 0.9,
        "doc": "a (driver, shape) circuit breaker changed state",
    },
    "driver_failover": {
        "weight": 0.7,
        "doc": "stacks re-executed on a safer driver after a failure",
    },
    "fleet_failover": {
        "weight": 0.9,
        "doc": "the serve fleet failed a worker's requests over",
    },
    "worker_down": {
        "weight": 0.6,
        "doc": "a serve worker left the fleet (crash or drain)",
    },
    "worker_up": {
        "weight": 0.4,
        "doc": "a serve worker joined the fleet (rolling restart)",
    },
    "incremental_degrade": {
        "weight": 0.8,
        "doc": "the incremental-multiply breaker degraded to full "
               "recompute",
    },
    "multihost_degraded_to_serial": {
        "weight": 0.9,
        "doc": "a world join failed and the engine degraded to serial",
    },
}

# env knobs whose mid-process flips are synthesized into the ledger
# (each is a registered Config-field knob; the values are small
# strings, so the per-boundary poll is a handful of getenv calls)
WATCHED_KNOBS = (
    "DBCSR_TPU_MM_FORMAT",
    "DBCSR_TPU_MM_DRIVER",
    "DBCSR_TPU_PRECISION",
    "DBCSR_TPU_MM_STACK_SIZE",
)

# payload keys copied into a ledger entry / ranked cause (bounded: a
# ledger entry must stay a small flat dict)
_KEEP_KEYS = ("driver", "mnk", "dtype", "generation", "displaced",
              "reason", "knob", "value", "prev", "format", "shape",
              "state", "from", "to", "worker", "tenant", "gflops",
              "stack_size", "kind")

_REPORT_RING_N = 64


def _env_flag() -> bool:
    return os.environ.get("DBCSR_TPU_RCA", "") not in ("0", "off")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


_enabled = _env_flag()


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Tests / embedding apps: flip attribution without the env var."""
    global _enabled
    _enabled = bool(on)


def window_s() -> float:
    """Attribution window: how far before the shift a change can still
    be a candidate cause."""
    return max(1.0, _env_float("DBCSR_TPU_RCA_WINDOW_S", 600.0))


def ledger_n() -> int:
    return max(8, _env_int("DBCSR_TPU_RCA_LEDGER_N", 256))


_ledger: collections.deque = collections.deque(maxlen=ledger_n())
_reports: collections.deque = collections.deque(maxlen=_REPORT_RING_N)
_knob_state: dict = {}
_subscribed = False


# ------------------------------------------------------------- ledger

def _entry_of(rec: dict) -> dict:
    ent = {"t": rec.get("t"), "kind": rec.get("event"),
           "product_id": rec.get("product_id")}
    for k in _KEEP_KEYS:
        if k in rec and rec[k] is not None:
            ent[k] = rec[k]
    return ent


def _on_event(rec: dict) -> None:
    """Bus subscriber: admit registered change kinds into the ledger."""
    if not _enabled:
        return
    kind = rec.get("event")
    if kind not in LEDGER_KINDS:
        return
    with _lock:
        _ledger.append(_entry_of(rec))


def _ensure_subscribed() -> None:
    global _subscribed
    if _subscribed:
        return
    try:
        from dbcsr_tpu.obs import events as _events

        _events.subscribe(_on_event)
        _subscribed = True
    except Exception:
        pass


_ensure_subscribed()


def record(kind: str, args: dict | None = None) -> None:
    """Publish a change onto the bus (and thus into the ledger).  The
    path `mm.format_planner` and the knob poll use — every ledger
    entry is a real bus event, so offline event shards replay the same
    ledger the live process had."""
    try:
        from dbcsr_tpu.obs import events as _events

        _events.publish(kind, args or {})
    except Exception:
        pass


def poll_knobs(now: float | None = None) -> None:
    """Diff the watched env knobs against their last-seen values; a
    flip becomes a ``knob_change`` ledger entry.  Called at every
    sample boundary (`on_sample`)."""
    if not _enabled:
        return
    for knob in WATCHED_KNOBS:
        cur = os.environ.get(knob)
        with _lock:
            seen = knob in _knob_state
            prev = _knob_state.get(knob)
            _knob_state[knob] = cur
        if seen and cur != prev:
            record("knob_change",
                   {"knob": knob, "value": cur, "prev": prev})


def on_sample(rec: dict) -> None:
    """Sample-boundary hook (`obs.timeseries.sample` tail): poll the
    watched knobs so a mid-run flip is on the ledger BEFORE the
    change-point scan of the same boundary runs."""
    if not _enabled or not rec:
        return
    try:
        poll_knobs(rec.get("t"))
    except Exception:
        pass


# ------------------------------------------------------------- ranking

def _overlap(series_labels: dict, ent: dict) -> int:
    n = 0
    for k, v in (series_labels or {}).items():
        if str(ent.get(k)) == str(v):
            n += 1
    return n


def _score(ent: dict, cp: dict, tau: float) -> float:
    w = LEDGER_KINDS.get(ent.get("kind"), {}).get("weight", 0.5)
    t_shift = cp.get("t_shift") or cp.get("t") or 0.0
    dt = t_shift - (ent.get("t") or 0.0)
    if dt < 0:
        # a cause cannot postdate its effect; tolerate shift-estimate
        # noise with a doubled distance instead of a hard cut
        dt = -dt * 2.0
    proximity = math.exp(-dt / max(tau, 1e-9))
    return w * proximity * (1.0 + _overlap(cp.get("labels"), ent))


def on_changepoint(cp: dict) -> dict | None:
    """Rank candidate causes for one regression change-point and emit
    the causal report.  Called by `obs.changepoint` on the sample
    boundary that detected the shift."""
    if not _enabled:
        return None
    t_shift = cp.get("t_shift") or cp.get("t") or 0.0
    win = window_s()
    tau = win / 5.0
    with _lock:
        candidates = [dict(e) for e in _ledger
                      if (e.get("t") or 0.0) >= t_shift - win]
    ranked = sorted(candidates,
                    key=lambda e: _score(e, cp, tau), reverse=True)
    causes = []
    for i, ent in enumerate(ranked[:5]):
        ent["rank"] = i + 1
        ent["score"] = round(_score(ent, cp, tau), 6)
        causes.append(ent)
    try:
        from dbcsr_tpu.obs import profiler as _profiler

        profile_diff = _profiler.diff_around(t_shift)
    except Exception:
        profile_diff = None
    report = {
        "t": cp.get("t"),
        "changepoint": dict(cp),
        "causes": causes,
        "top_cause": causes[0]["kind"] if causes else None,
        "profile_diff": profile_diff,
    }
    with _lock:
        _reports.append(report)
    _emit(report)
    return report


def _emit(report: dict) -> None:
    cause = report.get("top_cause") or "unknown"
    try:
        from dbcsr_tpu.obs import metrics as _metrics

        _metrics.counter(
            "dbcsr_tpu_rca_reports_total",
            "Ranked causal reports emitted, by top-ranked cause kind",
        ).inc(cause=cause)
    except Exception:
        pass
    cp = report.get("changepoint") or {}
    try:
        from dbcsr_tpu.obs import events as _events

        _events.publish("rca_report", {
            "series": cp.get("series"), "top_cause": cause,
            "n_causes": len(report.get("causes") or ()),
            "magnitude": cp.get("magnitude"),
        })
    except Exception:
        pass
    try:
        from dbcsr_tpu.obs import incidents as _incidents
        from dbcsr_tpu.obs import timeseries as _ts

        _incidents.trigger(f"rca:{cp.get('series')}",
                           {"top_cause": cause,
                            "magnitude": cp.get("magnitude")})
        _ts.request_sample(f"rca:{cp.get('series')}")
    except Exception:
        pass


# --------------------------------------------------------------- reads

def ledger(limit: int | None = None, kind: str | None = None) -> list:
    """Change-ledger entries, oldest first."""
    with _lock:
        out = list(_ledger)
    if kind is not None:
        out = [e for e in out if e.get("kind") == kind]
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


def reports(limit: int | None = None) -> list:
    """Ranked causal reports, oldest first."""
    with _lock:
        out = list(_reports)
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


def reset() -> None:
    """Drop the ledger, reports and knob state (tests).  The bus
    subscription stays — it is idempotent process state."""
    global _enabled
    with _lock:
        _ledger.clear()
        _reports.clear()
        _knob_state.clear()
    _enabled = _env_flag()
    _ensure_subscribed()
