"""Per-multiply flight recorder: a bounded ring of the last N products.

Every `multiply()` commits one record — shapes, occupancies, the driver
decisions the dispatch actually made (and *why*: tuned row, prediction,
config force, emulated-dtype default), filtering/eps stats, per-phase
milliseconds, and the memory high-water — into a ring of the last
``DBCSR_TPU_FLIGHT_N`` (default 32) multiplies.  When a production run
dies or a checksum trips, the recorder answers "what was the engine
doing for the last N products" without re-running under a profiler:
`perf/driver.py` dumps it on checksum failure, `bench.py` on any
error, and `dump()`/`to_json()` serve it on demand.

The reference has no analog — its STATISTICS block is cumulative only;
this is the black-box component of the ROADMAP's production-scale
north star.

Reentrancy: TAS group loops run `multiply()` inside `tas_multiply`,
so records form a stack — each nested multiply gets its own record and
commits independently.

Module-level imports are stdlib-only; `core.timings`/`core.stats` are
reached lazily (this module is imported by the multiply hot path).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import time

_ring: collections.deque = collections.deque(
    maxlen=max(1, int(os.environ.get("DBCSR_TPU_FLIGHT_N", "32")))
)
_current: list = []  # stack of in-flight records (nested multiplies)
_seq = 0

# the timed() regions whose per-multiply deltas make up the per-phase
# breakdown (single-chip engine + dense path)
_PHASES = (
    "multiply_index", "multiply_c_assemble", "multiply_stacks",
    "multiply_filter", "multiply_dense", "dense_canvas_ab",
    "dense_dot", "dense_carve", "dense_finalize",
)


def ring_capacity() -> int:
    return _ring.maxlen


def begin(**fields) -> dict:
    """Open a record for the multiply that is starting; hot paths fill
    it via `note`/`note_driver` until `commit`."""
    global _seq
    _seq += 1
    rec = {
        "seq": _seq,
        "t_unix": time.time(),
        "drivers": {},
        **fields,
    }
    rec["_t0"] = time.perf_counter()
    rec["_phase0"] = _phase_snapshot()
    _current.append(rec)
    return rec


def note(key: str, value) -> None:
    """Set a field on the innermost open record (no-op outside one)."""
    if _current:
        _current[-1][key] = value


def note_driver(driver: str, why: str, mnk=None, entries: int = 0) -> None:
    """Accumulate one stack-driver decision onto the open record."""
    if not _current:
        return
    d = _current[-1]["drivers"].setdefault(
        driver, {"stacks": 0, "entries": 0, "why": why})
    d["stacks"] += 1
    d["entries"] += entries
    if mnk is not None:
        d.setdefault("mnk", []).append(list(mnk))


_MAX_EVENTS_PER_RECORD = 64


def note_event(event: str, **fields) -> None:
    """Append one structured event (fault injected, breaker transition,
    driver failover) to the innermost open record's bounded ``events``
    list — the resilience layer's black-box entries.  No-op outside a
    record.

    Overflow drops the OLDEST entry: in a black box the events nearest
    the crash are the diagnostic ones.  ``events_total`` preserves the
    true count, so a truncated list is detectable (``events_total >
    len(events)``)."""
    if not _current:
        return
    rec = _current[-1]
    events = rec.setdefault("events", [])
    rec["events_total"] = rec.get("events_total", 0) + 1
    if len(events) >= _MAX_EVENTS_PER_RECORD:
        del events[0]
    events.append(dict(fields, event=event))


def commit(error: str | None = None) -> dict | None:
    """Close the innermost record: stamp duration, per-phase ms and
    memory high-water, then append it to the ring."""
    if not _current:
        return None
    rec = _current.pop()
    rec["dur_ms"] = round((time.perf_counter() - rec.pop("_t0")) * 1e3, 3)
    rec["phases_ms"] = _phase_delta(rec.pop("_phase0"))
    if error is not None:
        rec["error"] = error
    try:
        from dbcsr_tpu.core import stats

        rec["memory"] = stats.memory_high_water()
    except Exception:
        pass
    _ring.append(rec)
    try:
        from dbcsr_tpu.obs import profiler

        profiler.observe(rec)
    except Exception:
        pass  # profile folding must never fail a multiply
    return rec


def _phase_snapshot() -> dict:
    from dbcsr_tpu.core import timings

    snap = {}
    for name in _PHASES:
        st = timings._stats.get(name)
        if st is not None:
            snap[name] = st.total
    return snap


def _phase_delta(snap: dict) -> dict:
    from dbcsr_tpu.core import timings

    out = {}
    for name in _PHASES:
        st = timings._stats.get(name)
        if st is None:
            continue
        dt = st.total - snap.get(name, 0.0)
        if dt > 0:
            out[name] = round(dt * 1e3, 3)
    return out


def records() -> list:
    """Ring contents, oldest first."""
    return list(_ring)


def clear() -> None:
    _ring.clear()
    _current.clear()


def to_json() -> str:
    return json.dumps(records(), default=str)


def dump(out=None, path: str | None = None) -> None:
    """Human-readable dump of the ring (newest last).  ``path`` (or
    $DBCSR_TPU_FLIGHT_DUMP) additionally writes the full JSON."""
    if out is None:
        out = lambda s: print(s, file=sys.stderr)  # noqa: E731
    path = path or os.environ.get("DBCSR_TPU_FLIGHT_DUMP")
    recs = records()
    out(f" FLIGHT RECORDER — last {len(recs)} multiplies "
        f"(capacity {_ring.maxlen})")
    for r in recs:
        mnk = r.get("mnk") or ("?", "?", "?")
        drv = ",".join(
            f"{d}x{v['stacks']}({v['why']})"
            for d, v in sorted(r.get("drivers", {}).items())
        ) or r.get("algorithm", "-")
        phases = " ".join(
            f"{k.replace('multiply_', '').replace('dense_', 'd:')}="
            f"{v:.1f}"
            for k, v in (r.get("phases_ms") or {}).items()
        )
        err = f"  ERROR={r['error']}" if r.get("error") else ""
        if r.get("events"):
            kinds = ",".join(sorted({e["event"] for e in r["events"]}))
            err += f"  events={len(r['events'])}({kinds})"
        out(f"  #{r['seq']} {r.get('name', '?')} "
            f"{mnk[0]}x{mnk[1]}x{mnk[2]} occ={r.get('occ_c', '-')} "
            f"alg={r.get('algorithm', '?')} drivers=[{drv}] "
            f"eps={r.get('filter_eps')} {r.get('dur_ms', 0):.1f} ms "
            f"[{phases}]{err}")
    if path:
        with open(path, "w") as f:
            f.write(to_json())
        out(f"  (full JSON written to {path})")
