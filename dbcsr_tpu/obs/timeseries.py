"""Telemetry history plane: a sampled, windowed time-series store.

Every other obs surface is instantaneous — `metrics.snapshot()` is a
point read, the health detectors hold rolling windows only in memory,
``/metrics`` shows one scrape of one process.  Nothing answered "how
has this (driver, shape, dtype) cell / serve tenant / breaker behaved
*over time*" — the exact substrate the background autotuner (ROADMAP
item 1) and multi-worker serving (item 3) need, and what the SLO plane
(`obs.slo`) computes burn rates over.  This module is that substrate:

* **Sampling** — on a configurable cadence
  (``DBCSR_TPU_TS_INTERVAL_S``, default 10 s; ``0`` samples at every
  product boundary) `sample()` scrapes one point per live series: the
  roofline rollup per (driver, shape-bucket, dtype) cell, serve
  queue/latency/shed rates, breaker states, pool/transfer meters, ABFT
  mismatch rates, per-component health status, and the SLO burn-rate
  gauges `obs.slo` derives from the store itself.  `maybe_sample()` is
  the hot-path hook (`events.end_product`, the serve admission path):
  one module-attribute check when the store is off, one clock read
  when on-cadence.  Health-transition and SLO-burn rising edges call
  `request_sample()`, which FORCES the next boundary's sample — a
  deferred force, so a detector firing under its own lock never
  re-enters the collectors.

* **Multi-resolution retention** — each series holds a raw ring
  (``DBCSR_TPU_TS_RAW_N`` = 512 samples) plus 1-minute and 10-minute
  downsample tiers (``DBCSR_TPU_TS_1M_N`` = 360 / ``_10M_N`` = 288
  buckets: ~6 h and ~48 h at defaults).  Buckets carry
  last/min/max/sum/count; counter-typed series merge by ``max`` so a
  monotone counter NEVER decreases across a downsample (pinned by
  test).  Downsampling is deterministic in the sample timestamps —
  replaying the same points rebuilds identical tiers.

* **Persistence** — ``DBCSR_TPU_TS=<base path>`` streams every sample
  as one JSONL line to a per-process shard, exactly the trace/events
  contract (`obs.shard`: hostname+pid provisional name, append-merge
  rebind at `init_multihost`); ``DBCSR_TPU_TS=0`` disables the store
  entirely.  Unset keeps the in-memory rings on with no disk I/O.

* **Query** — `query(metric, labels=..., since=..., agg=...)` reads
  the live rings or a committed shard family (``path=``)
  interchangeably: shard replay rebuilds the same ring/tier structures
  from the persisted raw points, so live and replayed answers agree
  (pinned by test).  ``tier`` selects raw/60/600 explicitly or
  ``"auto"`` picks the finest tier that still covers ``since``.

Served live via ``/timeseries`` (+ fleet-merged via ``/cluster`` and
`tools/fleet.py`); read offline by `tools/doctor.py --trend`.

Stdlib at module level (`obs.shard` only); every engine layer is
reached lazily inside collectors.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time

from dbcsr_tpu.obs import shard as _shard
from dbcsr_tpu.utils import lockcheck as _lockcheck

GAUGE = "gauge"
COUNTER = "counter"

# downsample tier widths, seconds (raw -> 1-min -> 10-min)
TIERS = (60.0, 600.0)

_lock = _lockcheck.wrap("obs.timeseries", threading.Lock())


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# "0"/"off" disables the store entirely; a path enables the JSONL
# shard sink; unset/other keeps the in-memory rings on (mirrors
# DBCSR_TPU_EVENTS)
_env = os.environ.get("DBCSR_TPU_TS", "")
_enabled = _env not in ("0", "off")


# parsed-interval cache keyed by the raw env string: maybe_sample runs
# at every product boundary with the store on by default, so the float
# parse must not repeat per multiply (env re-reads stay, so tests that
# monkeypatch the knob see it immediately)
_iv_cache: list = [None, 10.0]


def _interval_s() -> float:
    raw = os.environ.get("DBCSR_TPU_TS_INTERVAL_S")
    if raw != _iv_cache[0]:
        _iv_cache[0] = raw
        try:
            _iv_cache[1] = max(0.0, float(raw)) if raw is not None \
                else 10.0
        except ValueError:
            _iv_cache[1] = 10.0
    return _iv_cache[1]


def _raw_n() -> int:
    return max(8, _env_int("DBCSR_TPU_TS_RAW_N", 512))


def _tier_n(width: float) -> int:
    if width == 60.0:
        return max(8, _env_int("DBCSR_TPU_TS_1M_N", 360))
    return max(8, _env_int("DBCSR_TPU_TS_10M_N", 288))


class _Series:
    """One (metric, labels) series: raw ring + per-tier bucket rings."""

    __slots__ = ("metric", "labels", "kind", "raw", "tiers")

    def __init__(self, metric: str, labels: dict, kind: str):
        self.metric = metric
        self.labels = dict(labels)
        self.kind = kind
        self.raw: collections.deque = collections.deque(maxlen=_raw_n())
        self.tiers = {w: collections.deque(maxlen=_tier_n(w))
                      for w in TIERS}

    def add(self, t: float, v: float) -> None:
        self.raw.append((t, v))
        for width, dq in self.tiers.items():
            b0 = math.floor(t / width) * width
            if dq and dq[-1]["t"] == b0:
                b = dq[-1]
                # counters merge by max: a monotone input can never
                # produce a decreasing downsample, even if a scrape
                # lands out of order inside the bucket
                b["last"] = (max(b["last"], v) if self.kind == COUNTER
                             else v)
                b["min"] = min(b["min"], v)
                b["max"] = max(b["max"], v)
                b["sum"] += v
                b["count"] += 1
            elif dq and dq[-1]["t"] > b0:
                pass  # sample older than the open bucket: raw keeps it
            else:
                dq.append({"t": b0, "last": v, "min": v, "max": v,
                           "sum": v, "count": 1})


def _series_key(metric: str, labels: dict) -> tuple:
    return (metric, tuple(sorted(labels.items())))


def _sanitize(points) -> list:
    """Well-formed ``[metric, labels, float value, kind]`` rows only —
    a registered collector returning one malformed point must never
    abort the sample (or poison the persisted record)."""
    out = []
    for pt in points:
        try:
            metric, labels, value, kind = pt
            # dict() also validates: non-dict labels (None, an int, a
            # string of pairs) must fail HERE, not later in
            # _series_key's labels.items()
            out.append((str(metric), dict(labels or {}), float(value),
                        str(kind)))
        except (TypeError, ValueError):
            continue
    return out


class _Store:
    """Series registry — one lives at module level, `query(path=...)`
    rebuilds throwaway ones from shard replays."""

    def __init__(self):
        self.series: dict = {}
        self.seq = 0

    def ingest(self, t: float, points) -> None:
        for pt in points:
            try:
                metric, labels, value, kind = pt
                labels = dict(labels or {})
                v = float(value)
            except (TypeError, ValueError):
                continue  # ONE malformed point (a broken registered
                #           collector, a corrupt shard row) must not
                #           drop the whole sample / replay
            key = _series_key(metric, labels)
            s = self.series.get(key)
            if s is None:
                s = self.series[key] = _Series(metric, labels, kind)
            s.add(float(t), v)

    def match(self, metric: str | None, labels: dict | None) -> list:
        out = []
        for s in self.series.values():
            if metric is not None and s.metric != metric:
                continue
            if labels and any(s.labels.get(k) != str(v) and
                              s.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            out.append(s)
        return out


_store = _Store()

# cadence + deferred-force state; the generation counter lets sample()
# consume exactly the requests pending when it started (string identity
# would drop a mid-sample request whose interned reason matched)
_last_sample_t = 0.0
_pending_force: str | None = None
_force_gen = 0
_sampling = False

# JSONL shard sink (the trace/events contract — obs.shard)
_sink = None
_sink_base: str | None = None
_sink_path: str | None = None
_sink_pid_final = False

# extra collectors registered by tests / embedding apps
_extra_collectors: list = []


# ------------------------------------------------------------ switches

def enabled() -> bool:
    """True when the store samples; False = every hook is a single
    attribute check (``DBCSR_TPU_TS=0``)."""
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def reset() -> None:
    """Drop every series, the cadence state and registered extra
    collectors (tests; paired with `metrics.reset`).  The sink stays
    open — its shard is an append log."""
    global _store, _last_sample_t, _pending_force
    with _lock:
        _store = _Store()
        _last_sample_t = 0.0
        _pending_force = None
        del _extra_collectors[:]


def register_collector(fn) -> None:
    """Add a callable returning an iterable of
    ``(metric, labels_dict, value, kind)`` points, scraped on every
    sample (embedding apps; cleared by `reset`)."""
    _extra_collectors.append(fn)


# ---------------------------------------------------------- collectors

def _collect_engine() -> list:
    """Roofline rollup per driver + per-(driver, shape-bucket, dtype)
    flop cells — the autotuner's evidence substrate."""
    pts: list = []
    try:
        from dbcsr_tpu.core import stats
        from dbcsr_tpu.obs import costmodel
    except Exception:
        return pts
    kind = costmodel.device_kind()
    # the stats registries are mutated lock-free by concurrent
    # multiplies (the serving plane's worker thread): snapshot every
    # dict with C-level list()/dict() calls before iterating — a
    # bytecode-level iteration racing record_stack's key insert raises
    # "changed size during iteration" and drops the whole collector
    for driver, agg in list(stats._driver_agg.items()):
        by_dtype = dict(agg.by_dtype)
        seconds = agg.seconds
        if seconds > 0 and agg.flops > 0:
            dtype = max(by_dtype, key=by_dtype.get) \
                if by_dtype else "float64"
            rl = costmodel.roofline(agg.flops, agg.nbytes, seconds,
                                    kind=kind, dtype=dtype)
            pts.append(("dbcsr_tpu_roofline_fraction", {"driver": driver},
                        rl["roofline_fraction"], GAUGE))
            pts.append(("dbcsr_tpu_achieved_gflops", {"driver": driver},
                        rl["achieved_gflops"], GAUGE))
        pts.append(("dbcsr_tpu_dispatch_seconds_total", {"driver": driver},
                    seconds, COUNTER))
        for dtype, fl in by_dtype.items():
            pts.append(("dbcsr_tpu_flops_total",
                        {"driver": driver, "dtype": dtype}, fl, COUNTER))
    for (m, n, k), st in list(stats._by_mnk.items()):
        mnk = f"{m}x{n}x{k}"
        for (driver, dtype), fl in dict(st.by_driver_dtype).items():
            pts.append(("dbcsr_tpu_cell_flops_total",
                        {"mnk": mnk, "driver": driver, "dtype": dtype},
                        fl, COUNTER))
    pts.append(("dbcsr_tpu_multiplies_total", {},
                stats._totals["multiplies"], COUNTER))
    return pts


def _collect_serve() -> list:
    """Serve queue/latency/shed rates (no-op until the serving plane
    ran — the engine is never CREATED by a scrape)."""
    import sys

    pts: list = []
    from dbcsr_tpu.obs import metrics
    for name in ("dbcsr_tpu_serve_requests_total",
                 "dbcsr_tpu_serve_shed_total",
                 "dbcsr_tpu_serve_deadline_missed_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    eng_mod = sys.modules.get("dbcsr_tpu.serve.engine")
    eng = eng_mod.current_engine() if eng_mod is not None else None
    if eng is not None:
        pts.append(("dbcsr_tpu_serve_queue_depth", {},
                    eng.queue.depth(), GAUGE))
        for tenant, q in eng.latency_quantiles().items():
            pts.append(("dbcsr_tpu_serve_latency_p50_ms",
                        {"tenant": tenant}, q["p50_ms"], GAUGE))
            pts.append(("dbcsr_tpu_serve_latency_p95_ms",
                        {"tenant": tenant}, q["p95_ms"], GAUGE))
    return pts


def _collect_breakers() -> list:
    import sys

    pts = []
    # fallback/failure counters ride this collector so the change-point
    # detector's fallback_rate series replays from the shard alone
    from dbcsr_tpu.obs import metrics

    for name in ("dbcsr_tpu_driver_fallback_total",
                 "dbcsr_tpu_driver_failures_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    br = sys.modules.get("dbcsr_tpu.resilience.breaker")
    board = getattr(br, "_board", None) if br is not None else None
    if board is None:
        return pts  # never CREATE a board just to sample it
    code = {"closed": 0, "half_open": 1, "open": 2}
    for key, ent in board.snapshot().items():
        driver, _, shape = key.partition("|")
        pts.append(("dbcsr_tpu_breaker_state",
                    {"driver": driver, "shape": shape},
                    code.get(ent["state"], 0), GAUGE))
    return pts


def _collect_pool() -> list:
    pts: list = []
    try:
        from dbcsr_tpu.core import mempool

        p = mempool.pool_stats()
    except Exception:
        return pts  # jax-free contexts
    for k in ("hits", "misses", "returns", "evictions",
              "h2d_bytes", "d2h_bytes"):
        pts.append((f"dbcsr_tpu_pool_{k}_total" if "bytes" not in k
                    else f"dbcsr_tpu_{k}_total", {}, p[k], COUNTER))
    pts.append(("dbcsr_tpu_pool_bytes_held", {}, p["bytes_held"], GAUGE))
    return pts


def _collect_integrity() -> list:
    from dbcsr_tpu.obs import metrics

    pts: list = []
    for name in ("dbcsr_tpu_abft_checks_total",
                 "dbcsr_tpu_abft_mismatches_total",
                 "dbcsr_tpu_abft_recoveries_total",
                 "dbcsr_tpu_chain_rollback_total",
                 "dbcsr_tpu_anomalies_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    return pts


def _collect_health() -> list:
    """Per-component health status as a 0/1/2 gauge series — the
    doctor's ``--trend`` table of how the verdict moved."""
    try:
        from dbcsr_tpu.obs import health
    except Exception:
        return []
    code = {health.OK: 0, health.DEGRADED: 1, health.CRITICAL: 2}
    try:
        v = health.verdict()
    except Exception:
        return []
    pts = [("dbcsr_tpu_health_status", {"component": "overall"},
            code.get(v["status"], 0), GAUGE)]
    for name, comp in v["components"].items():
        pts.append(("dbcsr_tpu_health_status", {"component": name},
                    code.get(comp["status"], 0), GAUGE))
    return pts


def _collect_precision() -> list:
    """Executed-precision plane (acc.precision): per-(m,n,k,dtype)
    adaptive cell state (1 = running demoted, 0 = promoted back to
    native), the cell's last probe residual (demotion headroom), and
    the demoted-launch / promotion counters — `doctor --trend` renders
    these next to the `dbcsr_tpu_cell_flops_total` cells, whose dtype
    label records the EXECUTED compute dtype."""
    import sys

    pts: list = []
    from dbcsr_tpu.obs import metrics

    for name in ("dbcsr_tpu_precision_launches_total",
                 "dbcsr_tpu_precision_promotions_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    prec = sys.modules.get("dbcsr_tpu.acc.precision")
    if prec is None:
        return pts  # planner never imported: nothing ever demoted
    for (m, n, k, dt), info in prec.cells_snapshot().items():
        labels = {"mnk": f"{m}x{n}x{k}", "dtype": dt}
        pts.append(("dbcsr_tpu_precision_cell_demoted", labels,
                    0.0 if info["state"] == "promoted" else 1.0, GAUGE))
        pts.append(("dbcsr_tpu_precision_cell_rel_err", labels,
                    info["last_rel_err"], GAUGE))
    return pts


def _collect_value_reuse() -> list:
    """Value-reuse plane: incremental-multiply outcomes/savings and the
    serve-layer content-addressed product cache (hit rates, pinned
    bytes per tenant) — `doctor --trend` renders these alongside the
    plan-cache and pool series they extend."""
    import sys

    pts: list = []
    from dbcsr_tpu.obs import metrics

    for name in ("dbcsr_tpu_incremental_total",
                 "dbcsr_tpu_incremental_saved_flops_total",
                 "dbcsr_tpu_incremental_saved_bytes_total",
                 "dbcsr_tpu_incremental_degrade_total",
                 "dbcsr_tpu_plan_cache_total",
                 "dbcsr_tpu_product_cache_total",
                 "dbcsr_tpu_product_cache_saved_flops_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    pcm = sys.modules.get("dbcsr_tpu.serve.product_cache")
    if pcm is not None:  # never instantiated by a scrape
        snap = pcm.snapshot()
        pts.append(("dbcsr_tpu_product_cache_bytes", {},
                    snap["bytes"], GAUGE))
        for t, v in snap["bytes_by_tenant"].items():
            pts.append(("dbcsr_tpu_product_cache_bytes", {"tenant": t},
                        v, GAUGE))
    return pts


def _collect_tune() -> list:
    """Online-autotuner plane (dbcsr_tpu.tune): trial/promotion/
    demotion counters, the mined-queue depth and cycle duration, and
    the params-table generation (a counter: every promotion/demotion
    bumps it, so `doctor --trend` can line parameter changes up
    against the roofline cells they were meant to move)."""
    import sys

    pts: list = []
    from dbcsr_tpu.obs import metrics

    for name in ("dbcsr_tpu_tune_trials_total",
                 "dbcsr_tpu_tune_promotions_total",
                 "dbcsr_tpu_tune_demotions_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    svc_mod = sys.modules.get("dbcsr_tpu.tune.service")
    svc = svc_mod.current_service() if svc_mod is not None else None
    if svc is not None:  # never CREATE a service just to sample it
        snap = svc.snapshot()
        pts.append(("dbcsr_tpu_tune_queue_depth", {},
                    snap["queue_depth"], GAUGE))
        pts.append(("dbcsr_tpu_tune_cycle_seconds", {},
                    snap["last_cycle_s"], GAUGE))
    pm = sys.modules.get("dbcsr_tpu.acc.params")
    if pm is not None:
        try:
            pts.append(("dbcsr_tpu_params_generation", {},
                        pm.generation(), COUNTER))
        except Exception:
            pass
    return pts


def _collect_format() -> list:
    """Storage-format planner plane (mm.format_planner): the
    decision counter by (format, reason), the fleet-sync counter, and
    per-format planner REGRET (latest measured/predicted GFLOP/s
    ratio) — the series `tune.miner.mine_format` and `doctor --trend`
    line mis-crossovers up against."""
    import sys

    pts: list = []
    from dbcsr_tpu.obs import metrics

    for name in ("dbcsr_tpu_format_decision_total",
                 "dbcsr_tpu_tune_fleet_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    fp = sys.modules.get("dbcsr_tpu.mm.format_planner")
    if fp is not None:  # an un-imported planner has no regrets
        try:
            # regret_gauges() yields (labels_dict, ratio) rows
            for labels, ratio in fp.regret_gauges():
                pts.append(("dbcsr_tpu_format_regret", dict(labels),
                            ratio, GAUGE))
        except Exception:
            pass
    return pts


def _collect_attribution() -> list:
    """Tenant cost-attribution plane (obs.attribution): the per-tenant
    device-seconds/flops/bytes/saved meters — sampled into shards so
    tenant usage history replays offline (`doctor --trend`,
    `tools/usage_report.py` in artifact mode)."""
    pts: list = []
    from dbcsr_tpu.obs import metrics

    for name in ("dbcsr_tpu_tenant_device_seconds_total",
                 "dbcsr_tpu_tenant_flops_total",
                 "dbcsr_tpu_tenant_bytes_moved_total",
                 "dbcsr_tpu_tenant_saved_flops_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    return pts


def _collect_workload() -> list:
    """Workload observability plane (serve.workload + tools/loadtest):
    trace records captured by the serve recorder, replayed-request
    meters, and whether the recorder sink is live — so a capacity
    certification run leaves its own telemetry trail."""
    import sys

    pts: list = []
    from dbcsr_tpu.obs import metrics

    for name in ("dbcsr_tpu_workload_records_total",
                 "dbcsr_tpu_replay_requests_total"):
        for labels, v in metrics.counter_items(name):
            pts.append((name, labels, v, COUNTER))
    wl = sys.modules.get("dbcsr_tpu.serve.workload")
    if wl is not None:  # never import the recorder just to sample it
        pts.append(("dbcsr_tpu_workload_sink_active", {},
                    1.0 if wl.sink_active() else 0.0, GAUGE))
    return pts


def _collect_profiler() -> list:
    """Continuous-profile plane (obs.profiler): the monotonic
    multiply-wall counter pair the latency change-point series derives
    from (dispatch_seconds only moves when a plan is BUILT, so cached
    steady-state multiplies would otherwise read as zero latency) plus
    the sealed-epoch cursor."""
    import sys

    pts: list = []
    prof = sys.modules.get("dbcsr_tpu.obs.profiler")
    if prof is None:  # never import the profiler just to sample it
        return pts
    tot = prof.totals()
    pts.append(("dbcsr_tpu_multiply_seconds_total", {},
                tot["ms"] / 1e3, COUNTER))
    pts.append(("dbcsr_tpu_profiled_multiplies_total", {},
                float(tot["n"]), COUNTER))
    return pts


_COLLECTORS = (_collect_engine, _collect_serve, _collect_breakers,
               _collect_pool, _collect_integrity, _collect_precision,
               _collect_value_reuse, _collect_tune, _collect_health,
               _collect_format, _collect_attribution, _collect_workload,
               _collect_profiler)


# ------------------------------------------------------------ sampling

def request_sample(reason: str = "forced") -> None:
    """Force the NEXT `maybe_sample` boundary to sample regardless of
    cadence (health-transition / SLO-burn rising edges call this —
    deferred, so a detector firing under its own lock never re-enters
    the collectors)."""
    global _pending_force, _force_gen
    if not _enabled:
        return
    with _lock:
        # under the lock: sample()'s generation-compare must never
        # observe the new reason with the old generation (it would
        # clear a request raised mid-sample)
        _pending_force = reason
        _force_gen += 1


def maybe_sample(now: float | None = None) -> dict | None:
    """The hot-path hook: sample when the cadence elapsed or a forced
    sample is pending.  One attribute check when the store is off."""
    if not _enabled:
        return None
    now = time.time() if now is None else now
    reason = _pending_force
    if reason is None:
        iv = _interval_s()
        if _last_sample_t and now - _last_sample_t < iv:
            return None
        reason = "interval"
    return sample(now=now, reason=reason)


def on_product() -> None:
    """Product-boundary hook (`events.end_product`)."""
    if not _enabled:
        return
    try:
        maybe_sample()
    except Exception:
        pass  # telemetry must never fail a multiply


def sample(now: float | None = None, reason: str = "manual") -> dict | None:
    """Take one full sample: scrape every collector, fold in the SLO
    burn gauges `obs.slo` derives from the store, ingest into the
    rings, and append ONE JSONL line to the shard sink (when on).
    Returns the persisted record (or None when the store is off /
    re-entered)."""
    global _last_sample_t, _pending_force, _sampling
    if not _enabled:
        return None
    now = time.time() if now is None else now
    # check-and-set the re-entrancy guard UNDER the lock: a serve
    # admission thread and a multiply's product boundary racing the
    # unlocked flag would both scrape and write duplicate samples
    with _lock:
        if _sampling:
            return None
        _sampling = True
        # consume only the force requests pending NOW: one raised
        # while this sample runs (slo._edge's own burn transition, a
        # detector on another thread) must survive to the NEXT boundary
        gen_at_start = _force_gen
    try:
        pts: list = []
        for fn in _COLLECTORS + tuple(_extra_collectors):
            try:
                pts.extend(fn())
            except Exception:
                pass  # one broken collector must not drop the sample
        pts = _sanitize(pts)
        ingest_points(now, pts, persist=False)
        # SLO burn rates are computed OVER the store (including the
        # points just ingested) and ride the same sample
        burn_pts: list = []
        try:
            from dbcsr_tpu.obs import slo as _slo

            burn_pts = _sanitize(_slo.collect(now=now))
            ingest_points(now, burn_pts, persist=False)
        except Exception:
            burn_pts = []
        with _lock:
            _store.seq += 1
            rec = {"seq": _store.seq, "t": now, "reason": reason,
                   "points": [[m, lb, v, k]
                              for m, lb, v, k in pts + burn_pts]}
            _last_sample_t = now
            if _force_gen == gen_at_start:
                _pending_force = None
            if _sink is not None:
                try:
                    _sink.write(json.dumps(rec, default=str) + "\n")
                    _sink.flush()
                except Exception:
                    pass  # a full disk must not fail the multiply
    finally:
        # clear the guard UNDER the lock like the check-and-set above:
        # an unlocked store is unordered against a concurrent CAS
        with _lock:
            _sampling = False
    # the incident-capture boundary: an armed anomaly/SLO-burn trigger
    # (obs.incidents) assembles its bundle HERE — outside the store
    # lock and the sampling guard, carrying the very sample the rising
    # edge forced
    try:
        import sys as _sys

        _inc = _sys.modules.get("dbcsr_tpu.obs.incidents")
        if _inc is not None:
            _inc.on_sample(rec)
    except Exception:
        pass  # capture must never fail the boundary that hosts it
    # the causal-diagnosis boundary (same contract): the RCA knob poll
    # runs BEFORE the change-point scan so a mid-run knob flip is on
    # the change ledger when a shift it caused fires on this sample
    try:
        import sys as _sys

        _rca = _sys.modules.get("dbcsr_tpu.obs.rca")
        if _rca is not None:
            _rca.on_sample(rec)
        _cpm = _sys.modules.get("dbcsr_tpu.obs.changepoint")
        if _cpm is not None:
            _cpm.on_sample(rec)
    except Exception:
        pass  # diagnosis must never fail the boundary that hosts it
    return rec


def ingest_points(t: float, points, persist: bool = True,
                  reason: str = "ingest") -> None:
    """Feed points straight into the rings (tests, `obs.slo`, replay).
    With ``persist`` (and an active sink) the points are also appended
    as one JSONL sample line.  Malformed points are dropped."""
    points = _sanitize(points)
    with _lock:
        _store.ingest(t, points)
        if persist and _sink is not None:
            _store.seq += 1
            rec = {"seq": _store.seq, "t": t, "reason": reason,
                   "points": [[m, lb, v, k] for m, lb, v, k in points]}
            try:
                _sink.write(json.dumps(rec, default=str) + "\n")
                _sink.flush()
            except Exception:
                pass


# --------------------------------------------------------------- query

def _read_shards(base: str) -> list:
    """All sample records of a shard family (or a concrete file),
    oldest first by (t, seq).  Family expansion is the shared
    `obs.shard.expand_family` contract."""
    recs = []
    for path in _shard.expand_family(base):
        try:
            with open(path) as fh:
                for line in fh:
                    try:
                        recs.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail line
        except OSError:
            continue
    recs.sort(key=lambda r: (r.get("t", 0), r.get("seq", 0)))
    return recs


def _replay_store(base: str) -> _Store:
    """Rebuild a store from persisted shards — the SAME ring/tier
    structures the live store holds, so queries agree."""
    st = _Store()
    for rec in _read_shards(base):
        t = rec.get("t")
        pts = rec.get("points")
        if t is None or not isinstance(pts, list):
            continue
        st.ingest(t, pts)  # ingest drops malformed rows itself
    return st


def _agg_value(points: list, agg: str):
    if not points:
        return None
    vs = [p[1] for p in points]
    if agg == "last":
        return points[-1][1]
    if agg == "min":
        return min(vs)
    if agg == "max":
        return max(vs)
    if agg in ("mean", "avg"):
        return sum(vs) / len(vs)
    if agg == "sum":
        return sum(vs)
    if agg == "count":
        return float(len(vs))
    if agg == "rate":
        dt = points[-1][0] - points[0][0]
        dv = points[-1][1] - points[0][1]
        return dv / dt if dt > 0 else 0.0
    raise ValueError(f"unknown agg {agg!r}")


def query(metric: str | None = None, labels: dict | None = None,
          since: float | None = None, until: float | None = None,
          agg: str | None = None, tier="auto",
          path: str | None = None) -> list:
    """Query the live rings (default) or a committed shard family
    (``path=``) — interchangeably, by contract.

    Returns one dict per matching series:
    ``{"metric", "labels", "kind", "tier", "points": [[t, v], ...]}``
    (+ ``"value"`` when ``agg`` is given: last/min/max/mean/sum/count/
    rate over the selected points).  ``since``/``until`` are unix
    seconds; a NEGATIVE ``since`` is relative to now.  ``tier`` is
    ``"raw"``, a tier width (60/600), or ``"auto"``: the finest tier
    whose retention still covers ``since``.
    """
    if since is not None and since < 0:
        since = time.time() + since
    # select and COPY the points under the lock: the sampler appends
    # to the same deques from other threads, and iterating a deque
    # mid-append raises RuntimeError (an HTTP /timeseries scrape must
    # never race a multiply's sample)
    if path is not None:
        store = _replay_store(path)
        with _lock:
            selected = [(s, *_select_points(s, since, tier))
                        for s in store.match(metric, labels)]
    else:
        with _lock:
            selected = [(s, *_select_points(s, since, tier))
                        for s in _store.match(metric, labels)]
    out = []
    for s, sel_tier, pts in selected:
        if since is not None:
            pts = [p for p in pts if p[0] >= since]
        if until is not None:
            pts = [p for p in pts if p[0] <= until]
        ent = {"metric": s.metric, "labels": dict(s.labels),
               "kind": s.kind, "tier": sel_tier,
               "points": [[t, v] for t, v in pts]}
        if agg:
            ent["value"] = _agg_value(ent["points"], agg)
        out.append(ent)
    out.sort(key=lambda e: (e["metric"], sorted(e["labels"].items())))
    return out


def _select_points(s: _Series, since: float | None, tier) -> tuple:
    """(tier_name, [(t, v), ...]) — tier buckets surface their
    ``last`` value (max-merged for counters: never decreasing).
    Callers hold the store lock (the deques are copied here)."""
    if tier in ("raw", 0, None) or (tier == "auto" and since is None):
        return "raw", list(s.raw)
    if tier != "auto":
        w = float(tier)
        if w not in s.tiers:
            raise ValueError(f"unknown tier {tier!r} (raw, 60, 600)")
        return str(int(w)), [(b["t"], b["last"]) for b in s.tiers[w]]
    # "auto": the FINEST candidate that covers `since` — complete
    # (never evicted: holds its whole history) or first retained point
    # predating `since` — AND holds at least 2 in-window points; if no
    # candidate qualifies, the one with the MOST in-window points
    # loses the least (a high-rate store whose raw ring spans less
    # than the window still beats one coarse bucket, and a young
    # process's complete-but-short history is never skipped)
    cands = [("raw", list(s.raw), len(s.raw) < (s.raw.maxlen or 0))]
    for w in TIERS:
        dq = s.tiers[w]
        cands.append((str(int(w)), [(b["t"], b["last"]) for b in dq],
                      len(dq) < (dq.maxlen or 0)))
    counts = [sum(1 for t, _ in pts if t >= since)
              for _, pts, _ in cands]
    for (name, pts, complete), n_in in zip(cands, counts):
        covers = complete or (pts and pts[0][0] <= since)
        if covers and n_in >= 2:
            return name, pts
    best = max(range(len(cands)), key=lambda i: counts[i])
    return cands[best][0], cands[best][1]


def series_list(path: str | None = None) -> list:
    """[{"metric", "labels", "kind", "n_raw"}] of every known series."""
    if path is not None:
        store = _replay_store(path)
        with _lock:
            sers = list(store.series.values())
    else:
        with _lock:
            sers = list(_store.series.values())
    return sorted(
        ({"metric": s.metric, "labels": dict(s.labels), "kind": s.kind,
          "n_raw": len(s.raw)} for s in sers),
        key=lambda e: (e["metric"], sorted(e["labels"].items())))


# ----------------------------------------------------------- persistence

def persist_active() -> bool:
    return _sink is not None


def persist_path() -> str | None:
    """The shard file the sink is currently writing (None when off)."""
    return _sink_path


def enable_persist(base_path: str | None = None) -> str:
    """Open the JSONL shard sink (default base: $DBCSR_TPU_TS) — the
    trace/events sharding contract via `obs.shard`.  Implies
    `set_enabled(True)`."""
    global _sink, _sink_base, _sink_path, _sink_pid_final
    base_path = base_path or os.environ.get("DBCSR_TPU_TS")
    if not base_path or base_path in ("0", "off", "1"):
        raise ValueError("no timeseries sink path: pass one or set "
                         "DBCSR_TPU_TS")
    disable_persist()
    set_enabled(True)
    pid = _shard.process_index()
    with _lock:
        _sink_base = base_path
        _sink_pid_final = pid is not None
        tag = pid if pid is not None else _shard.provisional_tag()
        _sink_path = _shard.shard_path(base_path, tag)
        _sink = open(_sink_path, "a")
    return _sink_path


def disable_persist() -> None:
    """Close the sink, settling a provisional shard name on index 0."""
    global _sink
    rebind(force=True)
    with _lock:
        if _sink is not None:
            try:
                _sink.close()
            except Exception:
                pass
            _sink = None


def rebind(process_index: int | None = None, force: bool = False) -> None:
    """Settle a provisionally-named shard onto its final ``p{index}``
    name (the `tracer.rebind` contract: called by `init_multihost`,
    ``force`` settles on 0 at close).  Appends onto an existing final
    shard instead of clobbering it (`obs.shard.settle`)."""
    global _sink, _sink_path, _sink_pid_final
    with _lock:
        if _sink is None or _sink_pid_final:
            return
        if process_index is None:
            process_index = _shard.process_index()
        if process_index is None:
            if not force:
                return
            process_index = 0
        _sink_pid_final = True
        _sink_path, _sink = _shard.settle(
            _sink_base, _sink_path, _sink, int(process_index))


import atexit


@atexit.register
def _atexit_close() -> None:  # pragma: no cover - process teardown
    try:
        disable_persist()
    except Exception:
        pass


# env activation: DBCSR_TPU_TS=<path> at import streams samples to
# disk with no code changes anywhere (mirrors DBCSR_TPU_EVENTS)
if _enabled and _env and _env != "1":
    try:
        enable_persist(_env)
    except (ValueError, OSError):
        pass
