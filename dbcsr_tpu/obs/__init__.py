"""dbcsr_tpu.obs — structured tracing, metrics and the flight recorder.

The observability subsystem the reference spreads across
`dbcsr_timings_report.F` (MPI-aggregated timer reports + cachegrind
export), the STATISTICS block (`dbcsr_mm_sched.F:390-546`) and the
NVTX/cachegrind hooks — rebuilt machine-readable:

* `tracer` — span tracer recording every `core.timings.timed()` region
  with structured attributes; JSONL streamed while running, Chrome
  ``trace_event`` JSON (Perfetto-loadable) on flush.  Enable with
  ``DBCSR_TPU_TRACE=<path>`` or `enable_trace(path)`.
* `metrics` — counter/gauge/histogram registry layered over
  `core.stats`: `metrics.snapshot()` → dict,
  `metrics.prometheus_text()` → Prometheus exposition; includes
  per-jitted-hot-function recompile/cache-hit counters.
* `flight` — bounded ring of the last N multiplies (shapes, driver
  decisions + why, per-phase ms, memory high-water), dumped on error
  by `perf/driver.py` / `bench.py` or on demand via `flight.dump()`.
* `events` — the unified structured-event bus (PR 5): one bounded
  ring + optional sharded JSONL sink, every resilience/perf emission
  published through it with a per-multiply ``product_id`` correlation
  key shared with the flight record and the multiply span.
* `timeseries` — the telemetry history plane: cadence-sampled,
  multi-resolution (raw/1-min/10-min) time series of every live
  signal, persisted as per-process JSONL rollup shards
  (``DBCSR_TPU_TS=<base>``) with a live-or-replay `query` API.
* `slo` — declarative objectives evaluated as multi-window
  error-budget burn rates over the store; feeds the ``slo`` health
  component, ``slo_burn`` events and
  ``dbcsr_tpu_slo_burn_rate{objective}``.
* `health` — per-component OK/DEGRADED/CRITICAL verdicts folded from
  breaker states, watchdog streaks, failure rates and roofline
  fractions, plus rolling-window anomaly detectors.
* `server` — opt-in stdlib HTTP introspection endpoint
  (``DBCSR_TPU_OBS_PORT``): ``/metrics``, ``/healthz``, ``/flight``,
  ``/events?product_id=…``; `tools/doctor.py` is the CLI reader.
* `profiler` / `changepoint` / `rca` — the causal diagnosis plane:
  continuous per-(driver, cell, phase) profile baselines, CUSUM
  level-shift detection over the telemetry store, and the change
  ledger + causal ranker that names which system change regressed a
  series (``/rca``, ``/profile/diff``, ``doctor --diagnose``).

Existing call sites need no churn: `core.timings.timed()` and
`core.stats.record_*` feed the tracer automatically, and the multiply
engine feeds the flight recorder.  With tracing disabled the only
hot-path cost is one attribute check per event site.
"""

from dbcsr_tpu.obs import shard
from dbcsr_tpu.obs import windows
from dbcsr_tpu.obs import tracer
from dbcsr_tpu.obs import flight
from dbcsr_tpu.obs import events
from dbcsr_tpu.obs import costmodel
from dbcsr_tpu.obs import metrics
from dbcsr_tpu.obs import timeseries
from dbcsr_tpu.obs import slo
from dbcsr_tpu.obs import health
from dbcsr_tpu.obs import profiler
from dbcsr_tpu.obs import changepoint
from dbcsr_tpu.obs import rca
from dbcsr_tpu.obs import server

from dbcsr_tpu.obs.tracer import (  # noqa: F401
    add as trace_add,
    annotate,
    instant,
    shard_path,
    write_chrome_trace,
)

# version stamp for machine-readable obs artifacts (bench capture JSON,
# trace shards, perf-gate reports): bump when the schema of any of
# them changes incompatibly.  v7 = the causal diagnosis plane
# (change-point events, ranked RCA reports + the `doctor --diagnose
# --json` report shape, profile-baseline epochs, the /rca +
# /profile/diff routes, RCA_CERT.json — this PR); v6 = workload trace
# capture + capacity
# certification (workload_request shards, WORKLOAD_TRACE.jsonl,
# CAPACITY_CERT.json); v5 = tenant cost attribution (tenant
# usage meters, the /usage route, incident bundles, the usage rollup
# artifact); v4 = telemetry time-series shards + SLO burn
# gauges + the `slo` health component; v3 = event bus JSONL +
# product_id correlation + health verdicts (PR 5); v2 = trace sharding
# + roofline/costmodel fields (PR 2); v1 = the original obs subsystem
# (PR 1).
OBS_SCHEMA_VERSION = 7


def enable_trace(path: str | None = None) -> "tracer.Tracer":
    """Start a trace session (see `tracer.enable`)."""
    return tracer.enable(path)


def disable_trace() -> None:
    """End the trace session, flushing JSONL + Chrome trace."""
    tracer.disable()


def trace_enabled() -> bool:
    return tracer.active()


def get_tracer() -> "tracer.Tracer | None":
    return tracer.get()


def obs_active() -> bool:
    """Did any OPT-IN/live obs layer capture something this process?
    True when a trace session is (or was) active, the event bus holds
    records or streams to a sink, or the introspection endpoint is
    serving — the gate `core.lib.finalize_lib` uses to decide whether
    the end-of-run report should include the machine-readable
    snapshot + health verdict next to the legacy stats tables."""
    return (tracer.active() or server.running() or events.sink_active()
            or (events.enabled() and bool(events.records(limit=1))))


__all__ = [
    "tracer", "flight", "metrics", "costmodel", "events", "health",
    "server", "timeseries", "slo", "windows", "shard",
    "profiler", "changepoint", "rca",
    "enable_trace", "disable_trace", "trace_enabled", "get_tracer",
    "annotate", "trace_add", "instant", "shard_path",
    "write_chrome_trace", "OBS_SCHEMA_VERSION", "obs_active",
]
