"""Change-point detection over the telemetry store: level shifts, not
thresholds.

The health model's anomaly detectors (`obs/health.py`) answer "is this
sample far outside its rolling window" — a *threshold* question.  What
they cannot answer is "did this series step to a new level, when, and
by how much": a 30% GFLOP/s regression that arrives as a clean step
(a bad tune promotion, a mis-placed format crossover, a knob flip)
sits inside every per-sample threshold yet is exactly the event the
causal diagnosis plane (`obs/rca.py`) exists to attribute.

This module runs a **window-pair CUSUM** detector over a small
registry of *derived* series (`SERIES`, the lint-checked registry —
`tools/lint` fails tier-1 when a series is undocumented), each
computed from the points of every `obs.timeseries` sample:

* a reference window of the first ``DBCSR_TPU_CP_REF_N`` samples
  freezes a baseline (median + MAD scale, the `tools/perf_gate.py`
  noise convention via `obs.windows`),
* each subsequent sample updates two one-sided CUSUM accumulators
  (slack ``K`` = 0.5 sigma); when the accumulator for a direction
  crosses ``DBCSR_TPU_CP_H`` sigmas the series has SHIFTED,
* the fired change-point carries the **estimated shift time** (the
  start of the CUSUM excursion, not the detection time) and the
  **magnitude** (new level − baseline) — the two facts the RCA ranker
  keys on,
* after a shift the detector re-baselines onto the new level: it
  cannot re-fire while the condition persists (the new level IS the
  baseline now) and it re-arms automatically — a later recovery is a
  fresh change-point in the improving direction.

Only shifts in a series' registered *regression* direction are handed
to `obs.rca.on_changepoint`; improvements are recorded (ring +
`dbcsr_tpu_changepoints_total{series}`) but never open an incident.

Wiring: `obs.timeseries.sample()` calls `on_sample(rec)` at its tail —
outside the store lock, on the sampling cadence, so the multiply hot
path never pays more than the sampler already does.  Stdlib-only.
"""

from __future__ import annotations

import collections
import math
import os
import threading

from dbcsr_tpu.obs import windows as _win

_lock = threading.Lock()

# ------------------------------------------------------------ registry
#
# The checked registry of derived change-point series (pure literals:
# `tools/lint` loads this dict by AST and fails when a series here is
# missing from docs/observability.md, or a metric it reads is not a
# documented family).  Forms:
#
# * "gauge" — one detector cell per distinct label set of ``metric``.
# * "ratio" — delta(num) / delta(den) between consecutive samples,
#   summed across label rows (``num_match`` filters numerator rows by
#   label subset); one global detector cell.  Counter-safe: a ratio is
#   only emitted when the denominator moved.

SERIES = {
    "multiply_latency_ms": {
        "form": "ratio",
        "num": "dbcsr_tpu_multiply_seconds_total",
        "num_match": None,
        "den": "dbcsr_tpu_profiled_multiplies_total",
        "scale": 1000.0,
        "regress": "up",
        "doc": "wall ms per multiply from the continuous profile "
               "baseline's monotonic totals (delta seconds over delta "
               "profiled multiplies between samples; both halves "
               "freeze together when profiling is disabled)",
    },
    "achieved_gflops": {
        "form": "gauge",
        "metric": "dbcsr_tpu_achieved_gflops",
        "regress": "down",
        "doc": "per-driver achieved GFLOP/s from the roofline rollup",
    },
    "roofline_fraction": {
        "form": "gauge",
        "metric": "dbcsr_tpu_roofline_fraction",
        "regress": "down",
        "doc": "per-driver achieved fraction of the roofline",
    },
    "fallback_rate": {
        "form": "ratio",
        "num": "dbcsr_tpu_driver_fallback_total",
        "num_match": None,
        "den": "dbcsr_tpu_multiplies_total",
        "scale": 1.0,
        "regress": "up",
        "doc": "driver fallbacks per multiply (chain failovers)",
    },
    "plan_cache_hit_rate": {
        "form": "ratio",
        "num": "dbcsr_tpu_plan_cache_total",
        "num_match": {"result": "hit"},
        "den": "dbcsr_tpu_plan_cache_total",
        "scale": 1.0,
        "regress": "down",
        "doc": "stack-plan cache hit fraction between samples",
    },
    "serve_p95_latency_ms": {
        "form": "gauge",
        "metric": "dbcsr_tpu_serve_latency_p95_ms",
        "regress": "up",
        "doc": "per-tenant serve p95 latency gauge",
    },
}

_CUSUM_K = 0.5          # CUSUM slack, in sigmas
_RING_N = 256           # fired change-points kept for /rca + doctor
# relative sigma floor: a perfectly quiet reference window must not
# make 1e-12 jitter look like an 8-sigma shift
_REL_SIGMA_FLOOR = 0.05
_ABS_SIGMA_FLOOR = 1e-9


def _env_flag() -> bool:
    return os.environ.get("DBCSR_TPU_CHANGEPOINT", "") not in ("0", "off")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


_enabled = _env_flag()


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Tests / embedding apps: flip detection without the env var."""
    global _enabled
    _enabled = bool(on)


def ref_n() -> int:
    """Reference-window length (samples) frozen into the baseline."""
    return max(4, _env_int("DBCSR_TPU_CP_REF_N", 12))


def threshold_h() -> float:
    """CUSUM decision threshold, in baseline sigmas."""
    return max(1.0, _env_float("DBCSR_TPU_CP_H", 8.0))


# ---------------------------------------------------------------- state

class _Cell:
    """Detector state for one (series, labels) cell."""

    __slots__ = ("ref", "mu", "sigma", "pos", "neg", "exc_t",
                 "exc_vals", "n")

    def __init__(self):
        self.ref: list = []      # warmup samples, then frozen
        self.mu = None           # baseline level (None = warming up)
        self.sigma = 0.0
        self.pos = 0.0           # one-sided CUSUM accumulators
        self.neg = 0.0
        self.exc_t = None        # start of the live excursion
        self.exc_vals: collections.deque = collections.deque(maxlen=64)
        self.n = 0


_cells: dict = {}                       # (series, labels_key) -> _Cell
_changepoints: collections.deque = collections.deque(maxlen=_RING_N)
_prev_counters: dict = {}               # ratio state: key -> (num, den)


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _freeze(cell: _Cell) -> None:
    """Freeze the reference window into (mu, sigma) and arm CUSUM."""
    cell.mu = _win.median(cell.ref)
    scale = _win.mad(cell.ref) * 1.4826
    cell.sigma = max(scale, abs(cell.mu) * _REL_SIGMA_FLOOR,
                     _ABS_SIGMA_FLOOR)
    cell.pos = cell.neg = 0.0
    cell.exc_t = None
    cell.exc_vals.clear()


def observe(series: str, labels: dict, t: float, value: float):
    """Feed one derived sample into the (series, labels) detector.

    Returns the fired change-point dict, or None.  Public so tests and
    replay tooling can drive the detector directly; `on_sample` is the
    production entry point."""
    if not _enabled or series not in SERIES:
        return None
    value = float(value)
    if not math.isfinite(value):
        return None
    spec = SERIES[series]
    key = (series, _labels_key(labels))
    with _lock:
        cell = _cells.get(key)
        if cell is None:
            cell = _cells[key] = _Cell()
        cell.n += 1
        if cell.mu is None:
            cell.ref.append(value)
            if len(cell.ref) >= ref_n():
                _freeze(cell)
            return None
        z = (value - cell.mu) / cell.sigma
        was_quiet = cell.pos == 0.0 and cell.neg == 0.0
        cell.pos = max(0.0, cell.pos + z - _CUSUM_K)
        cell.neg = max(0.0, cell.neg - z - _CUSUM_K)
        if cell.pos == 0.0 and cell.neg == 0.0:
            cell.exc_t = None
            cell.exc_vals.clear()
            return None
        if was_quiet:
            cell.exc_t = t          # excursion start = shift estimate
            cell.exc_vals.clear()
        cell.exc_vals.append(value)
        h = threshold_h()
        if cell.pos <= h and cell.neg <= h:
            return None
        direction = "up" if cell.pos > h else "down"
        level = sum(cell.exc_vals) / len(cell.exc_vals)
        cp = {
            "series": series,
            "labels": dict(labels),
            "t": t,
            "t_shift": cell.exc_t if cell.exc_t is not None else t,
            "direction": direction,
            "baseline": cell.mu,
            "level": level,
            "magnitude": level - cell.mu,
            "sigma": cell.sigma,
            "regression": direction == spec["regress"],
            "n": cell.n,
        }
        # re-baseline onto the new level: no re-fire while the shift
        # persists, automatic re-arm for the eventual recovery
        cell.ref = list(cell.exc_vals)[-ref_n():]
        if len(cell.ref) >= min(ref_n(), 4):
            _freeze(cell)
        else:
            cell.mu = None
            cell.pos = cell.neg = 0.0
            cell.exc_t = None
            cell.exc_vals.clear()
        _changepoints.append(cp)
    _emit(cp)
    return cp


def _emit(cp: dict) -> None:
    """Counter + bus event + RCA hand-off, all guarded: detection must
    never fail the sample boundary that hosts it."""
    try:
        from dbcsr_tpu.obs import metrics as _metrics

        _metrics.counter(
            "dbcsr_tpu_changepoints_total",
            "Change-point detections (level shifts) per derived series",
        ).inc(series=cp["series"])
    except Exception:
        pass
    try:
        from dbcsr_tpu.obs import events as _events

        _events.publish("changepoint", {
            "series": cp["series"], "labels": cp["labels"],
            "direction": cp["direction"], "t_shift": cp["t_shift"],
            "magnitude": cp["magnitude"], "baseline": cp["baseline"],
            "level": cp["level"], "regression": cp["regression"],
        })
    except Exception:
        pass
    if cp["regression"]:
        try:
            from dbcsr_tpu.obs import rca as _rca

            _rca.on_changepoint(cp)
        except Exception:
            pass


# ------------------------------------------------------ sample scanning

def _index_points(points) -> dict:
    idx: dict = {}
    for p in points:
        try:
            metric, labels, value, _kind = p
        except (TypeError, ValueError):
            continue
        idx.setdefault(metric, []).append((labels or {}, value))
    return idx


def _match(labels: dict, want) -> bool:
    if not want:
        return True
    return all(str(labels.get(k)) == str(v) for k, v in want.items())


def on_sample(rec: dict) -> None:
    """Scan one `obs.timeseries` sample record: derive every registered
    series and feed the detectors.  Called at the sampler's tail,
    outside the store lock."""
    if not _enabled or not rec:
        return
    t = rec.get("t", 0.0)
    idx = _index_points(rec.get("points") or [])
    for name, spec in SERIES.items():
        try:
            if spec["form"] == "gauge":
                for labels, value in idx.get(spec["metric"], []):
                    observe(name, labels, t, value)
                continue
            num = sum(v for lb, v in idx.get(spec["num"], [])
                      if _match(lb, spec.get("num_match")))
            den = sum(v for _lb, v in idx.get(spec["den"], []))
            if not idx.get(spec["den"]):
                continue
            with _lock:
                prev = _prev_counters.get(name)
                _prev_counters[name] = (num, den)
            if prev is None:
                continue
            dden = den - prev[1]
            if dden <= 0:
                continue
            dnum = max(0.0, num - prev[0])
            observe(name, {}, t, dnum / dden * spec.get("scale", 1.0))
        except Exception:
            pass  # one broken series must not drop the others


# --------------------------------------------------------------- reads

def changepoints(limit: int | None = None, series: str | None = None,
                 regressions_only: bool = False) -> list:
    """Fired change-points, oldest first."""
    with _lock:
        out = list(_changepoints)
    if series is not None:
        out = [c for c in out if c["series"] == series]
    if regressions_only:
        out = [c for c in out if c["regression"]]
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


def state() -> dict:
    """Per-cell detector state summary (doctor / tests)."""
    with _lock:
        return {
            f"{s}|{dict(k)}": {
                "n": c.n, "baseline": c.mu, "sigma": c.sigma,
                "cusum_pos": c.pos, "cusum_neg": c.neg,
                "warmed": c.mu is not None,
            }
            for (s, k), c in _cells.items()
        }


def reset() -> None:
    """Drop all detector state and fired change-points (tests)."""
    global _enabled
    with _lock:
        _cells.clear()
        _changepoints.clear()
        _prev_counters.clear()
    _enabled = _env_flag()
