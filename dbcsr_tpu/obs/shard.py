"""The ONE per-process JSONL sharding contract for obs sinks.

Three streaming sinks persist per-process shards under a shared BASE
path — the span tracer (``DBCSR_TPU_TRACE``), the event bus
(``DBCSR_TPU_EVENTS``) and the telemetry time-series store
(``DBCSR_TPU_TS``).  They used to carry three copies of the same
delicate logic; this module is the single implementation they all
call:

* `shard_path(base, index)` — ``t.jsonl`` + 0 -> ``t.p0.jsonl`` (the
  extension stays last so shell globs like ``t.p*.jsonl`` work).
* `provisional_tag()` — the collision-proof ``tmp{host}-{pid}`` tag a
  shard opens under when the process index is not yet knowable
  (env activation runs before any backend exists).  Hostname + OS pid:
  multihost processes on a SHARED filesystem can collide on pid alone.
* `process_index()` — the jax process index IF a backend is already
  initialized, None otherwise; never forces backend init (on a wedged
  tunnel that hangs the bare import, and in multi-process runs it
  races `jax.distributed.initialize`).
* `settle(base, path, fh, index)` — move a provisionally-named shard
  onto its final ``p{index}`` name: closes the stream, APPENDS onto an
  existing final shard instead of clobbering it (a rename must never
  destroy another session's data), renames otherwise, reopens for
  append.  On any OSError (cross-device, locked) the provisional shard
  is kept and reopened — data loss is never an option.

`parallel.multihost.init_multihost` drives the rebind for all three
sinks once the world's index is known.  Stdlib-only by contract: the
tracer imports this at module level.
"""

from __future__ import annotations

import os
import re


def shard_path(base: str, index) -> str:
    """Shard file for a base path: ``t.jsonl`` + 0 -> ``t.p0.jsonl``."""
    root, ext = os.path.splitext(base)
    return f"{root}.p{index}{ext}"


def provisional_tag() -> str:
    """Collision-proof provisional shard tag (``tmp{host}-{pid}``)."""
    import socket

    host = re.sub(r"[^A-Za-z0-9]+", "-", socket.gethostname())[:24] or "host"
    return f"tmp{host}-{os.getpid()}"


def process_index() -> int | None:
    """jax process index when a backend is ALREADY initialized; None
    otherwise (best-effort peek at xla_bridge's backend cache — never
    forces one)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return None  # no backend up yet: do NOT force one
    try:
        return int(jax.process_index())
    except Exception:
        return None


def expand_family(base: str) -> list:
    """The READ side of the contract: resolve a shard base (or a
    concrete file/glob) to its family's files.  A base like
    ``t.jsonl`` expands to ``t.p*.jsonl`` with unsettled ``.ptmp*``
    shards skipped (a run killed before its index resolved); a
    concrete path — even a provisional one — stays itself."""
    import glob

    hits = sorted(glob.glob(base))
    if not hits and not re.search(r"\.p\d+\.", os.path.basename(base)):
        root, ext = os.path.splitext(base)
        hits = [h for h in sorted(glob.glob(f"{root}.p*{ext}"))
                if ".ptmp" not in os.path.basename(h)]
    if not hits and os.path.exists(base):
        hits = [base]
    return hits


def settle(base: str, path: str, fh, index: int) -> tuple:
    """Move shard ``path`` (open stream ``fh``, may be None) onto its
    final ``shard_path(base, index)`` name.

    Returns ``(new_path, new_fh)`` — the final path and a re-opened
    append stream (or ``(path, fh)`` unchanged when the shard already
    sits at its final name).  Appends onto an existing final shard
    instead of replacing it; keeps the provisional shard on OSError.
    """
    new_path = shard_path(base, int(index))
    if new_path == path:
        return path, fh
    if fh is not None:
        fh.close()
        fh = None
    try:
        if os.path.exists(new_path):
            # a shard already lives at the final name (an earlier
            # run's, or another process's): APPEND this session's
            # records instead of clobbering it
            with open(path) as src, open(new_path, "a") as dst:
                dst.write(src.read())
            os.remove(path)
        else:
            os.replace(path, new_path)
    except OSError:  # cross-device/locked: keep the provisional shard
        new_path = path
    return new_path, open(new_path, "a")
