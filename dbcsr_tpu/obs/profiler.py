"""Continuous profile baselines: always-on per-(driver, cell, phase)
timing/occupancy histograms with generation-tagged epoch snapshots.

A device profile answers "where did the time go" for ONE run; what the
causal diagnosis plane needs is "where did the time go *relative to
last week's* (or last generation's) profile".  This module folds every
committed flight record (`obs/flight.py` already carries the per-phase
ms deltas, the driver decisions, the mnk shape and the occupancies —
no new instrumentation on the hot path) into compact histograms keyed
by::

    (primary driver, mnk cell, phase)

where the cell is the power-of-two shape bucket the autotuner's
evidence cells already use.  Every ``DBCSR_TPU_PROFILE_EPOCH_N``
multiplies the accumulating bucket is **sealed** into an epoch
snapshot stamped with its time range and the params-table generation
(`acc.params.generation()` — the join key against tune promotions),
kept in a bounded ring and optionally persisted as one JSONL line per
epoch (``DBCSR_TPU_PROFILE=<base>``, sharded per process like every
other obs sink).

`diff(a, b)` compares two snapshots (or merged snapshot ranges) and
localizes a regression to phases and cell populations: per-key mean-ms
deltas, a per-phase rollup, and the single worst (driver, cell, phase)
— exactly the differential evidence `obs/rca.py` attaches to a ranked
causal report, and what ``GET /profile/diff`` serves.

Fold cost is ~10 dict updates per multiply (measured with the rest of
the diagnosis plane under the <1% `tools/rca_bench.py` perf gate).
Stdlib-only; `obs.flight` calls `observe` from `commit()` guarded.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

_lock = threading.Lock()

_EPOCH_RING_N = 32      # sealed epochs kept in memory
_HIST_BUCKETS = 18      # log2-ms buckets: <1ms .. >64s


def _env_flag() -> bool:
    return os.environ.get("DBCSR_TPU_PROFILE", "") not in ("0", "off")


def _env_base() -> str | None:
    raw = os.environ.get("DBCSR_TPU_PROFILE", "")
    return raw if raw and raw not in ("0", "off", "1") else None


def _read_epoch_n() -> int:
    try:
        return max(1, int(os.environ.get("DBCSR_TPU_PROFILE_EPOCH_N",
                                         "64")))
    except ValueError:
        return 64


_epoch_n = _read_epoch_n()


def epoch_n() -> int:
    # cached: observe() sits on the multiply hot path, an os.environ
    # lookup per multiply would eat the budget (refreshed by reset())
    return _epoch_n


_enabled = _env_flag()


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Tests / embedding apps: flip folding without the env var."""
    global _enabled
    _enabled = bool(on)


# ----------------------------------------------------------- current fold

def _new_current() -> dict:
    return {
        "t0": None, "t1": None, "n": 0,
        # key "driver|cell|phase" -> [count, sum_ms, max_ms, hist...]
        "cells": {},
        # key "driver|cell" -> [n, occ_sum] (occupancy population)
        "occ": {},
    }


_current = _new_current()
_epochs: collections.deque = collections.deque(maxlen=_EPOCH_RING_N)
_epoch_seq = 0
# monotonic since-reset totals across ALL epochs: the telemetry
# store's per-multiply wall-latency source (dispatch_seconds only
# moves when a plan is BUILT — cached steady-state multiplies would
# read as zero latency without this)
_totals = {"n": 0, "ms": 0.0}


def _pow2_cell(mnk) -> str:
    try:
        return "x".join(
            str(1 << max(0, int(d) - 1).bit_length()) for d in mnk)
    except (TypeError, ValueError):
        return "?"


def _hist_idx(ms: float) -> int:
    b = 0
    v = ms
    while v >= 1.0 and b < _HIST_BUCKETS - 1:
        v /= 2.0
        b += 1
    return b


def _primary_driver(rec: dict) -> str:
    drivers = rec.get("drivers") or {}
    if drivers:
        return max(drivers,
                   key=lambda d: drivers[d].get("entries", 0) or 0)
    return str(rec.get("algorithm") or "none")


def observe(rec: dict) -> None:
    """Fold one committed flight record into the current epoch.  Called
    from `obs.flight.commit` (guarded there: profiling must never fail
    a multiply)."""
    global _current
    if not _enabled or not rec:
        return
    phases = rec.get("phases_ms")
    if not phases:
        return
    driver = _primary_driver(rec)
    cell = _pow2_cell(rec.get("mnk") or ())
    now = time.time()
    with _lock:
        cur = _current
        if cur["t0"] is None:
            cur["t0"] = now
        cur["t1"] = now
        cur["n"] += 1
        _totals["n"] += 1
        try:
            _totals["ms"] += float(rec.get("dur_ms") or 0.0)
        except (TypeError, ValueError):
            pass
        for phase, ms in phases.items():
            try:
                ms = float(ms)
            except (TypeError, ValueError):
                continue
            key = f"{driver}|{cell}|{phase}"
            row = cur["cells"].get(key)
            if row is None:
                row = cur["cells"][key] = \
                    [0, 0.0, 0.0] + [0] * _HIST_BUCKETS
            row[0] += 1
            row[1] += ms
            if ms > row[2]:
                row[2] = ms
            row[3 + _hist_idx(ms)] += 1
        occ = rec.get("occ_c")
        if occ is None:
            occ = rec.get("occ_a")
        if occ is not None:
            okey = f"{driver}|{cell}"
            orow = cur["occ"].get(okey)
            if orow is None:
                orow = cur["occ"][okey] = [0, 0.0]
            orow[0] += 1
            orow[1] += float(occ)
        full = cur["n"] >= epoch_n()
    if full:
        seal()


def _generation() -> int:
    try:
        from dbcsr_tpu.acc import params as _params

        return int(_params.generation())
    except Exception:
        return 0


def seal() -> dict | None:
    """Seal the current accumulation into an epoch snapshot: ring it,
    persist it (when a sink base is configured), start a fresh epoch.
    Returns the sealed epoch (None when nothing accumulated)."""
    global _current, _epoch_seq
    with _lock:
        if _current["n"] == 0:
            return None
        _epoch_seq += 1
        epoch = {
            "epoch": _epoch_seq,
            "t0": _current["t0"], "t1": _current["t1"],
            "n": _current["n"],
            "generation": _generation(),
            "cells": _current["cells"],
            "occ": _current["occ"],
        }
        _epochs.append(epoch)
        _current = _new_current()
    _persist(epoch)
    return epoch


def _persist(epoch: dict) -> None:
    base = _env_base()
    if not base:
        return
    try:
        from dbcsr_tpu.obs import shard as _shard

        pid = _shard.process_index()
        path = _shard.shard_path(base, pid if pid is not None else 0)
        with open(path, "a") as fh:
            fh.write(json.dumps(epoch, default=str) + "\n")
    except Exception:
        pass  # a full disk must not fail the multiply


# --------------------------------------------------------------- reads

def totals() -> dict:
    """Monotonic since-reset {n, ms} across all epochs — the telemetry
    collector's multiply-latency counter pair."""
    with _lock:
        return dict(_totals)


def epochs(limit: int | None = None) -> list:
    """Sealed epoch snapshots, oldest first."""
    with _lock:
        out = list(_epochs)
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


def current() -> dict:
    """The live (unsealed) accumulation, as a snapshot-shaped dict."""
    with _lock:
        return {
            "epoch": None,
            "t0": _current["t0"], "t1": _current["t1"],
            "n": _current["n"],
            "generation": _generation(),
            "cells": {k: list(v) for k, v in _current["cells"].items()},
            "occ": {k: list(v) for k, v in _current["occ"].items()},
        }


def merge(snaps: list) -> dict:
    """Merge several snapshots into one (window-pair assembly)."""
    out = {"epoch": None, "t0": None, "t1": None, "n": 0,
           "generation": 0, "cells": {}, "occ": {}}
    for s in snaps:
        if not s or not s.get("n"):
            continue
        out["n"] += s["n"]
        out["generation"] = max(out["generation"],
                                s.get("generation") or 0)
        if s.get("t0") is not None and \
                (out["t0"] is None or s["t0"] < out["t0"]):
            out["t0"] = s["t0"]
        if s.get("t1") is not None and \
                (out["t1"] is None or s["t1"] > out["t1"]):
            out["t1"] = s["t1"]
        for key, row in (s.get("cells") or {}).items():
            dst = out["cells"].get(key)
            if dst is None:
                out["cells"][key] = list(row)
                continue
            dst[0] += row[0]
            dst[1] += row[1]
            dst[2] = max(dst[2], row[2])
            for i in range(3, min(len(dst), len(row))):
                dst[i] += row[i]
        for key, row in (s.get("occ") or {}).items():
            dst = out["occ"].get(key)
            if dst is None:
                out["occ"][key] = list(row)
            else:
                dst[0] += row[0]
                dst[1] += row[1]
    return out


def _resolve(ref):
    """A snapshot argument: a dict, an epoch number, ``"current"``, or
    a negative ring index (-1 = most recent sealed)."""
    if isinstance(ref, dict):
        return ref
    if ref == "current":
        return current()
    with _lock:
        eps = list(_epochs)
    if isinstance(ref, int):
        if ref < 0:
            return eps[ref] if eps and -ref <= len(eps) else None
        for e in eps:
            if e["epoch"] == ref:
                return e
    return None


def diff(baseline_a, baseline_b, top: int = 8) -> dict:
    """Differential profile between two snapshots: per-(driver, cell,
    phase) mean-ms deltas sorted by total impact, a per-phase rollup,
    and the single worst key — the regression LOCALIZED to a phase and
    cell population."""
    a = _resolve(baseline_a)
    b = _resolve(baseline_b)
    if not a or not b or not a.get("n") or not b.get("n"):
        return {"ok": False, "reason": "missing snapshot",
                "a": _meta(a), "b": _meta(b), "phases": [],
                "by_phase": {}, "top": None}
    rows = []
    for key in set(a["cells"]) | set(b["cells"]):
        ra = a["cells"].get(key)
        rb = b["cells"].get(key)
        mean_a = (ra[1] / ra[0]) if ra and ra[0] else 0.0
        mean_b = (rb[1] / rb[0]) if rb and rb[0] else 0.0
        delta = mean_b - mean_a
        driver, cell, phase = (key.split("|") + ["?", "?"])[:3]
        rows.append({
            "driver": driver, "cell": cell, "phase": phase,
            "mean_ms_a": mean_a, "mean_ms_b": mean_b,
            "delta_ms": delta,
            "ratio": (mean_b / mean_a) if mean_a > 0 else None,
            "count_a": ra[0] if ra else 0,
            "count_b": rb[0] if rb else 0,
        })
    rows.sort(key=lambda r: abs(r["delta_ms"]), reverse=True)
    by_phase: dict = {}
    for r in rows:
        by_phase[r["phase"]] = by_phase.get(r["phase"], 0.0) \
            + r["delta_ms"]
    regressed = [r for r in rows if r["delta_ms"] > 0]
    return {
        "ok": True,
        "a": _meta(a), "b": _meta(b),
        "phases": rows[:max(1, int(top))],
        "by_phase": by_phase,
        "top": regressed[0] if regressed else None,
    }


def _meta(snap) -> dict | None:
    if not snap:
        return None
    return {"epoch": snap.get("epoch"), "t0": snap.get("t0"),
            "t1": snap.get("t1"), "n": snap.get("n", 0),
            "generation": snap.get("generation", 0)}


def diff_around(t: float, top: int = 8) -> dict:
    """The window-pair diff for a change-point at time ``t``: epochs
    sealed before the shift vs epochs (plus the live accumulation)
    after it."""
    with _lock:
        eps = list(_epochs)
    before = [e for e in eps if (e.get("t1") or 0) <= t]
    after = [e for e in eps if (e.get("t0") or 0) > t]
    cur = current()
    if cur["n"]:
        after.append(cur)
    if not before and eps:
        # the shift estimate can precede the first seal; fall back to
        # oldest-vs-newest so the diff still localizes the phase
        before = eps[:max(1, len(eps) // 2)]
        after = [e for e in eps[len(before):]] + \
            ([cur] if cur["n"] else [])
    return diff(merge(before), merge(after), top=top)


def reset() -> None:
    """Drop all accumulation and sealed epochs (tests)."""
    global _current, _epoch_seq, _enabled, _epoch_n
    with _lock:
        _current = _new_current()
        _epochs.clear()
        _epoch_seq = 0
        _totals["n"] = 0
        _totals["ms"] = 0.0
    _enabled = _env_flag()
    _epoch_n = _read_epoch_n()
