"""Opt-in HTTP introspection endpoint for live long-running jobs.

A tiered capture loop or a multihost perf run used to be a black box:
the only way to inspect it was to kill it and read JSONL off disk.
With ``DBCSR_TPU_OBS_PORT=<port>`` set (or `start()` called), every
engine process serves its live observability state over plain stdlib
``http.server`` — no dependencies, daemon thread, zero cost when off:

====================  ==================================================
route                 payload
====================  ==================================================
``/metrics``          Prometheus text exposition (`metrics.
                      prometheus_text()`) — scrapeable
``/healthz``          `health.verdict()` JSON; HTTP 200 for OK/
                      DEGRADED, 503 for CRITICAL (load-balancer
                      convention)
``/flight``           the flight-recorder ring (`flight.records()`)
``/events``           the event-bus ring; filters ``?product_id=…``,
                      ``?kind=…``, ``?limit=N``
``/serve/submit``     POST one serving-plane request (JSON body:
                      ``session``, ``a``/``b``/``c`` matrix names,
                      ``alpha``/``beta``/``op``/``priority``/
                      ``deadline_s``; optional ``wait`` +
                      ``timeout_s``); 503 when no engine runs, 429
                      with the structured rejection when shed
``/serve/status``     serving-plane snapshot (queue depth, in-flight,
                      coalescing/quota config); ``?request_id=…``
                      returns one request's ticket
``/serve/tenants``    per-tenant serving metrics: admitted/shed/
                      deadline-missed counters, queue load, rolling
                      p50/p95 latency
``/usage``            tenant cost-attribution rollup (`attribution.
                      usage()`): per-tenant device-seconds/flops/
                      bytes + saved credits, top consumers, grand
                      totals; ``?top=N``
``/timeseries``       telemetry history store (`obs.timeseries`):
                      ``?metric=&since=&until=&agg=&tier=`` + any
                      other param as a label matcher; no ``metric``
                      lists the known series
``/slo``              `obs.slo` burn-rate evaluation + the ``slo``
                      health component
``/cluster``          fleet federation: scrape the sibling processes'
                      endpoints (the multihost port-offset scheme, or
                      ``?ports=9100,9101`` / ``?n=4``) and merge them
                      into ONE exposition with per-process provenance
                      labels; ``?format=prom`` (default) or ``json``
``/``                 route index JSON
====================  ==================================================

**Multihost**: N processes sharing one env value must not fight over
one port — each binds ``base_port + process_index``.  When the index
is not yet knowable at activation (env activation runs before the
backend exists), the server starts on the base port best-effort and
`parallel.multihost.init_multihost` calls `rebind()` once the world
forms, restarting the listener on its offset port; a bind conflict at
activation simply defers the start to that rebind (same lazy-index
contract as `tracer._process_index`).

Loopback by default (``DBCSR_TPU_OBS_HOST``, default ``127.0.0.1``):
this is an introspection port, not a public API.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from dbcsr_tpu.obs import tracer as _trace

_lock = threading.Lock()
_server: "ObsServer | None" = None
# /serve/stage's per-process materialization memo: (tenant, digest) ->
# matrix (the loadtest mat_cache contract — repeated digests reuse ONE
# object so the value-digest memo and product cache behave as live)
_stage_cache: dict = {}
# remembered when an early start() could not bind (index unknown and
# the base port was taken by another rank): rebind() retries with the
# resolved offset
_pending_base: int | None = None


class _Handler(BaseHTTPRequestHandler):
    server_version = "dbcsr-tpu-obs/1"

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def _send(self, body: str, content_type: str, code: int = 200) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, obj, code: int = 200) -> None:
        self._send(json.dumps(obj, default=str), "application/json", code)

    def do_GET(self):  # noqa: N802 — http.server API
        try:
            url = urlparse(self.path)
            route = url.path.rstrip("/") or "/"
            if route == "/metrics":
                from dbcsr_tpu.obs import metrics

                self._send(metrics.prometheus_text(),
                           "text/plain; version=0.0.4")
            elif route == "/healthz":
                from dbcsr_tpu.obs import health

                v = health.verdict()
                self._send_json(
                    v, code=503 if v["status"] == health.CRITICAL else 200)
            elif route == "/flight":
                from dbcsr_tpu.obs import flight

                self._send_json(flight.records())
            elif route == "/events":
                from dbcsr_tpu.obs import events

                q = parse_qs(url.query)
                limit = None
                if "limit" in q:
                    try:
                        limit = int(q["limit"][0])
                    except ValueError:
                        pass
                self._send_json(events.records(
                    product_id=q.get("product_id", [None])[0],
                    kind=q.get("kind", [None])[0], limit=limit))
            elif route == "/timeseries":
                self._timeseries(parse_qs(url.query))
            elif route == "/rca":
                self._rca(parse_qs(url.query))
            elif route == "/profile/diff":
                self._profile_diff(parse_qs(url.query))
            elif route == "/slo":
                from dbcsr_tpu.obs import slo

                self._send_json({"objectives": slo.evaluate(),
                                 "component": slo.component()})
            elif route == "/cluster":
                self._cluster(parse_qs(url.query))
            elif route == "/serve/status":
                q = parse_qs(url.query)
                self._serve_status(q.get("request_id", [None])[0])
            elif route == "/serve/heartbeat":
                # fleet liveness probe: answers whether THIS process is
                # alive and routable — never 503s on a missing engine
                # (the router reads `engine`/`draining`, it does not
                # infer them from the status code)
                from dbcsr_tpu.serve import engine as _serve

                eng = _serve.current_engine()
                self._send_json({
                    "pid": os.getpid(),
                    "t_unix": time.time(),
                    "engine": eng is not None and eng.running(),
                    "draining": bool(eng.draining) if eng else False,
                    "queue_depth": eng.queue.depth() if eng else 0,
                })
            elif route == "/serve/checksum":
                self._serve_checksum(parse_qs(url.query))
            elif route == "/serve/cache":
                # fleet-shared product-cache tier: one entry by digest
                # handle (serve.product_cache.peer_lookup's wire call)
                from dbcsr_tpu.serve import product_cache as _pcache

                q = parse_qs(url.query)
                dig = q.get("digest", [None])[0]
                payload = _pcache.export_entry(dig) if dig else None
                if payload is None:
                    self._send_json({"found": False}, code=404)
                else:
                    self._send_json(dict(payload, found=True))
            elif route == "/tune/promotions":
                # fleet-shared tuning tier: this process's ORIGIN
                # promotions (never re-exported adoptions), filtered to
                # the caller's device kind (tune.store.peer_sync's
                # wire call)
                from dbcsr_tpu.tune import store as _tstore

                q = parse_qs(url.query)
                payload = _tstore.export_promotions(
                    kind=q.get("kind", [None])[0])
                if not payload.get("rows"):
                    self._send_json(dict(payload, found=False), code=404)
                else:
                    self._send_json(dict(payload, found=True))
            elif route == "/serve/tenants":
                eng = self._serve_engine()
                if eng is None:
                    return
                self._send_json(eng.tenants())
            elif route == "/usage":
                from dbcsr_tpu.obs import attribution

                q = parse_qs(url.query)
                try:
                    top = int(q.get("top", ["5"])[0])
                except ValueError:
                    top = 5
                self._send_json(attribution.usage(top=top))
            elif route == "/":
                self._send_json({
                    "routes": ["/metrics", "/healthz", "/flight",
                               "/events?product_id=&kind=&limit=",
                               "/timeseries?metric=&since=&agg=&tier=",
                               "/rca?limit=&ledger=",
                               "/profile/diff?a=&b=&top=",
                               "/slo",
                               "/cluster?format=prom|json&ports=&n=",
                               "/serve/submit (POST)",
                               "/serve/status?request_id=",
                               "/serve/tenants",
                               "/serve/heartbeat",
                               "/serve/checksum?session=&name=",
                               "/serve/cache?digest=",
                               "/tune/promotions?kind=",
                               "/serve/session/open (POST)",
                               "/serve/matrix (POST)",
                               "/serve/stage (POST)",
                               "/serve/drain (POST)",
                               "/serve/replay (POST)",
                               "/usage?top="],
                    "process_index": _server.process_index
                    if _server else None,
                })
            else:
                self._send_json({"error": f"no route {route}"}, code=404)
        except Exception as exc:  # introspection must never kill the job
            try:
                self._send_json(
                    {"error": f"{type(exc).__name__}: {exc}"}, code=500)
            except Exception:
                pass

    # -------------------------------------------------- telemetry history

    def _timeseries(self, q: dict) -> None:
        """``/timeseries``: query the live store.  Reserved params:
        ``metric``, ``since``, ``until``, ``agg``, ``tier``; every
        OTHER param is a label matcher (``?metric=…&driver=xla``).
        Without ``metric`` the known series are listed."""
        from dbcsr_tpu.obs import timeseries

        metric = q.get("metric", [None])[0]
        if not metric:
            self._send_json(timeseries.series_list())
            return
        reserved = ("metric", "since", "until", "agg", "tier", "format")
        labels = {k: v[0] for k, v in q.items() if k not in reserved}

        def num(name):
            raw = q.get(name, [None])[0]
            try:
                return float(raw) if raw not in (None, "") else None
            except ValueError:
                return None

        tier = q.get("tier", ["auto"])[0]
        if tier not in ("auto", "raw"):
            try:
                tier = float(tier)
            except ValueError:
                tier = "auto"
        self._send_json(timeseries.query(
            metric, labels=labels or None, since=num("since"),
            until=num("until"), agg=q.get("agg", [None])[0] or None,
            tier=tier))

    # --------------------------------------------- causal diagnosis plane

    def _rca(self, q: dict) -> None:
        """``/rca``: ranked causal reports + the change ledger + fired
        change-points, versioned by the obs schema (fleet merges key
        on it)."""
        from dbcsr_tpu import obs
        from dbcsr_tpu.obs import changepoint, rca

        limit = None
        try:
            raw = q.get("limit", [None])[0]
            limit = int(raw) if raw else None
        except ValueError:
            pass
        try:
            ledger_n = int(q.get("ledger", ["32"])[0])
        except ValueError:
            ledger_n = 32
        self._send_json({
            "schema": obs.OBS_SCHEMA_VERSION,
            "reports": rca.reports(limit=limit),
            "changepoints": changepoint.changepoints(limit=limit),
            "ledger": rca.ledger(limit=ledger_n),
        })

    def _profile_diff(self, q: dict) -> None:
        """``/profile/diff``: differential profile between two baseline
        snapshots.  ``a``/``b`` accept an epoch number, a negative ring
        index, or ``current``; defaults compare the previous sealed
        epoch against the newest profile state."""
        from dbcsr_tpu.obs import profiler

        def ref(name, default):
            raw = q.get(name, [None])[0]
            if raw in (None, ""):
                return default
            if raw == "current":
                return "current"
            try:
                return int(raw)
            except ValueError:
                return default

        try:
            top = int(q.get("top", ["8"])[0])
        except ValueError:
            top = 8
        a = ref("a", -2)
        b = ref("b", "current")
        d = profiler.diff(a, b, top=top)
        if b == "current" and not d.get("ok"):
            # a young process may have sealed nothing yet; fall back to
            # newest-sealed vs current before giving up
            d = profiler.diff(-1, "current", top=top)
        self._send_json(d)

    # --------------------------------------------------- fleet federation

    def _cluster(self, q: dict) -> None:
        """``/cluster``: scrape every sibling process's endpoint and
        merge into one fleet view with per-process provenance."""
        fmt = q.get("format", ["prom"])[0]
        ports = q.get("ports", [None])[0]
        n = q.get("n", [None])[0]
        peers = _cluster_peers(
            ports=[int(p) for p in ports.split(",") if p] if ports
            else None,
            n=int(n) if n else None)
        fleet = _fleet_mod()
        if fmt == "json":
            self._send_json(fleet.fleet_report(peers))
        else:
            self._send(fleet.merge_prometheus(peers),
                       "text/plain; version=0.0.4")

    # ------------------------------------------------------ serving plane

    def _serve_engine(self):
        """The live serving engine, or None (a 503 was sent).  The
        endpoint never CREATES an engine — serving is opt-in."""
        from dbcsr_tpu.serve import engine as _serve

        eng = _serve.current_engine()
        if eng is None:
            self._send_json(
                {"error": "serving plane not running "
                          "(dbcsr_tpu.serve.get_engine() starts it)"},
                code=503)
        return eng

    def _serve_status(self, request_id):
        eng = self._serve_engine()
        if eng is None:
            return
        if request_id:
            req = eng.get_request(request_id)
            if req is None:
                self._send_json(
                    {"error": f"unknown request {request_id}"}, code=404)
                return
            self._send_json(req.info())
            return
        self._send_json(eng.status())

    def do_POST(self):  # noqa: N802 — http.server API
        try:
            url = urlparse(self.path)
            route = url.path.rstrip("/")
            handlers = {
                "/serve/submit": self._serve_submit,
                "/serve/session/open": self._serve_session_open,
                "/serve/matrix": self._serve_matrix,
                "/serve/stage": self._serve_stage,
                "/serve/drain": self._serve_drain,
                "/serve/replay": self._serve_replay,
            }
            handler = handlers.get(route)
            if handler is None:
                self._send_json({"error": f"no POST route {route}"},
                                code=404)
                return
            length = int(self.headers.get("Content-Length") or 0)
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
            except ValueError:
                self._send_json({"error": "bad JSON body"}, code=400)
                return
            handler(body)
        except Exception as exc:  # the serve paths must never kill the job
            try:
                self._send_json(
                    {"error": f"{type(exc).__name__}: {exc}"}, code=500)
            except Exception:
                pass

    def _resolve_session(self, body: dict):
        """The session named by ``body`` or None (a 404 was sent)."""
        from dbcsr_tpu.serve import session as _session

        sess = _session.get_session(str(body.get("session", "")))
        if sess is None:
            self._send_json(
                {"error": f"unknown session {body.get('session')!r}"},
                code=404)
        return sess

    def _serve_submit(self, body: dict) -> None:
        eng = self._serve_engine()
        if eng is None:
            return
        sess = self._resolve_session(body)
        if sess is None:
            return
        params = {k: body[k] for k in
                  ("a", "b", "c", "p", "alpha", "beta", "transa",
                   "transb", "filter_eps", "retain_sparsity", "steps",
                   "out")
                  if k in body}
        try:
            req = eng.submit(
                sess, op=str(body.get("op", "multiply")),
                priority=int(body.get("priority", 10)),
                deadline_s=body.get("deadline_s"),
                request_id=body.get("request_id"), **params)
        except KeyError as exc:  # unregistered matrix name
            self._send_json({"error": str(exc.args[0])}, code=404)
            return
        except ValueError as exc:  # unknown op
            self._send_json({"error": str(exc)}, code=400)
            return
        if body.get("wait"):
            req.wait(timeout=float(body.get("timeout_s", 30.0)))
        info = req.info()
        self._send_json(info, code=429 if req.state == "shed" else 200)

    def _serve_session_open(self, body: dict) -> None:
        """Open (or idempotently re-open) a session.  An explicit
        ``session_id`` is what lets the fleet router re-pin a dead
        worker's tenant sessions on a surviving peer under the SAME
        id, so journaled requests resolve; re-opening an id the same
        tenant already holds returns it (idempotent), another tenant's
        id is refused 409 — the session-name-collision guard."""
        eng = self._serve_engine()
        if eng is None:
            return
        tenant = str(body.get("tenant") or "")
        if not tenant:
            self._send_json({"error": "no tenant"}, code=400)
            return
        sid = body.get("session_id")
        if sid is not None:
            from dbcsr_tpu.serve import session as _session

            existing = _session.get_session(str(sid))
            if existing is not None:
                if existing.tenant != tenant:
                    self._send_json(
                        {"error": f"session id {sid!r} is held by "
                                  f"tenant {existing.tenant!r}"},
                        code=409)
                    return
                self._send_json({"session_id": existing.session_id,
                                 "tenant": existing.tenant,
                                 "existing": True})
                return
        sess = eng.open_session(tenant, name=sid)
        self._send_json({"session_id": sess.session_id,
                         "tenant": sess.tenant, "existing": False})

    def _serve_matrix(self, body: dict) -> None:
        """Create a matrix in a session by spec — ``random`` (the
        deterministic per-(session, name, seed) generator: two workers
        given the same spec materialize bitwise-equal values, the
        cross-worker failover re-pinning primitive) or ``create``
        (an empty result target)."""
        import numpy as np

        sess = self._resolve_session(body)
        if sess is None:
            return
        name = str(body.get("name") or "")
        row_blk = body.get("row_blk") or []
        col_blk = body.get("col_blk") or row_blk
        if not name or not row_blk:
            self._send_json({"error": "need name and row_blk"}, code=400)
            return
        dtype = np.dtype(str(body.get("dtype", "float64")))
        if str(body.get("kind", "random")) == "create":
            sess.create(name, row_blk, col_blk, dtype=dtype)
        else:
            sess.random(name, row_blk, col_blk, dtype=dtype,
                        occupation=float(body.get("occupation", 0.5)),
                        seed=int(body.get("seed", 0)))
        self._send_json({"ok": True, "session": sess.session_id,
                         "name": name})

    def _serve_stage(self, body: dict) -> None:
        """Stage one workload stream entry: materialize its operands
        into the session (digest-derived seeds — deterministic across
        workers) and return the submit kwargs.  The stage cache is
        per-process and memoizes per (tenant, digest) exactly like the
        loadtest harness's."""
        from dbcsr_tpu.serve import workload as _workload

        sess = self._resolve_session(body)
        if sess is None:
            return
        entry = body.get("entry")
        if not isinstance(entry, dict):
            self._send_json({"error": "no entry"}, code=400)
            return
        kwargs = _workload.stage_entry(sess, entry, _stage_cache)
        self._send_json({"ok": True, "session": sess.session_id,
                         "kwargs": kwargs})

    def _serve_drain(self, body: dict) -> None:
        eng = self._serve_engine()
        if eng is None:
            return
        self._send_json(eng.drain(
            timeout=float(body.get("timeout_s", 30.0)),
            journal_path=body.get("journal")))

    def _serve_replay(self, body: dict) -> None:
        """Replay a journal on THIS worker (the fleet failover target's
        side of the handoff): ``skip_ids`` are request ids the router's
        ledger knows completed elsewhere — tombstoned, never re-run."""
        eng = self._serve_engine()
        if eng is None:
            return
        tickets = eng.replay_journal(
            path=body.get("journal"),
            skip_ids=body.get("skip_ids") or ())
        self._send_json({"replayed": [t.request_id for t in tickets],
                         "count": len(tickets)})

    def _serve_checksum(self, q: dict) -> None:
        """``/serve/checksum?session=&name=``: the scalar checksum of
        one registered matrix (`ops.test_methods.checksum`) — what the
        fleet chaos case compares bitwise across workers."""
        from dbcsr_tpu.ops.test_methods import checksum
        from dbcsr_tpu.serve import session as _session

        sid = q.get("session", [None])[0]
        name = q.get("name", [None])[0]
        sess = _session.get_session(str(sid or ""))
        if sess is None:
            self._send_json({"error": f"unknown session {sid!r}"},
                            code=404)
            return
        try:
            m = sess.get(str(name or ""))
        except KeyError as exc:
            self._send_json({"error": str(exc.args[0])}, code=404)
            return
        self._send_json({"session": sess.session_id, "name": name,
                         "checksum": float(checksum(m))})


class ObsServer:
    """One listening introspection endpoint (daemon thread)."""

    def __init__(self, host: str, port: int, process_index: int):
        self.process_index = process_index
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, name="dbcsr-tpu-obs-server",
            daemon=True)
        self.thread.start()

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def host(self) -> str:
        return self.httpd.server_address[0]

    def close(self) -> None:
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass


def _host() -> str:
    return os.environ.get("DBCSR_TPU_OBS_HOST", "127.0.0.1")


def start(port: int | None = None) -> "ObsServer | None":
    """Start (or restart) the endpoint on ``base port +
    process_index``.  ``port=0`` binds an ephemeral port (tests).
    Returns the server, or None when the bind failed with the process
    index still unknown — `rebind` retries once `init_multihost`
    resolves it."""
    global _server, _pending_base
    if port is None:
        raw = os.environ.get("DBCSR_TPU_OBS_PORT")
        if not raw:
            raise ValueError(
                "no port: pass one or set DBCSR_TPU_OBS_PORT")
        port = int(raw)
    with _lock:
        if _server is not None:
            _server.close()
            _server = None
        idx = _trace._process_index() or 0
        bind_port = port + idx if port else 0
        try:
            _server = ObsServer(_host(), bind_port, idx)
            _pending_base = port if port else None
        except OSError:
            # base port taken (very likely a sibling rank on this host,
            # our own index not yet knowable): defer to rebind()
            _pending_base = port if port else None
            return None
        return _server


def stop() -> None:
    global _server, _pending_base
    with _lock:
        if _server is not None:
            _server.close()
            _server = None
        _pending_base = None


def running() -> bool:
    return _server is not None


def get() -> "ObsServer | None":
    return _server


def url() -> str | None:
    """The endpoint base URL, or None when not running."""
    s = _server
    return f"http://{s.host}:{s.port}" if s is not None else None


def rebind(process_index: int | None = None) -> None:
    """Settle the endpoint onto its ``base + process_index`` port once
    the world's index is known (called by `init_multihost`, mirroring
    `tracer.rebind`).  No-op when the endpoint was never requested or
    is already on its final port."""
    global _server
    base = _pending_base
    if base is None:
        return
    if process_index is None:
        process_index = _trace._process_index()
    if process_index is None:
        return
    idx = int(process_index)
    with _lock:
        if _server is not None and _server.process_index == idx \
                and _server.port == base + idx:
            return
        if _server is not None:
            _server.close()
            _server = None
        try:
            _server = ObsServer(_host(), base + idx, idx)
        except OSError:
            _server = None


# --------------------------------------------------- fleet federation
#
# The multihost port-offset scheme (each process serves base + index)
# already tells every process where its siblings listen; /cluster
# turns that into one fleet-wide view.  The scrape/relabel/merge core
# lives ONCE in tools/fleet.py (which must stay dbcsr_tpu-import-free
# for offline use on copied artifacts, so the server loads it by file
# path); only peer DISCOVERY lives here — it needs the server's bind
# state and the jax world.

_fleet = None


def _fleet_mod():
    """tools/fleet.py loaded by path (tools/ is not a package; the
    shared merge logic must not be duplicated here — it already
    drifted once)."""
    global _fleet
    if _fleet is None:
        import importlib.util

        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        path = os.path.join(root, "tools", "fleet.py")
        spec = importlib.util.spec_from_file_location(
            "_dbcsr_tpu_fleet", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _fleet = mod
    return _fleet


def _cluster_peers(ports: list | None = None,
                   n: int | None = None) -> list:
    """[(index, url)] of the fleet's endpoints.  Explicit ``ports``
    win; else the remembered base port + the world's process count
    (falling back to probing up to 8 consecutive ports when no backend
    knows the count)."""
    host = _host()
    base = _pending_base
    if base is None and _server is not None:
        base = _server.port - _server.process_index
    if ports:
        # provenance must name the REAL process index: with the base
        # port known, index = port - base (so ?ports=9101 on a base
        # of 9100 labels process="1", and subsets stay truthful);
        # ports outside the offset scheme fall back to position
        out = []
        for i, p in enumerate(ports):
            idx = p - base if (base is not None
                               and 0 <= p - base < 4096) else i
            out.append((idx, f"http://{host}:{p}"))
        return out
    if base is None:
        return [(0, url())] if url() else []
    if n is None:
        import sys

        jax = sys.modules.get("jax")
        xb = sys.modules.get("jax._src.xla_bridge")
        if jax is not None and xb is not None \
                and getattr(xb, "_backends", None):
            try:
                n = int(jax.process_count())
            except Exception:
                n = None
    # no world evidence and no explicit count: the fleet is just this
    # process — fabricating sibling ports would report phantom peers
    # as down and page spuriously on a healthy single-process job
    count = n if n else 1
    return [(i, f"http://{host}:{base + i}") for i in range(count)]


# env activation: DBCSR_TPU_OBS_PORT set at import serves the endpoint
# with no code changes anywhere (mirrors DBCSR_TPU_TRACE); a bind
# conflict defers to init_multihost's rebind
if os.environ.get("DBCSR_TPU_OBS_PORT"):
    try:
        start()
    except (ValueError, OSError):
        pass
