"""Per-request cost attribution and tenant usage metering.

The serving plane (PR 8) made DBCSR-TPU multi-tenant; every existing
meter — roofline rollups, pool/transfer counters, dispatch seconds —
still aggregates by *driver*, never by tenant or request.  This module
answers the two questions a serving fleet lives on: "where did request
R's latency go?" and "which tenant is consuming the device?".

Design — the books must balance EXACTLY:

* The serve worker is single-writer, so the engine brackets every
  execution in a **window**: `begin_window()` snapshots the summed
  `core.stats` driver rollup (dispatch seconds, flops, modeled bytes)
  plus the mempool H2D/D2H and high-water meters; `bill_window()`
  attributes the delta to the window's requests.  Every rollup-recorded
  region the worker runs falls inside exactly one window, so the sum of
  per-tenant billings equals the engine rollup by construction.
* Billing is **integer-exact**: device time is billed in integer
  nanoseconds, flops/bytes as integers.  Split shares use largest-
  remainder apportionment, so per-member shares sum EXACTLY to the
  window total and per-tenant sums reproduce the grand total regardless
  of accumulation order (float addition is not associative; integer
  addition is).  Seconds are quantized once per window (≤ 1 ns each);
  flops and bytes conserve bit-exactly against `core.stats`.
* Coalesced composites split execute cost among member requests by
  FLOP share (the per-request true-flop shares `serve.coalesce`
  computed); product-cache hits bill the (zero) measured window and
  record a *saved* credit; ABFT re-executions land inside the same
  window and bill to the owning request; a degrade replay bills its
  serialized windows separately — each window is billed exactly once,
  so faults and replays can never double-bill.
* One **terminal attribution** per request id: the ledger marks a
  request terminal at its `Request._finish` chokepoint and ignores
  repeats; a journal-replayed id re-arms at submit (its resubmission
  is the same logical request, billed into the same ledger row).

Surfacing: `dbcsr_tpu_tenant_{device_seconds,flops,bytes_moved,
saved_flops}_total{tenant}` counters (scraped by `/metrics` and the
timeseries collector), `request_info()` for the `/serve/status`
phase breakdown (queued → coalesce-wait → execute → carve →
serialize), `usage()` for the `/usage` endpoint / doctor row /
`tools/usage_report.py`, and `conservation()` exposing both sides of
the invariant for tests and the chaos suite.

Bounded everywhere: the ledger keeps the last ``DBCSR_TPU_
ATTRIBUTION_N`` requests; tenant rollup rows are capped at
``DBCSR_TPU_ATTRIBUTION_TENANTS`` with least-recently-active rows
folded into an ``(evicted)`` aggregate — eviction never loses cost,
so the conservation invariant survives tenant churn.

Module-level imports are stdlib-only; `core.stats` / `core.mempool`
are reached through ``sys.modules`` (never imported here), so the
module stays usable in jax-free contexts and costs nothing when the
layers it snapshots were never loaded.  ``DBCSR_TPU_ATTRIBUTION=0``
turns every hook into an early return.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time

from dbcsr_tpu.utils import lockcheck as _lockcheck

_lock = _lockcheck.wrap("obs.attribution", threading.Lock())

# ledger phase names, in critical-path order (docs/serving.md)
PHASES = ("queued", "coalesce_wait", "execute", "carve", "serialize")

EVICTED = "(evicted)"

_ledger: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
_tenants: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
# least-recently-active tenant rows fold here when the cap is hit, so
# grand totals (and the conservation invariant) survive eviction
_evicted: dict = {}
_grand = {"device_ns": 0, "flops": 0, "bytes_moved": 0, "pool_bytes": 0,
          "saved_flops": 0, "saved_device_ns": 0, "requests": 0,
          "cache_hits": 0, "windows": 0}
# summed stats-rollup totals at the last reset(): `conservation()`
# compares the grand ledger against (live rollup - baseline)
_baseline = (0.0, 0, 0, 0)


def enabled() -> bool:
    return os.environ.get("DBCSR_TPU_ATTRIBUTION", "1") != "0"


def _ledger_cap() -> int:
    try:
        return max(16, int(os.environ.get("DBCSR_TPU_ATTRIBUTION_N",
                                          "1024")))
    except ValueError:
        return 1024


def _tenant_cap() -> int:
    try:
        return max(4, int(os.environ.get("DBCSR_TPU_ATTRIBUTION_TENANTS",
                                         "512")))
    except ValueError:
        return 512


def _zero_row() -> dict:
    return {"device_ns": 0, "flops": 0, "bytes_moved": 0, "pool_bytes": 0,
            "saved_flops": 0, "saved_device_ns": 0, "requests": 0,
            "cache_hits": 0}


# ------------------------------------------------------------ snapshots

def _rollup_totals() -> tuple:
    """(seconds, flops, bytes_moved, pool_high_water) summed over the
    engine's attribution layers right now.  ``bytes_moved`` folds the
    modeled HBM bytes of the driver rollup with the measured H2D/D2H
    staging meters — every byte the engine accounts anywhere.  Read
    through ``sys.modules``: a layer that was never imported reads 0."""
    seconds = 0.0
    flops = nbytes = 0
    h2d = d2h = hw = 0
    st = sys.modules.get("dbcsr_tpu.core.stats")
    if st is not None:
        for a in st._driver_agg.values():
            seconds += a.seconds
            flops += a.flops
            nbytes += a.nbytes
    mp = sys.modules.get("dbcsr_tpu.core.mempool")
    if mp is not None:
        s = mp._stats  # plain dict reads (GIL-atomic); worker-local use
        h2d = s["h2d_bytes"]
        d2h = s["d2h_bytes"]
        hw = s["high_water"]
    return (seconds, flops, nbytes + h2d + d2h, hw)


def _split_int(total: int, weights: list) -> list:
    """Largest-remainder apportionment: non-negative integer shares
    proportional to ``weights`` that sum EXACTLY to ``total``."""
    n = len(weights)
    wsum = sum(weights)
    if wsum <= 0:
        weights = [1] * n
        wsum = n
    shares = [total * w // wsum for w in weights]
    rem = total - sum(shares)
    # distribute the remainder by descending fractional part (stable)
    order = sorted(range(n),
                   key=lambda i: (total * weights[i]) % wsum, reverse=True)
    for i in range(rem):
        shares[order[i % n]] += 1
    return shares


# --------------------------------------------------------------- ledger

def _new_rec(request_id: str, tenant: str, op: str) -> dict:
    return {
        "request_id": request_id, "tenant": tenant, "op": op,
        "t_submit": time.time(),
        "phases": {},           # seconds per PHASES name
        "billed": {"device_ns": 0, "flops": 0, "bytes_moved": 0,
                   "pool_bytes": 0},
        "saved": {"flops": 0, "device_ns": 0},
        "cached": 0, "windows": 0, "resubmits": 0,
        "terminal": None, "counted": False,
    }


def _rec_locked(request_id: str, tenant: str, op: str) -> dict:
    rec = _ledger.get(request_id)
    if rec is None:
        rec = _ledger[request_id] = _new_rec(request_id, tenant, op)
        cap = _ledger_cap()
        while len(_ledger) > cap:
            _ledger.popitem(last=False)
    return rec


def _tenant_locked(name: str) -> dict:
    row = _tenants.get(name)
    if row is None:
        row = _tenants[name] = _zero_row()
        cap = _tenant_cap()
        while len(_tenants) > cap:
            _, old = _tenants.popitem(last=False)
            if not _evicted:
                _evicted.update(_zero_row())
            for k, v in old.items():
                _evicted[k] += v
    else:
        _tenants.move_to_end(name)
    return row


def on_submit(req) -> None:
    """Open (or re-arm) the ledger row for a submitted request.  A
    journal-replayed resubmission carries the SAME request id: its row
    re-arms — terminal cleared, billed totals kept — so the replay's
    cost lands on the same logical request and the terminal guard
    cannot swallow the replay's real completion."""
    if not enabled():
        return
    with _lock:
        rec = _ledger.get(req.request_id)
        if rec is None:
            _rec_locked(req.request_id, req.tenant, req.op)
        else:
            rec["resubmits"] += 1
            rec["terminal"] = None
            _ledger.move_to_end(req.request_id)


def phase(request_id: str, name: str, seconds: float) -> None:
    """Accumulate wall seconds into one critical-path phase of the
    request's ledger row (no-op for unknown ids — e.g. bare
    `AdmissionQueue` use outside the engine)."""
    if not enabled() or seconds <= 0:
        return
    with _lock:
        rec = _ledger.get(request_id)
        if rec is not None:
            rec["phases"][name] = rec["phases"].get(name, 0.0) + seconds


def group_phase(requests: list, name: str, seconds: float) -> None:
    """Record one group-level phase duration (e.g. the composite
    carve) on every member's ledger row — the group shares the wall
    interval, so each member sees the full duration."""
    if not enabled() or seconds <= 0:
        return
    with _lock:
        for r in requests:
            rec = _ledger.get(r.request_id)
            if rec is not None:
                rec["phases"][name] = (rec["phases"].get(name, 0.0)
                                       + seconds)


def on_terminal(req, state: str) -> None:
    """Terminal chokepoint (called from `Request._finish`): stamp the
    final state ONCE per armed request id — repeats (a replayed fail
    path re-finishing, defensive double-_finish) are ignored, so a
    request is never counted twice."""
    if not enabled():
        return
    with _lock:
        rec = _ledger.get(req.request_id)
        if rec is None or rec["terminal"] is not None:
            return
        rec["terminal"] = state
        if not rec["counted"]:
            rec["counted"] = True
            _tenant_locked(rec["tenant"])["requests"] += 1
            _grand["requests"] += 1


# -------------------------------------------------------------- billing

def begin_window() -> tuple | None:
    """Open a billing window around one worker execution (a coalesced
    composite, one serialized request, a cache-hit service).  Returns
    the opaque token `bill_window` consumes, or None when attribution
    is off."""
    if not enabled():
        return None
    return (time.perf_counter(),) + _rollup_totals()


def bill_window(token, requests: list, weights=None,
                phase_name: str = "execute") -> None:
    """Close a billing window: attribute the engine-rollup delta since
    ``token`` to ``requests``, split by ``weights`` (the coalesced
    group's per-request FLOP shares; equal split when absent — e.g. a
    failed composite whose per-request shares never materialized).
    Shares sum EXACTLY to the measured delta (`_split_int`).  The
    window's wall time lands in phase ``phase_name`` ("execute", or
    "serialize" for a degrade replay's serialized re-execution)."""
    if token is None or not requests:
        return
    wall = time.perf_counter() - token[0]
    cur = _rollup_totals()
    dev_ns = int(round(max(0.0, cur[0] - token[1]) * 1e9))
    flops = max(0, cur[1] - token[2])
    nbytes = max(0, cur[2] - token[3])
    pool = max(0, cur[3] - token[4])
    # chaos handle on the billing path: an injected fault here must be
    # observable (bus event + counter via the faults layer) but can
    # never unbalance the books or fail the request — attribution is
    # bookkeeping, not execution
    fa = sys.modules.get("dbcsr_tpu.resilience.faults")
    if fa is not None and fa.active():
        try:
            fa.maybe_inject("attribution", requests=str(len(requests)),
                            request_id=requests[0].request_id)
        except Exception:
            pass  # billing below still runs: the books stay balanced
    n = len(requests)
    if weights is None or len(weights) != n:
        weights = [1] * n
    weights = [max(0, int(w)) for w in weights]
    ns_sh = _split_int(dev_ns, weights)
    fl_sh = _split_int(flops, weights)
    by_sh = _split_int(nbytes, weights)
    po_sh = _split_int(pool, weights)
    with _lock:
        _grand["windows"] += 1
        _grand["device_ns"] += dev_ns
        _grand["flops"] += flops
        _grand["bytes_moved"] += nbytes
        _grand["pool_bytes"] += pool
        for i, r in enumerate(requests):
            rec = _rec_locked(r.request_id, r.tenant, r.op)
            rec["windows"] += 1
            rec["billed"]["device_ns"] += ns_sh[i]
            rec["billed"]["flops"] += fl_sh[i]
            rec["billed"]["bytes_moved"] += by_sh[i]
            rec["billed"]["pool_bytes"] += po_sh[i]
            rec["phases"][phase_name] = (
                rec["phases"].get(phase_name, 0.0) + wall)
            row = _tenant_locked(r.tenant)
            row["device_ns"] += ns_sh[i]
            row["flops"] += fl_sh[i]
            row["bytes_moved"] += by_sh[i]
            row["pool_bytes"] += po_sh[i]
            _meter(r.tenant, ns_sh[i], fl_sh[i], by_sh[i], 0)


def credit_saved(req, flops: int, seconds: float = 0.0) -> None:
    """Record a value-reuse credit: a product-cache (or incremental)
    hit served this request without dispatching — bill nothing, credit
    the tenant with the device work the hit avoided."""
    if not enabled():
        return
    flops = max(0, int(flops))
    ns = int(round(max(0.0, seconds) * 1e9))
    with _lock:
        rec = _rec_locked(req.request_id, req.tenant, req.op)
        rec["cached"] += 1
        rec["saved"]["flops"] += flops
        rec["saved"]["device_ns"] += ns
        row = _tenant_locked(req.tenant)
        row["saved_flops"] += flops
        row["saved_device_ns"] += ns
        row["cache_hits"] += 1
        _grand["saved_flops"] += flops
        _grand["saved_device_ns"] += ns
        _grand["cache_hits"] += 1
        _meter(req.tenant, 0, 0, 0, flops)


def _meter(tenant: str, dev_ns: int, flops: int, nbytes: int,
           saved_flops: int) -> None:
    """Mirror one billing into the Prometheus tenant meters (scraped
    by /metrics and replayed from telemetry shards via the timeseries
    collector).  Called with the attribution lock held; the registry
    has its own lock and never calls back into this module."""
    from dbcsr_tpu.obs import metrics as _metrics

    if dev_ns:
        _metrics.counter(
            "dbcsr_tpu_tenant_device_seconds_total",
            "device dispatch-seconds attributed to the owning tenant "
            "(exact split of the engine rollup; ns-quantized)",
        ).inc(dev_ns / 1e9, tenant=tenant)
    if flops:
        _metrics.counter(
            "dbcsr_tpu_tenant_flops_total",
            "true flops attributed to the owning tenant",
        ).inc(flops, tenant=tenant)
    if nbytes:
        _metrics.counter(
            "dbcsr_tpu_tenant_bytes_moved_total",
            "bytes moved (modeled HBM + measured H2D/D2H) attributed "
            "to the owning tenant",
        ).inc(nbytes, tenant=tenant)
    if saved_flops:
        _metrics.counter(
            "dbcsr_tpu_tenant_saved_flops_total",
            "flops a tenant's requests did NOT dispatch thanks to "
            "product-cache / value-reuse hits (the saved credit)",
        ).inc(saved_flops, tenant=tenant)


# -------------------------------------------------------------- readers

def _row_view(row: dict) -> dict:
    out = dict(row)
    out["device_seconds"] = row["device_ns"] / 1e9
    out["saved_device_seconds"] = row["saved_device_ns"] / 1e9
    return out


def request_info(request_id: str) -> dict | None:
    """JSON-safe ledger row for `/serve/status?request_id=` — the
    per-request critical-path phase breakdown plus billed totals."""
    with _lock:
        rec = _ledger.get(request_id)
        if rec is None:
            return None
        return {
            "request_id": rec["request_id"],
            "tenant": rec["tenant"],
            "op": rec["op"],
            "phases_ms": {k: round(v * 1e3, 3)
                          for k, v in rec["phases"].items()},
            "billed": {
                "device_seconds": rec["billed"]["device_ns"] / 1e9,
                "flops": rec["billed"]["flops"],
                "bytes_moved": rec["billed"]["bytes_moved"],
                "pool_bytes": rec["billed"]["pool_bytes"],
            },
            "saved": {"flops": rec["saved"]["flops"],
                      "device_seconds": rec["saved"]["device_ns"] / 1e9},
            "cached": rec["cached"],
            "windows": rec["windows"],
            "resubmits": rec["resubmits"],
            "terminal": rec["terminal"],
        }


def usage(top: int = 5) -> dict:
    """Per-tenant usage rollup + top consumers (the `/usage` endpoint,
    the doctor's usage row, and `tools/usage_report.py` all read this
    shape)."""
    with _lock:
        tenants = {t: _row_view(row) for t, row in _tenants.items()}
        if _evicted:
            tenants[EVICTED] = _row_view(_evicted)
        totals = dict(_grand)
    totals["device_seconds"] = totals["device_ns"] / 1e9
    totals["saved_device_seconds"] = totals["saved_device_ns"] / 1e9
    ranked = sorted(tenants.items(),
                    key=lambda kv: kv[1]["device_ns"], reverse=True)
    return {
        "tenants": tenants,
        "top": [{"tenant": t,
                 "device_seconds": row["device_seconds"],
                 "flops": row["flops"],
                 "requests": row["requests"]}
                for t, row in ranked[:max(0, top)]],
        "totals": totals,
    }


def conservation() -> dict:
    """Both sides of the hard invariant, machine-readable:

    * ``tenant_sum`` — per-tenant billings summed (evicted fold
      included): MUST equal ``grand`` exactly (integers).
    * ``rollup`` — the live `core.stats`/mempool totals minus the
      baseline taken at the last `reset()`: ``grand`` flops/bytes MUST
      equal it exactly; device seconds match to the per-window ns
      quantization (``grand["windows"]`` nanoseconds at most) PLUS
      whatever the process executed OUTSIDE serve billing windows —
      the serve-only conservation tests keep that at zero.
    """
    with _lock:
        tenant_sum = _zero_row()
        rows = list(_tenants.values()) + ([_evicted] if _evicted else [])
        for row in rows:
            for k in tenant_sum:
                tenant_sum[k] += row[k]
        grand = dict(_grand)
    cur = _rollup_totals()
    return {
        "tenant_sum": tenant_sum,
        "grand": grand,
        "rollup": {
            "device_seconds": cur[0] - _baseline[0],
            "flops": cur[1] - _baseline[1],
            "bytes_moved": cur[2] - _baseline[2],
        },
    }


def ledger_size() -> int:
    with _lock:
        return len(_ledger)


def tenant_rows() -> int:
    with _lock:
        return len(_tenants)


def reset() -> None:
    """Clear the ledger, tenant rollups and grand totals, and
    re-baseline against the (freshly reset) engine rollup.  Wired into
    `metrics.reset(include_stats=True)` — same contract as the
    roofline/pool layers (docs/observability.md § Reset semantics)."""
    global _baseline
    with _lock:
        _ledger.clear()
        _tenants.clear()
        _evicted.clear()
        for k in _grand:
            _grand[k] = 0
        _baseline = _rollup_totals()
