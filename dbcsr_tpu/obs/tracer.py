"""Low-overhead span tracer with JSONL + Chrome-trace export.

The structured-observability analog of the reference's profiling hooks:
where DBCSR offers cachegrind callgraph export
(`dbcsr_timings_report.F:303`) and NVTX ranges
(`dbcsr_cuda_profiling.F`), this tracer records every `timed()` region
as a machine-readable span — name, start, duration, nesting depth,
process index, plus structured attributes attached mid-span by the hot
paths (mnk bin, driver decision, stack entries, comm bytes).

Two export formats from one event stream:

* **JSONL** — streamed to the trace path one event per line while the
  run executes (crash-safe: whatever completed is on disk).
* **Chrome ``trace_event`` JSON** — written on `flush()`/`disable()`
  (and atexit) next to the JSONL as ``<path>.chrome.json``; loads in
  Perfetto / ``chrome://tracing`` so host phases line up with device
  profiles captured by `jax.profiler` (the `timed()` regions carry the
  same names as their `TraceAnnotation` ranges).

**Multihost sharding**: the configured path is a BASE path — each
process writes its own shard ``<base>.p{process_index}.jsonl`` (for
``DBCSR_TPU_TRACE=trace.jsonl``: ``trace.p0.jsonl``, ``trace.p1.jsonl``,
...), so N processes pointed at one env value never interleave writes
into one file.  When the process index cannot be known yet (env
activation runs before the backend exists, and `jax.process_index()`
must never be forced — see `_process_index`), the shard opens under a
collision-proof provisional name and is atomically renamed to its
final ``p{index}`` name as soon as the index resolves — at
`init_multihost`'s barrier (which calls `rebind`), at the next
`flush()`, or at close (index 0 then).  `tools/trace_merge.py` merges
shards into one Perfetto-loadable trace with one track per process,
aligned on the ``clock_align`` instant `init_multihost` emits.

Activation: ``DBCSR_TPU_TRACE=<path>`` at import, or
`dbcsr_tpu.obs.enable_trace(path)`.  When inactive, the only cost at
every call site is one module-attribute ``is None`` check — the
off-path no-op contract the <2% multiply-overhead budget requires.

This module is deliberately stdlib-only: `core.timings` and
`core.stats` import it at module level, so it must not pull in jax or
any dbcsr_tpu module beyond `obs.shard` (itself stdlib-only — the one
sharding-contract implementation the tracer, the event bus and the
time-series store share).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time

from dbcsr_tpu.obs import shard as _shard

# bound on the in-memory event list backing the Chrome export; the
# JSONL stream is unbounded (it goes straight to disk)
_MAX_EVENTS = 500_000

# the active tracer, or None.  Hot paths check this single attribute.
_tracer = None
_lock = threading.Lock()


def _json_default(o):
    return str(o)


# the one sharding-contract implementation lives in obs.shard; these
# aliases keep the tracer's historical import surface working (the
# event bus, the obs server and init_multihost all read them here)
shard_path = _shard.shard_path


class Tracer:
    """One trace session: an open JSONL shard stream + the in-memory
    event list the Chrome export is built from.  ``path`` is the BASE
    path; the stream actually writes the per-process shard (see the
    module docstring)."""

    def __init__(self, path: str, chrome_path: str | None = None,
                 max_events: int = _MAX_EVENTS):
        self.base_path = path
        self.max_events = max_events
        self.events: list = []
        self.dropped = 0
        self._t0 = time.perf_counter()
        # span stack entries: [name, t_start_us, attrs_dict]
        self._span_stack: list = []
        # pid resolves lazily: at enable time (often import time, via
        # DBCSR_TPU_TRACE) the backend may not be up yet, and resolving
        # it must never force backend init — re-checked at flush() and
        # at init_multihost's rebind().  Until then the shard lives
        # under a collision-proof provisional name (hostname + OS pid:
        # multihost processes on a SHARED filesystem can collide on pid
        # alone): two processes sharing the env path must never
        # co-write one file, and a rename-in-place of a shared "p0"
        # would hijack the other process's open stream.
        pid = _process_index()
        self._pid_final = pid is not None
        self.process_index = pid or 0
        tag = pid if self._pid_final else _shard.provisional_tag()
        self.path = shard_path(path, tag)
        self.chrome_path = chrome_path or (self.path + ".chrome.json")
        self._chrome_path_forced = chrome_path is not None
        self._fh = open(self.path, "a")
        self._emit({
            "ev": "meta",
            "t0_unix": time.time(),
            "pid": self.process_index,
            "base_path": os.path.basename(path),
            "clock": "perf_counter_us_since_enable",
        })

    # -- clock ---------------------------------------------------------
    def now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    # -- span lifecycle (driven by core.timings) -----------------------
    def begin(self, name: str, t_us: float | None = None) -> None:
        self._span_stack.append(
            [name, self.now_us() if t_us is None else t_us, None]
        )

    def end(self, name: str, dur_s: float | None = None) -> None:
        if not self._span_stack:
            return
        ent = self._span_stack.pop()
        if ent[0] != name:
            # a mismatched stop (host hooks, reset mid-span): resync by
            # dropping silently rather than corrupting the trace
            return
        t_start = ent[1]
        dur_us = (dur_s * 1e6) if dur_s is not None else self.now_us() - t_start
        rec = {
            "ev": "span",
            "name": name,
            "ts_us": round(t_start, 1),
            "dur_us": round(dur_us, 1),
            "depth": len(self._span_stack),
            "pid": self.process_index,
            "tid": threading.get_ident() % 10**6,
        }
        if ent[2]:
            rec["attrs"] = ent[2]
        self._emit(rec)

    # -- attributes ----------------------------------------------------
    def annotate(self, **attrs) -> None:
        """Attach attributes to the innermost open span (no-op when no
        span is open)."""
        if not self._span_stack:
            return
        top = self._span_stack[-1]
        if top[2] is None:
            top[2] = {}
        top[2].update(attrs)

    def add(self, key: str, value) -> None:
        """Accumulate a numeric attribute onto the innermost open span
        (comm bytes, entry counts): repeated adds sum."""
        if not self._span_stack:
            return
        top = self._span_stack[-1]
        if top[2] is None:
            top[2] = {}
        top[2][key] = top[2].get(key, 0) + value

    def instant(self, name: str, args: dict | None = None) -> None:
        rec = {
            "ev": "instant",
            "name": name,
            "ts_us": round(self.now_us(), 1),
            "pid": self.process_index,
            "tid": threading.get_ident() % 10**6,
        }
        if args:
            rec["args"] = args
        self._emit(rec)

    # -- output --------------------------------------------------------
    def _emit(self, rec: dict) -> None:
        line = json.dumps(rec, default=_json_default)
        self._fh.write(line + "\n")
        if len(self.events) < self.max_events:
            self.events.append(rec)
        else:
            self.dropped += 1

    def _finalize_pid(self, pid: int | None = None,
                      force: bool = False) -> None:
        """Move a provisionally-named shard to its final
        ``p{process_index}`` name once the index is knowable.  ``pid``
        overrides discovery (init_multihost passes the joined world's
        index); ``force`` settles on index 0 when nothing ever
        resolved (single-process close)."""
        if self._pid_final:
            return
        if pid is None:
            pid = _process_index()
        if pid is None:
            if not force:
                return
            pid = 0
        self._pid_final = True
        self.process_index = int(pid)
        new_path, fh = _shard.settle(self.base_path, self.path, self._fh,
                                     int(pid))
        if fh is not self._fh:
            self._fh = fh
            self.path = new_path
            if not self._chrome_path_forced:
                self.chrome_path = new_path + ".chrome.json"
        # retro-stamp the in-memory events so the Chrome export puts
        # the whole shard on one consistent track; the JSONL lines
        # already written keep their provisional pid — the meta line
        # below is the shard's authoritative index for the merger
        for rec in self.events:
            rec["pid"] = self.process_index
        self._emit({"ev": "meta", "pid": self.process_index,
                    "note": "process index resolved"})

    def flush(self) -> None:
        """Flush the JSONL stream and (re)write the Chrome trace."""
        self._finalize_pid()
        self._fh.flush()
        write_chrome_trace(self.chrome_path, self.events,
                           dropped=self.dropped)

    def close(self) -> None:
        self._finalize_pid(force=True)
        if self.dropped:
            self._emit({"ev": "meta", "dropped_events": self.dropped})
        self.flush()
        self._fh.close()


# see obs.shard.process_index — never forces backend init
_process_index = _shard.process_index


def chrome_events(events: list) -> list:
    """Map the native event records onto Chrome ``trace_event`` dicts
    (the `X` complete-event / `i` instant-event subset Perfetto loads)."""
    out = []
    for rec in events:
        ev = rec.get("ev")
        if ev == "span":
            ce = {
                "name": rec["name"],
                "cat": "dbcsr_tpu",
                "ph": "X",
                "ts": rec["ts_us"],
                "dur": rec["dur_us"],
                "pid": rec["pid"],
                "tid": rec.get("tid", 0),
            }
            if rec.get("attrs"):
                ce["args"] = rec["attrs"]
            out.append(ce)
        elif ev == "instant":
            ce = {
                "name": rec["name"],
                "cat": "dbcsr_tpu",
                "ph": "i",
                "s": "t",
                "ts": rec["ts_us"],
                "pid": rec["pid"],
                "tid": rec.get("tid", 0),
            }
            if rec.get("args"):
                ce["args"] = rec["args"]
            out.append(ce)
    return out


def write_chrome_trace(path: str, events: list, dropped: int = 0) -> None:
    doc = {
        "traceEvents": chrome_events(events),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "dbcsr_tpu.obs.tracer",
                      "dropped_events": dropped},
    }
    with open(path, "w") as f:
        json.dump(doc, f, default=_json_default)


# -- module-level API (what timings/stats/hot paths call) --------------

def enable(path: str | None = None) -> Tracer:
    """Start tracing to ``path`` (default: $DBCSR_TPU_TRACE).  Replaces
    any active tracer (the old one is closed).  ``path`` is the shard
    BASE: the stream lands in ``<path base>.p{process_index}<ext>``
    (see the module docstring); read the actual file from the returned
    tracer's ``.path``."""
    global _tracer
    path = path or os.environ.get("DBCSR_TPU_TRACE")
    if not path:
        raise ValueError(
            "no trace path: pass one or set DBCSR_TPU_TRACE")
    with _lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = Tracer(path)
    return _tracer


def disable() -> None:
    """Stop tracing; flushes the JSONL stream and writes the Chrome
    trace next to it."""
    global _tracer
    with _lock:
        if _tracer is not None:
            _tracer.close()
            _tracer = None


def active() -> bool:
    return _tracer is not None


def rebind(process_index: int | None = None) -> None:
    """Settle the active shard onto its final ``p{index}`` name (no-op
    when tracing is off or the index already resolved).  Called by
    `parallel.multihost.init_multihost` right after the world forms,
    with the joined world's process index."""
    t = _tracer
    if t is not None:
        t._finalize_pid(pid=process_index)


def get() -> Tracer | None:
    return _tracer


def annotate(**attrs) -> None:
    t = _tracer
    if t is not None:
        t.annotate(**attrs)


def add(key: str, value) -> None:
    t = _tracer
    if t is not None:
        t.add(key, value)


def instant(name: str, args: dict | None = None) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, args)


@atexit.register
def _atexit_flush() -> None:  # pragma: no cover - process teardown
    t = _tracer
    if t is not None:
        try:
            t.close()
        except Exception:
            pass


# env activation: DBCSR_TPU_TRACE set at import time starts the session
# immediately, so `DBCSR_TPU_TRACE=t.jsonl python -m dbcsr_tpu.perf...`
# needs no code changes anywhere
if os.environ.get("DBCSR_TPU_TRACE"):
    enable(os.environ["DBCSR_TPU_TRACE"])
