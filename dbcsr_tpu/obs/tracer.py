"""Low-overhead span tracer with JSONL + Chrome-trace export.

The structured-observability analog of the reference's profiling hooks:
where DBCSR offers cachegrind callgraph export
(`dbcsr_timings_report.F:303`) and NVTX ranges
(`dbcsr_cuda_profiling.F`), this tracer records every `timed()` region
as a machine-readable span — name, start, duration, nesting depth,
process index, plus structured attributes attached mid-span by the hot
paths (mnk bin, driver decision, stack entries, comm bytes).

Two export formats from one event stream:

* **JSONL** — streamed to the trace path one event per line while the
  run executes (crash-safe: whatever completed is on disk).
* **Chrome ``trace_event`` JSON** — written on `flush()`/`disable()`
  (and atexit) next to the JSONL as ``<path>.chrome.json``; loads in
  Perfetto / ``chrome://tracing`` so host phases line up with device
  profiles captured by `jax.profiler` (the `timed()` regions carry the
  same names as their `TraceAnnotation` ranges).

Activation: ``DBCSR_TPU_TRACE=<path>`` at import, or
`dbcsr_tpu.obs.enable_trace(path)`.  When inactive, the only cost at
every call site is one module-attribute ``is None`` check — the
off-path no-op contract the <2% multiply-overhead budget requires.

This module is deliberately stdlib-only: `core.timings` and
`core.stats` import it at module level, so it must not pull in any
dbcsr_tpu (or jax) module.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time

# bound on the in-memory event list backing the Chrome export; the
# JSONL stream is unbounded (it goes straight to disk)
_MAX_EVENTS = 500_000

# the active tracer, or None.  Hot paths check this single attribute.
_tracer = None
_lock = threading.Lock()


def _json_default(o):
    return str(o)


class Tracer:
    """One trace session: an open JSONL stream + the in-memory event
    list the Chrome export is built from."""

    def __init__(self, path: str, chrome_path: str | None = None,
                 max_events: int = _MAX_EVENTS):
        self.path = path
        self.chrome_path = chrome_path or (path + ".chrome.json")
        self.max_events = max_events
        self.events: list = []
        self.dropped = 0
        self._t0 = time.perf_counter()
        # span stack entries: [name, t_start_us, attrs_dict]
        self._span_stack: list = []
        # pid resolves lazily: at enable time (often import time, via
        # DBCSR_TPU_TRACE) the backend may not be up yet, and resolving
        # it must never force backend init — re-checked at flush()
        pid = _process_index()
        self._pid_final = pid is not None
        self.process_index = pid or 0
        self._fh = open(path, "a")
        self._emit({
            "ev": "meta",
            "t0_unix": time.time(),
            "pid": self.process_index,
            "clock": "perf_counter_us_since_enable",
        })

    # -- clock ---------------------------------------------------------
    def now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    # -- span lifecycle (driven by core.timings) -----------------------
    def begin(self, name: str, t_us: float | None = None) -> None:
        self._span_stack.append(
            [name, self.now_us() if t_us is None else t_us, None]
        )

    def end(self, name: str, dur_s: float | None = None) -> None:
        if not self._span_stack:
            return
        ent = self._span_stack.pop()
        if ent[0] != name:
            # a mismatched stop (host hooks, reset mid-span): resync by
            # dropping silently rather than corrupting the trace
            return
        t_start = ent[1]
        dur_us = (dur_s * 1e6) if dur_s is not None else self.now_us() - t_start
        rec = {
            "ev": "span",
            "name": name,
            "ts_us": round(t_start, 1),
            "dur_us": round(dur_us, 1),
            "depth": len(self._span_stack),
            "pid": self.process_index,
            "tid": threading.get_ident() % 10**6,
        }
        if ent[2]:
            rec["attrs"] = ent[2]
        self._emit(rec)

    # -- attributes ----------------------------------------------------
    def annotate(self, **attrs) -> None:
        """Attach attributes to the innermost open span (no-op when no
        span is open)."""
        if not self._span_stack:
            return
        top = self._span_stack[-1]
        if top[2] is None:
            top[2] = {}
        top[2].update(attrs)

    def add(self, key: str, value) -> None:
        """Accumulate a numeric attribute onto the innermost open span
        (comm bytes, entry counts): repeated adds sum."""
        if not self._span_stack:
            return
        top = self._span_stack[-1]
        if top[2] is None:
            top[2] = {}
        top[2][key] = top[2].get(key, 0) + value

    def instant(self, name: str, args: dict | None = None) -> None:
        rec = {
            "ev": "instant",
            "name": name,
            "ts_us": round(self.now_us(), 1),
            "pid": self.process_index,
            "tid": threading.get_ident() % 10**6,
        }
        if args:
            rec["args"] = args
        self._emit(rec)

    # -- output --------------------------------------------------------
    def _emit(self, rec: dict) -> None:
        line = json.dumps(rec, default=_json_default)
        self._fh.write(line + "\n")
        if len(self.events) < self.max_events:
            self.events.append(rec)
        else:
            self.dropped += 1

    def flush(self) -> None:
        """Flush the JSONL stream and (re)write the Chrome trace."""
        if not self._pid_final:
            pid = _process_index()
            if pid is not None:
                self._pid_final = True
                if pid != self.process_index:
                    self.process_index = pid  # events from here on
                    self._emit({"ev": "meta", "pid": pid,
                                "note": "process index resolved late"})
        self._fh.flush()
        write_chrome_trace(self.chrome_path, self.events,
                           dropped=self.dropped)

    def close(self) -> None:
        if self.dropped:
            self._emit({"ev": "meta", "dropped_events": self.dropped})
        self.flush()
        self._fh.close()


def _process_index() -> int | None:
    """jax process index when a backend is ALREADY initialized; None
    otherwise.  Calling `jax.process_index()` would itself initialize
    the backend — on a wedged axon tunnel that hangs the bare import,
    and in multi-process runs it races `jax.distributed.initialize()` —
    so only consult it once the backend registry is provably populated
    (best-effort peek at xla_bridge's cache; falls back to None)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return None  # no backend up yet: do NOT force one
    try:
        return int(jax.process_index())
    except Exception:
        return None


def chrome_events(events: list) -> list:
    """Map the native event records onto Chrome ``trace_event`` dicts
    (the `X` complete-event / `i` instant-event subset Perfetto loads)."""
    out = []
    for rec in events:
        ev = rec.get("ev")
        if ev == "span":
            ce = {
                "name": rec["name"],
                "cat": "dbcsr_tpu",
                "ph": "X",
                "ts": rec["ts_us"],
                "dur": rec["dur_us"],
                "pid": rec["pid"],
                "tid": rec.get("tid", 0),
            }
            if rec.get("attrs"):
                ce["args"] = rec["attrs"]
            out.append(ce)
        elif ev == "instant":
            ce = {
                "name": rec["name"],
                "cat": "dbcsr_tpu",
                "ph": "i",
                "s": "t",
                "ts": rec["ts_us"],
                "pid": rec["pid"],
                "tid": rec.get("tid", 0),
            }
            if rec.get("args"):
                ce["args"] = rec["args"]
            out.append(ce)
    return out


def write_chrome_trace(path: str, events: list, dropped: int = 0) -> None:
    doc = {
        "traceEvents": chrome_events(events),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "dbcsr_tpu.obs.tracer",
                      "dropped_events": dropped},
    }
    with open(path, "w") as f:
        json.dump(doc, f, default=_json_default)


# -- module-level API (what timings/stats/hot paths call) --------------

def enable(path: str | None = None) -> Tracer:
    """Start tracing to ``path`` (default: $DBCSR_TPU_TRACE).  Replaces
    any active tracer (the old one is closed)."""
    global _tracer
    path = path or os.environ.get("DBCSR_TPU_TRACE")
    if not path:
        raise ValueError(
            "no trace path: pass one or set DBCSR_TPU_TRACE")
    with _lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = Tracer(path)
    return _tracer


def disable() -> None:
    """Stop tracing; flushes the JSONL stream and writes the Chrome
    trace next to it."""
    global _tracer
    with _lock:
        if _tracer is not None:
            _tracer.close()
            _tracer = None


def active() -> bool:
    return _tracer is not None


def get() -> Tracer | None:
    return _tracer


def annotate(**attrs) -> None:
    t = _tracer
    if t is not None:
        t.annotate(**attrs)


def add(key: str, value) -> None:
    t = _tracer
    if t is not None:
        t.add(key, value)


def instant(name: str, args: dict | None = None) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, args)


@atexit.register
def _atexit_flush() -> None:  # pragma: no cover - process teardown
    t = _tracer
    if t is not None:
        try:
            t.close()
        except Exception:
            pass


# env activation: DBCSR_TPU_TRACE set at import time starts the session
# immediately, so `DBCSR_TPU_TRACE=t.jsonl python -m dbcsr_tpu.perf...`
# needs no code changes anywhere
if os.environ.get("DBCSR_TPU_TRACE"):
    enable(os.environ["DBCSR_TPU_TRACE"])
