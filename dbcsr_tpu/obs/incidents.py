"""Anomaly-triggered incident capture: the automatic black-box export.

When a health detector or an SLO objective fires its RISING edge, the
process should not depend on someone having exported ``DBCSR_TPU_
TRACE``/``DBCSR_TPU_EVENTS`` in advance to reconstruct what happened.
This module persists a bounded, rate-limited **incident bundle** —
the recent events ring, the flight-recorder ring, the forced
timeseries sample the edge requested, the health verdict and the
tenant usage rollup — as one JSONL file `tools/doctor.py --bundle`
renders offline.

Deferred capture (the same convention as `timeseries.request_sample`):
`trigger()` only arms a flag — `health._fire` invokes it while holding
the health lock on the roofline path, and assembling a bundle calls
`health.verdict()`/the collectors, which would deadlock there.  The
bundle is assembled by `on_sample()`, called from the tail of
`timeseries.sample()` at the next safe boundary (product end / serve
admission) — which is also exactly when the edge's forced sample
materializes, so the bundle carries it.

Rate limiting: at most ``DBCSR_TPU_INCIDENT_N`` bundles per process
(default 8), no closer than ``DBCSR_TPU_INCIDENT_INTERVAL_S`` apart
(default 60 s) — a storm of edges costs one bundle, counted in
``dbcsr_tpu_incident_bundles_total{result=captured|suppressed}``.
Persistence: ``DBCSR_TPU_INCIDENTS`` names the bundle directory
(default ``incidents/`` under the working directory, git-ignored);
``0`` keeps bundles in memory only (`bundles()`).

Module-level imports are stdlib-only; every collected layer is reached
lazily and guarded — a broken collector costs that section, never the
bundle.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

_lock = threading.Lock()
_pending: "str | None" = None
_pending_args: dict = {}
_last_capture = 0.0
_count = 0
_bundles: list = []  # in-memory ring of (reason, path, bundle) dicts
_BUNDLE_RING = 8
_EVENTS_TAIL = 256


def _dir() -> "str | None":
    v = os.environ.get("DBCSR_TPU_INCIDENTS", "")
    if v == "0":
        return None
    return v or "incidents"


def _interval_s() -> float:
    try:
        return float(os.environ.get("DBCSR_TPU_INCIDENT_INTERVAL_S", "60"))
    except ValueError:
        return 60.0


def _max_bundles() -> int:
    try:
        return int(os.environ.get("DBCSR_TPU_INCIDENT_N", "8"))
    except ValueError:
        return 8


def _counter(result: str) -> None:
    try:
        from dbcsr_tpu.obs import metrics as _metrics

        _metrics.counter(
            "dbcsr_tpu_incident_bundles_total",
            "anomaly/SLO-edge incident captures by result "
            "(captured = bundle assembled, suppressed = rate-limited)",
        ).inc(result=result)
    except Exception:
        pass


def trigger(reason: str, args: dict | None = None) -> bool:
    """Arm an incident capture for a rising edge.  Safe to call under
    the health/SLO locks: only sets a flag (plus one counter inc).
    Returns True when armed, False when rate-limited away."""
    global _pending, _pending_args
    now = time.time()
    with _lock:
        if _count >= _max_bundles() or (now - _last_capture
                                        < _interval_s()):
            limited = True
        else:
            limited = False
            if _pending is None:
                _pending = str(reason)
                _pending_args = dict(args or {})
    if limited:
        _counter("suppressed")
    return not limited


def on_sample(sample_rec: dict | None) -> "str | None":
    """Capture boundary (tail of `timeseries.sample()`, no store lock
    held): when a trigger is armed, assemble + persist the bundle.
    Returns the bundle path (None when nothing was armed or
    persistence is off)."""
    global _pending, _pending_args, _last_capture, _count
    with _lock:
        if _pending is None:
            return None
        reason, args = _pending, _pending_args
        _pending, _pending_args = None, {}
        _last_capture = time.time()
        _count += 1
        seq = _count
    bundle = _assemble(reason, args, sample_rec)
    path = _persist(bundle, reason, seq)
    with _lock:
        _bundles.append({"reason": reason, "path": path,
                         "bundle": bundle})
        del _bundles[:-_BUNDLE_RING]
    _counter("captured")
    try:
        from dbcsr_tpu.obs import events as _events

        _events.publish("incident_captured",
                        {"reason": reason, "path": path or ""})
    except Exception:
        pass
    return path


def _assemble(reason: str, args: dict, sample_rec) -> dict:
    """One bundle dict; every layer guarded so a broken collector
    costs its section, not the capture."""
    bundle = {
        "meta": {"kind": "incident", "reason": reason,
                 "args": {k: str(v) for k, v in (args or {}).items()},
                 "t_unix": time.time(), "pid": os.getpid()},
        "sample": sample_rec,
    }
    try:
        from dbcsr_tpu.obs import health as _health

        bundle["health"] = _health.verdict()
    except Exception:
        pass
    try:
        from dbcsr_tpu.obs import events as _events

        bundle["events"] = _events.records(limit=_EVENTS_TAIL)
    except Exception:
        pass
    try:
        from dbcsr_tpu.obs import flight as _flight

        bundle["flight"] = _flight.records()
    except Exception:
        pass
    try:
        from dbcsr_tpu.obs import attribution as _attr

        bundle["usage"] = _attr.usage()
    except Exception:
        pass
    try:
        from dbcsr_tpu.obs import rca as _rca

        reps = _rca.reports(limit=1)
        if reps:
            bundle["rca"] = reps[-1]
    except Exception:
        pass
    return bundle


def _persist(bundle: dict, reason: str, seq: int) -> "str | None":
    """Write the bundle as typed JSONL lines (``rec`` discriminator:
    meta / health / sample / usage / event / flight) — the shape
    `tools/doctor.py --bundle` consumes."""
    base = _dir()
    if base is None:
        return None
    tag = re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:48] or "incident"
    path = os.path.join(base,
                        f"incident-{tag}-{os.getpid()}-{seq}.jsonl")
    try:
        os.makedirs(base, exist_ok=True)
        with open(path, "w") as fh:
            fh.write(json.dumps(dict(bundle["meta"], rec="meta"),
                                default=str) + "\n")
            for key in ("health", "sample", "usage", "rca"):
                if bundle.get(key) is not None:
                    fh.write(json.dumps({"rec": key, key: bundle[key]},
                                        default=str) + "\n")
            for ev in bundle.get("events") or []:
                fh.write(json.dumps(dict(ev, rec="event"),
                                    default=str) + "\n")
            for fr in bundle.get("flight") or []:
                fh.write(json.dumps(dict(fr, rec="flight"),
                                    default=str) + "\n")
    except Exception:
        return None  # persistence must never break the boundary
    return path


def bundles() -> list:
    """In-memory ring of the bundles captured this process (newest
    last): [{"reason", "path", "bundle"}]."""
    with _lock:
        return list(_bundles)


def pending() -> "str | None":
    with _lock:
        return _pending


def reset() -> None:
    """Clear armed triggers, the capture budget and the in-memory
    ring (wired into `metrics.reset(include_stats=True)` alongside the
    attribution layer)."""
    global _pending, _pending_args, _last_capture, _count
    with _lock:
        _pending, _pending_args = None, {}
        _last_capture = 0.0
        _count = 0
        del _bundles[:]
