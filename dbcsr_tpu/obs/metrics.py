"""Counter/gauge/histogram registry with snapshot + Prometheus export.

Layered over `core/stats` (the reference's STATISTICS block,
`dbcsr_mm_sched.F:390-546`): `snapshot()` folds the raw per-(m,n,k)
flop counters, collective-traffic counters and memory meters into one
machine-readable dict, alongside metrics this module owns directly —
most importantly the **JIT-recompile counters**: every stack-kernel
launch reports its specialization key via `record_jit()`, so each
jitted hot function exposes how many distinct XLA compilations it
triggered versus how often it reused one.  A stack-plan or jit-cache
churn problem (new (m,n,k)/bucket shapes arriving every multiply) is
invisible in wall time until it dominates; here it is a counter.

Label model: each metric holds values keyed by a sorted
``(label, value)`` tuple — enough for Prometheus text exposition
without pulling in a client library (the container has none; the
export format is the stable contract, see `prometheus_text()`).

Module-level imports are stdlib-only (`core.stats` is imported lazily
inside `snapshot`): `acc.smm` imports this module on its hot path.
"""

from __future__ import annotations

import json
import threading

from dbcsr_tpu.obs import events as _events
from dbcsr_tpu.obs import tracer as _trace

_lock = threading.Lock()


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    """Monotone counter with optional labels."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.values: dict = {}

    def inc(self, n: float = 1, **labels) -> None:
        key = _label_key(labels)
        self.values[key] = self.values.get(key, 0) + n

    def value(self, **labels) -> float:
        return self.values.get(_label_key(labels), 0)


class Gauge:
    """Point-in-time value with optional labels."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.values: dict = {}

    def set(self, v: float, **labels) -> None:
        self.values[_label_key(labels)] = v

    def value(self, **labels) -> float:
        return self.values.get(_label_key(labels), 0)


class Histogram:
    """Fixed-bucket histogram (cumulative counts, Prometheus ``le``
    convention) + running sum/count."""

    DEFAULT_BUCKETS = (1e-4, 1e-3, 1e-2, 0.1, 0.5, 1.0, 5.0, 30.0)

    def __init__(self, name: str, help: str = "", buckets=None):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.values: dict = {}  # label key -> [counts per bucket, +inf]
        self.sums: dict = {}
        self.counts: dict = {}

    def observe(self, v: float, **labels) -> None:
        key = _label_key(labels)
        counts = self.values.setdefault(key, [0] * (len(self.buckets) + 1))
        for i, b in enumerate(self.buckets):
            if v <= b:
                counts[i] += 1
        counts[-1] += 1
        self.sums[key] = self.sums.get(key, 0.0) + v
        self.counts[key] = self.counts.get(key, 0) + 1


_counters: dict = {}
_gauges: dict = {}
_histograms: dict = {}
# per-fn specialization keys already seen (the jit-cache mirror)
_jit_seen: dict = {}


def counter(name: str, help: str = "") -> Counter:
    with _lock:
        c = _counters.get(name)
        if c is None:
            c = _counters[name] = Counter(name, help)
        return c


def counter_items(name: str) -> list:
    """Public enumeration of one counter's ``(labels_dict, value)``
    pairs — the supported way to read a labelled counter back out
    without binding to the registry's internal label-key encoding.
    Empty when the counter never incremented."""
    with _lock:
        c = _counters.get(name)
        if c is None:
            return []
        return [(dict(k), float(v)) for k, v in c.values.items()]


def gauge(name: str, help: str = "") -> Gauge:
    with _lock:
        g = _gauges.get(name)
        if g is None:
            g = _gauges[name] = Gauge(name, help)
        return g


def histogram(name: str, help: str = "", buckets=None) -> Histogram:
    with _lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = Histogram(name, help, buckets)
        return h


def record_jit(fn: str, key) -> bool:
    """Report one launch of jitted function ``fn`` specialized by
    ``key`` (shapes/dtype/static args — whatever keys its jit cache).
    First sighting of a key counts as a compile, every later launch as
    a cache hit.  Returns True when this launch compiled.

    The mirror can only over-count compiles (e.g. after an external
    `jax.clear_caches()` the real cache recompiles while the mirror
    still records hits is the one way it under-counts; a process sees
    that rarely enough that the counter stays a faithful churn signal).
    """
    seen = _jit_seen.setdefault(fn, set())
    if key in seen:
        counter("dbcsr_tpu_jit_cache_hits_total",
                "stack-kernel launches served by an existing XLA "
                "specialization").inc(fn=fn)
        return False
    seen.add(key)
    counter("dbcsr_tpu_jit_compiles_total",
            "distinct XLA specializations triggered per jitted hot "
            "function").inc(fn=fn)
    # compiles also land on the event bus (product-correlated: "which
    # multiply triggered this recompile") and in the trace stream, so
    # tools/trace_summary.py can rank recompile offenders from the
    # JSONL alone — one publish feeds both
    _events.publish("jit_compile", {"fn": fn, "key": str(key)})
    return True


def jit_stats() -> dict:
    """{fn: {"compiles": n, "cache_hits": n}} for every function that
    reported through `record_jit`."""
    comp = _counters.get("dbcsr_tpu_jit_compiles_total")
    hits = _counters.get("dbcsr_tpu_jit_cache_hits_total")
    out: dict = {}
    for c, field in ((comp, "compiles"), (hits, "cache_hits")):
        if c is None:
            continue
        for key, v in c.values.items():
            fn = dict(key).get("fn", "?")
            out.setdefault(fn, {"compiles": 0, "cache_hits": 0})[field] = v
    return out


def reset(include_stats: bool = True) -> None:
    """Clear the metric registries and the jit-recompile mirror.

    ``include_stats`` (default True) also resets the `core.stats`
    registries this module snapshots (per-(m,n,k) flops, comm traffic,
    driver rollups, memory meters) and the `costmodel` XLA-cost
    captures — so ``reset(); snapshot()`` reports a truly fresh state.
    Pass ``include_stats=False`` to clear only the obs-owned metrics
    while keeping the engine's cumulative statistics (e.g. to re-window
    counters mid-run without losing the STATISTICS block)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _histograms.clear()
        _jit_seen.clear()
    # mempool caches Counter OBJECTS for its hot-path increments: after
    # the registry is cleared those objects are orphaned (increments
    # would vanish from scrapes), so the cache must drop with the
    # registry — on BOTH include_stats settings
    try:
        import sys

        mp = sys.modules.get("dbcsr_tpu.core.mempool")
        if mp is not None:
            mp._metric_cache.clear()
    except Exception:
        pass
    if include_stats:
        from dbcsr_tpu.core import stats
        from dbcsr_tpu.obs import costmodel

        stats.reset()
        costmodel.reset()
        try:
            from dbcsr_tpu.core import mempool

            mempool.reset_stats()
        except Exception:
            pass  # jax-free contexts (doctor --selftest parses only)
        # the attribution ledger and the incident-capture budget follow
        # the same include_stats contract (docs/observability.md §
        # Reset semantics) — AFTER stats.reset() above, so the
        # attribution re-baseline snapshots the freshly zeroed rollup
        try:
            import sys as _sys

            _attr = _sys.modules.get("dbcsr_tpu.obs.attribution")
            if _attr is not None:
                _attr.reset()
            _inc = _sys.modules.get("dbcsr_tpu.obs.incidents")
            if _inc is not None:
                _inc.reset()
            # the causal diagnosis plane joins the same contract: a
            # full reset drops profile epochs, detector baselines and
            # the change ledger; a metric re-window keeps them
            for name in ("dbcsr_tpu.obs.profiler",
                         "dbcsr_tpu.obs.changepoint",
                         "dbcsr_tpu.obs.rca"):
                mod = _sys.modules.get(name)
                if mod is not None:
                    mod.reset()
        except Exception:
            pass


def _roofline_rollup() -> dict:
    """Per-driver roofline attribution from `core.stats.driver_rollup`
    + the `costmodel` peak table, refreshing the ``dbcsr_tpu_*`` gauges
    as a side effect so scrapes and snapshots agree.  Every driver
    that executed since the last reset gets an entry; seconds are
    dispatch-side wall time (see `stats.record_driver`)."""
    from dbcsr_tpu.core import stats
    from dbcsr_tpu.obs import costmodel

    kind = costmodel.device_kind()
    out: dict = {}
    for driver, agg in sorted(stats.driver_rollup().items()):
        dtype = max(agg["by_dtype"], key=agg["by_dtype"].get) \
            if agg["by_dtype"] else "float64"
        rl = costmodel.roofline(agg["flops"], agg["bytes"],
                                agg["seconds"], kind=kind, dtype=dtype)
        rl["stacks"] = agg["stacks"]
        # sync=true only when EVERY recorded region was timed through
        # block_until_ready (DBCSR_TPU_SYNC_TIMING at record time) —
        # a mixed aggregate must not present async dispatch rates as
        # device-completion rates
        rl["sync"] = bool(agg["stacks"]) and (
            agg["sync_stacks"] == agg["stacks"])
        out[driver] = rl
        gauge("dbcsr_tpu_achieved_gflops",
              "flops / dispatch seconds per stack driver").set(
            rl["achieved_gflops"], driver=driver)
        gauge("dbcsr_tpu_roofline_fraction",
              "achieved rate / attainable roofline rate per driver "
              "(min(peak compute, intensity*bandwidth) denominator)"
              ).set(rl["roofline_fraction"], driver=driver)
        gauge("dbcsr_tpu_arithmetic_intensity",
              "modeled flops per HBM byte per driver").set(
            rl["arithmetic_intensity"], driver=driver)
    # Cannon tick-loop overlap attribution rides on the owning driver's
    # row (engine "mesh" -> driver "mesh", engine "dense" -> "dense"):
    # per grid, the MODELED comm/compute ratio next to the MEASURED
    # comm-exposed fraction (parallel/overlap.py, DBCSR_TPU_SYNC_TIMING).
    # A standalone dense Cannon (cannon_multiply_dense called directly,
    # no record_stack row) still surfaces: its attribution lands in a
    # cannon_overlap-only row rather than being dropped.
    for engine, grids in stats.cannon_overlap_rollup().items():
        out.setdefault(engine, {})["cannon_overlap"] = grids
    return out


def _stats_snapshot() -> dict:
    """Fold core.stats' registries into plain dicts (per-driver flops,
    per-(m,n,k) stack counts, collective traffic, memory meters)."""
    from dbcsr_tpu.core import stats

    by_driver: dict = {}
    by_mnk = {}
    for (m, n, k), st in stats._by_mnk.items():
        by_mnk[f"{m}x{n}x{k}"] = {
            "stacks": st.nstacks,
            "entries": st.nentries,
            "flops": st.flops,
            "by_driver": dict(st.by_driver),
        }
        for d, f in st.by_driver.items():
            by_driver[d] = by_driver.get(d, 0) + f
    comm = {
        kind: {"messages": st.nmessages, "bytes": st.nbytes}
        for kind, st in stats._comm.items()
    }
    return {
        "flops_by_driver": by_driver,
        "by_mnk": by_mnk,
        "comm": comm,
        "totals": dict(stats._totals),
        "memory": stats.memory_high_water(),
    }


def snapshot() -> dict:
    """One machine-readable dict of everything observable right now:
    the core.stats layers + the roofline attribution rollup + this
    registry's own metrics + the jit-recompile mirror (+ captured XLA
    cost analyses when `costmodel` capture is on)."""
    from dbcsr_tpu.obs import costmodel

    def expand(metrics):
        return {
            name: {json.dumps(dict(k)): v for k, v in m.values.items()}
            for name, m in metrics.items()
        }

    snap = _stats_snapshot()
    # refresh the roofline gauges BEFORE expanding the gauge registry
    # so the snapshot's "gauges" section carries them too
    snap["roofline"] = _roofline_rollup()
    snap["device_kind"] = costmodel.device_kind()
    try:
        from dbcsr_tpu.core import mempool

        snap["pool"] = mempool.pool_stats()
        snap["transfer"] = mempool.transfer_totals()
    except Exception:
        pass  # jax-free contexts
    xc = costmodel.xla_costs()
    if xc:
        snap["xla_cost"] = xc
    snap["counters"] = expand(_counters)
    snap["gauges"] = expand(_gauges)
    snap["histograms"] = {
        name: {
            json.dumps(dict(k)): {
                "buckets": dict(zip([str(b) for b in h.buckets] + ["+Inf"],
                                    v)),
                "sum": h.sums.get(k, 0.0),
                "count": h.counts.get(k, 0),
            }
            for k, v in h.values.items()
        }
        for name, h in _histograms.items()
    }
    snap["jit"] = jit_stats()
    return snap


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def prometheus_text() -> str:
    """Prometheus text exposition (v0.0.4) of the full snapshot —
    registry metrics plus the core.stats layers rendered as
    ``dbcsr_tpu_*`` families."""
    from dbcsr_tpu.core import stats

    _roofline_rollup()  # refresh the roofline gauges before rendering
    lines: list = []

    def emit(name, kind, help, values):
        lines.append(f"# HELP {name} {help}")
        lines.append(f"# TYPE {name} {kind}")
        for key, v in values:
            lines.append(f"{name}{_fmt_labels(key)} {v}")

    # core.stats layers
    by_driver: dict = {}
    for st in stats._by_mnk.values():
        for d, f in st.by_driver.items():
            by_driver[d] = by_driver.get(d, 0) + f
    emit("dbcsr_tpu_flops_total", "counter",
         "true flops per stack driver",
         [((("driver", d),), f) for d, f in sorted(by_driver.items())])
    emit("dbcsr_tpu_comm_bytes_total", "counter",
         "collective traffic bytes per collective kind",
         [((("kind", k),), st.nbytes) for k, st in sorted(stats._comm.items())])
    emit("dbcsr_tpu_comm_messages_total", "counter",
         "collective message counts per collective kind",
         [((("kind", k),), st.nmessages)
          for k, st in sorted(stats._comm.items())])
    emit("dbcsr_tpu_multiplies_total", "counter",
         "multiply() invocations",
         [((), stats._totals["multiplies"])])
    emit("dbcsr_tpu_memory_bytes", "gauge",
         "host/device memory meters (peak and current)",
         [((("meter", k),), v)
          for k, v in sorted(stats.memory_high_water().items())])
    # registry metrics
    for name, c in sorted(_counters.items()):
        emit(name, "counter", c.help or name, sorted(c.values.items()))
    for name, g in sorted(_gauges.items()):
        emit(name, "gauge", g.help or name, sorted(g.values.items()))
    for name, h in sorted(_histograms.items()):
        lines.append(f"# HELP {name} {h.help or name}")
        lines.append(f"# TYPE {name} histogram")
        for key, counts in sorted(h.values.items()):
            for b, cnt in zip([str(b) for b in h.buckets] + ["+Inf"], counts):
                lines.append(
                    f"{name}_bucket{_fmt_labels(key + (('le', b),))} {cnt}")
            lines.append(f"{name}_sum{_fmt_labels(key)} {h.sums.get(key, 0.0)}")
            lines.append(f"{name}_count{_fmt_labels(key)} {h.counts.get(key, 0)}")
    return "\n".join(lines) + "\n"
