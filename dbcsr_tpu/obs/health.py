"""Health model: fold the engine's live signals into per-component
OK / DEGRADED / CRITICAL verdicts with machine-readable reasons.

The serving-stack counterpart of the reference's end-of-run report:
where `dbcsr_print_statistics` answers "what did this run do" after
the fact, `verdict()` answers "is this process healthy NOW" — the JSON
behind `obs.server`'s ``/healthz`` and the table `tools/doctor.py`
prints.

**Components**

* ``drivers`` — circuit-breaker board state (`resilience.breaker`):
  any open/half-open breaker degrades; an open breaker on the safe
  ``xla`` driver (the chain's backstop) or ≥4 concurrently open
  breakers is critical.
* ``watchdog`` — wedge streaks per guarded channel
  (`dbcsr_tpu_watchdog_wedge_streak`): streak ≥1 degrades, ≥3 critical
  (the capture loop's backoff has reached hours by then).
* ``engine`` — proven numeric corruption (checksum retries classified
  ``deterministic``/``unstable``) is critical; a degraded-to-serial
  world join or an active fallback/recompile storm degrades.
* ``perf`` — an active roofline-collapse anomaly degrades, as does
  memory-pool thrash (budget evictions while checkouts still miss —
  the pool's byte budget is below the chain's working set) and an
  active serving-plane shed storm; the per-driver roofline fractions
  and pool counters ride along.
* ``integrity`` — the end-to-end data-integrity plane (`acc.abft` +
  `models.integrity`): any ABFT probe mismatch (detected silent data
  corruption) or chain-invariant rollback degrades — the answer was
  healed, but the hardware produced a wrong finite result.  CRITICAL
  is reserved for corruption that ESCAPED recovery (mismatches
  exceeding recoveries) when repeated — from one driver at
  ``DBCSR_TPU_HEALTH_SDC_CRITICAL`` = 3 mismatches, or 3 unrecovered
  in total; fully-recovered SDC storms stay DEGRADED, the breaker
  owns quarantining the offending driver (docs/resilience.md
  § Runbook: silent data corruption).

**Anomaly detectors** (rolling windows over the last
``DBCSR_TPU_HEALTH_WINDOW`` = 64 multiplies, fed by
`events.end_product`; noise convention = `tools/perf_gate.py`'s
median/MAD):

* ``recompile_storm`` — fresh XLA specializations per multiply over
  the window exceed 0.5 (steady state is ~0: the jit caches absorb
  repeats; a storm means shape churn is recompiling every multiply).
* ``fallback_storm`` — chain failovers per multiply over the window
  exceed 0.25 (a quarantined driver is being re-routed constantly).
* ``dispatch_latency_spike`` — a multiply's wall time exceeds
  ``median * (1 + max(0.5, 3*MAD/median))`` of the window.
* ``roofline_collapse`` — a driver's per-multiply roofline fraction
  drops below half the window median (device silently throttled,
  tunnel latency regime change).
* ``shed_storm`` — the serving plane (`dbcsr_tpu.serve`) shed more
  than ``DBCSR_TPU_HEALTH_SHED_RATE`` (0.25) of the last admission
  window (fed per decision by `observe_serve`; surfaces as a
  DEGRADED reason on the ``perf`` component).

Each detector fires on the RISING edge only (publishing an ``anomaly``
bus event + ``dbcsr_tpu_anomalies_total{kind}``) and re-arms when the
signal returns below threshold — no per-multiply alert storms.

Thresholds are env-tunable (``DBCSR_TPU_HEALTH_*``); the clock-free
design (windows keyed by multiply count, not wall time) keeps verdicts
deterministic for tests.  Stdlib-only at import; `core.stats` /
`resilience.breaker` / `obs.costmodel` are reached lazily.
"""

from __future__ import annotations

import collections
import os
import threading
import time

OK = "OK"
DEGRADED = "DEGRADED"
CRITICAL = "CRITICAL"

_RANK = {OK: 0, DEGRADED: 1, CRITICAL: 2}

ANOMALY_KINDS = ("recompile_storm", "fallback_storm",
                 "dispatch_latency_spike", "roofline_collapse",
                 "shed_storm")

_lock = threading.Lock()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _window_n() -> int:
    return max(8, _env_int("DBCSR_TPU_HEALTH_WINDOW", 64))


# minimum samples before any detector may fire (half a window floor)
_MIN_SAMPLES = 8

# rolling per-multiply samples: dicts {dur_ms, recompiles, fallbacks}
_samples: collections.deque = collections.deque(maxlen=_window_n())
# running window sums (updated incrementally on append/evict: the
# storm detectors must not re-sum 64 samples per multiply — the bus-on
# budget is micro-seconds)
_sums = {"recompiles": 0.0, "fallbacks": 0.0}
# latency threshold cache: (median, threshold_ms), refreshed every
# _LAT_REFRESH observes (a full median/MAD pass per multiply is the
# single most expensive part of the naive detector)
_lat_cache: list = [0.0, None, 0]  # [median_ms, threshold_ms, age]
_LAT_REFRESH = 8
# per-driver roofline-fraction history (per-multiply deltas)
_rl_hist: dict = {}
# counter totals at the last observe (for per-multiply deltas)
_last = {"compiles": 0.0, "fallbacks": 0.0}
# per-driver rollup totals at the last observe
_last_rollup: dict = {}
# per-(kind, dtype) peak cache for the roofline observer (peaks_for
# re-reads the environment per call; health samples every multiply)
_peak_cache: dict = {}
# env-tunable thresholds, read once (reset() re-reads; tests that
# monkeypatch DBCSR_TPU_HEALTH_* must call health.reset())
_th_cache: dict = {}
# rising-edge state per anomaly kind (roofline keyed per driver)
_active: dict = {}
# serving-plane admission window: 1.0 per shed decision, 0.0 per
# admit (fed by serve.queue via observe_serve) — the shed-storm
# detector's rolling window, keyed by admission count like the
# multiply detectors are keyed by multiply count (clock-free).
# `obs.windows.Window` keeps the shed rate O(1) per decision.
from dbcsr_tpu.obs.windows import Window as _Window  # noqa: E402

_serve_window = _Window(_window_n())

# fleet worker liveness, fed by the serve router's heartbeat loop
# (`serve.router.FleetRouter` via observe_fleet): worker name -> up.
# Empty = this process routes no fleet (the component reads OK).
_fleet_state: dict = {}


def _threshold(name: str, default: float) -> float:
    v = _th_cache.get(name)
    if v is None:
        v = _th_cache[name] = _env_float(name, default)
    return v


# the one median/MAD implementation (perf_gate noise convention) lives
# in obs.windows; re-exported here because every detector below — and
# historical callers — read them as health.median/health.mad
from dbcsr_tpu.obs.windows import mad, median  # noqa: E402,F401


def reset() -> None:
    """Drop the rolling windows, detector states and cached env
    thresholds (tests; paired with `metrics.reset`).  Also clears the
    SLO plane's rising-edge/cached-evaluation state when that module
    is loaded — a stale burning objective must not leak a DEGRADED
    ``slo`` component into the next test."""
    import sys

    slo = sys.modules.get("dbcsr_tpu.obs.slo")
    if slo is not None:
        try:
            slo.reset()
        except Exception:
            pass
    with _lock:
        _samples.clear()
        _sums["recompiles"] = 0.0
        _sums["fallbacks"] = 0.0
        _lat_cache[0], _lat_cache[1], _lat_cache[2] = 0.0, None, 0
        _rl_hist.clear()
        _active.clear()
        _last["compiles"] = 0.0
        _last["fallbacks"] = 0.0
        _last_rollup.clear()
        _peak_cache.clear()
        _th_cache.clear()
        _serve_window.clear()
        _fleet_state.clear()


def _counter_total(name: str) -> float:
    from dbcsr_tpu.obs import metrics

    c = metrics._counters.get(name)
    return float(sum(c.values.values())) if c is not None else 0.0


def _counter_by(name: str) -> dict:
    from dbcsr_tpu.obs import metrics

    c = metrics._counters.get(name)
    return dict(c.values) if c is not None else {}


def _fire(kind: str, state_key, args: dict) -> None:
    """Rising-edge anomaly emission: one bus event + one counter inc
    per entry into the anomalous state."""
    if _active.get(state_key):
        return
    _active[state_key] = True
    from dbcsr_tpu.obs import events as _events
    from dbcsr_tpu.obs import metrics

    metrics.counter(
        "dbcsr_tpu_anomalies_total",
        "health-model anomaly detections by kind",
    ).inc(kind=kind)
    _events.publish("anomaly", dict(args, kind=kind), flight=True)
    try:
        # a health transition forces the telemetry store's NEXT sample
        # boundary (deferred: detectors fire under their own locks and
        # must never re-enter the collectors mid-verdict)
        from dbcsr_tpu.obs import timeseries as _ts

        _ts.request_sample(f"anomaly:{kind}")
    except Exception:
        pass
    try:
        # ...and arms an incident-bundle capture at that same boundary
        # (flag-set only — safe under the detector locks this runs in)
        from dbcsr_tpu.obs import incidents as _incidents

        _incidents.trigger(f"anomaly:{kind}", args)
    except Exception:
        pass


def _clear_state(state_key) -> None:
    _active.pop(state_key, None)


def observe_multiply(dur_ms: float | None = None,
                     error: str | None = None) -> None:
    """Feed one finished multiply into the rolling windows and run the
    anomaly detectors.  Called by `events.end_product` (bus on only);
    micro-second budget: running window sums, a cached latency
    threshold refreshed every `_LAT_REFRESH` observes, and a cached
    peak table — no O(window) pass on the common path."""
    if error is not None:
        # a failed multiply's wall time is chain-walk time, not
        # dispatch latency: keep its recompile/fallback deltas in the
        # storm windows but keep it out of the latency median
        dur_ms = None
    compiles = _counter_total("dbcsr_tpu_jit_compiles_total")
    fallbacks = _counter_total("dbcsr_tpu_driver_fallback_total")
    with _lock:
        if compiles < _last["compiles"] or fallbacks < _last["fallbacks"]:
            # a counter shrank: metrics.reset() ran mid-run — resync
            # the baselines instead of clamping every delta to zero
            # until the fresh counters outgrow the stale totals (which
            # would silently disarm the storm detectors)
            _last["compiles"] = compiles
            _last["fallbacks"] = fallbacks
        d_comp = max(0.0, compiles - _last["compiles"])
        d_fall = max(0.0, fallbacks - _last["fallbacks"])
        _last["compiles"] = compiles
        _last["fallbacks"] = fallbacks
        # -- latency spike: vs the PRIOR window's cached median/MAD
        # threshold (refreshed every _LAT_REFRESH appends — a detector
        # threshold, not a benchmark; staleness of <8 samples is noise)
        n_prior = len(_samples)
        if dur_ms is not None and n_prior >= _MIN_SAMPLES:
            _lat_cache[2] += 1
            if _lat_cache[1] is None or _lat_cache[2] >= _LAT_REFRESH:
                durs = [s["dur_ms"] for s in _samples
                        if s["dur_ms"] is not None]
                med = median(durs) if durs else 0.0
                if med > 0:
                    rel = max(
                        _threshold("DBCSR_TPU_HEALTH_LATENCY_RELTOL", 0.5),
                        3.0 * mad(durs) / med)
                    _lat_cache[0] = med
                    _lat_cache[1] = med * (1.0 + rel)
                else:
                    _lat_cache[1] = None
                _lat_cache[2] = 0
        spike_th = _lat_cache[1] if (dur_ms is not None
                                     and n_prior >= _MIN_SAMPLES) else None
        # -- append + running sums (evict before the deque drops it)
        if len(_samples) == _samples.maxlen:
            old = _samples[0]
            _sums["recompiles"] -= old["recompiles"]
            _sums["fallbacks"] -= old["fallbacks"]
        _samples.append({"dur_ms": dur_ms, "recompiles": d_comp,
                         "fallbacks": d_fall})
        _sums["recompiles"] += d_comp
        _sums["fallbacks"] += d_fall
        n = len(_samples)
        sum_comp, sum_fall = _sums["recompiles"], _sums["fallbacks"]
    # -- storms: rate over the window (running sums) ------------------
    if n >= _MIN_SAMPLES:
        rate = sum_comp / n
        th = _threshold("DBCSR_TPU_HEALTH_RECOMPILE_RATE", 0.5)
        if rate > th:
            _fire("recompile_storm", "recompile_storm",
                  {"rate_per_multiply": round(rate, 3), "threshold": th,
                   "window": n})
        else:
            _clear_state("recompile_storm")
        rate = sum_fall / n
        th = _threshold("DBCSR_TPU_HEALTH_FALLBACK_RATE", 0.25)
        if rate > th:
            _fire("fallback_storm", "fallback_storm",
                  {"rate_per_multiply": round(rate, 3), "threshold": th,
                   "window": n})
        else:
            _clear_state("fallback_storm")
    if spike_th is not None:
        if dur_ms > spike_th:
            _fire("dispatch_latency_spike", "dispatch_latency_spike",
                  {"dur_ms": round(dur_ms, 3),
                   "median_ms": round(_lat_cache[0], 3),
                   "threshold_ms": round(spike_th, 3)})
        else:
            _clear_state("dispatch_latency_spike")
    _observe_roofline()


def _attainable(kind: str, dtype: str, d_fl: float, d_by: float) -> float:
    """min(peak compute, intensity * bandwidth) with the (kind, dtype)
    peak pair cached — `costmodel.peaks_for` re-reads the environment
    per call, too heavy for a per-multiply sample."""
    key = (kind, dtype)
    pk = _peak_cache.get(key)
    if pk is None:
        from dbcsr_tpu.obs import costmodel

        pk = _peak_cache[key] = (costmodel.peak_gflops(kind, dtype),
                                 float(costmodel.peaks_for(kind)["gbs"]))
    peak, gbs = pk
    if d_by > 0:
        return min(peak, (d_fl / d_by) * gbs)
    return peak


def _observe_roofline() -> None:
    """Per-driver roofline fraction of the work THIS multiply added
    (delta of the cumulative rollup), appended to per-driver history;
    collapse = current below half the window median."""
    try:
        from dbcsr_tpu.core import stats
        from dbcsr_tpu.obs import costmodel
    except Exception:
        return
    kind = costmodel.device_kind()
    ratio = _threshold("DBCSR_TPU_HEALTH_COLLAPSE_RATIO", 0.5)
    with _lock:
        for driver, agg in stats._driver_agg.items():
            prev = _last_rollup.get(driver, (0, 0, 0.0))
            if agg.flops < prev[0]:  # stats.reset() ran mid-run: resync
                _last_rollup[driver] = (agg.flops, agg.nbytes, agg.seconds)
                continue
            d_fl = agg.flops - prev[0]
            d_by = agg.nbytes - prev[1]
            d_s = agg.seconds - prev[2]
            if d_fl <= 0 or d_s <= 0:
                continue
            _last_rollup[driver] = (agg.flops, agg.nbytes, agg.seconds)
            dtype = max(agg.by_dtype, key=agg.by_dtype.get) \
                if agg.by_dtype else "float64"
            attainable = _attainable(kind, dtype, d_fl, d_by)
            frac = (d_fl / d_s / 1e9) / attainable if attainable else 0.0
            hist = _rl_hist.setdefault(
                driver, collections.deque(maxlen=_window_n()))
            n_prior = len(hist)
            if n_prior >= _MIN_SAMPLES:
                med = median(hist)
                if med > 1e-6 and frac < ratio * med:
                    _fire("roofline_collapse", ("roofline_collapse", driver),
                          {"driver": driver, "fraction": round(frac, 5),
                           "window_median": round(med, 5),
                           "threshold": round(ratio * med, 5)})
                else:
                    _clear_state(("roofline_collapse", driver))
            hist.append(frac)


def observe_serve(shed: bool) -> None:
    """Feed one serving-plane admission decision into the shed-storm
    window (`serve.queue` calls this for every admit/shed).  Rising
    edge fires when the shed fraction of the last window exceeds
    ``DBCSR_TPU_HEALTH_SHED_RATE`` (default 0.25) with at least
    `_MIN_SAMPLES` decisions observed — the same rolling-window,
    rising-edge convention as the four multiply detectors."""
    with _lock:
        _serve_window.append(1.0 if shed else 0.0)
        n = len(_serve_window)
        rate = _serve_window.sum / n if n else 0.0
    if n < _MIN_SAMPLES:
        return
    th = _threshold("DBCSR_TPU_HEALTH_SHED_RATE", 0.25)
    if rate > th:
        _fire("shed_storm", "shed_storm",
              {"shed_fraction": round(rate, 3), "threshold": th,
               "window": n})
    else:
        _clear_state("shed_storm")


def active_anomalies() -> dict:
    """{kind: [detail…]} of detectors currently in the anomalous
    state (rising-edge flags, not historical counts)."""
    out: dict = {}
    with _lock:
        for key, on in _active.items():
            if not on:
                continue
            if isinstance(key, tuple):
                out.setdefault(key[0], []).append(key[1])
            else:
                out.setdefault(key, []).append(None)
    return out


# ------------------------------------------------------------- verdict

def _eval_drivers() -> dict:
    from dbcsr_tpu.resilience import breaker

    status, reasons = OK, []
    board = breaker._board  # do not CREATE a board just to inspect it
    snap = board.snapshot() if board is not None else {}
    open_keys = [k for k, v in snap.items() if v["state"] == "open"]
    half = [k for k, v in snap.items() if v["state"] == "half_open"]
    if half:
        status = DEGRADED
        reasons.append(f"breaker half-open (trial pending): "
                       f"{', '.join(sorted(half))}")
    if open_keys:
        status = DEGRADED
        reasons.append("breaker open: " + ", ".join(
            f"{k} ({snap[k]['last_kind']})" for k in sorted(open_keys)))
        crit_n = _env_int("DBCSR_TPU_HEALTH_BREAKER_CRITICAL_N", 4)
        if any(k.startswith("xla|") for k in open_keys):
            status = CRITICAL
            reasons.append("the safe xla driver itself has an open "
                           "breaker — the failover chain is losing its "
                           "backstop")
        elif len(open_keys) >= crit_n:
            status = CRITICAL
            reasons.append(f"{len(open_keys)} breakers open "
                           f"(critical at {crit_n})")
    return {"status": status, "reasons": reasons,
            "open": len(open_keys), "half_open": len(half),
            "tracked": len(snap)}


def _eval_watchdog() -> dict:
    from dbcsr_tpu.obs import metrics

    status, reasons = OK, []
    streaks = {}
    g = metrics._gauges.get("dbcsr_tpu_watchdog_wedge_streak")
    if g is not None:
        for key, v in g.values.items():
            name = dict(key).get("name", "?")
            streaks[name] = v
            if v >= 3:
                status = CRITICAL
                reasons.append(f"channel {name!r} wedged {int(v)}x "
                               f"consecutively (backoff is hours)")
            elif v >= 1:
                if status == OK:
                    status = DEGRADED
                reasons.append(f"channel {name!r} wedge streak {int(v)}")
    return {"status": status, "reasons": reasons, "wedge_streaks": streaks}


def _eval_engine() -> dict:
    status, reasons = OK, []
    retries = _counter_by("dbcsr_tpu_checksum_retry_total")
    for key, v in retries.items():
        outcome = dict(key).get("outcome")
        if outcome in ("deterministic", "unstable") and v:
            status = CRITICAL
            reasons.append(f"checksum retry classified {outcome} "
                           f"({int(v)}x): proven numeric corruption")
    degraded = _counter_total("dbcsr_tpu_multihost_degraded_total")
    if degraded:
        if status == OK:
            status = DEGRADED
        reasons.append(f"{int(degraded)} world join(s) degraded to "
                       f"serial")
    anomalies = active_anomalies()
    for kind in ("recompile_storm", "fallback_storm",
                 "dispatch_latency_spike"):
        if kind in anomalies:
            if status == OK:
                status = DEGRADED
            reasons.append(f"active anomaly: {kind}")
    return {"status": status, "reasons": reasons,
            "fallbacks": _counter_total("dbcsr_tpu_driver_fallback_total"),
            "failures": _counter_total("dbcsr_tpu_driver_failures_total"),
            "faults_injected": _counter_total(
                "dbcsr_tpu_faults_injected_total")}


def _eval_perf() -> dict:
    status, reasons = OK, []
    fractions: dict = {}
    try:
        from dbcsr_tpu.core import stats
        from dbcsr_tpu.obs import costmodel

        kind = costmodel.device_kind()
        for driver, agg in stats.driver_rollup().items():
            if agg["seconds"] <= 0:
                continue
            dtype = max(agg["by_dtype"], key=agg["by_dtype"].get) \
                if agg["by_dtype"] else "float64"
            fractions[driver] = round(costmodel.roofline(
                agg["flops"], agg["bytes"], agg["seconds"], kind=kind,
                dtype=dtype)["roofline_fraction"], 5)
    except Exception:
        pass
    anomalies = active_anomalies()
    collapsed = anomalies.get("roofline_collapse")
    if collapsed:
        status = DEGRADED
        reasons.append("active roofline collapse: "
                       + ", ".join(str(d) for d in collapsed))
    if "shed_storm" in anomalies:
        # the serving plane is rejecting a large fraction of recent
        # submissions (admission control, quotas, or injected faults):
        # DEGRADED — capacity or quota tuning, not engine corruption
        status = DEGRADED
        reasons.append(
            "active shed storm: the serving plane shed more than "
            f"{_threshold('DBCSR_TPU_HEALTH_SHED_RATE', 0.25):.0%} of "
            "the last admission window — raise quotas/queue bound or "
            "add capacity (docs/serving.md#shed-storms)")
    pool = {}
    try:
        from dbcsr_tpu.core import mempool

        pool = mempool.pool_stats()
        requests = pool["hits"] + pool["misses"]
        ev_th = _env_int("DBCSR_TPU_HEALTH_POOL_EVICTIONS", 8)
        if (pool["enabled"] and pool["evictions"] >= ev_th
                and requests >= 16
                and pool["hits"] < 0.5 * requests):
            # buffers are being dropped at the budget while checkouts
            # still miss: the byte budget is smaller than the chain's
            # working set, so the pool churns instead of serving
            if status == OK:
                status = DEGRADED
            reasons.append(
                f"memory-pool thrash: {int(pool['evictions'])} budget "
                f"evictions with hit ratio "
                f"{pool['hits'] / max(1, requests):.2f} — raise "
                f"DBCSR_TPU_POOL_BYTES (held "
                f"{pool['bytes_held']}/{pool['budget_bytes']} B)")
    except Exception:
        pass
    return {"status": status, "reasons": reasons,
            "roofline_fraction": fractions,
            "pool": {k: pool[k] for k in
                     ("hits", "misses", "returns", "evictions",
                      "bytes_held", "high_water") if k in pool}}


def _eval_integrity() -> dict:
    """The data-integrity component: detected-SDC and recovery
    counters folded into a verdict.  A recovered mismatch still
    degrades — the device produced a wrong finite answer and the next
    one may not be caught; repeated mismatches attributed to one
    driver are critical (deterministic corruption, quarantine-level
    evidence)."""
    status, reasons = OK, []
    mism: dict = {}
    for key, v in _counter_by("dbcsr_tpu_abft_mismatches_total").items():
        d = dict(key).get("driver", "?")
        mism[d] = mism.get(d, 0) + int(v)
    total = sum(mism.values())
    rollbacks = _counter_total("dbcsr_tpu_chain_rollback_total")
    recoveries = _counter_total("dbcsr_tpu_abft_recoveries_total")
    # recoveries pair with mismatches EXCEPT the chain labels, which
    # pair with rollbacks (a chain recompute heals an invariant
    # violation, not a counted probe mismatch)
    recov_sdc = sum(
        float(v) for key, v in _counter_by(
            "dbcsr_tpu_abft_recoveries_total").items()
        if not dict(key).get("driver", "").startswith("chain:"))
    unrecovered = max(0, total - int(recov_sdc))
    if total:
        status = DEGRADED
        reasons.append(
            f"{total} ABFT probe mismatch(es) — detected silent data "
            f"corruption: " + ", ".join(
                f"{d}={n}" for d, n in sorted(mism.items())))
    if rollbacks:
        status = DEGRADED if status == OK else status
        reasons.append(f"{int(rollbacks)} chain-invariant rollback(s) "
                       f"recomputed on the safe engine")
    crit_n = _env_int("DBCSR_TPU_HEALTH_SDC_CRITICAL", 3)
    repeat = {d: n for d, n in mism.items() if n >= crit_n}
    # fully-recovered SDC — detect → re-execute → verified — leaves the
    # verdict DEGRADED however often it repeats (the breaker owns
    # quarantining a driver that keeps corrupting); CRITICAL is
    # reserved for corruption that ESCAPED recovery: a wrong answer
    # may have reached a caller
    if unrecovered and (repeat or unrecovered >= crit_n):
        status = CRITICAL
        reasons.append(
            f"{unrecovered} detected-SDC result(s) NOT recovered"
            + (" with repeated mismatches from " + ", ".join(
                f"{d} ({n}x)" for d, n in sorted(repeat.items()))
               if repeat else "")
            + f" (critical at {crit_n} — see docs/resilience.md"
              f"#runbook-silent-data-corruption)")
    return {"status": status, "reasons": reasons,
            "abft_checks": _counter_total("dbcsr_tpu_abft_checks_total"),
            "abft_mismatches": mism,
            "recoveries": recoveries,
            "chain_rollbacks": int(rollbacks),
            "serve_drains": _counter_total("dbcsr_tpu_serve_drain_total"),
            "journal_replayed": _counter_total(
                "dbcsr_tpu_serve_journal_replayed_total")}


def _eval_tune() -> dict:
    """The online autotuner's component (`dbcsr_tpu.tune`): OK while
    idle or never started; DEGRADED on a repeated-trial-failure streak
    or when the last cycle demoted a promoted row (a regression the
    judge caught — the table healed itself, but someone should ask
    why).  Advisory like ``slo``: it pages operators and never closes
    serve admission (a sick tuner must not shed traffic)."""
    import sys

    status, reasons = OK, []
    svc_mod = sys.modules.get("dbcsr_tpu.tune.service")
    svc = svc_mod.current_service() if svc_mod is not None else None
    snap = svc.snapshot() if svc is not None else {}
    streak = int(snap.get("trial_failure_streak", 0))
    if streak >= 3:
        status = DEGRADED
        reasons.append(
            f"{streak} consecutive tuning trials failed "
            f"(last error: {snap.get('last_error')}) — see "
            "docs/autotuning.md#runbook-failing-trials")
    if snap.get("last_cycle_demoted"):
        # its own flag, not last_outcome: a cycle that demoted AND
        # then promoted/failed its trial must still page
        status = DEGRADED
        reasons.append(
            "the last tuner cycle demoted a promoted row: its live "
            "roofline cell regressed (docs/autotuning.md"
            "#demotion-on-regression)")
    trials = {dict(k).get("outcome", "?"): int(v)
              for k, v in _counter_by(
                  "dbcsr_tpu_tune_trials_total").items()}
    return {"status": status, "reasons": reasons,
            "running": bool(snap.get("running")),
            "cycles": int(snap.get("cycles", 0)),
            "queue_depth": int(snap.get("queue_depth", 0)),
            "trials": trials,
            "promotions": int(_counter_total(
                "dbcsr_tpu_tune_promotions_total")),
            "demotions": int(_counter_total(
                "dbcsr_tpu_tune_demotions_total")),
            "params_generation": _params_generation()}


def _params_generation() -> int:
    import sys

    pm = sys.modules.get("dbcsr_tpu.acc.params")
    try:
        return int(pm.generation()) if pm is not None else 0
    except Exception:
        return 0


def observe_fleet(workers: dict) -> None:
    """Router feed: the live worker-liveness map ``{name: up}`` (the
    whole table each heartbeat round — workers that left the fleet
    leave the map, so a drained-and-removed worker stops paging)."""
    with _lock:
        _fleet_state.clear()
        _fleet_state.update({str(k): bool(v) for k, v in workers.items()})


def _eval_fleet() -> dict:
    """The serve fleet's component (fed by `serve.router` heartbeats):
    OK when every known worker is up (or this process routes no
    fleet), DEGRADED when some workers are down (capacity lost, the
    router re-places around them), CRITICAL when ALL are down (no
    routable worker — the fleet serves nothing).  Advisory like
    ``slo``/``tune``: a dead PEER must never close THIS process's own
    admission (docs/serving.md § fleet)."""
    with _lock:
        snap = dict(_fleet_state)
    if not snap:
        return {"status": OK, "reasons": [], "workers": {}}
    down = sorted(w for w, up in snap.items() if not up)
    status, reasons = OK, []
    if down and len(down) == len(snap):
        status = CRITICAL
        reasons.append(
            f"all {len(snap)} fleet workers down ({', '.join(down)}) "
            "— docs/serving.md#runbook-worker-down")
    elif down:
        status = DEGRADED
        reasons.append(
            f"{len(down)}/{len(snap)} fleet workers down "
            f"({', '.join(down)}) — the router routes around them; "
            "docs/serving.md#runbook-worker-down")
    return {"status": status, "reasons": reasons, "workers": snap}


def _eval_slo() -> dict:
    """The SLO plane's component (`obs.slo.component`): error-budget
    burn over the telemetry history store — OK with a reason when the
    store is off or nothing evaluated yet."""
    try:
        from dbcsr_tpu.obs import slo

        return slo.component()
    except Exception:
        return {"status": OK, "reasons": [], "objectives": {}}


def _components(include_slo: bool = True) -> dict:
    """The ONE evaluator list both `verdict` and `admission_status`
    share — adding a component here reaches both automatically (a
    hand-maintained second copy would silently drift)."""
    components = {
        "drivers": _eval_drivers(),
        "watchdog": _eval_watchdog(),
        "engine": _eval_engine(),
        "perf": _eval_perf(),
        "integrity": _eval_integrity(),
    }
    if include_slo:
        # the ADVISORY components: they page operators via the full
        # verdict but must never close serve admission — an SLO burn
        # feeding back into sheds (or a sick background tuner shedding
        # live traffic) would be a positive feedback loop; likewise a
        # dead fleet PEER must not shed this worker's own traffic
        components["slo"] = _eval_slo()
        components["tune"] = _eval_tune()
        components["fleet"] = _eval_fleet()
    return components


def verdict() -> dict:
    """The full health verdict: worst component status + per-component
    reasons + the active anomaly set (the ``/healthz`` payload)."""
    components = _components()
    worst = max((c["status"] for c in components.values()),
                key=_RANK.get)
    from dbcsr_tpu.obs import events as _events

    return {
        "status": worst,
        "components": components,
        "anomalies": active_anomalies(),
        "anomaly_counts": {
            dict(k).get("kind", "?"): v
            for k, v in _counter_by("dbcsr_tpu_anomalies_total").items()},
        "window": len(_samples),
        "bus_enabled": _events.enabled(),
        "t_unix": time.time(),
    }


def admission_status() -> str:
    """The verdict the serving plane's admission control keys on:
    worst of every component EXCEPT the advisory ``slo`` and ``tune``
    pair.  The SLO burn component
    pages operators; it must never close admission — for the serve
    error-budget objective a SHED is itself the bad event, so a
    burn-driven shed would be a positive feedback loop (sheds → error
    burn → CRITICAL → shed everything) that locks the plane shut with
    no exit.  Routing-level reactions (the ``/healthz`` 503, fleet
    placement) still see the full verdict."""
    return max((c["status"]
                for c in _components(include_slo=False).values()),
               key=_RANK.get)


# back-compat friendly alias: "evaluate" reads naturally at call sites
evaluate = verdict
