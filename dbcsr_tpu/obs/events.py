"""Unified structured-event bus with per-multiply correlation ids.

PRs 1–3 left the engine emitting rich but *disconnected* signals:
trace instants, flight-recorder event lists, breaker transitions,
watchdog verdicts, fault-injection instants — each site calling two or
three obs layers by hand, with nothing tying "this fallback, this
recompile, this roofline collapse" to *one multiply*.  This module is
the single choke point those sites now publish through:

* **Correlation** — `mm.multiply` opens a ``product_id`` per multiply
  (`begin_product`/`end_product`; nested TAS multiplies form a stack),
  and every event published while it is open is stamped with it.  The
  id also lands on the flight record and the multiply span, so all
  three stores join on one key (Dapper-style, scoped to a process).
* **Ring** — a bounded deque of the last ``DBCSR_TPU_EVENTS_N``
  (default 4096) events backs live reads: `obs.server`'s
  ``/events?product_id=…`` endpoint and `tools/doctor.py`.
* **JSONL sink** — opt-in streaming to disk, sharded per process like
  ``DBCSR_TPU_TRACE`` (``DBCSR_TPU_EVENTS=<base path>`` →
  ``<base>.p{process_index}<ext>``; a provisional hostname+pid name
  until `parallel.multihost.init_multihost` resolves the index).
* **Fan-out** — `publish` still forwards to the tracer instant and the
  flight-recorder event the call sites used to emit directly, so the
  existing trace/flight schemas are unchanged; the bus is additive.

Off switch: ``DBCSR_TPU_EVENTS=0`` disables the ring, the sink AND the
health-window sampling; `publish` then only forwards to trace/flight
exactly as the call sites did before this module existed — the
measured bus-off cost is one function call + two attribute checks per
event site (PERF_NOTES.md).

Stdlib-only: `core.stats`/`acc.smm` reach this module from their hot
paths via `obs.metrics`/`obs.flight`, which must not pull in jax.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid

from dbcsr_tpu.obs import flight as _flight
from dbcsr_tpu.obs import shard as _shard
from dbcsr_tpu.obs import tracer as _trace

_lock = threading.Lock()


def _env_capacity() -> int:
    raw = os.environ.get("DBCSR_TPU_EVENTS_N", "4096")
    try:
        return int(raw)
    except ValueError:
        return 4096


# "0"/"off" disables the bus entirely; a path enables the JSONL sink;
# unset/other keeps the default ring-only mode
_env = os.environ.get("DBCSR_TPU_EVENTS", "")
_enabled = _env not in ("0", "off")
_ring: collections.deque = collections.deque(
    maxlen=max(1, _env_capacity()))
_seq = 0

# product-id correlation stack (nested TAS multiplies), kept PER
# THREAD: the serving plane publishes submission/shed events from
# client threads while the worker thread has a multiply open — a
# global stack would stamp those events with the worker's product id
# (same rationale as core.mempool's thread-local chain stack)
_product_tls = threading.local()
_product_seq = 0


def _pstack() -> list:
    st = getattr(_product_tls, "stack", None)
    if st is None:
        st = _product_tls.stack = []
    return st


# process-unique token so ids from N multihost shards never collide
_TOKEN = f"{os.getpid():x}-{uuid.uuid4().hex[:6]}"

# JSONL sink state (sharded like the tracer; see module docstring)
_sink = None          # open file handle, or None
_sink_base: str | None = None
_sink_path: str | None = None
_sink_pid_final = False

# in-process subscribers (obs.rca's change ledger): called with the
# bus record AFTER it is ringed, outside _lock, each guarded — a
# subscriber can publish further events without deadlocking the bus
_subscribers: list = []


def subscribe(fn) -> None:
    """Register ``fn(record)`` to observe every bus record (after the
    ring append, outside the bus lock).  Idempotent per function."""
    if fn not in _subscribers:
        _subscribers.append(fn)


def unsubscribe(fn) -> None:
    try:
        _subscribers.remove(fn)
    except ValueError:
        pass


def enabled() -> bool:
    """True when the bus records (ring + sink + health sampling); when
    False `publish` only forwards to trace/flight."""
    return _enabled


def set_enabled(on: bool) -> None:
    """Tests / embedding apps: flip the bus without the env var."""
    global _enabled
    _enabled = bool(on)


def sink_active() -> bool:
    return _sink is not None


def sink_path() -> str | None:
    """The shard file the sink is currently writing (None when off)."""
    return _sink_path


# ------------------------------------------------------------ products

def begin_product(**fields) -> str:
    """Open a correlation id for the multiply that is starting; every
    event published until the matching `end_product` carries it."""
    global _product_seq
    with _lock:
        _product_seq += 1
        seq = _product_seq
    pid = f"{_TOKEN}-{seq}"
    _pstack().append(pid)
    publish("multiply_begin", dict(fields, product_id=pid))
    return pid


def current_product() -> str | None:
    """The innermost open product id on THIS thread (None outside a
    multiply)."""
    st = _pstack()
    return st[-1] if st else None


def end_product(rec: dict | None = None, error: str | None = None,
                **fields) -> None:
    """Close the innermost product: publish ``multiply_end`` carrying
    the flight record's summary (duration, driver decisions, flops) and
    feed the health model's rolling windows.  The product stays on the
    correlation stack until the health detectors ran, so an anomaly
    THIS multiply trips is stamped with its product_id."""
    st = _pstack()
    if not st:
        return
    pid = st[-1]
    args = dict(fields, product_id=pid)
    dur_ms = None
    if rec is not None:
        dur_ms = rec.get("dur_ms")
        args["dur_ms"] = dur_ms
        if rec.get("flops") is not None:
            args["flops"] = rec["flops"]
        if rec.get("algorithm"):
            args["algorithm"] = rec["algorithm"]
        if rec.get("drivers"):
            args["drivers"] = {
                d: v.get("stacks", 0) for d, v in rec["drivers"].items()}
    if error is not None:
        args["error"] = error[:300]
    publish("multiply_end", args)
    try:
        if _enabled:
            from dbcsr_tpu.obs import health as _health

            _health.observe_multiply(dur_ms=dur_ms, error=error)
    except Exception:
        pass  # health sampling must never fail a multiply
    finally:
        if st and st[-1] == pid:
            st.pop()
    # product boundary = a telemetry-store sample boundary (cadence-
    # gated inside; one attribute check when DBCSR_TPU_TS=0).  AFTER
    # the product popped: a forced sample's health collector must not
    # observe this multiply as still open.
    try:
        from dbcsr_tpu.obs import timeseries as _ts

        _ts.on_product()
    except Exception:
        pass  # telemetry must never fail a multiply


import contextlib as _contextlib


@_contextlib.contextmanager
def product_scope(op: str, name: str, **flight_fields):
    """One correlation scope around a multiply-like operation: opens a
    product id + flight record, commits/closes them on exit, and on
    error stamps both with the formatted exception before re-raising.
    Used by the distributed engines (`parallel/sparse_dist.py`);
    `mm.multiply` keeps its bespoke scope (it notes flops/algorithm on
    the record between body and commit)."""
    pid = begin_product(op=op, name=name)
    _flight.begin(op=op, product_id=pid, **flight_fields)
    try:
        yield pid
    except Exception as exc:
        err = f"{type(exc).__name__}: {exc}"[:300]
        rec = _flight.commit(error=err)
        end_product(rec=rec, error=err)
        raise
    rec = _flight.commit()
    end_product(rec=rec)


# ------------------------------------------------------------- publish

def publish(kind: str, args: dict | None = None, *, instant: bool = True,
            flight=False) -> dict | None:
    """Publish one structured event.

    ``args`` is the event payload; a ``product_id`` is stamped from the
    open correlation stack unless the payload already carries one.
    ``instant=True`` forwards a tracer instant of the same name (the
    pre-bus behavior of every call site); ``flight`` forwards a
    flight-recorder event — ``True`` reuses (kind, args), a
    ``(name, fields)`` tuple keeps a site's historical flight schema.

    Returns the bus record (None when the bus is disabled — the
    trace/flight fan-out still ran)."""
    global _seq
    args = args or {}
    pid = args.get("product_id")
    if pid is None:
        pid = current_product()
        if pid is not None:
            args = dict(args, product_id=pid)
    if instant:
        _trace.instant(kind, args or None)
    if flight:
        if flight is True:
            fname, ffields = kind, {
                k: v for k, v in args.items() if k != "product_id"}
        else:
            fname, ffields = flight
        _flight.note_event(fname, **ffields)
    if not _enabled:
        return None
    with _lock:
        _seq += 1
        # the envelope field is "event" (the flight recorder's
        # convention), NOT "kind": payloads legitimately carry their
        # own "kind" (fault kind, failure classification) and must not
        # be able to shadow the event name
        rec = {"seq": _seq, "t": time.time(), "event": kind, **args}
        rec["event"] = kind
        if "product_id" not in rec:
            rec["product_id"] = None
        _ring.append(rec)
        if _sink is not None:
            try:
                _sink.write(json.dumps(rec, default=str) + "\n")
            except Exception:
                pass  # a full disk must not fail the multiply
    for fn in list(_subscribers):
        try:
            fn(rec)
        except Exception:
            pass  # a broken subscriber must not fail the publisher
    return rec


# --------------------------------------------------------------- reads

def records(product_id: str | None = None, kind: str | None = None,
            limit: int | None = None) -> list:
    """Ring contents (oldest first), optionally filtered.  ``kind``
    filters on the envelope ``event`` name."""
    with _lock:
        out = list(_ring)
    if product_id is not None:
        out = [r for r in out if r.get("product_id") == product_id]
    if kind is not None:
        out = [r for r in out if r.get("event") == kind]
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


def to_json(**filters) -> str:
    return json.dumps(records(**filters), default=str)


def clear() -> None:
    """Drop the ring (NOT the product stack: a clear mid-multiply must
    not orphan the open correlation id)."""
    with _lock:
        _ring.clear()


# ---------------------------------------------------------------- sink

def enable_sink(base_path: str | None = None) -> str:
    """Open the JSONL sink (default base: $DBCSR_TPU_EVENTS).  The base
    is sharded per process exactly like ``DBCSR_TPU_TRACE`` — see
    `tracer.shard_path`; the actual file is returned (and `sink_path`).
    Implies `set_enabled(True)`."""
    global _sink, _sink_base, _sink_path, _sink_pid_final
    base_path = base_path or os.environ.get("DBCSR_TPU_EVENTS")
    if not base_path or base_path in ("0", "off"):
        raise ValueError("no events sink path: pass one or set "
                         "DBCSR_TPU_EVENTS")
    disable_sink()
    set_enabled(True)
    pid = _shard.process_index()
    with _lock:
        _sink_base = base_path
        _sink_pid_final = pid is not None
        tag = pid if pid is not None else _shard.provisional_tag()
        _sink_path = _shard.shard_path(base_path, tag)
        _sink = open(_sink_path, "a")
    return _sink_path


def disable_sink() -> None:
    """Close the sink, settling a provisional shard name on index 0."""
    global _sink
    rebind(force=True)
    with _lock:
        if _sink is not None:
            try:
                _sink.close()
            except Exception:
                pass
            _sink = None


def rebind(process_index: int | None = None, force: bool = False) -> None:
    """Settle a provisionally-named sink shard onto its final
    ``p{index}`` name (same contract as `tracer.rebind`: called by
    `init_multihost` once the world's process index is known; ``force``
    settles on 0 at close).  Appends onto an existing final shard
    instead of clobbering it."""
    global _sink, _sink_path, _sink_pid_final
    with _lock:
        if _sink is None or _sink_pid_final:
            return
        if process_index is None:
            process_index = _shard.process_index()
        if process_index is None:
            if not force:
                return
            process_index = 0
        _sink_pid_final = True
        _sink_path, _sink = _shard.settle(
            _sink_base, _sink_path, _sink, int(process_index))


import atexit


@atexit.register
def _atexit_close() -> None:  # pragma: no cover - process teardown
    try:
        disable_sink()
    except Exception:
        pass


# env activation: DBCSR_TPU_EVENTS=<path> at import streams the bus to
# disk with no code changes anywhere (mirrors DBCSR_TPU_TRACE)
if _enabled and _env:
    try:
        enable_sink(_env)
    except (ValueError, OSError):
        pass
