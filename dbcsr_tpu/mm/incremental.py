"""Delta-aware incremental multiply: recompute only what changed.

DBCSR's life is SCF loops — long sequences of ``C := alpha * A @ B``
products whose operands change *slightly* per iteration.  The plan
cache already makes the HOST side of a repeated product free; this
module extends reuse to the VALUES: when a product's plan cache hits
and its operands carry a known dirty-block delta since the last
execution of the same (A, B, scalars, flags) product (the mutation
journal of `core.matrix.BlockSparseMatrix`), only the C blocks whose
accumulation reads a dirty A/B block are recomputed — the rest splice
from the cached device-resident result.

**Bitwise identity by construction**: a C block's accumulation
sequence is its candidate triples sorted by (C block, A entry),
independent of every other C block; the subset run keeps exactly that
per-block sequence (chunking at a different ``mm_stack_size`` boundary
only splits the same ordered scatter-adds — the coalescer's
established contract), and spliced blocks are the previous result's
bits, which unchanged inputs would reproduce.

**Safety ladder** (every rung falls back to full recompute, never to
a wrong answer):

* unknown delta (structure change, journal truncation, rolled-back
  epoch, different operand objects) -> full recompute;
* ABFT live on the recomputed launches like any stack run, plus —
  when the ABFT knob is on — a full-product probe over the assembled
  (spliced) C; a mismatch discards the splice and recomputes fully;
* the ``incremental`` fault site makes the splice injectable
  (`resilience.faults`: raise/oom abort the splice, nan/flip corrupt
  it for the probe to catch);
* repeated probe/fault failures open a breaker-style degrade: the
  plane disables itself for the process (``incremental_degrade`` on
  the event bus) instead of flapping.

Result snapshots are ZERO-COPY: the cache aliases the product's final
bin buffers and marks C's bins shared (`_bins_shared`), which
permanently blocks pool donation of those buffers — the chain-owned
residency contract extended to a cross-product cache.  Eviction drops
the references (device memory frees when the last holder lets go);
entries are never banked back into the pool because exclusivity
cannot be proven.

Kill switch: ``DBCSR_TPU_INCREMENTAL=auto|off|full`` (config
``incremental``).  ``off`` removes every hook; ``full`` keeps the
tracking + cache maintenance but always recomputes — the honest A/B
control leg that still pays the bookkeeping.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as np

from dbcsr_tpu.core import digests, mempool

_CACHE_MAX_ENTRIES = 8
_CACHE_MAX_BYTES = 512 * 1024 * 1024
# recomputing almost everything pays splice overhead for ~no savings
_MAX_RECOMPUTE_FRACTION = 0.95
_BREAKER_THRESHOLD = 3  # consecutive probe/fault failures before degrade


class _Entry:
    """One cached product result: the (A, B) operand identities and
    epochs the result is valid against, plus the result's structure
    and ALIASED device bin buffers (held here, shared-marked on C).
    Operands are held by WEAK reference — they exist only for the
    ``is``-identity check, and a strong reference would pin both full
    operand matrices (outside the byte budget, which counts only C's
    bins) for the entry's lifetime."""

    __slots__ = ("a", "b", "a_epoch", "b_epoch", "keys", "bins", "nbytes")

    def __init__(self, a, b, c):
        import weakref

        self.a = weakref.ref(a)
        self.b = weakref.ref(b)
        self.a_epoch = a.mutation_epoch
        self.b_epoch = b.mutation_epoch
        self.keys = c.keys
        self.bins, self.nbytes = mempool.alias_bins(c)


_cache: "OrderedDict[tuple, _Entry]" = OrderedDict()
_cache_bytes = 0
# plan keys executed once (with the operand ids): a key seen twice with
# the SAME operands starts caching — one-shot products never pay the
# snapshot bookkeeping
_seen: "OrderedDict[tuple, tuple]" = OrderedDict()
_SEEN_MAX = 64

_breaker = {"failures": 0, "open": False}

# cumulative reuse totals (cheap module ints; the models' per-iteration
# reuse-fraction events diff these through `stats_snapshot`)
_totals = {
    "products": 0, "reused_blocks": 0, "recomputed_blocks": 0,
    "saved_flops": 0, "fallbacks": 0,
}


def _counter(result: str) -> None:
    from dbcsr_tpu.obs import metrics as _metrics

    _metrics.counter(
        "dbcsr_tpu_incremental_total",
        "delta-aware incremental multiply outcomes (hit_splice = partial "
        "recompute + splice, hit_unchanged = zero-delta full reuse, "
        "fallback_* = full recompute with the named reason)",
    ).inc(result=result)


def mode() -> str:
    from dbcsr_tpu.core.config import get_config

    return get_config().incremental


def _key(plan_key, alpha) -> tuple:
    return (plan_key, digests.scalar_key(alpha))


def _drop(key) -> None:
    global _cache_bytes
    ent = _cache.pop(key, None)
    if ent is not None:
        _cache_bytes -= ent.nbytes


def note_format_executed(a, b) -> None:
    """A canvas-path (dense/composite) execution just restructured C
    for these operands: cached delta entries keyed to them can never be
    reused again under a stack plan built for the SAME product state
    (the format planner may flip back on the next generation bump), so
    drop them eagerly instead of waiting for the epoch check to churn
    through stale entries."""
    stale = [k for k, ent in _cache.items()
             if ent.a() is a or ent.b() is b]
    for k in stale:
        _drop(k)


def reset() -> None:
    """Drop every cached result and close the breaker (tests)."""
    global _cache_bytes
    _cache.clear()
    _seen.clear()
    _cache_bytes = 0
    _breaker["failures"] = 0
    _breaker["open"] = False
    for k in _totals:
        _totals[k] = 0


def stats_snapshot() -> dict:
    """Cumulative reuse totals (copy) — diff two snapshots for a
    per-phase reuse fraction (`reuse_delta`)."""
    return dict(_totals)


def reuse_delta(prev: dict) -> dict:
    """Per-interval reuse summary between a `stats_snapshot` and now:
    blocks reused/recomputed, saved flops, and the reuse fraction
    (0.0 when the interval ran no delta-eligible products)."""
    reused = _totals["reused_blocks"] - prev.get("reused_blocks", 0)
    recomputed = _totals["recomputed_blocks"] - prev.get(
        "recomputed_blocks", 0)
    total = reused + recomputed
    return {
        "products": _totals["products"] - prev.get("products", 0),
        "reused_blocks": int(reused),
        "recomputed_blocks": int(recomputed),
        "saved_flops": int(_totals["saved_flops"]
                           - prev.get("saved_flops", 0)),
        "reuse_fraction": round(reused / total, 6) if total else 0.0,
    }


def _breaker_trip(reason: str) -> None:
    from dbcsr_tpu.obs import events as _events
    from dbcsr_tpu.obs import metrics as _metrics

    _totals["fallbacks"] += 1
    _breaker["failures"] += 1
    if _breaker["failures"] >= _BREAKER_THRESHOLD and not _breaker["open"]:
        _breaker["open"] = True
        _metrics.counter(
            "dbcsr_tpu_incremental_degrade_total",
            "incremental plane breaker opens (consecutive probe/fault "
            "failures; the plane degrades to full recompute)",
        ).inc()
        _events.publish("incremental_degrade", {
            "reason": reason, "failures": _breaker["failures"]})


def _dirty_entry_mask(m, dirty_keys) -> Optional[np.ndarray]:
    """Boolean mask over ``m``'s entries whose block key is in
    ``dirty_keys``; None when a dirty key is not a stored entry (the
    journal refers to structure this index no longer has — treat the
    delta as unknown)."""
    mask = np.zeros(len(m.keys), bool)
    if not len(dirty_keys):
        return mask
    if not len(m.keys):
        return None  # dirty keys against an empty index: unknown
    pos = np.searchsorted(m.keys, dirty_keys)
    pos_c = np.minimum(pos, len(m.keys) - 1)
    if not bool(np.all(m.keys[pos_c] == dirty_keys)):
        return None
    mask[pos_c] = True
    return mask


def maybe_reuse(plan_key, a, b, c, alpha, new_keys, cand_keys, a_ent,
                b_ent) -> Optional[int]:
    """Attempt the delta-aware path for one eligible product (the
    caller has already verified: stack path, beta == 0, no limits or
    window, unfiltered, non-symmetric, plan-cacheable).  Returns the
    executed true flops on success, None for a full recompute."""
    md = mode()
    if md == "off":
        return None
    key = _key(plan_key, alpha)
    ent = _cache.get(key)
    if md == "full":
        if ent is not None:
            _counter("forced_full")
        return None
    if _breaker["open"]:
        if ent is not None:
            _counter("fallback_degraded")
        return None
    if ent is None:
        _counter("miss")
        return None
    if ent.a() is not a or ent.b() is not b:
        _counter("fallback_identity")
        _drop(key)
        return None
    dirty_a = a.dirty_keys_since(ent.a_epoch)
    dirty_b = b.dirty_keys_since(ent.b_epoch)
    if dirty_a is None or dirty_b is None:
        _counter("fallback_epoch")
        _drop(key)
        return None
    if len(new_keys) != len(ent.keys) or not np.array_equal(
            new_keys, ent.keys):
        # C entered with a different pattern: the union pattern moved
        _counter("fallback_structure")
        _drop(key)
        return None
    amask = _dirty_entry_mask(a, dirty_a)
    bmask = _dirty_entry_mask(b, dirty_b)
    if amask is None or bmask is None:
        _counter("fallback_epoch")
        _drop(key)
        return None

    from dbcsr_tpu.mm import multiply as _mm
    from dbcsr_tpu.obs import flight as _flight

    ntrip = len(cand_keys)
    if amask.any() or bmask.any():
        trip_dirty = amask[a_ent] | bmask[b_ent]
        affected = np.unique(cand_keys[trip_dirty])
        recompute = _mm.mask_in_sorted(cand_keys, affected)
    else:
        affected = np.empty(0, np.int64)
        recompute = np.zeros(ntrip, bool)
    n_rec = int(recompute.sum())
    if ntrip and n_rec / ntrip > _MAX_RECOMPUTE_FRACTION:
        _counter("fallback_all_dirty")
        return None  # entry refreshed by the full run's note_executed

    try:
        flops = _execute_splice(key, ent, a, b, c, alpha, new_keys,
                                cand_keys, a_ent, b_ent, recompute,
                                affected, plan_key)
    except _SpliceRejected as exc:
        _counter(exc.result)
        _breaker_trip(exc.result)
        return None
    _breaker["failures"] = 0
    _install(key, a, b, c)  # re-baseline on the just-assembled result
    n_reused = len(new_keys) - len(affected)
    _totals["products"] += 1
    _totals["reused_blocks"] += n_reused
    _totals["recomputed_blocks"] += len(affected)
    reuse_frac = n_reused / max(len(new_keys), 1)
    full_flops = _mm._true_product_flops(a, b)
    saved = max(0, full_flops - flops)
    _totals["saved_flops"] += saved
    from dbcsr_tpu.obs import metrics as _metrics

    _counter("hit_unchanged" if n_rec == 0 else "hit_splice")
    _metrics.counter(
        "dbcsr_tpu_incremental_saved_flops_total",
        "true flops avoided by delta-aware reuse (full product flops "
        "minus the recomputed subset's)",
    ).inc(saved)
    _metrics.counter(
        "dbcsr_tpu_incremental_saved_bytes_total",
        "device bytes of C blocks spliced from the cached result "
        "instead of recomputed",
    ).inc(_spliced_bytes(c, affected))
    _flight.note("incremental", "unchanged" if n_rec == 0 else "splice")
    _flight.note("reuse_fraction", round(reuse_frac, 4))
    return int(flops)


def _spliced_bytes(c, affected) -> int:
    """Exact device bytes of the C blocks served from the cache."""
    from dbcsr_tpu.mm.multiply import mask_in_sorted

    itemsize = int(np.dtype(c.dtype).itemsize)
    aff_mask = mask_in_sorted(c.keys, affected) if len(affected) else \
        np.zeros(len(c.keys), bool)
    total = 0
    for b_id, bin_ in enumerate(c.bins):
        sel = (c.ent_bin == b_id) & ~aff_mask
        total += int(sel.sum()) * bin_.shape[0] * bin_.shape[1] * itemsize
    return total


class _SpliceRejected(Exception):
    """Internal: the splice was aborted (fault, probe mismatch) and the
    caller must fall back to full recompute."""

    def __init__(self, result: str, cause: BaseException | None = None):
        super().__init__(result)
        self.result = result
        self.cause = cause


def _execute_splice(key, ent: _Entry, a, b, c, alpha, new_keys, cand_keys,
                    a_ent, b_ent, recompute, affected, plan_key) -> int:
    """Rebuild C (beta == 0 zeros), run ONLY the triples targeting
    affected C blocks (ABFT live on those launches like any stack
    run), splice every clean block from the cached result, then
    probe-verify the assembled product when the ABFT knob is on."""
    from dbcsr_tpu.acc import abft as _abft
    from dbcsr_tpu.mm import multiply as _mm
    from dbcsr_tpu.resilience import faults as _faults

    try:
        if _faults.active():
            _faults.maybe_inject("incremental", n=str(len(affected)))
        if not len(affected):
            # zero-delta repeat: adopt the cached bins wholesale (the
            # same `mempool.adopt_aliased_bins` the serve cache's
            # install uses) — no rebuild, no launches, no splice
            mempool.adopt_aliased_bins(c, ent.keys, ent.bins)
            flops = 0
        else:
            _mm._rebuild_c(c, new_keys, 0.0)
            sub_plan_key = plan_key + (
                "incremental", digests.index_digest(affected))
            flops = _mm._run_stacks(
                c, a, b, cand_keys[recompute], a_ent[recompute],
                b_ent[recompute], alpha, plan_key=sub_plan_key,
                c_zero=True)
            # splice clean blocks from the cached result (bin geometry
            # is identical: same keys -> same binning -> same buckets)
            aff_mask = _mm.mask_in_sorted(new_keys, affected)
            for b_id, bin_ in enumerate(c.bins):
                shape, cached, count = ent.bins[b_id]
                if shape != bin_.shape or count != bin_.count \
                        or cached.shape != bin_.data.shape:
                    raise _SpliceRejected("fallback_structure")
                sel = np.nonzero((c.ent_bin == b_id) & ~aff_mask)[0]
                if not len(sel):
                    continue
                # row-SELECT, not row-scatter: XLA-CPU lowers a
                # scatter as a serial per-row loop, which dominated
                # the splice on the bench; the where-select runs at
                # memory bandwidth.  The mask is content-stable across
                # an SCF loop's iterations (same dirty subset), so the
                # upload hits the index mirror.
                keep = np.zeros(bin_.data.shape[0], bool)
                keep[c.ent_slot[sel]] = True
                bin_.data = _splice(
                    bin_.data, cached,
                    mempool.upload_index("inc_keep", keep))
        if _faults.active():
            c.map_bin_data(lambda d: _faults.corrupt("incremental", d))
        if _abft.enabled():
            _abft.verify_product(a, b, c, alpha, 0.0, None)
        return flops
    except _SpliceRejected:
        raise
    except _abft.AbftMismatchError as exc:
        _abft.record_recovery("incremental")
        raise _SpliceRejected("fallback_abft", exc) from exc
    except Exception as exc:
        raise _SpliceRejected("fallback_fault", exc) from exc


def note_executed(plan_key, a, b, c, alpha) -> None:
    """Record a fully executed eligible product: the first sighting of
    a (plan, operands) pair only marks it seen; a repeat installs the
    zero-copy result snapshot (aliasing C's final bins, which are
    marked shared so the pool never recycles them under the cache)."""
    global _cache_bytes
    md = mode()
    if md == "off":
        return
    key = _key(plan_key, alpha)
    ids = (id(a), id(b))
    if key not in _cache and _seen.get(key) != ids:
        _seen[key] = ids
        _seen.move_to_end(key)
        while len(_seen) > _SEEN_MAX:
            _seen.popitem(last=False)
        return
    _install(key, a, b, c)


def _install(key, a, b, c) -> None:
    global _cache_bytes
    old = _cache.pop(key, None)
    if old is not None:
        _cache_bytes -= old.nbytes
    ent = _Entry(a, b, c)
    c._bins_shared = True  # the cache aliases these buffers: no donation
    _cache[key] = ent
    _cache_bytes += ent.nbytes
    while _cache and (len(_cache) > _CACHE_MAX_ENTRIES
                      or _cache_bytes > _CACHE_MAX_BYTES):
        if len(_cache) == 1 and _cache_bytes <= _CACHE_MAX_BYTES:
            break
        _, evicted = _cache.popitem(last=False)
        _cache_bytes -= evicted.nbytes


_splice_jit = None  # built on first use (keeps module import jax-light)


def _splice(computed, cached, keep_mask):
    """Per-row select: cached rows where ``keep_mask``, freshly
    computed rows elsewhere; the computed buffer is donated (the
    spliced output replaces it in C)."""
    global _splice_jit
    if _splice_jit is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, donate_argnums=0)
        def _impl(computed, cached, keep_mask):
            return jnp.where(keep_mask[:, None, None], cached, computed)

        _splice_jit = _impl
    return mempool.run_donated(_splice_jit, computed, cached, keep_mask)
