"""Adaptive storage-format planner: per-product dense/stack/composite.

The engine historically executed every product as BCSR stacks, with one
hardcoded escape hatch (`mm.multiply._dense_mode_wanted`) that converts
near-full matrices to a single dense GEMM.  This module makes the
format a PLANNED, per-(product, occupancy, device) decision between
three executions of the identical product:

* ``stack``     — the shape-bucketed BCSR stack engine (the default);
* ``dense``     — whole-panel padded dense GEMM (`_dense_multiply`,
  n/m/k-chunked beyond the canvas cap);
* ``composite`` — the block-diagonal composite panel: C's block-rows
  are greedily grouped into row-panels with narrow k-support, packed
  into ONE batched padded GEMM (`_composite_multiply`) — the serve
  coalescer's batching trick applied inside one matrix.

Decision funnel (first hit wins), resolved once per product and cached
by pattern fingerprints + config + params generation (a tuner
promotion/demotion bumps the generation, so learned crossovers retire
cached plans immediately):

1. ``DBCSR_TPU_MM_FORMAT`` forced format (``reason="forced"``; a
   structurally infeasible force falls back to stack,
   ``reason="ineligible"``);
2. the ``format_plan`` fault site (an injected fault degrades the plan
   to stack, ``reason="fault"`` — never cached);
3. a learned params-table row carrying ``format``/``format_occ``
   columns for this block cell: above the learned occupancy crossover
   the row's format wins (``reason="tuned"``) — this is where the
   autotuner (`dbcsr_tpu.tune`) overrides the model per device;
4. the legacy dense heuristic (`_dense_mode_wanted`: config forcing,
   the occupancy threshold, the emulated-dtype flop-ratio model) —
   preserved bit-for-bit so default behavior never changes
   (``reason="heuristic"``);
5. on an MXU (`effective_platform() == "tpu"`), the
   `obs.costmodel.format_costs` occupancy-parameterized curves: the
   cheapest modeled format among the structurally feasible ones
   (``reason="model"``); guarded by the >= 0.5 candidate-fill rule so
   a structurally sparse C is never silently densified;
6. stack (``reason="default"``; products that cannot take a non-stack
   format at all report ``reason="structural"``).

Every decision lands on ``dbcsr_tpu_format_decision_total{format,
reason}`` and in the product's trace span/flight record; every
EXECUTED product reports back through `note_outcome`, which keeps a
bounded regret ring (model-predicted vs measured GFLOP/s) that the
timeseries collector samples and `tune.miner.mine_format` mines for
re-trial when the planner's choice underperforms its own model.

Import-light: numpy only at import; jax, config, params, costmodel and
`mm.multiply` are reached lazily (multiply imports THIS module lazily
too, so there is no cycle).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

import numpy as np

FORMATS = ("stack", "dense", "composite")

_lock = threading.Lock()
_plan_cache: "collections.OrderedDict" = collections.OrderedDict()
_PLAN_CACHE_MAX = 256
_regret: "collections.deque" = collections.deque(maxlen=256)
# measured/predicted below this ratio marks the decision a regret the
# format miner re-trials (mirrors the tuner's roofline floor idea)
_REGRET_FLOOR = 0.5


class Plan:
    """One product's format decision plus the evidence it rode on."""

    __slots__ = ("fmt", "reason", "panels", "predicted", "cell", "occ",
                 "grid")

    def __init__(self, fmt: str, reason: str, panels=None,
                 predicted: Optional[dict] = None,
                 cell: Optional[tuple] = None, occ: Optional[float] = None,
                 grid: Optional[tuple] = None):
        self.fmt = fmt
        self.reason = reason
        self.panels = panels
        self.predicted = predicted
        self.cell = cell          # (bm, bn, bk, dtype) — uniform products
        self.occ = occ            # pair occupancy: entries/(nbr*nbc*nbk)
        self.grid = grid          # (nbr, nbc, nbk)

    def __repr__(self):
        return f"Plan({self.fmt}, reason={self.reason}, occ={self.occ})"


def _uniform(m) -> bool:
    return (len(np.unique(m.row_blk_sizes)) == 1
            and len(np.unique(m.col_blk_sizes)) == 1)


def _cache_get(key):
    with _lock:
        hit = _plan_cache.get(key)
        if hit is not None:
            _plan_cache.move_to_end(key)
        return hit


def _cache_put(key, plan) -> None:
    with _lock:
        _plan_cache[key] = plan
        while len(_plan_cache) > _PLAN_CACHE_MAX:
            _plan_cache.popitem(last=False)


def reset() -> None:
    """Drop cached plans and regret history (tests, config flips)."""
    with _lock:
        _plan_cache.clear()
        _regret.clear()
        _last_choice.clear()


def _tuned_row(bm: int, bn: int, bk: int, dtype: str) -> Optional[dict]:
    """The params-table row for this block cell IF it carries learned
    format columns (promoted by `tune.store`, adopted from fleet peers,
    or hand-written).  Falls back to the nearest same-device-kind
    format-carrying row (`tune.predictor.format_prior`) so one trialed
    cell informs its shape neighborhood; None otherwise."""
    try:
        from dbcsr_tpu.acc import params as params_mod

        row = params_mod.lookup(bm, bn, bk, dtype)
    except Exception:
        return None
    if row and row.get("format") in FORMATS:
        return row
    try:
        from dbcsr_tpu.tune.predictor import format_prior

        row = format_prior(bm, bn, bk, dtype)
    except Exception:
        return None
    if row and row.get("format") in FORMATS:
        return row
    return None


def choose(a, b, c, *, filter_eps, retain_sparsity, no_limits) -> Plan:
    """Resolve the product's execution format (see the module funnel).
    Cheap on repeat: cached by pattern fingerprints + config + params
    generation + device kind."""
    from dbcsr_tpu.core.config import effective_platform, get_config
    from dbcsr_tpu.mm import multiply as _mm
    from dbcsr_tpu.resilience import faults as _faults

    cfg = get_config()
    # structural gates shared by every non-stack format: these products
    # can only run on the stack engine (filtered/limited/symmetric
    # products, or dense explicitly disabled)
    from dbcsr_tpu.core.matrix import NO_SYMMETRY

    eligible = (
        filter_eps is None and not retain_sparsity and no_limits
        and c.matrix_type == NO_SYMMETRY
        and cfg.mm_dense is not False and cfg.mm_driver != "pallas"
    )
    if not eligible:
        return Plan("stack", "structural")
    # fault boundary: an injected plan fault degrades to stack for THIS
    # product only (never cached — the fault is transient)
    if _faults.active():
        try:
            _faults.maybe_inject("format_plan", name=c.name)
        except BaseException:
            return Plan("stack", "fault")

    from dbcsr_tpu.acc import params as params_mod

    key = (
        a.pattern_fingerprint(), b.pattern_fingerprint(),
        c.pattern_fingerprint(), str(np.dtype(c.dtype)),
        (cfg.mm_format, cfg.mm_dense, cfg.mm_driver,
         cfg.dense_occ_threshold, cfg.dense_flop_ratio,
         cfg.composite_max_panels, cfg.composite_ksup,
         effective_platform()),
        params_mod.generation(),
    )
    plan = _cache_get(key)
    if plan is not None:
        return plan
    plan = _choose_uncached(a, b, c, cfg, _mm)
    _cache_put(key, plan)
    return plan


def _choose_uncached(a, b, c, cfg, _mm) -> Plan:
    from dbcsr_tpu.core.config import effective_platform
    from dbcsr_tpu.obs import costmodel as _costmodel

    uniform = _uniform(a) and _uniform(b) and _uniform(c)
    cell = occ = grid = predicted = None
    entries = 0
    panels = None
    if uniform:
        bm = int(c.row_blk_sizes[0])
        bn = int(c.col_blk_sizes[0])
        bk = int(a.col_blk_sizes[0])
        nbr, nbc, nbk = a.nblkrows, c.nblkcols, a.nblkcols
        cell = (bm, bn, bk, str(np.dtype(c.dtype)))
        grid = (nbr, nbc, nbk)
        entries = max(
            int(round(_mm._true_product_flops(a, b) / (2.0 * bm * bn * bk))),
            0)
        occ = entries / float(max(nbr * nbc * nbk, 1))
        panels = _mm.composite_panels(a, b, c)
        predicted = _costmodel.format_costs(
            nbr=nbr, nbc=nbc, nbk=nbk, bm=bm, bn=bn, bk=bk,
            entries=entries,
            panels=(panels.G, panels.mp, panels.kp) if panels else None,
            dtype=str(np.dtype(c.dtype)),
            itemsize=np.dtype(c.dtype).itemsize)

    def _feasible(fmt: str) -> bool:
        if fmt == "stack":
            return True
        if fmt == "composite":
            return panels is not None
        return True  # dense: the chunked/general paths carry any shape

    def _plan(fmt, reason):
        return Plan(fmt, reason, panels=panels, predicted=predicted,
                    cell=cell, occ=occ, grid=grid)

    # 1. explicit force
    if cfg.mm_format != "auto":
        if _feasible(cfg.mm_format):
            return _plan(cfg.mm_format, "forced")
        return _plan("stack", "ineligible")
    # 3. learned per-device crossover (the tune axis)
    if cell is not None:
        row = _tuned_row(*cell)
        if row is not None:
            fmt = str(row["format"])
            crossover = float(row.get("format_occ", 0.0))
            if occ is not None and occ >= crossover and _feasible(fmt):
                return _plan(fmt, "tuned")
            return _plan("stack", "tuned")
    # 4. the legacy dense heuristic, preserved bit-for-bit
    if _mm._dense_mode_wanted(a, b, c, None, False, True,
                              allow_chunked=True):
        return _plan("dense", "heuristic")
    # 5. MXU cost curves (never densify a structurally sparse C)
    if (uniform and predicted is not None
            and effective_platform() == "tpu"
            and _mm._candidate_fill(a, b) >= 0.5):
        best, best_s = "stack", predicted["stack"]["seconds"]
        for fmt in ("dense", "composite"):
            leg = predicted.get(fmt)
            if leg is not None and _feasible(fmt) \
                    and leg["seconds"] < best_s:
                best, best_s = fmt, leg["seconds"]
        if best != "stack":
            return _plan(best, "model")
    return _plan("stack", "default" if uniform else "structural")


# ------------------------------------------------------- observability

def note_decision(plan: Plan) -> None:
    """Count + annotate one decision (called once per multiply, on the
    product — cache hits count too: the counter measures traffic, the
    cache measures planning cost)."""
    try:
        from dbcsr_tpu.obs import flight as _flight
        from dbcsr_tpu.obs import metrics as _metrics
        from dbcsr_tpu.obs import tracer as _trace

        _metrics.counter(
            "dbcsr_tpu_format_decision_total",
            "storage-format planner decisions by chosen format and "
            "reason (mm.format_planner)",
        ).inc(format=plan.fmt, reason=plan.reason)
        _flight.note("format", plan.fmt)
        _flight.note("format_reason", plan.reason)
        if plan.occ is not None:
            _flight.note("format_occ", round(plan.occ, 4))
        _trace.annotate(format=plan.fmt, format_reason=plan.reason)
        _note_choice_change(plan)
    except Exception:
        pass


# last (format, reason) chosen per cell: a CHANGED choice is a system
# change the causal diagnosis plane's ledger must see (obs.rca) — the
# first sight of a cell is a baseline, not a change, so startup never
# floods the ledger with one entry per cell
_last_choice: dict = {}


def _note_choice_change(plan: Plan) -> None:
    key = str(plan.cell) if plan.cell is not None else "uncelled"
    choice = (plan.fmt, plan.reason)
    with _lock:
        prev = _last_choice.get(key)
        _last_choice[key] = choice
    if prev is None or prev == choice:
        return
    from dbcsr_tpu.obs import events as _events

    _events.publish("format_decision", {
        "cell": key, "format": plan.fmt, "reason": plan.reason,
        "prev": f"{prev[0]}:{prev[1]}",
    })


def note_outcome(plan: Plan, seconds: float, flops: float) -> None:
    """Close the loop on one executed product: measured rate vs the
    model's prediction for the chosen format.  Feeds the regret ring
    (timeseries collector + `tune.miner.mine_format`)."""
    if plan.predicted is None or plan.cell is None or seconds <= 0:
        return
    leg = plan.predicted.get(plan.fmt)
    if not leg or not leg.get("gflops"):
        return
    measured = flops / seconds / 1e9
    predicted = float(leg["gflops"])
    rec = {
        "format": plan.fmt,
        "reason": plan.reason,
        "cell": plan.cell,
        "grid": plan.grid,
        "occ": plan.occ,
        "predicted_gflops": round(predicted, 4),
        "measured_gflops": round(measured, 4),
        "ratio": round(measured / predicted, 6) if predicted else 0.0,
        "predicted_alternatives": {
            f: round(v["gflops"], 4)
            for f, v in plan.predicted.items() if v},
        "t_unix": time.time(),
    }
    with _lock:
        _regret.append(rec)


def regret_records(limit: Optional[int] = None) -> list:
    """Recent outcome records, oldest first (the miner's substrate)."""
    with _lock:
        recs = list(_regret)
    return recs if limit is None else recs[-limit:]


def regret_gauges() -> list:
    """Latest measured/predicted ratio per format — the timeseries
    collector's points (`dbcsr_tpu_format_regret`); a ratio far below
    1.0 means the planner's model overpromised for that format."""
    latest: dict = {}
    with _lock:
        for rec in _regret:
            latest[rec["format"]] = rec["ratio"]
    return [({"format": f}, r) for f, r in sorted(latest.items())]


def mis_crossovers(floor: float = _REGRET_FLOOR) -> list:
    """Cells whose chosen format underperformed the model by more than
    ``floor`` on their latest sighting — the doctor hint's evidence and
    the format miner's candidate source."""
    latest: dict = {}
    with _lock:
        for rec in _regret:
            latest[(rec["cell"], rec["format"])] = rec
    return [r for r in latest.values() if r["ratio"] < floor]
