"""The multiply engine: C := alpha * op(A) * op(B) + beta * C.

Analog of `dbcsr_multiply_generic` (`src/mm/dbcsr_mm.F:336-1030`),
re-designed TPU-first:

* The reference discovers C's pattern inside per-thread recursive
  multiplies with hash-based block lookup (`dbcsr_mm_csr.F:178`);
  here the full symbolic product is computed up front with vectorized
  NumPy (the reference also keeps index work on CPU — SURVEY §7), so
  device work is purely static-shaped batched compute.
* Per-thread work matrices + stack flushing (`dbcsr_mm_multrec.F`,
  `dbcsr_mm_sched.F`) collapse into: one parameter stack per
  (m, n, k) shape-bin triple, sorted by C block then A entry, processed
  by the acc layer's prepared stack plans (`dbcsr_tpu.acc.smm.
  prepare_stack`/`execute_stack`, cached across same-pattern repeats)
  in mm_stack_size chunks.
* Accumulation order is fixed by the sort, giving bit-reproducible
  results per run configuration (north-star checksum requirement).

Filtering semantics follow the reference exactly (`dbcsr_mm.F:360-369`):
on-the-fly skip when ||A_ik||²·||B_kj||² < (eps/max(1, row_count_A(i)))²
with single-precision squared norms (`dbcsr_mm_cannon.F:1098-1105`,
`dbcsr_mm_csr.F:276`, `calc_norms` at `dbcsr_mm_common.F:728`), and a
final pass keeping blocks with ||C||² >= eps²
(`dbcsr_mm_multrec.F:694-748`), skipped when retain_sparsity.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dbcsr_tpu.core import mempool, stats
from dbcsr_tpu.acc import abft as _abft
from dbcsr_tpu.core.kinds import is_complex
from dbcsr_tpu.core.matrix import (
    NO_SYMMETRY,
    BlockSparseMatrix,
    _Bin,
    _bin_entries,
)
from dbcsr_tpu.core.timings import timed
from dbcsr_tpu.obs import costmodel as _costmodel
from dbcsr_tpu.obs import events as _events
from dbcsr_tpu.obs import flight as _flight
from dbcsr_tpu.obs import metrics as _metrics
from dbcsr_tpu.obs import tracer as _trace
from dbcsr_tpu.ops.operations import compress
from dbcsr_tpu.ops.transformations import desymmetrize, new_transposed
from dbcsr_tpu.resilience import faults as _faults
from dbcsr_tpu.utils.rounding import bucket_size


@functools.partial(jax.jit, static_argnames=())
def _scatter_scaled(dst, src, src_slots, dst_slots, beta):
    return dst.at[dst_slots].set(beta * jnp.take(src, src_slots, axis=0), mode="drop")


@jax.jit
def _scatter_scaled_window(dst, src, src_slots, dst_slots, beta, rl, rh, cl, ch):
    """Scatter blocks applying beta only to the in-window element range
    (rl..rh, cl..ch per block, inclusive) — straddling blocks of a
    windowed-beta multiply (ref: the windowed dgemm touches only the
    limited submatrix, `dbcsr_test_multiply.F:631-633`)."""
    from dbcsr_tpu.ops.operations import window_mask

    blk = jnp.take(src, src_slots, axis=0)
    mask = window_mask(blk.shape[1], blk.shape[2], rl, rh, cl, ch)
    factor = jnp.where(mask, beta, jnp.ones((), dst.dtype))
    return dst.at[dst_slots].set(blk * factor, mode="drop")


def _real_scalar(x, dtype):
    """Coerce alpha/beta for a real-dtype product, raising a clear
    TypeError (not a deep cast error) on a nonzero imaginary part."""
    arr = np.asarray(x)
    if np.iscomplexobj(arr):
        if complex(arr).imag != 0.0:
            raise TypeError(
                f"complex alpha/beta with a real matrix C "
                f"(dtype {np.dtype(dtype).name}); use a complex matrix "
                f"or real scalars"
            )
        return complex(arr).real
    return x


def _effective(matrix: BlockSparseMatrix, trans: str) -> BlockSparseMatrix:
    """Resolve op(X): desymmetrize + transpose/conjugate as needed
    (ref transpose wrappers at `dbcsr_mm.F:521-582`)."""
    trans = trans.upper()
    m = desymmetrize(matrix) if matrix.matrix_type != NO_SYMMETRY else matrix
    if trans == "N":
        return m
    if trans == "T":
        return new_transposed(m)
    if trans == "C":
        return new_transposed(m, conjugate=is_complex(m.dtype))
    raise ValueError(f"bad trans flag {trans!r}")


def multiply(
    transa: str,
    transb: str,
    alpha,
    matrix_a: BlockSparseMatrix,
    matrix_b: BlockSparseMatrix,
    beta,
    matrix_c: BlockSparseMatrix,
    retain_sparsity: bool = False,
    filter_eps: Optional[float] = None,
    first_row: Optional[int] = None,
    last_row: Optional[int] = None,
    first_col: Optional[int] = None,
    last_col: Optional[int] = None,
    first_k: Optional[int] = None,
    last_k: Optional[int] = None,
    element_limits=None,
) -> int:
    """Multiply two block-sparse matrices; returns the true flop count.

    The optional first/last row/col/k limits restrict the product to a
    block-index submatrix (0-based, inclusive).  ``element_limits``
    instead gives the reference `dbcsr_multiply` limit arguments at
    ELEMENT granularity — a 6-tuple (first_row, last_row, first_col,
    last_col, first_k, last_k) of 0-based inclusive element indices
    (None entries = open): limits that don't align with block
    boundaries are honored exactly, by cropping op(A)/op(B) at element
    level (ref `dbcsr_crop_matrix` inside `make_m2s`,
    `dbcsr_mm_cannon.F:194-220`).

    With limits, beta scales C only INSIDE the limited window — C
    elements outside keep their old values, like the reference's
    windowed dgemm (`dbcsr_test_multiply.F:631-633`).
    """
    with timed("multiply"):
        for m in (matrix_a, matrix_b, matrix_c):
            if not m.valid:
                m.finalize()
        # C may alias A or B (in-place squaring etc.): snapshot the input's
        # index before C is restructured; device arrays are immutable and
        # donation only touches C's freshly-built buffers, so a shallow
        # copy suffices.
        if matrix_a is matrix_c:
            matrix_a = matrix_a.copy()
        if matrix_b is matrix_c:
            matrix_b = matrix_b.copy()
        a = _effective(matrix_a, transa)
        b = _effective(matrix_b, transb)
        c = matrix_c
        if not np.issubdtype(np.dtype(c.dtype), np.complexfloating):
            # the reference's typed-alpha contract, surfaced clearly: a
            # complex scalar with nonzero imaginary part cannot scale a
            # real product; zero-imag complex scalars coerce
            alpha, beta = (_real_scalar(x, c.dtype) for x in (alpha, beta))
        if not np.array_equal(a.col_blk_sizes, b.row_blk_sizes):
            raise ValueError("inner blockings of op(A), op(B) differ")
        if not np.array_equal(c.row_blk_sizes, a.row_blk_sizes):
            raise ValueError("C row blocking != op(A) row blocking")
        if not np.array_equal(c.col_blk_sizes, b.col_blk_sizes):
            raise ValueError("C col blocking != op(B) col blocking")

        beta_window = None
        if element_limits is not None:
            if any(x is not None for x in (first_row, last_row, first_col,
                                           last_col, first_k, last_k)):
                raise ValueError("give block-index OR element limits, not both")
            (a, b, (first_row, last_row, first_col, last_col, first_k, last_k),
             beta_window) = _apply_element_limits(a, b, c, element_limits)
        elif any(x is not None for x in (first_row, last_row, first_col, last_col)):
            # windowed beta semantics for block limits too
            roff, coff = c.row_blk_offsets, c.col_blk_offsets
            beta_window = (
                int(roff[first_row]) if first_row is not None else 0,
                int(roff[last_row + 1]) - 1 if last_row is not None else c.nfullrows - 1,
                int(coff[first_col]) if first_col is not None else 0,
                int(coff[last_col + 1]) - 1 if last_col is not None else c.nfullcols - 1,
            )

        no_limits = all(
            x is None for x in (first_row, last_row, first_col, last_col, first_k, last_k)
        )
        # flight record + span attributes + correlation id for this
        # product (obs layer): shapes/occupancy now, driver decisions
        # and per-phase ms as the engine makes them, committed on
        # return OR error.  The product_id ties every bus event this
        # multiply causes (breaker trips, faults, failovers, recompiles)
        # to this one record across all three stores.
        product_id = _events.begin_product(
            name=c.name, mnk=[c.nfullrows, c.nfullcols, a.nfullcols])
        _flight.begin(
            op="multiply", name=c.name,
            mnk=(c.nfullrows, c.nfullcols, a.nfullcols),
            occ_a=round(a.occupation(), 4), occ_b=round(b.occupation(), 4),
            occ_c=round(c.occupation(), 4),
            filter_eps=filter_eps, retain_sparsity=retain_sparsity,
            product_id=product_id,
        )
        _trace.annotate(
            name=c.name, m=c.nfullrows, n=c.nfullcols, k=a.nfullcols,
            product_id=product_id,
        )
        try:
            flops = _multiply_body(
                a, b, c, alpha, beta, retain_sparsity, filter_eps,
                first_row, last_row, first_col, last_col, first_k, last_k,
                beta_window, no_limits,
            )
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            rec = _flight.commit(error=err)
            _events.end_product(rec=rec, error=err)
            raise
        _flight.note("flops", flops)
        _flight.note("algorithm", getattr(c, "_mm_algorithm", "?"))
        _trace.annotate(algorithm=getattr(c, "_mm_algorithm", "?"))
        rec = _flight.commit()
        _events.end_product(rec=rec)
        return flops


def _multiply_body(a, b, c, alpha, beta, retain_sparsity, filter_eps,
                   first_row, last_row, first_col, last_col, first_k,
                   last_k, beta_window, no_limits) -> int:
    """The format-planned engine body of `multiply` (split out so the
    flight recorder brackets every exit path exactly once).  The
    storage format — stack, dense, or composite — is resolved by
    `mm.format_planner.choose` (config force, learned tune crossover,
    the legacy dense heuristic, then the costmodel curves)."""
    from dbcsr_tpu.mm import format_planner as _fmt

    plan = _fmt.choose(a, b, c, filter_eps=filter_eps,
                       retain_sparsity=retain_sparsity,
                       no_limits=no_limits)
    _fmt.note_decision(plan)
    if plan.fmt in ("dense", "composite"):
        with timed("multiply_dense"):
            c._mm_algorithm = plan.fmt
            # canvas-path failover: the dense/composite MXU routes and
            # the stack path compute the identical product, so a canvas
            # failure (injected or real — compile gap, OOM, corrupted
            # canvas) degrades to the stack engine instead of killing
            # the multiply.  Only safe while C is still untouched: the
            # canvas paths restructure C last, and the held-identity
            # check proves no restructuring happened.
            held = [b_.data for b_ in c.bins]
            t0 = time.perf_counter()
            try:
                if plan.fmt == "composite" and plan.panels is not None:
                    flops = _composite_multiply(a, b, c, alpha, beta,
                                                plan.panels)
                else:
                    flops = _dense_multiply(a, b, c, alpha, beta)
                _fmt.note_outcome(plan, time.perf_counter() - t0, flops)
                # a canvas-path restructure makes any delta-cache entry
                # for these operands unreachable garbage: drop eagerly
                from dbcsr_tpu.mm import incremental as _inc

                _inc.note_format_executed(a, b)
                return flops
            except Exception as exc:
                if [id(b_.data) for b_ in c.bins] != [id(d) for d in held]:
                    raise  # C already restructured: unrecoverable here
                _note_dense_fallback(exc, driver=plan.fmt)
    c._mm_algorithm = "stack"

    with timed("multiply_index"):
        cand = _candidates(
            a, b, c, filter_eps,
            first_row, last_row, first_col, last_col, first_k, last_k,
        )
        i, j, a_ent, b_ent = cand
        # new C pattern
        old_keys = c.keys
        cand_keys = i * c.nblkcols + j
        if retain_sparsity:
            ok = mask_in_sorted(cand_keys, old_keys)
            i, j, a_ent, b_ent = i[ok], j[ok], a_ent[ok], b_ent[ok]
            cand_keys = cand_keys[ok]
            new_keys = old_keys
        else:
            new_keys = np.union1d(old_keys, np.unique(cand_keys))

    # plan-cache key: patterns + product options fully determine the
    # stack plan for UNFILTERED products.  Filtered products depend on
    # VALUES (the norm filter prunes candidates), so their key
    # additionally digests the surviving candidate list — an iterative
    # chain whose filter keeps reaching the same survivors (the
    # structure-stable steady state) then hits the cache too, paying a
    # host hash instead of the full group-sort + index re-upload.
    # Device-residency gated (mempool.enabled): the unpooled control
    # is the historical rebuild-every-multiply engine.
    plan_key = None
    if filter_eps is None or mempool.enabled():
        from dbcsr_tpu.acc import params as params_mod
        from dbcsr_tpu.acc import precision as precision_mod
        from dbcsr_tpu.core.config import get_config as _cfg

        cfg_ = _cfg()
        plan_key = (
            a.pattern_fingerprint(), b.pattern_fingerprint(),
            c.pattern_fingerprint(),
            str(np.dtype(a.dtype)), str(np.dtype(b.dtype)),
            str(np.dtype(c.dtype)),
            c.matrix_type, retain_sparsity,
            (first_row, last_row, first_col, last_col, first_k, last_k),
            (cfg_.mm_driver, cfg_.use_pallas, cfg_.flat_gather,
             cfg_.mm_stack_size, cfg_.max_kernel_dim,
             cfg_.validate_kernels, cfg_.mm_format),
            # params-table generation: a tuner promotion/demotion
            # (dbcsr_tpu.tune, or any save_entry/invalidate) bumps it,
            # so a cached plan can never serve superseded parameters
            params_mod.generation(),
            # executed-precision state: an adaptive promotion or a
            # chain-scope transition must never be served a cached
            # demoted plan (acc.precision bumps its generation on both)
            precision_mod.plan_token(),
        )
        if filter_eps is not None:
            from dbcsr_tpu.core import digests

            plan_key += ("filtered", float(filter_eps),
                         digests.index_digest(cand_keys, a_ent, b_ent))

    # delta-aware incremental path (mm.incremental): a repeated
    # beta==0 product whose operands carry a known dirty-block delta
    # since its last full execution recomputes only the affected C
    # blocks and splices the rest from the cached device-resident
    # result — bitwise-identical by construction, ABFT-certified, and
    # always falling back to the full path below on any doubt
    inc_eligible = (
        plan_key is not None and filter_eps is None and beta == 0
        and beta_window is None and not retain_sparsity and no_limits
        and mempool.enabled() and c.matrix_type == NO_SYMMETRY
    )
    if inc_eligible:
        from dbcsr_tpu.mm import incremental as _inc

        inc_flops = _inc.maybe_reuse(plan_key, a, b, c, alpha, new_keys,
                                     cand_keys, a_ent, b_ent)
        if inc_flops is not None:
            c._note_mutation(c.keys)  # spliced values installed
            stats.record_multiply(2 * c.nfullrows * c.nfullcols
                                  * a.nfullcols)
            stats.sample_memory()
            return int(inc_flops)

    with timed("multiply_c_assemble"):
        _rebuild_c(c, new_keys, beta, beta_window=beta_window)

    with timed("multiply_stacks"):
        flops = _run_stacks(c, a, b, cand_keys, a_ent, b_ent, alpha,
                            plan_key=plan_key,
                            c_zero=(beta == 0 and beta_window is None))
    # the stack launches rebound bin data after _rebuild_c's structure
    # note: stamp the completed values so epoch consumers (value
    # digests, delta caches) never see a pre-completion epoch as current
    c._note_mutation(c.keys)
    if inc_eligible:
        from dbcsr_tpu.mm import incremental as _inc

        _inc.note_executed(plan_key, a, b, c, alpha)

    if filter_eps is not None and not retain_sparsity:
        with timed("multiply_filter"):
            nblks_pre = c.nblks
            norms = c.block_norms()
            compress(c, norms.astype(np.float64) ** 2 >= float(filter_eps) ** 2)
            _flight.note("filtered_blocks", nblks_pre - c.nblks)
            _flight.note("kept_blocks", c.nblks)

    mflops = 2 * c.nfullrows * c.nfullcols * a.nfullcols
    stats.record_multiply(mflops)
    stats.sample_memory()
    return int(flops)


def mask_in_sorted(cand_keys: np.ndarray, sorted_keys: np.ndarray) -> np.ndarray:
    """Membership of each cand_key in sorted_keys (retain_sparsity's
    pattern lock, shared by the single-chip and mesh engines)."""
    if len(sorted_keys) == 0:
        return np.zeros(len(cand_keys), bool)
    pos = np.searchsorted(sorted_keys, cand_keys)
    return (pos < len(sorted_keys)) & (
        sorted_keys[np.minimum(pos, len(sorted_keys) - 1)] == cand_keys
    )


def _true_product_flops(a, b) -> int:
    """Exact flop count of the block-sparse product without enumerating
    candidate triples: sum_k 2 * W_m(k) * W_n(k) * k_k where W_m(k) is
    the total row extent of A's stored blocks in block-col k and W_n(k)
    the total col extent of B's stored blocks in block-row k.  O(nblks)
    — the 'true flops' of `dbcsr_mm.F:664-667`, computable up front."""
    if a.nblks == 0 or b.nblks == 0:
        return 0
    ar, ac = a.entry_coords()
    br, bc = b.entry_coords()
    wa = np.bincount(ac, weights=a.row_blk_sizes[ar].astype(np.float64),
                     minlength=a.nblkcols)
    wb = np.bincount(br, weights=b.col_blk_sizes[bc].astype(np.float64),
                     minlength=b.nblkrows)
    kk = a.col_blk_sizes.astype(np.float64)
    return int(round(2.0 * float(np.dot(wa * kk, wb))))


# canvases beyond this element count make the dense cost model decline
# (3 canvases must fit HBM comfortably; 10k^2 f64 = 0.8 GB each)
_DENSE_MAX_CANVAS = 2 * 10**8


def _dense_chunking(nbr, nbc, nbk, bm, bn, bk):
    """(block-rows per m-strip, k-block-cols per k-strip, block-cols
    per n-strip) so every strip canvas (A: m-strip x k-strip, B:
    k-strip x n-strip, C: m-strip x n-strip) fits `_DENSE_MAX_CANVAS`
    elements, or None when even single-block strips cannot fit.  Wide-N
    products (one full-width C block-row over the cap) chunk the n axis
    too instead of declining dense — the cost model used to silently
    keep such products on the stack path."""
    cap = _DENSE_MAX_CANVAS
    ncb = nbc
    if bm * nbc * bn > cap:
        ncb = min(nbc, max(1, cap // (bm * bn)))
    n_el = ncb * bn
    mrb = min(nbr, max(1, cap // (bm * n_el)))
    kcb = min(nbk, max(1, cap // (bk * max(mrb * bm, n_el))))
    if (mrb * bm) * (kcb * bk) > cap or (kcb * bk) * n_el > cap \
            or (mrb * bm) * n_el > cap:
        return None
    return mrb, kcb, ncb


def _dense_mode_wanted(a, b, c, filter_eps, retain_sparsity, no_limits,
                       allow_chunked=False) -> bool:
    """Dense-mode decision (ref `dbcsr_mm.F:593-617`): near-full uniformly
    blocked matrices degrade gracefully to one dense MXU matmul.

    TPU extension beyond the reference's occupancy gate: for dtypes the
    chip only EMULATES (f64/c128 run as split-f32/bf16 passes), tiny
    per-block dots are so MXU-starved that one dense matmul beats the
    stack path well below occ 0.1 — measured 2.33 TFLOP/s (marketing)
    dense vs 7.3 GFLOP/s grouped-sparse for the 23^3 north-star config
    (PERF_NOTES.md).  A flop-ratio cost model decides: go dense when
    dense_flops < dense_flop_ratio * true_sparse_flops.  The result is
    identical either way (same product, same final pattern semantics);
    only time-to-solution changes."""
    from dbcsr_tpu.core.config import get_config

    cfg = get_config()
    if cfg.mm_dense is False or cfg.mm_driver == "pallas":
        return False
    if filter_eps is not None or retain_sparsity or not no_limits:
        return False
    if c.matrix_type != NO_SYMMETRY:
        return False
    if cfg.mm_dense is True or cfg.mm_driver == "dense":
        _flight.note("dense_why", "config-forced")
        return True
    th = cfg.dense_occ_threshold
    if a.occupation() >= th and b.occupation() >= th:
        _flight.note("dense_why", f"occupancy>={th}")
        return True
    # emulated-dtype cost model (TPU only).  Guards beyond the flop
    # ratio: an explicitly forced stack driver wins, and the product's
    # EXPECTED block fill must be near-full — dense mode stores the full
    # pattern, which must not silently densify a structurally sparse
    # C (block-diagonal/banded operands keep the stack path).
    if cfg.mm_driver != "auto":
        return False
    if cfg.dense_flop_ratio <= 0:
        return False
    if np.dtype(c.dtype) not in (np.float64, np.complex128):
        return False
    from dbcsr_tpu.core.config import effective_platform

    if effective_platform() != "tpu":
        return False
    mm, nn, kk = a.nfullrows, b.nfullcols, a.nfullcols
    if max(mm * kk, kk * nn, mm * nn) > _DENSE_MAX_CANVAS:
        # beyond the canvas cap the dense route survives only via the
        # k/m-strip chunked path (single-chip, uniform blockings) — the
        # reference's dense mode is not size-capped (dbcsr_mm.F:593-617)
        if not allow_chunked:
            return False
        if any(
            len(np.unique(m.row_blk_sizes)) > 1
            or len(np.unique(m.col_blk_sizes)) > 1
            for m in (a, b, c)
        ):
            return False
        if _dense_chunking(
            a.nblkrows, c.nblkcols, a.nblkcols,
            int(a.row_blk_sizes[0]), int(b.col_blk_sizes[0]),
            int(a.col_blk_sizes[0]),
        ) is None:
            return False
    if _candidate_fill(a, b) < 0.5:
        return False
    dense_flops = 2.0 * mm * nn * kk
    wanted = dense_flops < cfg.dense_flop_ratio * _true_product_flops(a, b)
    if wanted:
        _flight.note("dense_why", "cost-model:emulated-dtype")
    return wanted


def _note_dense_fallback(exc: BaseException, driver: str = "dense") -> None:
    """Record a canvas-path (dense/composite) → stack failover, the
    mm-layer sibling of `acc.smm`'s stack-driver chain — emitted
    through the same smm helpers so the counter/trace/flight schema
    stays single-sourced."""
    from dbcsr_tpu.acc import smm as _smm

    kind = _smm._classify_failure(exc)
    _smm._record_driver_failure(driver, kind, exc, ())
    _smm._record_fallback(driver, "stack", ())
    if kind == "sdc":
        # C was untouched (held-identity check) and the stack engine
        # recomputes the product: the detected canvas SDC is healed
        _abft.record_recovery(driver)
    _flight.note("dense_fallback", f"{type(exc).__name__}: {exc}"[:200])


def _dense_guard(x):
    """Fault hook + opt-in finite check for a dense-path result, BEFORE
    it is committed into C (so the dense→stack failover sees an
    untouched C).  One `active()` check when disabled."""
    if _faults.active():
        x = _faults.corrupt("dense", x)
    from dbcsr_tpu.acc import smm as _smm

    if _smm._output_checks_enabled() and _smm._output_corrupted(x):
        raise _smm.CorruptedOutputError(
            "dense path produced non-finite output")
    return x


_fill_cache: "OrderedDict" = None  # created lazily; pattern-keyed


def _candidate_fill(a, b) -> float:
    """Fraction of C blocks the symbolic product would store.  EXACT
    (one host float32 boolean matmul over the block grids) when the
    grid volume and temp size allow — structured patterns (triangular,
    banded) are what the guard exists for, and a random-pattern
    estimate misses them; beyond the caps, fall back to the Poisson
    model.  Memoized by pattern fingerprints: repeated same-pattern
    multiplies (SCF loops) pay the matmul once."""
    import collections

    global _fill_cache
    nbr, nbk, nbc = a.nblkrows, a.nblkcols, b.nblkcols
    if a.nblks == 0 or b.nblks == 0 or nbr * nbc == 0:
        return 0.0
    exact_ok = (
        float(nbr) * nbk * nbc <= 1e9
        and float(nbr) * nbk + float(nbk) * nbc + float(nbr) * nbc <= 5e7
    )
    if not exact_ok:
        lam = float(a.nblks) * b.nblks / (float(nbr) * nbc * nbk)
        return 1.0 - float(np.exp(-lam))
    key = (a.pattern_fingerprint(), b.pattern_fingerprint())
    if _fill_cache is None:
        _fill_cache = collections.OrderedDict()
    if key in _fill_cache:
        _fill_cache.move_to_end(key)
        return _fill_cache[key]
    ar, ac = a.entry_coords()
    br, bc = b.entry_coords()
    ia = np.zeros((nbr, nbk), np.float32)
    ia[ar, ac] = 1.0
    ib = np.zeros((nbk, nbc), np.float32)
    ib[br, bc] = 1.0
    fill = float(np.count_nonzero(ia @ ib)) / (nbr * nbc)
    _fill_cache[key] = fill
    while len(_fill_cache) > 64:
        _fill_cache.popitem(last=False)
    return fill


@functools.partial(jax.jit, static_argnames=("nbr", "nbc", "bm", "bn"))
def _blocks_to_dense(data, rows, cols, nbr, nbc, bm, bn):
    """Uniform-blocked scatter to a 2-D canvas via element offsets.

    Deliberately NOT via an (nbr, nbc, bm, bn) grid intermediate: TPU
    tile padding blows a (435, 435, 23, 23) f64 grid up 5.8x (~4.5 GB);
    the 2-D canvas pads ~1.0x.  Three such grid temps pushed the
    nonempty-C north-star dense multiply from ~1 s to ~6.7 s (HBM
    thrash/remat)."""
    ro = (rows * bm).astype(jnp.int32)
    co = (cols * bn).astype(jnp.int32)
    canvas = jnp.zeros((nbr * bm, nbc * bn), data.dtype)
    return _scatter_bin_to_canvas(canvas, data, ro, co, bm=bm, bn=bn)


def _carve_choice() -> str:
    """The dense-carve lowering, read OUTSIDE jit at every call site
    and threaded in as a static argument — so the choice keys the jit
    cache and an env change mid-process retraces instead of silently
    keeping the stale lowering (ADVICE r4)."""
    return os.environ.get("DBCSR_TPU_DENSE_CARVE", "gather")


def _carve_full_pattern(cd, nbr, nbc, bm, bn, carve):
    """Carve a product canvas into the FULL row-major block pattern.

    Two lowerings, selected by ``carve`` (from ``DBCSR_TPU_DENSE_CARVE``
    via `_carve_choice`, a static jit argument at every caller):
    * ``gather`` — element-offset advanced-indexing gather (the
      historical path): builds (nbr*nbc, bm, bn) index tensors, i.e. an
      element-granular XLA gather over the whole canvas.
    * ``reshape`` — reshape/transpose/reshape: the full row-major
      carve is a pure layout permutation, which XLA lowers to a
      near-bandwidth copy instead of a 10^8-entry gather.  The 4-D
      intermediate is transient inside one fused program (the round-2
      HBM-thrash lesson was about MATERIALIZED grid temps across
      program boundaries) — but until it is A/B-timed on real
      hardware the measured ``gather`` path stays the default."""
    if carve == "gather":
        keys = jnp.arange(nbr * nbc, dtype=jnp.int32)
        ro = (keys // nbc) * bm
        co = (keys % nbc) * bn
        return _gather_bin_from_canvas(cd, ro, co, bm=bm, bn=bn)
    return (
        cd.reshape(nbr, bm, nbc, bn)
        .transpose(0, 2, 1, 3)
        .reshape(nbr * nbc, bm, bn)
    )


@functools.partial(jax.jit, donate_argnums=2,
                   static_argnames=("nbr", "nbc", "bm", "bn", "carve"))
def _dense_product_to_blocks(ad, bd, c_blocks, c_keys, alpha, beta, nbr, nbc,
                             bm, bn, carve):
    """Matmul on 2-D canvases, then carve the FULL row-major block
    pattern straight off the product canvas and scatter-add beta*old
    in block layout (position of old key k in the full pattern = k)."""
    acc = ad.dtype
    cd = jax.lax.dot_general(
        ad, bd, (((1,), (0,)), ((), ())), precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=acc,
    )
    out = alpha * _carve_full_pattern(cd, nbr, nbc, bm, bn, carve)
    return out.at[c_keys].add(beta * c_blocks.astype(acc), mode="drop")


@jax.jit
def _dense_dot_only(ad, bd):
    """Profile-mode split: the bare canvas matmul as its own program so
    a fence can time it separately from the carve."""
    return jax.lax.dot_general(
        ad, bd, (((1,), (0,)), ((), ())), precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=ad.dtype,
    )


@functools.partial(jax.jit, donate_argnums=(0, 1),
                   static_argnames=("nbr", "nbc", "bm", "bn", "carve"))
def _dense_carve_only(cd, c_blocks, c_keys, alpha, beta, nbr, nbc, bm, bn,
                      carve):
    """Profile-mode split: carve + beta-merge as its own program."""
    out = alpha * _carve_full_pattern(cd, nbr, nbc, bm, bn, carve)
    return out.at[c_keys].add(beta * c_blocks.astype(out.dtype), mode="drop")


@functools.partial(jax.jit, donate_argnums=0, static_argnames=("bm", "bn"))
def _scatter_bin_to_canvas(canvas, blocks, row_off, col_off, bm: int, bn: int):
    """Scatter an (N, bm, bn) bin onto a dense (M, K) canvas at element
    offsets — the make_dense data movement, on device.  Slots whose
    offsets are out of range are dropped (callers pass the bin's FULL
    bucket-padded buffer with out-of-range offsets for dead slots, so
    the jit shape is the stable bucket capacity, not the live count)."""
    r_idx = row_off[:, None, None] + jnp.arange(bm)[None, :, None]
    c_idx = col_off[:, None, None] + jnp.arange(bn)[None, None, :]
    return canvas.at[r_idx, c_idx].set(blocks, mode="drop")


@functools.partial(jax.jit, static_argnames=("bm", "bn"))
def _gather_bin_from_canvas(canvas, row_off, col_off, bm: int, bn: int):
    """Inverse carve: (N, bm, bn) patches from a dense canvas."""
    r_idx = row_off[:, None, None] + jnp.arange(bm)[None, :, None]
    c_idx = col_off[:, None, None] + jnp.arange(bn)[None, None, :]
    return canvas[r_idx, c_idx]


_dense_const_cache = None  # created lazily; OrderedDict LRU


def _dense_const(key, build):
    """Small device-constant LRU for the dense path's per-multiply
    h2d uploads (alpha/beta scalars, C's key vector): repeated
    same-pattern multiplies (driver reps, SCF loops) would otherwise
    pay a host->device round trip per rep per constant — visible
    through the remote tunnel.  Keys embed the full content
    (value/dtype, or the key vector's bytes), so staleness is
    impossible; LRU-bounded like _fill_cache/_plan_cache."""
    import collections

    global _dense_const_cache
    if _dense_const_cache is None:
        _dense_const_cache = collections.OrderedDict()
    hit = _dense_const_cache.get(key)
    if hit is None:
        hit = build()
        _dense_const_cache[key] = hit
        while len(_dense_const_cache) > 64:
            _dense_const_cache.popitem(last=False)
    else:
        _dense_const_cache.move_to_end(key)
    return hit


def _dense_canvas_cached(m: BlockSparseMatrix, build) -> object:
    """Device canvas of ``m``, cached on the instance keyed by its bin
    data-array identities (jax arrays are immutable, and the cache holds
    the arrays so ids cannot be recycled): repeated dense-mode
    multiplies with unchanged operands skip the scatter entirely.
    ``build`` constructs the canvas on a miss."""
    from dbcsr_tpu.core import digests

    key = digests.buffers_key(b.data for b in m.bins)
    cache = getattr(m, "_dense_canvas_cache", None)
    if cache is not None and cache[0] == key:
        return cache[1]
    # the mutation funnels (map_bin_data / set_structure_from_device)
    # drop the attribute, so a live cache is always for current data
    canvas = build()
    m._dense_canvas_cache = (key, canvas, [b.data for b in m.bins])
    return canvas


def _to_dense_device(m: BlockSparseMatrix):
    """Densify a (possibly non-uniformly blocked) matrix on device."""
    canvas = jnp.zeros((m.nfullrows, m.nfullcols), m.dtype)
    if m.nblks == 0:
        return canvas
    rows, cols = m.entry_coords()
    roff = m.row_blk_offsets[rows]
    coff = m.col_blk_offsets[cols]
    for b_id, b in enumerate(m.bins):
        if b.count == 0:
            continue

        def _offsets(b_id=b_id, b=b):
            sel = np.nonzero(m.ent_bin == b_id)[0]
            cap = b.data.shape[0]
            # dead (bucket-padding) slots get out-of-range offsets ->
            # dropped; the full-capacity buffer keeps the jit shape
            # stable across counts
            ro = np.full(cap, m.nfullrows, np.int64)
            co = np.full(cap, m.nfullcols, np.int64)
            ro[m.ent_slot[sel]] = roff[sel]
            co[m.ent_slot[sel]] = coff[sel]
            return jnp.asarray(ro), jnp.asarray(co)

        # structure-derived offsets ride the per-matrix device mirror:
        # a repeated same-pattern densify uploads them once
        ro_d, co_d = m.device_index(("dense_off", b_id), _offsets)
        canvas = _scatter_bin_to_canvas(
            canvas, b.data, ro_d, co_d, bm=b.shape[0], bn=b.shape[1],
        )
    return canvas


def _dense_multiply_general(a, b, c, alpha, beta) -> int:
    """Dense mode for arbitrary (non-uniform) blockings: densify on
    device, one MXU matmul, carve C back into its own full blocking
    (the `dbcsr_make_dense`/`dbcsr_make_undense` re-blocking pair,
    `dbcsr_mm.F:593-617`, generalized to one flat dense canvas).

    THIS is the production north-star path: m=10000 with (1,23) sizes
    expands to 434x23 + one 18 block (ceil-division blocking), so the
    uniform `_dense_multiply` never fires for it.  The profile buckets
    and the gather/reshape carve A/B therefore live here too — a
    hardware window spent profiling the uniform path would attribute
    the wrong program."""
    profile = os.environ.get("DBCSR_TPU_DENSE_PROFILE") == "1"
    if profile:
        from dbcsr_tpu.utils.sync import fetch_fence as _ff

    t_start = time.perf_counter()
    _metrics.record_jit(
        "mm.multiply._dense_general_dot",
        (a.nfullrows, b.nfullcols, a.nfullcols, str(np.dtype(c.dtype)),
         _carve_choice()),
    )
    with timed("dense_canvas_ab"):
        ad = _dense_canvas_cached(a, lambda: _to_dense_device(a))
        bd = _dense_canvas_cached(b, lambda: _to_dense_device(b))
        if profile:
            _ff(ad), _ff(bd)
    acc = ad.dtype
    with timed("dense_dot"):
        cd = jax.lax.dot_general(
            ad, bd, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=acc,
        )
        dt_name = str(np.dtype(c.dtype))
        alpha_dev = _dense_const(("scalar", complex(alpha), dt_name),
                                 lambda: jnp.asarray(alpha, dtype=c.dtype))
        beta_dev = _dense_const(("scalar", complex(beta), dt_name),
                                lambda: jnp.asarray(beta, dtype=c.dtype))
        cd = alpha_dev * cd
        c_old_dense = (_to_dense_device(c)
                       if beta != 0 and c.nblks else None)
        if c_old_dense is not None:
            cd = cd + beta_dev * c_old_dense
        cd = _dense_guard(cd)
        if _abft.enabled():
            _abft.check_dense_canvas(cd, ad, bd, c_old_dense, alpha,
                                     beta, dtype=c.dtype)
        # the old-C canvas (possibly hundreds of MB) must not stay
        # alive through carve/finalize: its uses end here
        del c_old_dense
        if profile:
            _ff(cd)
    with timed("dense_carve"):
        carve_full_pattern(c, cd)
        if profile:
            for bb in c.bins:
                _ff(bb.data)
    # marketing flops = the dense work performed; the RETURN value is the
    # true flops of the sparse product (comparable across algorithms,
    # ref marketing-vs-true `dbcsr_mm.F:664-667`)
    dcost = _costmodel.dense_cost(
        c.nfullrows, c.nfullcols, a.nfullcols,
        itemsize=np.dtype(c.dtype).itemsize)
    stats.record_driver(
        "dense", dcost["flops"], nbytes=dcost["bytes"],
        seconds=time.perf_counter() - t_start,
        dtype=str(np.dtype(c.dtype)))
    stats.record_multiply(2 * c.nfullrows * c.nfullcols * a.nfullcols)
    return _true_product_flops(a, b)


def _near_uniform(sizes) -> bool:
    """All block sizes equal except a possibly-smaller LAST one — the
    shape every ceil-division blocking (the perf driver's (1, s) sizes,
    `expand_block_sizes`) produces.  Offsets then align to multiples of
    the leading size, so a zero-padded canvas carves as a pure layout
    permutation."""
    if len(sizes) == 0:
        return False
    s0 = int(sizes[0])
    return bool(np.all(np.asarray(sizes[:-1]) == s0) and int(sizes[-1]) <= s0)


@functools.partial(jax.jit, static_argnames=("nbr", "nbc", "bm", "bn"))
def _carve_padded_reshape(cd, nbr, nbc, bm, bn):
    """Pad the canvas to (nbr*bm, nbc*bn) and carve the full row-major
    pattern via reshape/transpose — a near-bandwidth layout permutation
    instead of an element-granular gather (the `reshape` leg of the
    DBCSR_TPU_DENSE_CARVE A/B for near-uniform blockings)."""
    pm = nbr * bm - cd.shape[0]
    pn = nbc * bn - cd.shape[1]
    if pm or pn:
        cd = jnp.pad(cd, ((0, pm), (0, pn)))
    return (
        cd.reshape(nbr, bm, nbc, bn)
        .transpose(0, 2, 1, 3)
        .reshape(nbr * nbc, bm, bn)
    )


def carve_full_pattern(c, cd) -> None:
    """Carve a dense device canvas into ``c``'s FULL block pattern, bin
    by bin (`dbcsr_make_undense`, `dbcsr_mm.F:770-810`); shared by the
    single-chip and mesh dense modes.

    Two lowerings (the production side of the DBCSR_TPU_DENSE_CARVE
    A/B — `_carve_choice` is read outside jit on every call):
    * ``gather`` — per-bin element-offset gathers off the canvas (the
      historical path; at the north star that is ~10^8 index entries).
    * ``reshape`` — for near-uniform blockings (uniform except a
      smaller last row/col block, i.e. every ceil-division blocking):
      one padded reshape/transpose carve, then per-bin BLOCK-granular
      takes and edge slices.  Falls back to gather when the blocking
      is genuinely irregular."""
    nbr, nbc = c.nblkrows, c.nblkcols
    new_keys = np.arange(nbr * nbc, dtype=np.int64)
    rows = new_keys // nbc
    cols = new_keys % nbc
    nb, nsl, shapes = _bin_entries(c.row_blk_sizes, c.col_blk_sizes, rows, cols)
    use_reshape = (
        _carve_choice() == "reshape"
        and _near_uniform(c.row_blk_sizes)
        and _near_uniform(c.col_blk_sizes)
    )
    carved = None
    if use_reshape:
        carved = _carve_padded_reshape(
            cd, nbr, nbc,
            int(c.row_blk_sizes[0]), int(c.col_blk_sizes[0]),
        )
    roff = c.row_blk_offsets[rows]
    coff = c.col_blk_offsets[cols]
    bins = []
    for b_id, (bm, bn) in enumerate(shapes):
        sel = np.nonzero(nb == b_id)[0]
        count = len(sel)
        if use_reshape:
            idx = np.empty(count, np.int64)
            idx[nsl[sel]] = sel  # block-granular: flat key IS the
            data = jnp.take(carved, jnp.asarray(idx), axis=0)  # carved row
            if data.shape[1] != bm or data.shape[2] != bn:
                data = data[:, :int(bm), :int(bn)]  # edge blocks: crop pad
        else:
            ro = np.empty(count, np.int64)
            co = np.empty(count, np.int64)
            ro[nsl[sel]] = roff[sel]
            co[nsl[sel]] = coff[sel]
            data = _gather_bin_from_canvas(
                cd, jnp.asarray(ro), jnp.asarray(co), bm=int(bm), bn=int(bn)
            )
        cap = bucket_size(count)
        if cap > count:
            data = jnp.concatenate(
                [data, jnp.zeros((cap - count, int(bm), int(bn)), data.dtype)]
            )
        bins.append(_Bin((int(bm), int(bn)), data, count))
    c.set_structure_from_device(new_keys, bins, binning=(nb, nsl, shapes))


def _dense_multiply(a, b, c, alpha, beta) -> int:
    """Dense-mode path: scatter blocks to dense, one MXU matmul, carve C
    back into a full block pattern (ref `dbcsr_make_dense` +
    `use_dense_mult`, `dbcsr_mm.F:593-617,770-810`)."""
    if _faults.active():
        _faults.maybe_inject("dense")
    for m in (a, b, c):
        if len(np.unique(m.row_blk_sizes)) > 1 or len(np.unique(m.col_blk_sizes)) > 1:
            return _dense_multiply_general(a, b, c, alpha, beta)
    bm = int(c.row_blk_sizes[0])
    bn = int(c.col_blk_sizes[0])
    bk = int(a.col_blk_sizes[0])
    nbr, nbc, nbk = a.nblkrows, c.nblkcols, a.nblkcols
    if max(a.nfullrows * a.nfullcols, a.nfullcols * b.nfullcols,
           a.nfullrows * b.nfullcols) > _DENSE_MAX_CANVAS:
        return _dense_multiply_chunked(a, b, c, alpha, beta)
    def _build(m, nr, nc_, brow, bcol):
        rows, cols = m.entry_coords()
        return _blocks_to_dense(
            m.bins[0].data[: m.nblks] if m.nblks
            else jnp.zeros((0, brow, bcol), c.dtype),
            mempool.upload_index("dense_rows", rows),
            mempool.upload_index("dense_cols", cols), nr, nc_, brow, bcol,
        )

    profile = os.environ.get("DBCSR_TPU_DENSE_PROFILE") == "1"
    if profile:
        from dbcsr_tpu.utils.sync import fetch_fence as _ff

    t_start = time.perf_counter()
    dense_jit_key = (nbr, nbc, nbk, bm, bn, bk, str(np.dtype(c.dtype)),
                     _carve_choice())
    dense_compiled = _metrics.record_jit(
        "mm.multiply._dense_product_to_blocks", dense_jit_key,
    )
    with timed("dense_canvas_ab"):
        ad = _dense_canvas_cached(a, lambda: _build(a, nbr, nbk, bm, bk))
        bd = _dense_canvas_cached(b, lambda: _build(b, nbk, nbc, bk, bn))
        if profile:
            _ff(ad), _ff(bd)
    c_blocks = (
        c.bins[0].data[: c.nblks]
        if c.nblks
        else jnp.zeros((0, bm, bn), c.dtype)
    )
    dt_name = str(np.dtype(c.dtype))
    alpha_dev = _dense_const(
        ("scalar", complex(alpha), dt_name),
        lambda: jnp.asarray(alpha, dtype=c.dtype),
    )
    beta_dev = _dense_const(
        ("scalar", complex(beta), dt_name),
        lambda: jnp.asarray(beta, dtype=c.dtype),
    )
    keys32 = c.keys.astype(np.int32)
    c_keys_dev = _dense_const(
        ("ckeys", nbr, nbc, keys32.tobytes()),
        lambda: jnp.asarray(keys32),
    )
    if profile:
        # split programs + fences: attribute dot vs carve separately
        # (production fuses them — this is measurement-only)
        with timed("dense_dot"):
            cd = _dense_dot_only(ad, bd)
            _ff(cd)
        with timed("dense_carve"):
            out = _dense_carve_only(
                cd, c_blocks, c_keys_dev,
                alpha_dev, beta_dev, nbr, nbc, bm, bn,
                carve=_carve_choice(),
            )
            _ff(out)
    else:
        if dense_compiled and _costmodel.xla_capture_enabled():
            dcost = _costmodel.dense_cost(
                nbr * bm, nbc * bn, nbk * bk,
                itemsize=np.dtype(c.dtype).itemsize)
            _costmodel.capture_xla_cost(
                "mm.multiply._dense_product_to_blocks", dense_jit_key,
                _dense_product_to_blocks,
                (ad, bd, c_blocks, c_keys_dev, alpha_dev, beta_dev,
                 nbr, nbc, bm, bn),
                kwargs={"carve": _carve_choice()},
                model={"flops": dcost["flops"], "bytes": dcost["bytes"]},
            )
        out = _dense_product_to_blocks(
            ad, bd, c_blocks, c_keys_dev,
            alpha_dev, beta_dev, nbr, nbc, bm, bn,
            carve=_carve_choice(),
        )
    out = _dense_guard(out)
    if _abft.enabled():
        # the carved block pattern IS a layout permutation of the
        # result canvas: un-permute and probe-verify against the
        # operand canvases (+ the old-C canvas when beta != 0)
        res_canvas = (out.reshape(nbr, nbc, bm, bn)
                      .transpose(0, 2, 1, 3).reshape(nbr * bm, nbc * bn))
        c_old_canvas = (_build(c, nbr, nbc, bm, bn)
                        if beta != 0 and c.nblks else None)
        _abft.check_dense_canvas(res_canvas, ad, bd, c_old_canvas,
                                 alpha, beta, dtype=c.dtype)
        # probe canvases are full-N^2 buffers: release before finalize
        del res_canvas, c_old_canvas
    with timed("dense_finalize"):
        new_keys = np.arange(nbr * nbc, dtype=np.int64)  # full pattern, row-major
        cap = bucket_size(len(new_keys))
        pad = cap - len(new_keys)
        if pad:
            out = jnp.concatenate([out, jnp.zeros((pad, bm, bn), out.dtype)])
        c.set_structure_from_device(new_keys, [_Bin((bm, bn), out, len(new_keys))])
        if profile:
            _ff(c.bins[0].data)
    stats.record_stack(
        bm, bn, bk, nbr * nbc * nbk, driver="dense",
        seconds=time.perf_counter() - t_start,
        nbytes=_costmodel.dense_cost(
            nbr * bm, nbc * bn, nbk * bk,
            itemsize=np.dtype(c.dtype).itemsize)["bytes"],
        dtype=str(np.dtype(c.dtype)),
    )
    stats.record_multiply(2 * nbr * bm * nbc * bn * nbk * bk)
    return _true_product_flops(a, b)


@functools.partial(
    jax.jit, donate_argnums=0,
    static_argnames=("m_el", "k_el", "n_el", "bm", "bn", "bk"),
)
def _dense_strip_matmul(cd, a_data, a_ro, a_co, b_data, b_ro, b_co,
                        *, m_el, k_el, n_el, bm, bn, bk):
    """One (m-strip x k-strip) @ (k-strip x N) canvas accumulation.
    Operand strips are scattered from the FULL bin buffers with
    out-of-strip blocks carrying dropped (out-of-range) offsets, so the
    jit shape is the stable bucket capacity for every strip."""
    ad = _scatter_bin_to_canvas(
        jnp.zeros((m_el, k_el), a_data.dtype), a_data, a_ro, a_co,
        bm=bm, bn=bk,
    )
    bd = _scatter_bin_to_canvas(
        jnp.zeros((k_el, n_el), b_data.dtype), b_data, b_ro, b_co,
        bm=bk, bn=bn,
    )
    return cd + jax.lax.dot_general(
        ad, bd, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=cd.dtype,
    )


@functools.partial(
    jax.jit, donate_argnums=0,
    static_argnames=("nbc", "bm", "bn", "rows", "carve"),
)
def _dense_strip_to_blocks(cd, c_blocks, strip_pos, alpha, beta,
                           *, nbc, bm, bn, rows, carve):
    """Carve one C m-strip canvas into its full row-major block pattern
    and merge beta*old (strip_pos: old block -> strip-local full-pattern
    position, out-of-strip dropped).  A strip is a full row-major
    pattern over ``rows`` block rows, so it shares the gather/reshape
    carve selection with the unchunked path."""
    out = alpha * _carve_full_pattern(cd, rows, nbc, bm, bn, carve)
    return out.at[strip_pos].add(beta * c_blocks.astype(out.dtype), mode="drop")


def _dense_multiply_chunked(a, b, c, alpha, beta) -> int:
    """Dense mode beyond the canvas cap: tile over k-strips (plus
    m-strips and n-strips when the C canvas itself is too big), keeping
    every live canvas under `_DENSE_MAX_CANVAS` elements while the
    product stays on the dense MXU route (the reference's dense mode
    has no size cap, `dbcsr_mm.F:593-617`; this is its big-matrix
    realization)."""
    t_start = time.perf_counter()
    bm = int(c.row_blk_sizes[0])
    bn = int(c.col_blk_sizes[0])
    bk = int(a.col_blk_sizes[0])
    nbr, nbc, nbk = a.nblkrows, c.nblkcols, a.nblkcols
    chunking = _dense_chunking(nbr, nbc, nbk, bm, bn, bk)
    if chunking is None:
        # reached via the forced/occupancy gates (which skip the
        # feasibility check): no strip shape fits the cap, so keep the
        # pre-chunking single-canvas behavior rather than crash
        return _dense_multiply_general(a, b, c, alpha, beta)
    mrb, kcb, ncb = chunking
    nms = -(-nbr // mrb)
    nks = -(-nbk // kcb)
    nns = -(-nbc // ncb)

    ar, ac = a.entry_coords()
    br_, bc_ = b.entry_coords()
    a_data = (a.bins[0].data[: a.nblks] if a.nblks
              else jnp.zeros((0, bm, bk), c.dtype))
    b_data = (b.bins[0].data[: b.nblks] if b.nblks
              else jnp.zeros((0, bk, bn), c.dtype))
    c_data = (c.bins[0].data[: c.nblks] if c.nblks
              else jnp.zeros((0, bm, bn), c.dtype))
    c_rows = (c.keys // nbc).astype(np.int64)
    c_cols = (c.keys % nbc).astype(np.int64)
    # dropped by mode="drop" scatters.  MUST stay out of bounds after
    # jax's int32 scatter-index narrowing (1<<40 would truncate to 0 and
    # land IN bounds); 2^30 is far beyond any canvas dim (cap 2e8) and
    # int32-safe even after + block offsets
    oor = np.int64(1) << 30

    def strip_off(coords, lo, hi, blk):
        off = (coords - lo) * blk
        return np.where((coords >= lo) & (coords < hi), off, oor)

    dt_name = str(np.dtype(c.dtype))
    alpha_dev = _dense_const(("scalar", complex(alpha), dt_name),
                             lambda: jnp.asarray(alpha, dtype=c.dtype))
    beta_dev = _dense_const(("scalar", complex(beta), dt_name),
                            lambda: jnp.asarray(beta, dtype=c.dtype))
    acc = np.dtype(c.dtype)
    # per-k-strip / per-n-strip offsets depend only on their own strip
    # index: compute/upload once, not once per (ms, ks, ns) tile (an
    # out-of-strip offset on EITHER axis drops the whole block write)
    a_ko_ks = []
    b_ro_ks = []
    for ks in range(nks):
        k0, k1 = ks * kcb, min(nbk, (ks + 1) * kcb)
        a_ko_ks.append(jnp.asarray(strip_off(ac, k0, k1, bk)))
        b_ro_ks.append(jnp.asarray(strip_off(br_, k0, k1, bk)))
    b_co_ns = []
    for ns in range(nns):
        c0, c1 = ns * ncb, min(nbc, (ns + 1) * ncb)
        b_co_ns.append(jnp.asarray(strip_off(bc_, c0, c1, bn)))
    parts = []
    for ms in range(nms):
        r0, r1 = ms * mrb, min(nbr, (ms + 1) * mrb)
        a_ro_ms = jnp.asarray(strip_off(ar, r0, r1, bm))
        tiles = []
        for ns in range(nns):
            c0, c1 = ns * ncb, min(nbc, (ns + 1) * ncb)
            cd = jnp.zeros((mrb * bm, ncb * bn), acc)
            for ks in range(nks):
                cd = _dense_strip_matmul(
                    cd, a_data, a_ro_ms, a_ko_ks[ks],
                    b_data, b_ro_ks[ks], b_co_ns[ns],
                    m_el=mrb * bm, k_el=kcb * bk, n_el=ncb * bn,
                    bm=bm, bn=bn, bk=bk,
                )
            tile_pos = np.where(
                (c_rows >= r0) & (c_rows < r1)
                & (c_cols >= c0) & (c_cols < c1),
                (c_rows - r0) * ncb + (c_cols - c0), oor,
            )
            out = _dense_strip_to_blocks(
                cd, c_data, jnp.asarray(tile_pos), alpha_dev, beta_dev,
                nbc=ncb, bm=bm, bn=bn, rows=mrb, carve=_carve_choice(),
            )
            # (padded-rows x padded-cols) tile pattern -> live blocks
            tiles.append(out.reshape(mrb, ncb, bm, bn)
                         [: r1 - r0, : c1 - c0])
        strip = (jnp.concatenate(tiles, axis=1)
                 if len(tiles) > 1 else tiles[0])
        parts.append(strip.reshape((r1 - r0) * nbc, bm, bn))
    out = _dense_guard(
        jnp.concatenate(parts) if len(parts) > 1 else parts[0])
    new_keys = np.arange(nbr * nbc, dtype=np.int64)
    cap = bucket_size(len(new_keys))
    if cap > len(new_keys):
        out = jnp.concatenate(
            [out, jnp.zeros((cap - len(new_keys), bm, bn), out.dtype)]
        )
    c.set_structure_from_device(new_keys, [_Bin((bm, bn), out, len(new_keys))])
    # strip traffic model: every A strip is re-scattered per n-strip,
    # every B strip per m-strip, C is written once
    itemsize = np.dtype(c.dtype).itemsize
    strip_bytes = itemsize * (
        nns * nbr * bm * nbk * bk + nms * nbk * bk * nbc * bn
        + 2 * nbr * bm * nbc * bn
    )
    stats.record_stack(
        bm, bn, bk, nbr * nbc * nbk, driver="dense",
        seconds=time.perf_counter() - t_start, nbytes=strip_bytes,
        dtype=str(np.dtype(c.dtype)),
    )
    stats.record_multiply(2 * nbr * bm * nbc * bn * nbk * bk)
    return _true_product_flops(a, b)


# ------------------------------------------------- composite format

class _PanelPack:
    """Host-side plan for the composite format: a greedy contiguous
    partition of A's block-rows into ``G`` row-panels, each padded to
    ``mp`` block-rows and carrying its own COMPACTED k-support of at
    most ``kp`` block-cols — so one batched panel GEMM multiplies all
    panels at once against per-panel-duplicated B row-strips.  This is
    the serve coalescer's batching trick applied inside one product:
    banded/block-diagonal patterns that would pad a whole-matrix dense
    canvas mostly with zeros keep near-dense MXU shapes per panel."""

    __slots__ = ("G", "mp", "kp", "row_panel", "row_local", "kmap")

    def __init__(self, G, mp, kp, row_panel, row_local, kmap):
        self.G = int(G)     # panel count (batch dim)
        self.mp = int(mp)   # block-rows per panel (padded)
        self.kp = int(kp)   # k-support block-cols per panel (padded)
        self.row_panel = row_panel  # (nbr,) block-row -> panel id
        self.row_local = row_local  # (nbr,) block-row -> row in panel
        self.kmap = kmap    # (G, nbk) global k -> panel-local k or -1


_panel_cache = None  # created lazily; pattern+limits-keyed LRU


def composite_panels(a, b, c):
    """The composite-format plan for this product, or None when the
    pattern offers no compression over whole-panel dense (then dense or
    stack win anyway).  Memoized by pattern fingerprints + packing
    limits: repeated same-pattern multiplies plan once."""
    import collections

    from dbcsr_tpu.core.config import get_config

    global _panel_cache
    cfg = get_config()
    if a.nblks == 0 or b.nblks == 0:
        return None
    for m in (a, b, c):
        if len(np.unique(m.row_blk_sizes)) > 1 \
                or len(np.unique(m.col_blk_sizes)) > 1:
            return None
    nbr, nbk = a.nblkrows, a.nblkcols
    if nbr < 2 or float(nbr) * nbk > 5e7:
        return None
    key = (a.pattern_fingerprint(), b.pattern_fingerprint(),
           int(cfg.composite_max_panels), float(cfg.composite_ksup))
    if _panel_cache is None:
        _panel_cache = collections.OrderedDict()
    if key in _panel_cache:
        _panel_cache.move_to_end(key)
        return _panel_cache[key]
    pack = _build_panels(a, b, c, cfg)
    _panel_cache[key] = pack
    while len(_panel_cache) > 64:
        _panel_cache.popitem(last=False)
    return pack


def _greedy_panel_partition(support, limit, max_panels):
    """One greedy pass: walk block-rows in order, closing a panel when
    its k-support union would exceed ``limit``; then merge the adjacent
    pair with the smallest combined support until at most
    ``max_panels`` remain.  Returns (bounds, sups)."""
    nbr = support.shape[0]
    bounds, sups = [], []
    cur, start = support[0].copy(), 0
    for r in range(1, nbr):
        new = cur | support[r]
        if int(new.sum()) > limit:
            bounds.append((start, r))
            sups.append(cur)
            start, cur = r, support[r].copy()
        else:
            cur = new
    bounds.append((start, nbr))
    sups.append(cur)
    while len(bounds) > max_panels:
        unions = [int((sups[i] | sups[i + 1]).sum())
                  for i in range(len(sups) - 1)]
        i = int(np.argmin(unions))
        bounds[i] = (bounds[i][0], bounds[i + 1][1])
        sups[i] = sups[i] | sups[i + 1]
        del bounds[i + 1], sups[i + 1]
    return bounds, sups


def _build_panels(a, b, c, cfg):
    """Greedy contiguous panelization (see `_PanelPack`): sweep a few
    candidate k-support close-limits under ``composite_ksup * nbk``
    (`_greedy_panel_partition` per limit) and keep the partition with
    the smallest padded volume.  Returns None when batching cannot
    beat a single canvas (no k compression, padding blowup, B
    duplication blowup, or a canvas over the cap)."""
    nbr, nbk, nbc = a.nblkrows, a.nblkcols, b.nblkcols
    bm = int(c.row_blk_sizes[0])
    bn = int(c.col_blk_sizes[0])
    bk = int(a.col_blk_sizes[0])
    ar, ac = a.entry_coords()
    support = np.zeros((nbr, nbk), bool)
    support[ar, ac] = True
    ksup_limit = max(1, int(cfg.composite_ksup * nbk))
    # padding is what kills compression (panels pad to the WIDEST
    # support), so sweep a few candidate close-limits under the knob's
    # ceiling and keep the partition with the smallest padded volume
    best = None
    cap = _DENSE_MAX_CANVAS
    n_el = nbc * bn
    for lim in sorted({ksup_limit, max(1, nbk // 2), max(1, nbk // 4),
                       max(1, nbk // 8)}):
        if lim > ksup_limit:
            continue
        bounds, sups = _greedy_panel_partition(
            support, lim, cfg.composite_max_panels)
        G = len(bounds)
        if G < 2:
            continue
        mp = max(r1 - r0 for r0, r1 in bounds)
        kp = max(int(s.sum()) for s in sups)
        # feasibility gates apply PER candidate partition: a tighter
        # close-limit can have the smallest padded volume yet blow the
        # B-duplication bound (many small panels re-scatter many
        # overlapping supports) while a coarser partition passes
        if kp >= nbk:
            continue  # no k compression: plain dense dominates
        # row padding + support padding must still shrink the A volume
        if float(G) * mp * kp >= 0.9 * float(nbr) * nbk:
            continue
        # every panel re-scatters its k-support rows of B: bound the
        # blowup (sum of panel unions = how many B block-rows upload)
        if sum(int(s.sum()) for s in sups) > 3 * nbk:
            continue
        if (G * mp * bm * kp * bk > cap or G * kp * bk * n_el > cap
                or G * mp * bm * n_el > cap):
            continue
        if best is None or G * mp * kp < best[0]:
            best = (G * mp * kp, bounds, sups, G, mp, kp)
    if best is None:
        return None
    _, bounds, sups, G, mp, kp = best
    row_panel = np.empty(nbr, np.int64)
    row_local = np.empty(nbr, np.int64)
    kmap = np.full((G, nbk), -1, np.int64)
    for g, (r0, r1) in enumerate(bounds):
        row_panel[r0:r1] = g
        row_local[r0:r1] = np.arange(r1 - r0)
        supp_idx = np.nonzero(sups[g])[0]
        kmap[g, supp_idx] = np.arange(len(supp_idx))
    return _PanelPack(G, mp, kp, row_panel, row_local, kmap)


@functools.partial(
    jax.jit,
    static_argnames=("G", "m_el", "k_el", "n_el", "bm", "bn", "bk"),
)
def _composite_dot(a_data, a_ro, a_co, b_data, dup_idx, b_ro, b_co,
                   *, G, m_el, k_el, n_el, bm, bn, bk):
    """Scatter the A panels and the per-panel-duplicated B row-strips
    onto flat canvases, then ONE batched panel GEMM over the G groups.
    Returns (ad, bd, pd) so the ABFT batched probe can verify the raw
    product against the very canvases that produced it."""
    ad = _scatter_bin_to_canvas(
        jnp.zeros((G * m_el, k_el), a_data.dtype), a_data, a_ro, a_co,
        bm=bm, bn=bk,
    ).reshape(G, m_el, k_el)
    bd = _scatter_bin_to_canvas(
        jnp.zeros((G * k_el, n_el), b_data.dtype), b_data[dup_idx],
        b_ro, b_co, bm=bk, bn=bn,
    ).reshape(G, k_el, n_el)
    pd = jax.lax.dot_general(
        ad, bd, (((2,), (1,)), ((0,), (0,))),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=a_data.dtype,
    )
    return ad, bd, pd


@functools.partial(
    jax.jit, static_argnames=("G", "mp", "nbc", "bm", "bn"),
)
def _composite_to_blocks(pd, map_idx, c_blocks, c_keys, alpha, beta,
                         *, G, mp, nbc, bm, bn):
    """Carve the batched product canvas into C's FULL row-major block
    pattern (panel-major layout carve, then a block-granular take back
    into row-major key order) and merge beta*old like the dense path."""
    carved = (pd.reshape(G, mp, bm, nbc, bn)
              .transpose(0, 1, 3, 2, 4)
              .reshape(G * mp * nbc, bm, bn))
    out = alpha * jnp.take(carved, map_idx, axis=0)
    return out.at[c_keys].add(beta * c_blocks.astype(out.dtype),
                              mode="drop")


def _composite_multiply(a, b, c, alpha, beta, pack: _PanelPack) -> int:
    """Composite-format execution: one batched panel GEMM over the
    `_PanelPack` partition, bitwise-identical per block to the dense
    canvas product (same HIGHEST-precision dot over the same operand
    values; the panels only remove all-zero padding).  Shares the
    ``dense`` fault/corruption site with the other canvas paths."""
    if _faults.active():
        _faults.maybe_inject("dense")
    t_start = time.perf_counter()
    bm = int(c.row_blk_sizes[0])
    bn = int(c.col_blk_sizes[0])
    bk = int(a.col_blk_sizes[0])
    nbr, nbc, nbk = a.nblkrows, c.nblkcols, a.nblkcols
    G, mp, kp = pack.G, pack.mp, pack.kp
    _metrics.record_jit(
        "mm.multiply._composite_dot",
        (G, mp, kp, nbc, bm, bn, bk, str(np.dtype(c.dtype))),
    )
    ar, ac = a.entry_coords()
    br_, bc_ = b.entry_coords()
    g_e = pack.row_panel[ar]
    a_ro = (g_e * mp + pack.row_local[ar]) * bm
    a_co = pack.kmap[g_e, ac] * bk  # always >= 0: support is the union
    # B duplication: panel g re-scatters the B rows in its k-support at
    # panel-local row offsets (the only data the composite format pays
    # twice; `_build_panels` bounds the blowup)
    dup_sel, b_ro, b_co = [], [], []
    for g in range(G):
        kl = pack.kmap[g, br_]
        sel = np.nonzero(kl >= 0)[0]
        dup_sel.append(sel)
        b_ro.append((g * kp + kl[sel]) * bk)
        b_co.append(bc_[sel] * bn)
    dup_sel = np.concatenate(dup_sel)
    b_ro = np.concatenate(b_ro)
    b_co = np.concatenate(b_co)
    a_data = (a.bins[0].data[: a.nblks] if a.nblks
              else jnp.zeros((0, bm, bk), c.dtype))
    b_data = (b.bins[0].data[: b.nblks] if b.nblks
              else jnp.zeros((0, bk, bn), c.dtype))
    c_blocks = (c.bins[0].data[: c.nblks] if c.nblks
                else jnp.zeros((0, bm, bn), c.dtype))
    up = mempool.upload_index
    ad, bd, pd = _composite_dot(
        a_data, up("composite_aro", a_ro), up("composite_aco", a_co),
        b_data, up("composite_dup", dup_sel.astype(np.int64)),
        up("composite_bro", b_ro), up("composite_bco", b_co),
        G=G, m_el=mp * bm, k_el=kp * bk, n_el=nbc * bn,
        bm=bm, bn=bn, bk=bk,
    )
    pd = _dense_guard(pd)
    if _abft.enabled():
        _abft.check_dense_canvas_batched(pd, ad, bd, dtype=c.dtype)
    del ad, bd
    # full-pattern key -> panel-major carved row (every block-row lives
    # in exactly one panel, so the map is total)
    keys_full = np.arange(nbr * nbc, dtype=np.int64)
    rows_full = keys_full // nbc
    map_idx = ((pack.row_panel[rows_full] * mp
                + pack.row_local[rows_full]) * nbc + keys_full % nbc)
    dt_name = str(np.dtype(c.dtype))
    alpha_dev = _dense_const(("scalar", complex(alpha), dt_name),
                             lambda: jnp.asarray(alpha, dtype=c.dtype))
    beta_dev = _dense_const(("scalar", complex(beta), dt_name),
                            lambda: jnp.asarray(beta, dtype=c.dtype))
    keys32 = c.keys.astype(np.int32)
    c_keys_dev = _dense_const(("ckeys", nbr, nbc, keys32.tobytes()),
                              lambda: jnp.asarray(keys32))
    out = _composite_to_blocks(
        pd, up("composite_map", map_idx), c_blocks, c_keys_dev,
        alpha_dev, beta_dev, G=G, mp=mp, nbc=nbc, bm=bm, bn=bn,
    )
    cap = bucket_size(len(keys_full))
    if cap > len(keys_full):
        out = jnp.concatenate(
            [out, jnp.zeros((cap - len(keys_full), bm, bn), out.dtype)])
    c.set_structure_from_device(
        keys_full, [_Bin((bm, bn), out, len(keys_full))])
    itemsize = np.dtype(c.dtype).itemsize
    nbytes = itemsize * G * (mp * bm * kp * bk + kp * bk * nbc * bn
                             + 2 * mp * bm * nbc * bn)
    stats.record_stack(
        bm, bn, bk, G * mp * nbc * kp, driver="composite",
        seconds=time.perf_counter() - t_start, nbytes=nbytes,
        dtype=dt_name,
    )
    stats.record_multiply(2 * G * (mp * bm) * (nbc * bn) * (kp * bk))
    return _true_product_flops(a, b)


def _apply_element_limits(a, b, c, element_limits):
    """Resolve element-granular limits (ref `dbcsr_multiply`'s full-
    index limit args).  Block-aligned limits reduce to block-index
    limits; unaligned ones additionally crop op(A)/op(B) at element
    level (ref `dbcsr_crop_matrix` in `make_m2s`,
    `dbcsr_mm_cannon.F:194-220`) so partial boundary blocks contribute
    only their in-window elements.

    Returns (a, b, block_limits, beta_window)."""
    if len(element_limits) != 6:
        raise ValueError("element_limits must be a 6-tuple")
    fr, lr, fc, lc, fk, lk = element_limits
    fr = 0 if fr is None else int(fr)
    lr = c.nfullrows - 1 if lr is None else int(lr)
    fc = 0 if fc is None else int(fc)
    lc = c.nfullcols - 1 if lc is None else int(lc)
    fk = 0 if fk is None else int(fk)
    lk = a.nfullcols - 1 if lk is None else int(lk)
    if not (0 <= fr <= lr < c.nfullrows and 0 <= fc <= lc < c.nfullcols
            and 0 <= fk <= lk < a.nfullcols):
        raise ValueError(f"element limits out of range: {element_limits}")

    def axis(lo, hi, off, n_el):
        b0 = int(np.searchsorted(off, lo, side="right") - 1)
        b1 = int(np.searchsorted(off, hi, side="right") - 1)
        aligned = off[b0] == lo and off[b1 + 1] - 1 == hi
        full = lo == 0 and hi == n_el - 1
        return b0, b1, aligned, full

    rb0, rb1, r_al, r_full = axis(fr, lr, c.row_blk_offsets, c.nfullrows)
    cb0, cb1, c_al, c_full = axis(fc, lc, c.col_blk_offsets, c.nfullcols)
    kb0, kb1, k_al, k_full = axis(fk, lk, a.col_blk_offsets, a.nfullcols)

    if not (r_al and c_al and k_al):
        from dbcsr_tpu.ops.operations import crop_matrix

        a = crop_matrix(a, row_bounds=(fr, lr), col_bounds=(fk, lk))
        b = crop_matrix(b, row_bounds=(fk, lk), col_bounds=(fc, lc))
    block_limits = (
        None if r_full else rb0, None if r_full else rb1,
        None if c_full else cb0, None if c_full else cb1,
        None if k_full else kb0, None if k_full else kb1,
    )
    beta_window = None if (r_full and c_full) else (fr, lr, fc, lc)
    return a, b, block_limits, beta_window


def _candidates(a, b, c, filter_eps, fr, lr, fc, lc, fk, lk):
    """Symbolic product: all (i, k, j) triples as parallel arrays
    (a_ent indexes op(A) entries, b_ent op(B) entries).  Uses the native
    C++ engine when available; the NumPy path below is the fallback and
    the reference implementation for tests."""
    na2 = nb2 = row_eps = None
    if filter_eps is not None:
        # squared f32 norms, per-A-row eps (ref dbcsr_mm_cannon.F:1098-1105)
        na2 = a.block_norms().astype(np.float32) ** 2
        nb2 = b.block_norms().astype(np.float32) ** 2
        row_counts = np.diff(a.row_ptr)
        with np.errstate(over="ignore"):  # huge eps -> inf is a valid threshold
            row_eps = (
                np.float32(filter_eps) / np.maximum(1, row_counts).astype(np.float32)
            ) ** 2

    from dbcsr_tpu import native

    res = native.symbolic_product(
        a.row_ptr, (a.keys % a.nblkcols).astype(np.int32),
        b.row_ptr, (b.keys % b.nblkcols).astype(np.int32),
        na2, nb2, row_eps,
        sym_c=c.matrix_type != NO_SYMMETRY,
        fr=fr, lr=lr, fc=fc, lc=lc, fk=fk, lk=lk,
    )
    if res is not None:
        return res
    return _candidates_numpy(a, b, c, na2, nb2, row_eps, fr, lr, fc, lc, fk, lk)


def _candidates_numpy(a, b, c, na2, nb2, row_eps, fr, lr, fc, lc, fk, lk):
    rows_a = np.repeat(
        np.arange(a.nblkrows, dtype=np.int64), np.diff(a.row_ptr)
    )
    cols_a = (a.keys % a.nblkcols).astype(np.int64)  # k per A entry
    cols_b = (b.keys % b.nblkcols).astype(np.int64)  # j per B entry

    a_sel = np.ones(len(a.keys), bool)
    if fr is not None:
        a_sel &= rows_a >= fr
    if lr is not None:
        a_sel &= rows_a <= lr
    if fk is not None:
        a_sel &= cols_a >= fk
    if lk is not None:
        a_sel &= cols_a <= lk
    a_entries = np.nonzero(a_sel)[0]

    counts = (b.row_ptr[cols_a[a_entries] + 1] - b.row_ptr[cols_a[a_entries]]).astype(
        np.int64
    )
    tot = int(counts.sum())
    a_ent = np.repeat(a_entries, counts)
    if tot == 0:
        z = np.empty(0, np.int64)
        return z, z, z, z
    ends = np.cumsum(counts)
    starts = ends - counts
    b_ent = (
        np.arange(tot, dtype=np.int64)
        - np.repeat(starts, counts)
        + np.repeat(b.row_ptr[cols_a[a_entries]], counts)
    )
    i = rows_a[a_ent]
    j = cols_b[b_ent]

    keep = np.ones(tot, bool)
    if fc is not None:
        keep &= j >= fc
    if lc is not None:
        keep &= j <= lc
    if c.matrix_type != NO_SYMMETRY:
        # don't compute the redundant triangle (ref symmetric skip,
        # dbcsr_mm_csr.F:281)
        keep &= i <= j
    if na2 is not None:
        keep &= na2[a_ent] * nb2[b_ent] >= row_eps[i]
    if not keep.all():
        i, j, a_ent, b_ent = i[keep], j[keep], a_ent[keep], b_ent[keep]
    return i, j, a_ent, b_ent


def _rebuild_c(c: BlockSparseMatrix, new_keys: np.ndarray, beta,
               beta_window=None) -> None:
    """Re-structure C on the (possibly grown) pattern with data
    beta-scaled.  With ``beta_window`` = (r0, r1, c0, c1) inclusive
    element bounds, beta applies only inside the window: old blocks
    fully outside are copied unscaled, straddling blocks get an
    element-masked scale (reference windowed-dgemm semantics)."""
    old_keys = c.keys
    old_bins = c.bins
    old_ent_bin = c.ent_bin
    old_ent_slot = c.ent_slot
    rows = (new_keys // c.nblkcols).astype(np.int64)
    cols = (new_keys % c.nblkcols).astype(np.int64)
    nb, nsl, shapes = _bin_entries(c.row_blk_sizes, c.col_blk_sizes, rows, cols)
    dt_name_rc = str(np.dtype(c.dtype))
    beta_dev = _dense_const(("scalar", complex(beta), dt_name_rc),
                            lambda: jnp.asarray(beta, dtype=c.dtype))
    one_dev = _dense_const(("scalar", complex(1.0), dt_name_rc),
                           lambda: jnp.asarray(1.0, dtype=c.dtype))
    pos_old = np.searchsorted(new_keys, old_keys)  # old keys ⊆ new keys

    n_old = len(old_keys)
    if beta_window is None or beta == 1 or n_old == 0:
        cls_inside = np.ones(n_old, bool)
        cls_strad = np.zeros(n_old, bool)
        blk_r0 = blk_c0 = None
    else:
        r0, r1, c0w, c1w = beta_window
        orows = (old_keys // c.nblkcols).astype(np.int64)
        ocols = (old_keys % c.nblkcols).astype(np.int64)
        roff, coff = c.row_blk_offsets, c.col_blk_offsets
        blk_r0, blk_r1 = roff[orows], roff[orows + 1] - 1
        blk_c0, blk_c1 = coff[ocols], coff[ocols + 1] - 1
        overlap = (blk_r1 >= r0) & (blk_r0 <= r1) & (blk_c1 >= c0w) & (blk_c0 <= c1w)
        cls_inside = (
            overlap & (blk_r0 >= r0) & (blk_r1 <= r1)
            & (blk_c0 >= c0w) & (blk_c1 <= c1w)
        )
        cls_strad = overlap & ~cls_inside

    bins = []
    for b_id, (bm, bn) in enumerate(shapes):
        count = int((nb == b_id).sum())
        cap = bucket_size(count)
        data = mempool.zeros((cap, bm, bn), c.dtype)
        in_bin = (nb[pos_old] == b_id) if n_old else np.zeros(0, bool)

        def scatter(sel_mask, factor):
            nonlocal data
            sel = np.nonzero(sel_mask)[0]
            if not len(sel):
                return
            src_bin = old_bins[old_ent_bin[sel[0]]]
            data = _scatter_scaled(
                data, src_bin.data,
                mempool.upload_index("rebuild_src", old_ent_slot[sel]),
                mempool.upload_index("rebuild_dst", nsl[pos_old[sel]]),
                factor,
            )

        if beta != 0:
            scatter(in_bin & cls_inside, beta_dev)
        if beta_window is not None and beta != 1:
            scatter(in_bin & ~cls_inside & ~cls_strad, one_dev)
            sel = np.nonzero(in_bin & cls_strad)[0]
            if len(sel):
                r0, r1, c0w, c1w = beta_window
                rl = np.maximum(r0 - blk_r0[sel], 0)
                rh = np.minimum(r1 - blk_r0[sel], bm - 1)
                cl = np.maximum(c0w - blk_c0[sel], 0)
                ch = np.minimum(c1w - blk_c0[sel], bn - 1)
                src_bin = old_bins[old_ent_bin[sel[0]]]
                data = _scatter_scaled_window(
                    data, src_bin.data,
                    mempool.upload_index("rebuild_src", old_ent_slot[sel]),
                    mempool.upload_index("rebuild_dst", nsl[pos_old[sel]]),
                    beta_dev,
                    jnp.asarray(rl), jnp.asarray(rh),
                    jnp.asarray(cl), jnp.asarray(ch),
                )
        bins.append(_Bin((bm, bn), data, count))
    c.set_structure_from_device(new_keys, bins, binning=(nb, nsl, shapes))


# prepared-plan cache for repeated same-pattern multiplies (SCF-style
# loops; the perf driver's nrep reps): skips the host group-sort and
# the stack index upload entirely.  Keyed by pattern fingerprints +
# product options (see plan_key in multiply()); LRU-bounded by entry
# count AND by the device bytes the plans pin.
from collections import OrderedDict as _OrderedDict

_plan_cache: "_OrderedDict[tuple, _CachedSpans]" = _OrderedDict()
_plan_cache_bytes = 0  # running sum of the entries' at-insert nbytes
_PLAN_CACHE_MAX = 16
_PLAN_CACHE_MAX_BYTES = 256 * 1024 * 1024


class _CachedSpans:
    """One plan-cache entry: the per-span plan tuples plus the lazily
    built fused superstack plans per C bin (``None`` marks a bin whose
    spans cannot fuse) and the byte size snapshot the cache's running
    budget counter uses.  Plans mutate in place after insert (a
    crosspack demotion frees its payload; a failover heal can swap a
    cheap host plan for one pinning device index arrays), so every
    cache HIT refreshes the snapshot through `refresh_nbytes` — O(this
    entry's spans), vs the old global re-sum per insert."""

    __slots__ = ("spans", "super_plans", "nbytes")

    def __init__(self, spans):
        self.spans = spans
        self.super_plans: dict = {}
        self.nbytes = sum(p.nbytes() for (*_, p) in spans if p is not None)

    def refresh_nbytes(self) -> int:
        """Recompute the snapshot from the live plans; returns the
        delta for the cache's running byte counter."""
        new = sum(p.nbytes() for (*_, p) in self.spans if p is not None)
        delta = new - self.nbytes
        self.nbytes = new
        return delta

    def superstack_for(self, cbin, plans, prepare):
        """The bin's fused plan, (re)built whenever the spans' driver
        tuple changed since the cached decision — a failover/demotion
        heals plans IN PLACE, which can invalidate a built program OR
        make a previously unfusable (None) bin fusable."""
        drivers = tuple(p.driver for p in plans)
        hit = self.super_plans.get(cbin)
        if hit is not None and hit[0] == drivers:
            return hit[1]
        splan = prepare(plans)
        self.super_plans[cbin] = (drivers, splan)
        return splan


def _plan_cache_insert(key, entry: "_CachedSpans") -> None:
    """Insert + LRU/byte-budget eviction in O(evicted): the running
    byte counter replaces the old re-sum of every cached plan inside
    the eviction loop (O(cache·spans) per insert)."""
    global _plan_cache_bytes
    if not _plan_cache:
        _plan_cache_bytes = 0  # tests clear() the OrderedDict directly
    old = _plan_cache.pop(key, None)
    if old is not None:
        _plan_cache_bytes -= old.nbytes
    _plan_cache[key] = entry
    _plan_cache_bytes += entry.nbytes
    while len(_plan_cache) > _PLAN_CACHE_MAX or (
        len(_plan_cache) > 1 and _plan_cache_bytes > _PLAN_CACHE_MAX_BYTES
    ):
        _, evicted = _plan_cache.popitem(last=False)
        _plan_cache_bytes -= evicted.nbytes


def _superstack_mode() -> str:
    """The resolved stack execution mode: config.superstack with
    "auto" meaning fused (fuse whenever a bin's spans can; single-span
    bins and unfusable bins run per-span either way).  Values are
    validated at every entry point (`Config.validate` runs for env
    application and `set_config` alike), so a typo'd control run fails
    fast instead of silently executing fused."""
    from dbcsr_tpu.core.config import get_config

    mode = get_config().superstack
    return "fused" if mode == "auto" else mode


def _run_stacks(c, a, b, cand_keys, a_ent, b_ent, alpha, plan_key=None,  # lint: disable=mutation-epoch (the caller stamps `c._note_mutation(c.keys)` once after the run — per-launch bin swaps and ABFT rollbacks are interior states of one funnel)
                c_zero=False) -> int:
    """Group candidate triples by (m,n,k) shape-bin, sort by C block,
    and execute: spans sharing a destination C bin fuse into a single
    donated-buffer launch (`acc.smm.execute_superstack`) unless
    config.superstack forces the per-span path; returns true flops."""
    if len(cand_keys) == 0:
        return 0
    from dbcsr_tpu.acc.smm import (
        execute_stack,
        execute_superstack,
        plan_exec_dtype,
        prepare_stack,
        prepare_superstack,
    )

    global _plan_cache_bytes
    cached = None
    if plan_key is not None and plan_key in _plan_cache:
        _plan_cache.move_to_end(plan_key)
        cached = _plan_cache[plan_key]
        # plans heal/demote in place: keep the byte budget honest
        _plan_cache_bytes += cached.refresh_nbytes()
    _metrics.counter(
        "dbcsr_tpu_plan_cache_total",
        "stack-plan cache outcomes per multiply (uncacheable = "
        "value-dependent filtered products)",
    ).inc(result=("hit" if cached is not None
                  else "miss" if plan_key is not None else "uncacheable"))
    if cached is not None:
        _flight.note("plan_cache", "hit")
        # a cache hit skips prepare_stack (where decisions are noted);
        # the flight record still names the drivers actually launched
        for _cb, _ab, _bb, m, n, k, cnt, plan in cached.spans:
            if plan is not None:
                _flight.note_driver(plan.driver, "plan-cache-hit",
                                    mnk=(m, n, k), entries=cnt)
    if cached is None:
        c_ent = np.searchsorted(c.keys, cand_keys)
        cb = c.ent_bin[c_ent]
        ab = a.ent_bin[a_ent]
        bb = b.ent_bin[b_ent]
        c_slot = c.ent_slot[c_ent]
        a_slot = a.ent_slot[a_ent]
        b_slot = b.ent_slot[b_ent]
        g = (cb.astype(np.int64) * len(a.bins) + ab) * len(b.bins) + bb
        ngroups = len(c.bins) * len(a.bins) * len(b.bins)
        from dbcsr_tpu import native

        order, gbounds = native.sort_order(g, ngroups, c_slot, a_ent,
                                           return_bounds=True)
        nonempty = np.nonzero(np.diff(gbounds))[0]
        spans = [(int(gbounds[gi]), int(gbounds[gi + 1])) for gi in nonempty]
        c_slot = c_slot[order]
        a_slot = a_slot[order]
        b_slot = b_slot[order]
        cb = cb[order]
        ab = ab[order]
        bb = bb[order]
        spans_meta = []
        for s0, s1 in spans:
            cbin, abin, bbin = int(cb[s0]), int(ab[s0]), int(bb[s0])
            m, k = a.bins[abin].shape
            _, n = b.bins[bbin].shape
            a_bin = a.bins[abin]
            b_bin = b.bins[bbin]
            plan = prepare_stack(
                c.bins[cbin].data, a_bin.data, b_bin.data,
                a_slot[s0:s1], b_slot[s0:s1], c_slot[s0:s1],
                # bucket-padded rows beyond count are zeros — the Pallas
                # path masks short groups with them
                a_pad_row=a_bin.count if a_bin.count < a_bin.data.shape[0] else None,
                b_pad_row=b_bin.count if b_bin.count < b_bin.data.shape[0] else None,
            )
            spans_meta.append((cbin, abin, bbin, m, n, k, s1 - s0, plan))
        cached = _CachedSpans(spans_meta)
        if plan_key is not None:
            _plan_cache_insert(plan_key, cached)
    spans_meta = cached.spans
    mode = _superstack_mode()
    # opt-in synchronized timing: block on each launch before reading
    # the clock so the recorded seconds are device-completion time
    # (the default records dispatch-side seconds — the device may still
    # be draining; stats.record_driver documents the contract)
    sync = stats.sync_timing_enabled()
    itemsize = np.dtype(c.dtype).itemsize
    dt_name = str(np.dtype(c.dtype))
    # drivers that do not donate C (host family) leave the replaced
    # buffer alive: pool-owned Cs hand it back for the next checkout
    c_releasable = c._donatable
    # Deferred ABFT: a beta==0 product's pristine C is all zeros, so
    # the whole product is re-executable from metadata alone.  The
    # per-launch probes then queue their device-side scalars WITHOUT a
    # host sync (preserving host/device pipelining) and one flush at
    # the end of the product drains them; a flush-detected mismatch
    # rolls every bin back to zeros and re-executes with immediate
    # per-launch verification (where the smm failover chain localizes
    # and recovers).  beta != 0 launches keep immediate checks — their
    # pristine C exists only as the per-launch copy.
    abft_defer = bool(c_zero) and _abft.enabled()

    def _swap_cbin(cbin, out):
        old = c.bins[cbin].data
        c.bins[cbin].data = out
        if c_releasable and out is not old:
            mempool.release(old)  # no-op for donated (deleted) buffers

    def _exec_spans(defer):
        # beta == 0 (no window): _rebuild_c left every bin as untouched
        # jnp.zeros — the host driver can then synthesize its writable
        # host buffer as np.zeros instead of fetching ~hundreds of MB
        # of zeros off the device (first touch per bin only: later
        # spans accumulate onto real contributions; a fused launch
        # counts as the whole bin's first touch)
        zero_bins = set(range(len(c.bins))) if c_zero else set()
        flops = 0
        fused_bins = 0
        i = 0
        n_spans = len(spans_meta)
        while i < n_spans:
            # spans sharing a C bin are adjacent (the group key sorts
            # by (cbin, abin, bbin)) — one slice per destination bin
            j = i
            cbin = spans_meta[i][0]
            while j < n_spans and spans_meta[j][0] == cbin:
                j += 1
            group = spans_meta[i:j]
            splan = None
            if mode != "per_span" and j - i > 1:
                splan = cached.superstack_for(
                    cbin, [sm[7] for sm in group], prepare_superstack)
            if splan is not None:
                a_datas = [a.bins[sm[1]].data for sm in group]
                b_datas = [b.bins[sm[2]].data for sm in group]
                t0 = time.perf_counter()
                out, was_fused = execute_superstack(
                    c.bins[cbin].data, a_datas, b_datas, splan, alpha,
                    c_zero=cbin in zero_bins, abft_defer=defer,
                )
                if sync:
                    jax.block_until_ready(out)
                dt_s = time.perf_counter() - t0
                _swap_cbin(cbin, out)
                zero_bins.discard(cbin)
                fused_bins += was_fused
                nseg = out.shape[0]
                span_flops = [2 * m * n * k * cnt
                              for (_, _, _, m, n, k, cnt, _) in group]
                tot_flops = float(sum(span_flops)) or 1.0
                for gi, (_cb, _ab, _bb, m, n, k, cnt, plan) \
                        in enumerate(group):
                    # the launch's seconds split across its spans by
                    # flop share; a FUSED launch reads+writes the bin's
                    # C buffer ONCE, so only the first span is charged
                    # that round trip (costmodel.superstack_bytes
                    # convention) — but a bin the resilience layer
                    # decomposed really paid the per-span round-trips,
                    # and records them as such
                    stats.record_stack(
                        m, n, k, cnt, driver=plan.driver,
                        seconds=dt_s * (span_flops[gi] / tot_flops),
                        nbytes=_costmodel.stack_bytes(
                            m, n, k, cnt,
                            nseg=(nseg if (gi == 0 or not was_fused)
                                  else 0),
                            itemsize=itemsize),
                        # EXECUTED compute dtype (demoted launches must
                        # not roofline against the request dtype's peak)
                        dtype=plan_exec_dtype(plan, dt_name), sync=sync,
                    )
                    flops += span_flops[gi]
                i = j
                continue
            for _cb, abin, bbin, m, n, k, cnt, plan in group:
                t0 = time.perf_counter()
                out = execute_stack(
                    c.bins[cbin].data, a.bins[abin].data,
                    b.bins[bbin].data, plan, alpha,
                    c_zero=cbin in zero_bins, abft_defer=defer,
                )
                if sync:
                    jax.block_until_ready(out)
                dt_s = time.perf_counter() - t0
                _swap_cbin(cbin, out)
                zero_bins.discard(cbin)
                stats.record_stack(
                    m, n, k, cnt, driver=plan.driver, seconds=dt_s,
                    nbytes=_costmodel.stack_bytes(
                        m, n, k, cnt, nseg=out.shape[0],
                        itemsize=itemsize),
                    dtype=plan_exec_dtype(plan, dt_name), sync=sync,
                )
                flops += 2 * m * n * k * cnt
            i = j
        return flops, fused_bins

    recovered_from = None
    for attempt in (0, 1):
        defer = abft_defer and attempt == 0
        if defer:
            _abft.discard_pending()
        try:
            flops, fused_bins = _exec_spans(defer)
        except BaseException:
            if defer:
                # an unrelated failure aborted the product: its queued
                # probes must never be attributed to a later one
                _abft.discard_pending()
            raise
        if not defer:
            break
        try:
            _abft.flush()
            break
        except _abft.AbftMismatchError as exc:
            from dbcsr_tpu.acc import smm as _smm

            if isinstance(exc, _abft.PrecisionExceededError):
                # adaptive-precision promote, not SDC: the cells were
                # promoted when the flush evaluated the probe; the redo
                # below re-executes with immediate verification, where
                # each still-demoted plan heals itself to native — no
                # breaker feed, no recovery attribution
                recovered_from = None
            else:
                _smm.note_deferred_sdc(exc)
                recovered_from = getattr(exc, "mismatch_drivers", None) \
                    or [getattr(exc, "driver", "?")]
            # roll every bin back to its pristine (all-zero) pre-run
            # state and redo the product with immediate verification
            for bin_ in c.bins:
                old = bin_.data
                bin_.data = mempool.zeros(old.shape, c.dtype)
                if c_releasable:
                    mempool.release(old)
    if recovered_from is not None:
        for drv in recovered_from:
            _abft.record_recovery(drv)
    if fused_bins:
        _flight.note("fused_bins", fused_bins)
    if plan_key is not None and plan_key in _plan_cache:
        # execution can heal plans in place (failover/demotion) — keep
        # the byte budget honest even for an entry never hit again
        _plan_cache_bytes += cached.refresh_nbytes()
    return flops
