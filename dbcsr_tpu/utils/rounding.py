"""Size bucketing.

TPU-native replacement for the reference mempool + data-area resize
machinery (`src/data/dbcsr_data_types.F:62-81`, resize factor 1.2):
device array extents are rounded up to a coarse bucket so repeated
multiplies with slightly different sparsity hit the XLA jit cache
instead of recompiling.
"""

from __future__ import annotations


def bucket_size(n: int, minimum: int = 16) -> int:
    """Round ``n`` up to {1,2,4,...}×2^k with ~25% max slack."""
    if n <= 0:
        return 0
    if n <= minimum:
        return minimum
    # next value of form {4,5,6,7} * 2^k  (<=25% over-allocation)
    k = max((n - 1).bit_length() - 3, 0)
    step = 1 << k
    return ((n + step - 1) // step) * step


def ceil_div(a: int, b: int) -> int:
    return -(-a // b)
