"""Compatibility shims for the moving jax API surface.

``jax.enable_x64`` (the context manager) was deprecated and then
removed from the top-level namespace (AttributeError on jax 0.4.37,
the pinned version) — its home is ``jax.experimental.enable_x64``.
Every pallas/tuner call site that scopes x64 off for a kernel launch
goes through this shim, so an API move is one edit here instead of a
silent engine-wide driver outage (the pre-seed state: every pallas
launch died with AttributeError before reaching the kernel).
"""

from __future__ import annotations

try:
    from jax.experimental import enable_x64  # noqa: F401
except ImportError:  # pragma: no cover — older jax kept it top-level
    import jax

    enable_x64 = jax.enable_x64  # type: ignore[attr-defined]

# ``jax.shard_map`` is the promoted (jax >= 0.6) name of
# ``jax.experimental.shard_map.shard_map``; the pinned 0.4.37 only has
# the experimental home (top-level access raises the deprecation
# AttributeError).  Same deal: one shim, every mesh engine call site.
import jax as _jax

try:
    shard_map = _jax.shard_map  # the promoted top-level name
except AttributeError:
    from jax.experimental.shard_map import shard_map  # noqa: F401
