"""Dynamic lock-order assertion — the runtime complement of the
static ``lock-mixed-write``/``lock-callback`` rules (tools/lint).

Debug-gated by ``DBCSR_TPU_LOCKCHECK=1``: the instrumented locks
(mempool, serve queue/engine, product cache, telemetry store) record
each thread's acquisition ORDER into a global edge set; acquiring B
while holding A after some thread ever acquired A while holding B is
a deadlock waiting for the right interleaving — `LockOrderError`
raises immediately, with both witness chains, instead of the test
suite wedging once a year.

Disabled (the default) the wrappers never exist: `wrap` hands back
the raw lock, so production pays zero overhead and zero indirection.

Enabled in `tools/chaos_suite.py` and the 2-process world tests;
enable ad hoc with the env knob (see docs/static_analysis.md).
"""

from __future__ import annotations

import os
import threading


class LockOrderError(RuntimeError):
    """Two locks were taken in both orders (see message witnesses)."""


_edges: dict = {}        # (first, second) -> witness string
_edges_lock = threading.Lock()
_held = threading.local()  # .stack: per-thread list of held names


def enabled() -> bool:
    return os.environ.get("DBCSR_TPU_LOCKCHECK") == "1"


def wrap(name: str, lock):
    """Instrument ``lock`` under ``name`` when the checker is on;
    hand the raw lock back untouched otherwise."""
    return TrackedLock(name, lock) if enabled() else lock


def reset() -> None:
    """Forget every recorded ordering (tests)."""
    with _edges_lock:
        _edges.clear()


def held_names() -> tuple:
    """This thread's current lock chain, outermost first (tests)."""
    return tuple(getattr(_held, "stack", ()))


def _stack() -> list:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _note_acquired(name: str) -> None:
    st = _stack()
    me = threading.current_thread().name
    witness = f"{me}: {' -> '.join(st + [name])}"
    with _edges_lock:
        for h in st:
            if h == name:
                continue  # re-entrant RLock acquire
            inverse = _edges.get((name, h))
            if inverse is not None:
                raise LockOrderError(
                    f"lock order inversion: `{h}` -> `{name}` here "
                    f"({witness}) but `{name}` -> `{h}` was recorded "
                    f"({inverse}) — a deadlock under the right "
                    "interleaving")
            _edges.setdefault((h, name), witness)
    st.append(name)


def _note_released(name: str) -> None:
    st = _stack()
    # release may be out of LIFO order (rare but legal): drop the
    # newest matching hold
    for i in range(len(st) - 1, -1, -1):
        if st[i] == name:
            del st[i]
            return


class TrackedLock:
    """Lock proxy recording acquisition order.  Works as a Condition
    base too: `threading.Condition` only needs acquire/release and
    context-manager protocol, and its ``wait`` releases through them,
    keeping the per-thread chain truthful across waits."""

    def __init__(self, name: str, lock):
        self.name = name
        self._lock = lock

    def acquire(self, *args, **kwargs) -> bool:
        ok = self._lock.acquire(*args, **kwargs)
        if ok:
            try:
                _note_acquired(self.name)
            except LockOrderError:
                self._lock.release()
                raise
        return ok

    def release(self) -> None:
        self._lock.release()
        _note_released(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()
