"""Forced-completion fencing for honest timing.

`jax.block_until_ready` can return before the device work actually ran
on remote-tunnel backends (the axon pathology, PERF_NOTES.md): timing
fenced that way reports dispatch, not execution.  A data-dependent
fetch of one element cannot be served before the producing program
finished — the moral equivalent of the reference's `mp_sync` timing
fence (`dbcsr_performance_multiply.F:597`).  Every timed path (perf
driver, autotuner, acc micro-benchmarks) fences through this helper so
the contract lives in one place.
"""

from __future__ import annotations

import numpy as np


def fetch_fence(arr) -> float:
    """Force REAL completion of the program producing ``arr`` by
    fetching its first element (8-byte d2h); returns it as float."""
    return float(np.asarray(arr.ravel()[0]).real)
