"""ABFT probe checksums: catching wrong-but-finite answers.

The breaker plane (PR 3) catches crashes and NaNs; the end-of-run
checksum gate catches corruption after the fact.  What neither catches
is the dominant accelerator-fleet failure mode per the SDC literature:
a *finite* silently-corrupted product that sails through every
finite-output check, poisons an iterative chain into confident
convergence on garbage, and gets served to a tenant.  This module is
the runtime detector — the TPU-side analog of DBCSR's own checksum
utilities (``dbcsr_test_methods``'s ``dbcsr_checksum``), moved from
test-time to launch-time via algorithm-based fault tolerance.

**The probe.**  For one parameter stack ``C[ci] += alpha*A[ai]@B[bi]``
and fixed Rademacher vectors ``u`` (rows) and ``v`` (columns), the
double-sided rank-1 identity

    u · (C_new - C_old) · v  ==  alpha * Σ_s (uᵀA)[ai_s] · (B v)[bi_s]

holds exactly in real arithmetic; in floating point the two sides
disagree only by rounding, bounded by `obs.costmodel.abft_tolerance`
(accumulation-dtype epsilon × reduction depths).  The double-sided
form is what makes the probe affordable: ``uᵀA`` and ``B·v`` contract
once per *unique block* (the bucketed ``a_data``/``b_data`` panels,
read once each), and each span then costs a single k-length dot — so
the whole check is O(|A| + |B| + 2|C| + s·k) memory traffic against
the kernel's O(s·m·n·k) flops, evaluated as ONE fused dispatch and one
host sync per guarded launch.  A corrupted C element at (i, j) enters
the left side with weight ``u_i·v_j = ±1``, so single-element SDC is
never masked.

**The knob** (``DBCSR_TPU_ABFT``, `core.config.abft`):

* ``off`` — no checks (production default; zero overhead).
* ``verify`` — probe every stack/superstack launch; a mismatch raises
  `AbftMismatchError`, classified ``sdc`` by `acc.smm`, recorded
  against the per-(driver, shape) breaker, and the stack re-executes
  down the PR 3 failover chain (same-driver pristine retry first —
  SDC is transient corruption, and the retry is bitwise-faithful).
* ``recover`` — ``verify``, plus every recovery re-execution is itself
  probe-checked before its result is accepted.

Layer coverage beyond the stack boundary:

* `check_superstack` — one probe over a fused C-bin launch (the right
  side sums over the bin's spans);
* `tree_probe`/`shift_conserved` — the distributed tick pipelines'
  conservation check: a ring shift is a data permutation, so the
  global probe of the operand panels is invariant across it
  (`parallel/overlap.py`);
* `matrix_probe`/`verify_product` — whole-matrix probes for the
  serving plane's per-request verification (`serve/engine.py`).

Every check/mismatch/recovery is observable:
``dbcsr_tpu_abft_{checks,mismatches,recoveries}_total{driver}`` plus an
``abft_mismatch`` bus event correlated by product/request id.
"""

from __future__ import annotations

import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dbcsr_tpu.core import mempool as _mempool
from dbcsr_tpu.core.config import get_config
from dbcsr_tpu.obs import costmodel as _costmodel
from dbcsr_tpu.obs import events as _events
from dbcsr_tpu.obs import metrics as _metrics


class AbftMismatchError(RuntimeError):
    """A probe checksum disagreed beyond tolerance: the launch produced
    a wrong (possibly perfectly finite) answer.  Classified ``sdc`` by
    `acc.smm._classify_failure`."""


class PrecisionExceededError(AbftMismatchError):
    """A DEMOTED launch's probe residual breached its demotion ceiling
    (`obs.costmodel.demoted_abft_tolerance`): not corruption but the
    adaptive-precision promote signal.  `acc.smm.execute_stack` answers
    it by rebuilding the plan at native precision (the involved cells
    were already promoted by `acc.precision.note_exceeded` when this
    raised) instead of walking the SDC failover chain.  Subclasses
    `AbftMismatchError` so any unaware layer still treats it as a
    condemned result rather than accepting it."""


def mode() -> str:
    return get_config().abft


def enabled() -> bool:
    """THE hot-path gate: one config-attribute read per launch."""
    return get_config().abft != "off"


def recover_enabled() -> bool:
    return get_config().abft == "recover"


# ------------------------------------------------------------- probes

def _acc_dtype(dtype):
    """Accumulation dtype of the probe math (mirrors smm._accum_dtype
    without importing smm — this module must stay import-cycle-free)."""
    d = jnp.dtype(dtype)
    if d == jnp.bfloat16 or d == jnp.float16:
        return jnp.dtype(jnp.float32)
    return d


_vec_cache: dict = {}


def probe_vector(n: int, dtype, salt: int = 0) -> object:
    """The fixed Rademacher (±1) probe vector for a given length —
    exactly representable in every dtype, deterministic per process
    lifetime (seeded), cached on device.  ``salt`` decorrelates the
    row probe ``u`` from the column probe ``v`` of a double-sided
    check."""
    acc = _acc_dtype(dtype)
    key = (int(n), str(acc), int(salt))
    hit = _vec_cache.get(key)
    if hit is not None and not hit.is_deleted():
        return hit
    rng = np.random.default_rng(0xAB5D + int(salt))
    host = rng.choice(np.asarray([-1.0, 1.0]), size=int(n))
    dev = jnp.asarray(host, dtype=acc)
    _vec_cache[key] = dev
    if len(_vec_cache) > 64:
        _vec_cache.pop(next(iter(_vec_cache)))
    return dev


@jax.jit
def _delta_probe0(out, u, v):
    """`_delta_probe` for a first-touch (beta==0) launch: the pristine
    C is identically zero, so the left side reads only ``out``."""
    acc = _acc_dtype(out.dtype)
    r = jnp.einsum("smn,m,n->s", out.astype(acc), u, v,
                   precision=jax.lax.Precision.HIGHEST)
    return r, jnp.max(jnp.abs(out.astype(acc)))


@jax.jit
def _delta_probe(base, out, u, v):
    """Left side: ``u · (out - base) · v`` per C segment — a scalar
    per segment — plus the magnitude scale the relative comparison
    needs (|out| enters because the rounding of a stored C value is
    relative to C, not to the delta)."""
    acc = _acc_dtype(out.dtype)
    r = jnp.einsum("smn,m,n->s", out.astype(acc) - base.astype(acc),
                   u, v, precision=jax.lax.Precision.HIGHEST)
    return r, jnp.max(jnp.abs(out.astype(acc)))


@functools.partial(jax.jit, static_argnames=("nseg",))
def _span_probe(a_data, b_data, ai, bi, ci, u, v, alpha, nseg: int):
    """Right side: ``alpha * Σ_s (uᵀA)[ai_s] · (B v)[bi_s]`` per C
    segment (sorted segment-sum, same accumulation discipline as the
    kernels).  ``uᵀA``/``B·v`` contract over the unique bucketed
    panels, NOT per span — the probe reads each operand block once
    however many spans reuse it."""
    acc = _acc_dtype(a_data.dtype)
    ua = jnp.einsum("amk,m->ak", a_data.astype(acc), u,
                    precision=jax.lax.Precision.HIGHEST)
    bv = jnp.einsum("bkn,n->bk", b_data.astype(acc), v,
                    precision=jax.lax.Precision.HIGHEST)
    s = jnp.einsum("sk,sk->s", jnp.take(ua, ai, axis=0),
                   jnp.take(bv, bi, axis=0),
                   precision=jax.lax.Precision.HIGHEST)
    p = jax.ops.segment_sum(s, ci, num_segments=nseg,
                            indices_are_sorted=True)
    return alpha.astype(acc) * p


@functools.partial(jax.jit, static_argnames=("nseg",))
def _stack_probe_err(base, out, a_data, b_data, ai, bi, ci, u, v,
                     alpha, nseg: int):
    """The WHOLE per-stack probe as one program returning the scalar
    pair ``[err, scale]`` — the hot-path form: one dispatch and one
    host sync per guarded launch (the unfused probe paid ~3 dispatches
    plus two blocking reads, which dominated the check's cost on small
    kernels)."""
    r, out_scale = _delta_probe(base, out, u, v)
    p = _span_probe(a_data, b_data, ai, bi, ci, u, v, alpha, nseg)
    err = jnp.max(jnp.abs(r - p))
    scale = jnp.maximum(jnp.max(jnp.abs(p)), out_scale)
    return jnp.stack([err, scale]).real


@functools.partial(jax.jit, static_argnames=("nseg",))
def _stack_probe_err0(out, a_data, b_data, ai, bi, ci, u, v, alpha,
                      nseg: int):
    """`_stack_probe_err` for a first-touch (beta==0) launch — no base
    operand, and ONE pass over C.  The comparison scale comes from the
    abs-value probe ``S_c = |alpha|·Σ_s Σ_k |uᵀA|[ai]·|B v|[bi]``: with
    Rademacher ±1 weights, ``Σ|terms|`` of BOTH compared reductions is
    bounded by S (out == ΔC here, and ``|ΔC_ij| ≤ Σ_s |A@B|_ij``), so
    ``eps·S`` rigorously bounds the legitimate rounding disagreement
    without re-reading C for a ``max|out|``."""
    acc = _acc_dtype(out.dtype)
    r = jnp.einsum("smn,m,n->s", out.astype(acc), u, v,
                   precision=jax.lax.Precision.HIGHEST)
    p = _span_probe(a_data, b_data, ai, bi, ci, u, v, alpha, nseg)
    ua = jnp.einsum("amk,m->ak", jnp.abs(a_data.astype(acc)),
                    jnp.abs(u), precision=jax.lax.Precision.HIGHEST)
    bv = jnp.einsum("bkn,n->bk", jnp.abs(b_data.astype(acc)),
                    jnp.abs(v), precision=jax.lax.Precision.HIGHEST)
    s_abs = jnp.einsum("sk,sk->s", jnp.take(ua, ai, axis=0),
                       jnp.take(bv, bi, axis=0),
                       precision=jax.lax.Precision.HIGHEST)
    S = jnp.abs(alpha.astype(acc)) * jax.ops.segment_sum(
        s_abs, ci, num_segments=nseg, indices_are_sorted=True)
    err = jnp.max(jnp.abs(r - p))
    scale = jnp.max(S)
    return jnp.stack([err, scale]).real


@jax.jit
def _compare_err(r, p, out_scale):
    """Fused tail of an accumulated (superstack) probe: ``[err,
    scale]`` in one dispatch/sync."""
    err = jnp.max(jnp.abs(r - p))
    scale = jnp.maximum(jnp.max(jnp.abs(p)), out_scale)
    return jnp.stack([err, scale]).real


def _segment_depth(ci: np.ndarray) -> int:
    """Deepest accumulation any C segment sees (ci sorted ascending)."""
    if len(ci) == 0:
        return 1
    return int(np.bincount(ci.astype(np.int64)).max())


def _record_check(driver: str) -> None:
    _metrics.counter(
        "dbcsr_tpu_abft_checks_total",
        "ABFT probe checksums evaluated, by driver/site",
    ).inc(driver=driver)


def record_mismatch(driver: str, site: str, **detail) -> None:
    """Count + publish one detected probe mismatch WITHOUT raising —
    for callers that carry their own structured error (the tick
    pipelines' conservation check)."""
    _metrics.counter(
        "dbcsr_tpu_abft_mismatches_total",
        "ABFT probe checksums that disagreed beyond tolerance (silent "
        "data corruption detected), by driver/site",
    ).inc(driver=driver)
    _events.publish("abft_mismatch",
                    dict(detail, driver=driver, site=site), flight=True)


def _mismatch(driver: str, err: float, tol: float, scale: float,
              shape, site: str = "stack") -> None:
    shape_s = "x".join(str(x) for x in shape)
    record_mismatch(driver, site, rel_err=float(err),
                    tolerance=float(tol), scale=float(scale),
                    shape=shape_s)
    raise AbftMismatchError(
        f"ABFT probe mismatch at {site} (driver {driver!r}, shape "
        f"{shape_s}): relative error {err:.3e} > tolerance "
        f"{tol:.3e} — finite silent data corruption")


def record_recovery(driver: str) -> None:
    """Count one successful re-execution that replaced an SDC-condemned
    result (smm failover, chain rollback recompute, serve re-execute)."""
    _metrics.counter(
        "dbcsr_tpu_abft_recoveries_total",
        "SDC-condemned results successfully recomputed and accepted, "
        "by driver/site",
    ).inc(driver=driver)
    _events.publish("abft_recovery", {"driver": driver}, flight=True)


def _check_scalars(err: float, scale: float, *, dtype, k: int,
                   depth: int, driver: str, shape, site: str,
                   prec=None, cells=None) -> None:
    """``prec``/``cells`` mark a launch executed at a DEMOTED compute
    dtype (`acc.precision` spec + the (m,n,k,dtype) cells involved):
    the ceiling widens to the demotion tolerance, a breach promotes the
    cells and raises `PrecisionExceededError` instead of the SDC path,
    and a pass feeds the residual back to the planner as headroom."""
    dt = str(jnp.dtype(dtype))
    if prec is not None:
        tol = _costmodel.demoted_abft_tolerance(dt, prec[0], prec[1],
                                                k, depth)
    else:
        tol = _costmodel.abft_tolerance(dt, k, depth)
    rel = err / max(scale, 1e-30)
    if not np.isfinite(err) or err > tol * max(scale, 1e-30):
        if prec is not None:
            from dbcsr_tpu.acc import precision as _precision

            _precision.note_exceeded(cells, rel, tol)
            shape_s = "x".join(str(x) for x in shape)
            raise PrecisionExceededError(
                f"demoted-precision probe residual at {site} (driver "
                f"{driver!r}, shape {shape_s}, compute {prec[0]}"
                f"{'+comp' if prec[1] else ''}): relative error "
                f"{rel:.3e} > demotion ceiling {tol:.3e} — cells "
                f"promoted to native")
        _mismatch(driver, rel, tol, scale, shape, site=site)
    elif prec is not None and cells:
        from dbcsr_tpu.acc import precision as _precision

        _precision.note_probe_ok(cells, rel)


# ------------------------------------------------ deferred verification

_tls = threading.local()


def _pending_list() -> list:
    lst = getattr(_tls, "pending", None)
    if lst is None:
        lst = _tls.pending = []
    return lst


def pending_count() -> int:
    return len(_pending_list())


def discard_pending() -> None:
    """Drop this thread's queued-but-unevaluated probe scalars — called
    before a deferring run so an earlier aborted product can never
    misattribute its corruption to this one."""
    _pending_list().clear()


def flush() -> None:
    """Evaluate every probe this thread deferred.  Deferral is the
    overlap-preserving mode: a guarded launch queues its device-side
    ``[err, scale]`` pair WITHOUT a host sync, the dispatch pipeline
    keeps running ahead of the device, and the product boundary
    (`mm.multiply._run_stacks`) pays one drain here instead of a
    pipeline stall per launch.  Every queued probe is evaluated (so
    each mismatch is counted and published), then the FIRST mismatch
    re-raises with ``.driver``/``.shape_key`` attached so the caller
    can feed the breaker plane and re-execute the product."""
    pend = _pending_list()
    if not pend:
        return
    items, pend[:] = list(pend), []
    first_sdc: Optional[AbftMismatchError] = None
    first_prec: Optional[PrecisionExceededError] = None
    mismatch_drivers: list = []
    for es_dev, meta, shape_key in items:
        es = np.asarray(es_dev)
        try:
            _check_scalars(float(es[0]), float(es[1]), **meta)
        except PrecisionExceededError as exc:
            # adaptive promote, not corruption: the cells were
            # promoted when the check raised; keep it OUT of the
            # mismatch/recovery accounting (a PrecisionExceeded never
            # recorded a mismatch, so attributing a recovery to its
            # driver would unbalance the counters)
            exc.driver = meta["driver"]
            exc.shape_key = shape_key
            if first_prec is None:
                first_prec = exc
        except AbftMismatchError as exc:
            exc.driver = meta["driver"]
            exc.shape_key = shape_key
            mismatch_drivers.append(meta["driver"])
            if first_sdc is None:
                first_sdc = exc
    if first_sdc is not None:
        # one re-execution heals EVERY mismatched launch of the
        # product: the caller records one recovery per entry here, so
        # the mismatch/recovery counters stay balanced and health
        # never reports fully-recovered SDC as escaped corruption.
        # A genuine SDC outranks a co-queued precision breach — the
        # redo runs with immediate verification, where each demoted
        # plan still heals itself.
        first_sdc.mismatch_drivers = mismatch_drivers
        raise first_sdc
    if first_prec is not None:
        raise first_prec


# ----------------------------------------------------- stack boundary

def check_stack(base, out, a_data, b_data, plan, alpha,
                c_zero: bool = False, defer: bool = False,
                shape_key=None) -> None:
    """Probe-verify one executed stack plan: ``base`` is the pristine C
    the launch started from (ignored under ``c_zero``, where it is
    identically zero by the caller's contract and may not even exist),
    ``out`` its result.  Raises `AbftMismatchError` on disagreement —
    immediately, or at the caller's `flush` when ``defer`` is set (the
    overlap-preserving mode; only callers that can re-execute the whole
    product may defer).  Silently skips plans with no retained source
    indices (cannot reconstruct the right side)."""
    src = getattr(plan, "src_idx", None)
    if src is None or (base is None and not c_zero):
        return
    ai, bi, ci = src
    nseg, m, n = out.shape
    k = a_data.shape[2]
    _record_check(plan.driver)
    u = probe_vector(m, out.dtype, salt=1)
    v = probe_vector(n, out.dtype)
    acc = _acc_dtype(out.dtype)
    idx = (
        _mempool.upload_index("abft_a", np.ascontiguousarray(ai, np.int32)),
        _mempool.upload_index("abft_b", np.ascontiguousarray(bi, np.int32)),
        _mempool.upload_index("abft_c", np.ascontiguousarray(ci, np.int32)),
    )
    alpha_dev = jnp.asarray(alpha, dtype=acc)
    if c_zero:
        es_dev = _stack_probe_err0(
            out, a_data, b_data, *idx, u, v, alpha_dev, nseg)
    else:
        es_dev = _stack_probe_err(
            base, out, a_data, b_data, *idx, u, v, alpha_dev, nseg)
    # the double-sided probe folds the u (length-m) contraction into
    # every compared scalar: widen the accumulation depth accordingly
    prec = getattr(plan, "precision", None)
    # the k-merged grouped layout contracts r0*k products per dot: the
    # demoted ceiling's narrow-accumulation term must see the MERGED
    # length or it condemns healthy grouped launches
    k_tol = k * max(getattr(plan, "r_grp", 1), 1) \
        if (prec is not None and plan.driver == "xla_group") else k
    meta = dict(dtype=out.dtype, k=k_tol,
                depth=_segment_depth(np.asarray(ci)) * max(m, n),
                driver=plan.driver, shape=(m, n, k), site="stack",
                prec=prec,
                cells=([(m, n, k, str(jnp.dtype(out.dtype)))]
                       if prec is not None else None))
    if defer:
        _pending_list().append((es_dev, meta, shape_key))
        return
    es = np.asarray(es_dev)
    _check_scalars(float(es[0]), float(es[1]), **meta)


def check_superstack(base, out, a_datas, b_datas, splan, alpha,
                     c_zero: bool = False, defer: bool = False,
                     shape_key=None) -> None:
    """Probe-verify one fused C-bin launch: the right side sums every
    span's contribution (the bin's C is read+written once, so one delta
    probe covers the whole launch).  Under ``c_zero`` the pristine bin
    is identically zero and ``base`` is never touched (it may alias a
    donated buffer)."""
    nseg, m, n = out.shape
    u = probe_vector(m, out.dtype, salt=1)
    v = probe_vector(n, out.dtype)
    acc = _acc_dtype(out.dtype)
    alpha_dev = jnp.asarray(alpha, dtype=acc)
    if c_zero:
        r, out_scale = _delta_probe0(out, u, v)
    else:
        r, out_scale = _delta_probe(base, out, u, v)
    p = jnp.zeros((nseg,), acc)
    k_max, depth = 1, 1
    prec = None  # the loosest demoted spec among the bin's spans
    cells: list = []
    dt_name = str(jnp.dtype(out.dtype))
    for plan, a_d, b_d in zip(splan.plans, a_datas, b_datas):
        src = getattr(plan, "src_idx", None)
        if src is None:
            return  # cannot reconstruct this span: skip the whole bin
        p_prec = getattr(plan, "precision", None)
        if p_prec is not None:
            cells.append((a_d.shape[1], b_d.shape[2], a_d.shape[2],
                          dt_name))
            if prec is None or (
                _costmodel.effective_epsilon(*p_prec)
                > _costmodel.effective_epsilon(*prec)
            ):
                prec = p_prec
            if plan.driver == "xla_group":
                # merged contraction length (see check_stack)
                k_max = max(k_max,
                            a_d.shape[2] * max(plan.r_grp, 1))
        ai, bi, ci = src
        p = p + _span_probe(
            a_d, b_d,
            _mempool.upload_index("abft_a",
                                  np.ascontiguousarray(ai, np.int32)),
            _mempool.upload_index("abft_b",
                                  np.ascontiguousarray(bi, np.int32)),
            _mempool.upload_index("abft_c",
                                  np.ascontiguousarray(ci, np.int32)),
            u, v, alpha_dev, nseg,
        )
        k_max = max(k_max, a_d.shape[2])
        depth += _segment_depth(np.asarray(ci))
    _record_check("fused")
    es_dev = _compare_err(r, p, out_scale)
    meta = dict(dtype=out.dtype, k=k_max, depth=depth * max(m, n),
                driver="fused", shape=(m, n, len(splan.plans)),
                site="superstack", prec=prec, cells=cells or None)
    if defer:
        _pending_list().append((es_dev, meta, shape_key))
        return
    es = np.asarray(es_dev)
    _check_scalars(float(es[0]), float(es[1]), **meta)


# ------------------------------------------------ dense-path probes

def check_dense_canvas(cd, ad, bd, c_old, alpha, beta, *, dtype,
                       driver: str = "dense") -> None:
    """Probe-verify a dense-mode product canvas: ``cd`` must equal
    ``alpha * ad @ bd + beta * c_old`` (``c_old`` None when beta == 0
    or C was empty), checked through the rank-1 identity
    ``cd·v == alpha*ad@(bd·v) + beta*(c_old·v)``.  The mm layer calls
    this after `_dense_guard`; a mismatch raises `AbftMismatchError`,
    which the dense→stack failover classifies ``sdc`` and answers by
    re-executing the product on the stack engine (where the per-stack
    probes and the chain recovery apply)."""
    acc = _acc_dtype(dtype)
    n = int(cd.shape[1])
    k = int(ad.shape[1])
    _record_check(driver)
    v = probe_vector(n, dtype)
    lhs = cd.astype(acc) @ v
    rhs = jnp.asarray(alpha, dtype=acc) * (
        ad.astype(acc) @ (bd.astype(acc) @ v))
    if c_old is not None:
        rhs = rhs + jnp.asarray(beta, dtype=acc) * (c_old.astype(acc) @ v)
    err = float(jnp.max(jnp.abs(lhs - rhs)))
    scale = float(jnp.maximum(jnp.max(jnp.abs(lhs)),
                              jnp.max(jnp.abs(rhs))))
    tol = _costmodel.abft_tolerance(str(jnp.dtype(dtype)), k, 4)
    if not np.isfinite(err) or err > tol * max(scale, 1e-30):
        _mismatch(driver, err / max(scale, 1e-30), tol, scale,
                  (cd.shape[0], n, k), site="dense")


def check_dense_canvas_batched(pd, ad, bd, *, dtype,
                               driver: str = "composite") -> None:
    """Batched sibling of `check_dense_canvas` for the composite panel
    path: the raw batched product ``pd[g]`` must equal ``ad[g] @ bd[g]``
    for EVERY panel g, checked through the same rank-1 probe identity
    per panel and reduced to a single worst-panel error — ONE host sync
    for the whole batch, so the check never serializes the panels the
    composite format exists to fuse."""
    acc = _acc_dtype(dtype)
    n = int(pd.shape[2])
    k = int(ad.shape[2])
    _record_check(driver)
    v = probe_vector(n, dtype)
    lhs = jnp.einsum("gmn,n->gm", pd.astype(acc), v)
    rhs = jnp.einsum("gmk,gk->gm", ad.astype(acc),
                     jnp.einsum("gkn,n->gk", bd.astype(acc), v))
    err_d = jnp.max(jnp.abs(lhs - rhs))
    scale_d = jnp.maximum(jnp.max(jnp.abs(lhs)), jnp.max(jnp.abs(rhs)))
    es = np.asarray(jnp.stack([err_d, scale_d]))
    err, scale = float(es[0]), float(es[1])
    tol = _costmodel.abft_tolerance(str(jnp.dtype(dtype)), k, 4)
    if not np.isfinite(err) or err > tol * max(scale, 1e-30):
        _mismatch(driver, err / max(scale, 1e-30), tol, scale,
                  (int(pd.shape[0]), int(pd.shape[1]), n, k),
                  site="dense")


# ------------------------------------------- distributed tick probes

def tree_probe_device(tree):
    """Device-side `tree_probe`: the same permutation-invariant
    absolute-sum as ONE queued device scalar, NO host sync — the tick
    pipelines queue one per shift and evaluate after the loop, so the
    probe never serializes the comm/compute overlap the double-buffer
    mode exists for.  Returns None when the tree has no inexact
    leaves."""
    total = None
    for leaf in jax.tree_util.tree_leaves(tree):
        if not jnp.issubdtype(leaf.dtype, jnp.inexact):
            continue
        acc = _acc_dtype(leaf.dtype)
        s = jnp.sum(jnp.abs(leaf.astype(acc)))
        total = s if total is None else total + s
    return total


def tree_probe(tree) -> float:
    """Permutation-invariant probe of a pytree of device arrays: the
    global sum of finite absolute values.  A ring shift permutes shard
    contents without changing them, so this probe is conserved across
    every shift of the tick pipelines (`parallel/overlap.run_ticks`) —
    up to resummation rounding, which `shift_conserved` tolerates.
    Blocking form of `tree_probe_device`."""
    dev = tree_probe_device(tree)
    return 0.0 if dev is None else float(dev)


def shift_conserved(before: float, after: float, dtype,
                    nelem: int) -> bool:
    """True when a shift's probe survived within resummation rounding
    of ``nelem`` accumulated terms."""
    tol = _costmodel.abft_tolerance(str(jnp.dtype(dtype)), 1, nelem)
    scale = max(abs(before), abs(after), 1e-30)
    if not np.isfinite(after):
        return False
    return abs(after - before) <= tol * scale


# ------------------------------------------------- whole-matrix probes

@functools.partial(jax.jit, static_argnames=("bm", "bn"))
def _bin_probe(out_vec, data, ro, co, v, bm: int, bn: int):
    """One shape-bin's contribution to ``M @ v``: gather each block's v
    segment, block mat-vec, scatter-add at row offsets (dead bucket
    slots carry out-of-range row offsets -> dropped; their data rows
    are zeros by the bucket-padding invariant, so the clamped v gather
    is harmless)."""
    acc = _acc_dtype(data.dtype)
    vseg = jnp.take(v, co[:, None] + jnp.arange(bn)[None, :], axis=0,
                    mode="clip")
    prod = jnp.einsum("sij,sj->si", data.astype(acc), vseg.astype(acc),
                      precision=jax.lax.Precision.HIGHEST)
    idx = ro[:, None] + jnp.arange(bm)[None, :]
    return out_vec.at[idx].add(prod, mode="drop")


def matrix_probe(m, v) -> object:
    """``M @ v`` as a device vector (nfullrows,) — the whole-matrix
    probe the serving plane verifies requests with.  ``v`` is a device
    vector of length ``nfullcols`` (or any conformable probe, e.g. the
    output of another matrix_probe).  Structure-derived offsets ride
    the per-matrix device mirror, so repeated probes of a
    pattern-stable matrix upload nothing."""
    acc = _acc_dtype(m.dtype)
    out = jnp.zeros((m.nfullrows,), acc)
    if m.nblks == 0:
        return out
    rows, cols = m.entry_coords()
    roff = m.row_blk_offsets[rows]
    coff = m.col_blk_offsets[cols]
    oor = np.int64(1) << 30  # dropped by the scatter (int32-safe)
    for b_id, b in enumerate(m.bins):
        if b.count == 0:
            continue

        def _offsets(b_id=b_id, b=b):
            sel = np.nonzero(m.ent_bin == b_id)[0]
            cap = b.data.shape[0]
            ro = np.full(cap, oor, np.int64)
            co = np.zeros(cap, np.int64)  # clamped gather; zero rows
            ro[m.ent_slot[sel]] = roff[sel]
            co[m.ent_slot[sel]] = coff[sel]
            return jnp.asarray(ro), jnp.asarray(co)

        ro_d, co_d = m.device_index(("abft_off", b_id), _offsets)
        out = _bin_probe(out, b.data, ro_d, co_d, v.astype(acc),
                         bm=b.shape[0], bn=b.shape[1])
    return out


def product_probeable(params: dict) -> bool:
    """True when a serving-plane multiply request admits the algebraic
    probe identity: no value-dependent filtering (dropped small blocks
    break ``C = alpha*A@B + beta*C`` exactly), no pattern lock, no
    windowed limits, and plain 'N' operands (the probe does not model
    op() transposes)."""
    return (
        params.get("filter_eps") is None
        and not params.get("retain_sparsity")
        and str(params.get("transa", "N")).upper() == "N"
        and str(params.get("transb", "N")).upper() == "N"
    )


def verify_product(a, b, c, alpha, beta, r_old: Optional[object],
                   *, request_id: str = "") -> None:
    """Probe-verify one completed serving-plane multiply:
    ``C_new·v == alpha * A@(B@v) + beta * (C_old·v)``.  ``r_old`` is
    the pre-execution probe of C (None means beta == 0).  Raises
    `AbftMismatchError` on disagreement."""
    n = c.nfullcols
    k = a.nfullcols
    _record_check("serve")
    v = probe_vector(n, c.dtype)
    r_c = matrix_probe(c, v)
    rhs = matrix_probe(a, matrix_probe(b, v))
    acc = _acc_dtype(c.dtype)
    rhs = jnp.asarray(alpha, dtype=acc) * rhs
    if r_old is not None:
        rhs = rhs + jnp.asarray(beta, dtype=acc) * r_old
    err = float(jnp.max(jnp.abs(r_c - rhs)))
    scale = float(jnp.maximum(jnp.max(jnp.abs(r_c)),
                              jnp.max(jnp.abs(rhs))))
    tol = _costmodel.abft_tolerance(str(np.dtype(c.dtype)), k,
                                    max(a.nblkcols, 1) * 4)
    if not np.isfinite(err) or err > tol * max(scale, 1e-30):
        record_mismatch("serve", "serve_execute",
                        rel_err=err / max(scale, 1e-30), tolerance=tol,
                        request_id=request_id,
                        shape=f"{c.nfullrows}x{c.nfullcols}x{k}")
        raise AbftMismatchError(
            f"ABFT probe mismatch on served product {request_id or '?'}: "
            f"relative error {err / max(scale, 1e-30):.3e} > {tol:.3e}")
