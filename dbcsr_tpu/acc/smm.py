"""Batched small-matrix-multiply over parameter stacks (the hot kernel).

TPU-native equivalent of `libsmm_acc_process` / `libsmm_acc_transpose` /
`c_calculate_norms` (`src/acc/acc_libsmm.h:38-49`).  A parameter stack
is three int32 arrays of equal length S: for entry s,

    C[c_idx[s]] += alpha * A[a_idx[s]] @ B[b_idx[s]]

where A is a (Na, m, k) device array of same-shape blocks, B is
(Nb, k, n) and C is (Nc, m, n) — one array per block-shape bin (the
reference enumerates block sizes the same way, `dbcsr_mm_common.F:309`).

Key differences from the CUDA design, by intent:

* The reference relies on ``atomicAdd`` into C; TPU wants deterministic
  accumulation, so stacks arrive **sorted by c_idx** and accumulation is
  a sorted ``segment_sum`` (bit-reproducible for fixed stack order —
  the "bit-identical checksums" north star).
* The per-(m,n,k) NVRTC JIT cache (`libsmm_acc.cpp:89-224`) becomes the
  XLA jit cache: each (m, n, k, dtype, stack-bucket) specializes once.
* Stack entries are padded up to a size bucket with ``c_idx == Nc``;
  out-of-range segment ids are dropped by XLA, giving masked no-op
  entries with static shapes.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dbcsr_tpu.acc import abft as _abft
from dbcsr_tpu.core import mempool as _mempool
from dbcsr_tpu.core.config import get_config
from dbcsr_tpu.core.kinds import real_dtype_of
from dbcsr_tpu.obs import costmodel as _costmodel
from dbcsr_tpu.obs import events as _events
from dbcsr_tpu.obs import flight as _flight
from dbcsr_tpu.obs import metrics as _metrics
from dbcsr_tpu.obs import tracer as _trace
from dbcsr_tpu.resilience import breaker as _breaker
from dbcsr_tpu.resilience import faults as _faults
from dbcsr_tpu.utils.compat import enable_x64 as _enable_x64
from dbcsr_tpu.utils.rounding import bucket_size


def emulated_dtype_on_tpu(dtype) -> bool:
    """True when ``dtype`` is software-EMULATED on the current device
    (f64/c128 on TPU: split-f32/bf16 passes).  The single gate shared
    by every driver decision that exists to counter the emulation
    penalty (the xla_group default here and the mesh path's
    `_stack_r0`).  Keys on `effective_platform` so the CPU suite can
    assert the TPU branch (config.platform_override seam)."""
    from dbcsr_tpu.core.config import effective_platform

    return (
        np.dtype(dtype) in (np.float64, np.complex128)
        and effective_platform() == "tpu"
    )


def _accum_dtype(dtype):
    """Accumulate bf16 in f32; everything else in its own precision."""
    d = jnp.dtype(dtype)
    if d == jnp.bfloat16:
        return jnp.float32
    return d


_BATCH_DOT_DIMS = (((2,), (1,)), ((0,), (0,)))


def _split_hi_lo(x, cdt):
    """Two-product operand split: ``hi = compute(x)`` plus the residue
    ``lo = compute(x - hi)`` — hi recovers the top mantissa bits, lo
    the next compute-width's worth, so hi·hi + hi·lo + lo·hi restores
    the wide product up to O(eps_compute²) (the dropped lo·lo term)."""
    hi = x.astype(cdt)
    lo = (x - hi.astype(x.dtype)).astype(cdt)
    return hi, lo


def _batch_dot(a, b, acc, prec):
    """One batched block contraction at the plan's EXECUTED precision.

    ``prec`` is the `acc.precision` spec (compute_dtype, compensated)
    or None for native.  Native keeps the historical contract (HIGHEST
    precision at the request dtype — f32 runs as true f32 on the MXU,
    bf16 data uses fast bf16 inputs with f32 accumulation via
    preferred_element_type).  Demoted casts the gathered operands to
    the compute dtype IN-KERNEL (the stored panels stay at the request
    dtype — no operand duplication, HBM traffic unchanged) and
    accumulates in ``acc`` (the wide `_accum_dtype`); compensated adds
    the two cross-term dots of the hi/lo split."""
    dot = functools.partial(
        jax.lax.dot_general, dimension_numbers=_BATCH_DOT_DIMS,
        preferred_element_type=acc, precision=jax.lax.Precision.HIGHEST,
    )
    if prec is None:
        return dot(a, b)
    cdt = jnp.dtype(prec[0])
    if not prec[1]:
        # natural narrow accumulator inside the dot (f32 for f32/bf16
        # inputs), widened AFTER it: a narrow-input dot with a forced
        # wide preferred_element_type abandons the fast GEMM lowering
        # on every backend (measured ~12x on XLA-CPU), which would
        # erase the demotion win; the extra k-deep narrow accumulation
        # is inside the demotion ceiling (eps_compute * k << the x64
        # margin on block-sized k)
        narrow = jnp.promote_types(cdt, jnp.float32)
        out = jax.lax.dot_general(
            a.astype(cdt), b.astype(cdt), _BATCH_DOT_DIMS,
            preferred_element_type=narrow,
            precision=jax.lax.Precision.HIGHEST,
        )
        return out.astype(acc)
    ah, al = _split_hi_lo(a, cdt)
    bh, bl = _split_hi_lo(b, cdt)
    return dot(ah, bh) + (dot(ah, bl) + dot(al, bh))


def _chunk_contrib(a_data, b_data, a_idx, b_idx, c_idx, alpha, nseg,
                   out_dtype, prec=None):
    """One stack chunk: gather -> batched matmul -> sorted segment-sum."""
    a = jnp.take(a_data, a_idx, axis=0)
    b = jnp.take(b_data, b_idx, axis=0)
    acc = _accum_dtype(out_dtype)
    prod = _batch_dot(a, b, acc, prec)
    prod = (alpha.astype(acc) * prod).astype(out_dtype)
    return jax.ops.segment_sum(prod, c_idx, num_segments=nseg, indices_are_sorted=True)


def _stack_xla_flat_body(c_data, a_data, b_data, a_idx, b_idx, c_idx, alpha,
                         prec=None):
    """Flat-gather variant: A/B are re-laid-out once per call to
    (N, m*k) so the per-entry gathers move lane-packed rows instead of
    tile-padded (m, k) blocks — the TPU HBM layout pads the last two
    dims to (sublane, 128) tiles, so gathering a 23x23 block moves ~6x
    its bytes; a 529-lane row moves ~1.2x.  The relayout is paid once
    per multiply, the gather savings S times (S >> N on the hot
    configs).  Toggle: config.flat_gather."""
    nseg, m, n = c_data.shape
    k = a_data.shape[2]
    a_flat = a_data.reshape(a_data.shape[0], m * k)
    b_flat = b_data.reshape(b_data.shape[0], k * n)

    def body(c, idx):
        ai, bi, ci = idx
        a = jnp.take(a_flat, ai, axis=0).reshape(-1, m, k)
        b = jnp.take(b_flat, bi, axis=0).reshape(-1, k, n)
        acc = _accum_dtype(c.dtype)
        prod = _batch_dot(a, b, acc, prec)
        prod = (alpha.astype(acc) * prod).astype(c.dtype)
        return c + jax.ops.segment_sum(
            prod, ci, num_segments=nseg, indices_are_sorted=True
        ), None

    c_data, _ = jax.lax.scan(body, c_data, (a_idx, b_idx, c_idx))
    return c_data


# dispatch entries: the raw bodies stay callable so the fused
# superstack program can chain them inside ONE jitted program (donation
# is a top-level dispatch property, so the fused program donates
# instead).  ``prec`` (the executed-precision spec) is static: each
# demoted specialization compiles its own program, exactly like the
# reference's per-(m,n,k,dtype) kernel cache gaining a precision axis.
_process_stack_xla_flat = functools.partial(
    jax.jit, donate_argnums=0, static_argnames=("prec",))(
    _stack_xla_flat_body)


def _stack_xla_group_body(c_data, a_data, b_data, ga, gb, gc, alpha,
                          prec=None):
    """R-tiled ("k-merged") stack layout: entries sharing a C block are
    tiled into groups of R0; each group's A blocks concatenate along k
    into one (m, R0*k) strip, its B blocks into (R0*k, n), and the
    whole group contracts in ONE dot — k grows R0-fold, and the
    per-entry segment-sum collapses to a per-group one.

    This is the f64 answer to the MXU-utilization problem the reference
    solves with kernel `grouping` (`smm_acc_dnt_*.h`: one thread block
    processes `grouping` stack entries): on TPU, f64 is emulated in
    split-f32/bf16 passes, so per-entry 23^3 dots run at ~2 GFLOP/s;
    R0=8 merging measured 3.5x that on the north-star stack (chip,
    forced-fetch timing — PERF_NOTES.md).

    ``ga``/``gb`` are (nchunks, CH, R0) gather indices, padded with a
    guaranteed-zero row id; ``gc`` is (nchunks, CH) segment ids with
    nseg for dead groups (dropped).  Groups of one segment stay in
    index order -> deterministic accumulation.
    """
    nseg, m, n = c_data.shape
    k = a_data.shape[2]
    r0 = ga.shape[2]

    def body(c, idx):
        ia, ib, ic = idx
        ch = ia.shape[0]
        ablk = jnp.take(a_data, ia.reshape(-1), axis=0).reshape(ch, r0, m, k)
        bblk = jnp.take(b_data, ib.reshape(-1), axis=0).reshape(ch, r0, k, n)
        amat = jnp.swapaxes(ablk, 1, 2).reshape(ch, m, r0 * k)
        bmat = bblk.reshape(ch, r0 * k, n)
        acc = _accum_dtype(c.dtype)
        prod = _batch_dot(amat, bmat, acc, prec)
        prod = (alpha.astype(acc) * prod).astype(c.dtype)
        return c + jax.ops.segment_sum(
            prod, ic, num_segments=nseg, indices_are_sorted=True
        ), None

    c_data, _ = jax.lax.scan(body, c_data, (ga, gb, gc))
    return c_data


_process_stack_xla_group = functools.partial(
    jax.jit, donate_argnums=0, static_argnames=("prec",))(
    _stack_xla_group_body)


def build_group_tiles(c_idx, a_idx, b_idx, r0: int, a_pad: int, b_pad: int,
                      c_pad: int, chunk_groups: int):
    """Host side of the grouped layout: split each C segment's entries
    into runs of ``r0`` (pad the last run with zero-row ids), returning
    (nchunks, CH, r0) a/b gather arrays + (nchunks, CH) segment ids.
    ``c_idx`` must be sorted ascending; dead/pad groups carry segment id
    ``c_pad`` (= nseg), keeping ids sorted and dropped by segment_sum."""
    s = len(c_idx)
    seg_starts = np.concatenate([[0], np.nonzero(np.diff(c_idx))[0] + 1])
    seg_len = np.diff(np.append(seg_starts, s))
    off_in_seg = np.arange(s) - np.repeat(seg_starts, seg_len)
    # group index: consecutive per (segment, run-of-r0) in entry order
    is_new_group = np.ones(s, bool)
    is_new_group[1:] = (off_in_seg[1:] % r0 == 0) | (c_idx[1:] != c_idx[:-1])
    gidx = np.cumsum(is_new_group) - 1
    n_groups = int(gidx[-1]) + 1
    ga = np.full((n_groups, r0), a_pad, np.int32)
    gb = np.full((n_groups, r0), b_pad, np.int32)
    slot = off_in_seg % r0
    ga[gidx, slot] = a_idx
    gb[gidx, slot] = b_idx
    gc = np.empty(n_groups, np.int32)
    gc[gidx] = c_idx
    nchunks = bucket_size(-(-n_groups // chunk_groups), minimum=1)
    total = nchunks * chunk_groups
    if total > n_groups:
        pad = total - n_groups
        ga = np.concatenate([ga, np.full((pad, r0), a_pad, np.int32)])
        gb = np.concatenate([gb, np.full((pad, r0), b_pad, np.int32)])
        gc = np.concatenate([gc, np.full(pad, c_pad, np.int32)])
    return (
        ga.reshape(nchunks, chunk_groups, r0),
        gb.reshape(nchunks, chunk_groups, r0),
        gc.reshape(nchunks, chunk_groups),
    )


def _stack_xla_body(c_data, a_data, b_data, a_idx, b_idx, c_idx, alpha,
                    prec=None):
    """Process a whole stack in one device program.

    The chunk loop lives INSIDE jit as a `lax.scan` over (nchunks, L)
    index arrays — the TPU-native replacement for the reference's
    stream-cycled stack buffers (`dbcsr_mm_accdrv.F:279-326`): one
    dispatch and one compilation per (m,n,k,bucket) instead of a Python
    loop of per-chunk launches.  Entries padded with c_idx == Nc are
    dropped by the segment-sum.
    """
    nseg = c_data.shape[0]

    def body(c, idx):
        ai, bi, ci = idx
        contrib = _chunk_contrib(
            a_data, b_data, ai, bi, ci, alpha, nseg, c.dtype, prec=prec
        )
        return c + contrib, None

    c_data, _ = jax.lax.scan(body, c_data, (a_idx, b_idx, c_idx))
    return c_data


_process_stack_xla = functools.partial(
    jax.jit, donate_argnums=0, static_argnames=("prec",))(
    _stack_xla_body)


def _append_pad_row(data):
    """Append the virtual guaranteed-zero row plans index one past the
    end of a data array (`append_a_pad`/`append_b_pad`) — the ONE
    definition of the pad convention shared by every per-span driver
    branch and the fused superstack program (they must agree bitwise)."""
    return jnp.concatenate(
        [data, jnp.zeros((1,) + data.shape[1:], data.dtype)])


def pad_stack(a_idx, b_idx, c_idx, target_len: int, drop_segment: int):
    """Pad int32 stack arrays to ``target_len`` with masked no-op entries."""
    s = len(a_idx)
    if s == target_len:
        return (
            np.ascontiguousarray(a_idx, np.int32),
            np.ascontiguousarray(b_idx, np.int32),
            np.ascontiguousarray(c_idx, np.int32),
        )
    pad = target_len - s
    return (
        np.concatenate([a_idx, np.zeros(pad, np.int32)]).astype(np.int32),
        np.concatenate([b_idx, np.zeros(pad, np.int32)]).astype(np.int32),
        np.concatenate([c_idx, np.full(pad, drop_segment, np.int32)]).astype(np.int32),
    )


# (m, n, k, dtype) combos whose Pallas kernel passed first-use
# validation (ref: libsmm_acc's per-kernel JIT-time checksum gate,
# `libsmm_acc.cpp:81-85,216` — hard exit on mismatch)
_validated_kernels: set = set()
_VALIDATE_MAX_ENTRIES = 512


class KernelValidationError(RuntimeError):
    """A device kernel produced results that differ from the host oracle."""


def _validate_pallas_kernel(c_data, a_data, b_data, a_idx, b_idx, c_idx,
                            a_pad_row, b_pad_row, grouping,
                            variant=None, pack=None) -> None:
    """First-use validation of the Pallas kernel for this shape/dtype.

    Runs a prefix of the actual stack (still sorted by c_idx) on a
    zeroed C through the Pallas path and through a NumPy host oracle,
    and hard-fails on mismatch — like `validate_kernel` in
    `libsmm_acc.cpp:216` (checksum vs CPU, exit(1) at :81-85).
    """
    from dbcsr_tpu.acc.pallas_smm import (
        process_stack_crosspack,
        process_stack_pallas,
    )

    s = min(len(a_idx), _VALIDATE_MAX_ENTRIES)
    ai = np.asarray(a_idx[:s], np.int32)
    bi = np.asarray(b_idx[:s], np.int32)
    ci = np.asarray(c_idx[:s], np.int32)
    c0 = jnp.zeros_like(c_data)
    if variant in ("crosspack", "crosspack_vmem"):
        got = process_stack_crosspack(
            c0, a_data, b_data, ai, bi, ci, 1.0,
            a_pad_row=a_pad_row, b_pad_row=b_pad_row, pack=pack,
            vmem_resident=(variant == "crosspack_vmem"),
        )
        if got is None:  # prefix ineligible: nothing to validate against
            raise KernelValidationError(
                "crosspack validation prefix was ineligible for the "
                "crosspack kernel; refusing to run it unvalidated"
            )
    else:
        got = process_stack_pallas(
            c0, a_data, b_data, ai, bi, ci, 1.0,
            a_pad_row=a_pad_row, b_pad_row=b_pad_row, grouping=grouping,
            variant=variant,
        )
    a_h = np.asarray(a_data[ai]).astype(np.float64)
    b_h = np.asarray(b_data[bi]).astype(np.float64)
    ref = np.zeros(c_data.shape, np.float64)
    np.add.at(ref, ci, np.einsum("smk,skn->smn", a_h, b_h))
    scale = max(np.max(np.abs(ref)), 1.0)
    # compare ON DEVICE, fetch one scalar: fetching the full C-shaped
    # validation result d2h persistently degrades the axon tunnel
    # (PERF_NOTES.md) and this gate runs in the production path
    cmp_dtype = (jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    err = float(
        jnp.max(jnp.abs(got.astype(cmp_dtype) - jnp.asarray(ref, cmp_dtype)))
    ) / scale
    # dtype-aware tolerance shared with the runtime ABFT ceilings and
    # the test suite's oracle comparisons — one source of truth
    # (obs.costmodel) instead of the historical 5e-2/1e-5 literals
    depth = int(np.bincount(ci.astype(np.int64)).max()) if s else 1
    tol = _costmodel.kernel_validation_tolerance(
        str(jnp.dtype(got.dtype)), a_data.shape[2], depth)
    if not np.isfinite(err) or err > tol:
        m, k = a_data.shape[1:]
        n = b_data.shape[2]
        raise KernelValidationError(
            f"pallas SMM kernel validation failed for "
            f"(m={m}, n={n}, k={k}, dtype={c_data.dtype}): "
            f"relative error {err:.3e} > {tol:.0e} vs host oracle"
        )


class StackPlan:
    """A prepared stack: device-resident index arrays + the driver
    decision, reusable across multiplies that share sparsity patterns
    (the index arrays depend only on the patterns, not the values).
    Built by `prepare_stack`, run by `execute_stack`."""

    __slots__ = ("driver", "nseg", "xla_idx", "launches", "r_grp",
                 "a_pad_row", "b_pad_row", "append_a_pad", "append_b_pad",
                 "val_idx", "group_idx", "kmerge", "pack", "cross_launches",
                 "cross_vmem", "cross_src", "host_idx", "src_idx",
                 "src_pads", "precision")

    def __init__(self):
        self.driver = "xla"
        self.nseg = 0
        self.xla_idx = None      # (ai, bi, ci) device (nchunks, chunk)
        self.launches = None     # pallas: [(ai_flat, bi_flat, ci) device]
        self.r_grp = 1
        self.a_pad_row = None
        self.b_pad_row = None
        self.append_a_pad = False  # pallas/group: append a zero row at execute
        self.append_b_pad = False
        self.val_idx = None      # host prefix for first-use validation
        self.group_idx = None    # xla_group: (ga, gb, gc) device arrays
        self.kmerge = False      # pallas: k-merged MXU dot variant
        self.pack = None         # pallas_cross: (P, R) MXU packing
        self.cross_launches = None  # pallas_cross: launch dicts
        self.cross_vmem = False  # pallas_cross: whole-array VMEM variant
        self.cross_src = None    # pallas_cross: host (ai, bi, ci) for
                                 # the compile-failure demotion rebuild
        self.host_idx = None     # host: numpy (ai, bi, ci) for the
                                 # native C++ stack driver
        self.src_idx = None      # host (ai, bi, ci) retained for the
                                 # breaker failover rebuild (any driver)
        self.src_pads = (None, None)  # the (a_pad_row, b_pad_row)
                                 # prepare_stack was originally given
        self.precision = None    # executed-precision spec
                                 # (compute_dtype, compensated) from
                                 # acc.precision.resolve; None = native

    def nbytes(self) -> int:
        """Approximate device bytes pinned by this plan (cache budget)."""
        total = 0
        if self.xla_idx is not None:
            total += sum(int(x.size) * 4 for x in self.xla_idx)
        if self.group_idx is not None:
            total += sum(int(x.size) * 4 for x in self.group_idx)
        if self.launches is not None:
            for lc in self.launches:
                total += sum(int(x.size) * 4 for x in lc)
        if self.cross_launches is not None:
            for lc in self.cross_launches:
                total += sum(
                    int(lc[key].size) * 4
                    for key in ("ai", "bi", "cg", "cl", "scatter_idx")
                )
        if self.cross_src is not None:  # host bytes, freed on first success
            total += sum(int(x.nbytes) for x in self.cross_src)
        if self.host_idx is not None:  # host bytes
            total += sum(int(x.nbytes) for x in self.host_idx)
        if self.src_idx is not None:  # host bytes (failover payload)
            total += sum(int(x.nbytes) for x in self.src_idx)
        return total


def _note_driver(driver: str, why: str, S: int, c_data, a_data, b_data,
                 tuned=None) -> None:
    """Feed the dispatch decision (and its reason) to the flight
    recorder — `prepare_stack` is the only place the *why* is known."""
    if tuned is not None and "predicted_from" in tuned:
        why += f"+predicted_from={tuned['predicted_from']}"
    _flight.note_driver(
        driver, why,
        mnk=(a_data.shape[1], b_data.shape[2], a_data.shape[2]),
        entries=S,
    )


def _ensure_pallas_validated(c_data, a_data, b_data, plan: StackPlan) -> None:
    """First-use validation of a base-pallas plan's compiled variant,
    keyed per (m, n, k, dtype, kmerge, r_grp) — shared by the per-span
    dispatch and the fused superstack path (which must validate OUTSIDE
    its fused program, before the first fused launch of the shape).
    The plan's RESOLVED r_grp is forced so the validator exercises the
    exact compiled variant being launched (ADVICE r3)."""
    if plan.val_idx is None or not get_config().validate_kernels:
        return
    key = (
        a_data.shape[1], b_data.shape[2], a_data.shape[2],
        str(jnp.dtype(c_data.dtype)), plan.kmerge, plan.r_grp,
    )
    if key in _validated_kernels:
        return
    ai, bi, ci = plan.val_idx
    _validate_pallas_kernel(
        c_data, a_data, b_data, ai, bi, ci,
        None if plan.append_a_pad else plan.a_pad_row,
        None if plan.append_b_pad else plan.b_pad_row,
        plan.r_grp, variant="kmerge" if plan.kmerge else None,
    )
    _validated_kernels.add(key)


def prepare_stack(c_data, a_data, b_data, a_idx, b_idx, c_idx,
                  a_pad_row=None, b_pad_row=None) -> Optional[StackPlan]:
    """Host side of stack processing: driver selection (tuned table +
    prediction), grouping/chunking/padding, and upload of the int32
    index arrays.  Returns None for an empty stack.

    The returned plan retains a host copy of the index arrays
    (``src_idx``) so `execute_stack`'s breaker failover can rebuild it
    for a different driver without the engine re-deriving the stack.
    A planning failure (injected, or a real host-side grouping bug)
    re-plans once on the safe XLA path instead of killing the
    multiply."""
    try:
        if _faults.active():
            _faults.maybe_inject("prepare_stack")
        plan = _prepare_stack_impl(c_data, a_data, b_data, a_idx, b_idx,
                                   c_idx, a_pad_row=a_pad_row,
                                   b_pad_row=b_pad_row)
    except Exception as exc:  # noqa: BLE001 — classified + recorded
        shape_key = _stack_shape_key(c_data, a_data, b_data)
        _record_driver_failure("prepare", _classify_failure(exc), exc,
                               shape_key)
        plan = _prepare_stack_impl(c_data, a_data, b_data, a_idx, b_idx,
                                   c_idx, a_pad_row=a_pad_row,
                                   b_pad_row=b_pad_row,
                                   cfg=_forced_cfg("xla"))
        _record_fallback("prepare", plan.driver if plan else "none",
                         shape_key)
    if plan is not None and plan.src_idx is None:
        plan.src_idx = (
            np.ascontiguousarray(a_idx, np.int32),
            np.ascontiguousarray(b_idx, np.int32),
            np.ascontiguousarray(c_idx, np.int32),
        )
        plan.src_pads = (a_pad_row, b_pad_row)
    return plan


def _prepare_stack_impl(c_data, a_data, b_data, a_idx, b_idx, c_idx,
                        a_pad_row=None, b_pad_row=None,
                        cfg=None) -> Optional[StackPlan]:
    """Driver selection + plan construction.  ``cfg`` overrides the
    live config — the failover path passes a copy with ``mm_driver``
    forced so one rebuild targets one specific chain driver."""
    if cfg is None:
        cfg = get_config()
    S = len(a_idx)
    if S == 0:
        return None
    # tuned preference (dbcsr_tpu.acc.params; analog of the per-GPU
    # parameter table consulted by libsmm_acc.cpp:227-249, with
    # nearest-neighbor prediction for untuned shapes standing in for
    # the predict/ ML pipeline) — resolved once here for the driver
    # choice, grouping, and the flat-gather layout decision
    from dbcsr_tpu.acc import params as params_mod

    # native host stack driver (the reference's CPU path,
    # dbcsr_mm_hostdrv.F:90 / tools/build_libsmm): explicit opt-in, or
    # a tuned-table row, on CPU backends only — through the axon tunnel
    # a host round-trip per stack would be catastrophic, so on TPU it
    # demotes to auto
    def _host_plan():
        plan = StackPlan()
        plan.nseg = c_data.shape[0]
        plan.driver = "host"
        plan.a_pad_row = a_pad_row
        plan.b_pad_row = b_pad_row
        plan.host_idx = (
            np.ascontiguousarray(a_idx, np.int32),
            np.ascontiguousarray(b_idx, np.int32),
            np.ascontiguousarray(c_idx, np.int32),
        )
        return plan

    if cfg.mm_driver == "host":
        if _host_smm_available(c_data.dtype):
            _note_driver("host", "config-forced", S, c_data, a_data, b_data)
            return _host_plan()
        import warnings

        warnings.warn(
            "mm_driver='host' but the native host driver is unavailable "
            "on this backend/dtype; falling back to auto selection",
            RuntimeWarning,
            stacklevel=2,
        )
    tuned = params_mod.predict(
        a_data.shape[1], b_data.shape[2], a_data.shape[2], c_data.dtype,
        stack_size=S,
    )
    tuned_driver = tuned.get("driver") if tuned else None
    # executed-precision resolution (acc.precision): a demoted spec
    # constrains dispatch to the XLA family (the compensated/demoted
    # kernels live there); an EXPLICIT driver force wins over the
    # demotion policy — the operator asked for that exact kernel
    from dbcsr_tpu.acc import precision as precision_mod

    prec = None
    if cfg.mm_driver not in ("pallas", "pallas_cross", "host"):
        prec = precision_mod.resolve(
            a_data.shape[1], b_data.shape[2], a_data.shape[2],
            c_data.dtype, tuned=tuned,
        )
    if (cfg.mm_driver == "auto" and tuned_driver == "host"
            and (prec is None or not precision_mod.forced())
            and _host_smm_available(c_data.dtype)):
        # a tuned native-host row outranks ADAPTIVE demotion: the C++
        # driver is the measured winner on this device kind, and
        # demoting would force the stack onto the slower XLA family
        # (measured ~7x on the CPU container) — only the FORCED bench
        # modes override it
        prec = None
        # the autotuner measured the native driver fastest for this
        # shape on this (CPU) device kind — the reference's MM_DRIVER=
        # smm per-shape dispatch (dbcsr_config.F:34-38)
        _note_driver("host", "tuned", S, c_data, a_data, b_data, tuned)
        return _host_plan()
    if prec is not None:
        # executed-precision span annotation (trace_summary surfaces
        # it next to the format/algorithm attrs): what this stack will
        # actually compute in, not what was requested
        _trace.annotate(
            precision=f"{prec[0]}{'+comp' if prec[1] else ''}")
    plan = StackPlan()
    plan.nseg = c_data.shape[0]
    # R-tiled grouped layout (see _process_stack_xla_group): the default
    # for emulated-f64 dtypes on TPU, where the per-entry dot is
    # MXU-starved; elsewhere f64 is native and per-entry is fine (same
    # platform gate as the mesh path's _stack_r0)
    want_group = cfg.mm_driver == "xla_group" or (
        cfg.mm_driver == "auto"
        and (
            tuned_driver == "xla_group"
            or (
                tuned_driver is None
                and S >= 2048
                and emulated_dtype_on_tpu(c_data.dtype)
            )
        )
    )
    if want_group:
        r0 = int(tuned.get("r0", 8)) if tuned else 8
        if a_pad_row is None:
            plan.append_a_pad = True
            a_pad_row = a_data.shape[0]
        if b_pad_row is None:
            plan.append_b_pad = True
            b_pad_row = b_data.shape[0]
        chunk_groups = max(256, cfg.mm_stack_size // r0)
        ga, gb, gc = build_group_tiles(
            np.asarray(c_idx), np.asarray(a_idx), np.asarray(b_idx),
            r0, a_pad_row, b_pad_row, plan.nseg, chunk_groups,
        )
        plan.driver = "xla_group"
        plan.r_grp = r0  # metadata: the R-tile grouping actually used
        plan.precision = prec
        plan.a_pad_row = a_pad_row
        plan.b_pad_row = b_pad_row
        # the device index mirror (core.mempool): pattern-stable
        # repeats (incl. filtered products the plan cache skips)
        # re-upload nothing
        plan.group_idx = (
            _mempool.upload_index("grp_a", ga),
            _mempool.upload_index("grp_b", gb),
            _mempool.upload_index("grp_c", gc),
        )
        _note_driver(
            "xla_group",
            "config-forced" if cfg.mm_driver == "xla_group"
            else ("tuned" if tuned_driver == "xla_group"
                  else "auto:emulated-f64-large-stack"),
            S, c_data, a_data, b_data, tuned,
        )
        return plan
    if prec is None and _pallas_supported(cfg, c_data, a_data, b_data):
        prefer_xla = (
            cfg.mm_driver == "auto" and tuned_driver in ("xla", "xla_flat")
        )
        if not prefer_xla:
            from dbcsr_tpu.acc import pallas_smm

            grouping = None
            kmerge = False
            tuned_cross = False
            if tuned and tuned.get("driver") == "pallas":
                if tuned.get("grouping"):
                    grouping = int(tuned["grouping"])
                kmerge = tuned.get("variant") == "kmerge"
                tuned_cross = tuned.get("variant") in ("crosspack",
                                                       "crosspack_vmem")
            # no guaranteed-zero row in the data array: the plan indexes
            # a virtual row one past the end, appended at execute time
            # (capacities are pattern-deterministic, so cached plans
            # remain valid across value changes)
            if a_pad_row is None:
                plan.append_a_pad = True
                a_pad_row = a_data.shape[0]
            if b_pad_row is None:
                plan.append_b_pad = True
                b_pad_row = b_data.shape[0]
            # cross-packed variant: forced by config, tuned-table
            # choice, or — on a REAL TPU — the default for untuned
            # f32 shapes (P*R entries per MXU pass; bf16 excluded, see
            # below).  A compile failure demotes the shape for the
            # session (_cross_disabled), so dispatch can never be
            # bricked by a Mosaic lowering gap; ineligible stacks fall
            # through to the base kernel
            shape_key = _stack_shape_key(c_data, a_data, b_data)
            # bf16 crosspack runs ONLY from an EXACT tuned row: a 23^3
            # bf16 crosspack launch dies with a Mosaic FATAL (process
            # abort — the in-process demotion can't catch it; observed
            # 2026-07-31, capture_loop.log), and the abort is
            # shape-specific, so neither untuned auto-crosspack nor a
            # nearest-neighbor-predicted donor row (proved on a
            # DIFFERENT shape) may select it.  The tuner subprocess is
            # the sacrificial process that proves each exact shape on
            # this backend first.
            is_bf16 = jnp.dtype(c_data.dtype) == jnp.bfloat16
            if tuned_cross and is_bf16 and "predicted_from" in tuned:
                tuned_cross = False
                grouping = None  # donor's crosspack R must not leak
                # into the base kernel (same rule as below)
            auto_cross = (
                cfg.mm_driver == "auto" and tuned is None and _on_tpu()
                and not is_bf16
            )
            want_cross = shape_key not in _cross_disabled and (
                cfg.mm_driver == "pallas_cross"
                or (cfg.mm_driver == "auto" and tuned_cross)
                or auto_cross
            )
            if tuned_cross:
                # a crosspack entry's "grouping" is the crosspack
                # k-depth R (tuned jointly with pack_p); it must not
                # leak into the base kernel if crosspack falls through
                grouping = None
            if want_cross:
                m_blk, k_blk = a_data.shape[1:]
                n_blk = b_data.shape[2]
                pack = None
                if (tuned and tuned.get("pack_p") and tuned.get("grouping")
                        and "predicted_from" not in tuned):
                    # exact tuned entry: accept, clamped to this shape's
                    # MXU geometry (defensive against a hand-edited or
                    # stale table row)
                    pack = (
                        min(int(tuned["pack_p"]),
                            max(1, 128 // max(m_blk, n_blk))),
                        min(int(tuned["grouping"]), max(1, 128 // k_blk)),
                    )
                else:
                    # nearest-neighbor-predicted donor: its pack was
                    # tuned for a DIFFERENT block shape; re-derive from
                    # this shape's geometry instead
                    pack = pallas_smm.choose_pack(m_blk, n_blk, k_blk)
                cross = None
                if pack[0] > 1:
                    cross = pallas_smm.prepare_crosspack_launches(
                        np.asarray(c_idx), np.asarray(a_idx),
                        np.asarray(b_idx), a_pad_row, b_pad_row,
                        pack[0], pack[1],
                    )
                if cross is not None:
                    plan.driver = "pallas_cross"
                    plan.pack = pack
                    plan.cross_src = (
                        np.ascontiguousarray(a_idx, np.int32),
                        np.ascontiguousarray(b_idx, np.int32),
                        np.ascontiguousarray(c_idx, np.int32),
                    )
                    # VMEM-resident gather variant: tuned-table only,
                    # and only while the operand arrays actually fit
                    plan.cross_vmem = bool(
                        tuned and tuned.get("variant") == "crosspack_vmem"
                        and pallas_smm.supports_vmem_resident(a_data, b_data)
                    )
                    plan.a_pad_row = a_pad_row
                    plan.b_pad_row = b_pad_row
                    plan.cross_launches = [
                        {
                            "ai": jnp.asarray(lc["ai"]),
                            "bi": jnp.asarray(lc["bi"]),
                            "cg": jnp.asarray(lc["cg"]),
                            "cl": jnp.asarray(lc["cl"]),
                            # one concatenated scatter per launch: lanes
                            # own disjoint C blocks, so set (not add)
                            "scatter_idx": jnp.asarray(
                                pallas_smm.lane_scatter_index(lc["lane_c"])
                            ),
                            "lane_len": [len(c) for c in lc["lane_c"]],
                            "nc_out": lc["nc_out"],
                        }
                        for lc in cross
                    ]
                    if cfg.validate_kernels:
                        s = min(S, _VALIDATE_MAX_ENTRIES)
                        plan.val_idx = (
                            np.asarray(a_idx[:s], np.int32),
                            np.asarray(b_idx[:s], np.int32),
                            np.asarray(c_idx[:s], np.int32),
                        )
                    _note_driver(
                        "pallas_cross",
                        "config-forced" if cfg.mm_driver == "pallas_cross"
                        else ("tuned" if tuned_cross
                              else "auto:untuned-f32-on-tpu"),
                        S, c_data, a_data, b_data, tuned,
                    )
                    return plan
            ai2, bi2, ci2, r_grp = pallas_smm.build_grouped_stack(
                np.asarray(c_idx), np.asarray(a_idx), np.asarray(b_idx),
                a_pad_row, b_pad_row, grouping=grouping,
            )
            plan.driver = "pallas"
            plan.r_grp = r_grp
            plan.kmerge = kmerge
            plan.a_pad_row = a_pad_row
            plan.b_pad_row = b_pad_row
            plan.launches = [
                tuple(_mempool.upload_index("pl_idx", x) for x in lc)
                for lc in pallas_smm.prepare_launches(
                    ai2, bi2, ci2, r_grp, a_pad_row, b_pad_row
                )
            ]
            if cfg.validate_kernels:
                s = min(S, _VALIDATE_MAX_ENTRIES)
                plan.val_idx = (
                    np.asarray(a_idx[:s], np.int32),
                    np.asarray(b_idx[:s], np.int32),
                    np.asarray(c_idx[:s], np.int32),
                )
            _note_driver(
                "pallas",
                "config-forced" if cfg.mm_driver in ("pallas", "pallas_cross")
                else ("tuned" if tuned_driver == "pallas"
                      else "auto:pallas-default"),
                S, c_data, a_data, b_data, tuned,
            )
            return plan
    elif cfg.mm_driver in ("pallas", "pallas_cross"):
        import warnings

        warnings.warn(
            f"mm_driver={cfg.mm_driver!r} but dtype {jnp.dtype(c_data.dtype)}"
            f" / block shape unsupported by the Pallas kernel; falling back"
            f" to XLA path",
            RuntimeWarning,
            stacklevel=2,
        )
    chunk = max(cfg.mm_stack_size, 1)
    # pad to a whole number of chunks (bucketed) and reshape to
    # (nchunks, chunk) so the scan shape reuses the jit cache
    if S <= chunk:
        chunk = bucket_size(S)
        nchunks = 1
    else:
        nchunks = bucket_size(-(-S // chunk), minimum=1)
    ai, bi, ci = pad_stack(a_idx, b_idx, c_idx, nchunks * chunk, plan.nseg)
    plan.driver = "xla_flat" if (
        cfg.flat_gather
        or (cfg.mm_driver == "auto" and tuned_driver == "xla_flat")
    ) else "xla"
    plan.precision = prec
    plan.xla_idx = (
        _mempool.upload_index("stk_a", ai.reshape(nchunks, chunk)),
        _mempool.upload_index("stk_b", bi.reshape(nchunks, chunk)),
        _mempool.upload_index("stk_c", ci.reshape(nchunks, chunk)),
    )
    if plan.driver == "xla_flat":
        why = "config.flat_gather" if cfg.flat_gather else "tuned"
    else:
        why = ("tuned" if tuned_driver == "xla"
               else ("config-forced" if cfg.mm_driver == "xla"
                     else "auto:default"))
    _note_driver(plan.driver, why, S, c_data, a_data, b_data, tuned)
    return plan


def _record_stack_jit(plan: StackPlan, c_data, a_data, b_data):
    """Mirror the XLA jit cache for the stack kernels (the reference's
    per-(m,n,k) NVRTC kernel cache, `libsmm_acc.cpp:89-224`): each
    launch reports the shape/dtype signature that keys the real cache,
    so `obs.metrics` exposes compile-vs-hit counters per kernel — a
    fresh (m,n,k,dtype,bucket) bin shows up as one compile.

    Returns ``(compiled, fn_name, key)`` — compiled is True on the
    first sighting of this specialization, which is when the XLA-cost
    cross-check (`obs.costmodel.capture_xla_cost`, opt-in) fires."""
    drv = plan.driver
    dt = str(jnp.dtype(c_data.dtype))
    if drv in ("xla", "xla_flat"):
        key = (c_data.shape, a_data.shape, b_data.shape, dt,
               plan.xla_idx[0].shape, plan.precision)
        fn = ("_process_stack_xla_flat" if drv == "xla_flat"
              else "_process_stack_xla")
        dev_entries = int(plan.xla_idx[0].size)
    elif drv == "xla_group":
        key = (c_data.shape, a_data.shape, b_data.shape, dt,
               plan.group_idx[0].shape, plan.precision)
        fn = "_process_stack_xla_group"
        dev_entries = int(plan.group_idx[0].size)
    elif drv == "pallas":
        from dbcsr_tpu.acc import pallas_smm

        key = (c_data.shape, a_data.shape, b_data.shape, dt, plan.r_grp,
               plan.kmerge, tuple(lc[0].shape for lc in plan.launches))
        fn = "_pallas_process"
        dev_entries = pallas_smm.launch_entries(plan.launches, plan.r_grp)
    elif drv == "pallas_cross":
        from dbcsr_tpu.acc import pallas_smm

        key = (c_data.shape, a_data.shape, b_data.shape, dt, plan.pack,
               plan.cross_vmem,
               tuple(lc["ai"].shape for lc in plan.cross_launches))
        fn = "_pallas_crosspack"
        dev_entries = pallas_smm.crosspack_launch_entries(
            plan.cross_launches)
    else:  # host driver: no device compilation to account
        return False, None, None
    # device-work entries (incl. chunk/group/bucket padding) vs the
    # true entries in core.stats.by_mnk: the pad-overhead attribution
    # the roofline needs when achieved GFLOP/s (true flops) undershoots
    # the device's busy rate
    _metrics.counter(
        "dbcsr_tpu_device_entries_total",
        "stack entries actually launched per driver, padding included",
    ).inc(dev_entries, driver=drv)
    return _metrics.record_jit(f"acc.smm.{fn}", key), f"acc.smm.{fn}", key


def _capture_stack_xla_cost(fn_name, key, jit_fn, args, c_data, a_data,
                            b_data, entries: int, prec=None) -> None:
    """Opt-in XLA cost_analysis capture for a fresh stack-kernel
    specialization, with the analytic model of the DEVICE work (padded
    entries — XLA counts the masked pad rows too) stored alongside for
    the drift check."""
    from dbcsr_tpu.obs import costmodel

    m, k = a_data.shape[1], a_data.shape[2]
    n = b_data.shape[2]
    model = {
        "flops": costmodel.stack_flops(m, n, k, entries),
        "bytes": costmodel.stack_bytes(
            m, n, k, entries, nseg=c_data.shape[0],
            itemsize=jnp.dtype(c_data.dtype).itemsize),
    }
    costmodel.capture_xla_cost(
        fn_name, key, jit_fn, args, model=model,
        kwargs=({"prec": prec} if prec is not None else None))


# safety-ordered stack-driver chain (the reference's unsupported-kernel
# fallback, `libsmm_acc.cpp:227-249`, made dynamic): a failing driver's
# stack re-executes on the next entry that is available and whose
# breaker admits it.  "host" is last — correct everywhere a native lib
# exists, never fast.
_FAILOVER_CHAIN = ("pallas_cross", "pallas", "xla_group", "xla_flat",
                   "xla", "host")


class CorruptedOutputError(RuntimeError):
    """A stack driver returned non-finite output blocks (detected by
    the opt-in post-execution output check)."""


def _forced_cfg(driver: str):
    """A config copy that steers `_prepare_stack_impl` to exactly one
    chain driver (xla_flat is the xla driver + flat_gather layout)."""
    cfg = get_config()
    if driver == "xla_flat":
        return dataclasses.replace(cfg, mm_driver="xla", flat_gather=True)
    if driver == "xla":
        return dataclasses.replace(cfg, mm_driver="xla", flat_gather=False)
    return dataclasses.replace(cfg, mm_driver=driver)


def _classify_failure(exc: BaseException) -> str:
    """Failure taxonomy feeding the breaker and the
    ``dbcsr_tpu_driver_failures_total{driver,kind}`` counter."""
    if isinstance(exc, KernelValidationError):
        return "validation"
    if isinstance(exc, _abft.AbftMismatchError):
        return "sdc"
    if isinstance(exc, CorruptedOutputError):
        return "nan"
    msg = f"{type(exc).__name__}: {exc}"
    if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
        return "oom"
    return "runtime"


# production finite-output checking is an import-time opt-in: a per-
# launch os.environ lookup would eat the trace-off budget (hot path)
_CHECK_OUTPUTS_ENV = os.environ.get("DBCSR_TPU_CHECK_OUTPUTS") == "1"


def _output_checks_enabled() -> bool:
    """Post-execution finite-output check: always on under fault
    injection (the chaos suites rely on NaN corruption being CAUGHT),
    opt-in for production via DBCSR_TPU_CHECK_OUTPUTS=1 at process
    start (costs one device reduction + sync per stack launch)."""
    return _CHECK_OUTPUTS_ENV or _faults.active()


def _output_corrupted(out) -> bool:
    if not jnp.issubdtype(out.dtype, jnp.inexact):
        return False
    return not bool(jnp.all(jnp.isfinite(
        jnp.sum(out, axis=tuple(range(1, out.ndim))))))


def _is_deleted(x) -> bool:
    f = getattr(x, "is_deleted", None)
    try:
        return bool(f()) if callable(f) else False
    except Exception:
        return False


def _chain_candidates(failed: str, c_data, a_data, b_data) -> list:
    """Every OTHER driver that can run this stack, safer ones first:
    the chain entries after ``failed``, then — so a failure of the
    safest available driver still has somewhere to go — the entries
    before it in DESCENDING safety order (for failed='host' that is
    xla, xla_flat, xla_group, …).  Breaker admission is checked per
    attempt."""
    try:
        i = _FAILOVER_CHAIN.index(failed)
        rest = (_FAILOVER_CHAIN[i + 1:]
                + tuple(reversed(_FAILOVER_CHAIN[:i])))
    except ValueError:  # unknown driver name: anything qualifies
        rest = _FAILOVER_CHAIN
    out = []
    for drv in rest:
        if drv == failed:
            continue
        if drv == "host":
            if _host_smm_available(c_data.dtype):
                out.append(drv)
        elif drv in ("pallas", "pallas_cross"):
            if _pallas_supported(_forced_cfg(drv), c_data, a_data, b_data):
                out.append(drv)
        else:
            out.append(drv)
    return out


def _record_driver_failure(driver: str, kind: str, exc, shape_key) -> None:
    _metrics.counter(
        "dbcsr_tpu_driver_failures_total",
        "stack-driver execution failures by driver and failure kind",
    ).inc(driver=driver, kind=kind)
    err = f"{type(exc).__name__}: {exc}"[:200]
    _events.publish(
        "driver_failure",
        {"driver": driver, "kind": kind,
         "shape": "x".join(str(x) for x in shape_key), "error": err},
        flight=("driver_failure",
                {"driver": driver, "kind": kind, "error": err}),
    )


def _record_fallback(from_driver: str, to_driver: str, shape_key) -> None:
    _metrics.counter(
        "dbcsr_tpu_driver_fallback_total",
        "stacks re-executed on a safer driver after a chain failover",
    ).inc(**{"from": from_driver, "to": to_driver})
    _events.publish(
        "driver_failover",
        {"from": from_driver, "to": to_driver,
         "shape": "x".join(str(x) for x in shape_key)},
        flight=("failover", {"from": from_driver, "to": to_driver}),
    )


def _run_candidate(base, a_data, b_data, fb_plan, alpha, c_zero,
                   checks_on: bool):
    """Execute one failover candidate (fault hooks apply to fallback
    drivers too, so injected cascades walk the whole chain).

    ``base`` is ALWAYS copied: the xla-family drivers donate their C
    argument, so a candidate that dispatches and then fails would
    otherwise consume the only pristine buffer and poison every later
    candidate (falsely tripping their breakers).  We are already on
    the failure path — one C copy per attempt is cheap insurance.

    Whenever the ABFT plane is armed (``verify`` or ``recover``) the
    candidate's output is itself probe-verified against ``base`` before
    being accepted — a recovery must never replace one
    silently-corrupted result with another.  Gating this on ``recover``
    alone left a gap: under ``verify`` a flip corrupting the pristine
    same-driver retry was accepted unprobed (and even counted as a
    recovery) — pinned by tests/test_integrity.py."""
    trial = jnp.array(base, copy=True)
    if _faults.active():
        _faults.maybe_inject("execute_stack", driver=fb_plan.driver)
    out = _execute_plan(trial, a_data, b_data, fb_plan, alpha, c_zero)
    if _faults.active():
        out = _faults.corrupt("execute_stack", out, driver=fb_plan.driver)
    if checks_on and _output_corrupted(out):
        raise CorruptedOutputError(
            f"driver {fb_plan.driver!r} produced non-finite output blocks")
    if _abft.enabled():
        _abft.check_stack(base, out, a_data, b_data, fb_plan, alpha)
    return out


def note_deferred_sdc(exc: BaseException) -> None:
    """Attribute a flush-detected (deferred) ABFT mismatch: feed the
    per-(driver, shape) breaker and the failure counters exactly as an
    immediate in-launch detection would have.  ``exc`` carries
    ``.driver``/``.shape_key`` attached by `abft.flush`."""
    drv = getattr(exc, "driver", None) or "?"
    key = getattr(exc, "shape_key", None) or (drv, "deferred")
    board = _breaker.get_board()
    board.record_failure(drv, key, kind="sdc")
    _record_driver_failure(drv, "sdc", exc, key)


def _failover_execute(c_data, a_data, b_data, plan: StackPlan, alpha,
                      c_zero, exc: Optional[BaseException], base=None):
    """Re-execute a failed (or quarantined) stack down the driver
    chain.  ``exc`` is None when the original driver was never
    attempted (breaker open); ``base`` is the pristine C buffer to
    restart from (defaults to ``c_data``).  On success the original
    plan is healed IN PLACE to the surviving driver (the established
    demotion pattern), so cached plans stop paying the failure."""
    board = _breaker.get_board()
    failed = plan.driver
    shape_key = _stack_shape_key(c_data, a_data, b_data)
    if base is None:
        # c_zero launches never copy their pristine C (it is identically
        # zero): synthesize it from metadata — valid even after the
        # failing launch donated c_data's buffer
        base = (jnp.zeros(c_data.shape, np.dtype(c_data.dtype))
                if c_zero else c_data)
    checks_on = _output_checks_enabled()
    if plan.src_idx is None or _is_deleted(base):
        # no rebuild payload, or the failing launch consumed (donated)
        # the only copy of C: recovery is impossible from here
        if exc is not None:
            raise exc
        return _execute_plan(base, a_data, b_data, plan, alpha, c_zero)
    ai, bi, ci = plan.src_idx
    pad_a, pad_b = plan.src_pads
    was_sdc = exc is not None and _classify_failure(exc) == "sdc"
    # recoveries are recorded once per COUNTED mismatch of this stack
    # (a retry that itself mismatches counts another), so the
    # mismatch/recovery counters stay balanced and health never
    # reports fully-recovered SDC as corruption that escaped
    sdc_count = 1 if was_sdc else 0
    if was_sdc:
        # SDC is transient corruption (the particle-strike model): the
        # bitwise-faithful recovery is one pristine SAME-DRIVER retry —
        # same plan, same accumulation order — before walking the chain
        # onto a driver with different numerics.  The breaker already
        # recorded the sdc failure above, so REPEATED corruption from
        # this (driver, shape) still trips quarantine.
        try:
            out = _run_candidate(base, a_data, b_data, plan, alpha,
                                 c_zero, checks_on)
        except Exception as exc2:  # noqa: BLE001 — classified + recorded
            kind2 = _classify_failure(exc2)
            if kind2 == "sdc":
                sdc_count += 1
            board.record_failure(failed, shape_key, kind=kind2)
            _record_driver_failure(failed, kind2, exc2, shape_key)
        else:
            board.record_success(failed, shape_key)
            _record_fallback(failed, failed, shape_key)
            for _ in range(sdc_count):
                _abft.record_recovery(failed)
            return out
    for drv in _chain_candidates(failed, c_data, a_data, b_data):
        if not board.allow(drv, shape_key):
            continue
        try:
            fb_plan = _prepare_stack_impl(
                base, a_data, b_data, ai, bi, ci,
                a_pad_row=pad_a, b_pad_row=pad_b, cfg=_forced_cfg(drv),
            )
            if fb_plan is None or fb_plan.driver != drv:
                continue  # selection refused the force (e.g. host gone)
            fb_plan.src_idx = plan.src_idx
            fb_plan.src_pads = plan.src_pads
            out = _run_candidate(base, a_data, b_data, fb_plan, alpha,
                                 c_zero, checks_on)
        except Exception as exc2:  # noqa: BLE001 — classified + recorded
            kind2 = _classify_failure(exc2)
            if kind2 == "sdc":
                sdc_count += 1
            board.record_failure(drv, shape_key, kind=kind2)
            _record_driver_failure(drv, kind2, exc2, shape_key)
            continue
        board.record_success(drv, shape_key)
        _record_fallback(failed, drv, shape_key)
        for _ in range(sdc_count):
            _abft.record_recovery(drv)
        _flight.note_driver(drv, f"failover:{failed}",
                            mnk=shape_key[:3], entries=len(ai))
        for slot in StackPlan.__slots__:  # heal the cached plan
            setattr(plan, slot, getattr(fb_plan, slot))
        return out
    # chain exhausted
    if exc is None:
        # quarantined entry but nothing safer is available: running the
        # original driver beats refusing the multiply
        return _execute_plan(base, a_data, b_data, plan, alpha, c_zero)
    if _classify_failure(exc) != "validation" and not _is_deleted(base):
        # last resort: one same-driver retry from the pristine buffer —
        # transient corruption (the injected-NaN case, a flaky launch)
        # heals here; proven-deterministic validation failures do not
        try:
            out = _run_candidate(base, a_data, b_data, plan, alpha,
                                 c_zero, checks_on)
        except Exception:
            raise exc
        board.record_success(failed, shape_key)
        _record_fallback(failed, failed, shape_key)
        for _ in range(sdc_count):
            _abft.record_recovery(failed)
        return out
    raise exc


def _promote_execute(c_data, a_data, b_data, plan: StackPlan, alpha,
                     c_zero, base, exc):
    """A demoted launch's probe residual breached its demotion ceiling
    (`abft.PrecisionExceededError`): the involved (m,n,k,dtype) cells
    were promoted when the probe raised, so rebuild this plan — now
    resolving to native precision — from the retained source indices,
    heal it IN PLACE (cached plans stop re-demoting), and re-execute
    from the pristine buffer.  NOT an SDC path: no breaker feed, no
    failover chain — the condemned result was wrong only by demoted
    rounding, and one native re-execution is the complete cure."""
    if base is None:
        base = (jnp.zeros(c_data.shape, np.dtype(c_data.dtype))
                if c_zero else c_data)
    if plan.src_idx is None or _is_deleted(base):
        raise exc
    shape_key = _stack_shape_key(c_data, a_data, b_data)
    _events.publish(
        "precision_promote_reexec",
        {"driver": plan.driver,
         "shape": "x".join(str(x) for x in shape_key)},
        flight=("precision_promote_reexec", {"driver": plan.driver}),
    )
    ai, bi, ci = plan.src_idx
    pad_a, pad_b = plan.src_pads
    new_plan = _prepare_stack_impl(base, a_data, b_data, ai, bi, ci,
                                   a_pad_row=pad_a, b_pad_row=pad_b)
    if new_plan is None:
        raise exc
    # belt-and-braces: under the FORCED precision modes (bench/test
    # legs) resolve would re-demote the rebuild and loop — the
    # re-execution must be native regardless of policy
    new_plan.precision = None
    new_plan.src_idx = plan.src_idx
    new_plan.src_pads = plan.src_pads
    for slot in StackPlan.__slots__:  # heal the cached plan
        setattr(plan, slot, getattr(new_plan, slot))
    return execute_stack(base, a_data, b_data, plan, alpha, c_zero=c_zero)


def execute_stack(c_data, a_data, b_data, plan: Optional[StackPlan], alpha=1.0,
                  c_zero: bool = False, abft_defer: bool = False):
    """Device side: run a prepared plan against (possibly new) data,
    guarded by the resilience layer — injected faults fire here, a
    raising/corrupting driver is recorded against its per-shape circuit
    breaker, and the stack re-executes down the failover chain
    (pallas → xla_group → xla_flat → xla → host) so one bad kernel
    never poisons the multiply.  With no faults configured and no
    recorded failures, the added cost is two attribute checks.

    ``c_zero``: caller guarantees ``c_data`` is identically zero (the
    engine's beta==0 rebuild, first touch per bin) — the host driver
    then synthesizes its writable buffer as np.zeros instead of
    fetching hundreds of MB of device zeros."""
    if plan is None:
        return c_data
    record_dispatch("per_span")
    board = _breaker.get_board()
    faults_on = _faults.active()
    abft_on = _abft.enabled()
    # the ABFT probe subsumes the finite-output check (NaN/Inf in out
    # poisons the probe scalars, so isfinite(err) fails) — don't pay a
    # second full read + sync of C for it unless faults or the explicit
    # env knob ask for the `nan`-classified path
    finite_on = faults_on or _output_checks_enabled()
    checks_on = finite_on or abft_on
    if not checks_on and not board._breakers:
        # production fast path: no faults configured, nothing ever
        # failed — the guard is three attribute checks + this try frame
        # (the per-shape key construction is deferred to the failure
        # path; str(dtype) per launch would eat the trace-off budget)
        try:
            return _execute_plan(c_data, a_data, b_data, plan, alpha, c_zero)
        except Exception as exc:  # noqa: BLE001 — classified + recorded
            shape_key = _stack_shape_key(c_data, a_data, b_data)
            kind = _classify_failure(exc)
            board.record_failure(plan.driver, shape_key, kind=kind)
            _record_driver_failure(plan.driver, kind, exc, shape_key)
            return _failover_execute(c_data, a_data, b_data, plan, alpha,
                                     c_zero, exc=exc, base=c_data)
    shape_key = _stack_shape_key(c_data, a_data, b_data)
    if not board.allow(plan.driver, shape_key):
        return _failover_execute(c_data, a_data, b_data, plan, alpha,
                                 c_zero, exc=None)
    # the xla drivers donate C: keep a pristine copy while the output
    # check may condemn a COMPLETED launch (chaos/opt-in mode only).
    # A first-touch (beta==0) launch skips the copy — the failure path
    # re-synthesizes zeros from metadata, and the ABFT probe drops the
    # base subtraction outright (half its C traffic)
    if not checks_on:
        base = c_data
    elif c_zero:
        base = None
    else:
        base = jnp.array(c_data, copy=True)
    try:
        if faults_on:
            _faults.maybe_inject("execute_stack", driver=plan.driver)
        out = _execute_plan(c_data, a_data, b_data, plan, alpha, c_zero)
        if faults_on:
            out = _faults.corrupt("execute_stack", out, driver=plan.driver)
        if finite_on and _output_corrupted(out):
            raise CorruptedOutputError(
                f"driver {plan.driver!r} produced non-finite output blocks")
        if abft_on:
            # rank-1 probe: C·v vs A·(B·v) per product — the finite-SDC
            # detector; a mismatch classifies `sdc` below and the stack
            # re-executes (pristine same-driver retry first, then the
            # chain)
            _abft.check_stack(base, out, a_data, b_data, plan, alpha,
                              c_zero=c_zero,
                              defer=abft_defer and c_zero,
                              shape_key=shape_key)
    except _abft.PrecisionExceededError as exc:
        # adaptive-precision promote, not corruption: re-execute at
        # native precision (the cells were promoted when this raised)
        return _promote_execute(c_data, a_data, b_data, plan, alpha,
                                c_zero, base, exc)
    except Exception as exc:  # noqa: BLE001 — classified + recorded
        kind = _classify_failure(exc)
        board.record_failure(plan.driver, shape_key, kind=kind)
        _record_driver_failure(plan.driver, kind, exc, shape_key)
        return _failover_execute(c_data, a_data, b_data, plan, alpha,
                                 c_zero, exc=exc, base=base)
    board.record_success(plan.driver, shape_key)
    return out


def _execute_plan(c_data, a_data, b_data, plan: Optional[StackPlan], alpha=1.0,
                  c_zero: bool = False):
    """Run one prepared plan (the driver dispatch proper; failover and
    fault hooks live in `execute_stack`)."""
    if plan is None:
        return c_data
    compiled, jit_fn_name, jit_key = _record_stack_jit(
        plan, c_data, a_data, b_data)
    want_xla_cost = compiled and _costmodel.xla_capture_enabled()
    if plan.driver == "host":
        from dbcsr_tpu import native

        ai, bi, ci = plan.host_idx
        if c_zero:
            c_np = np.zeros(c_data.shape, np.dtype(c_data.dtype))
        else:
            c_np = np.array(c_data)  # writable host copy (memcpy)
            _mempool.record_d2h(c_np.nbytes)
        a_np, b_np = np.asarray(a_data), np.asarray(b_data)
        _mempool.record_d2h(a_np.nbytes + b_np.nbytes)
        ok = native.host_smm(c_np, a_np, b_np, ai, bi, ci, alpha)
        if ok:
            _mempool.record_h2d(c_np.nbytes)
            return jnp.asarray(c_np)
        # native library vanished after planning (e.g. DBCSR_TPU_NATIVE
        # flipped): rebuild the plan in place without the host driver.
        # prepare_stack re-checks _host_smm_available, which now fails,
        # so the rebuild falls through to the XLA selection — no global
        # config mutation (the crosspack demotion pattern).
        import warnings

        warnings.warn(
            "native host driver unavailable at execute time; rebuilding "
            "as an XLA plan",
            RuntimeWarning,
            stacklevel=2,
        )
        new_plan = prepare_stack(
            c_data, a_data, b_data, ai, bi, ci,
            a_pad_row=plan.a_pad_row, b_pad_row=plan.b_pad_row,
        )
        if new_plan.driver == "host":  # cannot happen; guard recursion
            raise RuntimeError("host driver rebuild selected host again")
        for slot in StackPlan.__slots__:
            setattr(plan, slot, getattr(new_plan, slot))
        return execute_stack(c_data, a_data, b_data, plan, alpha)
    if plan.precision is not None:
        from dbcsr_tpu.acc import precision as precision_mod

        precision_mod.note_launch(str(jnp.dtype(c_data.dtype)),
                                  plan.precision)
    if plan.driver == "xla_group":
        if plan.append_a_pad:
            a_data = _append_pad_row(a_data)
        if plan.append_b_pad:
            b_data = _append_pad_row(b_data)
        ga, gb, gc = plan.group_idx
        alpha_dev = jnp.asarray(alpha, dtype=c_data.dtype)
        if want_xla_cost:
            _capture_stack_xla_cost(
                jit_fn_name, jit_key, _process_stack_xla_group,
                (c_data, a_data, b_data, ga, gb, gc, alpha_dev),
                c_data, a_data, b_data, int(ga.size),
                prec=plan.precision,
            )
        return _process_stack_xla_group(
            c_data, a_data, b_data, ga, gb, gc, alpha_dev,
            prec=plan.precision,
        )
    if plan.driver == "pallas_cross":
        from dbcsr_tpu.acc import pallas_smm

        cfg = get_config()
        cross_variant = "crosspack_vmem" if plan.cross_vmem else "crosspack"
        try:
            if cfg.validate_kernels and plan.val_idx is not None:
                key = (
                    a_data.shape[1], b_data.shape[2], a_data.shape[2],
                    str(jnp.dtype(c_data.dtype)), cross_variant, plan.pack,
                )
                if key not in _validated_kernels:
                    ai, bi, ci = plan.val_idx
                    _validate_pallas_kernel(
                        c_data, a_data, b_data, ai, bi, ci,
                        None if plan.append_a_pad else plan.a_pad_row,
                        None if plan.append_b_pad else plan.b_pad_row,
                        None, variant=cross_variant, pack=plan.pack,
                    )
                    _validated_kernels.add(key)
            a_pad = _append_pad_row(a_data) if plan.append_a_pad else a_data
            b_pad = _append_pad_row(b_data) if plan.append_b_pad else b_data
            a_data_t = jnp.swapaxes(a_pad, 1, 2)
            alpha_arr = jnp.asarray([[alpha]], dtype=jnp.float32)
            interpret = jax.devices()[0].platform != "tpu"
            P, R = plan.pack
            launch_fn = (pallas_smm._pallas_crosspack_vmem if plan.cross_vmem
                         else pallas_smm._pallas_crosspack)
            # numpy c_data would crash scatter_lane_outputs (.at[]) and
            # the demotion handler would then blacklist a perfectly
            # good kernel shape — coerce up front
            c_out = jnp.asarray(c_data)
            for lc in plan.cross_launches:
                with _enable_x64(False):
                    outs = launch_fn(
                        c_out, a_data_t, b_pad,
                        lc["ai"], lc["bi"], lc["cg"], lc["cl"],
                        alpha_arr, P=P, R=R, nc_out=lc["nc_out"],
                        interpret=interpret,
                    )
                c_out = pallas_smm.scatter_lane_outputs(
                    c_out, outs, lc["lane_len"], lc["scatter_idx"]
                )
            # kernel proven on this backend: drop the demotion payload
            # (host index copies kept only until the first success)
            plan.cross_src = None
            return c_out
        except KernelValidationError:
            raise  # numeric corruption: hard fail, never fall back
        except Exception as exc:
            # compile/lowering failure (e.g. a Mosaic gap on this
            # backend): demote the shape and rebuild the plan IN PLACE
            # as a base-kernel plan from the retained source indices —
            # the reference's unsupported-kernel fallback
            # (`libsmm_acc.cpp:227-249`)
            if plan.cross_src is None:
                raise
            import warnings

            shape_key = _stack_shape_key(c_data, a_data, b_data)
            msg = f"{type(exc).__name__}: {exc}"
            transient = ("RESOURCE_EXHAUSTED" in msg
                         or "out of memory" in msg.lower())
            if not transient:
                # a lowering gap is deterministic — blacklist the shape;
                # resource pressure is not — fall back this time only
                _cross_disabled.add(shape_key)
            warnings.warn(
                f"crosspack kernel failed on this backend for shape "
                f"{shape_key} ({msg}); falling back to the base kernel"
                + ("" if transient else " for this session"),
                RuntimeWarning,
                stacklevel=2,
            )
            ai, bi, ci = plan.cross_src
            # the rebuild must not re-select crosspack; for transient
            # failures the disable is scoped to this rebuild only
            _cross_disabled.add(shape_key)
            try:
                new_plan = prepare_stack(
                    c_data, a_data, b_data, ai, bi, ci,
                    a_pad_row=None if plan.append_a_pad else plan.a_pad_row,
                    b_pad_row=None if plan.append_b_pad else plan.b_pad_row,
                )
            finally:
                if transient:
                    _cross_disabled.discard(shape_key)
            for slot in StackPlan.__slots__:  # cached plans heal too
                setattr(plan, slot, getattr(new_plan, slot))
            return execute_stack(c_data, a_data, b_data, plan, alpha)
    if plan.driver == "pallas":
        from dbcsr_tpu.acc import pallas_smm

        _ensure_pallas_validated(c_data, a_data, b_data, plan)
        if plan.append_a_pad:
            a_data = _append_pad_row(a_data)
        if plan.append_b_pad:
            b_data = _append_pad_row(b_data)
        alpha_arr = jnp.asarray([[alpha]], dtype=jnp.float32)
        interpret = jax.devices()[0].platform != "tpu"
        with _enable_x64(False):
            c_data = pallas_smm.process_launches(
                c_data, a_data, b_data, plan.launches, alpha_arr,
                r_grp=plan.r_grp, kmerge=plan.kmerge, interpret=interpret,
            )
        return c_data
    alpha_dev = jnp.asarray(alpha, dtype=c_data.dtype)
    ai, bi, ci = plan.xla_idx
    fn = (_process_stack_xla_flat if plan.driver == "xla_flat"
          else _process_stack_xla)
    if want_xla_cost:
        _capture_stack_xla_cost(
            jit_fn_name, jit_key, fn,
            (c_data, a_data, b_data, ai, bi, ci, alpha_dev),
            c_data, a_data, b_data, int(ai.size),
            prec=plan.precision,
        )
    return fn(c_data, a_data, b_data, ai, bi, ci, alpha_dev,
              prec=plan.precision)


def process_stack(c_data, a_data, b_data, a_idx, b_idx, c_idx, alpha=1.0,
                  a_pad_row=None, b_pad_row=None):
    """Process a full (possibly large) stack, chunked to mm_stack_size.

    ``c_idx`` must be sorted ascending (the stack builder guarantees it);
    chunk boundaries preserve order, so accumulation into each C block
    happens in a fixed, reproducible order (ref determinism requirement:
    stack order is deterministic in `dbcsr_mm_csr.F`).

    ``a_pad_row``/``b_pad_row`` optionally name a guaranteed-zero row of
    the data arrays (the engine's bucket padding) used by the Pallas
    path to mask short groups.

    Returns the updated ``c_data`` device array.
    """
    plan = prepare_stack(c_data, a_data, b_data, a_idx, b_idx, c_idx,
                         a_pad_row=a_pad_row, b_pad_row=b_pad_row)
    return execute_stack(c_data, a_data, b_data, plan, alpha)


# ------------------------------------------------------------------ fused
# superstack execution: every span (one per (abin, bbin) pair) whose
# stack targets the SAME C bin is lowered into a single jitted program
# with a donated C argument.  The per-span path pays, for each of a
# bin's N spans, one Python→XLA dispatch round-trip plus a full
# read-modify-write of the bin's device buffer; the fused launch pays
# both exactly once per bin — the TPU-side realization of the
# reference's stack batching (amortize launch overhead across thousands
# of block products, `dbcsr_mm_accdrv.F:279-326`).

_DISPATCHES_NAME = "dbcsr_tpu_dispatches_total"
_DISPATCHES_HELP = (
    "engine dispatch round-trips by mode: one per executed span in "
    "per_span mode, one per fused C-bin (or mesh) launch in fused "
    "mode, one per tick/shift region under the pipelined distributed "
    "drivers (cannon_db ring metronome, gather_pipe chunked "
    "all-gather)")
_FUSED_SPANS_NAME = "dbcsr_tpu_fused_spans"
_FUSED_SPANS_HELP = (
    "spans (or mesh tick-chunks) carried by each single fused launch")
_FUSED_SPANS_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

# the breaker/metrics pseudo-driver name of a fused C-bin launch: its
# failures never condemn the per-span drivers (the failing span is
# unknown from outside the program), they route the bin back to the
# per-span path where the real chain takes over
FUSED_DRIVER = "fused"


def record_dispatch(mode: str, fused_spans: Optional[int] = None) -> None:
    """Count one engine dispatch round-trip, and — for fused launches —
    how many spans it carried (the amortization histogram)."""
    _metrics.counter(_DISPATCHES_NAME, _DISPATCHES_HELP).inc(mode=mode)
    if fused_spans is not None:
        _metrics.histogram(
            _FUSED_SPANS_NAME, _FUSED_SPANS_HELP,
            buckets=_FUSED_SPANS_BUCKETS,
        ).observe(fused_spans)


_XLA_FAMILY = ("xla", "xla_flat", "xla_group")


class SuperstackPlan:
    """A prepared fused C-bin launch: the per-span `StackPlan`s (whose
    device index arrays are reused as-is) plus the cached jitted
    program that chains their kernels.  Built by `prepare_superstack`,
    run by `execute_superstack`; the engine caches it next to the
    per-span plans in `mm.multiply._plan_cache`."""

    __slots__ = ("family", "sig", "plans", "fn")

    def __init__(self, family, sig, plans, fn):
        self.family = family      # "xla" | "pallas" | "host"
        self.sig = sig
        self.plans = plans
        self.fn = fn
        # staleness note: a failover heals per-span plans IN PLACE
        # (driver changes), which invalidates this fused program — the
        # guard lives in `mm.multiply._CachedSpans.superstack_for`,
        # which keys the cached decision by the spans' driver tuple

    def nbytes(self) -> int:
        """Device bytes pinned beyond the per-span plans: none — the
        fused program reuses their index arrays."""
        return 0


def prepare_superstack(plans) -> Optional[SuperstackPlan]:
    """Lower the spans of one C bin (accumulation order preserved) into
    a fused plan, or return None when they cannot fuse.

    Fusable families — all spans must belong to ONE of:
    * the pure-XLA drivers (``xla``/``xla_flat``/``xla_group``, freely
      mixed): chained scan bodies inside one donated-C jit;
    * ``pallas``: the base kernel's launch loop traced inside one jit
      (`pallas_smm.process_launches`); first-use validation runs before
      the first fused dispatch, outside the program;
    * ``host``: the native C++ driver with ONE C fetch + writeback for
      the whole bin instead of one per span.

    ``pallas_cross`` spans keep the per-span path (their compile-
    failure demotion and lane scatters are execute-time host logic), as
    do mixed-family bins."""
    if not plans or any(p is None for p in plans):
        return None
    drivers = [p.driver for p in plans]
    if all(d in _XLA_FAMILY for d in drivers):
        family = "xla"
    elif all(d == "pallas" for d in drivers):
        family = "pallas"
    elif all(d == "host" for d in drivers):
        family = "host"
    else:
        return None
    if family == "host":
        return SuperstackPlan("host", None, list(plans), None)
    interpret = (jax.devices()[0].platform != "tpu"
                 if family == "pallas" else False)
    sig = tuple(
        (
            p.driver,
            3 if p.driver in _XLA_FAMILY else 3 * len(p.launches),
            bool(p.append_a_pad), bool(p.append_b_pad),
            p.r_grp, bool(p.kmerge), p.precision,
        )
        for p in plans
    )
    sig = (family, interpret, sig)
    return SuperstackPlan(family, sig, list(plans), _fused_fn(sig))


from collections import OrderedDict as _OrderedDict  # noqa: E402

# fused callables keyed by STRUCTURE (drivers, launch counts, static
# kernel params) — jax.jit handles shape/dtype specialization under
# each; LRU-bounded so pattern churn cannot pin compiled programs
_fused_fns: "_OrderedDict[tuple, object]" = _OrderedDict()
_FUSED_FN_MAX = 128


def _fused_fn(sig):
    fn = _fused_fns.get(sig)
    if fn is not None:
        _fused_fns.move_to_end(sig)
        return fn
    family, interpret, spans_sig = sig

    def fused(c_data, alpha_dev, *flat):
        from dbcsr_tpu.acc import pallas_smm

        pos = 0
        for driver, n_idx, ap_a, ap_b, r_grp, kmerge, prec in spans_sig:
            a_data = flat[pos]
            b_data = flat[pos + 1]
            idx = flat[pos + 2: pos + 2 + n_idx]
            pos += 2 + n_idx
            if ap_a:
                a_data = _append_pad_row(a_data)
            if ap_b:
                b_data = _append_pad_row(b_data)
            if driver == "xla_group":
                c_data = _stack_xla_group_body(
                    c_data, a_data, b_data, *idx, alpha_dev, prec=prec)
            elif driver == "pallas":
                launches = [tuple(idx[3 * j: 3 * j + 3])
                            for j in range(n_idx // 3)]
                c_data = pallas_smm.process_launches(
                    c_data, a_data, b_data, launches, alpha_dev,
                    r_grp=r_grp, kmerge=kmerge, interpret=interpret,
                )
            else:
                body = (_stack_xla_flat_body if driver == "xla_flat"
                        else _stack_xla_body)
                c_data = body(c_data, a_data, b_data, *idx, alpha_dev,
                              prec=prec)
        return c_data

    fn = jax.jit(fused, donate_argnums=0)
    _fused_fns[sig] = fn
    while len(_fused_fns) > _FUSED_FN_MAX:
        _fused_fns.popitem(last=False)
    return fn


def _superstack_key(c_data, nspans: int) -> tuple:
    """Breaker/metrics shape key of a fused C-bin launch: the bin's
    block shape + span count + dtype (per-span (m,n,k) keys stay with
    the per-span drivers)."""
    return (c_data.shape[1], c_data.shape[2], nspans,
            str(jnp.dtype(c_data.dtype)))


def _decompose_superstack(c_data, a_datas, b_datas, plans, alpha, c_zero,
                          why: str = ""):
    """Run a fused bin's spans through the per-span engine instead —
    the fused path's failover contract: a fused launch never hard-fails
    the multiply while per-span execution (with its full driver chain)
    can still make progress.  ``c_zero`` holds for the FIRST span only
    (later spans accumulate onto its contribution)."""
    _events.publish(
        "superstack_decompose", {"why": why[:200], "spans": len(plans)},
        flight=("superstack_decompose",
                {"why": why[:200], "spans": len(plans)}),
    )
    out = c_data
    first = True
    for plan, a_d, b_d in zip(plans, a_datas, b_datas):
        out = execute_stack(out, a_d, b_d, plan, alpha,
                            c_zero=c_zero and first)
        first = False
    return out


def _record_superstack_jit(splan: SuperstackPlan, c_data, a_datas,
                           b_datas):
    """Jit-cache mirror + per-driver device-entry accounting of one
    fused launch (the fused analog of `_record_stack_jit`).  Returns
    ``(compiled, key)`` so the XLA-cost capture can fire on fresh
    specializations, like the per-span path's."""
    from dbcsr_tpu.acc import pallas_smm

    dt = str(jnp.dtype(c_data.dtype))
    idx_shapes = []
    for plan in splan.plans:
        if plan.driver in ("xla", "xla_flat"):
            idx_shapes.append(plan.xla_idx[0].shape)
            dev_entries = int(plan.xla_idx[0].size)
        elif plan.driver == "xla_group":
            idx_shapes.append(plan.group_idx[0].shape)
            dev_entries = int(plan.group_idx[0].size)
        else:  # pallas
            idx_shapes.append(tuple(lc[0].shape for lc in plan.launches))
            dev_entries = pallas_smm.launch_entries(plan.launches,
                                                    plan.r_grp)
        _metrics.counter(
            "dbcsr_tpu_device_entries_total",
            "stack entries actually launched per driver, padding included",
        ).inc(dev_entries, driver=plan.driver)
    key = (splan.sig, c_data.shape, dt,
           tuple(a.shape for a in a_datas),
           tuple(b.shape for b in b_datas), tuple(idx_shapes))
    return _metrics.record_jit("acc.smm._fused_superstack", key), key


def _superstack_model(splan: SuperstackPlan, c_data, a_datas,
                      b_datas) -> dict:
    """Analytic flops/bytes of one fused launch: per-span DEVICE
    entries (XLA counts the masked pad work too), bin C traffic charged
    once (`costmodel.superstack_bytes` — the convention the engine's
    per-span recording mirrors)."""
    from dbcsr_tpu.acc import pallas_smm

    spans = []
    for plan, a_d, b_d in zip(splan.plans, a_datas, b_datas):
        m, k = a_d.shape[1], a_d.shape[2]
        n = b_d.shape[2]
        if plan.driver in ("xla", "xla_flat"):
            entries = int(plan.xla_idx[0].size)
        elif plan.driver == "xla_group":
            entries = int(plan.group_idx[0].size)
        else:
            entries = pallas_smm.launch_entries(plan.launches, plan.r_grp)
        spans.append((m, n, k, entries))
    return {
        "flops": sum(_costmodel.stack_flops(m, n, k, e)
                     for m, n, k, e in spans),
        "bytes": _costmodel.superstack_bytes(
            spans, nseg=c_data.shape[0],
            itemsize=jnp.dtype(c_data.dtype).itemsize),
    }


def _dispatch_superstack(c_data, a_datas, b_datas, splan: SuperstackPlan,
                         alpha, c_zero: bool):
    """Issue one fused launch (no failover here — `execute_superstack`
    owns the guard rails)."""
    plans = splan.plans
    if splan.family == "host":
        from dbcsr_tpu import native

        if c_zero:
            c_np = np.zeros(c_data.shape, np.dtype(c_data.dtype))
        else:
            c_np = np.array(c_data)  # ONE writable host copy per bin
            _mempool.record_d2h(c_np.nbytes)
        for plan, a_d, b_d in zip(plans, a_datas, b_datas):
            ai, bi, ci = plan.host_idx
            a_np, b_np = np.asarray(a_d), np.asarray(b_d)
            _mempool.record_d2h(a_np.nbytes + b_np.nbytes)
            ok = native.host_smm(c_np, a_np, b_np, ai, bi, ci, alpha)
            if not ok:
                raise RuntimeError(
                    "native host driver unavailable during a fused "
                    "superstack launch")
        _mempool.record_h2d(c_np.nbytes)
        return jnp.asarray(c_np)
    compiled, jit_key = _record_superstack_jit(splan, c_data, a_datas,
                                               b_datas)
    if any(p.precision is not None for p in plans):
        from dbcsr_tpu.acc import precision as precision_mod

        dt = str(jnp.dtype(c_data.dtype))
        for plan in plans:
            if plan.precision is not None:
                precision_mod.note_launch(dt, plan.precision)
    flat = []
    for plan, a_d, b_d in zip(plans, a_datas, b_datas):
        flat.append(a_d)
        flat.append(b_d)
        if plan.driver in ("xla", "xla_flat"):
            flat.extend(plan.xla_idx)
        elif plan.driver == "xla_group":
            flat.extend(plan.group_idx)
        else:
            for lc in plan.launches:
                flat.extend(lc)
    if splan.family == "pallas":
        alpha_dev = jnp.asarray([[alpha]], dtype=jnp.float32)
        with _enable_x64(False):
            return splan.fn(jnp.asarray(c_data), alpha_dev, *flat)
    alpha_dev = jnp.asarray(alpha, dtype=c_data.dtype)
    if compiled and _costmodel.xla_capture_enabled():
        # the fused program IS the compiled unit now: the opt-in
        # model-vs-XLA drift check captures it whole, with the
        # per-span analytic model summed (C round-trip charged once)
        _costmodel.capture_xla_cost(
            "acc.smm._fused_superstack", jit_key, splan.fn,
            (c_data, alpha_dev, *flat),
            model=_superstack_model(splan, c_data, a_datas, b_datas),
        )
    return splan.fn(c_data, alpha_dev, *flat)


def execute_superstack(c_data, a_datas, b_datas, splan: SuperstackPlan,
                       alpha=1.0, c_zero: bool = False,
                       abft_defer: bool = False):
    """Run all spans of one C bin as a single fused dispatch, guarded
    by the resilience layer: injected ``execute_superstack`` faults
    fire here, a failing fused launch is recorded against the bin's
    ``fused`` breaker and DECOMPOSES to per-span execution (where each
    span's own driver chain applies) rather than hard-failing, and an
    open fused breaker routes the bin per-span pre-emptively.

    Returns ``(new_c_buffer, fused)`` — ``fused`` is False when the
    bin actually ran per-span (breaker routing or failure decompose),
    so the caller's cost accounting can charge the per-span C
    round-trips that really happened instead of the fused convention.
    On a fused launch the program donates the old buffer, so the N−1
    intermediate copies of the per-span path never materialize."""
    plans = splan.plans
    board = _breaker.get_board()
    faults_on = _faults.active()
    abft_on = _abft.enabled()
    finite_on = faults_on or _output_checks_enabled()
    checks_on = finite_on or abft_on
    bin_key = _superstack_key(c_data, len(plans))
    if board._breakers:
        # a fused program cannot route around a quarantined member
        # kernel mid-launch, so any span whose own (driver, shape)
        # breaker is not fully closed sends the bin per-span — where
        # execute_stack's allow() gate runs the proper trial/failover.
        # state() is a read-only probe: it must not consume the
        # half-open trial admission the per-span path will claim; and
        # it must run BEFORE allow(FUSED) below, whose half-open trial
        # admission would otherwise be consumed and never resolved
        # (record_success/failure both skipped on this path), wedging
        # the fused breaker in half-open for good.
        for plan, a_d, b_d in zip(plans, a_datas, b_datas):
            if board.state(plan.driver,
                           _stack_shape_key(c_data, a_d, b_d)) \
                    != _breaker.CLOSED:
                return _decompose_superstack(
                    c_data, a_datas, b_datas, plans, alpha, c_zero,
                    why=f"span-breaker:{plan.driver}"), False
        if not board.allow(FUSED_DRIVER, bin_key):
            return _decompose_superstack(
                c_data, a_datas, b_datas, plans, alpha, c_zero,
                why="breaker-open"), False
    # first-use pallas validation happens OUTSIDE the fused program;
    # a validation failure walks the same decompose path below, where
    # execute_stack applies the hard-open breaker + chain contract.
    # The pristine copy is taken INSIDE the try: allow() above may have
    # consumed the fused half-open trial admission, and a copy failure
    # (device OOM on a big bin) must resolve that trial via
    # record_failure below — never leave the breaker wedged half-open.
    # c_data itself is still pristine then (nothing dispatched), so
    # the decompose path recovers from it.
    base = c_data
    try:
        if checks_on and splan.family != "host" and not c_zero:
            # the host family works on its own numpy copy and never
            # mutates c_data, so the original is always recoverable
            # there — don't pay a full-bin device copy for it; nor for
            # a first-touch (beta==0) bin, whose pristine C is zeros
            # the failure path re-synthesizes from metadata
            base = jnp.array(c_data, copy=True)
        if splan.family == "pallas":
            for plan, a_d, b_d in zip(plans, a_datas, b_datas):
                _ensure_pallas_validated(c_data, a_d, b_d, plan)
        # counted before the launch so a dispatch-then-fail round-trip
        # (injected faults model exactly that) still shows in the
        # per-mode comparison; the decompose's per_span dispatches are
        # counted on top — both round-trips happened
        record_dispatch("fused", fused_spans=len(plans))
        if faults_on:
            _faults.maybe_inject("execute_superstack")
        out = _dispatch_superstack(c_data, a_datas, b_datas, splan, alpha,
                                   c_zero)
        if faults_on:
            out = _faults.corrupt("execute_superstack", out)
        if finite_on and _output_corrupted(out):
            raise CorruptedOutputError(
                "fused superstack launch produced non-finite output blocks")
        if abft_on:
            # one probe covers the whole fused bin (the right side sums
            # every span); a mismatch decomposes to per-span execution,
            # where each span's own ABFT + chain recovery applies
            _abft.check_superstack(base, out, a_datas, b_datas, splan,
                                   alpha, c_zero=c_zero,
                                   defer=abft_defer and c_zero,
                                   shape_key=bin_key)
    except _abft.PrecisionExceededError:
        # adaptive-precision promote (cells already promoted): rerun
        # the bin per-span from the pristine buffer, where each span's
        # own probe + promote/re-execute handler applies — no breaker
        # feed, no SDC attribution
        if c_zero and _is_deleted(base):
            base = jnp.zeros(c_data.shape, np.dtype(c_data.dtype))
        if _is_deleted(base):
            raise
        out = _decompose_superstack(
            base, a_datas, b_datas, plans, alpha, c_zero,
            why="precision-promote")
        return out, False
    except Exception as exc:  # noqa: BLE001 — classified + recorded
        kind = _classify_failure(exc)
        board.record_failure(FUSED_DRIVER, bin_key, kind=kind)
        _record_driver_failure(FUSED_DRIVER, kind, exc, bin_key)
        if c_zero and _is_deleted(base):
            # the copy was skipped (pristine C is zeros): rebuild it
            base = jnp.zeros(c_data.shape, np.dtype(c_data.dtype))
        if _is_deleted(base):
            # the failing launch consumed (donated) the only copy of
            # the bin's C buffer: per-span recovery is impossible here
            raise
        _record_fallback(FUSED_DRIVER, "per_span", bin_key)
        out = _decompose_superstack(
            base, a_datas, b_datas, plans, alpha, c_zero,
            why=f"{type(exc).__name__}: {exc}")
        if kind == "sdc":
            _abft.record_recovery(FUSED_DRIVER)
        return out, False
    board.record_success(FUSED_DRIVER, bin_key)
    return out, True


def _on_tpu() -> bool:
    """Dispatch-decision platform gate — honors the CPU suite's
    platform_override seam; execution-level interpret= flags read the
    real platform directly (see config.effective_platform)."""
    from dbcsr_tpu.core.config import effective_platform

    return effective_platform() == "tpu"


def _host_smm_available(dtype) -> bool:
    """True when the native C++ stack driver can run this stack: CPU
    backend (no device round-trip), a dtype the C++ kernel's switch
    handles (the reference enum codes r4/r8/c4/c8 — not bf16), and the
    native library built.

    Gates on the REAL backend platform as well as `effective_platform`
    (ADVICE r5): the host driver changes where compute RUNS, not just
    policy, so `platform_override='cpu'` on a real TPU must never route
    stacks through a per-stack device->host->device tunnel round trip —
    the behavior `prepare_stack`'s own comment calls catastrophic.
    config.py's contract is that execution-level choices always follow
    the real platform; the seam only steers decisions."""
    from dbcsr_tpu.core.config import effective_platform

    if effective_platform() != "cpu":
        return False
    if jax.devices()[0].platform != "cpu":
        return False
    from dbcsr_tpu.core import kinds

    try:
        code = kinds.enum_of(dtype)
    except KeyError:
        return False
    if code not in (1, 3, 5, 7):
        return False
    from dbcsr_tpu import native

    return native.get_lib() is not None


def plan_exec_dtype(plan, request_dtype_name: str) -> str:
    """The dtype a plan's compute actually EXECUTES at: the demoted
    compute dtype for a precision-demoted plan, else the request dtype.
    Feeds `core.stats.record_stack` so the roofline rollup reports
    %-of-peak against the executed compute dtype (a demoted launch must
    not be scored against the request dtype's peak)."""
    prec = getattr(plan, "precision", None) if plan is not None else None
    return prec[0] if prec is not None else request_dtype_name


def _stack_shape_key(c_data, a_data, b_data) -> tuple:
    """(m, n, k, dtype) of a stack — the single key construction shared
    by crosspack dispatch and the demotion handler (they MUST match, or
    a demoted shape could re-select the failing kernel and recurse)."""
    return (
        a_data.shape[1], b_data.shape[2], a_data.shape[2],
        str(jnp.dtype(c_data.dtype)),
    )


# shapes whose crosspack kernel failed to COMPILE/run on this backend
# (not a numeric mismatch): dispatch demotes them to the base kernel
# for the session — the role of the reference's unsupported-kernel
# fallback (`libsmm_acc.cpp:227-249` falls back when no JIT kernel
# exists for an (m, n, k))
_cross_disabled: set = set()


def _pallas_supported(cfg, c_data, a_data, b_data) -> bool:
    if cfg.mm_driver == "xla":
        return False
    if not cfg.use_pallas and cfg.mm_driver not in ("pallas", "pallas_cross"):
        return False
    # off-TPU, pallas_call runs in INTERPRET mode — a per-step Python
    # evaluator meant for kernel testing, ~1000x slower at driver scale
    # (measured: 2000^2 23^3 bf16 north-star slice, 22 s/rep vs 0.09 s
    # for the f64 xla path on the same config).  Auto dispatch must
    # never select it; only an explicit mm_driver force (tests, kernel
    # debugging) may.
    if not _on_tpu() and cfg.mm_driver not in ("pallas", "pallas_cross"):
        return False
    try:
        from dbcsr_tpu.acc.pallas_smm import supports

        return supports(c_data, a_data, b_data)
    except Exception:
        return False


@jax.jit
def transpose_blocks(data):
    """Batched in-register block transpose: (N, m, n) -> (N, n, m).

    Ref `libsmm_acc_transpose` (`acc_libsmm.h`, kernel
    `smm_acc_transpose.h`) — used to put A panels in the (m, k)
    layout the multiply kernel wants.
    """
    return jnp.swapaxes(data, 1, 2)


@jax.jit
def _block_norms(data):
    sq = jnp.real(data * jnp.conj(data)) if jnp.iscomplexobj(data) else data * data
    return jnp.sqrt(jnp.sum(sq, axis=(1, 2), dtype=_accum_dtype(sq.dtype)))


def block_norms(data):
    """Per-block Frobenius norms, (N, m, n) -> (N,) real.

    Ref `c_calculate_norms` (`src/acc/cuda_hip/calculate_norms.cpp`),
    used for on-the-fly norm-product filtering in the stack builder.
    """
    out = np.asarray(_block_norms(data), dtype=real_dtype_of(data.dtype))
    _mempool.record_d2h(out.nbytes)
    return out
