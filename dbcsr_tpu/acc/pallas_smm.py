"""Fused Pallas TPU kernel for parameter-stack processing.

TPU-native replacement for the reference's five CUDA kernel families
(`src/acc/libsmm_acc/kernels/smm_acc_dnt_{tiny,small,medium,largeDB1,
largeDB2}.h`): a single blocked kernel whose tuning knob is the
*grouping* R — how many stack entries one grid step processes (the
CUDA kernels' `grouping` template parameter plays the same role).

Design (vs the CUDA design, by intent):

* The stack arrives **sorted by C block** (the engine guarantees it),
  so each C block is one contiguous run of entries.  Runs are chopped
  into grid steps of R entries; a step's contributions are summed into
  a float32 VMEM accumulator that persists across the run, and the C
  block is written back once when the run ends — no atomics
  (`atomicAdd` in `smm_acc_common.h`) and bit-reproducible order.
* A/B blocks are *gathered by the Pallas pipeline itself*: the int32
  stack arrays are scalar-prefetch operands and the BlockSpec
  `index_map`s read them to pick which (1, m, k) block to DMA next —
  the Mosaic pipeline double-buffers these fetches exactly like the
  CUDA kernels' double-buffered shared-memory loads (largeDB1/2).
* Short runs are padded to a multiple of R with entries pointing at a
  guaranteed-zero block row (the engine's bucket padding), which
  contribute exact zeros — the analog of the reference's masked
  tail entries.

Only real float32/bfloat16 stacks take this path (`supports`); f64 and
complex fall back to the XLA gather/segment-sum path in
`dbcsr_tpu.acc.smm` (TPU has no native f64 MXU path to win with).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SUPPORTED = (np.dtype(np.float32), np.dtype(jnp.bfloat16))
# blocks bigger than this blow the VMEM budget for 2*R in-flight panels
_MAX_DIM = 256


def supports(c_data, a_data, b_data) -> bool:
    if jnp.dtype(c_data.dtype) not in _SUPPORTED:
        return False
    if jnp.dtype(a_data.dtype) != jnp.dtype(c_data.dtype):
        return False
    if jnp.dtype(b_data.dtype) != jnp.dtype(c_data.dtype):
        return False
    dims = a_data.shape[1:] + b_data.shape[1:] + c_data.shape[1:]
    return max(dims) <= _MAX_DIM


def _choose_grouping(run_lengths: np.ndarray) -> int:
    """Pick R (entries per grid step) from the run-length distribution —
    the one-knob analog of the CUDA `grouping` parameter."""
    avg = float(run_lengths.mean()) if len(run_lengths) else 1.0
    for r in (8, 4, 2):
        if avg >= r * 0.75:
            return r
    return 1


def build_grouped_stack(c_idx: np.ndarray, a_idx: np.ndarray, b_idx: np.ndarray,
                        a_pad_row: int, b_pad_row: int, grouping: int | None = None):
    """Chop the (sorted-by-c) stack into grid steps of R entries.

    Returns int32 arrays ai2 (S, R), bi2 (S, R), ci2 (S,) where padded
    slots point at (a_pad_row, b_pad_row) — a zero block row each.
    """
    s_total = len(c_idx)
    run_first = np.flatnonzero(np.diff(c_idx)) + 1
    run_starts = np.concatenate([[0], run_first])
    run_lens = np.diff(np.concatenate([run_starts, [s_total]]))
    r_grp = grouping or _choose_grouping(run_lens)
    steps_per_run = -(-run_lens // r_grp)
    nsteps = int(steps_per_run.sum())
    # flat destination slot of each stack entry: step base of its run
    # (in units of R) plus its position within the run
    run_of = np.repeat(np.arange(len(run_lens)), run_lens)
    pos_in_run = np.arange(s_total) - run_starts[run_of]
    step_base = np.concatenate([[0], np.cumsum(steps_per_run)])[:-1]
    dst = step_base[run_of] * r_grp + pos_in_run
    ai2 = np.full(nsteps * r_grp, a_pad_row, np.int32)
    bi2 = np.full(nsteps * r_grp, b_pad_row, np.int32)
    ai2[dst] = a_idx
    bi2[dst] = b_idx
    ci2 = np.empty(nsteps, np.int32)
    ci2[step_base[run_of] + pos_in_run // r_grp] = c_idx
    return ai2.reshape(nsteps, r_grp), bi2.reshape(nsteps, r_grp), ci2, r_grp


def _a_map(s, ai, bi, ci, *, r):
    return (ai[s, r], 0, 0)


def _b_map(s, ai, bi, ci, *, r):
    return (bi[s, r], 0, 0)


def _c_map(s, ai, bi, ci):
    return (ci[s], 0, 0)


def _smm_kernel(ai_ref, bi_ref, ci_ref, *refs, r_grp):
    a_refs = refs[:r_grp]
    b_refs = refs[r_grp : 2 * r_grp]
    alpha_ref = refs[2 * r_grp]
    c_ref = refs[2 * r_grp + 1]
    o_ref = refs[2 * r_grp + 2]
    acc_ref = refs[2 * r_grp + 3]
    s = pl.program_id(0)
    cur = ci_ref[s]
    prev = ci_ref[jnp.maximum(s - 1, 0)]
    first = jnp.logical_or(s == 0, cur != prev)
    contrib = jnp.zeros(acc_ref.shape, jnp.float32)
    for r in range(r_grp):
        contrib = contrib + jax.lax.dot_general(
            a_refs[r][0],
            b_refs[r][0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    contrib = alpha_ref[0, 0] * contrib

    @pl.when(first)
    def _():
        acc_ref[...] = c_ref[0].astype(jnp.float32) + contrib

    @pl.when(jnp.logical_not(first))
    def _():
        acc_ref[...] = acc_ref[...] + contrib

    o_ref[0] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("r_grp", "interpret"),
    donate_argnums=(0,),
)
def _pallas_process(c_data, a_data, b_data, ai2, bi2, ci2, alpha, *, r_grp, interpret):
    nsteps = ci2.shape[0]
    m, k = a_data.shape[1:]
    n = b_data.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nsteps,),
        in_specs=[
            *[
                pl.BlockSpec((1, m, k), functools.partial(_a_map, r=r))
                for r in range(r_grp)
            ],
            *[
                pl.BlockSpec((1, k, n), functools.partial(_b_map, r=r))
                for r in range(r_grp)
            ],
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, m, n), _c_map),
        ],
        out_specs=pl.BlockSpec((1, m, n), _c_map),
        scratch_shapes=[pltpu.VMEM((m, n), jnp.float32)],
    )
    kernel = functools.partial(_smm_kernel, r_grp=r_grp)
    # operand positions (incl. the 3 scalar-prefetch args):
    # 0..2 = ai2/bi2/ci2, 3..3+2R-1 = A/B, 3+2R = alpha, 3+2R+1 = c_data
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(c_data.shape, c_data.dtype),
        input_output_aliases={3 + 2 * r_grp + 1: 0},
        interpret=interpret,
    )(
        ai2, bi2, ci2,
        *([a_data] * r_grp),
        *([b_data] * r_grp),
        alpha,
        c_data,
    )


def process_stack_pallas(
    c_data,
    a_data,
    b_data,
    a_idx: np.ndarray,
    b_idx: np.ndarray,
    c_idx: np.ndarray,
    alpha,
    a_pad_row: int | None = None,
    b_pad_row: int | None = None,
    grouping: int | None = None,
):
    """Process a flat stack (host int arrays, sorted by ``c_idx``).

    ``a_pad_row``/``b_pad_row`` must index a zero row of the data
    arrays; when None, a zero row is appended on the fly.  ``grouping``
    forces R (otherwise chosen from the run-length heuristic; the
    caller passes the tuned value from `dbcsr_tpu.acc.params` when one
    exists).
    """
    if len(a_idx) == 0:
        return c_data
    if a_pad_row is None:
        a_data = jnp.concatenate([a_data, jnp.zeros((1,) + a_data.shape[1:], a_data.dtype)])
        a_pad_row = a_data.shape[0] - 1
    if b_pad_row is None:
        b_data = jnp.concatenate([b_data, jnp.zeros((1,) + b_data.shape[1:], b_data.dtype)])
        b_pad_row = b_data.shape[0] - 1
    ai2, bi2, ci2, r_grp = build_grouped_stack(
        np.asarray(c_idx), np.asarray(a_idx), np.asarray(b_idx),
        a_pad_row, b_pad_row, grouping=grouping,
    )
    from dbcsr_tpu.utils.rounding import bucket_size

    # bucket the step count so jit shapes recur; padding steps repeat the
    # final C block with all-zero-block entries (exact no-ops)
    cap = bucket_size(ai2.shape[0])
    if cap > ai2.shape[0]:
        pad = cap - ai2.shape[0]
        ai2 = np.concatenate([ai2, np.full((pad, r_grp), a_pad_row, np.int32)])
        bi2 = np.concatenate([bi2, np.full((pad, r_grp), b_pad_row, np.int32)])
        ci2 = np.concatenate([ci2, np.full(pad, ci2[-1], np.int32)])
    alpha_arr = jnp.asarray([[alpha]], dtype=jnp.float32)
    interpret = jax.devices()[0].platform != "tpu"
    return _pallas_process(
        c_data, a_data, b_data,
        jnp.asarray(ai2), jnp.asarray(bi2), jnp.asarray(ci2),
        alpha_arr, r_grp=r_grp, interpret=interpret,
    )
