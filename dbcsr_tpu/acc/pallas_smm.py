"""Fused Pallas TPU kernel for parameter-stack processing.

TPU-native replacement for the reference's five CUDA kernel families
(`src/acc/libsmm_acc/kernels/smm_acc_dnt_{tiny,small,medium,largeDB1,
largeDB2}.h`): a single blocked kernel whose tuning knob is the
*grouping* R — how many stack entries one grid step processes (the
CUDA kernels' `grouping` template parameter plays the same role).

Design (vs the CUDA design, by intent):

* The stack arrives **sorted by C block** (the engine guarantees it),
  so each C block is one contiguous run of entries.  Runs are chopped
  into grid steps of R entries; a step's contributions are summed into
  a float32 VMEM accumulator that persists across the run, and the C
  block is written back once when the run ends — no atomics
  (`atomicAdd` in `smm_acc_common.h`) and bit-reproducible order.
* A/B blocks are *gathered by the Pallas pipeline itself*: the int32
  stack arrays are scalar-prefetch operands and the BlockSpec
  `index_map`s read them to pick which (1, m, k) block to DMA next —
  the Mosaic pipeline double-buffers these fetches exactly like the
  CUDA kernels' double-buffered shared-memory loads (largeDB1/2).
* Short runs are padded to a multiple of R with entries pointing at a
  guaranteed-zero block row (the engine's bucket padding), which
  contribute exact zeros — the analog of the reference's masked
  tail entries.

Only real float32/bfloat16 stacks take this path (`supports`); f64 and
complex fall back to the XLA gather/segment-sum path in
`dbcsr_tpu.acc.smm` (TPU has no native f64 MXU path to win with).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SUPPORTED = (np.dtype(np.float32), np.dtype(jnp.bfloat16))


def supports(c_data, a_data, b_data) -> bool:
    if jnp.dtype(c_data.dtype) not in _SUPPORTED:
        return False
    if jnp.dtype(a_data.dtype) != jnp.dtype(c_data.dtype):
        return False
    if jnp.dtype(b_data.dtype) != jnp.dtype(c_data.dtype):
        return False
    from dbcsr_tpu.core.config import get_config

    # blocks bigger than max_kernel_dim blow the VMEM budget for 2*R
    # in-flight panels and take the XLA dot path instead (the role of
    # the reference's max_kernel_dim=80 cuBLAS fallback,
    # `libsmm_acc.cpp:227-249`)
    dims = a_data.shape[1:] + b_data.shape[1:] + c_data.shape[1:]
    return max(dims) <= get_config().max_kernel_dim


def _choose_grouping(run_lengths: np.ndarray) -> int:
    """Pick R (entries per grid step) from the run-length distribution —
    the one-knob analog of the CUDA `grouping` parameter."""
    avg = float(run_lengths.mean()) if len(run_lengths) else 1.0
    for r in (8, 4, 2):
        if avg >= r * 0.75:
            return r
    return 1


def build_grouped_stack(c_idx: np.ndarray, a_idx: np.ndarray, b_idx: np.ndarray,
                        a_pad_row: int, b_pad_row: int, grouping: int | None = None):
    """Chop the (sorted-by-c) stack into grid steps of R entries.

    Returns int32 arrays ai2 (S, R), bi2 (S, R), ci2 (S,) where padded
    slots point at (a_pad_row, b_pad_row) — a zero block row each.
    """
    s_total = len(c_idx)
    run_first = np.flatnonzero(np.diff(c_idx)) + 1
    run_starts = np.concatenate([[0], run_first])
    run_lens = np.diff(np.concatenate([run_starts, [s_total]]))
    r_grp = grouping or _choose_grouping(run_lens)
    steps_per_run = -(-run_lens // r_grp)
    nsteps = int(steps_per_run.sum())
    # flat destination slot of each stack entry: step base of its run
    # (in units of R) plus its position within the run
    run_of = np.repeat(np.arange(len(run_lens)), run_lens)
    pos_in_run = np.arange(s_total) - run_starts[run_of]
    step_base = np.concatenate([[0], np.cumsum(steps_per_run)])[:-1]
    dst = step_base[run_of] * r_grp + pos_in_run
    ai2 = np.full(nsteps * r_grp, a_pad_row, np.int32)
    bi2 = np.full(nsteps * r_grp, b_pad_row, np.int32)
    ai2[dst] = a_idx
    bi2[dst] = b_idx
    ci2 = np.empty(nsteps, np.int32)
    ci2[step_base[run_of] + pos_in_run // r_grp] = c_idx
    return ai2.reshape(nsteps, r_grp), bi2.reshape(nsteps, r_grp), ci2, r_grp


# ai/bi arrive FLAT (nsteps*R,) — a 2D (nsteps, R) scalar-prefetch array
# would be lane-padded to (nsteps, 128) in SMEM (1 MB budget) and blow
# the allocation 128/R-fold; 1D arrays are tiled densely
def _a_map(s, ai, bi, ci, *, r, r_grp):
    return (ai[s * r_grp + r], 0, 0)


def _b_map(s, ai, bi, ci, *, r, r_grp):
    return (bi[s * r_grp + r], 0, 0)


def _c_map(s, ai, bi, ci):
    return (ci[s], 0, 0)


def _smm_kernel(ai_ref, bi_ref, ci_ref, *refs, r_grp, kmerge):
    a_refs = refs[:r_grp]
    b_refs = refs[r_grp : 2 * r_grp]
    alpha_ref = refs[2 * r_grp]
    c_ref = refs[2 * r_grp + 1]
    o_ref = refs[2 * r_grp + 2]
    acc_ref = refs[2 * r_grp + 3]
    s = pl.program_id(0)
    cur = ci_ref[s]
    prev = ci_ref[jnp.maximum(s - 1, 0)]
    first = jnp.logical_or(s == 0, cur != prev)
    # HIGHEST: true-f32 MXU passes for f32 inputs (default would be
    # one bf16 pass, ~1e-3 relative error — caught by the
    # validate_kernels gate on real hardware); bf16 inputs stay
    # single-pass with f32 accumulation either way
    if kmerge and r_grp > 1:
        # k-merged variant (the in-kernel sibling of the engine's
        # xla_group R-tiling): ONE (R*k, m)^T x (R*k, n) MXU dot per
        # grid step instead of R small dots — deeper MXU pipeline,
        # R-fold fewer matmul ops.  A arrives TRANSPOSED (k, m) per
        # block so both concatenations run along the cheap sublane
        # axis, never the lane axis.
        a_cat = jnp.concatenate([a_refs[r][0] for r in range(r_grp)], axis=0)
        b_cat = jnp.concatenate([b_refs[r][0] for r in range(r_grp)], axis=0)
        contrib = jax.lax.dot_general(
            a_cat, b_cat,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    else:
        contrib = jnp.zeros(acc_ref.shape, jnp.float32)
        for r in range(r_grp):
            contrib = contrib + jax.lax.dot_general(
                a_refs[r][0],
                b_refs[r][0],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
    contrib = alpha_ref[0, 0] * contrib

    @pl.when(first)
    def _():
        acc_ref[...] = c_ref[0].astype(jnp.float32) + contrib

    @pl.when(jnp.logical_not(first))
    def _():
        acc_ref[...] = acc_ref[...] + contrib

    o_ref[0] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("r_grp", "interpret", "kmerge"),
    donate_argnums=(0,),
)
def _pallas_process(c_data, a_data, b_data, ai2, bi2, ci2, alpha, *, r_grp,
                    interpret, kmerge=False):
    """One launch: ai2/bi2 flat (nsteps*R,), ci2 (nsteps,), all int32.
    With ``kmerge`` the A operand is consumed TRANSPOSED per block
    ((k, m) tiles) so the kernel's k-concatenations stay on the sublane
    axis; the transpose happens here, device-side, once per launch."""
    nsteps = ci2.shape[0]
    m, k = a_data.shape[1:]
    n = b_data.shape[2]
    kmerge = bool(kmerge and r_grp > 1)
    if kmerge:
        a_data = jnp.swapaxes(a_data, 1, 2)  # (N, k, m)
        a_block = (1, k, m)
    else:
        a_block = (1, m, k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nsteps,),
        in_specs=[
            *[
                pl.BlockSpec(a_block, functools.partial(_a_map, r=r, r_grp=r_grp))
                for r in range(r_grp)
            ],
            *[
                pl.BlockSpec((1, k, n), functools.partial(_b_map, r=r, r_grp=r_grp))
                for r in range(r_grp)
            ],
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, m, n), _c_map),
        ],
        out_specs=pl.BlockSpec((1, m, n), _c_map),
        scratch_shapes=[pltpu.VMEM((m, n), jnp.float32)],
    )
    kernel = functools.partial(_smm_kernel, r_grp=r_grp, kmerge=kmerge)
    # operand positions (incl. the 3 scalar-prefetch args):
    # 0..2 = ai2/bi2/ci2, 3..3+2R-1 = A/B, 3+2R = alpha, 3+2R+1 = c_data
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(c_data.shape, c_data.dtype),
        input_output_aliases={3 + 2 * r_grp + 1: 0},
        interpret=interpret,
    )(
        ai2, bi2, ci2,
        *([a_data] * r_grp),
        *([b_data] * r_grp),
        alpha,
        c_data,
    )


# per-launch cap on stack entries (ai+bi+ci int32 must fit the ~1 MB
# SMEM scalar-prefetch budget with headroom); longer stacks are chopped
# into sequential launches — C runs spanning a boundary continue
# correctly because the aliased C block already holds the partial sum
# and the next launch's first-step reload adds to it
_MAX_ENTRIES_PER_LAUNCH = 32768


def process_stack_pallas(
    c_data,
    a_data,
    b_data,
    a_idx: np.ndarray,
    b_idx: np.ndarray,
    c_idx: np.ndarray,
    alpha,
    a_pad_row: int | None = None,
    b_pad_row: int | None = None,
    grouping: int | None = None,
    variant: str | None = None,
):
    """Process a flat stack (host int arrays, sorted by ``c_idx``).

    ``a_pad_row``/``b_pad_row`` must index a zero row of the data
    arrays; when None, a zero row is appended on the fly.  ``grouping``
    forces R (otherwise chosen from the run-length heuristic; the
    caller passes the tuned value from `dbcsr_tpu.acc.params` when one
    exists).  ``variant="kmerge"`` selects the k-merged single-dot
    kernel (one (R*k, m)^T x (R*k, n) MXU dot per step).
    """
    if len(a_idx) == 0:
        return c_data
    if a_pad_row is None:
        a_data = jnp.concatenate([a_data, jnp.zeros((1,) + a_data.shape[1:], a_data.dtype)])
        a_pad_row = a_data.shape[0] - 1
    if b_pad_row is None:
        b_data = jnp.concatenate([b_data, jnp.zeros((1,) + b_data.shape[1:], b_data.dtype)])
        b_pad_row = b_data.shape[0] - 1
    ai2, bi2, ci2, r_grp = build_grouped_stack(
        np.asarray(c_idx), np.asarray(a_idx), np.asarray(b_idx),
        a_pad_row, b_pad_row, grouping=grouping,
    )
    launches = prepare_launches(ai2, bi2, ci2, r_grp, a_pad_row, b_pad_row)
    alpha_arr = jnp.asarray([[alpha]], dtype=jnp.float32)
    interpret = jax.devices()[0].platform != "tpu"
    for a_c, b_c, c_c in launches:
        # Mosaic fails to legalize scalar-prefetch index maps traced under
        # jax_enable_x64 (i64 SMEM index loads); the kernel only touches
        # f32/bf16 data and i32 indices, so trace with x64 off.
        with jax.enable_x64(False):
            c_data = _pallas_process(
                c_data, a_data, b_data,
                jnp.asarray(a_c), jnp.asarray(b_c), jnp.asarray(c_c),
                alpha_arr, r_grp=r_grp, interpret=interpret,
                kmerge=(variant == "kmerge"),
            )
    return c_data


def prepare_launches(ai2, bi2, ci2, r_grp: int, a_pad_row: int, b_pad_row: int):
    """Chop a grouped stack into SMEM-sized launches.

    Returns [(ai_flat (csteps*R,), bi_flat, ci (csteps,)), ...].  Chunk
    boundaries are pulled back to the start of the current C run so a
    block's accumulation stays within one launch (a mid-run split would
    round the f32 accumulator to the output dtype at the boundary —
    harmless for f32, a precision leak for bf16); a single run longer
    than the cap is split anyway.  Step counts are bucketed so jit
    shapes recur; padding steps repeat the chunk's final C block with
    zero-block entries (exact no-ops)."""
    from dbcsr_tpu.utils.rounding import bucket_size

    csteps_max = max(1, _MAX_ENTRIES_PER_LAUNCH // r_grp)
    nsteps_total = ai2.shape[0]
    out = []
    s0 = 0
    while s0 < nsteps_total:
        s1 = min(s0 + csteps_max, nsteps_total)
        if s1 < nsteps_total and ci2[s1 - 1] == ci2[s1]:
            # pull the boundary back to this run's first step
            run_start = s1 - 1
            while run_start > s0 and ci2[run_start - 1] == ci2[s1]:
                run_start -= 1
            if run_start > s0:
                s1 = run_start
        a_c, b_c, c_c = ai2[s0:s1], bi2[s0:s1], ci2[s0:s1]
        cap = bucket_size(a_c.shape[0])
        if cap > a_c.shape[0]:
            pad = cap - a_c.shape[0]
            a_c = np.concatenate([a_c, np.full((pad, r_grp), a_pad_row, np.int32)])
            b_c = np.concatenate([b_c, np.full((pad, r_grp), b_pad_row, np.int32)])
            c_c = np.concatenate([c_c, np.full(pad, c_c[-1], np.int32)])
        out.append((np.ascontiguousarray(a_c.reshape(-1)),
                    np.ascontiguousarray(b_c.reshape(-1)),
                    np.ascontiguousarray(c_c)))
        s0 = s1
    return out
