"""Fused Pallas TPU kernel for stack processing (placeholder).

Will fuse gather -> small-GEMM -> segment-accumulate in VMEM, replacing
the reference's five CUDA kernel families
(`src/acc/libsmm_acc/kernels/smm_acc_dnt_*.h`) with one blocked Pallas
matmul whose tuning space is (entries-per-step, k-concat length, vmem
budget).  Until implemented, `supports` returns False and the XLA path
in `dbcsr_tpu.acc.smm` is used.
"""

from __future__ import annotations


def supports(c_data, a_data, b_data) -> bool:
    return False


def process_stack_pallas(c_data, a_data, b_data, a_idx, b_idx, c_idx, alpha):
    raise NotImplementedError("pallas SMM kernel not yet implemented")
