"""Fused Pallas TPU kernel for parameter-stack processing.

TPU-native replacement for the reference's five CUDA kernel families
(`src/acc/libsmm_acc/kernels/smm_acc_dnt_{tiny,small,medium,largeDB1,
largeDB2}.h`): a single blocked kernel whose tuning knob is the
*grouping* R — how many stack entries one grid step processes (the
CUDA kernels' `grouping` template parameter plays the same role).

Design (vs the CUDA design, by intent):

* The stack arrives **sorted by C block** (the engine guarantees it),
  so each C block is one contiguous run of entries.  Runs are chopped
  into grid steps of R entries; a step's contributions are summed into
  a float32 VMEM accumulator that persists across the run, and the C
  block is written back once when the run ends — no atomics
  (`atomicAdd` in `smm_acc_common.h`) and bit-reproducible order.
* A/B blocks are *gathered by the Pallas pipeline itself*: the int32
  stack arrays are scalar-prefetch operands and the BlockSpec
  `index_map`s read them to pick which (1, m, k) block to DMA next —
  the Mosaic pipeline double-buffers these fetches exactly like the
  CUDA kernels' double-buffered shared-memory loads (largeDB1/2).
* Short runs are padded to a multiple of R with entries pointing at a
  guaranteed-zero block row (the engine's bucket padding), which
  contribute exact zeros — the analog of the reference's masked
  tail entries.

Only real float32/bfloat16 stacks take this path (`supports`); f64 and
complex fall back to the XLA gather/segment-sum path in
`dbcsr_tpu.acc.smm` (TPU has no native f64 MXU path to win with).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dbcsr_tpu.utils.compat import enable_x64 as _enable_x64

_SUPPORTED = (np.dtype(np.float32), np.dtype(jnp.bfloat16))


def supports(c_data, a_data, b_data) -> bool:
    if jnp.dtype(c_data.dtype) not in _SUPPORTED:
        return False
    if jnp.dtype(a_data.dtype) != jnp.dtype(c_data.dtype):
        return False
    if jnp.dtype(b_data.dtype) != jnp.dtype(c_data.dtype):
        return False
    from dbcsr_tpu.core.config import get_config

    # blocks bigger than max_kernel_dim blow the VMEM budget for 2*R
    # in-flight panels and take the XLA dot path instead (the role of
    # the reference's max_kernel_dim=80 cuBLAS fallback,
    # `libsmm_acc.cpp:227-249`)
    dims = a_data.shape[1:] + b_data.shape[1:] + c_data.shape[1:]
    return max(dims) <= get_config().max_kernel_dim


def _choose_grouping(run_lengths: np.ndarray) -> int:
    """Pick R (entries per grid step) from the run-length distribution —
    the one-knob analog of the CUDA `grouping` parameter."""
    avg = float(run_lengths.mean()) if len(run_lengths) else 1.0
    for r in (8, 4, 2):
        if avg >= r * 0.75:
            return r
    return 1


def build_grouped_stack(c_idx: np.ndarray, a_idx: np.ndarray, b_idx: np.ndarray,
                        a_pad_row: int, b_pad_row: int, grouping: int | None = None):
    """Chop the (sorted-by-c) stack into grid steps of R entries.

    Returns int32 arrays ai2 (S, R), bi2 (S, R), ci2 (S,) where padded
    slots point at (a_pad_row, b_pad_row) — a zero block row each.
    """
    s_total = len(c_idx)
    run_first = np.flatnonzero(np.diff(c_idx)) + 1
    run_starts = np.concatenate([[0], run_first])
    run_lens = np.diff(np.concatenate([run_starts, [s_total]]))
    r_grp = grouping or _choose_grouping(run_lens)
    steps_per_run = -(-run_lens // r_grp)
    nsteps = int(steps_per_run.sum())
    # flat destination slot of each stack entry: step base of its run
    # (in units of R) plus its position within the run
    run_of = np.repeat(np.arange(len(run_lens)), run_lens)
    pos_in_run = np.arange(s_total) - run_starts[run_of]
    step_base = np.concatenate([[0], np.cumsum(steps_per_run)])[:-1]
    dst = step_base[run_of] * r_grp + pos_in_run
    ai2 = np.full(nsteps * r_grp, a_pad_row, np.int32)
    bi2 = np.full(nsteps * r_grp, b_pad_row, np.int32)
    ai2[dst] = a_idx
    bi2[dst] = b_idx
    ci2 = np.empty(nsteps, np.int32)
    ci2[step_base[run_of] + pos_in_run // r_grp] = c_idx
    return ai2.reshape(nsteps, r_grp), bi2.reshape(nsteps, r_grp), ci2, r_grp


# ai/bi arrive FLAT (nsteps*R,) — a 2D (nsteps, R) scalar-prefetch array
# would be lane-padded to (nsteps, 128) in SMEM (1 MB budget) and blow
# the allocation 128/R-fold; 1D arrays are tiled densely
def _a_map(s, ai, bi, ci, *, r, r_grp):
    return (ai[s * r_grp + r], 0, 0)


def _b_map(s, ai, bi, ci, *, r, r_grp):
    return (bi[s * r_grp + r], 0, 0)


def _c_map(s, ai, bi, ci):
    return (ci[s], 0, 0)


def _dot_precision(dtype):
    """MXU precision per operand dtype.  HIGHEST forces true-f32
    multi-pass contraction for f32 inputs (the default single bf16
    pass loses ~1e-3 relative — caught by the validate_kernels gate on
    hardware).  bf16 operands MUST use DEFAULT: this Mosaic rejects an
    fp32 contract precision on bf16 vectors ("Bad lhs type" fatal,
    observed on-chip 2026-07-31), and bf16 inputs gain nothing from
    extra passes — the MXU multiplies bf16 exactly into the f32
    accumulator either way."""
    if jnp.dtype(dtype) == jnp.dtype(jnp.bfloat16):
        return jax.lax.Precision.DEFAULT
    return jax.lax.Precision.HIGHEST


def _smm_kernel(ai_ref, bi_ref, ci_ref, *refs, r_grp, kmerge):
    a_refs = refs[:r_grp]
    b_refs = refs[r_grp : 2 * r_grp]
    alpha_ref = refs[2 * r_grp]
    c_ref = refs[2 * r_grp + 1]
    o_ref = refs[2 * r_grp + 2]
    acc_ref = refs[2 * r_grp + 3]
    s = pl.program_id(0)
    cur = ci_ref[s]
    prev = ci_ref[jnp.maximum(s - 1, 0)]
    first = jnp.logical_or(s == 0, cur != prev)
    if kmerge and r_grp > 1:
        # k-merged variant (the in-kernel sibling of the engine's
        # xla_group R-tiling): ONE (R*k, m)^T x (R*k, n) MXU dot per
        # grid step instead of R small dots — deeper MXU pipeline,
        # R-fold fewer matmul ops.  A arrives TRANSPOSED (k, m) per
        # block so both concatenations run along the cheap sublane
        # axis, never the lane axis.
        a_cat = jnp.concatenate([a_refs[r][0] for r in range(r_grp)], axis=0)
        b_cat = jnp.concatenate([b_refs[r][0] for r in range(r_grp)], axis=0)
        contrib = jax.lax.dot_general(
            a_cat, b_cat,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_precision(a_cat.dtype),
        )
    else:
        contrib = jnp.zeros(acc_ref.shape, jnp.float32)
        for r in range(r_grp):
            contrib = contrib + jax.lax.dot_general(
                a_refs[r][0],
                b_refs[r][0],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_dot_precision(a_refs[r].dtype),
            )
    contrib = alpha_ref[0, 0] * contrib

    @pl.when(first)
    def _():
        acc_ref[...] = c_ref[0].astype(jnp.float32) + contrib

    @pl.when(jnp.logical_not(first))
    def _():
        acc_ref[...] = acc_ref[...] + contrib

    o_ref[0] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("r_grp", "interpret", "kmerge"),
    donate_argnums=(0,),
)
def _pallas_process(c_data, a_data, b_data, ai2, bi2, ci2, alpha, *, r_grp,
                    interpret, kmerge=False):
    """One launch: ai2/bi2 flat (nsteps*R,), ci2 (nsteps,), all int32.
    With ``kmerge`` the A operand is consumed TRANSPOSED per block
    ((k, m) tiles) so the kernel's k-concatenations stay on the sublane
    axis; the transpose happens here, device-side, once per launch."""
    nsteps = ci2.shape[0]
    m, k = a_data.shape[1:]
    n = b_data.shape[2]
    kmerge = bool(kmerge and r_grp > 1)
    if kmerge:
        a_data = jnp.swapaxes(a_data, 1, 2)  # (N, k, m)
        a_block = (1, k, m)
    else:
        a_block = (1, m, k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nsteps,),
        in_specs=[
            *[
                pl.BlockSpec(a_block, functools.partial(_a_map, r=r, r_grp=r_grp))
                for r in range(r_grp)
            ],
            *[
                pl.BlockSpec((1, k, n), functools.partial(_b_map, r=r, r_grp=r_grp))
                for r in range(r_grp)
            ],
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, m, n), _c_map),
        ],
        out_specs=pl.BlockSpec((1, m, n), _c_map),
        scratch_shapes=[pltpu.VMEM((m, n), jnp.float32)],
    )
    kernel = functools.partial(_smm_kernel, r_grp=r_grp, kmerge=kmerge)
    # operand positions (incl. the 3 scalar-prefetch args):
    # 0..2 = ai2/bi2/ci2, 3..3+2R-1 = A/B, 3+2R = alpha, 3+2R+1 = c_data
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(c_data.shape, c_data.dtype),
        input_output_aliases={3 + 2 * r_grp + 1: 0},
        interpret=interpret,
    )(
        ai2, bi2, ci2,
        *([a_data] * r_grp),
        *([b_data] * r_grp),
        alpha,
        c_data,
    )


# per-launch cap on stack entries (ai+bi+ci int32 must fit the ~1 MB
# SMEM scalar-prefetch budget with headroom); longer stacks are chopped
# into sequential launches — C runs spanning a boundary continue
# correctly because the aliased C block already holds the partial sum
# and the next launch's first-step reload adds to it
_MAX_ENTRIES_PER_LAUNCH = 32768


def process_stack_pallas(
    c_data,
    a_data,
    b_data,
    a_idx: np.ndarray,
    b_idx: np.ndarray,
    c_idx: np.ndarray,
    alpha,
    a_pad_row: int | None = None,
    b_pad_row: int | None = None,
    grouping: int | None = None,
    variant: str | None = None,
):
    """Process a flat stack (host int arrays, sorted by ``c_idx``).

    ``a_pad_row``/``b_pad_row`` must index a zero row of the data
    arrays; when None, a zero row is appended on the fly.  ``grouping``
    forces R (otherwise chosen from the run-length heuristic; the
    caller passes the tuned value from `dbcsr_tpu.acc.params` when one
    exists).  ``variant="kmerge"`` selects the k-merged single-dot
    kernel (one (R*k, m)^T x (R*k, n) MXU dot per step).
    """
    if len(a_idx) == 0:
        return c_data
    if a_pad_row is None:
        a_data = jnp.concatenate([a_data, jnp.zeros((1,) + a_data.shape[1:], a_data.dtype)])
        a_pad_row = a_data.shape[0] - 1
    if b_pad_row is None:
        b_data = jnp.concatenate([b_data, jnp.zeros((1,) + b_data.shape[1:], b_data.dtype)])
        b_pad_row = b_data.shape[0] - 1
    ai2, bi2, ci2, r_grp = build_grouped_stack(
        np.asarray(c_idx), np.asarray(a_idx), np.asarray(b_idx),
        a_pad_row, b_pad_row, grouping=grouping,
    )
    launches = prepare_launches(ai2, bi2, ci2, r_grp, a_pad_row, b_pad_row)
    alpha_arr = jnp.asarray([[alpha]], dtype=jnp.float32)
    interpret = jax.devices()[0].platform != "tpu"
    for a_c, b_c, c_c in launches:
        # Mosaic fails to legalize scalar-prefetch index maps traced under
        # jax_enable_x64 (i64 SMEM index loads); the kernel only touches
        # f32/bf16 data and i32 indices, so trace with x64 off.
        with _enable_x64(False):
            c_data = _pallas_process(
                c_data, a_data, b_data,
                jnp.asarray(a_c), jnp.asarray(b_c), jnp.asarray(c_c),
                alpha_arr, r_grp=r_grp, interpret=interpret,
                kmerge=(variant == "kmerge"),
            )
    return c_data


# --------------------------------------------------------------------------
# Cross-packed kernel ("crosspack"): P x R MXU tiling
#
# The looped kernel runs one (m,k)x(k,n) dot per stack entry — a 23x23
# block uses <4% of one 128x128x128 MXU pass.  kmerge packs R entries
# along the CONTRACTION axis (depth R*k).  crosspack adds the spatial
# axes: P independent C-runs are packed side by side, lane p occupying
# rows [p*m, (p+1)*m) / cols [p*n, (p+1)*n) of one big
# (R*k, P*m)^T x (R*k, P*n) -> (P*m, P*n) dot whose BLOCK-DIAGONAL
# holds each lane's k-merged contribution (off-diagonal products are
# discarded — the price of packing, paid in FLOPs the idle MXU had
# anyway).  One pass now advances P*R stack entries (25 at 23^3 vs 1),
# the spatial sibling the round-3 verdict asked for next to kmerge's
# k-packing.  Reference analog: the tile_m/tile_n register-tiling knobs
# of the CUDA kernel families (`kernels/smm_acc_dnt_medium.h` tiling
# parameters) — redesigned around the MXU's fixed 128x128 geometry.
#
# Scheduling: runs (one per C block; the stack arrives sorted) are
# dealt greedily onto P lanes; each lane is the existing one-column
# state machine (f32 VMEM accumulator persisting across a run,
# write-back every step).  Lanes own DISJOINT C blocks, so each lane
# writes its own output array (Pallas multiple-outputs), and the engine
# scatters lane outputs back into c_data afterwards — no atomics, and
# bit-reproducible per-run summation order, like the base kernel.
# --------------------------------------------------------------------------


def choose_pack(m: int, n: int, k: int, max_streams: int = 40):
    """Pick (P, R): spatial lanes P and k-depth R for one MXU pass.

    P*max(m,n) and R*k each aim to fill (not exceed) 128; the stream
    count 2*P*R (+2P for C) is capped so VMEM double-buffers and the
    SMEM prefetch budget stay comfortable."""
    P = max(1, min(8, 128 // max(m, n)))
    R = max(1, min(8, 128 // k))
    while P * R * 2 + 2 * P > max_streams:
        if R >= P and R > 1:
            R -= 1
        elif P > 1:
            P -= 1
        else:
            break
    return P, R


def build_crosspack_stack(c_idx: np.ndarray, a_idx: np.ndarray,
                          b_idx: np.ndarray, a_pad_row: int, b_pad_row: int,
                          P: int, R: int):
    """Deal the (sorted-by-c) stack onto P lanes of R-deep grid steps.

    Returns (ai (nsteps,P,R), bi (nsteps,P,R), cg (nsteps,P) global C
    block ids, cl (nsteps,P) lane-local output slots, lane_c: list of P
    int32 arrays — lane p's global C ids in lane-slot order).  Padded
    slots point at the zero rows / a dummy output slot.
    """
    s_total = len(c_idx)
    if s_total == 0:
        return (np.empty((0, P, R), np.int32), np.empty((0, P, R), np.int32),
                np.empty((0, P), np.int32), np.empty((0, P), np.int32),
                [np.empty(0, np.int32) for _ in range(P)])
    run_first = np.flatnonzero(np.diff(c_idx)) + 1
    run_starts = np.concatenate([[0], run_first])
    run_lens = np.diff(np.concatenate([run_starts, [s_total]]))
    run_steps = -(-run_lens // R)
    nruns = len(run_lens)
    # snake-order dealing over steps-descending runs (0..P-1, P-1..0,
    # ...): the vectorized stand-in for greedy LPT — within one run's
    # steps of perfectly balanced on sorted items, no Python loop
    lane_of = np.zeros(nruns, np.int64)
    if P > 1 and nruns:
        order = np.argsort(-run_steps, kind="stable")
        cyc = np.arange(nruns) % (2 * P)
        lane_of[order] = np.where(cyc < P, cyc, 2 * P - 1 - cyc)
    lane_loads = np.bincount(lane_of, weights=run_steps, minlength=P) \
        if nruns else np.zeros(P)
    nsteps = int(lane_loads.max()) if nruns else 0
    ai = np.full((nsteps, P, R), a_pad_row, np.int32)
    bi = np.full((nsteps, P, R), b_pad_row, np.int32)
    cg = np.zeros((nsteps, P), np.int32)
    cl = np.empty((nsteps, P), np.int32)
    lane_c = []
    run_of = np.repeat(np.arange(nruns), run_lens)
    for p in range(P):
        runs_p = np.flatnonzero(lane_of == p)  # ascending c within lane
        ent = np.flatnonzero(lane_of[run_of] == p)
        if not len(runs_p):
            cl[:, p] = 0
            lane_c.append(np.empty(0, np.int32))
            continue
        # the lane's subset keeps its sort-by-c; reuse the vectorized
        # single-lane step builder
        ai2, bi2, ci2, _ = build_grouped_stack(
            c_idx[ent], a_idx[ent], b_idx[ent], a_pad_row, b_pad_row,
            grouping=R,
        )
        sp = ai2.shape[0]
        ai[:sp, p, :] = ai2
        bi[:sp, p, :] = bi2
        cg[:sp, p] = ci2
        # lane-local slot: rank of each step's run within the lane
        cl[:sp, p] = np.searchsorted(c_idx[run_starts[runs_p]], ci2)
        # pad tail steps -> dummy slot len(runs_p): zero contributions
        # land there and the scatter never reads it
        cl[sp:, p] = len(runs_p)
        lane_c.append(c_idx[run_starts[runs_p]].astype(np.int32))
    return ai, bi, cg, cl, lane_c


def _cp_a_map(s, ai, bi, cg, cl, *, p, r, P, R):
    return (ai[(s * P + p) * R + r], 0, 0)


def _cp_b_map(s, ai, bi, cg, cl, *, p, r, P, R):
    return (bi[(s * P + p) * R + r], 0, 0)


def _cp_cin_map(s, ai, bi, cg, cl, *, p, P):
    return (cg[s * P + p], 0, 0)


def _cp_out_map(s, ai, bi, cg, cl, *, p, P):
    return (cl[s * P + p], 0, 0)


def _crosspack_epilogue(a_cols, b_cols, cl_ref, alpha_ref, c_refs, o_refs,
                        acc_ref, P):
    """Shared tail of both crosspack kernels: the big block-diagonal
    cross dot, per-lane diagonal extraction, run-boundary accumulation
    (first-step detection via cl), and per-lane write-back."""
    s = pl.program_id(0)
    m = a_cols[0].shape[1]
    n = b_cols[0].shape[1]
    a_all = jnp.concatenate(a_cols, axis=1) if P > 1 else a_cols[0]
    b_all = jnp.concatenate(b_cols, axis=1) if P > 1 else b_cols[0]
    full = jax.lax.dot_general(
        a_all, b_all,
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=_dot_precision(a_all.dtype),
    )
    alpha = alpha_ref[0, 0]
    for p in range(P):
        contrib = alpha * jax.lax.slice(
            full, (p * m, p * n), ((p + 1) * m, (p + 1) * n)
        )
        cur = cl_ref[s * P + p]
        prev = cl_ref[jnp.maximum(s - 1, 0) * P + p]
        first = jnp.logical_or(s == 0, cur != prev)

        @pl.when(first)
        def _(p=p, contrib=contrib):
            acc_ref[p] = c_refs[p][0].astype(jnp.float32) + contrib

        @pl.when(jnp.logical_not(first))
        def _(p=p, contrib=contrib):
            acc_ref[p] = acc_ref[p] + contrib

        o_refs[p][0] = acc_ref[p].astype(o_refs[p].dtype)


def _crosspack_kernel(ai_ref, bi_ref, cg_ref, cl_ref, *refs, P, R):
    a_refs = refs[:P * R]
    b_refs = refs[P * R:2 * P * R]
    alpha_ref = refs[2 * P * R]
    c_refs = refs[2 * P * R + 1:2 * P * R + 1 + P]
    o_refs = refs[2 * P * R + 1 + P:2 * P * R + 1 + 2 * P]
    acc_ref = refs[-1]  # VMEM (P, m, n) f32
    # lane strips: k-concats on the sublane axis (cheap), then the lane
    # concat packs strips side by side on the lane axis
    a_cols = [
        jnp.concatenate([a_refs[p * R + r][0] for r in range(R)], axis=0)
        if R > 1 else a_refs[p * R][0]
        for p in range(P)
    ]
    b_cols = [
        jnp.concatenate([b_refs[p * R + r][0] for r in range(R)], axis=0)
        if R > 1 else b_refs[p * R][0]
        for p in range(P)
    ]
    _crosspack_epilogue(a_cols, b_cols, cl_ref, alpha_ref, c_refs, o_refs,
                        acc_ref, P)


@functools.partial(
    jax.jit,
    static_argnames=("P", "R", "nc_out", "interpret"),
)
def _pallas_crosspack(c_data, a_data_t, b_data, ai, bi, cg, cl, alpha, *,
                      P, R, nc_out, interpret):
    """One crosspack launch.  ``a_data_t`` is (N, k, m) (pre-transposed,
    like kmerge).  ai/bi flat (nsteps*P*R,), cg/cl flat (nsteps*P,).
    Returns a tuple of P lane outputs, each (nc_out, m, n)."""
    nsteps = cg.shape[0] // P
    k, m = a_data_t.shape[1:]
    n = b_data.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nsteps,),
        in_specs=[
            *[
                pl.BlockSpec((1, k, m),
                             functools.partial(_cp_a_map, p=p, r=r, P=P, R=R))
                for p in range(P) for r in range(R)
            ],
            *[
                pl.BlockSpec((1, k, n),
                             functools.partial(_cp_b_map, p=p, r=r, P=P, R=R))
                for p in range(P) for r in range(R)
            ],
            pl.BlockSpec(memory_space=pltpu.SMEM),
            *[
                pl.BlockSpec((1, m, n), functools.partial(_cp_cin_map, p=p, P=P))
                for p in range(P)
            ],
        ],
        out_specs=[
            pl.BlockSpec((1, m, n), functools.partial(_cp_out_map, p=p, P=P))
            for p in range(P)
        ],
        scratch_shapes=[pltpu.VMEM((P, m, n), jnp.float32)],
    )
    kernel = functools.partial(_crosspack_kernel, P=P, R=R)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nc_out, m, n), c_data.dtype)
            for _ in range(P)
        ],
        interpret=interpret,
    )(
        ai, bi, cg, cl,
        *([a_data_t] * (P * R)),
        *([b_data] * (P * R)),
        alpha,
        *([c_data] * P),
    )


def _crosspack_vmem_kernel(ai_ref, bi_ref, cg_ref, cl_ref, a_ref, b_ref,
                           alpha_ref, *refs, P, R):
    """VMEM-resident sibling of `_crosspack_kernel`: the whole
    (transposed-A, B) block arrays live in VMEM and lanes gather their
    blocks IN-KERNEL by dynamic leading-dim indexing — zero per-step
    HBM traffic, the regime where the operands fit on-chip (the
    double-buffered shared-memory residency of the CUDA kernels,
    `smm_acc_dnt_largeDB1.h:147-150`, taken to its TPU limit)."""
    c_refs = refs[:P]
    o_refs = refs[P:2 * P]
    acc_ref = refs[-1]
    s = pl.program_id(0)
    a_cols = [
        jnp.concatenate(
            [a_ref[ai_ref[(s * P + p) * R + r]] for r in range(R)], axis=0
        ) if R > 1 else a_ref[ai_ref[s * P * R + p * R]]
        for p in range(P)
    ]
    b_cols = [
        jnp.concatenate(
            [b_ref[bi_ref[(s * P + p) * R + r]] for r in range(R)], axis=0
        ) if R > 1 else b_ref[bi_ref[s * P * R + p * R]]
        for p in range(P)
    ]
    _crosspack_epilogue(a_cols, b_cols, cl_ref, alpha_ref, c_refs, o_refs,
                        acc_ref, P)


@functools.partial(
    jax.jit,
    static_argnames=("P", "R", "nc_out", "interpret"),
)
def _pallas_crosspack_vmem(c_data, a_data_t, b_data, ai, bi, cg, cl, alpha,
                           *, P, R, nc_out, interpret):
    """One VMEM-resident crosspack launch: operand arrays are whole
    VMEM operands (caller gates on their byte size); per-lane outputs
    as in `_pallas_crosspack`."""
    nsteps = cg.shape[0] // P
    k, m = a_data_t.shape[1:]
    n = b_data.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # whole A (transposed)
            pl.BlockSpec(memory_space=pltpu.VMEM),  # whole B
            pl.BlockSpec(memory_space=pltpu.SMEM),   # alpha
            *[
                pl.BlockSpec((1, m, n), functools.partial(_cp_cin_map, p=p, P=P))
                for p in range(P)
            ],
        ],
        out_specs=[
            pl.BlockSpec((1, m, n), functools.partial(_cp_out_map, p=p, P=P))
            for p in range(P)
        ],
        scratch_shapes=[pltpu.VMEM((P, m, n), jnp.float32)],
    )
    kernel = functools.partial(_crosspack_vmem_kernel, P=P, R=R)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nc_out, m, n), c_data.dtype)
            for _ in range(P)
        ],
        interpret=interpret,
    )(
        ai, bi, cg, cl,
        a_data_t, b_data,
        alpha,
        *([c_data] * P),
    )


# byte gate for the VMEM-resident variant: A+B (plus headroom for C
# blocks, accumulators and double-buffered index streams) must fit the
# ~128 MB v5e VMEM; stay well under it
_VMEM_RESIDENT_MAX_BYTES = 64 * 1024 * 1024


def supports_vmem_resident(a_data, b_data) -> bool:
    return int(a_data.nbytes) + int(b_data.nbytes) <= _VMEM_RESIDENT_MAX_BYTES


def prepare_crosspack_launches(c_idx, a_idx, b_idx, a_pad_row, b_pad_row,
                               P: int, R: int):
    """Chop the stack at RUN boundaries into SMEM-sized crosspack
    launches, then lane-deal each chunk.

    Unlike the base kernel, a C run cannot span launches (lane outputs
    are fresh arrays, so there is no partial sum to reload); chunk
    boundaries therefore always align to run starts.  Returns a list of
    launch dicts, or None if any single run exceeds the per-launch
    entry budget (callers fall back to the base kernel).
    """
    from dbcsr_tpu.utils.rounding import bucket_size

    s_total = len(c_idx)
    run_first = np.flatnonzero(np.diff(c_idx)) + 1
    run_starts = np.concatenate([[0], run_first, [s_total]])
    if len(run_starts) > 1 and np.diff(run_starts).max() > _MAX_ENTRIES_PER_LAUNCH:
        return None
    launches = []
    lo = 0
    while lo < s_total:
        # furthest run start within the entry budget
        hi_idx = np.searchsorted(run_starts, lo + _MAX_ENTRIES_PER_LAUNCH,
                                 side="right") - 1
        hi = int(run_starts[max(hi_idx, 0)])
        if hi <= lo:
            hi = int(run_starts[min(hi_idx + 1, len(run_starts) - 1)])
        ai, bi, cg, cl, lane_c = build_crosspack_stack(
            c_idx[lo:hi], a_idx[lo:hi], b_idx[lo:hi],
            a_pad_row, b_pad_row, P, R,
        )
        nsteps = ai.shape[0]
        cap = bucket_size(max(nsteps, 1))
        if cap > nsteps:  # pad steps: zero entries into the dummy slot
            pad = cap - nsteps
            ai = np.concatenate([ai, np.full((pad, P, R), a_pad_row, np.int32)])
            bi = np.concatenate([bi, np.full((pad, P, R), b_pad_row, np.int32)])
            cg = np.concatenate([cg, np.zeros((pad, P), np.int32)])
            cl = np.concatenate(
                [cl, np.repeat(cl[-1:] if nsteps else
                               np.zeros((1, P), np.int32), pad, axis=0)]
            )
        # bucketed so the jitted launch shape recurs across patterns
        nc_out = bucket_size(
            (max(len(c) for c in lane_c) if lane_c else 0) + 1
        )
        launches.append({
            "ai": np.ascontiguousarray(ai.reshape(-1)),
            "bi": np.ascontiguousarray(bi.reshape(-1)),
            "cg": np.ascontiguousarray(cg.reshape(-1)),
            "cl": np.ascontiguousarray(cl.reshape(-1)),
            "lane_c": lane_c,
            "nc_out": nc_out,
        })
        lo = hi
    return launches


def process_stack_crosspack(
    c_data,
    a_data,
    b_data,
    a_idx: np.ndarray,
    b_idx: np.ndarray,
    c_idx: np.ndarray,
    alpha,
    a_pad_row: int | None = None,
    b_pad_row: int | None = None,
    pack: tuple | None = None,
    vmem_resident: bool = False,
):
    """Cross-packed stack processing (host entry point).

    Semantics match `process_stack_pallas`: stack sorted by c_idx,
    contributions added onto ``c_data``.  ``pack`` forces (P, R).
    ``vmem_resident`` selects the whole-array-in-VMEM gather variant
    (caller responsibility: `supports_vmem_resident`).
    Returns updated c_data, or None if the stack is crosspack-ineligible
    (degenerate packing or an over-long run) — callers then use the
    base kernel.
    """
    if len(a_idx) == 0:
        return c_data
    m, k = a_data.shape[1:]
    n = b_data.shape[2]
    P, R = pack or choose_pack(m, n, k)
    if P <= 1:
        return None  # no spatial packing possible; base kernel is equal
    if vmem_resident and not supports_vmem_resident(a_data, b_data):
        return None
    if a_pad_row is None:
        a_data = jnp.concatenate(
            [a_data, jnp.zeros((1,) + a_data.shape[1:], a_data.dtype)])
        a_pad_row = a_data.shape[0] - 1
    if b_pad_row is None:
        b_data = jnp.concatenate(
            [b_data, jnp.zeros((1,) + b_data.shape[1:], b_data.dtype)])
        b_pad_row = b_data.shape[0] - 1
    launches = prepare_crosspack_launches(
        np.asarray(c_idx), np.asarray(a_idx), np.asarray(b_idx),
        a_pad_row, b_pad_row, P, R,
    )
    if launches is None:
        return None
    a_data_t = jnp.swapaxes(a_data, 1, 2)
    interpret = jax.devices()[0].platform != "tpu"
    alpha_arr = jnp.asarray([[alpha]], dtype=jnp.float32)
    launch_fn = _pallas_crosspack_vmem if vmem_resident else _pallas_crosspack
    for lc in launches:
        with _enable_x64(False):
            outs = launch_fn(
                c_data, a_data_t, b_data,
                jnp.asarray(lc["ai"]), jnp.asarray(lc["bi"]),
                jnp.asarray(lc["cg"]), jnp.asarray(lc["cl"]),
                alpha_arr, P=P, R=R, nc_out=lc["nc_out"],
                interpret=interpret,
            )
        c_data = scatter_lane_outputs(
            c_data, outs, [len(c) for c in lc["lane_c"]],
            lane_scatter_index(lc["lane_c"]),
        )
    return c_data


def scatter_lane_outputs(c_data, outs, lane_len, idx):
    """Write each lane's finished C blocks back into the global array.

    Lanes own disjoint C blocks, so this is a plain scatter-set (no
    accumulation).  ``lane_len[p]`` = lane p's valid slot count; ``idx``
    = the concatenated global C indices in lane order (host or device).
    """
    parts = [outs[p][:ln] for p, ln in enumerate(lane_len) if ln]
    if not parts:
        return c_data
    vals = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    return c_data.at[jnp.asarray(idx)].set(vals)


def lane_scatter_index(lane_c):
    """Concatenated global C ids of the non-empty lanes (scatter order
    matching `scatter_lane_outputs`)."""
    arrs = [c for c in lane_c if len(c)]
    return np.concatenate(arrs) if arrs else np.empty(0, np.int32)


def process_launches(c_data, a_data, b_data, launches, alpha_arr, *,
                     r_grp: int, kmerge: bool, interpret: bool):
    """Chain the prepared launches of one base-pallas plan through the
    kernel entry, accumulating into ``c_data`` (operands already carry
    their virtual zero pad row).  This is the ONE launch loop shared by
    `acc.smm._execute_plan` (a top-level dispatch per launch) and the
    fused superstack program, which traces it INSIDE its own jit so a
    whole C bin's launches ride a single dispatch."""
    for dai, dbi, dci in launches:
        c_data = _pallas_process(
            c_data, a_data, b_data, dai, dbi, dci, alpha_arr,
            r_grp=r_grp, interpret=interpret, kmerge=kmerge,
        )
    return c_data


def launch_entries(launches, r_grp: int) -> int:
    """Device-work entry count of prepared launches, INCLUDING the
    grouping and bucket padding slots: what the kernel actually
    gathers and multiplies, as opposed to the stack's true entry
    count.  The difference is the pad overhead the obs layer charges
    to the pallas driver (`dbcsr_tpu_device_entries_total`), so a
    shape whose run lengths group badly shows up as attribution, not
    as mysteriously low achieved GFLOP/s."""
    return sum(len(lc[2]) for lc in launches) * r_grp


def crosspack_launch_entries(cross_launches) -> int:
    """Device-work entry count of prepared crosspack launches (each
    gathered A column is one packed entry slot, padding included)."""
    return sum(int(lc["ai"].size) for lc in cross_launches)


def prepare_launches(ai2, bi2, ci2, r_grp: int, a_pad_row: int, b_pad_row: int):
    """Chop a grouped stack into SMEM-sized launches.

    Returns [(ai_flat (csteps*R,), bi_flat, ci (csteps,)), ...].  Chunk
    boundaries are pulled back to the start of the current C run so a
    block's accumulation stays within one launch (a mid-run split would
    round the f32 accumulator to the output dtype at the boundary —
    harmless for f32, a precision leak for bf16); a single run longer
    than the cap is split anyway.  Step counts are bucketed so jit
    shapes recur; padding steps repeat the chunk's final C block with
    zero-block entries (exact no-ops)."""
    from dbcsr_tpu.utils.rounding import bucket_size

    csteps_max = max(1, _MAX_ENTRIES_PER_LAUNCH // r_grp)
    nsteps_total = ai2.shape[0]
    out = []
    s0 = 0
    while s0 < nsteps_total:
        s1 = min(s0 + csteps_max, nsteps_total)
        if s1 < nsteps_total and ci2[s1 - 1] == ci2[s1]:
            # pull the boundary back to this run's first step
            run_start = s1 - 1
            while run_start > s0 and ci2[run_start - 1] == ci2[s1]:
                run_start -= 1
            if run_start > s0:
                s1 = run_start
        a_c, b_c, c_c = ai2[s0:s1], bi2[s0:s1], ci2[s0:s1]
        cap = bucket_size(a_c.shape[0])
        if cap > a_c.shape[0]:
            pad = cap - a_c.shape[0]
            a_c = np.concatenate([a_c, np.full((pad, r_grp), a_pad_row, np.int32)])
            b_c = np.concatenate([b_c, np.full((pad, r_grp), b_pad_row, np.int32)])
            c_c = np.concatenate([c_c, np.full(pad, c_c[-1], np.int32)])
        out.append((np.ascontiguousarray(a_c.reshape(-1)),
                    np.ascontiguousarray(b_c.reshape(-1)),
                    np.ascontiguousarray(c_c)))
        s0 = s1
    return out
