"""Standalone acc-layer micro-benchmarks.

Analog of `src/acc/acc_bench_smm.c` / `acc_bench_trans.c` (~1,000 LoC C
drivers, `src/acc/README.md:31-43`): exercise ONLY the acc contract —
`process_stack` / `transpose_blocks` / `block_norms` — with no engine
or index machinery, validating against a host (NumPy) checksum exactly
like `libsmm_acc_benchmark.cpp:60-85`, and reporting GFLOP/s and GB/s.

CLI (positional, `0` = default, mirroring the reference drivers):

    python -m dbcsr_tpu.acc.bench smm   [nrep] [stack_size] [m] [n] [k] [dtype]
    python -m dbcsr_tpu.acc.bench trans [nrep] [stack_size] [m] [n] [dtype]

dtype is the reference datatype enum (1=r4, 3=r8; `acc_libsmm.h:31-36`).
"""

from __future__ import annotations

import sys
import time

import numpy as np

from dbcsr_tpu.core.kinds import dtype_of
from dbcsr_tpu.utils.sync import fetch_fence


def _rand_stack(rng, nblocks_a, nblocks_b, nblocks_c, stack_size):
    ai = rng.integers(0, nblocks_a, stack_size).astype(np.int32)
    bi = rng.integers(0, nblocks_b, stack_size).astype(np.int32)
    ci = np.sort(rng.integers(0, nblocks_c, stack_size)).astype(np.int32)
    return ai, bi, ci


def bench_smm(nrep=5, stack_size=30000, m=23, n=23, k=23, dtype_enum=3,
              out=print, seed=42):
    """Batched-SMM benchmark + host validation.  Returns a result dict."""
    import jax
    import jax.numpy as jnp

    dtype = dtype_of(dtype_enum)
    rng = np.random.default_rng(seed)
    # reference sizing: ~stack_size/16 distinct blocks cycle through HBM
    na = nb = max(stack_size // 16, 1)
    nc = max(stack_size // 8, 1)
    a_host = rng.standard_normal((na, m, k)).astype(dtype)
    b_host = rng.standard_normal((nb, k, n)).astype(dtype)
    ai, bi, ci = _rand_stack(rng, na, nb, nc, stack_size)
    a = jnp.asarray(a_host)
    b = jnp.asarray(b_host)

    # host oracle (float64 accumulate, like the LIBXSMM-side validation)
    want = np.zeros((nc, m, n), np.float64)
    np.add.at(
        want, ci,
        np.einsum("sij,sjk->sik", a_host[ai].astype(np.float64),
                  b_host[bi].astype(np.float64)),
    )

    from dbcsr_tpu.acc.smm import execute_stack, prepare_stack

    plan = prepare_stack(jnp.zeros((nc, m, n), dtype), a, b, ai, bi, ci)
    c = execute_stack(jnp.zeros((nc, m, n), dtype), a, b, plan, 1.0)
    # compare ON DEVICE and fetch 8 bytes: a full-result d2h fetch here
    # (tens of MB) persistently degrades the axon tunnel session and
    # can wedge the kernels that follow (PERF_NOTES.md)
    scale = max(np.abs(want).max(), 1.0)
    cmp_dtype = (jnp.float32 if np.dtype(dtype).itemsize <= 4
                 and not jax.config.jax_enable_x64 else jnp.float64)
    max_err = float(
        jnp.max(jnp.abs(c.astype(cmp_dtype) - jnp.asarray(want, cmp_dtype)))
    ) / scale
    # bf16 stores C at ~8 bit mantissa: even exact f32 accumulation
    # rounds to ~4e-3 relative on store, so 1e-3 would always "fail"
    itemsize = np.dtype(dtype).itemsize
    tol = 2e-2 if itemsize <= 2 else (1e-3 if itemsize <= 4 else 1e-10)
    ok = max_err < tol

    times = []
    for _ in range(nrep):
        c = jnp.zeros((nc, m, n), dtype)
        t0 = time.perf_counter()
        c = execute_stack(c, a, b, plan, 1.0)
        fetch_fence(c)  # forced completion (PERF_NOTES.md)
        times.append(time.perf_counter() - t0)
    best = min(times)
    flops = 2.0 * m * n * k * stack_size
    # HBM traffic model: gather A+B per entry, C blocks r/w once each
    # (the shared obs/costmodel convention, so kernel GB/s lines and
    # the engine's roofline rollups are directly comparable)
    from dbcsr_tpu.obs import costmodel

    bytes_moved = costmodel.stack_bytes(
        m, n, k, stack_size, nseg=nc, itemsize=np.dtype(dtype).itemsize
    )
    result = {
        "kernel": f"{m}x{n}x{k}",
        "dtype": np.dtype(dtype).name,
        "stack_size": stack_size,
        "device": str(jax.devices()[0]),
        "device_kind": str(jax.devices()[0].device_kind),
        "gflops": flops / best / 1e9,
        "gbs": bytes_moved / best / 1e9,
        "ms": best * 1e3,
        "max_rel_err": float(max_err),
        "errors": 0 if ok else 1,
        # which driver auto-dispatch chose — artifact lines are useless
        # for tuning decisions without it.  "timed": what the rep loop
        # measures — "execute" = kernel launches on a prepared stack
        # (the reference acc_bench_smm discipline); older artifact
        # lines without the field timed prepare+execute per rep
        "timed": "execute",
        "driver": plan.driver,
        "variant": ("kmerge" if plan.kmerge
                    else ("crosspack_vmem" if plan.cross_vmem
                          else ("crosspack" if plan.pack else None))),
        "r_grp": plan.r_grp,
        "pack": list(plan.pack) if plan.pack else None,
    }
    out(f"typename (id={dtype_enum}): {result['dtype']}")
    out(f"device: {result['device']}")
    out(f"smm {m}x{n}x{k} stack {stack_size}: {result['ms']:.2f} ms "
        f"{result['gflops']:.1f} GFLOP/s {result['gbs']:.1f} GB/s")
    out(f"errors: {result['errors']}")
    return result


def bench_trans(nrep=5, stack_size=30000, m=23, n=23, dtype_enum=3,
                out=print, seed=42):
    """Batched block-transpose benchmark (ref `acc_bench_trans.c`)."""
    import jax
    import jax.numpy as jnp

    from dbcsr_tpu.acc.smm import transpose_blocks

    dtype = dtype_of(dtype_enum)
    rng = np.random.default_rng(seed)
    nblocks = max(stack_size // 4, 1)
    host = rng.standard_normal((nblocks, m, n)).astype(dtype)
    data = jnp.asarray(host)
    got = np.asarray(transpose_blocks(data))
    ok = np.array_equal(got, host.transpose(0, 2, 1))

    times = []
    for _ in range(nrep):
        t0 = time.perf_counter()
        fetch_fence(transpose_blocks(data))  # forced completion
        times.append(time.perf_counter() - t0)
    best = min(times)
    bytes_moved = 2 * host.nbytes
    result = {
        "kernel": f"{m}x{n}",
        "dtype": np.dtype(dtype).name,
        "nblocks": nblocks,
        "device": str(jax.devices()[0]),
        "gbs": bytes_moved / best / 1e9,
        "ms": best * 1e3,
        "errors": 0 if ok else 1,
    }
    out(f"typename (id={dtype_enum}): {result['dtype']}")
    out(f"device: {result['ms']:.2f} ms {result['gbs']:.1f} GB/s")
    out(f"errors: {result['errors']}")
    return result


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("smm", "trans"):
        print(__doc__)
        return 1
    mode = argv.pop(0)
    defaults = [5, 30000, 23, 23, 23, 3] if mode == "smm" else [5, 30000, 23, 23, 3]
    vals = list(defaults)
    for i, arg in enumerate(argv[: len(defaults)]):
        if int(arg) != 0:
            vals[i] = int(arg)
    res = bench_smm(*vals) if mode == "smm" else bench_trans(*vals)
    return res["errors"]


if __name__ == "__main__":
    sys.exit(main())
