"""Mixed-precision planner: demoted compute dtypes, certified by ABFT.

The engine historically executed every stack at the request dtype, so
f64 workloads ran as slow multi-pass emulation on hardware whose
bf16/f32 peak sat idle.  This module opens a precision axis on the
stack engine: a stack may execute with its A/B inputs DEMOTED to a
narrower compute dtype (f32 or bf16) while accumulating in the wide
dtype (`acc.smm._accum_dtype`), optionally with two-product
compensation (hi/lo operand splits that restore every cross term, so
the dropped error is O(eps_compute²) instead of O(eps_compute)).

**Why this is safe here and nowhere else:** the PR 10 integrity plane
probes every launch (`acc.abft`), so a demoted launch carries a
quantitative per-product error certificate.  The planner closes the
loop: a probe residual breaching its demotion ceiling
(`obs.costmodel.demoted_abft_tolerance`) PROMOTES the (m, n, k, dtype)
cell back to native compute — the launch re-executes natively, and
every later plan for the cell resolves native.  Iterative ops chains
(purify/sign/invsqrt) additionally open a `chain_scope`, which
promotes the whole chain once its convergence measure tightens past
the demoted error floor — the per-iteration precision schedule is
published on the event bus (``precision_schedule``) and sampled into
the time-series store, so ``doctor --trend`` can show which cells run
demoted.

**The knob** (``DBCSR_TPU_PRECISION``, `core.config.precision`):

* ``native`` — no demotion (default; the planner resolves to None
  everywhere and the engine is byte-identical to the historical one).
* ``adaptive`` — demote eligible stacks per the policy below, gated on
  the ABFT plane being armed (no certificate, no demotion) and on the
  cell/chain state.
* ``f32`` / ``bf16`` — force the demoted compute dtype with
  compensation, no certification requirement (bench/test legs).

**Default adaptive policy** (`default_spec`): f64 demotes to f32 —
compensated where f64 is emulated anyway (TPU: the split passes are
already being paid, compensation buys accuracy nearly free), plain
f32 inputs with f64 accumulation elsewhere (the narrower dtype IS the
saving; the probe certifies it).  f32 demotes to bf16 (f32
accumulation) on TPU only, where the MXU's bf16 peak is ~4x f32.
Complex dtypes never demote.  A ``precision`` column in the tuned
parameter table (`acc.params`) overrides the default per cell.

Specs are ``(compute_dtype_name, compensated)`` tuples — hashable, so
they ride jit static args and plan-cache keys directly; ``None`` means
native.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import numpy as np

from dbcsr_tpu.core.config import get_config
from dbcsr_tpu.obs import costmodel as _costmodel
from dbcsr_tpu.obs import events as _events
from dbcsr_tpu.obs import metrics as _metrics

_lock = threading.Lock()

# (m, n, k, dtype_name) -> {"state": "demoted"|"promoted",
#                           "last_rel_err": float, "launches": int}
_cells: dict = {}
# bumped on ANY state change (cell promotion, chain-scope transition):
# the mm plan cache keys on it, so a promotion can never be served a
# stale demoted plan
_generation = 0

_tls = threading.local()


def _bump() -> None:
    global _generation
    _generation += 1


def generation() -> int:
    return _generation


def _scopes() -> list:
    st = getattr(_tls, "scopes", None)
    if st is None:
        st = _tls.scopes = []
    return st


def plan_token() -> tuple:
    """The precision state a cached stack plan depends on: config mode,
    the global adaptive generation, and the innermost chain scope's
    current demand — included in `mm.multiply`'s plan-cache key so any
    promotion invalidates the affected cached plans."""
    st = _scopes()
    # an INACTIVE scope (native config, non-demotable dtype) must not
    # perturb the token — native mode stays byte-identical, including
    # its plan-cache hits
    return (get_config().precision, _generation,
            st[-1].mode if st and st[-1].active else None)


# ------------------------------------------------------------- policy

def _abft_on() -> bool:
    from dbcsr_tpu.acc import abft as _abft

    return _abft.enabled()


def _on_tpu() -> bool:
    from dbcsr_tpu.acc.smm import _on_tpu as smm_on_tpu

    return smm_on_tpu()


def default_spec(dtype) -> Optional[tuple]:
    """The adaptive policy's demotion target for a request dtype, or
    None when the dtype is ineligible (complex, already narrowest)."""
    d = np.dtype(dtype)
    if d == np.float64:
        # where f64 is EMULATED the multi-pass cost is already paid and
        # compensation is nearly free accuracy; where it is native the
        # demotion IS the saving, so skip the extra compensation dots
        # and let the probe certify the plain-f32 error
        from dbcsr_tpu.acc.smm import emulated_dtype_on_tpu

        return ("float32", bool(emulated_dtype_on_tpu(d)))
    if d == np.float32 and _on_tpu():
        return ("bfloat16", False)
    return None


def _forced_spec(mode: str, dtype) -> Optional[tuple]:
    d = np.dtype(dtype)
    if np.issubdtype(d, np.complexfloating):
        return None
    if mode == "f32":
        return ("float32", True) if d == np.float64 else None
    if mode == "bf16":
        if d == np.float64:
            return ("bfloat16", True)
        if d == np.float32:
            return ("bfloat16", True)
    return None


def forced() -> bool:
    """True under the FORCED bench/test modes (``f32``/``bf16``),
    which override even a tuned host-driver row; adaptive mode defers
    to measured driver evidence."""
    return get_config().precision in ("f32", "bf16")


def resolve(m: int, n: int, k: int, dtype,
            tuned: Optional[dict] = None) -> Optional[tuple]:
    """The compute spec one stack plan should execute with, or None for
    native.  Consulted by `acc.smm._prepare_stack_impl`; the decision
    order is config force > chain-scope demand > params-table
    ``precision`` column > adaptive cell state > default policy."""
    mode = get_config().precision
    if mode == "native":
        return None
    d = np.dtype(dtype)
    if np.issubdtype(d, np.complexfloating):
        return None
    if mode in ("f32", "bf16"):
        return _forced_spec(mode, d)
    # adaptive: no certificate, no demotion
    if not _abft_on():
        return None
    st = _scopes()
    if st and st[-1].mode == "native":
        return None
    cell = (int(m), int(n), int(k), d.name)
    info = _cells.get(cell)
    if info is not None and info["state"] == "promoted":
        return None
    if tuned and tuned.get("precision"):
        col = str(tuned["precision"])
        if col == "native":
            return None
        spec = column_spec(col, d)
        if spec is not None:
            return spec
    return default_spec(d)


# column value -> (compute dtype, its byte width); a trailing "c"
# selects the two-product-compensated kernel — the column must carry
# the compensation bit, because the tuner ranks the compensated and
# uncompensated variants as SEPARATE candidates (they differ ~3x in
# dot count) and dispatch must run exactly the one that won
_COLUMN_COMPUTE = {"f32": ("float32", 4), "bf16": ("bfloat16", 2)}


def column_spec(col: str, dtype) -> Optional[tuple]:
    """Parse a params-table ``precision`` column value ("f32"/"bf16",
    optionally suffixed "c" for compensated) into a spec — None when
    the value is unknown or would not narrow the request dtype."""
    comp = col.endswith("c")
    entry = _COLUMN_COMPUTE.get(col[:-1] if comp else col)
    if entry is None:
        return None
    compute, width = entry
    if width >= np.dtype(dtype).itemsize:
        return None
    return (compute, comp)


# --------------------------------------------------- adaptive feedback

def note_launch(requested: str, spec: tuple) -> None:
    """Count one demoted launch (per driver dispatch, xla family)."""
    _metrics.counter(
        "dbcsr_tpu_precision_launches_total",
        "stack launches executed at a demoted compute dtype, by "
        "requested/compute dtype and compensation",
    ).inc(requested=str(requested), compute=spec[0],
          compensated=str(bool(spec[1])).lower())


def note_probe_ok(cells, rel_err: float) -> None:
    """Feedback from a passing ABFT probe of a demoted launch: keep the
    last AND worst residual per cell (doctor headroom / the bench's
    evidence rows)."""
    if not cells:
        return
    with _lock:
        for cell in cells:
            info = _cells.setdefault(
                cell, {"state": "demoted", "last_rel_err": 0.0,
                       "max_rel_err": 0.0, "launches": 0})
            info["last_rel_err"] = float(rel_err)
            info["max_rel_err"] = max(info.get("max_rel_err", 0.0),
                                      float(rel_err))
            info["launches"] += 1


def note_exceeded(cells, rel_err: float, ceiling: float) -> None:
    """A demoted launch's probe residual breached its demotion ceiling:
    promote every involved cell back to native compute (sticky for the
    process; the chain scopes and plan-cache generation pick it up
    immediately) and publish the schedule transition."""
    # a NaN probe scalar classifies as exceeded upstream: keep the
    # stored residuals (and the published events) finite-only so the
    # JSONL sinks stay strict-JSON and the gauges stay plottable
    rel = float(rel_err) if np.isfinite(rel_err) else None
    promoted = []
    with _lock:
        for cell in cells or ():
            info = _cells.setdefault(
                cell, {"state": "demoted", "last_rel_err": 0.0,
                       "max_rel_err": 0.0, "launches": 0})
            if rel is not None:
                info["last_rel_err"] = rel
                info["max_rel_err"] = max(info.get("max_rel_err", 0.0),
                                          rel)
            if info["state"] != "promoted":
                info["state"] = "promoted"
                promoted.append(cell)
        if promoted:
            _bump()
    for cell in promoted:
        m, n, k, dt = cell
        _metrics.counter(
            "dbcsr_tpu_precision_promotions_total",
            "(m,n,k,dtype) cells promoted back to native compute after "
            "a probe residual breached its demotion ceiling",
        ).inc(dtype=dt)
        _events.publish(
            "precision_promote",
            {"mnk": f"{m}x{n}x{k}", "dtype": dt,
             "rel_err": rel, "ceiling": float(ceiling),
             "why": "probe-ceiling"},
            flight=True,
        )


def cells_snapshot() -> dict:
    """{(m, n, k, dtype): {state, last_rel_err, launches}} — read by
    the time-series collector and `tools/doctor.py`."""
    with _lock:
        return {cell: dict(info) for cell, info in _cells.items()}


def reset() -> None:
    """Drop adaptive state and chain scopes (tests)."""
    with _lock:
        _cells.clear()
    _tls.scopes = []
    _bump()


# -------------------------------------------------------- chain scopes

class ChainScope:
    """Per-chain precision schedule: while ``mode == "demoted"`` the
    planner may demote stacks issued inside the scope; `observe` flips
    the scope to native once the chain's convergence measure drops
    below the demoted error floor (further demoted iterations could
    not make progress past it), publishing one ``precision_schedule``
    event per observed iteration."""

    __slots__ = ("name", "mode", "active", "floor", "step", "spec")

    def __init__(self, name: str, dtype=None, scale: float = 1.0,
                 promote_below: Optional[float] = None):
        self.name = name
        self.step = 0
        cfg_mode = get_config().precision
        self.spec = None
        if cfg_mode == "adaptive" and _abft_on() and dtype is not None:
            self.spec = default_spec(dtype)
        self.active = self.spec is not None
        self.mode = "demoted" if self.active else "native"
        if promote_below is not None:
            self.floor = float(promote_below)
        elif self.spec is not None:
            # the demoted scheme injects ~eps_eff relative error per
            # product; once the convergence measure is within 64x that
            # floor (scaled to the chain's measure), demotion stalls
            # the iteration — promote
            self.floor = 64.0 * _costmodel.effective_epsilon(
                *self.spec) * float(scale)
        else:
            self.floor = 0.0

    def observe(self, delta: float) -> None:
        """Record one iteration's convergence measure; may promote."""
        if not self.active:
            return
        self.step += 1
        finite = bool(np.isfinite(delta))
        promote = (self.mode == "demoted" and finite
                   and abs(float(delta)) <= self.floor)
        if promote:
            self.mode = "native"
            _bump()
        _events.publish(
            "precision_schedule",
            {"chain": self.name, "step": self.step,
             "precision": self.mode,
             # null, not Infinity/NaN: the event sink's JSONL must
             # stay strict JSON (a chain's first iteration has no
             # previous iterate to diff against)
             "delta": float(delta) if finite else None,
             "floor": float(self.floor),
             **({"promoted": True} if promote else {})},
        )


@contextlib.contextmanager
def chain_scope(name: str, dtype=None, scale: float = 1.0,
                promote_below: Optional[float] = None):
    """Open a chain precision scope around an iterative workload
    (purify/sign/invsqrt).  Inert (zero events, native resolution)
    unless the adaptive mode is armed and the dtype is demotable."""
    scope = ChainScope(name, dtype=dtype, scale=scale,
                       promote_below=promote_below)
    _scopes().append(scope)
    if scope.active:
        _bump()  # entering/leaving a demotable scope re-keys plans
    try:
        yield scope
    finally:
        st = _scopes()
        if st and st[-1] is scope:
            st.pop()
        if scope.active:
            _bump()
