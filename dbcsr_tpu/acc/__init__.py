"""ACC layer: the device-kernel contract.

TPU-native equivalent of the reference accelerator plugin boundary
(`src/acc/acc.h` + `src/acc/acc_libsmm.h`): batched small-matrix
multiply over integer parameter stacks, batched block transpose, and
per-block norms.  CUDA streams/events become XLA async dispatch; device
memory becomes jax Arrays in HBM; the NVRTC JIT-per-(m,n,k) kernel cache
becomes the XLA/Pallas jit cache keyed by block shape.
"""

from dbcsr_tpu.acc.smm import (
    process_stack,
    transpose_blocks,
    block_norms,
    pad_stack,
)
