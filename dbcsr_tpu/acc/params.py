"""Autotuned kernel-parameter table.

Analog of `src/acc/libsmm_acc/parameters/parameters_<GPU>.json` (+
`parameters_utils.h` lookup): per-(m, n, k, dtype) tuned launch
parameters for the stack kernel, keyed by device kind.  Entries are
produced by `dbcsr_tpu.acc.tune` and consulted at dispatch time — the
role the reference's per-GPU JSON plays for `libsmm_acc_process`
(`libsmm_acc.cpp:227-249` parameter lookup on kernel-cache miss).

Schema per entry: {"m", "n", "k", "dtype", "driver": "pallas"|"xla",
"grouping", "gflops"}.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Dict, Optional

_lock = threading.Lock()
_cache: Dict[str, Dict] = {}


def _params_dir() -> str:
    """Writable parameter directory: $DBCSR_TPU_PARAMS_DIR overrides the
    in-package default (which may be read-only in an installed tree)."""
    return os.environ.get(
        "DBCSR_TPU_PARAMS_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "params"),
    )


def device_kind() -> str:
    import jax

    return re.sub(r"\W+", "_", jax.devices()[0].device_kind).strip("_")


def params_path(kind: Optional[str] = None) -> str:
    return os.path.join(_params_dir(), f"parameters_{kind or device_kind()}.json")


def _key(m: int, n: int, k: int, dtype) -> str:
    import numpy as np

    return f"{m}x{n}x{k}:{np.dtype(dtype).name}"


def _load(kind: Optional[str] = None) -> Dict:
    kind = kind or device_kind()
    with _lock:
        if kind not in _cache:
            path = params_path(kind)
            table = {}
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        for e in json.load(f):
                            table[_key(e["m"], e["n"], e["k"], e["dtype"])] = e
                except (OSError, ValueError, KeyError):
                    table = {}
            _cache[kind] = table
        return _cache[kind]


def lookup(m: int, n: int, k: int, dtype) -> Optional[Dict]:
    """Tuned entry for this (m, n, k, dtype) on the current device."""
    try:
        return _load().get(_key(m, n, k, dtype))
    except Exception:
        return None


def save_entry(entry: Dict, kind: Optional[str] = None) -> str:
    """Merge one tuned entry into the device's parameter file."""
    kind = kind or device_kind()
    table = _load(kind)
    with _lock:
        table[_key(entry["m"], entry["n"], entry["k"], entry["dtype"])] = entry
        os.makedirs(_params_dir(), exist_ok=True)
        path = params_path(kind)
        with open(path, "w") as f:
            json.dump(sorted(table.values(), key=lambda e: (e["m"], e["n"], e["k"])),
                      f, indent=1)
    return path
