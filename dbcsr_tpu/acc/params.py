"""Autotuned kernel-parameter table.

Analog of `src/acc/libsmm_acc/parameters/parameters_<GPU>.json` (+
`parameters_utils.h` lookup): per-(m, n, k, dtype) tuned launch
parameters for the stack kernel, keyed by device kind.  Entries are
produced by `dbcsr_tpu.acc.tune` and consulted at dispatch time — the
role the reference's per-GPU JSON plays for `libsmm_acc_process`
(`libsmm_acc.cpp:227-249` parameter lookup on kernel-cache miss).

Schema per entry: {"m", "n", "k", "dtype", "stack_size",
"driver": "pallas"|"xla"|..., "grouping", "gflops", and optionally
"precision": "native"|"f32"|"f32c"|"bf16"|"bf16c" — the per-cell
compute-dtype column `acc.precision.resolve` consults in adaptive
mode ("native" pins the cell to full precision, "f32"/"bf16" name the
demoted compute dtype with a trailing "c" selecting the two-product-
compensated kernel — the tuner ranks compensated and uncompensated as
separate candidates, so the column carries which one won; absent =
the platform default policy)}.  Rows are keyed by
(m, n, k, dtype, stack_size): the same shape tuned at S=30k and S=800k
keeps BOTH rows (through the tunnel, small-stack timings are
latency-bound and would otherwise clobber production-scale rows —
VERDICT r3 item 3), and dispatch picks the row nearest the live stack
size.
"""

from __future__ import annotations

import functools
import json
import os
import re
import threading
from typing import Dict, Optional

_lock = threading.Lock()
_cache: Dict[str, Dict] = {}
_table_gen = 0  # bumped by save_entry; guards predict memoization
# (path, generation) -> {(m, n, k, dtype): [entries]}; one generation kept
_shape_index: Dict[tuple, Dict] = {}


def _by_shape(path: str, table: Dict) -> Dict:
    """Secondary index over the table for O(1) per-shape row lists
    (lookup sits on the multiply hot path via predict)."""
    key = (path, _table_gen)
    with _lock:
        idx = _shape_index.get(key)
        if idx is None:
            idx = {}
            for e in table.values():
                idx.setdefault(
                    (e["m"], e["n"], e["k"], e["dtype"]), []
                ).append(e)
            _shape_index.clear()
            _shape_index[key] = idx
    return idx


def _params_dir() -> str:
    """Writable parameter directory: $DBCSR_TPU_PARAMS_DIR overrides the
    in-package default (which may be read-only in an installed tree)."""
    return os.environ.get(
        "DBCSR_TPU_PARAMS_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "params"),
    )


@functools.lru_cache(maxsize=4)
def _device_kind_real() -> str:
    import jax

    return re.sub(r"\W+", "_", jax.devices()[0].device_kind).strip("_")


def device_kind() -> str:
    """Device kind keying the parameter table.  Under the CPU suite's
    platform_override seam a PRETEND platform must not consume the real
    device's tuned rows (a cpu-kind "host" row would steer pretend-TPU
    dispatch to a driver the real TPU never uses), so overrides that
    differ from the real platform get their own (normally empty) kind."""
    import jax

    from dbcsr_tpu.core.config import get_config

    ov = get_config().platform_override
    if ov and ov != jax.devices()[0].platform:
        return f"pretend_{ov}"
    return _device_kind_real()


def params_path(kind: Optional[str] = None) -> str:
    return os.path.join(_params_dir(), f"parameters_{kind or device_kind()}.json")


def _key(m: int, n: int, k: int, dtype, stack_size) -> str:
    import numpy as np

    return f"{m}x{n}x{k}:{np.dtype(dtype).name}:{int(stack_size)}"


def generation() -> int:
    """The parameter-table generation counter: bumped by `save_entry`,
    `delete_entry` and `invalidate`.  Plan caches that bake tuned
    parameters into a cached plan (``mm/multiply``'s `_plan_cache`, the
    fused superstack decisions cached next to it) key on this value, so
    a promotion/demotion by the online tuner (`dbcsr_tpu.tune`) retires
    every stale plan at its next lookup — no plan ever serves old
    parameters."""
    return _table_gen


def invalidate() -> int:
    """Drop the module-level table caches and bump the generation.

    The promotion seam for writers that bypass `save_entry` (the tune
    store's atomic file replace, an external tuner process updating the
    params dir): without it a process keeps serving the in-memory table
    it loaded at import forever.  Returns the new generation."""
    global _table_gen
    with _lock:
        _cache.clear()
        _shape_index.clear()
        _onchip_flag.clear()
        _predict_cache.clear()
        _table_gen += 1
        return _table_gen


def _load(kind: Optional[str] = None) -> Dict:
    # keyed by the RESOLVED path, so redirecting DBCSR_TPU_PARAMS_DIR
    # mid-process is honored without manual cache clearing
    path = params_path(kind or device_kind())
    with _lock:
        if path not in _cache:
            table = {}
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        for e in json.load(f):
                            table[_key(e["m"], e["n"], e["k"], e["dtype"],
                                       e.get("stack_size", 0))] = e
                except (OSError, ValueError, KeyError):
                    table = {}
            _cache[path] = table
        return _cache[path]


def _prefer_onchip(rows):
    """Provenance quarantine (VERDICT r4 item 6): rows measured on the
    real chip ("onchip") outrank tunnel-latency-bound ("tunnel") or
    CPU-measured rows — when at least one onchip row exists in the
    candidate set, the others get no vote.  Rows with no "env" field
    (pre-provenance tables) rank with the non-onchip ones.  The
    reference's analog is strictly per-device parameter files
    (parameters_utils.h); here one device file can accumulate rows of
    mixed measurement quality through the tunnel, so quality is a
    per-row field."""
    onchip = [e for e in rows if e.get("env") == "onchip"]
    return onchip or rows


def lookup(m: int, n: int, k: int, dtype,
           stack_size: Optional[int] = None) -> Optional[Dict]:
    """Tuned entry for this (m, n, k, dtype) on the current device.

    With ``stack_size``, the same-shape row tuned nearest that size (in
    log space, larger-S winning ties) is returned; without it, the
    largest-S row (production scale)."""
    import math

    import numpy as np

    try:
        path = params_path()
        table = _load()
    except Exception:
        return None
    rows = _by_shape(path, table).get((m, n, k, np.dtype(dtype).name), [])
    if not rows:
        return None
    rows = _prefer_onchip(rows)
    if stack_size is None:
        return max(rows, key=lambda e: e.get("stack_size", 0))
    want = math.log(max(int(stack_size), 1))
    return min(
        rows,
        key=lambda e: (
            abs(math.log(max(e.get("stack_size", 1), 1)) - want),
            -e.get("stack_size", 0),
        ),
    )


_onchip_flag: Dict[tuple, bool] = {}  # (path, generation) -> any-onchip


def _table_has_onchip() -> bool:
    """Whether the resolved table holds ANY onchip-tagged row, memoized
    per (path, generation) — predict() consults this on the dispatch
    hot path before its own memo cache."""
    key = (params_path(), _table_gen)
    with _lock:
        flag = _onchip_flag.get(key)
    if flag is None:
        flag = any(e.get("env") == "onchip" for e in _load().values())
        with _lock:
            _onchip_flag.clear()  # one generation kept, like _shape_index
            _onchip_flag[key] = flag
    return flag


# a donor entry only predicts for shapes within this flop-count ratio;
# farther shapes get no opinion (the default dispatch heuristics apply)
_PREDICT_MAX_FLOP_RATIO = 16.0

_predict_cache: Dict[tuple, Optional[Dict]] = {}


def predict(m: int, n: int, k: int, dtype,
            stack_size: Optional[int] = None) -> Optional[Dict]:
    """Nearest-tuned-entry prediction for an UNTUNED (m, n, k).

    The analog of the reference's predictive-modeling pipeline
    (`src/acc/libsmm_acc/predict/` — a trained model covers triplets the
    autotuner never ran): here the tuned table is small and the launch
    space is {driver, grouping}, so nearest-neighbor in log-flops space
    within the same dtype — capped at a 16x flop-count ratio, so a lone
    distant donor can't dictate dispatch globally — is a sound
    estimator; among equally-near shapes the row tuned nearest the live
    stack size wins.  Results are memoized (this sits on the multiply
    hot path).  Returns a copy of the donor entry tagged
    "predicted_from"."""
    import numpy as np

    exact = lookup(m, n, k, dtype, stack_size)
    if exact is not None:
        if exact.get("env") == "onchip":
            return exact
        # exact row exists but is not proven on-chip (tunnel-latency-
        # bound, cpu-measured, or a legacy untagged row — ONE policy
        # for missing env, matching _prefer_onchip's quarantine; ADVICE
        # r5): trust it outright only when the table holds no onchip
        # evidence AT ALL (then the donor-pool walk below would
        # re-select it through the exact-shape tie-break anyway);
        # otherwise fall through to the pool, where any onchip donor in
        # range mutes it
        try:
            if not _table_has_onchip():
                return exact
        except Exception:
            return exact
    # keyed by the resolved params file so env-redirected tables (tests,
    # DBCSR_TPU_PARAMS_DIR) never serve stale predictions.  Exact S in
    # the key: the engine buckets stack lengths already, so distinct S
    # values stay few — and a bucketed key would make the nearest-S
    # donor choice depend on which S in the bucket was queried first
    ck = (params_path(), m, n, k, np.dtype(dtype).name,
          None if stack_size is None else int(stack_size))
    if ck in _predict_cache:
        return _predict_cache[ck]
    gen0 = _table_gen
    try:
        table = _load()
    except Exception:
        return None
    want_dtype = np.dtype(dtype).name
    best, best_d = None, None
    target = np.log(float(m) * n * k)
    want_s = None if stack_size is None else np.log(float(max(stack_size, 1)))
    max_d = np.log(_PREDICT_MAX_FLOP_RATIO)
    eligible = []
    for e in table.values():
        if e["dtype"] != want_dtype:
            continue
        d = abs(np.log(float(e["m"]) * e["n"] * e["k"]) - target)
        if d > max_d:
            continue
        eligible.append(e)
    # provenance quarantine across the whole donor pool: one onchip
    # donor silences every tunnel/cpu row, so a latency-poisoned
    # 0.1-GFLOP/s row can never steer dispatch once real evidence exists
    for e in _prefer_onchip(eligible):
        d = abs(np.log(float(e["m"]) * e["n"] * e["k"]) - target)
        if want_s is None:
            ds = -float(e.get("stack_size", 0))  # larger S preferred
        else:
            ds = abs(np.log(float(max(e.get("stack_size", 1), 1))) - want_s)
        # exact-shape term (ADVICE r5): permuted shapes share the m*n*k
        # product, so d alone ties at 0 and table iteration order would
        # pick a donor row (wrong tuned params, exactness-gated
        # crosspack disabled) over the exact row.  Exact (m, n, k)
        # outranks any same-distance donor.
        key = (d, 0 if (e["m"], e["n"], e["k"]) == (m, n, k) else 1, ds)
        if best_d is None or key < best_d:
            best, best_d = e, key
    out = None
    if best is not None:
        out = dict(best)
        if (best["m"], best["n"], best["k"]) != (m, n, k):
            # an exact-shape row that won through the pool (tunnel row
            # with no onchip donor) is still EXACT evidence, not a
            # donor prediction — the tag gates bf16-crosspack/pack
            # acceptance on exactness
            out["predicted_from"] = (best["m"], best["n"], best["k"])
    with _lock:
        if _table_gen == gen0:  # table unchanged while we computed
            _predict_cache[ck] = out
    return out


def save_entry(entry: Dict, kind: Optional[str] = None) -> str:
    """Merge one tuned entry into the device's parameter file."""
    kind = kind or device_kind()
    table = _load(kind)
    with _lock:
        table[_key(entry["m"], entry["n"], entry["k"], entry["dtype"],
                   entry.get("stack_size", 0))] = entry
        os.makedirs(_params_dir(), exist_ok=True)
        path = params_path(kind)
        with open(path, "w") as f:
            json.dump(sorted(table.values(), key=lambda e: (e["m"], e["n"], e["k"])),
                      f, indent=1)
        # after the insert, under the lock: a concurrent predict() must
        # not be able to re-memoize a pre-insert prediction (the bumped
        # generation invalidates any in-flight computation)
        global _table_gen
        _table_gen += 1
        _predict_cache.clear()
    return path


def delete_entry(m: int, n: int, k: int, dtype, stack_size,
                 kind: Optional[str] = None) -> bool:
    """Remove one row from the device's parameter file (the tune
    store's demotion path — `save_entry`'s mirror).  Returns whether a
    row was actually removed; the generation bumps either way only on a
    real removal."""
    kind = kind or device_kind()
    table = _load(kind)
    key = _key(m, n, k, dtype, stack_size)
    with _lock:
        if key not in table:
            return False
        del table[key]
        os.makedirs(_params_dir(), exist_ok=True)
        path = params_path(kind)
        with open(path, "w") as f:
            json.dump(sorted(table.values(),
                             key=lambda e: (e["m"], e["n"], e["k"])),
                      f, indent=1)
        global _table_gen
        _table_gen += 1
        _predict_cache.clear()
    return True
