"""Kernel autotuner.

Analog of `src/acc/libsmm_acc/tune/` (tune_setup/submit/collect/merge)
collapsed into one loop: for a given (m, n, k, dtype), time every
candidate launch config of the stack kernel — the Pallas kernel at each
grouping R plus the XLA gather/segment-sum path — and write the winner
into the device parameter table (`dbcsr_tpu.acc.params`), which
dispatch consults.  The reference's tuning space (algorithm family,
tile_m/n, w, v, threads, grouping, minblocks per `kernels/smm_acc.py`)
collapses to {driver, grouping} because XLA/Mosaic own the tiling.

CLI:  python -m dbcsr_tpu.acc.tune M N K [dtype_enum] [stack_size] [nrep]
"""

from __future__ import annotations

import sys
import time

import numpy as np

from dbcsr_tpu.acc import params as params_mod
from dbcsr_tpu.core.kinds import dtype_of
from dbcsr_tpu.utils.compat import enable_x64 as _enable_x64


def _measure_env() -> str:
    """Measurement provenance stamped on every saved row (VERDICT r4
    item 6): the REAL backend platform — never the dispatch seam —
    because this records where the number came from.  "tunnel" is
    reserved for rows known to be tunnel-latency-bound (tagged by
    maintenance, e.g. the legacy S=30k sweep); dispatch prefers
    "onchip" rows whenever one exists for the candidate set."""
    import jax

    return "onchip" if jax.devices()[0].platform == "tpu" else "cpu"


def _time_config(fn, nrep: int) -> float:
    """Times include a data-dependent 8-byte fetch of the result —
    `block_until_ready` alone can return before the device work ran on
    remote-tunnel backends (the axon illusion, PERF_NOTES.md), which
    produced the bogus round-2 table this replaces."""

    from dbcsr_tpu.utils.sync import fetch_fence

    fetch_fence(fn())  # compile/warm
    best = float("inf")
    for _ in range(nrep):
        t0 = time.perf_counter()
        fetch_fence(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def tune_smm(m: int, n: int, k: int, dtype_enum: int = 1,
             stack_size: int = 30000, nrep: int = 3, out=print, seed=7,
             persist: bool = True, candidates_out=None):
    """Tune one (m, n, k, dtype); returns (and, with ``persist``, saves
    into the device table) the best entry.

    ``persist=False`` runs the identical candidate sweep without
    touching the parameter table — the online tuner's trial mode
    (`dbcsr_tpu.tune.trials`), where the PROMOTION STORE decides what
    lands.  ``candidates_out``, when a list, receives every timed
    candidate dict (driver/grouping/precision/gflops) so the caller can
    re-rank them under its own policy (breaker-aware winner selection).
    """
    import jax
    import jax.numpy as jnp

    # f64 must tune as true f64; scoped so a f32-only host application
    # calling tune_smm() keeps its global x64 setting
    with _enable_x64(True):
        return _tune_smm_x64(m, n, k, dtype_enum, stack_size, nrep, out, seed,
                             jax, jnp, persist, candidates_out)


class _Candidates(list):
    """Candidate list that persists the best row after every append: a
    later candidate that crashes the PROCESS (a Mosaic fatal error
    aborts before Python sees an exception) must not lose the timings
    already measured — the sweep's resumability contract.  With
    ``persist=False`` (trial mode) nothing is written; the caller owns
    promotion."""

    def __init__(self, m, n, k, dtype, stack_size, out, persist=True,
                 mirror=None):
        super().__init__()
        self._row = {"m": m, "n": n, "k": k, "dtype": np.dtype(dtype).name,
                     "stack_size": stack_size, "env": _measure_env()}
        self._out = out
        self._best = None
        self._persist = persist
        self._mirror = mirror

    def append(self, cand) -> None:
        super().append(cand)
        if self._mirror is not None:
            self._mirror.append(dict(cand))
        if self._best is None or cand["gflops"] > self._best:
            self._best = cand["gflops"]
            if not self._persist:
                return
            entry = {**self._row, **cand,
                     "gflops": round(cand["gflops"], 2)}
            try:
                params_mod.save_entry(entry)
            except OSError as exc:
                self._out(f"  (best-so-far persist failed: {exc})")


def _tune_smm_x64(m, n, k, dtype_enum, stack_size, nrep, out, seed, jax, jnp,
                  persist=True, candidates_out=None):

    from dbcsr_tpu.acc import pallas_smm
    from dbcsr_tpu.acc.smm import _process_stack_xla, _process_stack_xla_flat
    from dbcsr_tpu.utils.rounding import bucket_size

    dtype = dtype_of(dtype_enum)
    rng = np.random.default_rng(seed)
    na = nb = max(stack_size // 16, 2)
    nc = max(stack_size // 8, 1)
    a = jnp.asarray(rng.standard_normal((na, m, k)).astype(dtype))
    b = jnp.asarray(rng.standard_normal((nb, k, n)).astype(dtype))
    ai = rng.integers(0, na - 1, stack_size).astype(np.int32)
    bi = rng.integers(0, nb - 1, stack_size).astype(np.int32)
    ci = np.sort(rng.integers(0, nc, stack_size)).astype(np.int32)
    flops = 2.0 * m * n * k * stack_size
    candidates = _Candidates(m, n, k, dtype, stack_size, out,
                             persist=persist, mirror=candidates_out)

    # XLA gather/segment-sum path (always available)
    chunk = bucket_size(min(stack_size, 30000))
    nchunks = -(-stack_size // chunk)
    from dbcsr_tpu.acc.smm import pad_stack

    pai, pbi, pci = pad_stack(ai, bi, ci, nchunks * chunk, nc)
    xla_args = (
        jnp.asarray(pai.reshape(nchunks, chunk)),
        jnp.asarray(pbi.reshape(nchunks, chunk)),
        jnp.asarray(pci.reshape(nchunks, chunk)),
    )

    def run_xla():
        return _process_stack_xla(
            jnp.zeros((nc, m, n), dtype), a, b, *xla_args,
            jnp.asarray(1.0, dtype),
        )

    t = _time_config(run_xla, nrep)
    candidates.append({"driver": "xla", "grouping": None, "gflops": flops / t / 1e9})
    out(f"  xla: {flops / t / 1e9:.1f} GFLOP/s")

    # flat-gather layout variant (lane-packed (N, m*k) rows; see
    # _process_stack_xla_flat) — the main alternative for dtypes the
    # Pallas kernel doesn't take (f64/complex)
    def run_xla_flat():
        return _process_stack_xla_flat(
            jnp.zeros((nc, m, n), dtype), a, b, *xla_args,
            jnp.asarray(1.0, dtype),
        )

    t = _time_config(run_xla_flat, nrep)
    candidates.append({"driver": "xla_flat", "grouping": None, "gflops": flops / t / 1e9})
    out(f"  xla_flat: {flops / t / 1e9:.1f} GFLOP/s")

    # demoted-precision candidates (acc.precision specs on the xla
    # driver): a winner stamps the table's "precision" column, which
    # adaptive dispatch consults per (m,n,k,dtype) cell — runtime
    # certification stays with the ABFT probes, the tuner only ranks
    # throughput
    prec_specs = []
    if np.dtype(dtype) == np.float64:
        prec_specs = [("f32c", ("float32", True)),
                      ("f32", ("float32", False))]
    elif np.dtype(dtype) == np.float32:
        prec_specs = [("bf16", ("bfloat16", False))]
    for col, spec in prec_specs:
        def run_xla_prec(spec=spec):
            return _process_stack_xla(
                jnp.zeros((nc, m, n), dtype), a, b, *xla_args,
                jnp.asarray(1.0, dtype), prec=spec,
            )

        tag = f"xla {col}{'+comp' if spec[1] else ''}"
        try:
            t = _time_config(run_xla_prec, nrep)
        except Exception as exc:
            out(f"  {tag}: failed ({type(exc).__name__})")
            continue
        candidates.append({"driver": "xla", "grouping": None,
                           "precision": col,
                           "gflops": flops / t / 1e9})
        out(f"  {tag}: {flops / t / 1e9:.1f} GFLOP/s")

    # native host stack driver (CPU backends; the reference's tuned CPU
    # SMM library is likewise a per-shape dispatch candidate,
    # dbcsr_mm_hostdrv.F:90) — auto dispatch takes a tuned "host" row
    # via prepare_stack when the native library is available
    from dbcsr_tpu.acc.smm import _host_smm_available

    if _host_smm_available(dtype):
        from dbcsr_tpu import native

        a_np = np.asarray(a)
        b_np = np.asarray(b)

        def run_host():
            c_np = np.zeros((nc, m, n), dtype)
            ok = native.host_smm(c_np, a_np, b_np, ai, bi, ci, 1.0)
            assert ok
            return jnp.asarray(c_np)

        t = _time_config(run_host, nrep)
        candidates.append(
            {"driver": "host", "grouping": None, "gflops": flops / t / 1e9}
        )
        out(f"  host: {flops / t / 1e9:.1f} GFLOP/s")

    # R-tiled grouped layout (k-merged dots; see _process_stack_xla_group)
    from dbcsr_tpu.acc.smm import _process_stack_xla_group, build_group_tiles

    a_padded = jnp.concatenate([a, jnp.zeros((1, m, k), dtype)])
    b_padded = jnp.concatenate([b, jnp.zeros((1, k, n), dtype)])
    for r0 in (4, 8, 16):
        # chunking mirrors prepare_stack's production choice
        ga, gb, gc = build_group_tiles(
            ci, ai, bi, r0, na, nb, nc, max(256, stack_size // r0)
        )
        grp_args = (jnp.asarray(ga), jnp.asarray(gb), jnp.asarray(gc))

        def run_group(grp_args=grp_args):
            return _process_stack_xla_group(
                jnp.zeros((nc, m, n), dtype), a_padded, b_padded, *grp_args,
                jnp.asarray(1.0, dtype),
            )

        try:
            t = _time_config(run_group, nrep)
        except Exception as exc:
            out(f"  xla_group r0={r0}: failed ({type(exc).__name__})")
            continue
        candidates.append(
            {"driver": "xla_group", "grouping": None, "r0": r0,
             "gflops": flops / t / 1e9}
        )
        out(f"  xla_group r0={r0}: {flops / t / 1e9:.1f} GFLOP/s")

    # off-TPU, Pallas runs in INTERPRET mode (~1000x): timing it at
    # production stack sizes burns the whole sweep budget producing
    # numbers that can never win on this device.  Tiny stacks (tests)
    # still exercise the candidates for coverage.
    pallas_worth_timing = (
        jax.devices()[0].platform == "tpu" or stack_size <= 2000
    )
    if pallas_worth_timing and pallas_smm.supports(
            jnp.zeros((1, m, n), dtype), a, b):
        zero_a, zero_b = na - 1, nb - 1
        a = a.at[zero_a].set(0)
        b = b.at[zero_b].set(0)
        for r in (1, 2, 4, 8):
            ai2, bi2, ci2, _ = pallas_smm.build_grouped_stack(
                ci, ai, bi, zero_a, zero_b, grouping=r
            )
            # time exactly the launch sequence dispatch would run
            # (shared prep: flatten, SMEM chunking, bucket padding)
            launches = [
                tuple(map(jnp.asarray, lc))
                for lc in pallas_smm.prepare_launches(ai2, bi2, ci2, r,
                                                      zero_a, zero_b)
            ]
            alpha = jnp.asarray([[1.0]], jnp.float32)
            interpret = jax.devices()[0].platform != "tpu"

            # both kernel variants: looped R small dots, and the
            # k-merged single (R*k,m)^T x (R*k,n) dot per step
            for variant in ((None, "kmerge") if r > 1 else (None,)):
                def run_pallas(r=r, launches=launches, variant=variant):
                    # x64 off during trace: see process_stack_pallas
                    # (Mosaic cannot legalize i64 scalar-prefetch loads)
                    c = jnp.zeros((nc, m, n), dtype)
                    with _enable_x64(False):
                        for dai2, dbi2, dci2 in launches:
                            c = pallas_smm._pallas_process(
                                c, a, b, dai2, dbi2, dci2,
                                alpha, r_grp=r, interpret=interpret,
                                kmerge=(variant == "kmerge"),
                            )
                    return c

                tag = f"pallas R={r}" + (" kmerge" if variant else "")
                try:
                    t = _time_config(run_pallas, nrep)
                except Exception as exc:  # config failed to compile/run
                    out(f"  {tag}: failed ({type(exc).__name__})")
                    continue
                cand = {"driver": "pallas", "grouping": r,
                        "gflops": flops / t / 1e9}
                if variant:
                    cand["variant"] = variant
                candidates.append(cand)
                out(f"  {tag}: {flops / t / 1e9:.1f} GFLOP/s")

        # cross-packed P x R MXU tiling (block-diagonal lane packing);
        # sweep around the geometric default — the stream-count cap is
        # a guess that only on-chip timing can settle
        p0, r0c = pallas_smm.choose_pack(m, n, k)
        pmax = max(1, min(8, 128 // max(m, n)))
        rmax = max(1, min(8, 128 // k))
        packs = {(p0, r0c), (pmax, rmax), (p0, max(1, r0c // 2)),
                 (max(2, p0 // 2), r0c)}
        # only geometry-legal candidates: dispatch clamps tuned packs to
        # the 128-tile bound, so a winner beyond it would be recorded
        # but never actually run
        packs = {(P, R) for P, R in packs if P <= pmax and R <= rmax}
        a_t = jnp.swapaxes(a, 1, 2)
        interpret = jax.devices()[0].platform != "tpu"
        for P, R in sorted(packs):
            if P <= 1:
                continue
            # prep (lane dealing, upload) runs once, like the cached
            # plan in production dispatch; only device work is timed
            cross = pallas_smm.prepare_crosspack_launches(
                ci, ai, bi, zero_a, zero_b, P, R
            )
            if cross is None:
                continue
            dev_launches = [
                (jnp.asarray(lc["ai"]), jnp.asarray(lc["bi"]),
                 jnp.asarray(lc["cg"]), jnp.asarray(lc["cl"]),
                 jnp.asarray(pallas_smm.lane_scatter_index(lc["lane_c"])),
                 [len(c) for c in lc["lane_c"]], lc["nc_out"])
                for lc in cross
            ]
            alpha32 = jnp.asarray([[1.0]], jnp.float32)

            variants = [("crosspack", pallas_smm._pallas_crosspack)]
            if pallas_smm.supports_vmem_resident(a, b):
                variants.append(
                    ("crosspack_vmem", pallas_smm._pallas_crosspack_vmem)
                )
            for vname, vfn in variants:
                def run_v(P=P, R=R, dev_launches=dev_launches, vfn=vfn):
                    c = jnp.zeros((nc, m, n), dtype)
                    with _enable_x64(False):
                        for dai, dbi, dcg, dcl, sidx, lens, nc_out in dev_launches:
                            outs = vfn(
                                c, a_t, b, dai, dbi, dcg, dcl, alpha32,
                                P=P, R=R, nc_out=nc_out, interpret=interpret,
                            )
                            c = pallas_smm.scatter_lane_outputs(
                                c, outs, lens, sidx
                            )
                    return c

                tag = f"pallas {vname} P={P} R={R}"
                try:
                    t = _time_config(run_v, nrep)
                except Exception as exc:
                    out(f"  {tag}: failed ({type(exc).__name__})")
                    continue
                candidates.append(
                    {"driver": "pallas", "variant": vname,
                     "grouping": R, "pack_p": P, "gflops": flops / t / 1e9}
                )
                out(f"  {tag}: {flops / t / 1e9:.1f} GFLOP/s")

    best = max(candidates, key=lambda c: c["gflops"])
    entry = {
        "m": m, "n": n, "k": k, "dtype": np.dtype(dtype).name,
        "stack_size": stack_size, "env": _measure_env(), **best,
        "gflops": round(best["gflops"], 2),
    }
    if persist:
        path = params_mod.save_entry(entry)
        out(f"best: {entry['driver']} grouping={entry['grouping']} "
            f"{entry['gflops']} GFLOP/s -> {path}")
    else:
        out(f"best (trial, not persisted): {entry['driver']} "
            f"grouping={entry['grouping']} {entry['gflops']} GFLOP/s")
    return entry


def main(argv=None):
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # the axon sitecustomize force-sets jax_platforms="axon,cpu" at
        # interpreter start, overriding the env var — honor an explicit
        # CPU request (the CPU-device-kind tuning sweep) here, or the
        # process hangs connecting to a wedged tunnel
        import jax

        jax.config.update("jax_platforms", "cpu")
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 3:
        print(__doc__)
        return 1
    m, n, k = (int(x) for x in argv[:3])
    dtype_enum = int(argv[3]) if len(argv) > 3 and int(argv[3]) else 1
    stack_size = int(argv[4]) if len(argv) > 4 and int(argv[4]) else 30000
    nrep = int(argv[5]) if len(argv) > 5 and int(argv[5]) else 3
    tune_smm(m, n, k, dtype_enum, stack_size, nrep)
    return 0


if __name__ == "__main__":
    sys.exit(main())
