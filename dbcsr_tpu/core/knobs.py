"""Checked registry of every non-config ``DBCSR_TPU_*`` environment knob.

Pure data, import-free: `tools/lint` parses this file with stdlib
``ast`` (never importing dbcsr_tpu), so the registry stays checkable
even when jax is broken.  The static analyzer enforces two directions:

* every literal ``DBCSR_TPU_*`` string in source must be either a
  `core/config.py` Config field knob (``DBCSR_TPU_<FIELD>``, validated
  by `Config.validate`) or an entry here (rule ``knob-registry``);
* every entry here must have a row in the generated `docs/knobs.md`
  (regenerate with ``python -m tools.lint --gen-docs``) — the docs
  table is EMITTED from this registry plus the Config fields, so the
  three previously hand-kept lists cannot drift again.

Each entry: ``owner`` (the module that reads it — informational) and
``doc`` (the one-line operator-facing description that lands in
docs/knobs.md).  Keep entries alphabetical.
"""

KNOBS = {
    "DBCSR_TPU_ATTRIBUTION": {
        "owner": "obs/attribution.py",
        "doc": "=0 disables per-request cost attribution / tenant usage "
               "metering (every hook becomes an early return).",
    },
    "DBCSR_TPU_ATTRIBUTION_N": {
        "owner": "obs/attribution.py",
        "doc": "attribution ledger capacity (per-request rows, LRU; "
               "default 1024).",
    },
    "DBCSR_TPU_ATTRIBUTION_TENANTS": {
        "owner": "obs/attribution.py",
        "doc": "per-tenant usage rollup row cap (default 512); evicted "
               "rows fold into the '(evicted)' aggregate so conservation "
               "survives tenant churn.",
    },
    "DBCSR_TPU_BENCH_CPU_DRIVER": {
        "owner": "bench.py",
        "doc": "stack driver forced when a bench run lands on the CPU "
               "backend instead of a real TPU (default: config mm_driver).",
    },
    "DBCSR_TPU_BENCH_DTYPE": {
        "owner": "bench.py",
        "doc": "dtype of the bench.py north-star multiply "
               "(f64/f32/bf16; default f64).",
    },
    "DBCSR_TPU_BENCH_FLIGHT": {
        "owner": "bench.py",
        "doc": "path to write the bench run's flight-recorder dump.",
    },
    "DBCSR_TPU_BENCH_METRICS": {
        "owner": "bench.py",
        "doc": "path to write the bench run's Prometheus metrics snapshot.",
    },
    "DBCSR_TPU_BENCH_NREP": {
        "owner": "bench.py",
        "doc": "repetitions of the bench north-star multiply (median "
               "reported).",
    },
    "DBCSR_TPU_BENCH_PROBE_TIMEOUT": {
        "owner": "bench.py",
        "doc": "seconds before the TPU availability probe is declared "
               "wedged (watchdog deadline).",
    },
    "DBCSR_TPU_BENCH_TIMINGS": {
        "owner": "bench.py",
        "doc": "emit the bench per-phase timing report (1 = stdout, "
               "path = file).",
    },
    "DBCSR_TPU_BREAKER_COOLDOWN_S": {
        "owner": "resilience/breaker.py",
        "doc": "circuit-breaker open -> half-open cooldown seconds "
               "(doubles on failed half-open trials).",
    },
    "DBCSR_TPU_BREAKER_THRESHOLD": {
        "owner": "resilience/breaker.py",
        "doc": "consecutive classified failures before a per-(driver, "
               "shape) breaker opens.",
    },
    "DBCSR_TPU_CHAIN_BLOCKS": {
        "owner": "bench.py",
        "doc": "chained-workload bench (--chain): blocks per matrix "
               "dimension.",
    },
    "DBCSR_TPU_CHAIN_FILTER_EPS": {
        "owner": "bench.py",
        "doc": "chained-workload bench: inter-iteration filter threshold.",
    },
    "DBCSR_TPU_CHAIN_ITERS": {
        "owner": "bench.py",
        "doc": "chained-workload bench: iteration count.",
    },
    "DBCSR_TPU_CHANGEPOINT": {
        "owner": "obs/changepoint.py",
        "doc": "=0 disables CUSUM change-point detection over the "
               "telemetry store (default on).",
    },
    "DBCSR_TPU_CHECK_OUTPUTS": {
        "owner": "acc/smm.py",
        "doc": "=1 forces the per-launch finite-output check (always on "
               "under fault injection).",
    },
    "DBCSR_TPU_CP_H": {
        "owner": "obs/changepoint.py",
        "doc": "CUSUM decision threshold in baseline sigmas (default 8): "
               "a series has shifted when the accumulator crosses it.",
    },
    "DBCSR_TPU_CP_REF_N": {
        "owner": "obs/changepoint.py",
        "doc": "reference-window samples frozen into a change-point "
               "baseline (default 12).",
    },
    "DBCSR_TPU_DENSE_CARVE": {
        "owner": "mm/multiply.py",
        "doc": "dense-path operand carve lowering: 'gather' (default) or "
               "'reshape'; read outside jit and threaded as a static arg.",
    },
    "DBCSR_TPU_DENSE_PROFILE": {
        "owner": "mm/multiply.py",
        "doc": "=1 emits the dense-path per-phase timing breakdown.",
    },
    "DBCSR_TPU_EVENTS": {
        "owner": "obs/events.py",
        "doc": "event bus control: '0'/'off' disables the bus, a path "
               "enables the JSONL sink.",
    },
    "DBCSR_TPU_EVENTS_N": {
        "owner": "obs/events.py",
        "doc": "bounded event-bus ring capacity (records).",
    },
    "DBCSR_TPU_FAULTS": {
        "owner": "resilience/faults.py",
        "doc": "fault-injection DSL: 'target:kind[@stack>=N][,prob=]"
               "[,seed=][,times=][,sleep=]', ';'-separated "
               "(docs/resilience.md).",
    },
    "DBCSR_TPU_FLEET_BACKOFF_S": {
        "owner": "serve/router.py",
        "doc": "fleet router base retry backoff seconds (doubles per "
               "attempt; default 0.05).",
    },
    "DBCSR_TPU_FLEET_CACHE_TIMEOUT_S": {
        "owner": "serve/product_cache.py",
        "doc": "fleet-shared product-cache tier: per-peer lookup "
               "timeout seconds (default 0.3); a slow/down peer costs "
               "one timeout, then the cool-off degrades lookups to "
               "local-only.",
    },
    "DBCSR_TPU_FLEET_HEARTBEAT_TIMEOUT_S": {
        "owner": "serve/router.py",
        "doc": "fleet router heartbeat probe timeout seconds "
               "(default 2).",
    },
    "DBCSR_TPU_FLEET_PEER_COOLOFF_S": {
        "owner": "serve/product_cache.py",
        "doc": "seconds a failed fleet cache peer is skipped before "
               "being probed again (default 30).",
    },
    "DBCSR_TPU_FLEET_PEERS": {
        "owner": "serve/product_cache.py",
        "doc": "comma-separated sibling-worker obs URLs for the "
               "fleet-shared product-cache tier (set per worker by "
               "serve.fleet; empty = local-only).",
    },
    "DBCSR_TPU_FLEET_RETRIES": {
        "owner": "serve/router.py",
        "doc": "routed submit attempts per request before the router "
               "marks the worker suspect and raises (default 3).",
    },
    "DBCSR_TPU_FLEET_SUBMIT_TIMEOUT_S": {
        "owner": "serve/router.py",
        "doc": "per-attempt HTTP timeout of a routed submit, seconds "
               "(default 10).",
    },
    "DBCSR_TPU_FLEET_SUSPECT_AFTER": {
        "owner": "serve/router.py",
        "doc": "consecutive missed heartbeats before a SUSPECT worker "
               "is declared DOWN (default 3).",
    },
    "DBCSR_TPU_FLIGHT_DUMP": {
        "owner": "obs/flight.py",
        "doc": "path the flight recorder dumps to at process exit.",
    },
    "DBCSR_TPU_FLIGHT_N": {
        "owner": "obs/flight.py",
        "doc": "flight-recorder ring capacity (per-product records).",
    },
    "DBCSR_TPU_HEALTH_BREAKER_CRITICAL_N": {
        "owner": "obs/health.py",
        "doc": "open breakers before the drivers component degrades to "
               "CRITICAL.",
    },
    "DBCSR_TPU_HEALTH_COLLAPSE_RATIO": {
        "owner": "obs/health.py",
        "doc": "roofline-collapse detector: fraction of the baseline "
               "roofline below which perf health degrades.",
    },
    "DBCSR_TPU_HEALTH_FALLBACK_RATE": {
        "owner": "obs/health.py",
        "doc": "driver-fallback rate per window that counts as a "
               "fallback storm.",
    },
    "DBCSR_TPU_HEALTH_LATENCY_RELTOL": {
        "owner": "obs/health.py",
        "doc": "relative dispatch-latency spike tolerance of the health "
               "model.",
    },
    "DBCSR_TPU_HEALTH_POOL_EVICTIONS": {
        "owner": "obs/health.py",
        "doc": "pool evictions per window that count as pool thrash.",
    },
    "DBCSR_TPU_HEALTH_RECOMPILE_RATE": {
        "owner": "obs/health.py",
        "doc": "jit recompiles per window that count as a recompile storm.",
    },
    "DBCSR_TPU_HEALTH_SDC_CRITICAL": {
        "owner": "obs/health.py",
        "doc": "ABFT/SDC detections per window before integrity health "
               "goes CRITICAL.",
    },
    "DBCSR_TPU_HEALTH_SHED_RATE": {
        "owner": "obs/health.py",
        "doc": "serving-plane shed fraction per window that counts as a "
               "shed storm.",
    },
    "DBCSR_TPU_HEALTH_WINDOW": {
        "owner": "obs/health.py",
        "doc": "sliding-window length (samples) of the health anomaly "
               "detectors.",
    },
    "DBCSR_TPU_ICI_GBS": {
        "owner": "obs/costmodel.py",
        "doc": "inter-chip-interconnect GB/s override for the comm cost "
               "model.",
    },
    "DBCSR_TPU_INCIDENTS": {
        "owner": "obs/incidents.py",
        "doc": "incident-bundle directory ('0' keeps bundles in memory "
               "only; default 'incidents/' under the working directory).",
    },
    "DBCSR_TPU_INCIDENT_INTERVAL_S": {
        "owner": "obs/incidents.py",
        "doc": "minimum seconds between captured incident bundles "
               "(default 60).",
    },
    "DBCSR_TPU_INCIDENT_N": {
        "owner": "obs/incidents.py",
        "doc": "maximum incident bundles captured per process "
               "(default 8).",
    },
    "DBCSR_TPU_LOADTEST_SEED": {
        "owner": "tools/loadtest.py",
        "doc": "default replay seed for the load harness (default 0): "
               "same trace + seed => bitwise-identical request stream "
               "(docs/loadtest.md).",
    },
    "DBCSR_TPU_LOADTEST_WAIT_S": {
        "owner": "tools/loadtest.py",
        "doc": "per-ticket completion wait during replay legs, seconds "
               "(default 120).",
    },
    "DBCSR_TPU_LOCKCHECK": {
        "owner": "utils/lockcheck.py",
        "doc": "=1 enables the dynamic lock-order checker: per-thread "
               "acquisition order across the instrumented locks is "
               "recorded and an order inversion raises LockOrderError "
               "(docs/static_analysis.md).",
    },
    "DBCSR_TPU_MP_PLATFORM": {
        "owner": "perf/driver.py",
        "doc": "jax_platforms value handed to spawned multi-process perf "
               "workers (default cpu).",
    },
    "DBCSR_TPU_MULTIHOST_TIMEOUT_S": {
        "owner": "parallel/multihost.py",
        "doc": "multihost world-join timeout seconds before degraded "
               "single-host fallback.",
    },
    "DBCSR_TPU_NATIVE": {
        "owner": "native/__init__.py",
        "doc": "=0 disables loading the native C++ host stack library.",
    },
    "DBCSR_TPU_OBS_HOST": {
        "owner": "obs/server.py",
        "doc": "observability HTTP server bind host.",
    },
    "DBCSR_TPU_OBS_PORT": {
        "owner": "obs/server.py",
        "doc": "observability HTTP server port (0 = ephemeral).",
    },
    "DBCSR_TPU_PARAMS_DIR": {
        "owner": "acc/params.py",
        "doc": "directory holding autotuned kernel parameter tables.",
    },
    "DBCSR_TPU_PEAK_GBS": {
        "owner": "obs/costmodel.py",
        "doc": "device HBM GB/s override for the roofline model.",
    },
    "DBCSR_TPU_PEAK_GFLOPS": {
        "owner": "obs/costmodel.py",
        "doc": "device peak GFLOP/s override for the roofline model.",
    },
    "DBCSR_TPU_PERF_DEVICES": {
        "owner": "perf/driver.py",
        "doc": "device count the multi-process perf driver spawns.",
    },
    "DBCSR_TPU_POOL": {
        "owner": "core/mempool.py",
        "doc": "=0/false/no disables the device memory pool (default on).",
    },
    "DBCSR_TPU_PROFILE": {
        "owner": "obs/profiler.py",
        "doc": "continuous profile baseline: =0 disables the fold, a "
               "path persists sealed epochs as per-process JSONL shards "
               "(default: on, in-memory ring only).",
    },
    "DBCSR_TPU_PROFILE_EPOCH_N": {
        "owner": "obs/profiler.py",
        "doc": "multiplies folded per profile-baseline epoch before it "
               "is sealed and generation-tagged (default 64).",
    },
    "DBCSR_TPU_POOL_BYTES": {
        "owner": "core/mempool.py",
        "doc": "device memory pool budget in bytes (evicts LRU beyond it).",
    },
    "DBCSR_TPU_PREC_BENCH_BS": {
        "owner": "tools/precision_bench.py",
        "doc": "precision bench: block size.",
    },
    "DBCSR_TPU_PREC_BENCH_M": {
        "owner": "tools/precision_bench.py",
        "doc": "precision bench: matrix dimension (blocks).",
    },
    "DBCSR_TPU_PREC_BENCH_OCC": {
        "owner": "tools/precision_bench.py",
        "doc": "precision bench: block occupancy.",
    },
    "DBCSR_TPU_PREC_BENCH_REPS": {
        "owner": "tools/precision_bench.py",
        "doc": "precision bench: repetitions per case.",
    },
    "DBCSR_TPU_RCA": {
        "owner": "obs/rca.py",
        "doc": "=0 disables the change ledger + causal ranking "
               "(default on).",
    },
    "DBCSR_TPU_RCA_LEDGER_N": {
        "owner": "obs/rca.py",
        "doc": "change-ledger ring capacity (default 256 entries).",
    },
    "DBCSR_TPU_RCA_WINDOW_S": {
        "owner": "obs/rca.py",
        "doc": "attribution window in seconds: how far before an "
               "estimated shift a change is still a candidate cause "
               "(default 600).",
    },
    "DBCSR_TPU_ROOFLINE": {
        "owner": "obs/costmodel.py",
        "doc": "JSON peak-table override for the roofline model "
               "(per-device-kind peaks).",
    },
    "DBCSR_TPU_SERVE_JOURNAL": {
        "owner": "serve/engine.py",
        "doc": "serving-plane request journal path (drain/restart "
               "recovery, docs/serving.md).",
    },
    "DBCSR_TPU_SERVE_TENANT_MAX": {
        "owner": "serve/engine.py",
        "doc": "cap on the engine's per-tenant latency/outcome "
               "accounting rows (least recently active evicted; "
               "default 256).",
    },
    "DBCSR_TPU_SERVE_TENANT_TTL_S": {
        "owner": "serve/engine.py",
        "doc": "idle seconds before a tenant's engine accounting rows "
               "(rolling latency window, outcome tallies) expire "
               "(default 3600).",
    },
    "DBCSR_TPU_SERVE_WAL": {
        "owner": "serve/engine.py",
        "doc": "=1 journals every admitted by-name request to "
               "DBCSR_TPU_SERVE_JOURNAL at SUBMIT time (write-ahead) "
               "instead of only at drain, tombstoned at its terminal "
               "state — what makes a SIGKILLed fleet worker's queue "
               "replayable on a peer (docs/serving.md § fleet).",
    },
    "DBCSR_TPU_SLO_CRITICAL_BURN": {
        "owner": "obs/slo.py",
        "doc": "burn-rate multiple at which an SLO objective goes "
               "CRITICAL.",
    },
    "DBCSR_TPU_SLO_LONG_S": {
        "owner": "obs/slo.py",
        "doc": "long SLO burn window seconds.",
    },
    "DBCSR_TPU_SLO_ROOFLINE_BUDGET": {
        "owner": "obs/slo.py",
        "doc": "error budget (fraction of samples) for the roofline-floor "
               "objective.",
    },
    "DBCSR_TPU_SLO_ROOFLINE_FLOOR": {
        "owner": "obs/slo.py",
        "doc": "roofline fraction below which a sample burns the "
               "roofline objective.",
    },
    "DBCSR_TPU_SLO_SDC_BUDGET": {
        "owner": "obs/slo.py",
        "doc": "error budget for silent-data-corruption detections.",
    },
    "DBCSR_TPU_SLO_SERVE_ERR_BUDGET": {
        "owner": "obs/slo.py",
        "doc": "error budget for serving-plane request failures.",
    },
    "DBCSR_TPU_SLO_SERVE_P95_BUDGET": {
        "owner": "obs/slo.py",
        "doc": "error budget for serve-latency p95 breaches.",
    },
    "DBCSR_TPU_SLO_SERVE_P95_MS": {
        "owner": "obs/slo.py",
        "doc": "serve-latency p95 objective in milliseconds.",
    },
    "DBCSR_TPU_SLO_SHORT_S": {
        "owner": "obs/slo.py",
        "doc": "short SLO burn window seconds.",
    },
    "DBCSR_TPU_SYNC_TIMING": {
        "owner": "core/stats.py",
        "doc": "=1 enables synchronized per-stack/per-tick timing (the "
               "documented sync seam; adds device fences to hot paths).",
    },
    "DBCSR_TPU_TRACE": {
        "owner": "obs/tracer.py",
        "doc": "trace control: path writes the Perfetto/Chrome JSON "
               "trace, '1' enables in-memory tracing.",
    },
    "DBCSR_TPU_TUNE": {
        "owner": "tune/service.py",
        "doc": "=1 starts the online autotuning service alongside the "
               "serving plane (serve engine start/shutdown own its "
               "lifecycle); unset/0 leaves tuning manual "
               "(docs/autotuning.md).",
    },
    "DBCSR_TPU_TUNE_BUDGET_BYTES": {
        "owner": "tune/trials.py",
        "doc": "per-trial operand byte budget: the trial stack size is "
               "clamped so staged A/B/C temporaries stay under it "
               "(default 64 MiB).",
    },
    "DBCSR_TPU_TUNE_BUDGET_S": {
        "owner": "tune/trials.py",
        "doc": "wall budget for one tuning trial's candidate sweep, "
               "seconds: checked after every timed leg (the sweep "
               "stops, keeping the legs already measured) and doubling "
               "as the tune_trial watchdog deadline.",
    },
    "DBCSR_TPU_TUNE_DEMOTE_RATIO": {
        "owner": "tune/store.py",
        "doc": "demotion-on-regression judge: a promoted row is demoted "
               "when its driver's live roofline fraction falls below "
               "this fraction of the at-promotion value (default 0.5).",
    },
    "DBCSR_TPU_TUNE_FLOOR": {
        "owner": "tune/miner.py",
        "doc": "per-device roofline-fraction floor below which a live "
               "(driver, mnk, dtype) cell counts as underperforming "
               "(default 0.25).",
    },
    "DBCSR_TPU_TUNE_INTERVAL_S": {
        "owner": "tune/service.py",
        "doc": "background tuner cycle cadence, seconds (default 60).",
    },
    "DBCSR_TPU_TUNE_MARGIN": {
        "owner": "tune/service.py",
        "doc": "minimum relative GFLOP/s uplift over the incumbent "
               "row/prediction before a trial winner is promoted "
               "(default 0.05).",
    },
    "DBCSR_TPU_TUNE_MAX_CELLS": {
        "owner": "tune/miner.py",
        "doc": "bound on the mined candidate-cell queue per cycle "
               "(default 32).",
    },
    "DBCSR_TPU_TUNE_NREP": {
        "owner": "tune/trials.py",
        "doc": "timing repetitions per candidate leg inside a tuning "
               "trial (default 2).",
    },
    "DBCSR_TPU_TS": {
        "owner": "obs/timeseries.py",
        "doc": "telemetry history store: '0'/'off' disables, a path "
               "enables the JSONL shard sink.",
    },
    "DBCSR_TPU_TS_10M_N": {
        "owner": "obs/timeseries.py",
        "doc": "10-minute rollup ring capacity.",
    },
    "DBCSR_TPU_TS_1M_N": {
        "owner": "obs/timeseries.py",
        "doc": "1-minute rollup ring capacity.",
    },
    "DBCSR_TPU_TS_INTERVAL_S": {
        "owner": "obs/timeseries.py",
        "doc": "minimum seconds between telemetry samples.",
    },
    "DBCSR_TPU_TS_RAW_N": {
        "owner": "obs/timeseries.py",
        "doc": "raw-resolution telemetry ring capacity.",
    },
    "DBCSR_TPU_WATCHDOG_LOG_MAX_BYTES": {
        "owner": "resilience/watchdog.py",
        "doc": "watchdog JSONL log rotation bound in bytes.",
    },
    "DBCSR_TPU_WATCHDOG_STATE": {
        "owner": "resilience/watchdog.py",
        "doc": "path persisting watchdog wedge-streak state across "
               "processes.",
    },
    "DBCSR_TPU_WORKLOAD": {
        "owner": "serve/workload.py",
        "doc": "workload-trace recorder control: unset/'0'/'off' "
               "disables it (the default — tracing every request is an "
               "operator decision), a path enables the JSONL shard "
               "sink capturing each terminal request's digest-only "
               "schema (docs/loadtest.md).",
    },
    "DBCSR_TPU_XLA_COST": {
        "owner": "obs/costmodel.py",
        "doc": "=1 captures XLA-reported cost analysis into the cost "
               "model.",
    },
}
