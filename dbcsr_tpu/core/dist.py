"""Process grids and block distributions.

Analog of `dbcsr_mp_type` (2D process grid, `src/core/dbcsr_types.F:110-134`)
and `dbcsr_distribution_type` (block-row/col -> process-row/col maps,
`dbcsr_types.F:143-182`, methods in `src/dist/dbcsr_dist_methods.F`).

TPU-native twist: the "process grid" is a 2D `jax.sharding.Mesh` axis
pair instead of an MPI cartesian communicator; for the single-chip
engine a trivial 1x1 grid is used and all blocks are local.  OpenMP
thread distributions have no equivalent (device work is vectorized).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class ProcessGrid:
    """2D grid of workers; optionally backed by a jax Mesh ('prow','pcol')."""

    nprows: int = 1
    npcols: int = 1
    mesh: Optional[object] = None  # jax.sharding.Mesh, lazy to keep import light

    @property
    def nprocs(self) -> int:
        return self.nprows * self.npcols

    @staticmethod
    def from_mesh(mesh, row_axis: str = "prow", col_axis: str = "pcol") -> "ProcessGrid":
        return ProcessGrid(
            nprows=mesh.shape[row_axis], npcols=mesh.shape[col_axis], mesh=mesh
        )


class Distribution:
    """Maps each block row/col to a grid row/col.

    Ref `dbcsr_distribution_new` (`src/dist/dbcsr_dist_methods.F:49`).
    """

    def __init__(self, row_dist, col_dist, grid: Optional[ProcessGrid] = None):
        self.row_dist = np.ascontiguousarray(row_dist, dtype=np.int32)
        self.col_dist = np.ascontiguousarray(col_dist, dtype=np.int32)
        self.grid = grid or ProcessGrid()
        if self.row_dist.size and self.row_dist.max(initial=0) >= self.grid.nprows:
            raise ValueError("row_dist entry exceeds grid rows")
        if self.col_dist.size and self.col_dist.max(initial=0) >= self.grid.npcols:
            raise ValueError("col_dist entry exceeds grid cols")

    @property
    def nblkrows(self) -> int:
        return len(self.row_dist)

    @property
    def nblkcols(self) -> int:
        return len(self.col_dist)

    def local_rows(self, prow: int) -> np.ndarray:
        return np.nonzero(self.row_dist == prow)[0]

    def local_cols(self, pcol: int) -> np.ndarray:
        return np.nonzero(self.col_dist == pcol)[0]

    def stored_coordinates(self, row: int, col: int):
        """Owning (prow, pcol) of a block (ref
        `dbcsr_get_stored_coordinates`, `dbcsr_dist_operations.F`)."""
        return int(self.row_dist[row]), int(self.col_dist[col])

    def get_info(self) -> dict:
        """Distribution summary (ref `dbcsr_distribution_get`,
        `dbcsr_api.F:226`)."""
        return {
            "nblkrows": self.nblkrows,
            "nblkcols": self.nblkcols,
            "nprows": self.grid.nprows,
            "npcols": self.grid.npcols,
            "row_dist": self.row_dist.copy(),
            "col_dist": self.col_dist.copy(),
        }

    def checksum(self) -> int:
        """Content hash of the maps (ref `dbcsr_dist_util.F:57`
        distribution checksum/verify)."""
        import hashlib

        # lengths first: without them the concatenated maps of a 2x3
        # and a 3x2 blocking hash identically
        h = hashlib.sha1(np.int64(
            [self.nblkrows, self.nblkcols, self.grid.nprows, self.grid.npcols]
        ).tobytes())
        h.update(self.row_dist.tobytes())
        h.update(self.col_dist.tobytes())
        return int.from_bytes(h.digest()[:8], "little")

    def fingerprint(self) -> int:
        """Memoized `checksum()` (maps are treated as immutable once the
        distribution is attached to a matrix — nothing in the package
        mutates them in place).  Used to key mesh plan caches."""
        fp = getattr(self, "_fp", None)
        if fp is None:
            fp = self._fp = self.checksum()
        return fp

    def transposed(self) -> "Distribution":
        """Ref `dbcsr_transpose_distribution` (`dbcsr_dist_operations.F:55`)."""
        grid = ProcessGrid(self.grid.npcols, self.grid.nprows, self.grid.mesh)
        return Distribution(self.col_dist, self.row_dist, grid)

    @staticmethod
    def trivial(nblkrows: int, nblkcols: int) -> "Distribution":
        return Distribution(
            np.zeros(nblkrows, np.int32), np.zeros(nblkcols, np.int32), ProcessGrid()
        )


def random_dist(nblks: int, nbins: int, seed: int = 0) -> np.ndarray:
    """Ref `dbcsr_random_dist` (tests/dbcsr_performance_multiply.F)."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, nbins, size=nblks).astype(np.int32)


def cyclic_dist(nblks: int, nbins: int) -> np.ndarray:
    return (np.arange(nblks) % nbins).astype(np.int32)


def dist_bin(
    nelements: int,
    nbins: int,
    element_sizes: Optional[np.ndarray] = None,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Load-aware 1-D binning (ref `dbcsr_dist_bin`,
    `dbcsr_dist_operations.F:705-745`): with sizes, assign each element
    in order to the currently least-loaded bin (min-heap); without,
    uniform random."""
    import heapq

    if element_sizes is None:
        rng = rng or np.random.default_rng()
        return rng.integers(0, nbins, nelements).astype(np.int32)
    element_sizes = np.asarray(element_sizes)
    if len(element_sizes) != nelements:
        raise ValueError("element_sizes length != nelements")
    heap = [(0, b) for b in range(nbins)]
    heapq.heapify(heap)
    out = np.empty(nelements, np.int32)
    for i in range(nelements):
        load, b = heapq.heappop(heap)
        out[i] = b
        heapq.heappush(heap, (load + int(element_sizes[i]), b))
    return out


def convert_sizes_to_offsets(sizes) -> np.ndarray:
    """Block sizes -> start offsets, length n+1 with the total last
    (ref `convert_sizes_to_offsets`, `src/dist/dbcsr_dist_util.F:140`)."""
    sizes = np.ascontiguousarray(sizes, np.int64)
    out = np.empty(len(sizes) + 1, np.int64)
    out[0] = 0
    np.cumsum(sizes, out=out[1:])
    return out


def convert_offsets_to_sizes(offsets) -> np.ndarray:
    """Start offsets (length n+1) -> block sizes
    (ref `convert_offsets_to_sizes`, `src/dist/dbcsr_dist_util.F:180`)."""
    offsets = np.ascontiguousarray(offsets, np.int64)
    return np.diff(offsets)
