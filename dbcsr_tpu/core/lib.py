"""Library lifecycle.

Analog of `dbcsr_init_lib` / `dbcsr_finalize_lib`
(`src/core/dbcsr_lib.F:108-366`).  The reference's per-rank GPU
round-robin device pick, acc_init, and per-thread pool setup collapse
into: enable 64-bit dtypes (this is a double-precision library) and
reset statistics.  Auto-initialization on first use is provided because
there is no Fortran-style hard ordering requirement in Python.
"""

from __future__ import annotations

import jax

from dbcsr_tpu.core import stats
from dbcsr_tpu.core import timings

_initialized = False


def init_lib(enable_x64: bool = True) -> None:
    global _initialized
    if _initialized:
        return
    if enable_x64:
        jax.config.update("jax_enable_x64", True)
    _initialized = True


def ensure_init() -> None:
    if not _initialized:
        init_lib()


def finalize_lib(print_stats: bool = False, out=print) -> None:
    global _initialized
    if print_stats:
        print_statistics(out=out)
    stats.reset()
    timings.reset()
    _initialized = False


def print_statistics(out=print) -> None:
    """Ref `dbcsr_print_statistics` (`src/core/dbcsr_lib.F:326`)."""
    stats.print_statistics(out=out)
    timings.report(out=out)
