"""Library lifecycle.

Analog of `dbcsr_init_lib` / `dbcsr_finalize_lib`
(`src/core/dbcsr_lib.F:108-366`).  The reference's per-rank GPU
round-robin device pick, acc_init, and per-thread pool setup collapse
into: enable 64-bit dtypes (this is a double-precision library) and
reset statistics.  Auto-initialization on first use is provided because
there is no Fortran-style hard ordering requirement in Python.
"""

from __future__ import annotations

import jax

from dbcsr_tpu.core import stats
from dbcsr_tpu.core import timings

_initialized = False


def init_lib(enable_x64: bool = True) -> None:
    global _initialized
    if _initialized:
        return
    if enable_x64:
        jax.config.update("jax_enable_x64", True)
    _initialized = True


def ensure_init() -> None:
    if not _initialized:
        init_lib()


def finalize_lib(print_stats: bool = False, out=print) -> None:
    global _initialized
    if print_stats:
        print_statistics(out=out)
    stats.reset()
    timings.reset()
    _initialized = False


def _print_obs_summary(out=print) -> None:
    """Finalize parity for the obs layers: when any of them captured
    something this process (trace session, event bus, introspection
    endpoint — `obs.obs_active`), the end-of-run report also emits ONE
    machine-readable JSON line: the full `metrics.snapshot()` (the
    per-driver roofline rollup, recompile mirror, every counter) plus
    the final `health.verdict()` — DBCSR's finalize-time STATISTICS
    block, extended to cover what the live ops plane was watching.
    Emitted through the same ``out=`` hook as the legacy tables so
    capture harnesses that redirect one redirect both."""
    try:
        from dbcsr_tpu import obs
        from dbcsr_tpu.obs import health as _health
        from dbcsr_tpu.obs import metrics as _metrics

        if not obs.obs_active():
            return
        import json

        out(" -" + "OBS SNAPSHOT (machine-readable)".center(68) + "-")
        out(json.dumps({
            "obs_schema": obs.OBS_SCHEMA_VERSION,
            "snapshot": _metrics.snapshot(),
            "health": _health.verdict(),
        }, default=str))
    except Exception:
        pass  # the legacy report must never fail on the obs extension


def print_statistics(out=print) -> None:
    """Ref `dbcsr_print_statistics` (`src/core/dbcsr_lib.F:326`)."""
    stats.print_statistics(out=out)
    timings.report(out=out)
    _print_obs_summary(out=out)
