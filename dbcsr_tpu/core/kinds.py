"""Datatype kinds.

Mirrors the reference datatype enum (`src/acc/acc_libsmm.h:31-36`:
{r4=1, r8=3, c4=5, c8=7}) and the kind constants of
`src/base/dbcsr_kinds.F`, mapped onto JAX dtypes.  bfloat16 is an extra,
TPU-native kind with no reference counterpart (the MXU's native input
type); float64/complex128 are kept for CP2K-equivalent semantics and run
on TPU via XLA's f64 emulation (or on CPU backends natively).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Reference enum values (acc_libsmm.h:31-36), kept numerically identical
# so .perf files and the C shim agree with the reference.
dbcsr_type_real_4 = 1
dbcsr_type_real_8 = 3
dbcsr_type_complex_4 = 5
dbcsr_type_complex_8 = 7
dbcsr_type_bfloat16 = 9  # TPU-native extension

_ENUM_TO_DTYPE = {
    dbcsr_type_real_4: np.float32,
    dbcsr_type_real_8: np.float64,
    dbcsr_type_complex_4: np.complex64,
    dbcsr_type_complex_8: np.complex128,
    dbcsr_type_bfloat16: jnp.bfloat16,
}

_DTYPE_TO_ENUM = {np.dtype(v): k for k, v in _ENUM_TO_DTYPE.items()}


def dtype_of(kind) -> np.dtype:
    """Resolve a dbcsr kind enum, dtype, or string to a numpy dtype."""
    if isinstance(kind, int):
        return np.dtype(_ENUM_TO_DTYPE[kind])
    return np.dtype(kind)


def enum_of(dtype) -> int:
    """Inverse of :func:`dtype_of`."""
    return _DTYPE_TO_ENUM[np.dtype(dtype)]


def is_complex(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.complexfloating)


def real_dtype_of(dtype) -> np.dtype:
    """The real dtype with matching precision (for norms)."""
    d = np.dtype(dtype)
    if d == np.complex64:
        return np.dtype(np.float32)
    if d == np.complex128:
        return np.dtype(np.float64)
    return d
