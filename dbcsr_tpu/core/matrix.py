"""The block-sparse matrix type.

Analog of `dbcsr_type` (`src/core/dbcsr_types.F:363-461`): a CSR index
over blocks plus block data.  TPU-first data model (SURVEY §7 design
mapping):

* Host index (NumPy): sorted int64 keys ``row * nblkcols + col`` with a
  derived ``row_ptr`` — the reference's row_p/col_i/blk_p triplet.
* Device data (HBM): one jax array per distinct block shape, of shape
  ``(capacity, bm, bn)`` — "shape bins".  The reference enumerates block
  sizes the same way (`dbcsr_mm_common.F:309` enumerate_blk_sizes);
  binning keeps every kernel launch statically shaped for XLA while
  supporting arbitrary mixed block sizes.  ``capacity >= count`` is
  bucketed (mempool analog) so repeated multiplies reuse compiled code.
* Assembly goes through a host-side work buffer then `finalize()`, like
  the reference's work matrices -> `dbcsr_finalize`
  (`src/work/dbcsr_work_operations.F:749`).

Symmetric/antisymmetric/hermitian matrices store the canonical upper
triangle only (row <= col), as the reference does; `put_block` folds
lower-triangle writes onto the stored transpose.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbcsr_tpu.core import mempool
from dbcsr_tpu.core.dist import Distribution
from dbcsr_tpu.core.kinds import dtype_of, is_complex
from dbcsr_tpu.core.lib import ensure_init
from dbcsr_tpu.utils.rounding import bucket_size

# matrix_type flags, ref dbcsr_type_no_symmetry/_symmetric/_antisymmetric/
# _hermitian in src/core/dbcsr_types.F
NO_SYMMETRY = "N"
SYMMETRIC = "S"
ANTISYMMETRIC = "A"
HERMITIAN = "H"


@dataclasses.dataclass
class _Bin:
    """One block-shape bin: device array of same-shape blocks."""

    shape: Tuple[int, int]
    data: object  # jnp.ndarray (capacity, bm, bn)
    count: int

    @property
    def capacity(self) -> int:
        return self.data.shape[0]


def _fold_block(block: np.ndarray, matrix_type: str) -> np.ndarray:
    """Transform a lower-triangle block to its stored upper-triangle image."""
    if matrix_type == SYMMETRIC:
        return block.T
    if matrix_type == ANTISYMMETRIC:
        return -block.T
    if matrix_type == HERMITIAN:
        return block.conj().T
    raise AssertionError(matrix_type)


@jax.jit
def _rezero_pad_rows(data, count):
    # count is a traced scalar: one compiled program per bin shape, not
    # one per (shape, count) pair as matrices grow
    mask = (jnp.arange(data.shape[0]) < count).reshape(-1, 1, 1)
    return jnp.where(mask, data, jnp.zeros_like(data))


@jax.jit
def _migrate_blocks(dst, src, src_slots, dst_slots):
    """Device-to-device move of surviving blocks into a rebuilt bin —
    the no-host-round-trip half of `dbcsr_merge_all`
    (`dbcsr_work_operations.F:1393`)."""
    return dst.at[dst_slots].set(jnp.take(src, src_slots, axis=0), mode="drop")


@functools.partial(jax.jit, static_argnames=("add",))
def _scatter_staged(dst, blocks, slots, add: bool):
    if add:
        return dst.at[slots].add(blocks, mode="drop")
    return dst.at[slots].set(blocks, mode="drop")


class BlockSparseMatrix:
    """A distributed block-compressed sparse row matrix."""

    def __init__(
        self,
        name: str,
        row_blk_sizes,
        col_blk_sizes,
        dtype=np.float64,
        dist: Optional[Distribution] = None,
        matrix_type: str = NO_SYMMETRY,
    ):
        ensure_init()
        self.name = name
        self.row_blk_sizes = np.ascontiguousarray(row_blk_sizes, np.int32)
        self.col_blk_sizes = np.ascontiguousarray(col_blk_sizes, np.int32)
        self.dtype = dtype_of(dtype)
        self.matrix_type = matrix_type
        if matrix_type != NO_SYMMETRY:
            if len(self.row_blk_sizes) != len(self.col_blk_sizes) or not np.array_equal(
                self.row_blk_sizes, self.col_blk_sizes
            ):
                raise ValueError("symmetric matrix needs identical row/col blocking")
            if matrix_type == HERMITIAN and not is_complex(self.dtype):
                matrix_type = self.matrix_type = SYMMETRIC
        self.dist = dist or Distribution.trivial(
            len(self.row_blk_sizes), len(self.col_blk_sizes)
        )
        assert self.dist.nblkrows == self.nblkrows
        assert self.dist.nblkcols == self.nblkcols
        # finalized index
        self.keys = np.empty(0, np.int64)
        self.row_ptr = np.zeros(self.nblkrows + 1, np.int64)
        self.ent_bin = np.empty(0, np.int32)
        self.ent_slot = np.empty(0, np.int32)
        self.bins: List[_Bin] = []
        self._shape_to_bin: Dict[Tuple[int, int], int] = {}
        self.valid = True
        # pre-finalize work buffer: (row, col) -> host block
        self._work: Dict[Tuple[int, int], np.ndarray] = {}
        # batched staging: (keys int64, blocks (N, bm, bn), summation)
        self._work_batches: List[Tuple[np.ndarray, np.ndarray, bool]] = []
        # device residency (core.mempool): pool-owned matrices donate
        # replaced bin buffers back to the pool from the mutation
        # funnels; copy() marks bins shared, which disables donation
        self._pool_owned = False
        self._bins_shared = False
        # per-matrix device index mirrors, invalidated when the pattern
        # fingerprint changes (any structure-altering finalize)
        self._dev_mirrors: Dict = {}
        self._mirror_fp = None
        # value-delta tracking (mm.incremental / serve.product_cache):
        # a monotone mutation epoch plus a bounded journal of
        # (epoch, dirtied block keys | None) entries — None marks a
        # structure change (everything dirty).  Each matrix owns its
        # delta state exclusively; `copy()` deliberately does NOT
        # carry it over (shared bins never alias delta state).
        self._epoch = 0
        self._delta_log: List = []
        ch = mempool.current_chain()
        if ch is not None:
            ch.adopt(self)

    # ---------------------------------------------------------------- shape
    @property
    def nblkrows(self) -> int:
        return len(self.row_blk_sizes)

    @property
    def nblkcols(self) -> int:
        return len(self.col_blk_sizes)

    @property
    def nfullrows(self) -> int:
        return int(self.row_blk_sizes.sum())

    @property
    def nfullcols(self) -> int:
        return int(self.col_blk_sizes.sum())

    @property
    def row_blk_offsets(self) -> np.ndarray:
        return np.concatenate([[0], np.cumsum(self.row_blk_sizes)]).astype(np.int64)

    @property
    def col_blk_offsets(self) -> np.ndarray:
        return np.concatenate([[0], np.cumsum(self.col_blk_sizes)]).astype(np.int64)

    @property
    def nblks(self) -> int:
        return len(self.keys)

    @property
    def nnz(self) -> int:
        rows, cols = self.entry_coords()
        return int(
            (self.row_blk_sizes[rows].astype(np.int64) * self.col_blk_sizes[cols]).sum()
        )

    def occupation(self) -> float:
        """Fraction of nonzero elements (ref dbcsr_get_occupation)."""
        full = self.nfullrows * self.nfullcols
        return self.nnz / full if full else 0.0

    def setname(self, name: str) -> None:
        """Ref `dbcsr_setname`."""
        self.name = str(name)

    def get_stored_coordinates(self, row: int, col: int):
        """Owning (prow, pcol) of a block under this matrix's
        distribution (ref `dbcsr_get_stored_coordinates`)."""
        srow, scol = row, col
        if self.matrix_type != NO_SYMMETRY and row > col:
            srow, scol = col, row  # canonical triangle owns the block
        return self.dist.stored_coordinates(srow, scol)

    @property
    def valid_index(self) -> bool:
        """Finalized and consistent (ref `dbcsr_valid_index`)."""
        return self.valid

    @property
    def _donatable(self) -> bool:
        """THE donation-eligibility rule, single-sourced: replaced bin
        buffers may return to the memory pool only when this matrix is
        pool-owned (chain-adopted) and its bins were never shared
        through `copy` (a shared buffer must never be recycled)."""
        return self._pool_owned and not self._bins_shared

    def get_data_size(self) -> int:
        """Stored elements incl. bucket padding — the data-area size
        (ref `dbcsr_get_data_size`)."""
        return int(sum(b.capacity * b.shape[0] * b.shape[1] for b in self.bins))

    def get_info(self) -> dict:
        """Structure summary (ref `dbcsr_get_info`, `dbcsr_api.F`)."""
        return {
            "name": self.name,
            "matrix_type": self.matrix_type,
            "data_type": np.dtype(self.dtype).name,
            "nblkrows_total": self.nblkrows,
            "nblkcols_total": self.nblkcols,
            "nfullrows_total": self.nfullrows,
            "nfullcols_total": self.nfullcols,
            "nblks": self.nblks,
            "nze": self.nnz,
            "data_size": self.get_data_size(),
            "occupation": self.occupation(),
            "row_blk_sizes": self.row_blk_sizes.copy(),
            "col_blk_sizes": self.col_blk_sizes.copy(),
            "row_blk_offsets": self.row_blk_offsets[:-1].copy(),
            "col_blk_offsets": self.col_blk_offsets[:-1].copy(),
            "distribution": self.dist,
        }

    def block_shape(self, row: int, col: int) -> Tuple[int, int]:
        return int(self.row_blk_sizes[row]), int(self.col_blk_sizes[col])

    def entry_coords(self) -> Tuple[np.ndarray, np.ndarray]:
        """(rows, cols) arrays for all finalized entries, key-ordered."""
        return (
            (self.keys // self.nblkcols).astype(np.int64),
            (self.keys % self.nblkcols).astype(np.int64),
        )

    # ------------------------------------------------------------- assembly
    def put_block(self, row: int, col: int, block, summation: bool = False) -> None:
        """Stage a block for the next `finalize` (ref `dbcsr_put_block`,
        `src/block/dbcsr_block_access.F:73-76`)."""
        row, col, block = self._canonicalize(row, col, np.asarray(block))
        bm, bn = self.block_shape(row, col)
        if block.shape != (bm, bn):
            raise ValueError(
                f"block ({row},{col}) has shape {block.shape}, expected {(bm, bn)}"
            )
        block = block.astype(self.dtype, copy=True)
        key = (row, col)
        if summation and key in self._work:
            self._work[key] = self._work[key] + block
        elif summation and self._find_entry(row, col) >= 0:
            existing = self.get_block(row, col)
            self._work[key] = existing + block
        else:
            self._work[key] = block
        self.valid = False

    def put_blocks(self, rows, cols, blocks, summation: bool = False) -> None:
        """Stage many blocks at once — the vectorized assembly path
        (array-of-blocks analog of the reference's work matrices,
        `dbcsr_work_operations.F:674`; merged by `finalize` without a
        host round-trip of existing device data).

        ``blocks`` is an (N, bm, bn) array (uniform shape) or a list of
        2-D arrays; the data is snapshotted (caller may reuse buffers).
        Staged batches become visible at `finalize`; they are applied
        after any single `put_block` stagings, in call order, with
        ``summation=True`` batches adding to whatever value the block
        has at merge time.  Duplicates within one call are pre-reduced:
        summed when ``summation``, last-write-wins otherwise.
        """
        self._work_batches.extend(
            self._make_batches(rows, cols, blocks, summation)
        )
        self.valid = False

    def _validate_coords(self, rows: np.ndarray, cols: np.ndarray) -> None:
        if rows.min() < 0 or rows.max() >= self.nblkrows or cols.min() < 0 or (
            cols.max() >= self.nblkcols
        ):
            raise IndexError("block coordinates out of range")

    def _validate_batch_shape(self, rows, cols, bm: int, bn: int) -> None:
        if not (
            np.all(self.row_blk_sizes[rows] == bm)
            and np.all(self.col_blk_sizes[cols] == bn)
        ):
            raise ValueError(
                f"batch of shape ({bm},{bn}) does not match the blocking "
                f"at all its coordinates"
            )

    def _make_batches(self, rows, cols, blocks, summation: bool):
        """Canonicalize (symmetry fold), validate, group by block shape,
        and pre-reduce duplicates; returns [(keys, (N,bm,bn) array,
        summation)] staging batches."""
        rows = np.ascontiguousarray(rows, np.int64)
        cols = np.ascontiguousarray(cols, np.int64)
        if len(rows) != len(cols):
            raise ValueError("rows/cols length mismatch")
        if len(rows) == 0:
            return []
        self._validate_coords(rows, cols)
        uniform = isinstance(blocks, np.ndarray) and blocks.ndim == 3
        if not uniform and len(blocks) != len(rows):
            raise ValueError("blocks length mismatch")
        # canonicalize BEFORE grouping: folding transposes blocks, which
        # changes their shape group for rectangular off-diagonal blocks
        if self.matrix_type != NO_SYMMETRY:
            fold = rows > cols
            if fold.any():
                blocks = [
                    _fold_block(np.asarray(blocks[i]), self.matrix_type)
                    if fold[i] else np.asarray(blocks[i])
                    for i in range(len(rows))
                ]
                uniform = False
                rows, cols = np.where(fold, cols, rows), np.where(fold, rows, cols)
        if uniform:
            groups = [(np.arange(len(rows)), np.array(blocks, dtype=self.dtype))]
        else:
            shapes = np.array([np.asarray(b).shape for b in blocks], np.int64)
            code = shapes[:, 0] << 32 | shapes[:, 1]
            groups = []
            for u in np.unique(code):
                idx = np.nonzero(code == u)[0]
                groups.append(
                    (idx, np.stack([blocks[i] for i in idx]).astype(self.dtype))
                )
        out = []
        for idx, arr in groups:
            r, c = rows[idx], cols[idx]
            bm, bn = arr.shape[1], arr.shape[2]
            self._validate_batch_shape(r, c, bm, bn)
            keys = r * self.nblkcols + c
            if len(np.unique(keys)) != len(keys):
                if summation:
                    uniq, inv = np.unique(keys, return_inverse=True)
                    red = np.zeros((len(uniq), bm, bn), self.dtype)
                    np.add.at(red, inv, arr)
                    keys, arr = uniq, red
                else:
                    # deterministic last-write-wins (jnp scatter with
                    # duplicate indices is undefined-order)
                    uniq, first_rev = np.unique(keys[::-1], return_index=True)
                    last = len(keys) - 1 - first_rev
                    keys, arr = uniq, arr[last]
            out.append((keys, arr, summation))
        return out

    def stage_device_blocks(self, rows, cols, blocks, summation: bool = False) -> None:
        """Stage an (N, bm, bn) DEVICE array of uniform-shape blocks
        without a host round-trip — the device-side sibling of
        `put_blocks` (used by the tensor reshape path, ref
        `dbcsr_t_reshape`'s buffered block alltoall,
        `dbcsr_tensor_reshape.F:67,288`).  The batch merges at
        `finalize` via the same device gather/scatter as host batches.

        Caller contract: (row, col) pairs are unique within the batch
        (jnp scatter with duplicates is undefined-order), and the
        matrix has no symmetry (device blocks are not host-foldable).
        """
        if self.matrix_type != NO_SYMMETRY:
            raise NotImplementedError(
                "stage_device_blocks requires a non-symmetric matrix"
            )
        rows = np.ascontiguousarray(rows, np.int64)
        cols = np.ascontiguousarray(cols, np.int64)
        if len(rows) != len(cols) or len(rows) != blocks.shape[0]:
            raise ValueError("rows/cols/blocks length mismatch")
        if len(rows) == 0:
            return
        self._validate_coords(rows, cols)
        self._validate_batch_shape(rows, cols, int(blocks.shape[1]), int(blocks.shape[2]))
        keys = rows * self.nblkcols + cols
        if blocks.dtype != np.dtype(self.dtype):
            blocks = blocks.astype(self.dtype)
        self._work_batches.append((keys, blocks, summation))
        self.valid = False

    def reserve_block(self, row: int, col: int) -> None:
        """Ref `dbcsr_reserve_block2d`: allocate a zero block."""
        row, col, _ = self._canonicalize(row, col, None)
        if (row, col) not in self._work and self._find_entry(row, col) < 0:
            self._work[(row, col)] = np.zeros(self.block_shape(row, col), self.dtype)
            self.valid = False

    def _canonicalize(self, row, col, block):
        if not (0 <= row < self.nblkrows and 0 <= col < self.nblkcols):
            raise IndexError(f"block ({row},{col}) out of range")
        if self.matrix_type != NO_SYMMETRY and row > col:
            if block is not None:
                block = _fold_block(block, self.matrix_type)
            row, col = col, row
        return row, col, block

    def finalize(self) -> "BlockSparseMatrix":
        """Merge staged blocks into the CSR index (ref `dbcsr_finalize` ->
        `dbcsr_merge_all`, `dbcsr_work_operations.F:749,1393`).

        Existing device data is never round-tripped through host:
        surviving blocks move bin-to-bin with one device gather/scatter
        per shape, and only the staged host blocks are uploaded.
        """
        if not self._work and not self._work_batches:
            self.valid = True
            return self
        nbc = self.nblkcols
        if self._work:
            # single-put stagings become a leading replace batch (keys
            # are already canonical; dict semantics were last-wins)
            self._work_batches = self._make_batches(
                np.array([r for (r, _) in self._work], np.int64),
                np.array([c for (_, c) in self._work], np.int64),
                [blk for blk in self._work.values()],
                False,
            ) + self._work_batches
            self._work.clear()
        staged_keys = np.unique(
            np.concatenate([k for (k, _, _) in self._work_batches]))
        merged = np.union1d(self.keys, staged_keys)
        # same-pattern finalize (the SCF-loop value update): the delta
        # journal records exactly the staged keys instead of marking
        # the whole matrix dirty
        same_pattern = len(merged) == len(self.keys) and np.array_equal(
            merged, self.keys)
        rows = (merged // nbc).astype(np.int64)
        cols = (merged % nbc).astype(np.int64)
        nb, nsl, shapes = _bin_entries(
            self.row_blk_sizes, self.col_blk_sizes, rows, cols
        )
        shape_to_bin = {(int(bm), int(bn)): i for i, (bm, bn) in enumerate(shapes)}
        counts = np.bincount(nb, minlength=len(shapes))
        data_arrs = [
            mempool.zeros((bucket_size(int(counts[i])), int(bm), int(bn)),
                          self.dtype)
            for i, (bm, bn) in enumerate(shapes)
        ]
        # 1) surviving old blocks: device-to-device migration per shape
        if len(self.keys):
            pos_old = np.searchsorted(merged, self.keys)
            new_bin_of_old = nb[pos_old]
            for b in range(len(shapes)):
                old_sel = np.nonzero(new_bin_of_old == b)[0]
                if not len(old_sel):
                    continue
                src = self.bins[self.ent_bin[old_sel[0]]]
                data_arrs[b] = _migrate_blocks(
                    data_arrs[b],
                    src.data,
                    mempool.upload_index("fin_src", self.ent_slot[old_sel]),
                    mempool.upload_index("fin_dst", nsl[pos_old[old_sel]]),
                )
        # 2) staged batches in call order (a batch is shape-uniform ->
        #    exactly one bin; single puts were prepended as a batch)
        for keys_b, arr, summation in self._work_batches:
            b = shape_to_bin[(arr.shape[1], arr.shape[2])]
            slots = nsl[np.searchsorted(merged, keys_b)]
            if isinstance(arr, np.ndarray):
                mempool.record_h2d(arr.nbytes)  # staged host blocks
            data_arrs[b] = _scatter_staged(
                data_arrs[b], jnp.asarray(arr),
                mempool.upload_index("fin_slot", slots), bool(summation)
            )
        bins = [
            _Bin((int(bm), int(bn)), data_arrs[i], int(counts[i]))
            for i, (bm, bn) in enumerate(shapes)
        ]
        self._work.clear()
        self._work_batches.clear()
        self.set_structure_from_device(
            merged, bins, binning=(nb, nsl, shapes),
            value_delta_keys=staged_keys if same_pattern else None)
        return self

    def set_structure_from_device(
        self, keys: np.ndarray, bins: List[_Bin], binning=None,
        value_delta_keys=None,
    ) -> None:
        """Adopt a prebuilt index + device bins (used by the multiply
        engine, which assembles C on device).  ``binning`` optionally
        carries a precomputed ``_bin_entries`` result to avoid
        recomputing it.  ``value_delta_keys`` refines the delta
        journal: a same-pattern caller (value-only finalize) passes
        exactly the touched block keys; the default None records a
        structure change (everything dirty).

        Caller contract (every in-tree caller satisfies it): ``bins``
        hold FRESHLY CONSTRUCTED device arrays not aliased into any
        other matrix — which is why a full restructure clears the
        `copy`-induced shared mark: the new bins are exclusively this
        matrix's again, so pool donation resumes."""
        keys = np.ascontiguousarray(keys, np.int64)
        rows = (keys // self.nblkcols).astype(np.int64)
        cols = (keys % self.nblkcols).astype(np.int64)
        if binning is None:
            binning = _bin_entries(self.row_blk_sizes, self.col_blk_sizes, rows, cols)
        bin_ids, slots, shapes = binning
        # pool-owned matrices donate the buffers this restructure
        # retires (the dbcsr_mem_methods "return to pool" half);
        # anything aliased into the NEW bins — or ever shared via
        # copy() — is kept
        old_data = [b.data for b in self.bins] if self._donatable else None
        self.keys = keys
        self.row_ptr = np.zeros(self.nblkrows + 1, np.int64)
        self.row_ptr[1:] = np.cumsum(np.bincount(rows, minlength=self.nblkrows))
        self.ent_bin = bin_ids
        self.ent_slot = slots
        by_shape = {b.shape: b for b in bins}
        self.bins = [by_shape[(int(bm), int(bn))] for (bm, bn) in shapes]
        self._shape_to_bin = {b.shape: i for i, b in enumerate(self.bins)}
        self._work.clear()
        self._work_batches.clear()
        self.invalidate_dense_cache()  # structure changed
        if old_data is not None:
            live = {id(b.data) for b in self.bins}
            for d in old_data:
                if id(d) not in live:
                    mempool.release(d)
        self._bins_shared = False  # fresh bins: exclusively owned again
        self._note_mutation(value_delta_keys)
        self.valid = True

    # --------------------------------------------------------------- access
    def _find_entry(self, row: int, col: int) -> int:
        key = row * self.nblkcols + col
        i = np.searchsorted(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            return int(i)
        return -1

    def get_block(self, row: int, col: int, unfold: bool = True):
        """Fetch one block to host; None if absent (ref `dbcsr_get_block_p`)."""
        srow, scol = row, col
        folded = False
        if self.matrix_type != NO_SYMMETRY and row > col:
            srow, scol, folded = col, row, True
        if (srow, scol) in self._work:
            blk = self._work[(srow, scol)].copy()
        else:
            e = self._find_entry(srow, scol)
            if e < 0:
                return None
            b = self.bins[self.ent_bin[e]]
            blk = np.asarray(b.data[self.ent_slot[e]])
            mempool.record_d2h(blk.nbytes)
        if folded and unfold:
            blk = _fold_block(blk, self.matrix_type)
        return blk

    def get_blocks(self, rows, cols, unfold: bool = True) -> List:
        """Fetch many blocks with ONE batched device gather per shape
        bin instead of a per-entry D2H round-trip (`get_block` in a
        loop fetches block-by-block; this is its `stage_device_blocks`
        sibling on the read side).  Returns a list aligned with
        ``rows``/``cols``; absent blocks are None.  Blocks still
        sitting in the pre-finalize work buffer are served from host."""
        rows = np.ascontiguousarray(rows, np.int64)
        cols = np.ascontiguousarray(cols, np.int64)
        if len(rows) != len(cols):
            raise ValueError("rows/cols length mismatch")
        n = len(rows)
        out: List = [None] * n
        if n == 0:
            return out
        self._validate_coords(rows, cols)
        srows, scols = rows.copy(), cols.copy()
        folded = np.zeros(n, bool)
        if self.matrix_type != NO_SYMMETRY:
            folded = rows > cols
            srows = np.where(folded, cols, rows)
            scols = np.where(folded, rows, cols)
        keys = srows * self.nblkcols + scols
        pos = np.searchsorted(self.keys, keys)
        pos_c = np.minimum(pos, max(len(self.keys) - 1, 0))
        found = (
            np.zeros(n, bool) if len(self.keys) == 0
            else self.keys[pos_c] == keys
        )
        for b_id, b in enumerate(self.bins):
            sel = np.nonzero(found & (self.ent_bin[pos_c] == b_id))[0]
            if not len(sel):
                continue
            slots = self.ent_slot[pos_c[sel]]
            fetched = np.asarray(
                jnp.take(b.data, mempool.upload_index("getblk", slots),
                         axis=0))
            mempool.record_d2h(fetched.nbytes)
            for i, e in enumerate(sel):
                out[e] = fetched[i]
        for e in range(n):
            key = (int(srows[e]), int(scols[e]))
            if key in self._work:
                out[e] = self._work[key].copy()
            if out[e] is not None and folded[e] and unfold:
                out[e] = _fold_block(out[e], self.matrix_type)
        return out

    def iterate_blocks(self) -> Iterator[Tuple[int, int, np.ndarray]]:
        """Iterate stored blocks in index order (ref `dbcsr_iterator_*`,
        `src/block/dbcsr_iterator_operations.F:91`).  Fetches each bin
        from device once."""
        if not self.valid:
            raise RuntimeError("finalize() before iterating")
        host_bins = [np.asarray(b.data[: b.count]) for b in self.bins]
        mempool.record_d2h(sum(hb.nbytes for hb in host_bins))
        rows, cols = self.entry_coords()
        for e in range(self.nblks):
            yield int(rows[e]), int(cols[e]), host_bins[self.ent_bin[e]][
                self.ent_slot[e]
            ]

    def iterator(self) -> "BlockIterator":
        """Reference-style explicit iterator (ref `dbcsr_iterator_start`
        / `_blocks_left` / `_next_block` / `_stop`,
        `src/block/dbcsr_iterator_operations.F:44-91`); `iterate_blocks`
        is the Pythonic equivalent."""
        return BlockIterator(self)

    def block_norms(self) -> np.ndarray:
        """Frobenius norm per finalized entry, key-ordered (device
        compute).  Memoized against the bin data-array identities
        under device residency (`core.mempool`): a matrix used as both
        operands of a filtered product — or reused across a chain's
        multiplies — computes (and fetches) its norms once, like the
        reference's per-data-area `calc_norms` caching.  The cache
        holds the hashed arrays, so ids cannot recycle (the
        `core.digests.buffers_key` identity-key convention)."""
        from dbcsr_tpu.core import digests

        key = digests.buffers_key(b.data for b in self.bins)
        cached = getattr(self, "_norms_cache", None)
        if mempool.enabled() and cached is not None and cached[0] == key:
            return cached[1]
        from dbcsr_tpu.acc.smm import block_norms as _bn

        out = np.zeros(self.nblks, np.float64)
        for b_id, b in enumerate(self.bins):
            if b.count == 0:
                continue
            norms = _bn(b.data)
            mask = self.ent_bin == b_id
            out[mask] = np.asarray(norms)[self.ent_slot[mask]]
        if mempool.enabled():
            self._norms_cache = (key, out, [b.data for b in self.bins])
        return out

    # ------------------------------------------------------------ structure
    def pattern_fingerprint(self):
        """Cheap content hash of the sparsity pattern (keys + the full
        BLOCKING vectors — same keys under different blockings are
        different patterns), memoized against the keys array object.
        Holding the hashed array alive makes the identity check sound
        (no id reuse).  Used to key plan caches for repeated
        same-pattern multiplies (SCF-style loops)."""
        from dbcsr_tpu.core import digests

        if getattr(self, "_blk_fp", None) is None:
            self._blk_fp = digests.digest(
                self.row_blk_sizes.tobytes(), self.col_blk_sizes.tobytes()
            )[:8]
        if getattr(self, "_fp_keys", None) is not self.keys:
            self._fp_keys = self.keys
            self._fp = (
                self.nblkrows, self.nblkcols, len(self.keys), self._blk_fp,
                digests.digest(self.keys.tobytes())[:8],
            )
        return self._fp

    # ---------------------------------------------------------- value deltas
    # bounded journal: older baselines than the journal reaches degrade
    # to "unknown" (full recompute), never to a wrong delta
    _DELTA_LOG_MAX = 64

    @property
    def mutation_epoch(self) -> int:
        """Monotone per-matrix mutation counter: bumped by every
        mutation funnel (finalize/restructure, `map_bin_data`, diag
        writes, donated adds, pool restore/free).  Consumers snapshot
        it and later ask `dirty_keys_since` for the delta."""
        return self._epoch

    def _note_mutation(self, keys) -> None:
        """Record one mutation: ``keys`` is the int64 block-key array
        the mutation touched (values only, structure unchanged), or
        None for a structure change / unknown extent (everything
        dirty).  The journal holds consecutive epochs; a None entry
        resets it (nothing older can be reconstructed past it)."""
        self._epoch += 1
        if keys is None:
            self._delta_log = [(self._epoch, None)]
            return
        self._delta_log.append(
            (self._epoch, np.asarray(keys, np.int64)))
        if len(self._delta_log) > self._DELTA_LOG_MAX:
            del self._delta_log[0]

    def dirty_keys_since(self, epoch: int):
        """Block keys whose VALUES may have changed since ``epoch`` (a
        prior `mutation_epoch` snapshot): an int64 key array (possibly
        empty = provably unchanged), or None when the delta is unknown
        — the structure changed, the journal no longer reaches back to
        ``epoch``, or ``epoch`` was never this matrix's (a rolled-back
        or foreign epoch).  None always means "treat everything as
        dirty"; it is never wrong, only conservative."""
        if epoch == self._epoch:
            return np.empty(0, np.int64)
        if epoch > self._epoch or not self._delta_log:
            return None
        first = self._delta_log[0][0]
        if epoch < first - 1:
            return None  # journal truncated past the baseline
        parts = []
        for e, k in self._delta_log:
            if e <= epoch:
                continue
            if k is None:
                return None
            parts.append(k)
        if not parts:
            return None  # epoch inside a reset journal: unknown
        return np.unique(np.concatenate(parts))

    def copy(self, name: Optional[str] = None) -> "BlockSparseMatrix":
        m = BlockSparseMatrix(
            name or self.name,
            self.row_blk_sizes,
            self.col_blk_sizes,
            self.dtype,
            self.dist,
            self.matrix_type,
        )
        m.keys = self.keys.copy()
        m.row_ptr = self.row_ptr.copy()
        m.ent_bin = self.ent_bin.copy()
        m.ent_slot = self.ent_slot.copy()
        m.bins = [_Bin(b.shape, b.data, b.count) for b in self.bins]
        m._shape_to_bin = dict(self._shape_to_bin)
        m._work = {k: v.copy() for k, v in self._work.items()}
        m._work_batches = [(k.copy(), a.copy(), s) for (k, a, s) in self._work_batches]
        m.valid = self.valid
        # both sides now alias the same device buffers: neither may
        # ever donate them back to the pool (conservative, permanent)
        if self.bins:
            self._bins_shared = True
            m._bins_shared = True
        return m

    def map_bin_data(self, fn) -> None:
        """Apply a jax fn to every bin's device data in place.

        Bucket-padding rows (slot >= count) are re-zeroed afterwards:
        the engine's Pallas path masks short stack groups with them and
        relies on the rows-beyond-count-are-zero invariant, which an
        arbitrary elementwise fn (fn(0) != 0) would otherwise break.
        """
        releasable = self._donatable
        all_fresh = True
        for b in self.bins:
            if b.count:
                data = fn(b.data)
                if data.shape[0] > b.count:
                    data = _rezero_pad_rows(data, b.count)
                if releasable and data is not b.data:
                    mempool.release(b.data)
                if data is b.data:
                    all_fresh = False
                b.data = data
            else:
                all_fresh = False  # empty bin: data possibly still aliased
        if all_fresh and self.bins:
            # every buffer was replaced with a fresh fn output: a
            # copy-induced shared mark no longer applies (a chain whose
            # lineage passed through copy()+scale regains donation)
            self._bins_shared = False
        self.invalidate_dense_cache()  # values changed
        self._note_mutation(self.keys)  # every stored value touched

    def device_index(self, tag, build):
        """Per-matrix device mirror of a structure-derived index array
        (or tuple of arrays) — the `acc_devmem` + `acc_ready` analog:
        ``build`` runs on the first request and whenever the sparsity
        pattern changed since (any finalize that altered structure
        invalidates — the mirror is keyed to `pattern_fingerprint`, so
        a same-pattern finalize keeps it).  Only STRUCTURE-derived
        uploads belong here; value-dependent arrays must not be
        mirrored.  Honors the residency knob like every other mirror:
        with `mempool` disabled, ``build`` runs every call (the
        historical re-upload-per-op engine)."""

        def _count(x):
            for leaf in x if isinstance(x, (tuple, list)) else (x,):
                mempool.record_h2d(
                    int(np.prod(leaf.shape))
                    * int(jnp.dtype(leaf.dtype).itemsize))

        if not mempool.enabled():
            hit = build()
            _count(hit)
            return hit
        fp = self.pattern_fingerprint()
        if self._mirror_fp != fp:
            self._dev_mirrors.clear()
            self._mirror_fp = fp
        hit = self._dev_mirrors.get(tag)
        if hit is None:
            hit = self._dev_mirrors[tag] = build()
            _count(hit)
        return hit

    def free(self) -> None:
        """Release this matrix's device storage back to the memory pool
        (the `dbcsr_release` analog): bin buffers and any cached dense
        canvas are donated when this matrix owns them exclusively
        (pool-owned, never shared through `copy`), then the matrix is
        emptied and marked invalid.  Stale outside references to the
        released buffers raise on use once recycled — they never read
        recycled data."""
        if self._donatable:
            for b in self.bins:
                mempool.release(b.data)
            cache = getattr(self, "_dense_canvas_cache", None)
            if cache is not None:
                mempool.release(cache[1])
        self.bins = []
        self._shape_to_bin = {}
        self.keys = np.empty(0, np.int64)
        self.row_ptr = np.zeros(self.nblkrows + 1, np.int64)
        self.ent_bin = np.empty(0, np.int32)
        self.ent_slot = np.empty(0, np.int32)
        self._work.clear()
        self._work_batches.clear()
        self._dev_mirrors.clear()
        self._mirror_fp = None
        self._dense_canvas_cache = None
        self._norms_cache = None
        self._note_mutation(None)  # emptied: nothing reusable remains
        self.valid = False

    def invalidate_dense_cache(self) -> None:
        """Drop the cached dense canvas (multiply engine) and the
        block-norms memo.  Correctness never depends on this — both
        caches key by bin data-array identity, so any rebind misses —
        but the caches PIN the old device arrays (id-stability), so
        every mutation funnel calls this to release them early
        (`map_bin_data` / `set_structure_from_device` do)."""
        self._dense_canvas_cache = None
        self._norms_cache = None

    def zero_data(self) -> None:
        self.map_bin_data(lambda d: jnp.zeros_like(d))

    def __repr__(self) -> str:
        return (
            f"BlockSparseMatrix({self.name!r}, {self.nblkrows}x{self.nblkcols} blocks,"
            f" {self.nblks} stored, dtype={np.dtype(self.dtype).name},"
            f" type={self.matrix_type})"
        )


class BlockIterator:
    """Explicit start/next/stop block iterator mirroring the reference
    API shape (`dbcsr_iterator_operations.F`): ``blocks_left()`` /
    ``next_block() -> (row, col, block)`` / ``stop()``.  Fetches each
    device bin once at start, like `iterate_blocks`."""

    def __init__(self, matrix: "BlockSparseMatrix"):
        if not matrix.valid:
            raise RuntimeError("finalize() before iterating")
        self._it = matrix.iterate_blocks()
        self._next = None
        self._advance()

    def _advance(self):
        try:
            self._next = next(self._it)
        except StopIteration:
            self._next = None

    def blocks_left(self) -> bool:
        return self._next is not None

    def next_block(self):
        # IndexError, not StopIteration: a StopIteration escaping from a
        # plain method into a caller's generator frame becomes
        # RuntimeError under PEP 479
        if self._next is None:
            raise IndexError("no blocks left")
        out = self._next
        self._advance()
        return out

    def stop(self) -> None:
        self._it = iter(())
        self._next = None


def _bin_entries(row_blk_sizes, col_blk_sizes, rows, cols):
    """Assign each entry a shape-bin id and an in-bin slot (key order).

    Avoids sorting the (possibly huge) entry list: distinct block SIZES
    are few (the reference enumerates them the same way,
    `dbcsr_mm_common.F:309`), so bin ids come from a small size->id
    lookup and slots from per-bin cumulative counts.
    """
    n = len(rows)
    if n == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32), []
    ur = np.unique(row_blk_sizes)
    uc = np.unique(col_blk_sizes)
    if len(ur) * len(uc) > max(4 * n, 1 << 20):
        # degenerate many-distinct-sizes case: dense size table would
        # dwarf the entry list; pay the O(n log n) sort instead
        code64 = row_blk_sizes[rows].astype(np.int64) << 32 | col_blk_sizes[cols]
        uniq, inv = np.unique(code64, return_inverse=True)
        inv = inv.astype(np.int32)
        shapes = [(int(u >> 32), int(u & 0xFFFFFFFF)) for u in uniq]
    else:
        # size -> small id per entry via tiny searchsorted tables
        rid = np.searchsorted(ur, row_blk_sizes[rows])
        cid = np.searchsorted(uc, col_blk_sizes[cols])
        code = rid.astype(np.int32) * len(uc) + cid
        counts_all = np.bincount(code, minlength=len(ur) * len(uc))
        present = np.nonzero(counts_all)[0]
        remap = np.zeros(len(ur) * len(uc), np.int32)
        remap[present] = np.arange(len(present), dtype=np.int32)
        inv = remap[code]
        shapes = [(int(ur[p // len(uc)]), int(uc[p % len(uc)])) for p in present]
    nbins = len(shapes)
    if nbins == 1:
        return inv, np.arange(n, dtype=np.int32), shapes
    slots = np.empty(n, np.int32)
    if nbins <= 16:
        for b in range(nbins):
            idx = np.nonzero(inv == b)[0]
            slots[idx] = np.arange(len(idx), dtype=np.int32)
    else:
        counts = np.bincount(inv, minlength=nbins)
        starts = np.concatenate([[0], np.cumsum(counts[:-1])])
        order = np.argsort(inv, kind="stable")
        slots[order] = (np.arange(n) - np.repeat(starts, counts)).astype(np.int32)
    return inv, slots, shapes


def create(
    name: str,
    row_blk_sizes,
    col_blk_sizes,
    dtype=np.float64,
    dist: Optional[Distribution] = None,
    matrix_type: str = NO_SYMMETRY,
) -> BlockSparseMatrix:
    """Ref `dbcsr_create` (`src/work/dbcsr_work_operations.F:106`)."""
    return BlockSparseMatrix(name, row_blk_sizes, col_blk_sizes, dtype, dist, matrix_type)
