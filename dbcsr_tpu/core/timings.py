"""Timer framework.

Analog of the reference timing subsystem (`src/core/dbcsr_timings.F`:
timeset/timestop handlers with a call stack, per-routine self/total
time; report at `dbcsr_timings_report.F:51`; cachegrind callgraph export
at :303).  Host apps can override via `set_hooks`, mirroring
`dbcsr_base_hooks.F:88-110`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time

# stdlib-only module; feeds every timed() region to the span tracer
# when one is active (obs.tracer._tracer is None otherwise — a single
# attribute check on the off path)
from dbcsr_tpu.obs import tracer as _trace


@dataclasses.dataclass
class _RoutineStat:
    calls: int = 0
    total: float = 0.0  # inclusive
    self_time: float = 0.0  # exclusive
    callees: dict = dataclasses.field(default_factory=dict)  # name -> (calls, time)


_stats: dict[str, _RoutineStat] = {}
_stack: list[list] = []  # entries: [name, t_start, child_time]
_hooks = None  # optional (timeset_fn, timestop_fn) override


def set_hooks(timeset_fn, timestop_fn) -> None:
    """Install host-application timer hooks (ref `dbcsr_init_lib_hooks`,
    `dbcsr_base_hooks.F:54-110`); ``set_hooks(None, None)`` restores
    the built-in timer."""
    global _hooks
    _hooks = None if timeset_fn is None and timestop_fn is None else (
        timeset_fn, timestop_fn
    )


def timeset(name: str) -> None:
    if _hooks:
        _hooks[0](name)
        return
    _stack.append([name, time.perf_counter(), 0.0])
    if _trace._tracer is not None:
        _trace._tracer.begin(name)


def timestop(name: str) -> None:
    if _hooks:
        _hooks[1](name)
        return
    ent = _stack.pop()
    assert ent[0] == name, f"timer mismatch: stopped {name}, open {ent[0]}"
    dt = time.perf_counter() - ent[1]
    if _trace._tracer is not None:
        _trace._tracer.end(name, dur_s=dt)
    st = _stats.setdefault(name, _RoutineStat())
    st.calls += 1
    st.total += dt
    st.self_time += dt - ent[2]
    if _stack:
        parent = _stack[-1]
        parent[2] += dt
        pst = _stats.setdefault(parent[0], _RoutineStat())
        c, t = pst.callees.get(name, (0, 0.0))
        pst.callees[name] = (c + 1, t + dt)


# resolved once on first use: timed() sits on every phase boundary and
# the per-call import lookup is measurable at driver-loop frequency
_TraceAnnotation = None
_ta_resolved = False


@contextlib.contextmanager
def timed(name: str):
    """Timer + device-profiler range.

    Besides the host timer, each phase is emitted as a
    `jax.profiler.TraceAnnotation` so xprof/perfetto traces show the
    engine phases — the NVTX/ROCTX range analog
    (`src/acc/cuda/dbcsr_cuda_nvtx_cu.cpp`, `dbcsr_cuda_profiling.F`).
    The host-side span goes to `obs.tracer` (via timeset/timestop) with
    the same name, so the Chrome-trace export lines up with device
    profiles.
    """
    global _TraceAnnotation, _ta_resolved
    if not _ta_resolved:
        try:
            from jax.profiler import TraceAnnotation as _ta

            _TraceAnnotation = _ta
        except ImportError:  # pragma: no cover - jax always present
            _TraceAnnotation = None
        _ta_resolved = True
    timeset(name)
    try:
        if _TraceAnnotation is None:
            yield
        else:
            with _TraceAnnotation(f"dbcsr_tpu:{name}"):
                yield
    finally:
        timestop(name)


def reset() -> None:
    _stats.clear()
    _stack.clear()
    if _trace._tracer is not None:
        # keep the tracer's span stack in sync with the timer stack
        _trace._tracer._span_stack.clear()


def report(out=print, top: int = 30, aggregate: bool = False) -> None:
    """Per-routine table, self-time ordered (ref timings_report.F:51).

    ``aggregate=True`` in a multi-process world prints the
    rank-aggregated table — AVERAGE and MAX self/total time per routine
    across processes, on the coordinator only (ref the MPI-aggregated
    report, `dbcsr_timings_report.F:51-301`)."""
    if aggregate:
        import jax
    if aggregate and jax.process_count() > 1:
        rows = _aggregate_ranks()
        if rows is None or jax.process_index() != 0:
            return
        out(" " + "-" * 88)
        out(" -" + f"T I M I N G  ({jax.process_count()} ranks)".center(86) + "-")
        out(" " + "-" * 88)
        out(f" {'SUBROUTINE':<30} {'CALLS':>8} {'SELF avg':>10} "
            f"{'SELF max':>10} {'TOT avg':>10} {'TOT max':>10}")
        for name, calls, s_avg, s_max, t_avg, t_max in rows[:top]:
            out(f" {name:<30} {calls:>8} {s_avg:>10.3f} {s_max:>10.3f} "
                f"{t_avg:>10.3f} {t_max:>10.3f}")
        out(" " + "-" * 88)
        return
    if not _stats:
        return
    out(" " + "-" * 70)
    out(" -" + "T I M I N G".center(68) + "-")
    out(" " + "-" * 70)
    out(f" {'SUBROUTINE':<36} {'CALLS':>8} {'SELF [s]':>11} {'TOTAL [s]':>11}")
    rows = sorted(_stats.items(), key=lambda kv: -kv[1].self_time)[:top]
    for name, st in rows:
        out(f" {name:<36} {st.calls:>8} {st.self_time:>11.3f} {st.total:>11.3f}")
    out(" " + "-" * 70)


_AGG_MAX_ROUTINES = 64
_AGG_NAME_BYTES = 40


def _aggregate_ranks():
    """Gather every rank's (name, calls, self, total) table via
    `process_allgather` (fixed-shape padded arrays — routine sets may
    differ per rank) and reduce to per-routine avg/max rows sorted by
    avg self time.  Returns None when no rank has timings."""
    import numpy as np
    from jax.experimental import multihost_utils

    local = sorted(_stats.items(), key=lambda kv: -kv[1].self_time)
    local = local[:_AGG_MAX_ROUTINES]
    names = np.zeros((_AGG_MAX_ROUTINES, _AGG_NAME_BYTES), np.uint8)
    vals = np.zeros((_AGG_MAX_ROUTINES, 3), np.float64)
    for i, (name, st) in enumerate(local):
        raw = name.encode()
        if len(raw) > _AGG_NAME_BYTES:
            # keep long names distinct after truncation: last 6 bytes
            # carry a content hash, not the (possibly shared) prefix
            import hashlib

            raw = raw[: _AGG_NAME_BYTES - 6] + hashlib.sha1(raw).hexdigest()[:6].encode()
        names[i, : len(raw)] = np.frombuffer(raw, np.uint8)
        vals[i] = (st.calls, st.self_time, st.total)
    gathered = multihost_utils.process_allgather((names, vals))
    all_names = np.asarray(gathered[0])
    all_vals = np.asarray(gathered[1])
    table = {}
    for r in range(all_names.shape[0]):
        for i in range(_AGG_MAX_ROUTINES):
            raw = bytes(all_names[r, i][all_names[r, i] != 0])
            if not raw:
                continue
            name = raw.decode(errors="replace")
            calls, s, t = all_vals[r, i]
            e = table.setdefault(name, [0, [], []])
            e[0] = max(e[0], int(calls))
            e[1].append(float(s))
            e[2].append(float(t))
    if not table:
        return None
    nproc = all_names.shape[0]
    rows = []
    for name, (calls, selfs, tots) in table.items():
        # ranks missing the routine contribute 0 to the average, like
        # the reference's sum/nranks
        s_avg = sum(selfs) / nproc
        t_avg = sum(tots) / nproc
        rows.append((name, calls, s_avg, max(selfs), t_avg, max(tots)))
    rows.sort(key=lambda r: -r[2])
    return rows


def export_callgraph(path: str) -> None:
    """Cachegrind-format callgraph (ref timings_report.F:303-351)."""
    with open(path, "w") as f:
        f.write("events: Walltime_usec\n\n")
        for name, st in _stats.items():
            f.write(f"fn={name}\n1 {int(st.self_time * 1e6)}\n")
            for callee, (calls, t) in st.callees.items():
                f.write(f"cfn={callee}\ncalls={calls} 1\n1 {int(t * 1e6)}\n")
            f.write("\n")
