"""Global configuration.

Analog of the reference `dbcsr_cfg` singleton of typed CONF_PAR entries
(`src/core/dbcsr_config.F:142-172`), with env-var overrides
(``DBCSR_TPU_<NAME>``) and programmatic `set_config` like
`dbcsr_set_config` (`src/dbcsr_api.F:174`).

Knobs that only make sense for CUDA streams/OpenMP threads are replaced
by their TPU-native equivalents (stack-size bucketing for jit-cache
reuse, pallas kernel toggles, mesh defaults).
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass
class Config:
    # --- multiply driver selection (ref MM_DRIVER {auto,matmul,blas,smm,xsmm},
    #     dbcsr_config.F:34-38) -> here {auto, xla, xla_group, pallas,
    #     pallas_cross, dense, host} ("host" = native C++ stack driver on
    #     CPU backends, the ref smm/blas CPU path)
    mm_driver: str = "auto"
    # max entries pushed to the device per kernel call before flushing
    # (ref MM_STACK_SIZE: 30000 accel / 1000 CPU, dbcsr_config.F:77-79)
    mm_stack_size: int = 30000
    # dense-mode multiply for near-full matrices with uniform blocking
    # (ref MM_DENSE + decision at dbcsr_mm.F:593-617); None = auto
    mm_dense: object = None
    dense_occ_threshold: float = 0.8
    # TPU cost model for EMULATED dtypes (f64/c128): below the occupancy
    # threshold, still go dense when dense_flops < ratio * true_flops —
    # the measured dense:grouped-sparse throughput advantage on a v5e is
    # ~320x for f64 (PERF_NOTES.md); 0 disables the cost model
    dense_flop_ratio: float = 250.0
    # ---- adaptive storage-format planner (mm/format_planner.py; env
    #      DBCSR_TPU_MM_FORMAT) ----
    # per-product execution format: "auto" (the planner picks between
    # the BCSR shape-bucketed stack path, the whole-panel padded dense
    # GEMM, and the block-diagonal composite panel from the pattern
    # fingerprint's occupancy, the live roofline, and learned per-device
    # crossover rows in the tune params table), or a forced
    # "stack"/"dense"/"composite" (A/B legs; a forced format that is
    # structurally ineligible — e.g. composite with no independent row
    # panels — falls back to stack, counted under reason="ineligible")
    mm_format: str = "auto"
    # composite panel packing limits (mm/multiply.py:composite_panels):
    # most row-panels one batched GEMM may carry, and the largest
    # fraction of the k-dimension a panel's k-support may span while
    # still counting as "narrow" (above it the batched GEMM does the
    # same flops as whole-panel dense and the batching is pure overhead)
    composite_max_panels: int = 64
    composite_ksup: float = 0.75
    # use the fused pallas SMM kernel when available (ref: libsmm_acc JIT
    # kernels vs cuBLAS loop)
    use_pallas: bool = True
    # validate pallas kernels against the XLA path on first use per
    # (m,n,k,dtype), like libsmm_acc's JIT-time checksum validation
    # (libsmm_acc.cpp:216)
    validate_kernels: bool = True
    # lay A/B out as (N, m*k) flat rows before the per-entry gather so
    # gathers move lane-packed rows instead of tile-padded blocks
    # (see acc/smm.py:_process_stack_xla_flat)
    flat_gather: bool = False
    # fused superstack launches (acc/smm.py:execute_superstack): all
    # spans sharing a destination C bin lower into ONE donated-C
    # program — "auto" (fuse whenever a bin's spans can), "fused"
    # (same, explicit), or "per_span" (the historical one-dispatch-
    # per-span engine).  Env: DBCSR_TPU_SUPERSTACK.
    superstack: str = "auto"
    # distributed Cannon tick scheduling (parallel/cannon.py +
    # parallel/sparse_dist.py): "double_buffer" issues tick k+1's A/B
    # ring shifts against a second operand buffer BEFORE tick k's
    # contraction is consumed (per-tick dispatches; the comm-thread
    # overlap of the reference's async isend/irecv panel exchange,
    # dbcsr_mpiwrap.F:305-421), "serial" is the bitwise-reference
    # single-program shift-after-compute path, "auto" double-buffers
    # whenever the grid actually ring-shifts (s > 1 square Cannon).
    # Env: DBCSR_TPU_CANNON_OVERLAP.
    cannon_overlap: str = "auto"
    # keep per-(m,n,k) flop statistics (ref STATISTICS block)
    keep_stats: bool = True
    # largest block dim the fused Pallas kernel handles; bigger blocks
    # take the XLA dot path (ref max_kernel_dim=80 with cuBLAS-loop
    # fallback, dbcsr_config.F:177, libsmm_acc.cpp:227-249)
    max_kernel_dim: int = 256
    # multiplier on the TAS split-factor estimate
    # (ref TAS_SPLIT_FACTOR, dbcsr_config.F:170)
    tas_split_factor: float = 1.0
    # default 2.5D k-layer count for auto-built meshes
    # (ref NUM_LAYERS_3D, dbcsr_config.F:152); 0 = auto (largest square),
    # any value >= 1 is honored exactly (1 forces a 2D grid and raises
    # when the device count is not a square)
    num_layers_3d: int = 0
    # ---- serving plane (dbcsr_tpu.serve; env DBCSR_TPU_SERVE_*) ----
    # bound on queued requests; beyond it submissions shed queue_full
    serve_queue_max: int = 256
    # cross-request batching window: how long the worker waits for
    # more same-structure requests after popping one (0 disables the
    # wait; coalescing then only groups requests already queued)
    serve_window_ms: float = 5.0
    # master switch for block-diagonal composite execution; off =
    # every request runs serialized (the A/B control leg)
    serve_coalesce: bool = True
    # largest request group one composite multiply may carry
    serve_coalesce_max: int = 8
    # per-tenant quota: queued + running requests
    serve_tenant_inflight: int = 8
    # per-tenant quota: operand bytes queued (a+b+c device bytes)
    serve_tenant_bytes: int = 256 * 1024 * 1024
    # deadline assigned under a DEGRADED health verdict when the
    # request didn't bring its own (seconds)
    serve_degraded_deadline_s: float = 10.0
    # ---- end-to-end data integrity (acc/abft.py; env DBCSR_TPU_ABFT) --
    # ABFT probe checksums at the stack/superstack boundary: "off" (no
    # checks — the production default), "verify" (rank-1 C·v vs
    # A·(B·v) probe per launch; a mismatch classifies `sdc`, feeds the
    # per-(driver, shape) breaker and re-executes down the failover
    # chain), "recover" (verify, plus every recovery re-execution is
    # itself probe-checked before being accepted).  The knob also arms
    # the chain-invariant rollback in models/ and the serving plane's
    # per-request probe (docs/resilience.md § ABFT).
    abft: str = "off"
    # ---- mixed-precision block GEMMs (acc/precision.py; env
    #      DBCSR_TPU_PRECISION) ----
    # compute-dtype policy of the stack engine: "native" (every stack
    # executes at the request dtype — the historical engine), "adaptive"
    # (demote eligible stacks to a narrower compute dtype with
    # wide-dtype accumulation, certified per launch by the ABFT probe
    # and promoted back per (m,n,k,dtype) cell when a probe residual
    # breaches its demotion ceiling or an ops chain tightens past the
    # demoted error floor; inert unless the ABFT plane is on), "f32" /
    # "bf16" (force the demoted compute dtype with two-product
    # compensation, no certification requirement — benchmark/test legs)
    precision: str = "native"
    # ---- delta-aware incremental multiply (mm/incremental.py; env
    #      DBCSR_TPU_INCREMENTAL) ----
    # "auto" (delta-aware: a repeated beta==0 product whose operands
    # carry a known dirty-block delta recomputes only the affected C
    # blocks and splices the rest from the cached device-resident
    # result — bitwise-identical by construction), "off" (machinery
    # fully disabled, zero overhead — the historical engine), "full"
    # (track deltas and maintain the result cache but always recompute
    # fully: the A/B control leg that carries the bookkeeping cost)
    incremental: str = "auto"
    # ---- serve-layer content-addressed product cache (serve/
    #      product_cache.py; env DBCSR_TPU_SERVE_PRODUCT_CACHE*) ----
    # identical (A, B, scalars, flags) submissions — keyed by VALUE
    # digests, invalidated through the mutation-epoch machinery —
    # return the cached C without an engine dispatch
    serve_product_cache: bool = True
    serve_product_cache_entries: int = 32
    serve_product_cache_bytes: int = 128 * 1024 * 1024
    # platform-injection seam (VERDICT r4 item 5): "" = the real JAX
    # backend platform; "tpu"/"cpu" makes every dispatch DECISION
    # (_pallas_supported, _dense_mode_wanted, emulated-dtype R-tiling)
    # behave as if running there, so the CPU suite can assert TPU-only
    # dispatch branches without hardware.  Execution-level choices
    # (pallas interpret=, device placement) always follow the REAL
    # platform — the seam steers policy, never lowering, so a faked
    # "tpu" still runs correctly (if non-production-shaped) on CPU.
    # Analog of the careful-mode dispatch asserts the reference keeps
    # testable off-GPU (dbcsr_mm_sched.F:295-321).
    platform_override: str = ""

    def validate(self) -> None:
        if self.platform_override not in ("", "tpu", "cpu"):
            raise ValueError(
                f"platform_override must be ''/'tpu'/'cpu', "
                f"got {self.platform_override!r}")
        if self.mm_driver not in ("auto", "xla", "xla_group", "pallas",
                                  "pallas_cross", "dense", "host"):
            raise ValueError(f"unknown mm_driver {self.mm_driver!r}")
        if self.mm_format not in ("auto", "stack", "dense", "composite"):
            raise ValueError(
                f"mm_format must be 'auto'/'stack'/'dense'/'composite', "
                f"got {self.mm_format!r}")
        if self.composite_max_panels < 2:
            raise ValueError("composite_max_panels must be >= 2")
        if not 0.0 < self.composite_ksup <= 1.0:
            raise ValueError("composite_ksup must be in (0, 1]")
        if self.superstack not in ("auto", "fused", "per_span"):
            raise ValueError(
                f"superstack must be 'auto'/'fused'/'per_span', "
                f"got {self.superstack!r}")
        if self.cannon_overlap not in ("auto", "double_buffer", "serial"):
            raise ValueError(
                f"cannon_overlap must be 'auto'/'double_buffer'/'serial', "
                f"got {self.cannon_overlap!r}")
        if self.mm_stack_size <= 0:
            raise ValueError("mm_stack_size must be positive")
        if self.max_kernel_dim <= 0:
            raise ValueError("max_kernel_dim must be positive")
        if self.tas_split_factor <= 0:
            raise ValueError("tas_split_factor must be positive")
        if self.num_layers_3d < 0:
            raise ValueError("num_layers_3d must be >= 0")
        if self.serve_queue_max <= 0:
            raise ValueError("serve_queue_max must be positive")
        if self.serve_window_ms < 0:
            raise ValueError("serve_window_ms must be >= 0")
        if self.serve_coalesce_max < 1:
            raise ValueError("serve_coalesce_max must be >= 1")
        if self.serve_tenant_inflight <= 0:
            raise ValueError("serve_tenant_inflight must be positive")
        if self.serve_tenant_bytes <= 0:
            raise ValueError("serve_tenant_bytes must be positive")
        if self.serve_degraded_deadline_s <= 0:
            raise ValueError("serve_degraded_deadline_s must be positive")
        if self.abft not in ("off", "verify", "recover"):
            raise ValueError(
                f"abft must be 'off'/'verify'/'recover', got {self.abft!r}")
        if self.precision not in ("native", "adaptive", "f32", "bf16"):
            raise ValueError(
                f"precision must be 'native'/'adaptive'/'f32'/'bf16', "
                f"got {self.precision!r}")
        if self.incremental not in ("auto", "off", "full"):
            raise ValueError(
                f"incremental must be 'auto'/'off'/'full', "
                f"got {self.incremental!r}")
        if self.serve_product_cache_entries < 1:
            raise ValueError("serve_product_cache_entries must be >= 1")
        if self.serve_product_cache_bytes <= 0:
            raise ValueError("serve_product_cache_bytes must be positive")


_cfg = Config()


def _apply_env(cfg: Config) -> None:
    for f in dataclasses.fields(Config):
        env = os.environ.get(f"DBCSR_TPU_{f.name.upper()}")
        if env is None:
            continue
        if f.name == "mm_dense":
            setattr(cfg, f.name, env.lower() in ("1", "true", "yes"))
        elif isinstance(getattr(cfg, f.name), bool):
            setattr(cfg, f.name, env.lower() in ("1", "true", "yes"))
        elif isinstance(getattr(cfg, f.name), int):
            setattr(cfg, f.name, int(env))
        elif isinstance(getattr(cfg, f.name), float):
            setattr(cfg, f.name, float(env))
        else:
            setattr(cfg, f.name, env)
    # fail FAST on a typo'd env knob (DBCSR_TPU_SUPERSTACK=per-span,
    # DBCSR_TPU_MM_DRIVER=xla_grp, ...): silently running a different
    # configuration than the operator asked for poisons A/B evidence —
    # the same contract set_config enforces for programmatic updates
    cfg.validate()


_apply_env(_cfg)


def get_config() -> Config:
    return _cfg


def set_config(**kwargs) -> None:
    """Programmatic config update (ref `dbcsr_set_config`).

    Validates on a candidate copy first: a rejected update must leave
    the live config untouched."""
    for k in kwargs:
        if not hasattr(_cfg, k):
            raise ValueError(f"unknown config key {k!r}")
    candidate = dataclasses.replace(_cfg, **kwargs)
    candidate.validate()
    for k, v in kwargs.items():
        setattr(_cfg, k, v)


def print_config(out=print) -> None:
    """Ref `dbcsr_print_config`."""
    for f in dataclasses.fields(Config):
        out(f"  dbcsr_tpu.{f.name:<28} {getattr(_cfg, f.name)}")


def effective_platform() -> str:
    """The platform dispatch DECISIONS key on: `platform_override` when
    set (the CPU suite's seam for asserting TPU-only branches), else
    the real JAX backend platform.  Execution-level code (interpret=
    flags, device placement) must NOT use this — it reads the real
    platform directly, so an override never changes lowering."""
    if _cfg.platform_override:
        return _cfg.platform_override
    import jax

    return jax.devices()[0].platform


def get_default_config() -> Config:
    """A fresh Config with compile-time defaults — env overrides NOT
    applied (ref `dbcsr_get_default_config`, `dbcsr_api.F:175`)."""
    return Config()
