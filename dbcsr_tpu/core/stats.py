"""Multiplication statistics registry.

Analog of the reference STATISTICS block: per-(m,n,k) flop counters with
driver breakdown, stack counts and sizes (`src/mm/dbcsr_mm_sched.F:390-546`
stats_add/collect/print), marketing-vs-true flops (`dbcsr_mm.F:664-667`).
"""

from __future__ import annotations

import collections
import dataclasses


@dataclasses.dataclass
class _MnkStat:
    nstacks: int = 0
    nentries: int = 0
    flops: int = 0
    by_driver: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _CommStat:
    nmessages: int = 0
    nbytes: int = 0


_by_mnk: dict = collections.defaultdict(_MnkStat)
_comm: dict = collections.defaultdict(_CommStat)
_totals = {"multiplies": 0, "flops": 0, "marketing_flops": 0}


def record_stack(m: int, n: int, k: int, nentries: int, *,
                 driver: str) -> None:
    """Per-(m,n,k) stack accounting with a DRIVER breakdown — the
    reference's BLAS/SMM/ACC split (`dbcsr_mm_sched.F:390-546`) maps to
    {xla, xla_flat, xla_group, pallas, dense, mesh} here."""
    from dbcsr_tpu.core.config import get_config

    if not get_config().keep_stats:
        return
    st = _by_mnk[(m, n, k)]
    st.nstacks += 1
    st.nentries += nentries
    st.flops += 2 * m * n * k * nentries
    st.by_driver[driver] = st.by_driver.get(driver, 0) + 2 * m * n * k * nentries


def record_comm(kind: str, nmessages: int, nbytes: int) -> None:
    """Collective-traffic counters (analog of the reference's MPI
    statistics: message counts/sizes per class,
    `dbcsr_mm_common.F:135` count_mpi_statistics /
    `dbcsr_mpi_statistics_type`).  ``kind`` names the collective
    ('ppermute', 'psum', 'alltoall', 'host2dev', ...)."""
    from dbcsr_tpu.core.config import get_config

    if not get_config().keep_stats:
        return
    st = _comm[kind]
    st.nmessages += int(nmessages)
    st.nbytes += int(nbytes)


def record_multiply(marketing_flops: int) -> None:
    _totals["multiplies"] += 1
    _totals["marketing_flops"] += marketing_flops


def total_flops() -> int:
    return sum(s.flops for s in _by_mnk.values())


def reset() -> None:
    _by_mnk.clear()
    _comm.clear()
    for k in _totals:
        _totals[k] = 0


def print_statistics(out=print) -> None:
    """Format mirrors the reference's DBCSR STATISTICS table
    (documented in `docs/guide/3-developer-guide/4-performance/1-insights.md`)."""
    out(" " + "-" * 70)
    out(" -" + "DBCSR-TPU STATISTICS".center(68) + "-")
    out(" " + "-" * 70)
    out(f" {'COUNT':>24} {'m x n x k':>14} {'entries':>12} {'GFLOP':>12}"
        f"  {'drivers'}")
    tot = 0
    for (m, n, k), st in sorted(_by_mnk.items()):
        tot += st.flops
        drv = ",".join(f"{d}={f / 1e9:.2f}" for d, f in sorted(st.by_driver.items()))
        out(
            f" {st.nstacks:>24} {f'{m}x{n}x{k}':>14} {st.nentries:>12}"
            f" {st.flops / 1e9:>12.3f}  {drv}"
        )
    out(f" {'total (TPU stacks)':>24} {'':>14} {'':>12} {tot / 1e9:>12.3f}")
    out(f" multiplications:       {_totals['multiplies']}")
    out(f" marketing flops:       {_totals['marketing_flops'] / 1e9:.3f} GFLOP")
    if _comm:
        out(" -" + "COLLECTIVE TRAFFIC".center(68) + "-")
        out(f" {'collective':>24} {'messages':>14} {'MB':>12}")
        for kind, st in sorted(_comm.items()):
            out(f" {kind:>24} {st.nmessages:>14} {st.nbytes / 1e6:>12.2f}")
    out(" " + "-" * 70)
