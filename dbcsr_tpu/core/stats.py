"""Multiplication statistics registry.

Analog of the reference STATISTICS block: per-(m,n,k) flop counters with
driver breakdown, stack counts and sizes (`src/mm/dbcsr_mm_sched.F:390-546`
stats_add/collect/print), marketing-vs-true flops (`dbcsr_mm.F:664-667`).
"""

from __future__ import annotations

import collections
import dataclasses

# stdlib-only module; record_* feed the span tracer when one is active
# (one attribute check on the off path — see obs/tracer.py)
from dbcsr_tpu.obs import tracer as _trace


@dataclasses.dataclass
class _MnkStat:
    nstacks: int = 0
    nentries: int = 0
    flops: int = 0
    by_driver: dict = dataclasses.field(default_factory=dict)
    # flops keyed (driver, dtype) — the full (driver, shape-bucket,
    # dtype) evidence cell the telemetry time-series store samples
    # (obs/timeseries.py); callers without a dtype land under ""
    by_driver_dtype: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _CommStat:
    nmessages: int = 0
    nbytes: int = 0


@dataclasses.dataclass
class _DriverAgg:
    """Per-driver attribution rollup: flops + modeled HBM bytes
    (`obs.costmodel` convention) + host-side dispatch seconds, with a
    per-dtype flop split so the roofline denominator can use the
    dominant dtype's peak.  ``sync_stacks`` counts the regions whose
    seconds were recorded through block_until_ready (DBCSR_TPU_SYNC_
    TIMING at record time) — a rollup row is labeled synchronized only
    when EVERY region was."""
    flops: int = 0
    nbytes: int = 0
    seconds: float = 0.0
    stacks: int = 0
    sync_stacks: int = 0
    by_dtype: dict = dataclasses.field(default_factory=dict)


_by_mnk: dict = collections.defaultdict(_MnkStat)
_comm: dict = collections.defaultdict(_CommStat)
_driver_agg: dict = collections.defaultdict(_DriverAgg)
_totals = {"multiplies": 0, "flops": 0, "marketing_flops": 0}


def _agg_driver(driver: str, flops: int, nbytes: int, seconds: float,
                dtype: str, stacks: int, sync: bool = False) -> None:
    """The one place the per-driver rollup is updated (callers have
    already passed the keep_stats gate)."""
    agg = _driver_agg[driver]
    agg.flops += flops
    agg.nbytes += nbytes
    agg.seconds += seconds
    agg.stacks += stacks
    if sync:
        agg.sync_stacks += stacks
    if dtype:
        agg.by_dtype[dtype] = agg.by_dtype.get(dtype, 0) + flops


def sync_timing_enabled() -> bool:
    """Opt-in synchronized stack timing (``DBCSR_TPU_SYNC_TIMING=1``):
    the multiply engine times each stack/superstack launch through
    ``jax.block_until_ready`` instead of recording dispatch-side
    seconds, so per-driver achieved GFLOP/s in the roofline rollup
    reflects device completion rather than async dispatch.  Each
    record carries its own flag value (``_DriverAgg.sync_stacks``);
    a rollup row reads ``sync=true`` only when EVERY recorded region
    was synchronized, so mid-process flips never mislabel mixed
    aggregates.  Read from the environment per call (once per
    multiply) so tests and in-process A/Bs can flip it."""
    import os

    return os.environ.get("DBCSR_TPU_SYNC_TIMING") == "1"


def record_driver(driver: str, flops: int, *, nbytes: int = 0,
                  seconds: float = 0.0, dtype: str = "",
                  stacks: int = 1, sync: bool = False) -> None:
    """Attribute one executed region (a stack launch, a dense matmul,
    a mesh plan execution) to its driver: flops, modeled bytes moved,
    and host-observed seconds.  Seconds are DISPATCH-side wall time
    unless the caller timed through block_until_ready and says so with
    ``sync=True`` — on async backends the device may still be
    draining, so per-driver achieved GFLOP/s is an attribution signal,
    not a benchmark; the forced-fetch bench numbers remain the ground
    truth."""
    from dbcsr_tpu.core.config import get_config

    if not get_config().keep_stats:
        return
    _agg_driver(driver, flops, nbytes, seconds, dtype, stacks, sync=sync)


def driver_rollup() -> dict:
    """Plain-dict view of the per-driver attribution aggregates."""
    return {
        d: {
            "flops": a.flops,
            "bytes": a.nbytes,
            "seconds": a.seconds,
            "stacks": a.stacks,
            "sync_stacks": a.sync_stacks,
            "by_dtype": dict(a.by_dtype),
        }
        for d, a in _driver_agg.items()
    }


def record_stack(m: int, n: int, k: int, nentries: int, *,
                 driver: str, seconds: float | None = None,
                 nbytes: int | None = None, dtype: str = "",
                 sync: bool = False) -> None:
    """Per-(m,n,k) stack accounting with a DRIVER breakdown — the
    reference's BLAS/SMM/ACC split (`dbcsr_mm_sched.F:390-546`) maps to
    {xla, xla_flat, xla_group, pallas, dense, mesh} here.  ``seconds``
    / ``nbytes`` / ``dtype`` additionally feed the per-driver roofline
    rollup (`record_driver`); callers without a cost model pass none
    and still appear in the flop breakdown.  ``sync`` marks seconds
    timed through block_until_ready (see `sync_timing_enabled`)."""
    from dbcsr_tpu.core.config import get_config

    if not get_config().keep_stats:
        return
    flops = 2 * m * n * k * nentries
    st = _by_mnk[(m, n, k)]
    st.nstacks += 1
    st.nentries += nentries
    st.flops += flops
    st.by_driver[driver] = st.by_driver.get(driver, 0) + flops
    cell = (driver, dtype)
    st.by_driver_dtype[cell] = st.by_driver_dtype.get(cell, 0) + flops
    _agg_driver(driver, flops, nbytes or 0, seconds or 0.0, dtype, 1,
                sync=sync)
    t = _trace._tracer
    if t is not None:
        t.instant("stack", {"mnk": f"{m}x{n}x{k}", "entries": nentries,
                            "driver": driver})
        t.add("stack_entries", nentries)


def record_comm(kind: str, nmessages: int, nbytes: int) -> None:
    """Collective-traffic counters (analog of the reference's MPI
    statistics: message counts/sizes per class,
    `dbcsr_mm_common.F:135` count_mpi_statistics /
    `dbcsr_mpi_statistics_type`).  ``kind`` names the collective
    ('ppermute', 'psum', 'alltoall', 'host2dev', ...)."""
    from dbcsr_tpu.core.config import get_config

    if not get_config().keep_stats:
        return
    st = _comm[kind]
    st.nmessages += int(nmessages)
    st.nbytes += int(nbytes)
    t = _trace._tracer
    if t is not None:
        t.instant(f"comm:{kind}", {"messages": int(nmessages),
                                   "bytes": int(nbytes)})
        t.add("comm_bytes", int(nbytes))


def record_multiply(marketing_flops: int) -> None:
    _totals["multiplies"] += 1
    _totals["marketing_flops"] += marketing_flops


# Cannon tick-loop overlap attribution, per (engine, grid): the MODELED
# comm/compute ratio (obs.costmodel.cannon_tick_model /
# mesh_tick_model) next to the MEASURED comm-exposed fraction the
# per-tick driver times under DBCSR_TPU_SYNC_TIMING
# (parallel/overlap.py).  metrics.snapshot()["roofline"] folds this
# into the owning driver's rollup row.
_cannon_overlap: dict = {}


def record_cannon_overlap(engine: str, grid: str, *, mode: str | None = None,
                          modeled: float | None = None,
                          measured: float | None = None,
                          shift_exposed_s: float | None = None,
                          compute_s: float | None = None,
                          drop_measured: bool = False) -> None:
    """Merge one multiply's overlap attribution (modeled ratio and/or
    measured exposed fraction) for an (engine, grid) cell; latest
    values win — this is a point-in-time gauge, not an accumulator.
    ``drop_measured`` clears any earlier measured sample from the cell
    (the degrade path: a serial-delivered product must not keep a
    previous double-buffer run's numbers attached to its mode)."""
    from dbcsr_tpu.core.config import get_config

    if not get_config().keep_stats:
        return
    row = _cannon_overlap.setdefault((engine, grid), {})
    if drop_measured:
        for k in ("measured_exposed", "shift_exposed_s", "compute_s"):
            row.pop(k, None)
    if mode is not None:
        row["mode"] = mode
    if modeled is not None:
        row["modeled_ratio"] = float(modeled)
    if measured is not None:
        row["measured_exposed"] = float(measured)
    if shift_exposed_s is not None:
        row["shift_exposed_s"] = float(shift_exposed_s)
    if compute_s is not None:
        row["compute_s"] = float(compute_s)


def cannon_overlap_rollup() -> dict:
    """{engine: {grid: {mode, modeled_ratio, measured_exposed, ...}}}
    since the last `reset()`."""
    out: dict = {}
    for (engine, grid), row in _cannon_overlap.items():
        out.setdefault(engine, {})[grid] = dict(row)
    return out


# memory high-water meter (analog of `m_memory`, `dbcsr_machine.F`, and
# the `max_memory` line `dbcsr_lib.F:326` prints): host side reads the
# OS-tracked process peak (VmHWM) and current RSS; device side polls the
# PJRT client's allocator stats where the backend provides them (TPU
# does; the CPU backend usually returns nothing).
_memory = {"host_peak": 0, "host_current": 0, "device_peak": 0,
           "device_in_use": 0}
# VmHWM at the last reset(): the OS meter is process-lifetime monotone,
# so "host peak since reset" is VmHWM only when it has grown past this
# baseline; otherwise the best observable bound is max(RSS samples).
_hwm_at_reset = 0


def _read_proc_status(*fields: str):
    """Read byte values for the given `/proc/self/status` prefixes (kB
    fields); returns a tuple in `fields` order, or None on any failure."""
    vals = {f: 0 for f in fields}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                for field in fields:
                    if line.startswith(field):
                        vals[field] = int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    return tuple(vals[f] for f in fields)


def sample_memory() -> None:
    """Update the high-water meters; called at the end of every multiply
    (cheap: one /proc read + one local allocator-stats call)."""
    from dbcsr_tpu.core.config import get_config

    if not get_config().keep_stats:
        return
    meters = _read_proc_status("VmHWM:", "VmRSS:")
    if meters is not None:
        hwm, rss = meters
        _memory["host_current"] = rss
        if hwm > _hwm_at_reset:
            _memory["host_peak"] = hwm
        else:  # peak predates the reset; bound by RSS seen since
            _memory["host_peak"] = max(_memory["host_peak"], rss)
    try:
        import jax

        ms = jax.devices()[0].memory_stats()
        if ms:
            in_use = int(ms.get("bytes_in_use", 0))
            _memory["device_in_use"] = in_use
            _memory["device_peak"] = max(
                _memory["device_peak"],
                int(ms.get("peak_bytes_in_use", in_use)),
            )
    except Exception:  # backend without allocator stats / remote hiccup
        pass


def memory_high_water() -> dict:
    """Current meter values (bytes); see `sample_memory`."""
    return dict(_memory)


def total_flops() -> int:
    return sum(s.flops for s in _by_mnk.values())


def reset() -> None:
    global _hwm_at_reset
    _by_mnk.clear()
    _comm.clear()
    _driver_agg.clear()
    _cannon_overlap.clear()
    for k in _totals:
        _totals[k] = 0
    for k in _memory:
        _memory[k] = 0
    # record the monotone OS high-water mark so later samples report the
    # peak SINCE this reset, not the process-lifetime peak (ADVICE r3)
    meters = _read_proc_status("VmHWM:")
    _hwm_at_reset = meters[0] if meters is not None else 0


def print_statistics(out=print) -> None:
    """Format mirrors the reference's DBCSR STATISTICS table
    (documented in `docs/guide/3-developer-guide/4-performance/1-insights.md`)."""
    out(" " + "-" * 70)
    out(" -" + "DBCSR-TPU STATISTICS".center(68) + "-")
    out(" " + "-" * 70)
    out(f" {'COUNT':>24} {'m x n x k':>14} {'entries':>12} {'GFLOP':>12}"
        f"  {'drivers'}")
    tot = 0
    for (m, n, k), st in sorted(_by_mnk.items()):
        tot += st.flops
        drv = ",".join(f"{d}={f / 1e9:.2f}" for d, f in sorted(st.by_driver.items()))
        out(
            f" {st.nstacks:>24} {f'{m}x{n}x{k}':>14} {st.nentries:>12}"
            f" {st.flops / 1e9:>12.3f}  {drv}"
        )
    out(f" {'total (TPU stacks)':>24} {'':>14} {'':>12} {tot / 1e9:>12.3f}")
    out(f" multiplications:       {_totals['multiplies']}")
    out(f" marketing flops:       {_totals['marketing_flops'] / 1e9:.3f} GFLOP")
    if _comm:
        out(" -" + "COLLECTIVE TRAFFIC".center(68) + "-")
        out(f" {'collective':>24} {'messages':>14} {'MB':>12}")
        for kind, st in sorted(_comm.items()):
            out(f" {kind:>24} {st.nmessages:>14} {st.nbytes / 1e6:>12.2f}")
    if _memory["host_peak"]:
        # ref the `max_memory` line of the lib print (`dbcsr_lib.F:326`)
        out(" -" + "MEMORY USAGE".center(68) + "-")
        out(f" {'host peak (VmHWM)':>24} {_memory['host_peak'] / 1e6:>14.1f} MB")
        out(f" {'host current (VmRSS)':>24} {_memory['host_current'] / 1e6:>14.1f} MB")
        if _memory["device_peak"]:
            out(f" {'device peak':>24} {_memory['device_peak'] / 1e6:>14.1f} MB")
            out(f" {'device in use':>24} {_memory['device_in_use'] / 1e6:>14.1f} MB")
    out(" " + "-" * 70)
