"""Device memory pool, chain ownership, and persistent device mirrors.

The analog of the reference's data-area memory pools
(`dbcsr_mem_methods.F`: `dbcsr_mempool_get`/`dbcsr_mempool_add` over
`dbcsr_memtype_type` areas, `dbcsr_data_types.F:86-114`): repeated
multiplies in an iterative workload (McWeeny purification, Newton–
Schulz sign/invsqrt) should never re-allocate device storage or
re-stage index arrays the previous iteration already placed on device.

Three cooperating mechanisms, all env-gated by ``DBCSR_TPU_POOL``:

* **The buffer pool** (`zeros`/`release`): freed bin buffers are kept
  keyed by (shape, dtype) and recycled through a donated
  ``zeros_like`` program, so XLA writes zeros INTO the retired buffer
  instead of allocating a new one — the jax realization of
  `dbcsr_mempool_get`.  A byte budget (``DBCSR_TPU_POOL_BYTES``) bounds
  held memory; releases beyond it are dropped (eviction), and
  high-water accounting feeds `obs.metrics`.
* **Chain ownership** (`chain`): a context manager that adopts every
  matrix created inside it.  Adopted matrices may donate replaced bin
  buffers back to the pool from the structure-mutation funnels
  (`BlockSparseMatrix.set_structure_from_device` / `map_bin_data`) and
  are freed wholesale when retired or when the chain exits — the
  `dbcsr_release` discipline of the reference's work-matrix lifecycle,
  made explicit.  `BlockSparseMatrix.copy` marks both sides shared,
  which permanently disables donation for those buffers (conservative:
  a shared buffer must never be recycled).
* **Device index mirrors** (`upload_index`): a content-keyed LRU of
  host->device uploads of gather/scatter index arrays (the
  ``jnp.asarray`` calls scattered through the engine).  A
  structure-stable chain uploads each index array once; later
  iterations hit the mirror even when the owning matrices are fresh
  temporaries.  Complemented by `BlockSparseMatrix.device_index`
  (per-matrix mirrors invalidated when the pattern fingerprint
  changes, i.e. on any finalize that alters structure).

H2D/D2H accounting: `record_h2d`/`record_d2h` feed the
``dbcsr_tpu_{h2d,d2h}_bytes_total`` counters and cheap module totals
(`transfer_totals`), instrumented at the engine's staging choke points
— the per-iteration "restage bytes" signal the chained-workload bench
gates on (bytes collapse to ~zero after iteration 1).
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dbcsr_tpu.utils import lockcheck as _lockcheck  # noqa: E402

_lock = _lockcheck.wrap("core.mempool", threading.RLock())

# --------------------------------------------------------------- enable

_enabled = os.environ.get("DBCSR_TPU_POOL", "1") not in ("0", "false", "no")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Programmatic pool/mirror toggle (the bench A/B's unpooled
    control); disabling does not drop already-held buffers — call
    `clear()` for a cold start."""
    global _enabled
    _enabled = bool(on)


def _budget_bytes() -> int:
    try:
        return int(os.environ.get("DBCSR_TPU_POOL_BYTES", str(2 << 30)))
    except ValueError:
        return 2 << 30


# ----------------------------------------------------------- accounting

# module totals are the authoritative cheap stats (metrics counters are
# refreshed alongside so scrapes and snapshots agree)
_stats = {
    "hits": 0, "misses": 0, "returns": 0, "evictions": 0,
    "bytes_held": 0, "high_water": 0, "h2d_bytes": 0, "d2h_bytes": 0,
}

_metric_cache: dict = {}


def _metric(name: str, help: str):
    m = _metric_cache.get(name)
    if m is None:
        from dbcsr_tpu.obs import metrics as _metrics

        m = _metric_cache[name] = _metrics.counter(name, help)
    return m


def _bump(kind: str, n: float = 1) -> None:
    _stats[kind] += n
    _metric(
        f"dbcsr_tpu_pool_{kind}_total",
        "device memory pool events by kind (checkout hits/misses, "
        "buffer returns, budget evictions)",
    ).inc(n)


def _held_gauge(v: int) -> None:
    from dbcsr_tpu.obs import metrics as _metrics

    _metrics.gauge(
        "dbcsr_tpu_pool_bytes_held",
        "device bytes currently held by the memory pool free lists",
    ).set(v)


def record_h2d(nbytes: int) -> None:
    """Count one host->device staging transfer (block data or index
    uploads) — the restage-bytes signal of the chained-workload bench."""
    if nbytes:
        _stats["h2d_bytes"] += int(nbytes)
        _metric("dbcsr_tpu_h2d_bytes_total",
                "host->device bytes staged (block data + index uploads)"
                ).inc(int(nbytes))


def record_d2h(nbytes: int) -> None:
    """Count one device->host fetch (block reads, host-driver C
    round-trips)."""
    if nbytes:
        _stats["d2h_bytes"] += int(nbytes)
        _metric("dbcsr_tpu_d2h_bytes_total",
                "device->host bytes fetched (block reads + host-driver "
                "round-trips)").inc(int(nbytes))


def transfer_totals() -> dict:
    """{"h2d": bytes, "d2h": bytes} since the last `reset_stats`."""
    return {"h2d": _stats["h2d_bytes"], "d2h": _stats["d2h_bytes"]}


def pool_stats() -> dict:
    """Machine-readable pool state for `obs.metrics.snapshot()`."""
    with _lock:
        return {
            "enabled": _enabled,
            "hits": _stats["hits"],
            "misses": _stats["misses"],
            "returns": _stats["returns"],
            "evictions": _stats["evictions"],
            "bytes_held": _stats["bytes_held"],
            "high_water": _stats["high_water"],
            "budget_bytes": _budget_bytes(),
            "buckets": len(_free),
            "mirror_entries": len(_mirror),
            "mirror_bytes": _mirror_bytes,
            "h2d_bytes": _stats["h2d_bytes"],
            "d2h_bytes": _stats["d2h_bytes"],
        }


def reset_stats() -> None:
    """Zero the counters/totals (paired with `obs.metrics.reset`);
    held buffers and mirrors survive — use `clear()` to drop them."""
    with _lock:
        for k in _stats:
            _stats[k] = 0
        _stats["bytes_held"] = sum(
            sum(_arr_bytes(a) for a in lst) for lst in _free.values())
        _stats["high_water"] = _stats["bytes_held"]
        _metric_cache.clear()


# ------------------------------------------------------------ free lists

# (shape, dtype str) -> [retired device arrays]
_free: dict = {}


def _arr_bytes(a) -> int:
    return int(np.prod(a.shape)) * int(jnp.dtype(a.dtype).itemsize)


# donated zeros_like: XLA writes zeros INTO the retired buffer — the
# checkout path's allocation-free rezero (one tiny specialization per
# (shape, dtype), reused for the life of the process)
_rezero = jax.jit(jnp.zeros_like, donate_argnums=0)


def zeros(shape, dtype):
    """A zeroed device array of ``shape``/``dtype`` — recycled from the
    pool when a retired buffer of the exact (shape, dtype) is held
    (checkout hit), freshly allocated otherwise (miss).  Checkout is
    always safe: pooled buffers are exclusively owned by the pool."""
    shape = tuple(int(s) for s in shape)
    dt = jnp.dtype(dtype)
    if not _enabled:
        return jnp.zeros(shape, dt)
    key = (shape, str(dt))
    buf = None
    with _lock:
        lst = _free.get(key)
        while lst:
            cand = lst.pop()
            if not lst:
                _free.pop(key, None)
            _stats["bytes_held"] -= _arr_bytes(cand)
            if not cand.is_deleted():
                buf = cand
                break
        _bump("hits" if buf is not None else "misses")
        # refresh the gauge on BOTH outcomes: a miss that skipped
        # deleted entries changed bytes_held too
        _held_gauge(_stats["bytes_held"])
    if buf is None:
        return jnp.zeros(shape, dt)
    try:
        return run_donated(_rezero, buf)
    except Exception:  # backend refused the donation: fall back fresh
        return jnp.zeros(shape, dt)


def run_donated(fn, *args, **kwargs):
    """Invoke a donating jitted callable with the donated-buffer trace
    warning silenced: a backend that declines the aliasing (CPU XLA
    often does for ``zeros_like``-style programs) still computes the
    same values — the warning is per-specialization noise, and this is
    the ONE place the suppression pattern lives."""
    import warnings

    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        return fn(*args, **kwargs)


def release(arr) -> bool:
    """Return a device buffer to the pool.  OWNERSHIP CONTRACT: the
    caller asserts no other live reference will ever read ``arr``
    again — the next checkout donates the buffer, which invalidates
    every stale reference (a later read raises, it never reads
    recycled data).  Returns True when the buffer was banked."""
    if not _enabled:
        return False
    if not isinstance(arr, jax.Array):
        return False
    try:
        if arr.is_deleted() or not arr.is_fully_addressable:
            return False
        if len(arr.devices()) != 1:
            return False  # sharded arrays are never pool candidates
    except Exception:
        return False
    nbytes = _arr_bytes(arr)
    with _lock:
        budget = _budget_bytes()
        if nbytes > budget:
            _bump("evictions")  # can never fit: drop the incoming buffer
            return False
        # over budget: evict the OLDEST held buffers (oldest free-list
        # keys first — dict insertion order approximates LRU by shape)
        # so a workload phase change reclaims dead shapes instead of
        # wedging the pool full of buffers nothing checks out anymore
        while _stats["bytes_held"] + nbytes > budget and _free:
            k0 = next(iter(_free))
            lst0 = _free[k0]
            old = lst0.pop(0)
            if not lst0:
                del _free[k0]
            _stats["bytes_held"] -= _arr_bytes(old)
            _bump("evictions")
        key = (tuple(int(s) for s in arr.shape), str(jnp.dtype(arr.dtype)))
        _free.setdefault(key, []).append(arr)
        _stats["bytes_held"] += nbytes
        _stats["high_water"] = max(_stats["high_water"],
                                   _stats["bytes_held"])
        _bump("returns")
        _held_gauge(_stats["bytes_held"])
    return True


def clear() -> None:
    """Drop every held buffer and mirror entry (tests / OOM pressure)."""
    global _mirror_bytes
    with _lock:
        _free.clear()
        _mirror.clear()
        _mirror_bytes = 0
        _stats["bytes_held"] = 0
        _held_gauge(0)


# ---------------------------------------------------------- index mirror

# content-keyed LRU of device uploads: (tag, shape, dtype, sha1(bytes))
# -> device array.  Ordered dict emulation via insertion + move.
from collections import OrderedDict as _OrderedDict  # noqa: E402

_mirror: "_OrderedDict[tuple, object]" = _OrderedDict()
_mirror_bytes = 0
_MIRROR_MAX_ENTRIES = 512
_MIRROR_MAX_BYTES = 128 * 1024 * 1024


def upload_index(tag: str, arr) -> object:
    """Device copy of a host index array, cached by CONTENT — the
    persistent device mirror of the engine's per-op ``jnp.asarray``
    staging (`acc_devmem` + `acc_ready` analog): a structure-stable
    chain uploads each gather/scatter index once, and every later
    iteration (even through fresh temporary matrices) hits the mirror.
    Staleness is impossible by construction (the key embeds the
    bytes); the LRU is bounded by entries AND bytes.  Cached arrays
    are shared and never donated."""
    arr = np.ascontiguousarray(arr)
    if not _enabled:
        record_h2d(arr.nbytes)
        return jnp.asarray(arr)
    key = (tag, arr.shape, str(arr.dtype),
           hashlib.sha1(arr.tobytes()).digest())
    global _mirror_bytes
    with _lock:
        hit = _mirror.get(key)
        if hit is not None and not hit.is_deleted():
            _mirror.move_to_end(key)
            return hit
    dev = jnp.asarray(arr)
    record_h2d(arr.nbytes)
    with _lock:
        if key not in _mirror:
            _mirror[key] = dev
            _mirror_bytes += _arr_bytes(dev)
            while _mirror and (len(_mirror) > _MIRROR_MAX_ENTRIES
                               or _mirror_bytes > _MIRROR_MAX_BYTES):
                _, old = _mirror.popitem(last=False)
                _mirror_bytes -= _arr_bytes(old)
    return dev


def alias_bins(m) -> tuple:
    """Zero-copy result snapshot of ``m``'s bins: ``([(shape, data,
    count)], total_device_bytes)``.  The snapshot ALIASES the live
    buffers — the caller must mark the matrix's bins shared
    (``m._bins_shared = True``) so no funnel ever donates them back to
    the pool, and must never bank the aliased buffers itself
    (exclusivity is unprovable; eviction just drops the references).
    Shared by the incremental-multiply result cache and the serve
    product cache."""
    bins = [(b.shape, b.data, b.count) for b in m.bins]
    return bins, sum(_arr_bytes(d) for _, d, _ in bins)


def adopt_aliased_bins(m, keys, bins_snapshot) -> None:
    """Install an `alias_bins` snapshot into ``m`` wholesale: the
    matrix adopts the ALIASED device buffers and its bins are marked
    shared so no later funnel can donate them while the snapshot's
    holder (the incremental result cache, the serve product cache)
    still references them.  The one adoption implementation both
    caches share."""
    from dbcsr_tpu.core.matrix import _Bin

    m.set_structure_from_device(
        np.ascontiguousarray(keys, np.int64).copy(),
        [_Bin(shape, data, count) for shape, data, count in bins_snapshot])
    m._bins_shared = True


# ----------------------------------------------------------- snapshots

class SnapshotError(RuntimeError):
    """Structured checkpoint/rollback contract violation (e.g. restoring
    a snapshot whose matrix was already retired to the pool)."""


class MatrixSnapshot:
    """A pooled, device-resident point-in-time checkpoint of one
    matrix: host index arrays plus fresh device copies of every bin
    buffer.  Built by `snapshot_matrix` / `chain.snapshot`, applied by
    `restore_matrix` / `chain.restore`.  The snapshot owns its copies
    exclusively (never aliased into the matrix), so it stays valid
    across any later mutation, donation, or failure of the source —
    and one snapshot can be restored more than once (each restore
    installs fresh copies)."""

    __slots__ = ("matrix", "keys", "row_ptr", "ent_bin", "ent_slot",
                 "bins", "valid", "chain_owner")

    def __init__(self, m, chain_owner: Optional["chain"] = None):
        import jax.numpy as _jnp

        self.matrix = m
        self.keys = m.keys.copy()
        self.row_ptr = m.row_ptr.copy()
        self.ent_bin = m.ent_bin.copy()
        self.ent_slot = m.ent_slot.copy()
        self.bins = [(b.shape, _jnp.array(b.data, copy=True), b.count)
                     for b in m.bins]
        self.valid = m.valid
        self.chain_owner = chain_owner

    def nbytes(self) -> int:
        return sum(_arr_bytes(d) for _, d, _ in self.bins)


def snapshot_matrix(m, chain_owner: Optional["chain"] = None
                    ) -> MatrixSnapshot:
    """Checkpoint ``m``'s structure and device data (see
    `MatrixSnapshot`)."""
    return MatrixSnapshot(m, chain_owner=chain_owner)


def restore_matrix(snap: MatrixSnapshot):
    """Roll ``snap.matrix`` back to the snapshotted state: structure
    fields replaced, bins rebuilt from FRESH copies of the snapshot's
    device data (the snapshot stays reusable).  The replaced bin
    buffers are donated back to the pool only when the matrix owns
    them exclusively — `copy()`-shared bins are NEVER restored via
    donation (the other side still reads them).  Returns the matrix."""
    from dbcsr_tpu.core.matrix import _Bin

    import jax.numpy as _jnp

    m = snap.matrix
    donatable = m._donatable  # decided on the PRE-restore aliasing
    old_data = [b.data for b in m.bins] if donatable else None
    m.keys = snap.keys.copy()
    m.row_ptr = snap.row_ptr.copy()
    m.ent_bin = snap.ent_bin.copy()
    m.ent_slot = snap.ent_slot.copy()
    m.bins = [_Bin(shape, _jnp.array(data, copy=True), count)
              for shape, data, count in snap.bins]
    m._shape_to_bin = {b.shape: i for i, b in enumerate(m.bins)}
    m._work.clear()
    m._work_batches.clear()
    m.invalidate_dense_cache()
    m._bins_shared = False  # restored bins are exclusively owned again
    # the epoch stays MONOTONE through a rollback and marks everything
    # dirty: a consumer that cached a result computed from the
    # now-discarded post-snapshot state must never see "unchanged" —
    # a rolled-back matrix is never served as current
    m._note_mutation(None)
    m.valid = snap.valid
    if old_data is not None:
        for d in old_data:
            release(d)
    return m


# -------------------------------------------------------------- chains

# per-THREAD chain stack: the obs server (and the roadmap's concurrent
# serving direction) run worker threads — a chain entered on one thread
# must never adopt (and later free) matrices another thread is building
_chain_tls = threading.local()


def _stack() -> list:
    st = getattr(_chain_tls, "stack", None)
    if st is None:
        st = _chain_tls.stack = []
    return st


def current_chain() -> Optional["chain"]:
    """The innermost chain active ON THIS THREAD, or None."""
    st = _stack()
    return st[-1] if st else None


class chain:
    """Scope of device-resident matrix state: matrices created inside
    the ``with`` block are ADOPTED (pool-owned) — their structure
    mutations donate replaced bin buffers back to the pool, and
    whatever is still adopted when the block exits is freed wholesale.

    * ``retire(m)`` — free an adopted intermediate NOW (its buffers
      feed the next iteration's checkouts);
    * ``detach(m)`` — let a result escape the scope: transferred to
      the enclosing chain when one is active, otherwise it keeps pool
      ownership but is never freed by this chain.

    The pattern (`models/purify.py` et al.)::

        with chain() as ch:
            cur = p0
            for _ in range(steps):
                new = step(cur)          # temporaries auto-adopted
                if cur is not p0:
                    ch.retire(cur)       # buffers -> pool
                cur = new
            ch.detach(cur)
        return cur
    """

    def __init__(self):
        self._adopted: dict = {}  # id(matrix) -> matrix
        # retirement is stamped ON the matrix object (_chain_retired),
        # never tracked as a raw id: a retired matrix's id is eligible
        # for CPython reuse the moment the last reference drops, and a
        # stale id in a set would make `restore` spuriously reject a
        # LEGITIMATE rollback of a later same-address matrix.  Every
        # restorable snapshot holds a strong reference to its matrix,
        # so the attribute is always authoritative.

    def __enter__(self) -> "chain":
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        try:
            _stack().remove(self)
        except ValueError:
            pass
        for m in list(self._adopted.values()):
            try:
                m.free()
            except Exception:
                pass  # a half-built matrix mid-fault: never mask the error
        self._adopted.clear()
        return False

    def adopt(self, m) -> object:
        """Mark ``m`` pool-owned and track it for end-of-chain free."""
        m._pool_owned = True
        self._adopted[id(m)] = m
        return m

    def retire(self, m) -> None:
        """Free an adopted matrix now, returning its bins to the pool.
        A no-op for matrices this chain does not own (a caller-provided
        input is never freed)."""
        tracked = self._adopted.pop(id(m), None)
        if tracked is not None:
            tracked._chain_retired = True
            tracked.free()

    def snapshot(self, m) -> MatrixSnapshot:
        """Pooled, device-resident checkpoint of ``m`` (any matrix —
        chain-owned or a caller input), restorable through
        `chain.restore`.  The rollback half of the chain-integrity
        contract: models checkpoint the accepted iterate before a step
        and roll back instead of iterating on a corrupted one
        (docs/resilience.md § Chain checkpoint/rollback)."""
        return snapshot_matrix(m, chain_owner=self)

    def restore(self, snap: MatrixSnapshot):
        """Roll the snapshotted matrix back to its checkpoint.

        Structured errors instead of silent corruption: restoring a
        matrix that was `retire`d after the snapshot raises
        `SnapshotError` (its buffers are pool property now).  Ownership
        is NEVER changed by a restore — a matrix adopted by an outer
        chain stays the outer chain's to free, whichever (nested) chain
        performs the restore; `copy()`-shared bins are never donated by
        the restore (see `restore_matrix`)."""
        if getattr(snap.matrix, "_chain_retired", False):
            raise SnapshotError(
                f"cannot restore {snap.matrix.name!r}: the matrix was "
                f"retired after the snapshot (its buffers belong to "
                f"the pool; take the snapshot before retiring, or "
                f"defer the retire until the iterate is validated)")
        return restore_matrix(snap)

    def scope(self):
        """Context manager for one split/iteration of a loop running
        inside this chain: matrices ADOPTED while the scope is open
        (engine temporaries — desymmetrized operands, transposes,
        remapped tensors) are retired at its exit, feeding the next
        split's checkouts, unless they were already retired or
        detached.  Matrices created before the scope (the caller's
        operands and C) are untouched — the ownership check in
        `retire` makes over-retiring impossible."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            before = set(self._adopted)
            try:
                yield self
            finally:
                for key in [k for k in self._adopted if k not in before]:
                    m = self._adopted.pop(key, None)
                    if m is not None:
                        m._chain_retired = True
                        try:
                            m.free()
                        except Exception:
                            pass  # a half-built temporary mid-fault
        return _scope()

    def detach(self, m) -> object:
        """Release ``m`` from this chain's end-of-scope free.  With an
        enclosing chain active the matrix transfers to it (nested
        step/iteration scopes); otherwise it escapes with pool
        ownership intact (still donates on later mutations, never
        auto-freed)."""
        if self._adopted.pop(id(m), None) is None:
            # never ours (e.g. the caller's input threaded straight
            # through a zero-iteration loop): detach must not grant
            # ownership — an enclosing chain would otherwise FREE the
            # caller's matrix at its exit
            return m
        # the enclosing chain is the one UNDER self on the stack
        # (detach runs inside the with block, so self is the top)
        parent = None
        st = _stack()
        if self in st:
            i = st.index(self)
            parent = st[i - 1] if i > 0 else None
        elif st:
            parent = st[-1]
        if parent is not None:
            parent.adopt(m)
        return m
