"""Content digests and identity keys — THE value-keying convention.

Three mechanisms grew up independently keying caches by "the same
values": the block-norms memo (bin data-array identities), the
filtered-product candidate-list sha1 (`mm.multiply`), and the serve
coalescer's pattern-fingerprint tuples.  This module single-sources
the convention so every value-level cache — the plan cache's filtered
leg, the delta-aware incremental multiply, and the serve-layer
content-addressed product cache — keys the same way:

* **Identity keys** (`buffers_key`): jax device arrays are immutable,
  so ``id(data)`` identifies CONTENT as long as the array is held
  alive (the holder pins it, so ids cannot recycle).  The cheap
  convention for caches that live next to the arrays they key.
* **Content digests** (`digest` / `host_digest` / `index_digest`):
  sha1 over the raw bytes (+ shape/dtype where aliasing matters) for
  keys that must survive across objects and processes — candidate
  lists, pattern fingerprints, value-addressed product keys.
* **Value digests of matrices** (`bin_value_digest` /
  `matrix_value_digest`): the per-shape-bin content hash of the LIVE
  rows (bucket padding excluded — two value-identical matrices may
  sit in different bucket capacities), memoized twice over: per
  buffer by identity (immutability) and per matrix by its mutation
  epoch (`BlockSparseMatrix.mutation_epoch`), so an unchanged matrix
  re-digests in O(1) however often it is submitted.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Tuple

import numpy as np


def digest(*chunks: bytes) -> bytes:
    """sha1 over the concatenated byte chunks (the one hash function
    every value key in the tree uses)."""
    h = hashlib.sha1()
    for c in chunks:
        h.update(c)
    return h.digest()


def host_digest(arr) -> bytes:
    """Content digest of one host array, shape/dtype-qualified (two
    arrays with identical bytes but different shape or dtype must not
    collide — a (2,3) and a (3,2) int64 view share bytes)."""
    arr = np.ascontiguousarray(arr)
    return digest(
        str(arr.dtype).encode(),
        np.asarray(arr.shape, np.int64).tobytes(),
        arr.tobytes(),
    )


def index_digest(*arrays) -> bytes:
    """Digest of a fixed-arity tuple of host index arrays (candidate
    lists, key vectors).  Shape-unqualified on purpose: the caller's
    arity and ordering are part of the call-site contract, exactly the
    semantics of the historical filtered-product sha1."""
    h = hashlib.sha1()
    for a in arrays:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.digest()


def scalar_key(x):
    """Canonical scalar for cache keys: ``complex`` collapses python
    floats, numpy scalars, and 0-d arrays of the same value onto one
    key (the coalesce-key convention, now shared)."""
    return complex(x)


def buffers_key(arrays) -> Tuple[int, ...]:
    """Identity key of a sequence of immutable device buffers.
    OWNERSHIP CONTRACT: the cache storing this key must also hold the
    arrays (ids recycle the moment the last reference drops)."""
    return tuple(id(a) for a in arrays)


# -------------------------------------------------- device value digests

# id(buffer) -> (buffer, count, digest, nbytes): the buffer is held so
# the id stays pinned — which means the memo PINS device memory, so it
# is bounded by BYTES as well as entries (the `mempool.upload_index`
# mirror convention); eviction only costs a re-fetch + re-hash
_bin_memo: "OrderedDict[int, tuple]" = OrderedDict()
_bin_memo_bytes = 0
_BIN_MEMO_MAX = 256
_BIN_MEMO_MAX_BYTES = 128 * 1024 * 1024


def bin_value_digest(data, count: int) -> bytes:
    """Content digest of one shape bin's LIVE rows (``data[:count]``),
    memoized by buffer identity.  The D2H fetch on a miss is counted
    against the transfer totals like every other engine fetch."""
    from dbcsr_tpu.core import mempool

    global _bin_memo_bytes
    key = id(data)
    hit = _bin_memo.get(key)
    if hit is not None and hit[0] is data and hit[1] == count:
        _bin_memo.move_to_end(key)
        return hit[2]
    host = np.asarray(data[:count])
    mempool.record_d2h(host.nbytes)
    d = host_digest(host)
    nbytes = int(np.prod(data.shape)) * int(np.dtype(str(data.dtype)).itemsize)
    if hit is not None:
        _bin_memo_bytes -= hit[3]
    _bin_memo[key] = (data, count, d, nbytes)
    _bin_memo_bytes += nbytes
    while _bin_memo and (len(_bin_memo) > _BIN_MEMO_MAX
                         or _bin_memo_bytes > _BIN_MEMO_MAX_BYTES):
        if len(_bin_memo) == 1 and _bin_memo_bytes <= _BIN_MEMO_MAX_BYTES:
            break
        _, old = _bin_memo.popitem(last=False)
        _bin_memo_bytes -= old[3]
    return d


def matrix_value_digest(m) -> bytes:
    """Full value digest of a finalized matrix: structure (pattern
    fingerprint, which covers keys AND blocking) + dtype + per-bin
    content.  Memoized on the matrix by its mutation epoch: an
    unchanged matrix (same epoch) returns the cached digest without
    touching the device; any mutation funnel bumps the epoch and the
    next call re-digests (only the replaced buffers miss the per-bin
    memo) — the epoch machinery IS the invalidation path."""
    cached = getattr(m, "_value_digest_cache", None)
    if cached is not None and cached[0] == m.mutation_epoch:
        return cached[1]
    parts = [repr(m.pattern_fingerprint()).encode(),
             str(np.dtype(m.dtype)).encode()]
    for b in m.bins:
        parts.append(np.asarray(
            (b.shape[0], b.shape[1], b.count), np.int64).tobytes())
        if b.count:
            parts.append(bin_value_digest(b.data, b.count))
    d = digest(*parts)
    m._value_digest_cache = (m.mutation_epoch, d)
    return d


def clear() -> None:
    """Drop the per-buffer digest memo (tests / memory pressure)."""
    global _bin_memo_bytes
    _bin_memo.clear()
    _bin_memo_bytes = 0
