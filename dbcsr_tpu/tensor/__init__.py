"""Tensor layer: n-rank block-sparse tensor contraction.

Re-design of `src/tensors`: a rank-2..4 block-sparse tensor is stored
as a block-sparse matrix through an nd->2d mapping (which tensor dims
become matrix rows vs cols, `dbcsr_tensor_types.F:119-136`);
`contract` aligns indices, remaps operands to compatible matrix
layouts, runs the TAS multiply, and maps back
(`dbcsr_tensor.F:418,1162-1183`).
"""

from dbcsr_tpu.tensor.types import (
    BlockSparseTensor,
    copy_matrix_to_tensor,
    copy_tensor_to_matrix,
    create_tensor,
    split_blocks,
)
from dbcsr_tpu.tensor.contract import (
    contract,
    contract_test,
    tensor_copy,
    remap,
    restrict_tensor,
)
from dbcsr_tpu.tensor.batched import (
    batched_contract_init,
    batched_contract_finalize,
    batched_contraction,
)
