"""Batched tensor contraction.

Ref `dbcsr_t_batched_contract_init/finalize` + the batched storage
machinery (`dbcsr_tensor.F:1964-2186`): a sequence of contractions into
the same C (typically chunked over an index range with the contract
``bounds`` arguments) runs with filtering deferred and split choices
reused, then one finalize applies the filter.  The reference also
re-optimizes the process grid between batches; on a single-controller
mesh that corresponds to re-choosing the TAS ``nsplit``, which the
state caches here.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

from dbcsr_tpu.ops.operations import filter_matrix
from dbcsr_tpu.tensor.types import BlockSparseTensor


def batched_contract_init(
    tensor_c: BlockSparseTensor, nsplit: Optional[int] = None
) -> None:
    """Enter batched mode on C (ref `dbcsr_t_batched_contract_init`)."""
    if getattr(tensor_c, "_batched_state", None) is not None:
        raise RuntimeError("tensor already in a batched contraction")
    from dbcsr_tpu.tas.batched import batched_mm_init

    tensor_c._batched_state = {"filter_eps": None}
    # the TAS-level state machine on C's matrix caches the split
    # decision across the whole batch (and is what tas_multiply reads)
    batched_mm_init(tensor_c.matrix, nsplit=nsplit)


def batched_contract_finalize(tensor_c: BlockSparseTensor) -> None:
    """Leave batched mode: apply the deferred filter once
    (ref `dbcsr_t_batched_contract_finalize`)."""
    state = getattr(tensor_c, "_batched_state", None)
    if state is None:
        raise RuntimeError("tensor not in a batched contraction")
    from dbcsr_tpu.tas.batched import batched_mm_finalize

    tensor_c._batched_state = None
    batched_mm_finalize(tensor_c.matrix)
    eps = state.get("filter_eps")
    if eps is not None:
        filter_matrix(tensor_c.matrix, eps)


@contextlib.contextmanager
def batched_contraction(
    tensor_c: BlockSparseTensor, nsplit: Optional[int] = None
) -> Iterator[BlockSparseTensor]:
    """Context-manager form: ``with batched_contraction(c): contract(...)``."""
    batched_contract_init(tensor_c, nsplit=nsplit)
    try:
        yield tensor_c
    finally:
        batched_contract_finalize(tensor_c)
