"""Tensor contraction.

Ref `dbcsr_t_contract` (`dbcsr_tensor.F:418`) and its expert path
(:540): align indices (:1162), remap operands to matrix-compatible
layouts (`reshape_mm_compatible`, :1183), run the TAS multiply, map the
result back.  `contract_a[i]` is contracted against `contract_b[i]`;
`notcontract_a` dims land in C at positions `map_1` (order-preserving),
`notcontract_b` at `map_2`.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dbcsr_tpu.core import mempool as _mempool
from dbcsr_tpu.core.timings import timed
from dbcsr_tpu.obs import events as _events
from dbcsr_tpu.obs import tracer as _trace
from dbcsr_tpu.ops.operations import scale
from dbcsr_tpu.tas.mm import tas_multiply
from dbcsr_tpu.tensor.types import BlockSparseTensor


@functools.partial(jax.jit, static_argnames=("src_shape", "comb", "dst_shape"))
def _remap_rows(bin_data, slots, *, src_shape, comb, dst_shape):
    """Gather + per-block nd transpose + reshape, all on device: the
    block-movement kernel of the reshape path (ref the buffered block
    alltoall in `dbcsr_tensor_reshape.F:288`; here the 'communication'
    is one fused device gather/permute)."""
    x = jnp.take(bin_data, slots, axis=0).reshape((slots.shape[0],) + src_shape)
    y = x.transpose((0,) + tuple(1 + i for i in comb))
    return y.reshape((slots.shape[0],) + dst_shape)


def _flat_multi(nd_idx: np.ndarray, dims: Sequence[int], nblks) -> np.ndarray:
    """Vectorized mixed-radix linearization (C-order over `dims`)."""
    f = np.zeros(len(nd_idx), np.int64)
    for d in dims:
        f = f * nblks[d] + nd_idx[:, d]
    return f


def remap(
    t: BlockSparseTensor,
    row_dims: Sequence[int],
    col_dims: Sequence[int],
    name: Optional[str] = None,
) -> BlockSparseTensor:
    """Same tensor, different nd->2d mapping (ref `dbcsr_t_remap`,
    `dbcsr_tensor.F:1604`).

    Fully device-side: blocks are grouped by nd shape, gathered,
    permuted and re-laid-out in one jitted op per shape group, then
    staged into the output matrix without any host round-trip of block
    data (the reference moves blocks with a buffered MPI alltoall,
    `dbcsr_tensor_reshape.F:67,288`; the single-controller analog is
    device gather/scatter)."""
    row_dims, col_dims = tuple(row_dims), tuple(col_dims)
    if (row_dims, col_dims) == (t.row_dims, t.col_dims):
        return t
    t.finalize()
    out = BlockSparseTensor(
        name or t.name, t.blk_sizes, row_dims, col_dims, t.dtype
    )
    mat = t.matrix
    n = mat.nblks
    if n == 0:
        return out.finalize()
    nd_idx = t.entry_multi_coords()
    nblks = t.nblks_per_dim
    shp = np.empty((n, t.ndim), np.int64)
    for d in range(t.ndim):
        shp[:, d] = t.blk_sizes[d][nd_idx[:, d]]
    _, ginv = np.unique(shp, axis=0, return_inverse=True)
    old_perm = t.row_dims + t.col_dims
    new_perm = row_dims + col_dims
    comb = tuple(old_perm.index(d) for d in new_perm)
    new_rows = _flat_multi(nd_idx, row_dims, nblks)
    new_cols = _flat_multi(nd_idx, col_dims, nblks)
    for g in range(ginv.max() + 1):
        sel = np.nonzero(ginv == g)[0]
        s = shp[sel[0]]
        # one nd shape + one mapping -> one matrix shape -> one source bin
        bid = mat.ent_bin[sel[0]]
        src_shape = tuple(int(s[d]) for d in old_perm)
        dst_shape = (
            int(np.prod([s[d] for d in row_dims], dtype=np.int64)),
            int(np.prod([s[d] for d in col_dims], dtype=np.int64)),
        )
        dev = _remap_rows(
            mat.bins[bid].data, jnp.asarray(mat.ent_slot[sel]),
            src_shape=src_shape, comb=comb, dst_shape=dst_shape,
        )
        out.matrix.stage_device_blocks(new_rows[sel], new_cols[sel], dev)
    return out.finalize()


def tensor_copy(
    dest: BlockSparseTensor, src: BlockSparseTensor, summation: bool = False
) -> BlockSparseTensor:
    """Copy blocks between same-shape tensors in any mappings
    (ref `dbcsr_t_copy` -> `dbcsr_t_reshape`, `dbcsr_tensor_reshape.F:67`).

    Device-side: src is remapped into dest's mapping (one fused
    gather/permute per shape group), then its bins are staged into
    dest's matrix and merged by the batched finalize — no host
    round-trip of block data."""
    if dest.nblks_per_dim != src.nblks_per_dim:
        raise ValueError("tensor shapes differ")
    for d in range(src.ndim):
        # per-dim block sizes must match, not just counts: different
        # blockings can flatten to identical matrix block shapes and
        # would otherwise copy with silently reinterpreted data
        if not np.array_equal(dest.blk_sizes[d], src.blk_sizes[d]):
            raise ValueError(f"tensor dim {d} blockings differ")
    src2 = remap(src, dest.row_dims, dest.col_dims)
    src2.finalize()
    mat = src2.matrix
    nbc = mat.nblkcols
    for b_id, b in enumerate(mat.bins):
        if b.count == 0:
            continue
        sel = np.nonzero(mat.ent_bin == b_id)[0]
        keys_by_slot = np.empty(b.count, np.int64)
        keys_by_slot[mat.ent_slot[sel]] = mat.keys[sel]
        dest.matrix.stage_device_blocks(
            keys_by_slot // nbc, keys_by_slot % nbc,
            b.data[: b.count], summation=summation,
        )
    return dest.finalize()


def restrict_tensor(
    t: BlockSparseTensor,
    dim_bounds,
    name: Optional[str] = None,
) -> BlockSparseTensor:
    """Restrict to blocks whose multi-index lies within ``dim_bounds``
    — a {dim: (lo, hi)} map of inclusive block-index ranges (the
    restriction step behind the reference's contract ``bounds_1/2/3``
    arguments, `dbcsr_tensor.F:470-490`).

    When no restriction applies (and no ``name`` is requested), the
    input tensor itself is returned — callers must not mutate the
    result in place.  With a ``name`` or an effective restriction, a
    fresh copy is returned."""
    from dbcsr_tpu.ops.operations import compress, copy as matrix_copy

    dim_bounds = {d: b for d, b in (dim_bounds or {}).items() if b is not None}
    mask = None
    if dim_bounds:
        nd_idx = t.entry_multi_coords()
        mask = np.ones(len(nd_idx), bool)
        for d, (lo, hi) in dim_bounds.items():
            mask &= (nd_idx[:, d] >= lo) & (nd_idx[:, d] <= hi)
        if mask.all():
            mask = None
    if mask is None:
        if name is None:
            # no restriction: share the tensor (downstream remap /
            # multiply do not mutate their inputs, so the O(nnz) copy
            # is pure overhead on every bound-less contract)
            return t
        out = BlockSparseTensor(name, t.blk_sizes, t.row_dims, t.col_dims, t.dtype)
        out.matrix = matrix_copy(t.matrix, name=name)
        return out
    out = BlockSparseTensor(
        name or t.name, t.blk_sizes, t.row_dims, t.col_dims, t.dtype
    )
    out.matrix = compress(matrix_copy(t.matrix, name=out.name), mask)
    return out


def contract(
    alpha,
    tensor_a: BlockSparseTensor,
    tensor_b: BlockSparseTensor,
    beta,
    tensor_c: BlockSparseTensor,
    contract_a: Sequence[int],
    notcontract_a: Sequence[int],
    contract_b: Sequence[int],
    notcontract_b: Sequence[int],
    map_1: Optional[Sequence[int]] = None,
    map_2: Optional[Sequence[int]] = None,
    filter_eps: Optional[float] = None,
    nsplit: Optional[int] = None,
    bounds_1=None,
    bounds_2=None,
    bounds_3=None,
    mesh=None,
) -> int:
    """C[map_1, map_2] = alpha * sum over contracted dims of A*B + beta*C.

    Returns flops.  (ref `dbcsr_t_contract`, `dbcsr_tensor.F:418`)

    ``bounds_1[i]`` optionally restricts contracted dim pair
    (contract_a[i], contract_b[i]) to an inclusive block-index range;
    ``bounds_2[i]`` restricts notcontract_a[i], ``bounds_3[i]``
    notcontract_b[i] (ref bounds args, `dbcsr_tensor.F:470-490`; the
    batched-contraction driver chunks index space with these).
    """
    ca, nca = tuple(contract_a), tuple(notcontract_a)
    cb, ncb = tuple(contract_b), tuple(notcontract_b)
    if map_1 is None:
        map_1 = tuple(range(len(nca)))
    if map_2 is None:
        map_2 = tuple(range(len(nca), len(nca) + len(ncb)))
    map_1, map_2 = tuple(map_1), tuple(map_2)

    if sorted(ca + nca) != list(range(tensor_a.ndim)):
        raise ValueError("contract_a + notcontract_a must partition A dims")
    if sorted(cb + ncb) != list(range(tensor_b.ndim)):
        raise ValueError("contract_b + notcontract_b must partition B dims")
    if len(ca) != len(cb):
        raise ValueError("contracted dim counts differ")
    for da, db in zip(ca, cb):
        if not np.array_equal(tensor_a.blk_sizes[da], tensor_b.blk_sizes[db]):
            raise ValueError(f"contracted dim blockings differ: A{da} vs B{db}")
    if sorted(map_1 + map_2) != list(range(tensor_c.ndim)):
        raise ValueError("map_1 + map_2 must partition C dims")
    for da, dc in zip(nca, map_1):
        if not np.array_equal(tensor_a.blk_sizes[da], tensor_c.blk_sizes[dc]):
            raise ValueError(f"A dim {da} blocking != C dim {dc}")
    for db, dc in zip(ncb, map_2):
        if not np.array_equal(tensor_b.blk_sizes[db], tensor_c.blk_sizes[dc]):
            raise ValueError(f"B dim {db} blocking != C dim {dc}")

    def _bounds_map(dims, bounds):
        if bounds is None:
            return {}
        bounds = list(bounds)
        if len(bounds) != len(dims):
            raise ValueError("bounds length must match the dim-section length")
        return {d: b for d, b in zip(dims, bounds) if b is not None}

    a_bounds = {**_bounds_map(ca, bounds_1), **_bounds_map(nca, bounds_2)}
    b_bounds = {**_bounds_map(cb, bounds_1), **_bounds_map(ncb, bounds_3)}

    # batched-contraction state on C defers filtering to the finalize;
    # the split decision is cached by the TAS batched-MM state that
    # batched_contract_init installed on C's matrix
    # (ref dbcsr_t_batched_contract_init/finalize, dbcsr_tensor.F:1964-2186)
    batch = getattr(tensor_c, "_batched_state", None)
    if batch is not None:
        if filter_eps is not None:
            batch["filter_eps"] = filter_eps
        filter_eps = None

    # the contraction is a first-class product on the ops plane: one
    # correlation scope (flight record + product_id on the bus) wraps
    # the reshape -> multiply -> map pipeline, exactly like mesh/TAS
    # multiplies — every inner multiply/breaker/fault event nests under
    # its own product id while this scope is what doctor/bus queries
    # see for the contraction itself
    with timed("tensor_contract"), _events.product_scope(
            "tensor_contract", tensor_c.name,
            a=tensor_a.name, b=tensor_b.name,
            ndim_a=tensor_a.ndim, ndim_b=tensor_b.ndim):
        _trace.annotate(
            a=tensor_a.name, b=tensor_b.name, c=tensor_c.name,
            contract_a=list(ca), contract_b=list(cb),
            ndim_a=tensor_a.ndim, ndim_b=tensor_b.ndim,
            bounded=bool(a_bounds or b_bounds),
        )
        # device-resident contraction intermediates (core.mempool): the
        # restriction copies, the remapped operand layouts and the
        # result-layout shell are all chain-owned — retired the moment
        # they are dead, so an iterative contraction loop recycles
        # their device buffers instead of re-allocating (and, with the
        # index mirrors, stops re-staging index arrays) every call.
        # The caller's tensors were created OUTSIDE this chain and are
        # never adopted or freed by it.
        with _mempool.chain() as ch:
            restricted_a = restrict_tensor(tensor_a, a_bounds)
            restricted_b = restrict_tensor(tensor_b, b_bounds)
            # remap operands into matrix-compatible layouts (ref :1183)
            a2 = remap(restricted_a, nca, ca, name=tensor_a.name + "_mm")
            b2 = remap(restricted_b, cb, ncb, name=tensor_b.name + "_mm")
            # restrict/remap may have passed an operand through
            # unchanged; if the caller aliased C to an operand,
            # multiply would then read A/B while overwriting them —
            # copy to break the alias
            from dbcsr_tpu.ops.operations import copy as matrix_copy

            if a2.matrix is tensor_c.matrix:
                a2.matrix = matrix_copy(a2.matrix, name=a2.name)
            if b2.matrix is tensor_c.matrix:
                b2.matrix = matrix_copy(b2.matrix, name=b2.name)
            c_layout = (map_1, map_2)
            if (tensor_c.row_dims, tensor_c.col_dims) == c_layout:
                flops = tas_multiply(
                    "N", "N", alpha, a2.matrix, b2.matrix, beta,
                    tensor_c.matrix,
                    filter_eps=filter_eps, nsplit=nsplit, mesh=mesh,
                )
                return flops
            tmp = BlockSparseTensor(
                tensor_c.name + "_mm", tensor_c.blk_sizes, map_1, map_2,
                tensor_c.dtype
            )
            tmp.finalize()
            flops = tas_multiply(
                "N", "N", alpha, a2.matrix, b2.matrix, 0.0, tmp.matrix,
                filter_eps=filter_eps, nsplit=nsplit, mesh=mesh,
            )
            # the remapped operands are dead once the multiply returned:
            # retire them now so the result-map staging below checks
            # its buffers out of the pool they just fed
            ch.retire(a2.matrix)
            ch.retire(b2.matrix)
            if beta != 1.0:
                scale(tensor_c.matrix, beta)
            tensor_copy(tensor_c, tmp, summation=True)
            return flops


def contract_test(
    alpha,
    tensor_a: BlockSparseTensor,
    tensor_b: BlockSparseTensor,
    beta,
    tensor_c: BlockSparseTensor,
    contract_a: Sequence[int],
    notcontract_a: Sequence[int],
    contract_b: Sequence[int],
    notcontract_b: Sequence[int],
    map_1: Optional[Sequence[int]] = None,
    map_2: Optional[Sequence[int]] = None,
    eps: Optional[float] = None,
    io=print,
    **contract_kwargs,
) -> bool:
    """Run the contraction AND verify it against a dense einsum oracle
    (ref `dbcsr_t_contract_test`, `dbcsr_tensor_api.F:55`): returns
    True when the result matches within ``eps`` (dtype-scaled default),
    False otherwise, reporting the error through ``io``.  ``tensor_c``
    is updated with the contraction result either way."""
    ca, nca = tuple(contract_a), tuple(notcontract_a)
    cb, ncb = tuple(contract_b), tuple(notcontract_b)
    if map_1 is None:
        map_1 = tuple(range(len(nca)))
    if map_2 is None:
        map_2 = tuple(range(len(nca), len(nca) + len(ncb)))
    if contract_kwargs.get("filter_eps") is not None:
        raise ValueError(
            "contract_test's dense oracle cannot model filter_eps; "
            "call contract() directly for filtered contractions"
        )
    dense_a = tensor_a.to_dense().copy()
    dense_b = tensor_b.to_dense().copy()
    dense_c0 = tensor_c.to_dense()

    # bounds semantics (same as contract): operands are zeroed outside
    # the block-index windows, so the oracle masks its dense inputs
    def _mask(dense, tensor, dim, lo_hi):
        off = np.concatenate([[0], np.cumsum(tensor.blk_sizes[dim])])
        lo, hi = lo_hi
        sl = [slice(None)] * dense.ndim
        sl[dim] = slice(0, int(off[lo]))
        dense[tuple(sl)] = 0
        sl[dim] = slice(int(off[hi + 1]), None)
        dense[tuple(sl)] = 0

    for i, b in enumerate(contract_kwargs.get("bounds_1") or []):
        if b is not None:
            _mask(dense_a, tensor_a, ca[i], b)
            _mask(dense_b, tensor_b, cb[i], b)
    for i, b in enumerate(contract_kwargs.get("bounds_2") or []):
        if b is not None:
            _mask(dense_a, tensor_a, nca[i], b)
    for i, b in enumerate(contract_kwargs.get("bounds_3") or []):
        if b is not None:
            _mask(dense_b, tensor_b, ncb[i], b)
    # einsum subscripts: one letter per A dim; contracted B dims share
    # A's letters, free B dims get fresh ones; C positions by map_1/2
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    sub_a = [next(letters) for _ in range(tensor_a.ndim)]
    sub_b = [None] * tensor_b.ndim
    for da, db in zip(ca, cb):
        sub_b[db] = sub_a[da]
    for db in ncb:
        sub_b[db] = next(letters)
    sub_c = [None] * tensor_c.ndim
    for da, dc in zip(nca, map_1):
        sub_c[dc] = sub_a[da]
    for db, dc in zip(ncb, map_2):
        sub_c[dc] = sub_b[db]
    spec = f"{''.join(sub_a)},{''.join(sub_b)}->{''.join(sub_c)}"
    want = alpha * np.einsum(spec, dense_a, dense_b) + beta * dense_c0

    contract(alpha, tensor_a, tensor_b, beta, tensor_c,
             ca, nca, cb, ncb, map_1=map_1, map_2=map_2, **contract_kwargs)
    got = tensor_c.to_dense()
    if eps is None:
        resolution = np.finfo(np.zeros(1, tensor_c.dtype).real.dtype).resolution
        k_extent = int(np.prod(
            [int(tensor_a.blk_sizes[d].sum()) for d in ca], dtype=np.int64
        ))
        eps = 100.0 * np.sqrt(max(k_extent, 1)) * resolution
    scale_ref = max(float(np.abs(want).max()), 1.0)
    err = float(np.abs(got - want).max()) / scale_ref
    ok = bool(np.isfinite(err) and err <= eps)
    io(f" contract_test {spec}: max rel err {err:.3e} "
       f"{'<=' if ok else '>'} eps {eps:.1e} -> {'OK' if ok else 'FAILED'}")
    return ok
