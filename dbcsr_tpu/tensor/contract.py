"""Tensor contraction.

Ref `dbcsr_t_contract` (`dbcsr_tensor.F:418`) and its expert path
(:540): align indices (:1162), remap operands to matrix-compatible
layouts (`reshape_mm_compatible`, :1183), run the TAS multiply, map the
result back.  `contract_a[i]` is contracted against `contract_b[i]`;
`notcontract_a` dims land in C at positions `map_1` (order-preserving),
`notcontract_b` at `map_2`.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from dbcsr_tpu.core.timings import timed
from dbcsr_tpu.ops.operations import scale
from dbcsr_tpu.tas.mm import tas_multiply
from dbcsr_tpu.tensor.types import BlockSparseTensor


def remap(
    t: BlockSparseTensor,
    row_dims: Sequence[int],
    col_dims: Sequence[int],
    name: Optional[str] = None,
) -> BlockSparseTensor:
    """Same tensor, different nd->2d mapping (ref `dbcsr_t_remap`,
    `dbcsr_tensor.F:1604`)."""
    row_dims, col_dims = tuple(row_dims), tuple(col_dims)
    if (row_dims, col_dims) == (t.row_dims, t.col_dims):
        return t
    out = BlockSparseTensor(
        name or t.name, t.blk_sizes, row_dims, col_dims, t.dtype
    )
    for idx, blk in t.iterate_blocks():
        out.put_block(idx, blk)
    return out.finalize()


def tensor_copy(
    dest: BlockSparseTensor, src: BlockSparseTensor, summation: bool = False
) -> BlockSparseTensor:
    """Copy blocks between same-shape tensors in any mappings
    (ref `dbcsr_t_copy` -> `dbcsr_t_reshape`, `dbcsr_tensor_reshape.F:67`)."""
    if dest.nblks_per_dim != src.nblks_per_dim:
        raise ValueError("tensor shapes differ")
    for idx, blk in src.iterate_blocks():
        dest.put_block(idx, blk, summation=summation)
    return dest.finalize()


def contract(
    alpha,
    tensor_a: BlockSparseTensor,
    tensor_b: BlockSparseTensor,
    beta,
    tensor_c: BlockSparseTensor,
    contract_a: Sequence[int],
    notcontract_a: Sequence[int],
    contract_b: Sequence[int],
    notcontract_b: Sequence[int],
    map_1: Optional[Sequence[int]] = None,
    map_2: Optional[Sequence[int]] = None,
    filter_eps: Optional[float] = None,
    nsplit: Optional[int] = None,
) -> int:
    """C[map_1, map_2] = alpha * sum over contracted dims of A*B + beta*C.

    Returns flops.  (ref `dbcsr_t_contract`, `dbcsr_tensor.F:418`)
    """
    ca, nca = tuple(contract_a), tuple(notcontract_a)
    cb, ncb = tuple(contract_b), tuple(notcontract_b)
    if map_1 is None:
        map_1 = tuple(range(len(nca)))
    if map_2 is None:
        map_2 = tuple(range(len(nca), len(nca) + len(ncb)))
    map_1, map_2 = tuple(map_1), tuple(map_2)

    if sorted(ca + nca) != list(range(tensor_a.ndim)):
        raise ValueError("contract_a + notcontract_a must partition A dims")
    if sorted(cb + ncb) != list(range(tensor_b.ndim)):
        raise ValueError("contract_b + notcontract_b must partition B dims")
    if len(ca) != len(cb):
        raise ValueError("contracted dim counts differ")
    for da, db in zip(ca, cb):
        if not np.array_equal(tensor_a.blk_sizes[da], tensor_b.blk_sizes[db]):
            raise ValueError(f"contracted dim blockings differ: A{da} vs B{db}")
    if sorted(map_1 + map_2) != list(range(tensor_c.ndim)):
        raise ValueError("map_1 + map_2 must partition C dims")
    for da, dc in zip(nca, map_1):
        if not np.array_equal(tensor_a.blk_sizes[da], tensor_c.blk_sizes[dc]):
            raise ValueError(f"A dim {da} blocking != C dim {dc}")
    for db, dc in zip(ncb, map_2):
        if not np.array_equal(tensor_b.blk_sizes[db], tensor_c.blk_sizes[dc]):
            raise ValueError(f"B dim {db} blocking != C dim {dc}")

    with timed("tensor_contract"):
        # remap operands into matrix-compatible layouts (ref :1183)
        a2 = remap(tensor_a, nca, ca, name=tensor_a.name + "_mm")
        b2 = remap(tensor_b, cb, ncb, name=tensor_b.name + "_mm")
        c_layout = (map_1, map_2)
        if (tensor_c.row_dims, tensor_c.col_dims) == c_layout:
            flops = tas_multiply(
                "N", "N", alpha, a2.matrix, b2.matrix, beta, tensor_c.matrix,
                filter_eps=filter_eps, nsplit=nsplit,
            )
            return flops
        tmp = BlockSparseTensor(
            tensor_c.name + "_mm", tensor_c.blk_sizes, map_1, map_2, tensor_c.dtype
        )
        tmp.finalize()
        flops = tas_multiply(
            "N", "N", alpha, a2.matrix, b2.matrix, 0.0, tmp.matrix,
            filter_eps=filter_eps, nsplit=nsplit,
        )
        if beta != 1.0:
            scale(tensor_c.matrix, beta)
        tensor_copy(tensor_c, tmp, summation=True)
        return flops
