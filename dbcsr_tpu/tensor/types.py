"""Block-sparse tensor type and the nd->2d mapping.

Ref `dbcsr_tensor_types.F:119-136` (`nd_to_2d_mapping`): tensor dims
are partitioned into (row_dims, col_dims); the tensor is stored as a
block-sparse matrix whose block rows enumerate the mixed-radix product
of the row dims' blocks (C-order) and likewise for columns.  A tensor
block of shape (s_0,...,s_{d-1}) is stored as the matrix block
transpose(row_dims + col_dims).reshape(prod_rows, prod_cols).
"""

from __future__ import annotations

import itertools
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from dbcsr_tpu.core.matrix import BlockSparseMatrix


def _mixed_radix_sizes(blk_sizes: List[np.ndarray], dims: Sequence[int]) -> np.ndarray:
    """Matrix block sizes for the product of `dims` (C-order)."""
    if not dims:
        return np.asarray([1], np.int32)
    out = np.asarray([1], np.int64)
    for d in dims:
        out = np.multiply.outer(out, blk_sizes[d].astype(np.int64)).reshape(-1)
    return out.astype(np.int32)


class BlockSparseTensor:
    """A rank-d block-sparse tensor stored as a matrix."""

    def __init__(
        self,
        name: str,
        blk_sizes: List[np.ndarray],
        row_dims: Sequence[int],
        col_dims: Sequence[int],
        dtype=np.float64,
    ):
        self.name = name
        self.blk_sizes = [np.ascontiguousarray(s, np.int32) for s in blk_sizes]
        self.ndim = len(self.blk_sizes)
        self.row_dims = tuple(row_dims)
        self.col_dims = tuple(col_dims)
        if sorted(self.row_dims + self.col_dims) != list(range(self.ndim)):
            raise ValueError("row_dims + col_dims must partition the tensor dims")
        self.dtype = dtype
        self.matrix = BlockSparseMatrix(
            name,
            _mixed_radix_sizes(self.blk_sizes, self.row_dims),
            _mixed_radix_sizes(self.blk_sizes, self.col_dims),
            dtype,
        )

    # ------------------------------------------------------------- indexing
    @property
    def nblks_per_dim(self) -> Tuple[int, ...]:
        return tuple(len(s) for s in self.blk_sizes)

    def _flat(self, idx: Sequence[int], dims: Sequence[int]) -> int:
        f = 0
        for d in dims:
            f = f * len(self.blk_sizes[d]) + idx[d]
        return f

    def _unflat(self, flat: int, dims: Sequence[int]) -> List[int]:
        out = []
        for d in reversed(dims):
            out.append(flat % len(self.blk_sizes[d]))
            flat //= len(self.blk_sizes[d])
        return list(reversed(out))

    def block_coords(self, row: int, col: int) -> Tuple[int, ...]:
        """Matrix (row, col) -> tensor block multi-index."""
        idx = [0] * self.ndim
        for d, v in zip(self.row_dims, self._unflat(row, self.row_dims)):
            idx[d] = v
        for d, v in zip(self.col_dims, self._unflat(col, self.col_dims)):
            idx[d] = v
        return tuple(idx)

    def block_shape(self, idx: Sequence[int]) -> Tuple[int, ...]:
        return tuple(int(self.blk_sizes[d][idx[d]]) for d in range(self.ndim))

    # --------------------------------------------------------------- blocks
    def put_block(self, idx: Sequence[int], block, summation: bool = False) -> None:
        """Stage a rank-d block (ref `dbcsr_t_put_block`)."""
        block = np.asarray(block)
        if block.shape != self.block_shape(idx):
            raise ValueError(
                f"block {tuple(idx)} has shape {block.shape}, "
                f"expected {self.block_shape(idx)}"
            )
        perm = self.row_dims + self.col_dims
        mat = block.transpose(perm).reshape(
            int(np.prod([block.shape[d] for d in self.row_dims], dtype=np.int64)),
            int(np.prod([block.shape[d] for d in self.col_dims], dtype=np.int64)),
        )
        self.matrix.put_block(
            self._flat(idx, self.row_dims), self._flat(idx, self.col_dims), mat,
            summation=summation,
        )

    def get_block(self, idx: Sequence[int]):
        """Fetch a rank-d block or None (ref `dbcsr_t_get_block`)."""
        mat = self.matrix.get_block(
            self._flat(idx, self.row_dims), self._flat(idx, self.col_dims)
        )
        if mat is None:
            return None
        shape = self.block_shape(idx)
        perm = self.row_dims + self.col_dims
        inv = np.argsort(perm)
        return mat.reshape(tuple(shape[d] for d in perm)).transpose(inv)

    def finalize(self) -> "BlockSparseTensor":
        self.matrix.finalize()
        return self

    def iterate_blocks(self) -> Iterator[Tuple[Tuple[int, ...], np.ndarray]]:
        """Yield (multi-index, rank-d block) (ref `dbcsr_t_iterator`)."""
        perm = self.row_dims + self.col_dims
        inv = np.argsort(perm)
        for r, c, mat in self.matrix.iterate_blocks():
            idx = self.block_coords(r, c)
            shape = self.block_shape(idx)
            yield idx, mat.reshape(tuple(shape[d] for d in perm)).transpose(inv)

    @property
    def nblks(self) -> int:
        return self.matrix.nblks

    def to_dense(self) -> np.ndarray:
        """Densify (test oracle; ref tensor unittest pattern)."""
        full = tuple(int(s.sum()) for s in self.blk_sizes)
        out = np.zeros(full, dtype=np.dtype(self.dtype))
        offs = [np.concatenate([[0], np.cumsum(s)]) for s in self.blk_sizes]
        for idx, blk in self.iterate_blocks():
            sl = tuple(
                slice(offs[d][idx[d]], offs[d][idx[d]] + blk.shape[d])
                for d in range(self.ndim)
            )
            out[sl] = blk
        return out

    def block_indices(self) -> List[Tuple[int, ...]]:
        rows, cols = self.matrix.entry_coords()
        return [self.block_coords(int(r), int(c)) for r, c in zip(rows, cols)]

    def entry_multi_coords(self) -> np.ndarray:
        """(nblks, ndim) int64 array of tensor block multi-indices, in
        matrix key order (vectorized `block_coords`)."""
        rows, cols = self.matrix.entry_coords()
        nd = np.empty((len(rows), self.ndim), np.int64)
        f = rows.copy()
        for d in reversed(self.row_dims):
            n = len(self.blk_sizes[d])
            nd[:, d] = f % n
            f //= n
        f = cols.copy()
        for d in reversed(self.col_dims):
            n = len(self.blk_sizes[d])
            nd[:, d] = f % n
            f //= n
        return nd

    def __repr__(self) -> str:
        return (
            f"BlockSparseTensor({self.name!r}, rank {self.ndim}, "
            f"nblks/dim {self.nblks_per_dim}, map {self.row_dims}|{self.col_dims})"
        )


def create_tensor(
    name: str,
    blk_sizes: List,
    row_dims: Optional[Sequence[int]] = None,
    col_dims: Optional[Sequence[int]] = None,
    dtype=np.float64,
) -> BlockSparseTensor:
    """Create a tensor (ref `dbcsr_t_create`).  Default mapping splits
    dims in half: first ceil(d/2) dims -> rows."""
    nd = len(blk_sizes)
    if row_dims is None and col_dims is None:
        half = (nd + 1) // 2
        row_dims, col_dims = tuple(range(half)), tuple(range(half, nd))
    elif row_dims is None:
        row_dims = tuple(d for d in range(nd) if d not in set(col_dims))
    elif col_dims is None:
        col_dims = tuple(d for d in range(nd) if d not in set(row_dims))
    return BlockSparseTensor(name, blk_sizes, row_dims, col_dims, dtype)
