"""Block-sparse tensor type and the nd->2d mapping.

Ref `dbcsr_tensor_types.F:119-136` (`nd_to_2d_mapping`): tensor dims
are partitioned into (row_dims, col_dims); the tensor is stored as a
block-sparse matrix whose block rows enumerate the mixed-radix product
of the row dims' blocks (C-order) and likewise for columns.  A tensor
block of shape (s_0,...,s_{d-1}) is stored as the matrix block
transpose(row_dims + col_dims).reshape(prod_rows, prod_cols).
"""

from __future__ import annotations

import itertools
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from dbcsr_tpu.core.matrix import BlockSparseMatrix


def _mixed_radix_sizes(blk_sizes: List[np.ndarray], dims: Sequence[int]) -> np.ndarray:
    """Matrix block sizes for the product of `dims` (C-order)."""
    if not dims:
        return np.asarray([1], np.int32)
    out = np.asarray([1], np.int64)
    for d in dims:
        out = np.multiply.outer(out, blk_sizes[d].astype(np.int64)).reshape(-1)
    return out.astype(np.int32)


class BlockSparseTensor:
    """A rank-d block-sparse tensor stored as a matrix."""

    def __init__(
        self,
        name: str,
        blk_sizes: List[np.ndarray],
        row_dims: Sequence[int],
        col_dims: Sequence[int],
        dtype=np.float64,
    ):
        self.name = name
        self.blk_sizes = [np.ascontiguousarray(s, np.int32) for s in blk_sizes]
        self.ndim = len(self.blk_sizes)
        self.row_dims = tuple(row_dims)
        self.col_dims = tuple(col_dims)
        if sorted(self.row_dims + self.col_dims) != list(range(self.ndim)):
            raise ValueError("row_dims + col_dims must partition the tensor dims")
        self.dtype = dtype
        self.matrix = BlockSparseMatrix(
            name,
            _mixed_radix_sizes(self.blk_sizes, self.row_dims),
            _mixed_radix_sizes(self.blk_sizes, self.col_dims),
            dtype,
        )

    # ------------------------------------------------------------- indexing
    @property
    def nblks_per_dim(self) -> Tuple[int, ...]:
        return tuple(len(s) for s in self.blk_sizes)

    def _flat(self, idx: Sequence[int], dims: Sequence[int]) -> int:
        f = 0
        for d in dims:
            f = f * len(self.blk_sizes[d]) + idx[d]
        return f

    def _unflat(self, flat: int, dims: Sequence[int]) -> List[int]:
        out = []
        for d in reversed(dims):
            out.append(flat % len(self.blk_sizes[d]))
            flat //= len(self.blk_sizes[d])
        return list(reversed(out))

    def block_coords(self, row: int, col: int) -> Tuple[int, ...]:
        """Matrix (row, col) -> tensor block multi-index."""
        idx = [0] * self.ndim
        for d, v in zip(self.row_dims, self._unflat(row, self.row_dims)):
            idx[d] = v
        for d, v in zip(self.col_dims, self._unflat(col, self.col_dims)):
            idx[d] = v
        return tuple(idx)

    def block_shape(self, idx: Sequence[int]) -> Tuple[int, ...]:
        return tuple(int(self.blk_sizes[d][idx[d]]) for d in range(self.ndim))

    # --------------------------------------------------------------- blocks
    def put_block(self, idx: Sequence[int], block, summation: bool = False) -> None:
        """Stage a rank-d block (ref `dbcsr_t_put_block`)."""
        block = np.asarray(block)
        if block.shape != self.block_shape(idx):
            raise ValueError(
                f"block {tuple(idx)} has shape {block.shape}, "
                f"expected {self.block_shape(idx)}"
            )
        perm = self.row_dims + self.col_dims
        mat = block.transpose(perm).reshape(
            int(np.prod([block.shape[d] for d in self.row_dims], dtype=np.int64)),
            int(np.prod([block.shape[d] for d in self.col_dims], dtype=np.int64)),
        )
        self.matrix.put_block(
            self._flat(idx, self.row_dims), self._flat(idx, self.col_dims), mat,
            summation=summation,
        )

    def get_block(self, idx: Sequence[int]):
        """Fetch a rank-d block or None (ref `dbcsr_t_get_block`)."""
        mat = self.matrix.get_block(
            self._flat(idx, self.row_dims), self._flat(idx, self.col_dims)
        )
        if mat is None:
            return None
        shape = self.block_shape(idx)
        perm = self.row_dims + self.col_dims
        inv = np.argsort(perm)
        return mat.reshape(tuple(shape[d] for d in perm)).transpose(inv)

    def finalize(self) -> "BlockSparseTensor":
        self.matrix.finalize()
        return self

    def iterate_blocks(self) -> Iterator[Tuple[Tuple[int, ...], np.ndarray]]:
        """Yield (multi-index, rank-d block) (ref `dbcsr_t_iterator`)."""
        perm = self.row_dims + self.col_dims
        inv = np.argsort(perm)
        for r, c, mat in self.matrix.iterate_blocks():
            idx = self.block_coords(r, c)
            shape = self.block_shape(idx)
            yield idx, mat.reshape(tuple(shape[d] for d in perm)).transpose(inv)

    @property
    def nblks(self) -> int:
        return self.matrix.nblks

    def to_dense(self) -> np.ndarray:
        """Densify (test oracle; ref tensor unittest pattern)."""
        full = tuple(int(s.sum()) for s in self.blk_sizes)
        out = np.zeros(full, dtype=np.dtype(self.dtype))
        offs = [np.concatenate([[0], np.cumsum(s)]) for s in self.blk_sizes]
        for idx, blk in self.iterate_blocks():
            sl = tuple(
                slice(offs[d][idx[d]], offs[d][idx[d]] + blk.shape[d])
                for d in range(self.ndim)
            )
            out[sl] = blk
        return out

    def block_indices(self) -> List[Tuple[int, ...]]:
        rows, cols = self.matrix.entry_coords()
        return [self.block_coords(int(r), int(c)) for r, c in zip(rows, cols)]

    def entry_multi_coords(self) -> np.ndarray:
        """(nblks, ndim) int64 array of tensor block multi-indices, in
        matrix key order (vectorized `block_coords`)."""
        rows, cols = self.matrix.entry_coords()
        nd = np.empty((len(rows), self.ndim), np.int64)
        f = rows.copy()
        for d in reversed(self.row_dims):
            n = len(self.blk_sizes[d])
            nd[:, d] = f % n
            f //= n
        f = cols.copy()
        for d in reversed(self.col_dims):
            n = len(self.blk_sizes[d])
            nd[:, d] = f % n
            f //= n
        return nd

    # ----------------------------------------------- api parity (dbcsr_t_*)
    def reserve_blocks(self, indices) -> "BlockSparseTensor":
        """Ensure the listed multi-index blocks exist, zero where absent
        (ref `dbcsr_t_reserve_blocks`)."""
        from dbcsr_tpu.ops.operations import reserve_blocks as _rb

        if np.asarray(indices).size == 0:
            self.matrix.finalize()
            return self
        idxs = np.atleast_2d(np.asarray(indices, np.int64))
        if idxs.shape[1] != self.ndim:
            raise ValueError(f"indices must be (N, {self.ndim})")
        rows = np.array([self._flat(i, self.row_dims) for i in idxs], np.int64)
        cols = np.array([self._flat(i, self.col_dims) for i in idxs], np.int64)
        _rb(self.matrix, rows, cols)
        return self

    def scale(self, alpha) -> "BlockSparseTensor":
        """Ref `dbcsr_t_scale`."""
        from dbcsr_tpu.ops.operations import scale as _scale

        _scale(self.matrix, alpha)
        return self

    def set_value(self, alpha) -> "BlockSparseTensor":
        """Set every stored element (ref `dbcsr_t_set`)."""
        from dbcsr_tpu.ops.operations import set_value as _sv

        _sv(self.matrix, alpha)
        return self

    def clear(self) -> "BlockSparseTensor":
        """Remove all blocks (ref `dbcsr_t_clear`)."""
        from dbcsr_tpu.ops.operations import clear as _clear

        _clear(self.matrix)
        return self

    def filter(self, eps: float) -> "BlockSparseTensor":
        """Drop blocks below the Frobenius threshold (ref `dbcsr_t_filter`)."""
        from dbcsr_tpu.ops.operations import filter_matrix

        filter_matrix(self.matrix, eps)
        return self

    def checksum(self, pos: bool = False) -> float:
        """Ref `dbcsr_t_checksum`."""
        from dbcsr_tpu.ops.test_methods import checksum as _cs

        return _cs(self.matrix, pos=pos)

    def get_num_blocks(self) -> int:
        """Ref `dbcsr_t_get_num_blocks`/`_total` (single-controller:
        local == total)."""
        return self.nblks

    def get_nze(self) -> int:
        """Stored element count (ref `dbcsr_t_get_nze`/`_total`)."""
        return self.matrix.nnz

    def get_stored_coordinates(self, idx: Sequence[int]) -> Tuple[int, int]:
        """Owning (prow, pcol) of a block (ref
        `dbcsr_t_get_stored_coordinates`, which returns the flat rank;
        here the 2d grid position is the process identity); delegates
        to the 2d matrix distribution."""
        return self.matrix.dist.stored_coordinates(
            self._flat(idx, self.row_dims), self._flat(idx, self.col_dims)
        )

    def blk_sizes_of(self, idx: Sequence[int]) -> Tuple[int, ...]:
        """Block dims at a multi-index (ref `dbcsr_t_blk_sizes`)."""
        return self.block_shape(idx)

    def get_info(self) -> dict:
        """Ref `dbcsr_t_get_info`."""
        return {
            "name": self.name,
            "ndim": self.ndim,
            "nblks_per_dim": self.nblks_per_dim,
            "nfull_per_dim": tuple(int(s.sum()) for s in self.blk_sizes),
            "nblks": self.nblks,
            "nze": self.get_nze(),
            "blk_sizes": [s.copy() for s in self.blk_sizes],
            "row_dims": self.row_dims,
            "col_dims": self.col_dims,
            "data_type": np.dtype(self.dtype).name,
        }

    def get_mapping_info(self) -> dict:
        """nd<->2d mapping summary (ref `dbcsr_t_get_mapping_info`)."""
        return {
            "ndim_nd": self.ndim,
            "row_dims": self.row_dims,
            "col_dims": self.col_dims,
            "dims_2d": (self.matrix.nblkrows, self.matrix.nblkcols),
        }

    def write_blocks(self, file=None) -> None:
        """Print every stored block (ref `dbcsr_t_write_blocks`)."""
        import sys

        out = file or sys.stdout
        print(self, file=out)
        for idx, blk in self.iterate_blocks():
            print(f" block {tuple(int(i) for i in idx)} shape {blk.shape}:",
                  file=out)
            with np.printoptions(precision=6, suppress=True):
                print(np.array2string(blk, prefix="  "), file=out)

    def write_split_info(self, file=None) -> None:
        """Print the nd->2d mapping (ref `dbcsr_t_write_split_info`)."""
        import sys

        out = file or sys.stdout
        mi = self.get_mapping_info()
        print(f" tensor {self.name!r}: rank {mi['ndim_nd']}, "
              f"row dims {mi['row_dims']} x col dims {mi['col_dims']} -> "
              f"2d grid {mi['dims_2d'][0]} x {mi['dims_2d'][1]}", file=out)

    def __repr__(self) -> str:
        return (
            f"BlockSparseTensor({self.name!r}, rank {self.ndim}, "
            f"nblks/dim {self.nblks_per_dim}, map {self.row_dims}|{self.col_dims})"
        )


def create_tensor(
    name: str,
    blk_sizes: List,
    row_dims: Optional[Sequence[int]] = None,
    col_dims: Optional[Sequence[int]] = None,
    dtype=np.float64,
) -> BlockSparseTensor:
    """Create a tensor (ref `dbcsr_t_create`).  Default mapping splits
    dims in half: first ceil(d/2) dims -> rows."""
    nd = len(blk_sizes)
    if row_dims is None and col_dims is None:
        half = (nd + 1) // 2
        row_dims, col_dims = tuple(range(half)), tuple(range(half, nd))
    elif row_dims is None:
        row_dims = tuple(d for d in range(nd) if d not in set(col_dims))
    elif col_dims is None:
        col_dims = tuple(d for d in range(nd) if d not in set(row_dims))
    return BlockSparseTensor(name, blk_sizes, row_dims, col_dims, dtype)


def split_blocks(tensor: BlockSparseTensor, new_blk_sizes: List,
                 name: Optional[str] = None) -> BlockSparseTensor:
    """Re-block a tensor onto FINER per-dim block sizes — every original
    block boundary must survive in the new blocking (ref
    `dbcsr_t_split_blocks`, `dbcsr_tensor_split.F`).  Data moves
    block-by-block on host: the mixed-radix 2d mapping interleaves dims,
    so this is NOT expressible as a matrix re-blocking."""
    new_sizes = [np.ascontiguousarray(s, np.int32) for s in new_blk_sizes]
    if len(new_sizes) != tensor.ndim:
        raise ValueError("need one block-size list per tensor dim")
    old_offs, new_offs, split_of = [], [], []
    for d in range(tensor.ndim):
        oo = np.concatenate([[0], np.cumsum(tensor.blk_sizes[d])])
        no = np.concatenate([[0], np.cumsum(new_sizes[d])])
        if oo[-1] != no[-1] or not np.isin(oo, no).all():
            raise ValueError(
                f"dim {d}: new blocking must refine the old (same total, "
                f"all old boundaries kept)"
            )
        old_offs.append(oo)
        new_offs.append(no)
        # for each new block: which old block contains it
        split_of.append(np.searchsorted(oo, no[:-1], side="right") - 1)
    out = BlockSparseTensor(
        name or tensor.name, new_sizes, tensor.row_dims, tensor.col_dims,
        tensor.dtype,
    )
    for idx, blk in tensor.iterate_blocks():
        # enumerate the new sub-blocks inside this old block, per dim
        per_dim = [
            np.nonzero(split_of[d] == idx[d])[0] for d in range(tensor.ndim)
        ]
        for sub in itertools.product(*per_dim):
            sl = tuple(
                slice(
                    int(new_offs[d][sub[d]] - old_offs[d][idx[d]]),
                    int(new_offs[d][sub[d] + 1] - old_offs[d][idx[d]]),
                )
                for d in range(tensor.ndim)
            )
            out.put_block(list(sub), blk[sl])
    return out.finalize()


def copy_matrix_to_tensor(matrix: BlockSparseMatrix,
                          tensor: BlockSparseTensor) -> BlockSparseTensor:
    """Fill a rank-2 tensor from a matrix with identical blocking
    (ref `dbcsr_t_copy_matrix_to_tensor`)."""
    if tensor.ndim != 2:
        raise ValueError("target tensor must be rank 2")
    if not (
        np.array_equal(tensor.blk_sizes[0], matrix.row_blk_sizes)
        and np.array_equal(tensor.blk_sizes[1], matrix.col_blk_sizes)
    ):
        raise ValueError("blockings differ")
    src = matrix
    if src.matrix_type != "N":
        from dbcsr_tpu.ops.transformations import desymmetrize

        src = desymmetrize(src)
    tensor.clear()
    for r, c, blk in src.iterate_blocks():
        tensor.put_block((r, c), blk)
    return tensor.finalize()


def copy_tensor_to_matrix(tensor: BlockSparseTensor,
                          matrix: BlockSparseMatrix) -> BlockSparseMatrix:
    """Fill a matrix from a rank-2 tensor with identical blocking
    (ref `dbcsr_t_copy_tensor_to_matrix`)."""
    if tensor.ndim != 2:
        raise ValueError("source tensor must be rank 2")
    if not (
        np.array_equal(tensor.blk_sizes[0], matrix.row_blk_sizes)
        and np.array_equal(tensor.blk_sizes[1], matrix.col_blk_sizes)
    ):
        raise ValueError("blockings differ")
    from dbcsr_tpu.ops.operations import clear as _clear

    _clear(matrix)
    for idx, blk in tensor.iterate_blocks():
        matrix.put_block(int(idx[0]), int(idx[1]), blk)
    return matrix.finalize()
