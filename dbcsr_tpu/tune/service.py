"""The autotuning service loop: mine → trial → promote, continuously.

One `cycle()` is the whole closed loop, synchronous and deterministic
(the tested form; the background thread just paces cycles on
``DBCSR_TPU_TUNE_INTERVAL_S``):

1. **admission gate** — the cycle runs only while
   `obs.health.admission_status()` is OK: a DEGRADED/CRITICAL process
   must spend its capacity on traffic, not trials (the same verdict
   the serve plane keys admission on, so the tuner can never compete
   with a struggling worker);
2. **regression judge** — `store.check_regressions()` first: a
   promoted row whose live roofline cell collapsed is demoted before
   any new work starts;
3. **mine** — `miner.mine()` ranks underperforming cells by wasted
   FLOP-seconds; the top cell gets this cycle's trial;
4. **trial** — `trials.run_trial()` (watchdog-guarded, byte/wall
   budgets, ``tune_trial`` fault boundary).  A non-OK trial promotes
   NOTHING — ever;
5. **promote** — the breaker-aware winner is promoted through
   `store.promote` only when it beats the incumbent evidence by
   ``DBCSR_TPU_TUNE_MARGIN`` (default 5%).  The promotion bumps the
   params generation, retiring every stale plan.

Two side channels ride each cycle: `store.peer_sync` adopts
same-device-kind peers' promotions over the fleet tier
(``DBCSR_TPU_FLEET_PEERS``) so one worker's trial pays for the whole
fleet, and an IDLE cycle (empty kernel queue) spends itself on the
FORMAT axis instead — `miner.mine_format` ranks the storage-format
planner's mis-crossovers, `trials.run_format_trial` A/Bs the formats
off the hot path, and the winning format columns merge into the
incumbent params row (`docs/performance.md` § storage formats).

Lifecycle: `maybe_start_from_env()` starts the background thread when
``DBCSR_TPU_TUNE=1`` (the serve engine calls it at start and
`stop_service` at shutdown); embedding apps construct `TuneService`
directly.  `current_service()` is the obs layers' read seam (health
component, timeseries collector, doctor) — it never CREATES a service.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from dbcsr_tpu.tune import miner, store, trials
from dbcsr_tpu.tune._env import env_float as _env_float

_lock = threading.Lock()
_service: Optional["TuneService"] = None


class TuneService:
    """The online tuner: one instance per process (module singleton via
    `get_service`), cycles run synchronously or on the background
    thread."""

    def __init__(self, interval_s: Optional[float] = None,
                 kind: Optional[str] = None, seed: int = 7):
        self.interval_s = (_env_float("DBCSR_TPU_TUNE_INTERVAL_S", 60.0)
                           if interval_s is None else float(interval_s))
        self.margin = _env_float("DBCSR_TPU_TUNE_MARGIN", 0.05)
        self.kind = kind
        self.seed = seed
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._state_lock = threading.Lock()
        self.stats: Dict = {
            "cycles": 0, "trials": 0, "promotions": 0, "demotions": 0,
            "deferred": 0, "queue_depth": 0, "last_cycle_s": 0.0,
            "last_outcome": None, "last_error": None,
            "last_cycle_demoted": False,
            "trial_failure_streak": 0,
        }

    # ------------------------------------------------------------ state

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def snapshot(self) -> Dict:
        with self._state_lock:
            snap = dict(self.stats)
        snap["running"] = self.running
        snap["interval_s"] = self.interval_s
        snap["generation"] = store.generation()
        return snap

    def _note(self, **updates) -> None:
        with self._state_lock:
            self.stats.update(updates)

    # ------------------------------------------------------------ cycle

    def cycle(self, cells: Optional[List[Dict]] = None) -> Dict:
        """One mine → trial → promote pass.  Returns the outcome dict
        (also folded into `snapshot()`)."""
        t0 = time.monotonic()
        with self._state_lock:
            self.stats["cycles"] += 1
        out: Dict = {"outcome": "idle", "cell": None, "promoted": None,
                     "demoted": []}
        try:
            out = self._cycle_inner(cells, out)
            self._note(last_error=None)
        except Exception as exc:
            out["outcome"] = "error"
            out["error"] = f"{type(exc).__name__}: {exc}"
            self._note(last_error=out["error"])
        dur = time.monotonic() - t0
        # demotion visibility is its OWN flag: a cycle that demotes a
        # regressed row and then also promotes/fails its trial would
        # otherwise overwrite last_outcome and hide the demotion from
        # the health component's operator page
        self._note(last_cycle_s=round(dur, 4),
                   last_outcome=out["outcome"],
                   last_cycle_demoted=bool(out.get("demoted")))
        try:
            from dbcsr_tpu.obs import metrics

            metrics.gauge(
                "dbcsr_tpu_tune_cycle_seconds",
                "wall seconds of the last online-tuner cycle",
            ).set(round(dur, 4))
        except Exception:
            pass
        return out

    def _admission(self) -> str:
        try:
            from dbcsr_tpu.obs import health

            return health.admission_status()
        except Exception:
            return "OK"

    def _cycle_inner(self, cells, out: Dict) -> Dict:
        admission = self._admission()
        if admission != "OK":
            # a degraded process tunes nothing: trials compete with the
            # traffic that degraded it (serve admission shares this
            # verdict, so the gate can never starve a healthy worker)
            with self._state_lock:
                self.stats["deferred"] += 1
            out["outcome"] = f"deferred:{admission}"
            return out
        demoted = store.check_regressions(kind=self.kind)
        if demoted:
            with self._state_lock:
                self.stats["demotions"] += len(demoted)
            out["demoted"] = demoted
            out["outcome"] = "demoted"
        try:
            # fleet tier: adopt same-device-kind peers' promotions so
            # one worker's trial pays for the whole fleet (bounded
            # per-peer timeout + cool-off inside peer_sync; a peerless
            # process returns [] without any I/O)
            adopted = store.peer_sync(kind=self.kind)
        except Exception:
            adopted = []
        if adopted:
            with self._state_lock:
                self.stats["adoptions"] = \
                    self.stats.get("adoptions", 0) + len(adopted)
            out["adopted"] = adopted
        if cells is None:
            cells = miner.mine()
        self._note(queue_depth=len(cells))
        if not cells:
            # no kernel cell wastes FLOP-seconds: spend the idle cycle
            # on the FORMAT axis (planner regrets mined off the live
            # mis-crossover ring; same trial guards, merge-promotion)
            return self._format_cycle(out)
        cell = cells[0]
        out["cell"] = {k: cell.get(k)
                       for k in ("m", "n", "k", "dtype", "stack_size",
                                 "wasted_flop_seconds", "reason")}
        with self._state_lock:
            self.stats["trials"] += 1
        trial = trials.run_trial(cell, seed=self.seed)
        if not trial.ok:
            with self._state_lock:
                self.stats["trial_failure_streak"] += 1
            out["outcome"] = f"trial_{trial.outcome}"
            out["error"] = trial.error
            return out
        self._note(trial_failure_streak=0)
        winner = trials.select_winner(trial.candidates, int(cell["m"]),
                                      int(cell["n"]), int(cell["k"]),
                                      cell.get("dtype", "float64"))
        if winner is None:
            out["outcome"] = "quarantined"
            return out
        promoted = self._maybe_promote(cell, trial, winner)
        if promoted is not None:
            with self._state_lock:
                self.stats["promotions"] += 1
            out["promoted"] = {
                "driver": promoted["entry"].get("driver"),
                "gflops": promoted["entry"].get("gflops"),
                "generation": promoted["generation"],
            }
            out["outcome"] = "promoted"
        elif out["outcome"] != "demoted":
            out["outcome"] = "held"
        return out

    def _incumbent_gflops(self, cell: Dict) -> Optional[float]:
        """The evidence bar a winner must clear: what the cell
        ACHIEVES live (the miner's observed rate).  Deliberately NOT
        the incumbent row's gflops claim — a stale row whose number
        was measured in another life (different device, wedged tunnel)
        must not be able to block its own displacement.  The claim is
        the fallback only when the cell was mined without a live
        rate."""
        obs = cell.get("observed_gflops")
        if isinstance(obs, (int, float)) and obs > 0:
            return float(obs)
        try:
            from dbcsr_tpu.acc import params as params_mod

            row = params_mod.predict(
                int(cell["m"]), int(cell["n"]), int(cell["k"]),
                cell.get("dtype", "float64"),
                stack_size=cell.get("stack_size"))
        except Exception:
            row = None
        claim = (row or {}).get("gflops")
        return float(claim) if isinstance(claim, (int, float)) \
            and claim > 0 else None

    @staticmethod
    def _same_config(winner: Dict, incumbent: Optional[Dict]) -> bool:
        if not incumbent:
            return False
        fields = ("driver", "grouping", "r0", "variant", "pack_p",
                  "precision")
        return all(winner.get(f) == incumbent.get(f) for f in fields)

    def _maybe_promote(self, cell: Dict, trial, winner: Dict):
        from dbcsr_tpu.acc import params as params_mod

        import numpy as np

        m, n, k = int(cell["m"]), int(cell["n"]), int(cell["k"])
        dtype = np.dtype(cell.get("dtype", "float64")).name
        incumbent = params_mod.lookup(m, n, k, dtype,
                                      stack_size=cell.get("stack_size"))
        if self._same_config(winner, incumbent):
            return None  # the table already says this; don't churn plans
        bar = self._incumbent_gflops(cell)
        if bar is not None and winner.get("gflops", 0.0) \
                <= bar * (1.0 + self.margin):
            return None
        base = trial.entry or {}
        row = {
            "m": m, "n": n, "k": k, "dtype": dtype,
            "stack_size": trial.stack_size,
            "env": base.get("env", "cpu"),
            **{f: winner[f] for f in winner
               if f not in ("m", "n", "k", "dtype", "stack_size", "env")},
        }
        row["gflops"] = round(float(winner.get("gflops", 0.0)), 2)
        return store.promote(
            row,
            trial={"stack_size": trial.stack_size,
                   "elapsed_s": round(trial.elapsed_s, 3),
                   "candidates": trial.candidates,
                   "mined": {kk: cell.get(kk) for kk in
                             ("observed_gflops", "target_gflops",
                              "wasted_flop_seconds", "reason",
                              "source")}},
            stack_size=int(cell.get("stack_size", trial.stack_size)),
            kind=self.kind)

    def _format_cycle(self, out: Dict) -> Dict:
        """Idle-cycle format-axis pass: trial the worst planner
        mis-crossover and merge the winning format columns into the
        incumbent params row.  A non-OK trial promotes nothing."""
        cells = miner.mine_format()
        if not cells:
            return out
        cell = cells[0]
        out["cell"] = {k: cell.get(k)
                       for k in ("m", "n", "k", "dtype", "format", "occ",
                                 "wasted_flop_seconds", "reason")}
        with self._state_lock:
            self.stats["trials"] += 1
        trial = trials.run_format_trial(cell, seed=self.seed)
        if not trial.ok:
            with self._state_lock:
                self.stats["trial_failure_streak"] += 1
            out["outcome"] = f"trial_{trial.outcome}"
            out["error"] = trial.error
            return out
        self._note(trial_failure_streak=0)
        promoted = self._maybe_promote_format(cell, trial)
        if promoted is not None:
            with self._state_lock:
                self.stats["promotions"] += 1
            out["promoted"] = {
                "format": promoted["entry"].get("format"),
                "format_occ": promoted["entry"].get("format_occ"),
                "generation": promoted["generation"],
            }
            out["outcome"] = "promoted"
        elif out["outcome"] != "demoted":
            out["outcome"] = "held"
        return out

    def _maybe_promote_format(self, cell: Dict, trial):
        """Merge the trial's format columns into the incumbent kernel
        row (or start a fresh row when none exists) — the kernel
        engine's driver/grouping fields are never displaced.  The bar:
        the winning format must beat the planner's measured rate for
        the cell by the promotion margin, and re-pinning the format
        the planner already chose is churn, not progress."""
        import numpy as np

        entry = trial.entry
        if not entry or not entry.get("format"):
            return None
        if entry["format"] == cell.get("format"):
            return None  # the trial agreed with the regretted plan
        bar = cell.get("observed_gflops")
        if isinstance(bar, (int, float)) and bar > 0 and \
                entry.get("format_gflops", 0.0) <= bar * (1.0 + self.margin):
            return None
        from dbcsr_tpu.acc import params as params_mod

        m, n, k = int(cell["m"]), int(cell["n"]), int(cell["k"])
        dtype = np.dtype(cell.get("dtype", "float64")).name
        incumbent = params_mod.lookup(
            m, n, k, dtype, stack_size=cell.get("stack_size")) or {}
        row = dict(incumbent)
        row.update({
            "m": m, "n": n, "k": k, "dtype": dtype,
            "stack_size": int(cell.get("stack_size")
                              or incumbent.get("stack_size") or 0),
            "env": incumbent.get("env", "cpu"),
            "format": entry["format"],
            "format_occ": entry["format_occ"],
            "format_gflops": entry["format_gflops"],
        })
        if entry.get("format_driver"):
            row["format_driver"] = entry["format_driver"]
        else:
            row.pop("format_driver", None)
        return store.promote(
            row,
            trial={"axis": "format",
                   "elapsed_s": round(trial.elapsed_s, 3),
                   "candidates": trial.candidates,
                   "mined": {kk: cell.get(kk) for kk in
                             ("format", "occ", "grid", "observed_gflops",
                              "target_gflops", "wasted_flop_seconds",
                              "reason", "source")}},
            stack_size=int(cell.get("stack_size", 0)),
            kind=self.kind)

    # ------------------------------------------------------- background

    def start(self) -> None:
        """Start the background cycle thread (idempotent)."""
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="dbcsr-tpu-tune", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.cycle()
            except Exception as exc:  # the loop must survive anything
                self._note(last_error=f"{type(exc).__name__}: {exc}")


# -------------------------------------------------------------- module

def get_service(create: bool = True, **kwargs) -> Optional[TuneService]:
    """The process's tuner singleton (created on first call unless
    ``create=False``)."""
    global _service
    with _lock:
        if _service is None and create:
            _service = TuneService(**kwargs)
        return _service


def current_service() -> Optional[TuneService]:
    """The live service or None — the obs read seam (never creates)."""
    return _service


def maybe_start_from_env() -> Optional[TuneService]:
    """Start the background tuner when ``DBCSR_TPU_TUNE`` is truthy
    (the serve engine's start hook).  Returns the service (or None
    when the knob is off)."""
    if os.environ.get("DBCSR_TPU_TUNE", "") not in ("1", "on", "true"):
        return None
    svc = get_service()
    svc.start()
    return svc


def stop_service() -> None:
    """Stop and drop the singleton (serve shutdown, tests)."""
    global _service
    with _lock:
        svc, _service = _service, None
    if svc is not None:
        svc.stop()
